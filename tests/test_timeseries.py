"""Metric history rings & derived views (ISSUE 18 tentpole): sampling
on a deterministic injected clock, ring wraparound, counter/gauge/
histogram streams, the rate/delta/ewma/window/sustained views the
alert engine consumes, downsampled export + sparkline rendering,
staleness stamps (snapshot age_s, prometheus `# age` lines, reset
epoch), the background tick, and the zero-extra-host-syncs contract
with the whole time axis enabled on the serving hot path."""
import os
import sys
import threading
import time

import numpy as np
import pytest

HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(HERE))

import paddle_tpu as paddle                              # noqa: E402
from paddle_tpu.core import monitor                      # noqa: E402
from paddle_tpu.core.monitor import MetricsRegistry      # noqa: E402
from paddle_tpu.core.timeseries import (MetricHistory,   # noqa: E402
                                        series_key, sparkline)


@pytest.fixture()
def clocked():
    """Private registry + history on one injected clock; the monitor
    module clock is swapped too so publish-side stamps agree."""
    t = {'now': 0.0}
    prev = monitor.set_time_fn(lambda: t['now'])
    reg = MetricsRegistry()
    hist = reg.enable_history(capacity=8, clock=lambda: t['now'])
    try:
        yield reg, hist, t
    finally:
        monitor.set_time_fn(prev)


# ---------------------------------------------------------------------------
# sampling & rings
# ---------------------------------------------------------------------------
class TestRings:
    def test_sample_all_kinds(self, clocked):
        reg, hist, t = clocked
        reg.gauge('t_g').set(3.0)
        reg.counter('t_c_total').inc(2)
        reg.histogram('t_h_seconds', buckets=(0.1, 1.0)).observe(0.05)
        hist.sample()
        assert hist.points('t_g') == [(0.0, 3.0)]
        assert hist.points('t_c_total') == [(0.0, 2.0)]
        # histograms contribute their _count/_sum counter streams
        assert hist.points('t_h_seconds_count') == [(0.0, 1.0)]
        assert hist.points('t_h_seconds_sum')[0][1] == \
            pytest.approx(0.05)

    def test_labeled_series_are_separate_rings(self, clocked):
        reg, hist, t = clocked
        g = reg.gauge('t_lbl', labelnames=('site',))
        g.set(1.0, site='a')
        g.set(2.0, site='b')
        hist.sample()
        assert hist.last('t_lbl', labels={'site': 'a'}) == 1.0
        assert hist.last('t_lbl', labels={'site': 'b'}) == 2.0
        # ambiguous unlabeled access on a multi-series metric raises
        with pytest.raises(ValueError):
            hist.points('t_lbl')
        assert hist.label_keys('t_lbl') == [('a',), ('b',)]

    def test_ring_wraparound_bounds_memory(self, clocked):
        reg, hist, t = clocked          # capacity=8
        g = reg.gauge('t_wrap')
        for i in range(20):
            t['now'] = float(i)
            g.set(float(i))
            hist.sample()
        pts = hist.points('t_wrap')
        assert len(pts) == 8            # oldest overwritten, never 20
        assert pts[0] == (12.0, 12.0) and pts[-1] == (19.0, 19.0)

    def test_self_gauges_published(self, clocked):
        reg, hist, t = clocked
        reg.gauge('t_one').set(1.0)
        hist.sample()
        hist.sample()
        assert reg.counter('ptpu_ts_samples_total').value() == 2
        assert reg.gauge('ptpu_ts_ring_capacity').value() == 8
        assert reg.gauge('ptpu_ts_series_tracked').value() >= 1
        assert reg.gauge('ptpu_ts_points_retained').value() >= 2

    def test_tick_rate_limit(self):
        t = {'now': 0.0}
        reg = MetricsRegistry()
        hist = reg.enable_history(capacity=8, min_interval_s=5.0,
                                  clock=lambda: t['now'])
        reg.gauge('t_rl').set(1.0)
        hist.tick()                     # first always samples
        t['now'] = 2.0
        hist.tick()                     # inside the interval: skipped
        assert len(hist.points('t_rl')) == 1
        t['now'] = 6.0
        hist.tick()
        assert len(hist.points('t_rl')) == 2

    def test_registry_reset_clears_rings(self, clocked):
        reg, hist, t = clocked
        reg.gauge('t_epoch').set(1.0)
        hist.sample()
        assert hist.points('t_epoch')
        reg.reset()                     # bumps epoch + clears history
        assert hist.points('t_epoch') == []
        # and samples never bleed across the epoch on the next pass
        reg.gauge('t_epoch').set(9.0)
        t['now'] = 1.0
        hist.sample()
        assert hist.points('t_epoch') == [(1.0, 9.0)]

    def test_enable_history_idempotent(self, clocked):
        reg, hist, t = clocked
        assert reg.enable_history(capacity=999) is hist
        assert hist.capacity == 8       # first call's capacity wins

    def test_capacity_floor(self):
        with pytest.raises(ValueError):
            MetricHistory(MetricsRegistry(), capacity=1)


# ---------------------------------------------------------------------------
# derived views
# ---------------------------------------------------------------------------
class TestViews:
    def _fill(self, values, step=1.0):
        t = {'now': 0.0}
        reg = MetricsRegistry()
        hist = reg.enable_history(capacity=64, clock=lambda: t['now'])
        g = reg.gauge('t_v')
        for i, v in enumerate(values):
            t['now'] = i * step
            g.set(float(v))
            hist.sample()
        return hist, t

    def test_delta_and_rate(self):
        hist, t = self._fill([0, 10, 20, 30, 40])      # t = 0..4
        assert hist.delta('t_v', 2.0) == 20.0          # 40 - v(t<=2)
        assert hist.rate('t_v', 2.0) == pytest.approx(10.0)
        # window wider than the ring: falls back to the oldest point
        assert hist.delta('t_v', 100.0) == 40.0

    def test_views_none_until_data(self):
        reg = MetricsRegistry()
        hist = reg.enable_history(capacity=8, clock=lambda: 0.0)
        assert hist.last('absent') is None
        assert hist.delta('absent', 10) is None
        assert hist.rate('absent', 10) is None
        assert hist.ewma('absent', 10) is None
        assert hist.window('absent', 10)['n'] == 0
        assert hist.age_s('absent') is None

    def test_ewma_tracks_trend(self):
        hist, _t = self._fill([100.0] * 30)
        assert hist.ewma('t_v', tau_s=5.0) == pytest.approx(100.0)
        hist2, _t2 = self._fill([100.0] * 20 + [10.0] * 10)
        ew = hist2.ewma('t_v', tau_s=30.0)
        # slow tau: the trend still remembers the 100s; the last value
        # sits far below it (the decode_tps_drop rule's shape)
        assert 10.0 < ew < 100.0
        assert hist2.last('t_v') < 0.5 * ew

    def test_window_stats(self):
        hist, _t = self._fill([1, 2, 3, 4, 5])
        w = hist.window('t_v', 2.0)     # t in [2, 4] -> values 3,4,5
        assert w == {'mean': 4.0, 'min': 3.0, 'max': 5.0, 'n': 3}

    def test_sustained_requires_full_coverage(self):
        hist, _t = self._fill([0.98, 0.98, 0.98, 0.98, 0.98])
        assert hist.sustained('t_v', lambda v: v >= 0.9, 2.0)
        # one dip inside the window breaks the sustain
        hist2, _t2 = self._fill([0.98, 0.98, 0.98, 0.5, 0.98])
        assert not hist2.sustained('t_v', lambda v: v >= 0.9, 2.0)
        # a series younger than the bound is never vacuously sustained
        hist3, _t3 = self._fill([0.98, 0.98])
        assert not hist3.sustained('t_v', lambda v: v >= 0.9, 10.0)

    def test_age_tracks_sampling(self):
        hist, t = self._fill([1, 2, 3])
        t['now'] = 10.0
        assert hist.age_s('t_v') == pytest.approx(8.0)


# ---------------------------------------------------------------------------
# export / rendering
# ---------------------------------------------------------------------------
class TestExport:
    def test_export_downsamples(self):
        t = {'now': 0.0}
        reg = MetricsRegistry()
        hist = reg.enable_history(capacity=128, clock=lambda: t['now'])
        g = reg.gauge('t_exp')
        for i in range(100):
            t['now'] = float(i)
            g.set(float(i))
            hist.sample()
        out = hist.export(max_points=10)
        s = out['t_exp']
        assert len(s['t']) == len(s['v']) == 10
        assert s['t'][-1] == 0.0            # relative to newest
        assert s['v'][-1] == s['last'] == 99.0
        assert s['min'] <= s['v'][0] and s['max'] == 99.0
        assert s['kind'] == 'gauge'

    def test_export_label_keys_and_names_filter(self):
        t = {'now': 0.0}
        reg = MetricsRegistry()
        hist = reg.enable_history(capacity=8, clock=lambda: t['now'])
        reg.gauge('t_exp_l', labelnames=('replica',)).set(
            1.0, replica='r0')
        reg.gauge('t_other').set(2.0)
        hist.sample()
        out = hist.export(names={'t_exp_l'})
        assert list(out) == [series_key('t_exp_l',
                                        (('replica', 'r0'),))]

    def test_snapshot_carries_series(self):
        t = {'now': 0.0}
        prev = monitor.set_time_fn(lambda: t['now'])
        try:
            reg = MetricsRegistry()
            reg.gauge('t_snap_g').set(5.0)
            hist = reg.enable_history(capacity=8,
                                      clock=lambda: t['now'])
            hist.sample()
            snap = reg.snapshot()
            assert 't_snap_g' in snap['series']
            assert snap['series']['t_snap_g']['last'] == 5.0
        finally:
            monitor.set_time_fn(prev)

    def test_sparkline(self):
        assert sparkline([]) == ''
        assert set(sparkline([1.0, 1.0, 1.0])) == {'▄'}
        s = sparkline(list(range(100)), width=12)
        assert len(s) == 12
        assert s[0] == '▁' and s[-1] == '█'

    def test_sampler_snapshot(self):
        t = {'now': 0.0}
        reg = MetricsRegistry()
        hist = reg.enable_history(capacity=8, clock=lambda: t['now'])
        reg.gauge('t_ss').set(1.0)
        hist.sample()
        ss = hist.snapshot()
        assert ss['samples'] == 1 and ss['capacity'] == 8
        assert ss['series'] >= 1 and ss['points'] >= 1


# ---------------------------------------------------------------------------
# staleness stamps (publish-side)
# ---------------------------------------------------------------------------
class TestStaleness:
    def test_snapshot_and_prometheus_age(self):
        t = {'now': 100.0}
        prev = monitor.set_time_fn(lambda: t['now'])
        try:
            reg = MetricsRegistry()
            reg.gauge('t_age_g').set(1.0)
            t['now'] = 130.0
            snap = reg.snapshot()
            row = snap['metrics']['t_age_g']['series'][0]
            assert row['age_s'] == pytest.approx(30.0)
            text = reg.prometheus_text(include_age=True)
            assert '# age t_age_g 30' in text
            # age lines are comments: opt-in and scrape-compatible
            assert '# age' not in reg.prometheus_text()
        finally:
            monitor.set_time_fn(prev)

    def test_publish_refreshes_stamp(self):
        t = {'now': 0.0}
        prev = monitor.set_time_fn(lambda: t['now'])
        try:
            reg = MetricsRegistry()
            g = reg.gauge('t_age_r')
            g.set(1.0)
            t['now'] = 50.0
            g.set(2.0)
            t['now'] = 51.0
            row = reg.snapshot()['metrics']['t_age_r']['series'][0]
            assert row['age_s'] == pytest.approx(1.0)
        finally:
            monitor.set_time_fn(prev)


# ---------------------------------------------------------------------------
# background tick
# ---------------------------------------------------------------------------
class TestBackgroundTick:
    def test_background_samples_and_stops(self):
        reg = MetricsRegistry()
        hist = reg.enable_history(capacity=16)
        reg.gauge('t_bg').set(1.0)
        th = hist.start_background(interval_s=0.01)
        assert hist.start_background() is th        # idempotent
        deadline = time.time() + 5.0
        while hist.snapshot()['samples'] < 2 and time.time() < deadline:
            time.sleep(0.01)
        hist.stop_background()
        assert not th.is_alive()
        assert hist.snapshot()['samples'] >= 2
        n = hist.snapshot()['samples']
        time.sleep(0.05)
        assert hist.snapshot()['samples'] == n      # really stopped


# ---------------------------------------------------------------------------
# zero-overhead contract on the serving hot path (PR-6 harness)
# ---------------------------------------------------------------------------
@pytest.fixture(scope='module')
def tiny_lm():
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM
    paddle.seed(7)
    cfg = GPTConfig(vocab_size=128, hidden_size=64, num_layers=2,
                    num_heads=2, max_seq_len=128, hidden_dropout=0.0,
                    attn_dropout=0.0, use_flash_attention=False)
    m = GPTForCausalLM(cfg)
    m.eval()
    return m


class TestSyncBudget:
    def test_time_axis_adds_no_host_syncs(self, tiny_lm, monkeypatch):
        """History sampling + alert evaluation read host-side floats
        the publishers already materialized: enabling the WHOLE time
        axis (rings + default rule pack) must not add a single
        engine._host_fetch, and outputs stay identical."""
        from paddle_tpu.serving import ServingConfig, ServingEngine
        from paddle_tpu.serving import engine as engine_mod
        from paddle_tpu.core.alerts import AlertManager, default_rules
        rng = np.random.RandomState(3)
        prompts = [list(rng.randint(1, 128, n)) for n in (5, 11, 3)]

        def run(enable_axis):
            monitor.metrics().reset()
            counts = [0]
            real = engine_mod._host_fetch

            def counting(x):
                counts[0] += 1
                return real(x)
            monkeypatch.setattr(engine_mod, '_host_fetch', counting)
            mgr = None
            try:
                if enable_axis:
                    hist = monitor.metrics().enable_history(
                        capacity=64)
                    mgr = AlertManager(hist, rules=default_rules(),
                                       source='test')
                eng = ServingEngine(tiny_lm, ServingConfig(
                    page_size=8, max_batch_size=3, prefill_chunk=8,
                    num_pages=4))
                outs = eng.generate(prompts, max_new_tokens=6, top_k=0)
                eng.publish_metrics()       # ticks the rings + rules
                eng.shutdown()
            finally:
                monkeypatch.setattr(engine_mod, '_host_fetch', real)
                if mgr is not None:
                    mgr.detach()
                monitor.metrics().reset()
            return counts[0], outs

        n_off, outs_off = run(False)
        n_on, outs_on = run(True)
        assert outs_on == outs_off
        assert n_on == n_off, (n_on, n_off)
