"""Wave-4 detection-tail ops vs numpy oracles (reference semantics:
test_yolov3_loss_op.py, test_prroi_pool_op.py,
test_box_decoder_and_assign_op.py, test_target_assign_op.py,
test_retinanet_detection_output.py, fluid/layers/detection.py
sigmoid_focal_loss:475)."""
import numpy as np
import pytest

from paddle_tpu.core.tensor import Tensor
from paddle_tpu.vision import detection as det


def _sigmoid(x):
    return 1.0 / (1.0 + np.exp(-x))


def test_sigmoid_focal_loss_oracle():
    rng = np.random.RandomState(0)
    N, C = 12, 6
    x = rng.randn(N, C).astype(np.float32)
    label = rng.randint(0, C + 1, (N, 1)).astype(np.int32)
    fg = np.array([4], np.int32)
    out = np.asarray(det.sigmoid_focal_loss(
        Tensor(x), Tensor(label), Tensor(fg), gamma=2.0,
        alpha=0.25).data)
    s = _sigmoid(x)
    want = np.zeros((N, C), np.float32)
    for i in range(N):
        for j in range(C):
            if j + 1 == label[i, 0]:
                want[i, j] = -0.25 * (1 - s[i, j]) ** 2 \
                    * np.log(s[i, j]) / 4
            else:
                want[i, j] = -0.75 * s[i, j] ** 2 \
                    * np.log(1 - s[i, j]) / 4
    np.testing.assert_allclose(out, want, rtol=1e-4, atol=1e-6)


def test_sigmoid_focal_loss_grad():
    rng = np.random.RandomState(1)
    x = Tensor(rng.randn(4, 3).astype(np.float32))
    x.stop_gradient = False
    lab = Tensor(rng.randint(0, 4, (4, 1)).astype(np.int32))
    out = det.sigmoid_focal_loss(x, lab, Tensor(np.array([2], np.int32)))
    out.sum().backward()
    assert np.isfinite(np.asarray(x.grad.data)).all()


def test_target_assign_oracle():
    rng = np.random.RandomState(2)
    B, P, K = 3, 20, 4
    gt_counts = [2, 3, 1]
    R = sum(gt_counts)
    enc = rng.rand(R, P, K).astype(np.float32)
    mi = -np.ones((B, P), np.int32)
    offs = np.concatenate([[0], np.cumsum(gt_counts)[:-1]])
    for b in range(B):
        ids = rng.choice(P, gt_counts[b], replace=False)
        mi[b, ids] = np.arange(gt_counts[b])
    out, w = det.target_assign(Tensor(enc), Tensor(mi),
                               input_lod=gt_counts, mismatch_value=0)
    o, wv = np.asarray(out.data), np.asarray(w.data)
    for b in range(B):
        for p in range(P):
            if mi[b, p] >= 0:
                np.testing.assert_allclose(
                    o[b, p], enc[offs[b] + mi[b, p], p], rtol=1e-6)
                assert wv[b, p, 0] == 1.0
            else:
                assert (o[b, p] == 0).all() and wv[b, p, 0] == 0.0


def test_target_assign_negative_indices():
    B, P = 2, 10
    enc = np.ones((2, P, 1), np.float32)
    mi = -np.ones((B, P), np.int32)
    mi[0, 3] = 0
    mi[1, 7] = 0
    neg = np.array([[1], [2], [5]], np.int32)
    out, w = det.target_assign(
        Tensor(enc), Tensor(mi), negative_indices=Tensor(neg),
        neg_lod=[2, 1], input_lod=[1, 1], mismatch_value=-1)
    wv = np.asarray(w.data)[..., 0]
    assert wv[0, 1] == 1.0 and wv[0, 2] == 1.0 and wv[1, 5] == 1.0
    assert wv[0, 3] == 1.0 and wv[1, 7] == 1.0
    assert wv[0, 5] == 0.0


def test_box_decoder_and_assign_oracle():
    rng = np.random.RandomState(3)
    R, C = 10, 5
    prior = np.abs(rng.rand(R, 4).astype(np.float32)) * 10
    prior[:, 2:] += prior[:, :2] + 2
    var = np.array([0.1, 0.1, 0.2, 0.2], np.float32)
    deltas = rng.randn(R, C * 4).astype(np.float32) * 0.3
    score = rng.rand(R, C).astype(np.float32)
    clip = 4.135
    dec, assign = det.box_decoder_and_assign(
        Tensor(prior), Tensor(var), Tensor(deltas), Tensor(score), clip)
    # numpy oracle (test_box_decoder_and_assign_op.py)
    w = prior[:, 2] - prior[:, 0] + 1.0
    h = prior[:, 3] - prior[:, 1] + 1.0
    cx = prior[:, 0] + 0.5 * w
    cy = prior[:, 1] + 0.5 * h
    dx = deltas[:, 0::4] * var[0]
    dy = deltas[:, 1::4] * var[1]
    dw = np.minimum(deltas[:, 2::4] * var[2], clip)
    dh = np.minimum(deltas[:, 3::4] * var[3], clip)
    pcx = dx * w[:, None] + cx[:, None]
    pcy = dy * h[:, None] + cy[:, None]
    pw = np.exp(dw) * w[:, None]
    ph = np.exp(dh) * h[:, None]
    want = np.zeros_like(deltas)
    want[:, 0::4] = pcx - 0.5 * pw
    want[:, 1::4] = pcy - 0.5 * ph
    want[:, 2::4] = pcx + 0.5 * pw - 1
    want[:, 3::4] = pcy + 0.5 * ph - 1
    np.testing.assert_allclose(np.asarray(dec.data), want, rtol=1e-4)
    av = np.asarray(assign.data)
    for r in range(R):
        rank = np.argsort(-score[r])
        best = rank[0] if rank[0] != 0 else rank[1]
        np.testing.assert_allclose(av[r], want[r, best * 4:best * 4 + 4],
                                   rtol=1e-4)


def _py_prroi_pool(x, rois, batch_idx, scale, ph, pw):
    """Exact integral of bilinear interpolation (PyPrRoIPool semantics)."""
    def cdf(t):
        t = np.clip(t, -1.0, 1.0)
        return np.where(t <= 0, 0.5 * (t + 1) ** 2,
                        0.5 + t - 0.5 * t * t)

    R = rois.shape[0]
    C, H, W = x.shape[1:]
    out = np.zeros((R, C, ph, pw), np.float64)
    for r in range(R):
        x1, y1, x2, y2 = rois[r] * scale
        for i in range(ph):
            for j in range(pw):
                ax = x1 + (x2 - x1) * j / pw
                bx = x1 + (x2 - x1) * (j + 1) / pw
                ay = y1 + (y2 - y1) * i / ph
                by = y1 + (y2 - y1) * (i + 1) / ph
                wx = cdf(bx - np.arange(W)) - cdf(ax - np.arange(W))
                wy = cdf(by - np.arange(H)) - cdf(ay - np.arange(H))
                area = max((bx - ax), 1e-9) * max((by - ay), 1e-9)
                out[r, :, i, j] = np.einsum(
                    'h,chw,w->c', wy, x[batch_idx[r]], wx) / area
    return out


def test_prroi_pool_oracle():
    rng = np.random.RandomState(4)
    x = rng.rand(2, 3, 12, 16).astype(np.float32)
    rois = np.array([[2.0, 2.0, 20.0, 16.0],
                     [4.0, 4.0, 28.0, 20.0],
                     [0.0, 0.0, 30.0, 22.0]], np.float32)
    rois_num = np.array([2, 1], np.int32)
    out = det.prroi_pool(Tensor(x), Tensor(rois), spatial_scale=0.5,
                         pooled_height=4, pooled_width=4,
                         rois_num=Tensor(rois_num))
    want = _py_prroi_pool(x, rois, [0, 0, 1], 0.5, 4, 4)
    np.testing.assert_allclose(np.asarray(out.data), want, rtol=1e-3,
                               atol=1e-5)


def test_prroi_pool_grad():
    rng = np.random.RandomState(5)
    x = Tensor(rng.rand(1, 2, 8, 8).astype(np.float32))
    x.stop_gradient = False
    rois = Tensor(np.array([[0.0, 0.0, 7.0, 7.0]], np.float32))
    out = det.prroi_pool(x, rois, 1.0, 2, 2)
    out.sum().backward()
    g = np.asarray(x.grad.data)
    assert np.isfinite(g).all() and (np.abs(g) > 0).any()


def test_retinanet_detection_output_runs():
    rng = np.random.RandomState(6)
    L, A, C = 2, 16, 4
    boxes = [Tensor(rng.randn(A, 4).astype(np.float32) * 0.1)
             for _ in range(L)]
    scores = [Tensor(_sigmoid(rng.randn(A, C)).astype(np.float32) * 0.5)
              for _ in range(L)]
    anch = []
    for _ in range(L):
        a = rng.rand(A, 4).astype(np.float32) * 50
        a[:, 2:] += a[:, :2] + 8
        anch.append(Tensor(a))
    im_info = Tensor(np.array([128.0, 128.0, 1.0], np.float32))
    rows, count = det.retinanet_detection_output(
        boxes, scores, anch, im_info, score_threshold=0.05,
        nms_top_k=100, keep_top_k=10, nms_threshold=0.3)
    r = np.asarray(rows.data)
    n = int(count.data)
    assert r.shape == (10, 6)
    assert 0 < n <= 10
    valid = r[:n]
    assert (valid[:, 0] >= 1).all()                  # 1-based labels
    assert (valid[:, 2] <= valid[:, 4] + 1e-3).all()
    assert (r[n:, 0] == -1).all()


def test_locality_aware_nms_merges_adjacent():
    # two nearly-identical boxes merge (scores add), one distant survives
    boxes = np.array([[[0., 0., 10., 10.],
                       [0.5, 0.5, 10.5, 10.5],
                       [50., 50., 60., 60.]]], np.float32)
    scores = np.array([[[0.6, 0.8, 0.9]]], np.float32)
    rows, count = det.locality_aware_nms(
        Tensor(boxes), Tensor(scores), score_threshold=0.1,
        nms_threshold=0.3, keep_top_k=5)
    r = np.asarray(rows.data)[0]
    n = int(np.asarray(count.data)[0])
    assert n == 2
    got_scores = sorted(r[:n, 1].tolist(), reverse=True)
    # merged pair carries the SUMMED score 1.4
    assert abs(got_scores[0] - 1.4) < 1e-5
    assert abs(got_scores[1] - 0.9) < 1e-5
    merged = r[np.argmax(r[:, 1])]
    # merged box is the score-weighted average of the pair
    want = (boxes[0, 0] * 0.6 + boxes[0, 1] * 0.8) / 1.4
    np.testing.assert_allclose(merged[2:], want, rtol=1e-5)


def test_detection_output_composes():
    rng = np.random.RandomState(7)
    N, P, C = 2, 8, 3
    loc = Tensor(rng.randn(N, P, 4).astype(np.float32) * 0.1)
    prior = np.abs(rng.rand(P, 4).astype(np.float32)) * 0.5
    prior[:, 2:] += prior[:, :2] + 0.2
    var = np.full((P, 4), 0.1, np.float32)
    sc = np.abs(rng.rand(N, P, C).astype(np.float32))
    sc /= sc.sum(-1, keepdims=True)
    out, idx, cnt = det.detection_output(
        loc, Tensor(sc), Tensor(prior), Tensor(var),
        score_threshold=0.01, keep_top_k=10)
    o = np.asarray(out.data)
    assert o.shape == (N, 10, 6)
    assert (np.asarray(cnt.data) >= 0).all()


def _yolo_oracle(x, gtbox, gtlabel, gtscore, attrs):
    """test_yolov3_loss_op.py YOLOv3Loss, trimmed to loss-only."""
    from scipy.special import expit

    def sce(v, label):
        sig = expit(v)
        return -label * np.log(sig) - (1 - label) * np.log(1 - sig)

    def batch_xywh_box_iou(box1, box2):
        b1l = box1[:, :, 0] - box1[:, :, 2] / 2
        b1r = box1[:, :, 0] + box1[:, :, 2] / 2
        b1t = box1[:, :, 1] - box1[:, :, 3] / 2
        b1b = box1[:, :, 1] + box1[:, :, 3] / 2
        b2l = box2[:, :, 0] - box2[:, :, 2] / 2
        b2r = box2[:, :, 0] + box2[:, :, 2] / 2
        b2t = box2[:, :, 1] - box2[:, :, 3] / 2
        b2b = box2[:, :, 1] + box2[:, :, 3] / 2
        left = np.maximum(b1l[:, :, None], b2l[:, None, :])
        right = np.minimum(b1r[:, :, None], b2r[:, None, :])
        top = np.maximum(b1t[:, :, None], b2t[:, None, :])
        bot = np.minimum(b1b[:, :, None], b2b[:, None, :])
        iw = np.clip(right - left, 0., 1.)
        ih = np.clip(bot - top, 0., 1.)
        inter = iw * ih
        a1 = (b1r - b1l) * (b1b - b1t)
        a2 = (b2r - b2l) * (b2b - b2t)
        return inter / (a1[:, :, None] + a2[:, None, :] - inter)

    n, c, h, w = x.shape
    b = gtbox.shape[1]
    anchors = attrs['anchors']
    an_num = len(anchors) // 2
    anchor_mask = attrs['anchor_mask']
    mask_num = len(anchor_mask)
    class_num = attrs['class_num']
    ignore_thresh = attrs['ignore_thresh']
    downsample = attrs['downsample_ratio']
    scale_x_y = attrs['scale_x_y']
    bias_x_y = -0.5 * (scale_x_y - 1.)
    input_size = downsample * h
    x = x.reshape((n, mask_num, 5 + class_num, h, w)) \
        .transpose((0, 1, 3, 4, 2))
    loss = np.zeros((n,), np.float64)
    smooth_w = min(1. / class_num, 1. / 40)
    use_ls = attrs['use_label_smooth']
    pos_l, neg_l = (1 - smooth_w, smooth_w) if use_ls else (1., 0.)

    pred_box = x[:, :, :, :, :4].copy()
    gx = np.tile(np.arange(w).reshape(1, w), (h, 1))
    gy = np.tile(np.arange(h).reshape(h, 1), (1, w))
    pred_box[..., 0] = (gx + expit(pred_box[..., 0]) * scale_x_y
                        + bias_x_y) / w
    pred_box[..., 1] = (gy + expit(pred_box[..., 1]) * scale_x_y
                        + bias_x_y) / h
    mask_anchors = [(anchors[2 * m], anchors[2 * m + 1])
                    for m in anchor_mask]
    an_s = np.array([(aw / input_size, ah / input_size)
                     for aw, ah in mask_anchors])
    pred_box[..., 2] = np.exp(pred_box[..., 2]) \
        * an_s[:, 0].reshape(1, mask_num, 1, 1)
    pred_box[..., 3] = np.exp(pred_box[..., 3]) \
        * an_s[:, 1].reshape(1, mask_num, 1, 1)
    pred_box = pred_box.reshape((n, -1, 4))
    pred_obj = x[:, :, :, :, 4].reshape((n, -1))
    objness = np.zeros(pred_box.shape[:2])
    ious = batch_xywh_box_iou(pred_box, gtbox)
    objness = np.where(ious.max(-1) > ignore_thresh, -1., objness)

    gt_shift = gtbox.copy()
    gt_shift[:, :, :2] = 0
    anchors_p = [(anchors[2 * i], anchors[2 * i + 1])
                 for i in range(an_num)]
    all_s = np.array([(aw / input_size, ah / input_size)
                      for aw, ah in anchors_p])
    anchor_boxes = np.concatenate([np.zeros_like(all_s), all_s], -1)
    anchor_boxes = np.tile(anchor_boxes[None], (n, 1, 1))
    iou2 = batch_xywh_box_iou(gt_shift, anchor_boxes)
    matches = iou2.argmax(-1)
    for i in range(n):
        for j in range(b):
            if gtbox[i, j, 2:].sum() == 0 or \
                    matches[i, j] not in anchor_mask:
                continue
            an_idx = anchor_mask.index(matches[i, j])
            gi = int(gtbox[i, j, 0] * w)
            gj = int(gtbox[i, j, 1] * h)
            tx = gtbox[i, j, 0] * w - gi
            ty = gtbox[i, j, 1] * w - gj
            tw = np.log(gtbox[i, j, 2] * input_size
                        / mask_anchors[an_idx][0])
            th = np.log(gtbox[i, j, 3] * input_size
                        / mask_anchors[an_idx][1])
            scale = (2. - gtbox[i, j, 2] * gtbox[i, j, 3]) * gtscore[i, j]
            loss[i] += sce(x[i, an_idx, gj, gi, 0], tx) * scale
            loss[i] += sce(x[i, an_idx, gj, gi, 1], ty) * scale
            loss[i] += abs(x[i, an_idx, gj, gi, 2] - tw) * scale
            loss[i] += abs(x[i, an_idx, gj, gi, 3] - th) * scale
            objness[i, an_idx * h * w + gj * w + gi] = gtscore[i, j]
            for li in range(class_num):
                loss[i] += sce(
                    x[i, an_idx, gj, gi, 5 + li],
                    pos_l if li == gtlabel[i, j] else neg_l) \
                    * gtscore[i, j]
        for j in range(mask_num * h * w):
            if objness[i, j] > 0:
                loss[i] += sce(pred_obj[i, j], 1.0) * objness[i, j]
            elif objness[i, j] == 0:
                loss[i] += sce(pred_obj[i, j], 0.0)
    return loss


@pytest.mark.parametrize('label_smooth', [True, False])
def test_yolov3_loss_oracle(label_smooth):
    from scipy.special import logit
    rng = np.random.RandomState(8)
    attrs = {
        'anchors': [10, 13, 16, 30, 33, 23],
        'anchor_mask': [1, 2],
        'class_num': 5,
        'ignore_thresh': 0.7,
        'downsample_ratio': 32,
        'use_label_smooth': label_smooth,
        'scale_x_y': 1.0,
    }
    n, h, w, B = 2, 5, 5, 4
    mask_num = len(attrs['anchor_mask'])
    x = logit(rng.uniform(0.05, 0.95,
                          (n, mask_num * 10, h, w))).astype(np.float32)
    gtbox = rng.random((n, B, 4)).astype(np.float32)
    gtlabel = rng.randint(0, 5, (n, B))
    gtmask = rng.randint(0, 2, (n, B))
    gtbox = gtbox * gtmask[:, :, None]
    gtlabel = (gtlabel * gtmask).astype(np.int32)
    gtscore = rng.random((n, B)).astype(np.float32)

    loss, obj, match = det.yolov3_loss(
        Tensor(x), Tensor(gtbox), Tensor(gtlabel),
        attrs['anchors'], attrs['anchor_mask'], attrs['class_num'],
        attrs['ignore_thresh'], attrs['downsample_ratio'],
        gt_score=Tensor(gtscore), use_label_smooth=label_smooth)
    want = _yolo_oracle(x.astype(np.float64), gtbox.astype(np.float64),
                        gtlabel, gtscore.astype(np.float64), attrs)
    np.testing.assert_allclose(np.asarray(loss.data), want, rtol=2e-3)


def test_yolov3_loss_grad():
    rng = np.random.RandomState(9)
    x = Tensor(rng.randn(1, 2 * 8, 3, 3).astype(np.float32))
    x.stop_gradient = False
    gtbox = Tensor(np.array([[[0.5, 0.5, 0.3, 0.4]]], np.float32))
    gtlabel = Tensor(np.array([[1]], np.int32))
    loss, _, _ = det.yolov3_loss(
        x, gtbox, gtlabel, [10, 13, 16, 30], [0, 1], 3, 0.7, 32)
    loss.sum().backward()
    assert np.isfinite(np.asarray(x.grad.data)).all()


def test_static_nn_detection_names_resolve():
    from paddle_tpu.static import nn as snn
    for n in ['sigmoid_focal_loss', 'target_assign',
              'box_decoder_and_assign', 'prroi_pool',
              'retinanet_detection_output', 'locality_aware_nms',
              'detection_output', 'yolov3_loss', 'polygon_box_transform']:
        assert callable(getattr(snn, n)), n
