"""Activation economy (ISSUE 12): tuned remat policies, sequence-
parallel activation sharding, dropout-fused flash attention, and the
activation-byte census.

Equivalence bars (docs/performance.md#remat-policy):
  * remat is a pure scheduling transform — per-step LOSS is
    bit-identical under every policy on all three engines; params/grads
    agree to fp32 ulp-level XLA-reassociation noise (strict grad
    bit-equality across different XLA fusions is not a backend
    guarantee).
  * sequence-parallel LayerNorm/dropout sharding == the replicated
    route within fp32 tolerance on the 8-dev mesh (SGD trajectory).
  * the dropout-fused flash route matches the dense reference fwd+VJP
    at the same mask/seed (interpret mode on the CPU mesh).
"""
import math
import os

import numpy as np
import pytest
import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.distributed import topology_runtime
import paddle_tpu.distributed.fleet as fm
from paddle_tpu.distributed.fleet.utils.recompute import (
    resolve_policy, boundary_counts, snapshot as remat_snapshot,
    POLICY_NAMES, checkpoint_policy)
from paddle_tpu.models.gpt import (GPTConfig, GPTForCausalLM,
                                   GPTPretrainingCriterion,
                                   build_gpt_pipeline)

TINY = dict(vocab_size=64, hidden_size=32, num_layers=2, num_heads=4,
            max_seq_len=64, hidden_dropout=0.0, attn_dropout=0.0,
            use_flash_attention=False)


def _data(B=4, L=64, vocab=64, seed=0):
    rng = np.random.RandomState(seed)
    ids = rng.randint(0, vocab, (B, L)).astype('int32')
    return ids, np.roll(ids, -1, 1).astype('int32')


def _reset_topology():
    fm.fleet._hcg = None
    fm.fleet._user_defined_strategy = None


def _mp_topology(dp, mp):
    from paddle_tpu.distributed.fleet.base.topology import (
        CommunicateTopology, HybridCommunicateGroup)
    fm.fleet._hcg = None
    topo = CommunicateTopology(["data", "pipe", "sharding", "model"],
                               [dp, 1, 1, mp])
    fm.fleet._topology = topo
    fm.fleet._hcg = HybridCommunicateGroup(topo)
    return topology_runtime.build_mesh(['dp', 'mp'], [dp, mp])


# ---------------------------------------------------------------------------
# policy resolution units (kwarg -> env -> strategy)
# ---------------------------------------------------------------------------
class TestPolicyResolution:
    def teardown_method(self):
        os.environ.pop('PTPU_REMAT_POLICY', None)
        fm.fleet._user_defined_strategy = None

    def test_kwarg_wins(self):
        os.environ['PTPU_REMAT_POLICY'] = 'dots'
        assert resolve_policy('full') == 'full'

    def test_env_beats_strategy_and_default(self):
        os.environ['PTPU_REMAT_POLICY'] = 'attn_mlp_boundaries'
        strat = fm.DistributedStrategy()
        strat.recompute = True
        strat.recompute_configs = {'policy': 'dots'}
        fm.fleet._user_defined_strategy = strat
        assert resolve_policy(None) == 'attn_mlp_boundaries'

    def test_strategy_when_recompute_on(self):
        strat = fm.DistributedStrategy()
        strat.recompute = True
        strat.recompute_configs = {'policy': 'dots'}
        fm.fleet._user_defined_strategy = strat
        assert resolve_policy(None) == 'dots'
        # strategy.recompute off -> the policy key is ignored
        strat2 = fm.DistributedStrategy()
        strat2.recompute_configs = {'policy': 'dots'}
        fm.fleet._user_defined_strategy = strat2
        assert resolve_policy(None, default='none') == 'none'

    def test_default_and_sentinel(self):
        assert resolve_policy(None, default='full') == 'full'
        assert resolve_policy(None, default=None) is None
        assert resolve_policy(True) == 'full'
        assert resolve_policy(False) == 'none'

    def test_invalid_raises(self):
        with pytest.raises(ValueError):
            resolve_policy('no_such_policy')

    def test_policy_table(self):
        for name in POLICY_NAMES:
            on, pol = checkpoint_policy(name)
            assert on == (name != 'none')


# ---------------------------------------------------------------------------
# remat ON == OFF equivalence on the three engines
# ---------------------------------------------------------------------------
POLICIES = ('none', 'full', 'attn_mlp_boundaries')


def _close_params(a, b):
    # Adam's rsqrt amplifies ulp-level grad reassociation noise where
    # second moments are near zero, so params get a slightly looser
    # bound than raw grads; the hard bar is the bit-identical loss
    for n in a:
        np.testing.assert_allclose(
            a[n], b[n], rtol=5e-4, atol=1e-5,
            err_msg=f'param {n} drifted beyond fp32 remat noise')



def _check_traj(base, got, pol):
    """Step-1 loss is computed from IDENTICAL params, so it must be
    bit-identical under remat (the pure scheduling-transform bar);
    later steps feed Adam-amplified ulp noise back through the params,
    so the trajectory gets an fp32-noise bound."""
    assert got[0][0] == base[0][0], (pol, got[0][0], base[0][0])
    np.testing.assert_allclose(base[0], got[0], rtol=1e-6,
                               err_msg=str(pol))
    _close_params(base[1], got[1])


class TestRematEquivalence:
    def _hybrid(self, policy, steps=3):
        from paddle_tpu.distributed.fleet.meta_parallel.hybrid_engine \
            import HybridParallelTrainStep
        _reset_topology()
        topology_runtime.build_mesh(['dp'], [2])
        paddle.seed(7)
        cfg = GPTConfig(**TINY)
        m = GPTForCausalLM(cfg)
        crit = GPTPretrainingCriterion(cfg)
        opt = paddle.optimizer.Adam(learning_rate=0.01, parameters=[])
        eng = HybridParallelTrainStep(
            m, lambda mm, i, l: crit(mm(i), l), opt, remat_policy=policy)
        ids, lab = _data()
        losses = [float(eng(Tensor(ids), Tensor(lab)))
                  for _ in range(steps)]
        params = {n: np.asarray(v) for n, v in eng.params.items()}
        eng.shutdown()
        return losses, params

    def test_hybrid_loss_bit_identity(self):
        base = self._hybrid('none')
        for pol in ('full', 'attn_mlp_boundaries'):
            got = self._hybrid(pol)
            _check_traj(base, got, pol)

    def test_trainstep_loss_bit_identity(self):
        from paddle_tpu.jit import TrainStep

        def run(policy):
            _reset_topology()
            topology_runtime.build_mesh(['dp'], [1])
            paddle.seed(7)
            cfg = GPTConfig(**TINY)
            m = GPTForCausalLM(cfg)
            crit = GPTPretrainingCriterion(cfg)
            opt = paddle.optimizer.Adam(learning_rate=0.01,
                                        parameters=m.parameters())
            ts = TrainStep(m, lambda mm, i, l: crit(mm(i), l), opt,
                           remat_policy=policy)
            ids, lab = _data()
            losses = [float(ts(Tensor(ids), Tensor(lab)))
                      for _ in range(3)]
            return losses, {n: np.asarray(v)
                            for n, v in ts._params.items()}

        base = run('none')
        for pol in ('full', 'attn_mlp_boundaries'):
            got = run(pol)
            _check_traj(base, got, pol)

    def test_pipeline_loss_bit_identity(self):
        from paddle_tpu.distributed.fleet.meta_parallel.spmd_pipeline \
            import SpmdPipelineEngine

        def run(policy):
            _reset_topology()
            topology_runtime.build_mesh(['dp', 'pp'], [1, 1])
            paddle.seed(7)
            cfg = GPTConfig(**TINY)
            embed, blocks, head = build_gpt_pipeline(cfg)
            opt = paddle.optimizer.Adam(learning_rate=0.01, parameters=[])
            eng = SpmdPipelineEngine(embed, blocks, head, opt,
                                     accumulate_steps=2,
                                     use_remat=policy != 'none',
                                     remat_policy=policy)
            ids, lab = _data()
            losses = [float(eng.train_batch((Tensor(ids), Tensor(lab))))
                      for _ in range(3)]
            params = {f'{g}/{n}': np.asarray(v)
                      for g in ('embed', 'blocks', 'head')
                      for n, v in eng._params[g].items()}
            eng.shutdown()
            return losses, params

        base = run('none')
        for pol in ('full', 'attn_mlp_boundaries'):
            got = run(pol)
            _check_traj(base, got, pol)

    def test_boundary_tags_counted(self):
        before = dict(boundary_counts())
        self._hybrid('attn_mlp_boundaries', steps=1)
        after = boundary_counts()
        for tag in ('attn_qkv', 'attn_ctx', 'attn_out', 'mlp_fc1',
                    'mlp_out', 'embed_out'):
            assert after.get(tag, 0) > before.get(tag, 0), (tag, after)
        snap = remat_snapshot()
        assert snap and snap['policies'].get('hybrid') == \
            'attn_mlp_boundaries'
        assert snap['boundary_total'] >= sum(before.values())


# ---------------------------------------------------------------------------
# taps invariant: the PR-3 per-param stat boundaries survive remat
# ---------------------------------------------------------------------------
class TestTapsUnderRemat:
    def test_same_tap_tree_and_values(self):
        from paddle_tpu.core import flags
        from paddle_tpu.distributed.fleet.meta_parallel.hybrid_engine \
            import HybridParallelTrainStep
        flags.set_flags({'FLAGS_tensor_stats': True})
        try:
            def run(policy):
                _reset_topology()
                topology_runtime.build_mesh(['dp'], [2])
                paddle.seed(7)
                cfg = GPTConfig(**TINY)
                m = GPTForCausalLM(cfg)
                crit = GPTPretrainingCriterion(cfg)
                opt = paddle.optimizer.Adam(learning_rate=0.01,
                                            parameters=[])
                eng = HybridParallelTrainStep(
                    m, lambda mm, i, l: crit(mm(i), l), opt,
                    remat_policy=policy)
                ids, lab = _data()
                eng(Tensor(ids), Tensor(lab))
                num = eng.last_numerics
                eng.shutdown()
                return num
            base = run('none')
            remat = run('attn_mlp_boundaries')
            assert base is not None and remat is not None
            # same per-param boundaries ...
            assert set(base['grads']) == set(remat['grads'])
            assert set(base['params']) == set(remat['params'])
            # ... and the same statistics up to remat fp32 noise
            np.testing.assert_allclose(
                base['grad_norm'], remat['grad_norm'], rtol=1e-5)
            for n in base['grads']:
                np.testing.assert_allclose(
                    base['grads'][n].rms, remat['grads'][n].rms,
                    rtol=1e-4, atol=1e-9, err_msg=n)
        finally:
            flags.set_flags({'FLAGS_tensor_stats': None})


# ---------------------------------------------------------------------------
# activation-byte census: attn_mlp_boundaries shrinks the compiled
# step's resident temp bytes (CPU dryrun acceptance)
# ---------------------------------------------------------------------------
class TestActivationCensus:
    def _temp_bytes(self, policy):
        from paddle_tpu.core import memory as mem
        from paddle_tpu.distributed.fleet.meta_parallel.hybrid_engine \
            import HybridParallelTrainStep
        mem.reset()
        _reset_topology()
        topology_runtime.build_mesh(['dp'], [1])
        paddle.seed(7)
        cfg = GPTConfig(vocab_size=128, hidden_size=64, num_layers=4,
                        num_heads=4, max_seq_len=128, hidden_dropout=0.0,
                        attn_dropout=0.0, use_flash_attention=False)
        m = GPTForCausalLM(cfg)
        crit = GPTPretrainingCriterion(cfg)
        opt = paddle.optimizer.Adam(learning_rate=0.01, parameters=[])
        eng = HybridParallelTrainStep(
            m, lambda mm, i, l: crit(mm(i), l), opt, remat_policy=policy)
        ids, lab = _data(B=8, L=128, vocab=128)
        loss = float(eng(Tensor(ids), Tensor(lab)))
        acts = mem.activation_bytes()
        sample = mem.sample()
        eng.shutdown()
        assert np.isfinite(loss)
        assert sample['activation_bytes'] == acts
        return acts['hybrid.step']

    def test_census_drop_under_boundary_policy(self):
        dense = self._temp_bytes('none')
        tuned = self._temp_bytes('attn_mlp_boundaries')
        assert tuned < dense, (tuned, dense)

    def test_gauge_published(self):
        from paddle_tpu.core import monitor
        self._temp_bytes('none')
        g = monitor.metrics().get('ptpu_mem_activation_bytes')
        assert g is not None
        sites = {labels[0] for labels in g._series()}
        assert 'hybrid.step' in sites


# ---------------------------------------------------------------------------
# sequence-parallel activation sharding == replicated (8-dev mesh)
# ---------------------------------------------------------------------------
class TestSequenceParallel:
    def _run(self, seqp, opt_name='sgd', dropout=0.0, steps=3, seed=7):
        from paddle_tpu.distributed.fleet.meta_parallel.hybrid_engine \
            import HybridParallelTrainStep
        _mp_topology(2, 4)
        paddle.seed(seed)
        cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                        num_heads=4, max_seq_len=64,
                        hidden_dropout=dropout, attn_dropout=0.0,
                        use_flash_attention=False)
        m = GPTForCausalLM(cfg)
        crit = GPTPretrainingCriterion(cfg)
        opt = (paddle.optimizer.SGD(learning_rate=0.5, parameters=[])
               if opt_name == 'sgd'
               else paddle.optimizer.Adam(learning_rate=0.01,
                                          parameters=[]))
        eng = HybridParallelTrainStep(
            m, lambda mm, i, l: crit(mm(i), l), opt,
            sequence_parallel=seqp)
        assert eng._seq_parallel == bool(seqp)
        ids, lab = _data()
        losses = [float(eng(Tensor(ids), Tensor(lab)))
                  for _ in range(steps)]
        params = {n: np.asarray(v) for n, v in eng.params.items()}
        eng.shutdown()
        return losses, params

    def test_sharded_equals_replicated_sgd(self):
        """The headline acceptance bar: SGD (scale-sensitive — no Adam
        normalization masking) trajectory with the LayerNorm/dropout/
        residual segments sequence-scattered over mp matches the
        replicated route to fp32 noise."""
        base = self._run(False)
        got = self._run(True)
        np.testing.assert_allclose(base[0], got[0], rtol=1e-6)
        for n in base[1]:
            np.testing.assert_allclose(
                base[1][n], got[1][n], rtol=1e-4, atol=1e-6,
                err_msg=f'param {n}')

    def test_sharded_equals_replicated_adam(self):
        base = self._run(False, opt_name='adam')
        got = self._run(True, opt_name='adam')
        np.testing.assert_allclose(base[0], got[0], rtol=1e-5)

    def test_dropout_deterministic_and_trains(self):
        """With dropout on, each token's mask is drawn by its owner
        rank (same stream, local shapes) — not mask-identical to the
        replicated route, but deterministic across runs and a valid
        dropout trajectory."""
        a = self._run(True, dropout=0.1)
        b = self._run(True, dropout=0.1)
        assert a[0] == b[0]
        assert np.isfinite(a[0]).all()

    def test_resolution_and_gating(self):
        from paddle_tpu.distributed import collective as C
        os.environ['PTPU_SEQUENCE_PARALLEL'] = '1'
        try:
            assert C.resolve_sequence_parallel(None) is True
            assert C.resolve_sequence_parallel(False) is False
        finally:
            del os.environ['PTPU_SEQUENCE_PARALLEL']
        strat = fm.DistributedStrategy()
        strat.tensor_parallel_configs = {'sequence_parallel': True}
        fm.fleet._user_defined_strategy = strat
        try:
            assert C.resolve_sequence_parallel(None) is True
        finally:
            fm.fleet._user_defined_strategy = None
        # no mp axis -> the knob is inert (engine gates on mp > 1)
        from paddle_tpu.distributed.fleet.meta_parallel.hybrid_engine \
            import HybridParallelTrainStep
        _reset_topology()
        topology_runtime.build_mesh(['dp'], [2])
        paddle.seed(0)
        cfg = GPTConfig(**TINY)
        m = GPTForCausalLM(cfg)
        crit = GPTPretrainingCriterion(cfg)
        opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=[])
        eng = HybridParallelTrainStep(
            m, lambda mm, i, l: crit(mm(i), l), opt,
            sequence_parallel=True)
        assert eng._seq_parallel is False
        eng.shutdown()


# ---------------------------------------------------------------------------
# dropout-fused flash attention (interpret mode)
# ---------------------------------------------------------------------------
class TestFlashDropout:
    B, nh, L, hd = 2, 2, 128, 64
    rate = 0.1

    def _qkv_mask(self, seed=3):
        rs = np.random.RandomState(0)
        q = jnp.asarray(rs.randn(self.B * self.nh, self.L, self.hd),
                        jnp.float32)
        k = jnp.asarray(rs.randn(self.B * self.nh, self.L, self.hd),
                        jnp.float32)
        v = jnp.asarray(rs.randn(self.B * self.nh, self.L, self.hd),
                        jnp.float32)
        keep = jax.random.bernoulli(
            jax.random.key(seed), 1.0 - self.rate,
            (self.B, self.nh, self.L, self.L))
        return q, k, v, keep

    def _dense(self, q, k, v, keep):
        s = jnp.einsum('bqd,bkd->bqk', q, k,
                       preferred_element_type=jnp.float32) \
            / math.sqrt(self.hd)
        causal = jnp.tril(jnp.ones((self.L, self.L), bool))
        s = jnp.where(causal, s, -1e9)
        p = jax.nn.softmax(s, axis=-1)
        kp = keep.reshape(self.B * self.nh, self.L, self.L)
        p = jnp.where(kp, p / (1.0 - self.rate), 0.0)
        return jnp.einsum('bqk,bkd->bqd', p, v)

    def test_fwd_and_vjp_match_dense_same_mask(self):
        from paddle_tpu.ops.pallas import flash_attention as fa
        q, k, v, keep = self._qkv_mask()
        mask8 = keep.reshape(self.B * self.nh, self.L,
                             self.L).astype(jnp.int8)
        o_ref = self._dense(q, k, v, keep)
        o_fl = jax.jit(lambda q, k, v: fa._flash_attn_dropout(
            self.rate, q, k, v, mask8))(q, k, v)
        np.testing.assert_allclose(np.asarray(o_ref), np.asarray(o_fl),
                                   rtol=1e-5, atol=1e-5)

        g_ref = jax.jit(jax.grad(
            lambda q, k, v: jnp.sum(self._dense(q, k, v, keep) ** 2),
            argnums=(0, 1, 2)))(q, k, v)
        g_fl = jax.jit(jax.grad(
            lambda q, k, v: jnp.sum(fa._flash_attn_dropout(
                self.rate, q, k, v, mask8) ** 2),
            argnums=(0, 1, 2)))(q, k, v)
        for name, a, b in zip('qkv', g_ref, g_fl):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4,
                err_msg=f'd{name}')

    def test_route_counters_and_errors(self):
        from paddle_tpu.ops.pallas import flash_attention as fa
        from paddle_tpu.ops.pallas import scaffold
        before = scaffold.routes_snapshot().get(
            'flash_dropout', {'kernel': 0})['kernel']
        qkv = Tensor(jnp.zeros((1, 64, 4 * 3 * 16), jnp.float32))
        fa.causal_attention(qkv, 4, 16, dropout=0.1,
                            dropout_key=jax.random.key(0))
        after = scaffold.routes_snapshot()['flash_dropout']['kernel']
        assert after == before + 1
        # clear errors only when no route exists
        with pytest.raises(ValueError, match='dropout_key'):
            fa.causal_attention(qkv, 4, 16, dropout=0.1)
        with pytest.raises(ValueError, match='rate'):
            fa.causal_attention(qkv, 4, 16, dropout=1.5,
                                dropout_key=jax.random.key(0))

    def test_gpt_attention_same_seed_matches_dense(self):
        """End to end: the model-level flash-dropout route (the dense
        fallback for attention_dropout > 0 is GONE) vs the dense
        reference config at the same RNG-stream point."""
        from paddle_tpu.models.gpt import GPTAttention
        _reset_topology()
        rs = np.random.RandomState(0)
        x = rs.randn(1, 512, 64).astype(np.float32)

        def run(use_flash):
            cfg = GPTConfig(vocab_size=64, hidden_size=64, num_layers=2,
                            num_heads=1, max_seq_len=512,
                            attn_dropout=0.2,
                            use_flash_attention=use_flash)
            paddle.seed(11)
            att = GPTAttention(cfg)
            att.train()
            paddle.seed(42)
            return np.asarray(att(Tensor(jnp.asarray(x))).data)

        np.testing.assert_allclose(run(True), run(False),
                                   rtol=1e-5, atol=1e-6)

    def test_eval_and_zero_dropout_keep_packed_route(self):
        from paddle_tpu.ops.pallas import flash_attention as fa
        from paddle_tpu.ops.pallas import scaffold
        qkv = Tensor(jnp.zeros((1, 64, 4 * 3 * 16), jnp.float32))
        before = scaffold.routes_snapshot().get(
            'flash_attention', {'kernel': 0})['kernel']
        fa.causal_attention(qkv, 4, 16, dropout=0.0)
        after = scaffold.routes_snapshot()['flash_attention']['kernel']
        assert after == before + 1
