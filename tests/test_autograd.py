"""Autograd engine tests (reference pattern: imperative tests —
BasicEngine/PartialGradEngine semantics)."""
import numpy as np
import jax
import jax.numpy as jnp

import paddle_tpu as paddle


def test_backward_accumulates():
    x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
    (x * 2).sum().backward()
    (x * 3).sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [5.0, 5.0])


def test_clear_grad():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    (x * 2).backward()
    x.clear_grad()
    assert x.grad is None


def test_stop_gradient_blocks():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    y = paddle.to_tensor([2.0], stop_gradient=True)
    (x * y).backward()
    np.testing.assert_allclose(x.grad.numpy(), [2.0])
    assert y.grad is None


def test_detach():
    x = paddle.to_tensor([3.0], stop_gradient=False)
    y = x * 2
    z = y.detach() * x
    z.backward()
    np.testing.assert_allclose(x.grad.numpy(), [6.0])


def test_multi_output_op():
    x = paddle.to_tensor(np.arange(6, dtype='float32').reshape(2, 3),
                         stop_gradient=False)
    a, b, c = paddle.split(x, 3, axis=1)
    (a.sum() + 2 * c.sum()).backward()
    np.testing.assert_allclose(x.grad.numpy(),
                               [[1, 0, 2], [1, 0, 2]])


def test_no_grad_context():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    with paddle.no_grad():
        y = x * 5
    assert y.stop_gradient


def test_paddle_grad_nonleaf():
    x = paddle.to_tensor([2.0], stop_gradient=False)
    y = x * x          # non-leaf
    z = (y * y).sum()  # z = x^4, dz/dy = 2y = 8
    g = paddle.framework.grad(z, y)
    np.testing.assert_allclose(g[0].numpy(), [8.0])


def test_deep_chain_matches_jax():
    rng = np.random.RandomState(3)
    a = rng.randn(4, 4).astype('float32')

    def f(x):
        h = jnp.tanh(x @ x)
        h = jax.nn.softmax(h, axis=-1)
        return jnp.sum(h * h)

    t = paddle.to_tensor(a, stop_gradient=False)
    h = paddle.tanh(paddle.matmul(t, t))
    h = paddle.nn.functional.softmax(h)
    paddle.sum(h * h).backward()
    ref = jax.grad(f)(jnp.asarray(a))
    np.testing.assert_allclose(t.grad.numpy(), np.asarray(ref), rtol=1e-4,
                               atol=1e-5)


def test_pylayer():
    from paddle_tpu.autograd import PyLayer

    class Double(PyLayer):
        @staticmethod
        def forward(ctx, x):
            ctx.save_for_backward(x)
            return x * 2

        @staticmethod
        def backward(ctx, grad):
            return grad * 2

    x = paddle.to_tensor([3.0], stop_gradient=False)
    y = Double.apply(x)
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), [2.0])
    np.testing.assert_allclose(y.numpy(), [6.0])


def test_recompute_matches_plain():
    from paddle_tpu.distributed.fleet.utils import recompute
    net = paddle.nn.Sequential(paddle.nn.Linear(4, 4), paddle.nn.Tanh())
    x = paddle.to_tensor(np.random.RandomState(0)
                         .randn(2, 4).astype('float32'), stop_gradient=False)

    out1 = net(x)
    out1.sum().backward()
    g_plain = [p.grad.numpy().copy() for p in net.parameters()]
    for p in net.parameters():
        p.clear_grad()
    x.clear_grad()

    out2 = recompute(net, x)
    out2.sum().backward()
    g_rc = [p.grad.numpy() for p in net.parameters()]
    for a, b in zip(g_plain, g_rc):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


def test_register_hook():
    x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
    h = x.register_hook(lambda g: g * 2)
    (x * 3).sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [6.0, 6.0])
    h.remove()
    x.clear_grad()
    (x * 3).sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [3.0, 3.0])
    # observing hook on an intermediate
    y = paddle.to_tensor([2.0], stop_gradient=False)
    z = y * 4
    seen = []
    z.register_hook(lambda g: seen.append(g.numpy()) or None)
    (z * z).backward()
    np.testing.assert_allclose(seen[0], [16.0])
    np.testing.assert_allclose(y.grad.numpy(), [64.0])
