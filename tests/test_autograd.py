"""Autograd engine tests (reference pattern: imperative tests —
BasicEngine/PartialGradEngine semantics)."""
import numpy as np
import jax
import jax.numpy as jnp

import paddle_tpu as paddle


def test_backward_accumulates():
    x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
    (x * 2).sum().backward()
    (x * 3).sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [5.0, 5.0])


def test_clear_grad():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    (x * 2).backward()
    x.clear_grad()
    assert x.grad is None


def test_stop_gradient_blocks():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    y = paddle.to_tensor([2.0], stop_gradient=True)
    (x * y).backward()
    np.testing.assert_allclose(x.grad.numpy(), [2.0])
    assert y.grad is None


def test_detach():
    x = paddle.to_tensor([3.0], stop_gradient=False)
    y = x * 2
    z = y.detach() * x
    z.backward()
    np.testing.assert_allclose(x.grad.numpy(), [6.0])


def test_multi_output_op():
    x = paddle.to_tensor(np.arange(6, dtype='float32').reshape(2, 3),
                         stop_gradient=False)
    a, b, c = paddle.split(x, 3, axis=1)
    (a.sum() + 2 * c.sum()).backward()
    np.testing.assert_allclose(x.grad.numpy(),
                               [[1, 0, 2], [1, 0, 2]])


def test_no_grad_context():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    with paddle.no_grad():
        y = x * 5
    assert y.stop_gradient


def test_paddle_grad_nonleaf():
    x = paddle.to_tensor([2.0], stop_gradient=False)
    y = x * x          # non-leaf
    z = (y * y).sum()  # z = x^4, dz/dy = 2y = 8
    g = paddle.framework.grad(z, y)
    np.testing.assert_allclose(g[0].numpy(), [8.0])


def test_deep_chain_matches_jax():
    rng = np.random.RandomState(3)
    a = rng.randn(4, 4).astype('float32')

    def f(x):
        h = jnp.tanh(x @ x)
        h = jax.nn.softmax(h, axis=-1)
        return jnp.sum(h * h)

    t = paddle.to_tensor(a, stop_gradient=False)
    h = paddle.tanh(paddle.matmul(t, t))
    h = paddle.nn.functional.softmax(h)
    paddle.sum(h * h).backward()
    ref = jax.grad(f)(jnp.asarray(a))
    np.testing.assert_allclose(t.grad.numpy(), np.asarray(ref), rtol=1e-4,
                               atol=1e-5)


def test_pylayer():
    from paddle_tpu.autograd import PyLayer

    class Double(PyLayer):
        @staticmethod
        def forward(ctx, x):
            ctx.save_for_backward(x)
            return x * 2

        @staticmethod
        def backward(ctx, grad):
            return grad * 2

    x = paddle.to_tensor([3.0], stop_gradient=False)
    y = Double.apply(x)
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), [2.0])
    np.testing.assert_allclose(y.numpy(), [6.0])


def test_recompute_matches_plain():
    from paddle_tpu.distributed.fleet.utils import recompute
    net = paddle.nn.Sequential(paddle.nn.Linear(4, 4), paddle.nn.Tanh())
    x = paddle.to_tensor(np.random.RandomState(0)
                         .randn(2, 4).astype('float32'), stop_gradient=False)

    out1 = net(x)
    out1.sum().backward()
    g_plain = [p.grad.numpy().copy() for p in net.parameters()]
    for p in net.parameters():
        p.clear_grad()
    x.clear_grad()

    out2 = recompute(net, x)
    out2.sum().backward()
    g_rc = [p.grad.numpy() for p in net.parameters()]
    for a, b in zip(g_plain, g_rc):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


def test_register_hook():
    x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
    h = x.register_hook(lambda g: g * 2)
    (x * 3).sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [6.0, 6.0])
    h.remove()
    x.clear_grad()
    (x * 3).sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [3.0, 3.0])
    # observing hook on an intermediate
    y = paddle.to_tensor([2.0], stop_gradient=False)
    z = y * 4
    seen = []
    z.register_hook(lambda g: seen.append(g.numpy()) or None)
    (z * z).backward()
    np.testing.assert_allclose(seen[0], [16.0])
    np.testing.assert_allclose(y.grad.numpy(), [64.0])


# ---- round-2 fixes (ADVICE.md) ----------------------------------------------

def test_double_grad_create_graph():
    """d2/dx2 of x^2 = 2 (ADVICE: create_graph was silently ignored).
    Parity: PartialGradEngine create_graph (partial_grad_engine.cc)."""
    x = paddle.to_tensor([3.0], stop_gradient=False)
    y = x * x
    (g1,) = paddle.framework.grad(y, x, create_graph=True)
    assert not g1.stop_gradient
    (g2,) = paddle.framework.grad(g1, x)
    np.testing.assert_allclose(g1.numpy(), [6.0])
    np.testing.assert_allclose(g2.numpy(), [2.0])


def test_double_grad_mixed_expression():
    """grad of (dy/dx)^2 for y = x^3: d/dx (3x^2)^2 = 36 x^3."""
    x = paddle.to_tensor([2.0], stop_gradient=False)
    y = x * x * x
    (g1,) = paddle.framework.grad(y, x, create_graph=True)
    loss = (g1 * g1).sum()
    loss.backward()
    np.testing.assert_allclose(x.grad.numpy(), [36.0 * 8.0], rtol=1e-6)


def test_triple_grad():
    """d3/dx3 of x^4 = 24x."""
    x = paddle.to_tensor([1.5], stop_gradient=False)
    y = x * x * x * x
    (g1,) = paddle.framework.grad(y, x, create_graph=True)
    (g2,) = paddle.framework.grad(g1, x, create_graph=True)
    (g3,) = paddle.framework.grad(g2, x)
    np.testing.assert_allclose(g3.numpy(), [24.0 * 1.5], rtol=1e-6)


def test_grad_allow_unused_raises():
    """ADVICE: allow_unused=False must raise, not mask with zeros."""
    import pytest
    x = paddle.to_tensor([1.0], stop_gradient=False)
    z = paddle.to_tensor([1.0], stop_gradient=False)
    y = (x * 2).sum()
    with pytest.raises(RuntimeError, match="unreachable"):
        paddle.framework.grad(y, [x, z])
    gx, gz = paddle.framework.grad((x * 2).sum(), [x, z],
                                   allow_unused=True)
    np.testing.assert_allclose(gx.numpy(), [2.0])
    assert gz is None


def test_hook_fires_once_for_captured_intermediate():
    """ADVICE: grad hook double-fired when the hooked intermediate is also
    a paddle.grad capture target."""
    calls = []
    x = paddle.to_tensor([2.0], stop_gradient=False)
    y = x * x
    y.register_hook(lambda g: calls.append(float(g.numpy()[0])) or None)
    (gy,) = paddle.framework.grad((y * y).sum(), y)
    assert len(calls) == 1, calls
    np.testing.assert_allclose(gy.numpy(), [8.0])


def test_recompute_pylayer_accumulates_param_grads():
    """ADVICE: RecomputeFunction.apply returned None grads (re-forward ran
    under no_grad). Parity: fleet/utils/recompute.py:63."""
    from paddle_tpu.distributed.fleet.utils.recompute import (
        RecomputeFunction)
    x = paddle.to_tensor([[1.0, 2.0]], stop_gradient=False)
    w = paddle.to_tensor([[1.0], [3.0]], stop_gradient=False)

    def fn(a):
        return paddle.matmul(a, w)

    out = RecomputeFunction.apply(fn, True, x)
    out.sum().backward()
    assert x.grad is not None and w.grad is not None
    np.testing.assert_allclose(x.grad.numpy(), [[1.0, 3.0]])
    np.testing.assert_allclose(w.grad.numpy(), [[1.0], [2.0]])


def test_spmd_standalone_send_recv_raise():
    """ADVICE: send/recv built wrong ppermute pairs from the host rank;
    they now refuse inside SPMD regions (use ppermute/shift)."""
    import pytest
    import paddle_tpu.distributed.collective as C
    from paddle_tpu.distributed import topology_runtime
    topology_runtime.build_mesh(['dp'], [8])
    t = paddle.to_tensor([1.0])
    with C.spmd_region(('dp',)):
        with pytest.raises(NotImplementedError):
            C.send(t, dst=1)
        with pytest.raises(NotImplementedError):
            C.recv(t, src=0)
