"""fluid.layers legacy surface (VERDICT r3 #10): the legacy names resolve
on static.nn with legacy signatures, record real ops, and the recsys
layer wrappers create parameters. Plus the hapi ReduceLROnPlateau
callback (hapi/callbacks.py:956 parity)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.static as static
from paddle_tpu.static import nn as L
from paddle_tpu.core.tensor import Tensor


def test_legacy_name_coverage():
    names = [
        # elementwise / reduce / logic / compare
        'elementwise_add', 'elementwise_sub', 'elementwise_mul',
        'elementwise_div', 'elementwise_pow', 'elementwise_max',
        'elementwise_min', 'elementwise_mod', 'elementwise_floordiv',
        'reduce_sum', 'reduce_mean', 'reduce_max', 'reduce_min',
        'reduce_prod', 'reduce_all', 'reduce_any',
        'logical_and', 'logical_or', 'logical_not', 'equal', 'not_equal',
        'less_than', 'less_equal', 'greater_than', 'greater_equal',
        # creation / manipulation
        'fill_constant', 'fill_constant_batch_size_like', 'zeros', 'ones',
        'zeros_like', 'ones_like', 'eye', 'linspace', 'range',
        'create_tensor', 'create_global_var', 'create_parameter',
        'cast', 'concat', 'reshape', 'squeeze', 'unsqueeze', 'transpose',
        'split', 'stack', 'unstack', 'unbind', 'slice', 'strided_slice',
        'gather', 'gather_nd', 'scatter', 'expand', 'expand_as',
        'flatten', 'shard_index', 'shape', 'one_hot', 'where', 'topk',
        'argmax', 'argmin', 'argsort', 'unique', 'multiplex', 'diag',
        # math / nn
        'matmul', 'mul', 'scale', 'clip', 'clip_by_norm', 'l2_normalize',
        'pool2d', 'image_resize', 'resize_bilinear', 'resize_nearest',
        'cos_sim', 'increment', 'assign', 'sums', 'has_inf', 'has_nan',
        'hard_sigmoid', 'hard_swish', 'swish', 'mish', 'brelu',
        'soft_relu', 'stanh', 'leaky_relu', 'elu', 'selu', 'relu',
        'shuffle_channel', 'space_to_depth', 'add_position_encoding',
        'fsp_matrix', 'sampling_id', 'autoincreased_step_counter',
        # losses
        'log_loss', 'huber_loss', 'smooth_l1', 'bpr_loss', 'rank_loss',
        'margin_rank_loss', 'dice_loss', 'kldiv_loss', 'mse_loss',
        'sigmoid_cross_entropy_with_logits',
        'teacher_student_sigmoid_loss', 'square_error_cost',
        # recsys / contrib tier
        'continuous_value_model', 'data_norm', 'shuffle_batch',
        'batch_fc', 'rank_attention', 'tdm_child', 'tdm_sampler',
        'match_matrix_tensor', 'var_conv_2d', 'tree_conv',
        'search_pyramid_hash',
        # detection / sequence / control flow (re-exported)
        'yolo_box', 'prior_box', 'multiclass_nms', 'roi_align',
        'sequence_pad', 'sequence_pool', 'while_loop', 'cond',
    ]
    missing = [n for n in names if not hasattr(L, n)]
    assert not missing, missing


def test_legacy_semantics_spotchecks():
    x = Tensor(np.arange(6, dtype='float32').reshape(2, 3))
    y = Tensor(np.ones((3,), 'float32'))
    # axis-aligned elementwise broadcast
    out = L.elementwise_add(x, Tensor(np.array([10., 20.], 'float32')),
                            axis=0)
    np.testing.assert_allclose(np.asarray(out.data),
                               np.arange(6).reshape(2, 3)
                               + np.array([[10.], [20.]]))
    # reduce with legacy dim/keep_dim spelling
    r = L.reduce_sum(x, dim=1, keep_dim=True)
    np.testing.assert_allclose(np.asarray(r.data), [[3.], [12.]])
    r2 = L.reduce_mean(x)
    assert abs(float(r2) - 2.5) < 1e-6
    # fill_constant & batch_size_like
    f = L.fill_constant([2, 2], 'float32', 3.5)
    np.testing.assert_allclose(np.asarray(f.data), np.full((2, 2), 3.5))
    fb = L.fill_constant_batch_size_like(x, [-1, 5], 'float32', 1.0)
    assert fb.shape[0] == 2 and fb.shape[1] == 5
    # activations
    hs = L.hard_sigmoid(Tensor(np.array([-10., 0., 10.], 'float32')))
    np.testing.assert_allclose(np.asarray(hs.data), [0., 0.5, 1.])
    # losses
    hl = L.huber_loss(Tensor(np.array([[0.]], 'float32')),
                      Tensor(np.array([[2.]], 'float32')), delta=1.0)
    assert abs(float(np.asarray(hl.data).reshape(-1)[0]) - 1.5) < 1e-6


def test_legacy_layers_record_in_static_program():
    paddle.enable_static()
    try:
        main = static.Program()
        with static.program_guard(main):
            x = static.data('x', [4, 6])
            h = L.fc(x, 8, activation='relu')
            h = L.elementwise_add(h, h)
            s = L.reduce_sum(h, dim=1, keep_dim=True)
            loss = L.reduce_mean(s)
        types = [op.type for op in main.global_block().ops]
        assert 'elementwise_add' in types and 'reduce_sum' in types
        exe = static.Executor()
        with static.scope_guard(static.Scope()):
            out = exe.run(main,
                          feed={'x': np.ones((4, 6), 'float32')},
                          fetch_list=[loss])[0]
        assert np.isfinite(out).all()
    finally:
        paddle.disable_static()


def test_recsys_layer_wrappers_create_parameters():
    paddle.enable_static()
    try:
        main = static.Program()
        with static.program_guard(main):
            x = static.data('x', [4, 3, 5])     # [S, N, D] for batch_fc
            out = L.batch_fc(x, param_size=[4, 5, 2], bias_size=[4, 2])
            mm_x = static.data('mx', [2, 3, 6])
            mm_y = static.data('my', [2, 4, 6])
            mm = L.match_matrix_tensor(mm_x, mm_y, channel_num=2)
        assert len(main.all_parameters()) == 3   # w, b, match W
        assert list(out.shape) == [4, 3, 2]
        assert list(mm.shape) == [2, 2, 3, 4]
    finally:
        paddle.disable_static()


def test_data_norm_layer_normalizes():
    paddle.enable_static()
    try:
        main = static.Program()
        with static.program_guard(main):
            x = static.data('x', [8, 4])
            y = L.data_norm(x)
        exe = static.Executor()
        with static.scope_guard(static.Scope()):
            xs = np.random.RandomState(0).rand(8, 4).astype('float32')
            out = exe.run(main, feed={'x': xs}, fetch_list=[y])[0]
        # stats init: size=1e4, sum=0, sq=1e4 -> mean 0, scale 1
        np.testing.assert_allclose(out, xs, rtol=1e-4)
    finally:
        paddle.disable_static()


class _FakeModel:
    def __init__(self, opt):
        self._optimizer = opt
        self.stop_training = False


def test_reduce_lr_on_plateau_callback():
    from paddle_tpu.hapi import ReduceLROnPlateau
    opt = paddle.optimizer.SGD(learning_rate=0.1)
    cb = ReduceLROnPlateau(monitor='loss', factor=0.5, patience=2,
                           verbose=0, cooldown=1, min_lr=0.02)
    cb.set_model(_FakeModel(opt))
    # improving: no reduction
    for e, v in enumerate([1.0, 0.9, 0.8]):
        cb.on_epoch_end(e, {'loss': v})
    assert abs(opt.get_lr() - 0.1) < 1e-9
    # plateau: after `patience` bad epochs the lr halves
    cb.on_epoch_end(3, {'loss': 0.85})
    cb.on_epoch_end(4, {'loss': 0.85})
    assert abs(opt.get_lr() - 0.05) < 1e-9
    # cooldown epoch ignores the next bad reading
    cb.on_epoch_end(5, {'loss': 0.85})
    assert abs(opt.get_lr() - 0.05) < 1e-9
    # then two more bad epochs reduce again, clamped at min_lr
    cb.on_epoch_end(6, {'loss': 0.85})
    cb.on_epoch_end(7, {'loss': 0.85})
    assert abs(opt.get_lr() - 0.025) < 1e-9
    cb.on_epoch_end(8, {'loss': 0.85})
    cb.on_epoch_end(9, {'loss': 0.85})
    cb.on_epoch_end(10, {'loss': 0.85})
    assert opt.get_lr() >= 0.02 - 1e-12
