"""Serving engine: page-allocator invariants, ragged paged-attention
parity (Pallas interpret mode + dense fallback vs a per-sequence
oracle), continuous-batching equivalence with sequential generate,
preemption/resume correctness (ISSUE 5), copy-on-write prefix-cache
invariants and speculative-decode equivalence (ISSUE 9)."""
import math

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle
from paddle_tpu.core import flags
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.ops.pallas import paged_attention as pa
from paddle_tpu.serving import (KVPagePool, PoolExhausted, RequestState,
                                ServingConfig, ServingEngine)


# ---------------------------------------------------------------------------
# page allocator
# ---------------------------------------------------------------------------
class TestPageAllocator:
    def test_alloc_free_reuse_and_occupancy(self):
        pool = KVPagePool(num_pages=8, page_size=4)
        assert pool.pages_for(1) == 1 and pool.pages_for(4) == 1
        assert pool.pages_for(5) == 2 and pool.pages_for(0) == 1
        pool.ensure_capacity('a', 9)           # 3 pages
        pool.ensure_capacity('b', 4)           # 1 page
        assert pool.pages_in_use == 4 and pool.free_pages == 4
        assert pool.utilization() == 0.5
        assert len(pool.page_table('a')) == 3
        # growth is incremental, already-held pages are kept
        pool.ensure_capacity('a', 10)
        assert len(pool.page_table('a')) == 3
        pool.ensure_capacity('a', 13)
        assert len(pool.page_table('a')) == 4
        # release returns every page exactly once
        freed = pool.release('a')
        assert freed == 4
        assert pool.pages_in_use == 1 and pool.free_pages == 7
        assert pool.release('a') == 0          # idempotent
        # freed pages are reused
        pool.ensure_capacity('c', 8 * 4 - 4)   # everything left
        assert pool.free_pages == 0
        st = pool.stats()
        assert st['high_water'] == 8 and st['pages_in_use'] == 8
        assert st['alloc_total'] == 4 + 1 + 7 and st['free_total'] == 4

    def test_no_double_mapping(self):
        pool = KVPagePool(num_pages=6, page_size=2)
        pool.ensure_capacity('a', 6)
        pool.ensure_capacity('b', 6)
        pages_a = set(pool.page_table('a'))
        pages_b = set(pool.page_table('b'))
        assert not pages_a & pages_b
        assert pages_a | pages_b == set(range(6)) & (pages_a | pages_b)
        assert pool.pages_in_use + pool.free_pages == pool.num_pages

    def test_exhaustion_raises_and_partial_growth_kept(self):
        pool = KVPagePool(num_pages=3, page_size=4)
        pool.ensure_capacity('a', 8)           # 2 pages
        with pytest.raises(PoolExhausted):
            pool.ensure_capacity('b', 12)      # needs 3, only 1 free
        # the partial page stays mapped (caller preempts + retries)
        assert pool.pages_in_use == 3
        assert pool.pages_in_use + pool.free_pages == pool.num_pages
        pool.release('a')
        pool.ensure_capacity('b', 12)
        assert len(pool.page_table('b')) == 3


# ---------------------------------------------------------------------------
# copy-on-write prefix cache: allocator-level invariants (ISSUE 9)
# ---------------------------------------------------------------------------
def _partition_ok(pool):
    """free + cached + mapped partitions the pool at all times."""
    return (len(pool._free) + len(pool._cached) + len(pool._ref)
            == pool.num_pages)


class TestPrefixCacheAllocator:
    def test_refcount_share_and_exact_once_release(self):
        pool = KVPagePool(num_pages=8, page_size=4, prefix_cache=True)
        toks = list(range(100, 112))           # 3 full blocks
        pool.ensure_capacity('a', 12)
        pool.register_prefix('a', toks, written=12)
        # b maps all 3 indexed pages — same physical pages, ref 2
        assert pool.match_and_map('b', toks + [7, 8]) == 12
        assert pool.page_table('b') == pool.page_table('a')
        assert pool.shared_pages == 3
        assert pool.pages_in_use == 3 and _partition_ok(pool)
        # a releases: pages stay mapped for b (nothing reclaimed)
        assert pool.release('a') == 0
        assert pool.shared_pages == 0 and pool.pages_in_use == 3
        # b releases: indexed pages park in the cached set, not free
        assert pool.release('b') == 3
        assert pool.pages_in_use == 0 and pool.cached_pages == 3
        assert pool.free_pages == 8 and _partition_ok(pool)
        # double release stays a no-op
        assert pool.release('a') == 0 and pool.release('b') == 0
        # a third request resurrects them from the cached set
        assert pool.match_and_map('c', toks) == 12
        assert pool.cached_pages == 0 and pool.pages_in_use == 3
        assert pool.prefix_hits == 2 and pool.prefix_hit_tokens == 24

    def test_fork_on_divergence_shares_only_common_blocks(self):
        pool = KVPagePool(num_pages=16, page_size=4, prefix_cache=True)
        common = [1, 2, 3, 4, 5, 6, 7, 8]      # 2 full blocks
        pool.ensure_capacity('a', 12)
        pool.register_prefix('a', common + [9, 10, 11, 12], written=12)
        # b shares the first 2 blocks then DIVERGES at token 9: the
        # divergent tail must land in private pages (fork-on-write =
        # recompute from the page boundary, never touch shared pages)
        b_toks = common + [99, 98, 97, 96]
        assert pool.match_and_map('b', b_toks) == 8
        pool.ensure_capacity('b', 12)
        ta, tb = pool.page_table('a'), pool.page_table('b')
        assert tb[:2] == ta[:2]                # shared prefix blocks
        assert tb[2] != ta[2]                  # private divergent page
        assert pool.shared_pages == 2
        # b's divergent block registers under its own chain and is
        # matchable by a third request; a's block 2 stays distinct
        pool.register_prefix('b', b_toks, written=12)
        assert pool._match_pages(b_toks) == tb[:3]
        assert pool._match_pages(common + [9, 10, 11, 12]) == ta[:3]

    def test_match_is_capped_and_block_granular(self):
        pool = KVPagePool(num_pages=8, page_size=4, prefix_cache=True)
        toks = list(range(50, 58))             # 2 full blocks
        pool.ensure_capacity('a', 8)
        pool.register_prefix('a', toks, written=8)
        # limit (engine passes len-1 so one token stays to compute):
        # 7 tokens -> only the first full block matches
        assert pool.peek_prefix(toks, limit=7) == (4, 1, 0, 0)
        assert pool.match_and_map('b', toks, limit=7) == 4
        # partial block never matches: 6 tokens -> 1 block
        assert pool.peek_prefix(toks[:6]) == (4, 1, 0, 0)
        # disabled pool: no matching, no counting
        off = KVPagePool(num_pages=4, page_size=4)
        off.ensure_capacity('x', 4)
        off.register_prefix('x', [1, 2, 3, 4], written=4)
        assert off.peek_prefix([1, 2, 3, 4]) == (0, 0, 0, 0)
        assert off.match_and_map('y', [1, 2, 3, 4]) == 0
        assert off.prefix_misses == 0

    def test_eviction_reclaims_cached_subtree_lru(self):
        pool = KVPagePool(num_pages=4, page_size=4, prefix_cache=True)
        chain = list(range(10, 22))            # 3 blocks
        pool.ensure_capacity('a', 12)
        pool.register_prefix('a', chain, written=12)
        pool.release('a')
        assert pool.cached_pages == 3 and pool.free_pages == 4
        # allocating 2 pages: 1 free + evicting the LRU root drops the
        # WHOLE chain (descendants keyed on a recycled parent id would
        # be a stale-chain hazard), so everything is allocatable
        pool.ensure_capacity('b', 8)
        assert pool.pages_in_use == 2
        assert pool.prefix_evictions == 3
        assert pool._match_pages(chain) == []  # index fully dropped
        assert _partition_ok(pool)
        # pool can still be filled to the brim
        pool.ensure_capacity('b', 16)
        assert pool.free_pages == 0
        with pytest.raises(PoolExhausted):
            pool.ensure_capacity('c', 4)

    def test_match_after_partial_allocation_is_noop(self):
        # review fix: a prefill retried after PoolExhausted kept its
        # partial pages; the lookup must degrade to a miss (shared
        # pages go at the FRONT of the table), not crash
        pool = KVPagePool(num_pages=8, page_size=4, prefix_cache=True)
        toks = list(range(40, 48))
        pool.ensure_capacity('a', 8)
        pool.register_prefix('a', toks, written=8)
        pool.ensure_capacity('b', 4)           # partial growth kept
        assert pool.match_and_map('b', toks) == 0
        assert len(pool.page_table('b')) == 1

    def test_deep_chain_eviction_is_iterative(self):
        # review fix: chains grow one node per page; at page_size=1
        # they get deeper than Python's recursion limit — eviction
        # must not blow the stack (or half-mutate the index)
        n = 1200
        pool = KVPagePool(num_pages=n, page_size=1, prefix_cache=True)
        toks = list(range(n))
        pool.ensure_capacity('a', n)
        pool.register_prefix('a', toks, written=n)
        pool.release('a')
        assert pool.cached_pages == n
        pool.ensure_capacity('b', 2)           # evicts the LRU chain
        assert pool.prefix_evictions == n
        assert pool._match_pages(toks) == []
        assert _partition_ok(pool)

    def test_trim_returns_private_tail_only(self):
        pool = KVPagePool(num_pages=8, page_size=4, prefix_cache=True)
        toks = list(range(60, 68))
        pool.ensure_capacity('a', 16)          # 4 pages
        pool.register_prefix('a', toks, written=8)
        # trim to 9 tokens: pages 3 and... keep=3, page 3 freed; the
        # indexed pages (0, 1) and page 2 stay
        assert pool.trim('a', 9) == 1
        assert len(pool.page_table('a')) == 3
        # shared page is never trimmed even when trailing
        pool2 = KVPagePool(num_pages=8, page_size=4, prefix_cache=True)
        pool2.ensure_capacity('x', 8)
        pool2.register_prefix('x', toks, written=8)
        pool2.match_and_map('y', toks, limit=None)
        assert pool2.trim('y', 1) == 0         # both pages indexed
        assert len(pool2.page_table('y')) == 2


# ---------------------------------------------------------------------------
# ragged paged attention: kernel + fallback vs a per-sequence oracle
# ---------------------------------------------------------------------------
def _oracle(q, k_pages, v_pages, page_tables, seq_lens, q_lens, H, D):
    """Host reference: gather each row's tokens from its pages, run
    plain per-head causal softmax attention over the valid prefix."""
    q = np.asarray(q, np.float64)
    kp = np.asarray(k_pages, np.float64)
    vp = np.asarray(v_pages, np.float64)
    B, T, HD = q.shape
    ps = kp.shape[1]
    out = np.zeros_like(q)
    for b in range(B):
        S, QL = int(seq_lens[b]), int(q_lens[b])
        keys = np.concatenate([kp[p] for p in page_tables[b]], 0)[:S]
        vals = np.concatenate([vp[p] for p in page_tables[b]], 0)[:S]
        for t in range(QL):
            pos = S - QL + t
            for h in range(H):
                qh = q[b, t, h * D:(h + 1) * D] / math.sqrt(D)
                s = keys[:pos + 1, h * D:(h + 1) * D] @ qh
                p_ = np.exp(s - s.max())
                p_ /= p_.sum()
                out[b, t, h * D:(h + 1) * D] = \
                    p_ @ vals[:pos + 1, h * D:(h + 1) * D]
    return out


def _mixed_case(dtype=np.float32, seed=0):
    """Mixed decode/prefill rows; row contexts span 1..4 pages; page
    tables deliberately shuffled so page order != pool order."""
    rng = np.random.RandomState(seed)
    B, T, H, D, ps, P = 3, 4, 2, 8, 8, 4
    HD = H * D
    num_pages = B * P + 3
    q = rng.randn(B, T, HD).astype(dtype)
    k_pages = rng.randn(num_pages, ps, HD).astype(dtype)
    v_pages = rng.randn(num_pages, ps, HD).astype(dtype)
    page_tables = rng.permutation(num_pages)[:B * P] \
        .reshape(B, P).astype(np.int32)
    # (seq_len, q_len): decode row, pure-prefill row, long multi-page
    # row with padding (q_len < T)
    lens = np.asarray([[13, 1], [4, 4], [29, 2]], np.int32)
    return (q, k_pages, v_pages, page_tables, lens[:, 0], lens[:, 1],
            H, D)


class TestRaggedPagedAttention:
    def test_kernel_matches_oracle_fp32(self):
        q, kp, vp, pt, sl, ql, H, D = _mixed_case()
        o = pa.ragged_paged_attention_pallas(
            jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
            jnp.asarray(pt), jnp.asarray(sl), jnp.asarray(ql),
            num_heads=H, head_dim=D)
        ref = _oracle(q, kp, vp, pt, sl, ql, H, D)
        for b in range(q.shape[0]):
            np.testing.assert_allclose(
                np.asarray(o)[b, :ql[b]], ref[b, :ql[b]],
                rtol=2e-4, atol=2e-5)

    def test_dense_fallback_matches_oracle_fp32(self):
        q, kp, vp, pt, sl, ql, H, D = _mixed_case(seed=1)
        o = pa.ragged_paged_attention_dense(
            jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
            jnp.asarray(pt), jnp.asarray(sl), jnp.asarray(ql),
            num_heads=H, head_dim=D)
        ref = _oracle(q, kp, vp, pt, sl, ql, H, D)
        for b in range(q.shape[0]):
            np.testing.assert_allclose(
                np.asarray(o)[b, :ql[b]], ref[b, :ql[b]],
                rtol=2e-4, atol=2e-5)

    def test_kernel_matches_dense_bf16(self):
        q, kp, vp, pt, sl, ql, H, D = _mixed_case()
        qb = jnp.asarray(q, jnp.bfloat16)
        kb = jnp.asarray(kp, jnp.bfloat16)
        vb = jnp.asarray(vp, jnp.bfloat16)
        o_k = pa.ragged_paged_attention_pallas(
            qb, kb, vb, jnp.asarray(pt), jnp.asarray(sl),
            jnp.asarray(ql), num_heads=H, head_dim=D)
        o_d = pa.ragged_paged_attention_dense(
            qb, kb, vb, jnp.asarray(pt), jnp.asarray(sl),
            jnp.asarray(ql), num_heads=H, head_dim=D)
        for b in range(q.shape[0]):
            np.testing.assert_allclose(
                np.asarray(o_k, np.float32)[b, :ql[b]],
                np.asarray(o_d, np.float32)[b, :ql[b]],
                rtol=5e-2, atol=5e-2)

    def test_route_selection(self):
        assert not pa.use_pallas_route()       # CPU test mesh -> dense
        flags.set_flags({'FLAGS_paged_attention_kernel': True})
        try:
            assert pa.use_pallas_route()
        finally:
            flags.set_flags({'FLAGS_paged_attention_kernel': None})
        assert not pa.use_pallas_route()

    def test_write_kv_pages_scatter(self):
        ps, HD, N = 4, 6, 5
        kp = jnp.zeros((N, ps, HD))
        vp = jnp.zeros((N, ps, HD))
        # row 0: 2 valid tokens at positions 5, 6 (page_table[1] slots
        # 1, 2); row 1: q_len=0 idle slot, nothing may be written
        k_new = jnp.arange(2 * 3 * HD, dtype=jnp.float32) \
            .reshape(2, 3, HD) + 1.0
        pt = jnp.asarray([[3, 1, 0, 0], [2, 2, 2, 2]], jnp.int32)
        sl = jnp.asarray([7, 1], jnp.int32)
        ql = jnp.asarray([2, 0], jnp.int32)
        kp2, vp2 = pa.write_kv_pages(kp, vp, k_new, 2 * k_new, pt, sl, ql)
        kp2 = np.asarray(kp2)
        np.testing.assert_allclose(kp2[1, 1], np.asarray(k_new)[0, 0])
        np.testing.assert_allclose(kp2[1, 2], np.asarray(k_new)[0, 1])
        # nothing else written: total nonzero rows == 2
        assert (np.abs(kp2).sum(-1) > 0).sum() == 2
        np.testing.assert_allclose(np.asarray(vp2)[1, 1],
                                   2 * np.asarray(k_new)[0, 0])


# ---------------------------------------------------------------------------
# continuous batching vs sequential generate
# ---------------------------------------------------------------------------
@pytest.fixture(scope='module')
def tiny_lm():
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM
    paddle.seed(7)
    cfg = GPTConfig(vocab_size=128, hidden_size=64, num_layers=2,
                    num_heads=2, max_seq_len=128, hidden_dropout=0.0,
                    attn_dropout=0.0, use_flash_attention=False)
    m = GPTForCausalLM(cfg)
    m.eval()
    return m


@pytest.fixture(scope='module')
def mixed_prompts():
    rng = np.random.RandomState(3)
    return [list(rng.randint(1, 128, n)) for n in (5, 11, 3, 17, 8)]


@pytest.fixture(scope='module')
def sequential_greedy(tiny_lm, mixed_prompts):
    outs = []
    for p in mixed_prompts:
        out = tiny_lm.generate(Tensor(np.asarray([p], 'int32')),
                               max_new_tokens=6, top_k=0, use_cache=True)
        outs.append(np.asarray(out.data)[0].tolist())
    return outs


class TestContinuousBatching:
    def test_equivalence_with_sequential_generate(
            self, tiny_lm, mixed_prompts, sequential_greedy):
        eng = ServingEngine(tiny_lm, ServingConfig(
            page_size=8, max_batch_size=3, prefill_chunk=8))
        outs = eng.generate(mixed_prompts, max_new_tokens=6, top_k=0)
        assert outs == sequential_greedy
        st = eng.stats()
        assert st['requests_completed_total'] == len(mixed_prompts)
        assert st['decode_tokens_per_sec'] > 0
        assert 0 < st['batch_occupancy'] <= 1
        # every page back in the free list after the stream drains
        assert eng.pool.pages_in_use == 0
        eng.shutdown()

    def test_preemption_resume_equivalence(
            self, tiny_lm, mixed_prompts, sequential_greedy):
        # 4 pages of 8 tokens can't hold the concurrent contexts this
        # stream grows into: the scheduler must preempt and resume, and
        # outputs must not change (greedy decode is deterministic)
        eng = ServingEngine(tiny_lm, ServingConfig(
            page_size=8, max_batch_size=3, prefill_chunk=8, num_pages=4))
        outs = eng.generate(mixed_prompts, max_new_tokens=6, top_k=0)
        assert outs == sequential_greedy
        assert eng.stats()['preemptions_total'] > 0
        assert eng.pool.pages_in_use == 0
        eng.shutdown()

    def test_pool_too_small_raises(self, tiny_lm):
        eng = ServingEngine(tiny_lm, ServingConfig(
            page_size=8, max_batch_size=2, prefill_chunk=8, num_pages=1))
        with pytest.raises(PoolExhausted, match='raise num_pages'):
            eng.generate([[1, 2, 3]], max_new_tokens=16, top_k=0)

    def test_request_validation(self, tiny_lm):
        eng = ServingEngine(tiny_lm, ServingConfig(
            page_size=8, max_batch_size=2, max_pages_per_seq=2))
        with pytest.raises(ValueError, match='page table holds'):
            eng.submit(list(range(1, 15)), max_new_tokens=8)
        with pytest.raises(ValueError, match='empty prompt'):
            eng.submit([], max_new_tokens=4)

    def test_admission_respects_page_budget(self, tiny_lm):
        # 3 free slots but pages for only ONE first chunk: admission
        # must stop at the budget, not fill every slot and churn
        eng = ServingEngine(tiny_lm, ServingConfig(
            page_size=8, max_batch_size=3, prefill_chunk=8, num_pages=1))
        for p in ([1] * 6, [2] * 6, [3] * 6):
            eng.submit(list(p), max_new_tokens=2)
        eng._admit()
        assert len(eng.scheduler.running()) == 1
        assert len(eng.scheduler.waiting) == 2

    def test_admit_oversized_head_does_not_starve_followers(
            self, tiny_lm):
        # ISSUE 11 satellite: the queue HEAD needs 2 pages but only 1
        # is free — the old sweep broke at the head and left an
        # admissible 1-page follower starving behind it. The head must
        # be skipped (keeping its queue position) and the follower
        # admitted in the SAME sweep.
        eng = ServingEngine(tiny_lm, ServingConfig(
            page_size=8, max_batch_size=3, prefill_chunk=16,
            num_pages=3, prefix_cache=False))
        blocker = eng.submit([9] * 6, max_new_tokens=10)
        eng.step()                      # holds 1 page, decodes on
        assert blocker.state == RequestState.RUNNING
        head = eng.submit(list(range(1, 18)), max_new_tokens=2)
        follower = eng.submit([7] * 6, max_new_tokens=2)
        assert eng.pool.free_pages == 2     # head's chunk needs 2,
        eng.pool.ensure_capacity('pin', 8)  # pin one -> budget 1
        assert eng._admit() == 1
        assert follower.state == RequestState.PREFILL
        assert eng.scheduler.waiting == [head]   # kept FCFS position
        eng.pool.release('pin')
        # next sweep's budget fits the head again
        assert eng._admit() == 1
        assert head.state == RequestState.PREFILL
        while eng.scheduler.has_work:
            eng.step()
        eng.shutdown()

    def test_admit_bypass_bound_prevents_head_starvation(
            self, tiny_lm):
        # the fairness scan is BOUNDED: once HOL_BYPASS_LIMIT
        # followers have been admitted past a budget-blocked head, the
        # sweep reverts to blocking at the head so freed pages can
        # accumulate for it instead of feeding a small-request stream
        # forever
        eng = ServingEngine(tiny_lm, ServingConfig(
            page_size=8, max_batch_size=2, prefill_chunk=16,
            num_pages=3, prefix_cache=False))
        eng.pool.ensure_capacity('pin', 16)     # 1 page budget left
        head = eng.submit(list(range(1, 18)), max_new_tokens=2)
        eng.submit([7] * 6, max_new_tokens=2)
        assert eng._admit() == 1                # follower bypasses
        assert eng.scheduler.waiting == [head]
        assert head.admit_bypasses == 1
        head.admit_bypasses = ServingEngine.HOL_BYPASS_LIMIT
        follower2 = eng.submit([8] * 6, max_new_tokens=2)
        assert eng._admit() == 0                # bound hit: sweep
        assert follower2.state == RequestState.WAITING  # blocks at head
        eng.pool.release('pin')
        # head fits now and takes the one remaining slot FIRST
        assert eng._admit() == 1
        assert head.state == RequestState.PREFILL
        assert eng.scheduler.waiting == [follower2]
        eng.shutdown()

    def test_generate_batch_config_change_replaces_engine(
            self, tiny_lm):
        tiny_lm.generate_batch([[1, 2, 3]], max_new_tokens=2, top_k=0,
                               serving_config=ServingConfig(
                                   page_size=8, max_batch_size=2))
        (old,) = tiny_lm._serving_engines.values()
        assert old.config.max_batch_size == 2
        tiny_lm.generate_batch([[1, 2, 3]], max_new_tokens=2, top_k=0,
                               serving_config=ServingConfig(
                                   page_size=16, max_batch_size=4))
        (new,) = tiny_lm._serving_engines.values()
        # no silent config collision, and the evicted engine released
        # its device KV pool (one live pool per model, not a leak)
        assert new.config.max_batch_size == 4
        assert new is not old and old.pool.kv is None

    def test_oversized_request_rejected_at_submit(self, tiny_lm):
        # a request the pool can NEVER hold must fail fast, not sit in
        # the queue forever while the admission budget skips it
        eng = ServingEngine(tiny_lm, ServingConfig(
            page_size=8, max_batch_size=2, prefill_chunk=16,
            num_pages=1))
        with pytest.raises(PoolExhausted, match='raise num_pages'):
            eng.submit(list(range(1, 11)), max_new_tokens=0)
        assert not eng.scheduler.has_work

    def test_max_new_tokens_zero_emits_nothing(self, tiny_lm):
        eng = ServingEngine(tiny_lm, ServingConfig(
            page_size=8, max_batch_size=2, prefill_chunk=8))
        outs = eng.generate([[1, 2, 3], [4, 5]], max_new_tokens=0)
        assert outs == [[1, 2, 3], [4, 5]]     # prefill-only, no token
        assert eng.pool.pages_in_use == 0
        eng.shutdown()

    def test_generate_batch_method_and_engine_reuse(
            self, tiny_lm, mixed_prompts, sequential_greedy):
        outs = tiny_lm.generate_batch(mixed_prompts, max_new_tokens=6,
                                      top_k=0, page_size=8,
                                      max_batch_size=3, prefill_chunk=8)
        assert outs == sequential_greedy
        eng = tiny_lm._serving_engines
        outs2 = tiny_lm.generate_batch(mixed_prompts[:2],
                                       max_new_tokens=6, top_k=0,
                                       page_size=8, max_batch_size=3,
                                       prefill_chunk=8)
        assert outs2 == sequential_greedy[:2]
        assert tiny_lm._serving_engines is eng      # cached, not rebuilt

    def test_pallas_route_equivalence_short(self, tiny_lm):
        # force the kernel body (interpret mode on CPU) through a short
        # end-to-end decode and compare with the dense route
        prompts = [[5, 9, 2], [7, 1, 1, 1, 4]]
        eng_d = ServingEngine(tiny_lm, ServingConfig(
            page_size=8, max_batch_size=2, prefill_chunk=4))
        ref = eng_d.generate(prompts, max_new_tokens=3, top_k=0)
        eng_d.shutdown()
        flags.set_flags({'FLAGS_paged_attention_kernel': True})
        try:
            eng_k = ServingEngine(tiny_lm, ServingConfig(
                page_size=8, max_batch_size=2, prefill_chunk=4))
            outs = eng_k.generate(prompts, max_new_tokens=3, top_k=0)
            eng_k.shutdown()
        finally:
            flags.set_flags({'FLAGS_paged_attention_kernel': None})
        assert outs == ref

    def test_top_k_sampling_runs_on_device(self, tiny_lm):
        eng = ServingEngine(tiny_lm, ServingConfig(
            page_size=8, max_batch_size=2, prefill_chunk=8, seed=11))
        outs = eng.generate([[3, 4, 5], [9, 8]], max_new_tokens=5,
                            top_k=4, temperature=0.8)
        assert all(len(o) in (len(p) + 1, len(p) + 5)
                   or len(p) < len(o) <= len(p) + 5
                   for o, p in zip(outs, [[3, 4, 5], [9, 8]]))
        eng.shutdown()


# ---------------------------------------------------------------------------
# prefix caching + speculative decoding through the engine (ISSUE 9)
# ---------------------------------------------------------------------------
@pytest.fixture(scope='module')
def shared_prefix_prompts(tiny_lm):
    """Requests sharing a 24-token system prompt + distinct tails."""
    rng = np.random.RandomState(11)
    system = list(rng.randint(1, 128, 24))
    return [system + list(rng.randint(1, 128, n)) for n in (4, 7, 5, 9)]


class TestPrefixCacheEngine:
    def _run(self, tiny_lm, prompts, max_new=5, **cfg):
        eng = ServingEngine(tiny_lm, ServingConfig(
            page_size=8, max_batch_size=2, prefill_chunk=8, **cfg))
        outs = eng.generate(prompts, max_new_tokens=max_new, top_k=0)
        st = eng.stats()
        return eng, outs, st

    def test_shared_prefix_identical_outputs_fewer_prefill_tokens(
            self, tiny_lm, shared_prefix_prompts):
        eng0, ref, st0 = self._run(tiny_lm, shared_prefix_prompts,
                                   prefix_cache=False)
        eng0.shutdown()
        eng, outs, st = self._run(tiny_lm, shared_prefix_prompts)
        # acceptance: token-identical to the PR-5 path, and cache hits
        # skipped whole prefill chunks (the TTFT win)
        assert outs == ref
        assert st['prefix_hits_total'] >= 3
        # a sibling admitted mid-prefill only matches the blocks
        # registered so far, so the floor is one block for the
        # concurrent hit plus full 3-block (24-token) hits after
        assert st['prefix_hit_tokens_total'] >= 8 + 2 * 24
        assert st['prefill_tokens_total'] < st0['prefill_tokens_total']
        # every page released exactly once even through sharing: the
        # drained pool has nothing mapped, only resurrectable cache
        assert eng.pool.pages_in_use == 0
        assert eng.pool.cached_pages > 0
        assert eng.pool.free_pages == eng.pool.num_pages
        eng.shutdown()

    def test_concurrent_sharing_maps_same_physical_pages(
            self, tiny_lm, shared_prefix_prompts):
        # submit two shared-prefix requests and step just past both
        # prefills: the live page tables must overlap physically
        eng = ServingEngine(tiny_lm, ServingConfig(
            page_size=8, max_batch_size=2, prefill_chunk=32))
        r1 = eng.submit(shared_prefix_prompts[0], max_new_tokens=8)
        r2 = eng.submit(shared_prefix_prompts[1], max_new_tokens=8)
        for _ in range(3):
            eng.step()
        t1, t2 = eng.pool.page_table(r1.id), eng.pool.page_table(r2.id)
        assert t1[:3] == t2[:3]            # 24-token system prompt
        assert eng.pool.shared_pages >= 3
        # and the serve gauges see it
        eng.publish_metrics()
        from paddle_tpu.serving import metrics as sm
        snap = sm.serve_snapshot()
        assert snap['ptpu_serve_prefix_shared_pages'] >= 3
        assert snap['prefix_hit_rate'] is not None
        while eng.scheduler.has_work:
            eng.step()
        eng.shutdown()

    def test_preempt_resume_with_sharing_keeps_outputs(
            self, tiny_lm, shared_prefix_prompts):
        # pool pressure on a shared-prefix stream: preempting the
        # youngest must not yank pages its sibling still references,
        # and resume (which may prefix-hit its own cached pages) must
        # not change outputs
        eng0, ref, _ = self._run(tiny_lm, shared_prefix_prompts,
                                 max_new=6, prefix_cache=False,
                                 num_pages=64)
        eng0.shutdown()
        eng = ServingEngine(tiny_lm, ServingConfig(
            page_size=8, max_batch_size=3, prefill_chunk=8,
            num_pages=7))
        outs = eng.generate(shared_prefix_prompts, max_new_tokens=6,
                            top_k=0)
        assert outs == ref
        assert eng.stats()['preemptions_total'] > 0
        assert eng.pool.pages_in_use == 0
        eng.shutdown()

    def test_admission_budget_counts_shared_pages_once(
            self, tiny_lm, shared_prefix_prompts):
        # the ISSUE 9 satellite fix: with most of the first chunk
        # covered by live shared pages, a second request must be
        # admitted even when the free budget alone could not hold its
        # whole first chunk (the PR-5 estimate charged every chunk
        # page and refused)
        eng = ServingEngine(tiny_lm, ServingConfig(
            page_size=8, max_batch_size=2, prefill_chunk=32,
            num_pages=6))
        first = eng.submit(shared_prefix_prompts[0], max_new_tokens=4)
        while first.state != RequestState.RUNNING:
            eng.step()
        # 4 pages mapped (25+ tokens); 2 free. A sibling's first chunk
        # is fully covered by the shared system prompt -> need 0 new
        eng.submit(shared_prefix_prompts[1], max_new_tokens=4)
        assert eng._admit() == 1
        while eng.scheduler.has_work:
            eng.step()
        eng.shutdown()

    def test_int8_kv_pages_share_scales(self, tiny_lm,
                                        shared_prefix_prompts):
        # quantized pools share pages AND their sibling scale buffers
        # (same page id addresses both); outputs stay identical to the
        # unshared int8 engine
        eng0, ref, _ = self._run(tiny_lm, shared_prefix_prompts,
                                 prefix_cache=False, kv_dtype='int8')
        eng0.shutdown()
        eng, outs, st = self._run(tiny_lm, shared_prefix_prompts,
                                  kv_dtype='int8')
        assert outs == ref
        assert st['prefix_hits_total'] >= 3
        assert eng.pool.quantized
        eng.shutdown()


class TestSpeculativeDecode:
    def test_ngram_proposer(self):
        from paddle_tpu.serving.engine import _ngram_propose
        # trailing bigram [3, 4] last recurs at position 2 -> proposes
        # the continuation that followed it
        t = [1, 2, 3, 4, 5, 6, 3, 4]
        assert _ngram_propose(t, 2, 3) == [5, 6, 3]
        # no recurrence of [5, 6] and no [6]: nothing to propose
        assert _ngram_propose([1, 2, 5, 6], 2, 3) == []
        # backoff to the unigram (most recent occurrence wins) when
        # the bigram never recurred
        assert _ngram_propose([7, 1, 2, 7, 3, 7], 2, 2) == [3, 7]
        # repetition loop proposes through the overlap
        assert _ngram_propose([9, 9, 9], 2, 4) == [9]
        assert _ngram_propose([5], 2, 4) == []
        assert _ngram_propose(t, 2, 0) == []

    def test_greedy_equivalence_with_spec_on(self, tiny_lm,
                                             mixed_prompts,
                                             sequential_greedy):
        # acceptance: speculation ON is token-identical to OFF, across
        # page boundaries (page_size 8, contexts grow past 16)
        eng = ServingEngine(tiny_lm, ServingConfig(
            page_size=8, max_batch_size=3, prefill_chunk=8, spec_k=4))
        outs = eng.generate(mixed_prompts, max_new_tokens=6, top_k=0)
        assert outs == sequential_greedy
        assert eng.pool.pages_in_use == 0
        eng.shutdown()

    def test_spec_accepts_drafts_and_advances_multitoken(
            self, tiny_lm, mixed_prompts):
        eng0 = ServingEngine(tiny_lm, ServingConfig(
            page_size=8, max_batch_size=3, prefill_chunk=8,
            prefix_cache=False))
        ref = eng0.generate(mixed_prompts, max_new_tokens=16, top_k=0)
        st0 = eng0.stats()
        eng0.shutdown()
        eng = ServingEngine(tiny_lm, ServingConfig(
            page_size=8, max_batch_size=3, prefill_chunk=8, spec_k=4))
        outs = eng.generate(mixed_prompts, max_new_tokens=16, top_k=0)
        st = eng.stats()
        assert outs == ref
        # the tiny model settles into repetition, so the n-gram
        # proposer fires and the verify step accepts drafts: more than
        # one token per decode dispatch (deterministic: fixed seeds)
        assert st['spec_proposed_tokens_total'] > 0
        assert st['spec_accepted_tokens_total'] > 0
        assert st['decode_steps_total'] < st0['decode_steps_total']
        assert st['decode_tokens_total'] == st0['decode_tokens_total']
        assert 0 < st['spec_acceptance_rate'] <= 1
        eng.shutdown()

    def test_spec_eos_early_exit_token_identical(self, tiny_lm,
                                                 mixed_prompts):
        # pick an eos that actually occurs mid-stream in the baseline
        # output, then require speculation to stop at exactly the same
        # token — nothing after eos may escape a multi-token burst
        eng0 = ServingEngine(tiny_lm, ServingConfig(
            page_size=8, max_batch_size=3, prefill_chunk=8,
            prefix_cache=False))
        base = eng0.generate(mixed_prompts, max_new_tokens=12, top_k=0)
        eng0.shutdown()
        gen0 = [o[len(p):] for o, p in zip(base, mixed_prompts)]
        eos = gen0[0][len(gen0[0]) // 2]       # fires mid-generation
        ref = []
        for g, p in zip(gen0, mixed_prompts):
            cut = g.index(eos) + 1 if eos in g else len(g)
            ref.append(p + g[:cut])
        eng = ServingEngine(tiny_lm, ServingConfig(
            page_size=8, max_batch_size=3, prefill_chunk=8, spec_k=4))
        outs = eng.generate(mixed_prompts, max_new_tokens=12,
                            eos_token_id=int(eos), top_k=0)
        assert outs == ref
        for o, p in zip(outs, mixed_prompts):
            gen = o[len(p):]
            assert eos not in gen[:-1]         # eos only terminal
        eng.shutdown()

    def test_spec_respects_max_new_tokens(self, tiny_lm):
        eng = ServingEngine(tiny_lm, ServingConfig(
            page_size=8, max_batch_size=2, prefill_chunk=8, spec_k=4))
        outs = eng.generate([[1, 2, 3, 1, 2, 3, 1, 2]],
                            max_new_tokens=3, top_k=0)
        assert len(outs[0]) == 8 + 3
        eng.shutdown()

    def test_spec_with_sampling_rows_mixed_batch(self, tiny_lm):
        # greedy rows speculate; a top-k row rides the same verify
        # dispatch through the sampled column — both must complete
        eng = ServingEngine(tiny_lm, ServingConfig(
            page_size=8, max_batch_size=2, prefill_chunk=8, spec_k=3,
            seed=5))
        greedy = eng.submit([1, 2, 3, 1, 2, 3, 1], max_new_tokens=8,
                            top_k=0)
        sampled = eng.submit([4, 5, 6, 7], max_new_tokens=8, top_k=4,
                             temperature=0.9)
        while eng.scheduler.has_work:
            eng.step()
        assert len(greedy.generated) == 8
        assert 1 <= len(sampled.generated) <= 8
        eng.shutdown()

    def test_spec_with_prefix_and_preemption_pressure(
            self, tiny_lm, shared_prefix_prompts):
        # everything on at once under pool pressure: outputs must
        # still match the plain PR-5 engine
        eng0 = ServingEngine(tiny_lm, ServingConfig(
            page_size=8, max_batch_size=3, prefill_chunk=8,
            prefix_cache=False))
        ref = eng0.generate(shared_prefix_prompts, max_new_tokens=8,
                            top_k=0)
        eng0.shutdown()
        eng = ServingEngine(tiny_lm, ServingConfig(
            page_size=8, max_batch_size=3, prefill_chunk=8, spec_k=4,
            num_pages=9))
        outs = eng.generate(shared_prefix_prompts, max_new_tokens=8,
                            top_k=0)
        assert outs == ref
        assert eng.pool.pages_in_use == 0
        eng.shutdown()

    def test_spec_trace_and_gauges(self, tiny_lm, mixed_prompts):
        eng = ServingEngine(tiny_lm, ServingConfig(
            page_size=8, max_batch_size=3, prefill_chunk=8, spec_k=4))
        eng.generate(mixed_prompts, max_new_tokens=16, top_k=0)
        eng.publish_metrics()
        from paddle_tpu.serving import metrics as sm
        snap = sm.serve_snapshot()
        assert snap['ptpu_serve_spec_proposed_tokens_total'] > 0
        assert snap['spec_acceptance_rate'] is not None
        # journals carry spec_verify events; reconstruct() aggregates
        # per-request proposed/accepted
        table = eng.request_table()
        assert sum(r['spec_proposed'] for r in table.values()) == \
            eng.stats()['spec_proposed_tokens_total']
        assert sum(r['spec_accepted'] for r in table.values()) == \
            eng.stats()['spec_accepted_tokens_total']
        eng.shutdown()


# ---------------------------------------------------------------------------
# int8 KV pages + weight-only-quantized decode (ISSUE 7)
# ---------------------------------------------------------------------------
class TestQuantizedKV:
    def test_quantized_kernel_and_fallback_match_oracle(self):
        # quantize fp32 pages per (slot, head); the dequantizing kernel
        # and dense fallback must agree with each other to fp32
        # precision and sit within the int8 rounding envelope of the
        # unquantized oracle — across page boundaries (rows span 1..4
        # pages, shuffled tables)
        q, kp, vp, pt, sl, ql, H, D = _mixed_case(seed=2)
        N, ps, HD = kp.shape
        kq, ks = pa.quantize_kv_rows(jnp.asarray(kp), H)
        vq, vs = pa.quantize_kv_rows(jnp.asarray(vp), H)
        kq, vq = kq.reshape(N, ps, HD), vq.reshape(N, ps, HD)
        ks, vs = ks.reshape(N, ps, H), vs.reshape(N, ps, H)
        o_k = pa.ragged_paged_attention_pallas(
            jnp.asarray(q), kq, vq, jnp.asarray(pt), jnp.asarray(sl),
            jnp.asarray(ql), num_heads=H, head_dim=D,
            k_scales=ks, v_scales=vs)
        o_d = pa.ragged_paged_attention_dense(
            jnp.asarray(q), kq, vq, jnp.asarray(pt), jnp.asarray(sl),
            jnp.asarray(ql), num_heads=H, head_dim=D,
            k_scales=ks, v_scales=vs)
        ref = _oracle(q, kp, vp, pt, sl, ql, H, D)
        for b in range(q.shape[0]):
            np.testing.assert_allclose(
                np.asarray(o_k)[b, :ql[b]], np.asarray(o_d)[b, :ql[b]],
                rtol=2e-4, atol=2e-5)
            np.testing.assert_allclose(
                np.asarray(o_d)[b, :ql[b]], ref[b, :ql[b]],
                rtol=5e-2, atol=5e-2)

    def test_write_kv_pages_quantized_scatter(self):
        ps, H, D, N = 4, 2, 3, 5
        HD = H * D
        kp = jnp.zeros((N, ps, HD), jnp.int8)
        vp = jnp.zeros((N, ps, HD), jnp.int8)
        ks = jnp.zeros((N, ps, H))
        vs = jnp.zeros((N, ps, H))
        k_new = jnp.asarray(
            np.arange(2 * 3 * HD, dtype=np.float32).reshape(2, 3, HD)
            + 1.0)
        pt = jnp.asarray([[3, 1, 0, 0], [2, 2, 2, 2]], jnp.int32)
        sl = jnp.asarray([7, 1], jnp.int32)
        ql = jnp.asarray([2, 0], jnp.int32)    # row 1 idle: no writes
        kp2, vp2, ks2, vs2 = pa.write_kv_pages_quantized(
            kp, vp, ks, vs, k_new, 2 * k_new, pt, sl, ql, num_heads=H)
        kp2, ks2 = np.asarray(kp2), np.asarray(ks2)
        # positions 5, 6 of row 0 -> page_table[1] slots 1, 2; the
        # dequantized rows must match the written values within half a
        # bin of the per-(slot, head) scale
        want = np.asarray(k_new)[0, :2].reshape(2, H, D)
        for slot, tok in ((1, 0), (2, 1)):
            deq = (kp2[1, slot].reshape(H, D).astype(np.float32)
                   * ks2[1, slot][:, None])
            bound = ks2[1, slot][:, None] / 2 + 1e-6
            assert (np.abs(deq - want[tok]) <= bound).all()
        # nothing else written (idle row dropped by the scatter)
        assert (np.abs(kp2).sum(-1) > 0).sum() == 2
        assert (ks2 > 0).sum() == 2 * H
        vdeq = (np.asarray(vp2)[1, 1].reshape(H, D).astype(np.float32)
                * np.asarray(vs2)[1, 1][:, None])
        assert (np.abs(vdeq - 2 * want[0])
                <= np.asarray(vs2)[1, 1][:, None] / 2 + 1e-6).all()

    def test_int8_kv_engine_matches_fp32_greedy(
            self, tiny_lm, mixed_prompts, sequential_greedy):
        # acceptance: int8-KV continuous batching == fp32-KV greedy
        # outputs across page boundaries, on BOTH routes
        eng = ServingEngine(tiny_lm, ServingConfig(
            page_size=8, max_batch_size=3, prefill_chunk=8,
            kv_dtype='int8'))
        outs = eng.generate(mixed_prompts, max_new_tokens=6, top_k=0)
        assert outs == sequential_greedy
        assert eng.pool.quantized
        assert eng.pool.stats()['kv_dtype'] == 'int8'
        eng.shutdown()
        flags.set_flags({'FLAGS_paged_attention_kernel': True})
        try:
            eng_k = ServingEngine(tiny_lm, ServingConfig(
                page_size=8, max_batch_size=3, prefill_chunk=8,
                kv_dtype='int8'))
            outs_k = eng_k.generate(mixed_prompts, max_new_tokens=6,
                                    top_k=0)
            eng_k.shutdown()
        finally:
            flags.set_flags({'FLAGS_paged_attention_kernel': None})
        assert outs_k == sequential_greedy

    def test_int8_kv_preemption_resume_equivalence(
            self, tiny_lm, mixed_prompts, sequential_greedy):
        # pool pressure exercises preempt/re-prefill on quantized
        # pages: slots re-quantize on resume, outputs must not change
        eng = ServingEngine(tiny_lm, ServingConfig(
            page_size=8, max_batch_size=3, prefill_chunk=8,
            num_pages=4, kv_dtype='int8'))
        outs = eng.generate(mixed_prompts, max_new_tokens=6, top_k=0)
        assert outs == sequential_greedy
        assert eng.stats()['preemptions_total'] > 0
        eng.shutdown()

    def test_int8_pool_capacity_at_least_2x(self, tiny_lm):
        # acceptance: the int8 pool fits >= 2x the in-flight tokens at
        # the same byte budget vs the default (fp32 on CPU) pool
        dense = ServingEngine(tiny_lm, ServingConfig(
            page_size=8, max_batch_size=2))
        quant = ServingEngine(tiny_lm, ServingConfig(
            page_size=8, max_batch_size=2, kv_dtype='int8'))
        d, qs = dense.pool.stats(), quant.pool.stats()
        assert d['num_pages'] == qs['num_pages']
        ratio = d['bytes_per_token'] / qs['bytes_per_token']
        assert ratio >= 2.0, ratio
        assert qs['pool_bytes'] * 2 <= d['pool_bytes']
        # byte math is exact: int8 pages + fp32 per-(slot, head) scales
        attn = tiny_lm.gpt.layers[0].attn
        hd = attn.local_heads * attn.head_dim
        per_tok = 2 * (hd + attn.local_heads * 4) * \
            tiny_lm.config.num_layers
        assert qs['bytes_per_token'] == per_tok
        dense.shutdown()
        quant.shutdown()


class TestWeightOnlyQuantizedDecode:
    def test_predictor_decode_top1_equivalent(
            self, tiny_lm, mixed_prompts, sequential_greedy):
        # acceptance: weight-only-quantized decode through the
        # inference.Predictor produces top-1-equivalent greedy output
        from paddle_tpu import inference
        cfg = inference.Config()
        cfg.enable_serving_engine(tiny_lm, max_new_tokens=6, top_k=0,
                                  page_size=8, max_batch_size=3,
                                  prefill_chunk=8, weight_dtype='int8')
        pred = inference.create_predictor(cfg)
        outs = pred.run([mixed_prompts])[0]
        for i, want in enumerate(sequential_greedy):
            assert outs[i, :len(want)].tolist() == want
        st = pred._engine.stats()
        assert st['weight_dtype'] == 'int8'
        # every 2-D non-embedding matmul weight quantized: qkv/out +
        # fc1/fc2 per layer = 4 * num_layers
        assert st['quantized_params'] == 4 * tiny_lm.config.num_layers
        pred._engine.shutdown()

    def test_weight_and_kv_quantized_together(self, tiny_lm,
                                              mixed_prompts,
                                              sequential_greedy):
        eng = ServingEngine(tiny_lm, ServingConfig(
            page_size=8, max_batch_size=3, prefill_chunk=8,
            kv_dtype='int8', weight_dtype='int8'))
        outs = eng.generate(mixed_prompts, max_new_tokens=6, top_k=0)
        assert outs == sequential_greedy
        eng.shutdown()

    def test_invalid_weight_dtype_rejected(self):
        with pytest.raises(ValueError, match='weight_dtype'):
            ServingConfig(weight_dtype='int4')


# ---------------------------------------------------------------------------
# metrics + predictor wiring
# ---------------------------------------------------------------------------
class TestServingSurface:
    def test_serve_gauges_in_step_telemetry(self, tiny_lm):
        from paddle_tpu.profiler import StepTelemetry
        eng = ServingEngine(tiny_lm, ServingConfig(
            page_size=8, max_batch_size=2, prefill_chunk=8))
        eng.generate([[2, 3, 4], [6, 7]], max_new_tokens=3, top_k=0)
        snap = StepTelemetry(publish=False).snapshot()
        serve = snap.get('serve')
        assert serve, 'snapshot has no serve section'
        assert serve['ptpu_serve_requests_completed_total'] >= 2
        assert serve['ptpu_serve_kv_pages_total'] == eng.pool.num_pages
        assert serve['ptpu_serve_ttft_seconds']['count'] >= 2
        eng.shutdown()

    def test_health_dump_serve_renders(self, tiny_lm):
        import importlib.util
        import os
        spec = importlib.util.spec_from_file_location(
            'health_dump', os.path.join(
                os.path.dirname(os.path.dirname(
                    os.path.abspath(__file__))),
                'tools', 'health_dump.py'))
        hd = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(hd)
        eng = ServingEngine(tiny_lm, ServingConfig(
            page_size=8, max_batch_size=2, prefill_chunk=8))
        eng.generate([[2, 3, 4]], max_new_tokens=2, top_k=0)
        eng.publish_metrics()
        from paddle_tpu.serving import metrics as sm
        doc = {'telemetry': {'serve': sm.serve_snapshot()}}
        serve = hd._find_serve(doc)
        assert serve is not None
        text = hd.render_serve(serve)
        assert 'decode throughput' in text
        assert 'KV pool' in text
        eng.shutdown()

    def test_predictor_runs_on_engine(self, tiny_lm, mixed_prompts,
                                      sequential_greedy):
        from paddle_tpu import inference
        cfg = inference.Config()
        cfg.enable_serving_engine(tiny_lm, max_new_tokens=6, top_k=0,
                                  page_size=8, max_batch_size=3,
                                  prefill_chunk=8)
        pred = inference.create_predictor(cfg)
        assert pred.get_input_names() == ['input_ids']
        outs = pred.run([mixed_prompts])
        assert len(outs) == 1
        padded = outs[0]
        for i, want in enumerate(sequential_greedy):
            got = padded[i, :len(want)].tolist()
            assert got == want
        # padded [B, L] array input round-trips too (rows pad-trimmed)
        n = max(len(p) for p in mixed_prompts[:2])
        arr = np.zeros((2, n), np.int32)
        for i, p in enumerate(mixed_prompts[:2]):
            arr[i, :len(p)] = p
        outs2 = pred.run([arr])
        for i, want in enumerate(sequential_greedy[:2]):
            assert outs2[0][i, :len(want)].tolist() == want
        # edge inputs fail loudly at the Predictor, not deep in the
        # engine: all-pad rows and empty batches
        with pytest.raises(ValueError, match='rows \\[1\\] are empty'):
            pred.run([np.asarray([[5, 0, 0], [0, 0, 0]], np.int32)])
        assert pred.run([[]])[0].shape == (0, 0)
