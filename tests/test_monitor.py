"""core/monitor Histogram.percentile edge cases (ISSUE 16 satellite):
empty histogram, single sample, all-samples-in-overflow-bucket, and the
q=0 / q=100 bounds. Plus the ISSUE 18 registry concurrency contract:
publisher threads racing scrapes (prometheus text / snapshot / HTTP)
and the history sampler must never produce torn series, duplicate
`# TYPE` lines, or non-monotone counter reads."""
import os
import re
import sys
import threading
import urllib.request

import pytest

HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(HERE))

from paddle_tpu.core.monitor import (Histogram,   # noqa: E402
                                     MetricsRegistry, MetricsServer)


def _hist(buckets=(1.0, 2.0, 4.0)):
    return Histogram('t_hist', help='t', buckets=buckets)


class TestPercentileEdges:
    def test_empty_histogram_is_none(self):
        h = _hist()
        assert h.percentile(0) is None
        assert h.percentile(50) is None
        assert h.percentile(100) is None

    def test_out_of_range_q_raises(self):
        h = _hist()
        h.observe(1.0)
        with pytest.raises(ValueError):
            h.percentile(-1)
        with pytest.raises(ValueError):
            h.percentile(101)

    def test_single_sample(self):
        h = _hist()
        h.observe(1.5)      # lands in the (1, 2] bucket
        # every quantile interpolates inside that one bucket
        for q in (0, 25, 50, 75, 100):
            p = h.percentile(q)
            assert 1.0 <= p <= 2.0, (q, p)
        assert h.percentile(100) == pytest.approx(2.0)

    def test_all_samples_in_overflow_bucket(self):
        h = _hist()
        for _ in range(5):
            h.observe(100.0)    # past the last finite bound
        # the estimator can't see past the last finite boundary: every
        # quantile degrades to it
        for q in (0, 50, 99, 100):
            assert h.percentile(q) == pytest.approx(4.0), q

    def test_q0_lands_in_first_occupied_bucket(self):
        h = _hist()
        h.observe(3.0)      # (2, 4] — the leading buckets stay empty
        # q=0 must NOT report the empty first bucket's upper bound (the
        # pre-fix behavior); it converges to the occupied bucket's
        # lower bound
        assert h.percentile(0) == pytest.approx(2.0)

    def test_q0_with_occupied_first_bucket(self):
        h = _hist()
        h.observe(0.5)
        assert h.percentile(0) == pytest.approx(0.0)

    def test_q100_is_last_occupied_upper_bound(self):
        h = _hist()
        h.observe(0.5)
        h.observe(1.5)
        assert h.percentile(100) == pytest.approx(2.0)

    def test_interpolation_monotone(self):
        h = _hist()
        for v in (0.5, 0.6, 1.2, 1.8, 3.0, 3.5):
            h.observe(v)
        qs = [h.percentile(q) for q in (0, 10, 25, 50, 75, 90, 100)]
        assert qs == sorted(qs), qs
        assert qs[0] == pytest.approx(0.0)
        assert qs[-1] == pytest.approx(4.0)

    def test_labeled_children_are_independent(self):
        h = Histogram('t_hist_l', help='t', labelnames=('site',),
                      buckets=(1.0, 2.0))
        h.observe(0.5, site='a')
        h.observe(1.5, site='b')
        assert h.percentile(100, site='a') == pytest.approx(1.0)
        assert h.percentile(0, site='b') == pytest.approx(1.0)


# ---------------------------------------------------------------------------
# registry concurrency (ISSUE 18 satellite): publishers vs scrapes
# ---------------------------------------------------------------------------
N_PUBLISHERS = 4
ROUNDS = 400


class TestConcurrentPublishers:
    """4 publisher threads hammer one registry while scrape readers
    (prometheus text, snapshot, the HTTP exporter) and the history
    sampler run concurrently — renders must never tear."""

    def _publish(self, reg, worker, stop):
        c = reg.counter('t_cc_events_total', labelnames=('worker',))
        g = reg.gauge('t_cc_depth', labelnames=('worker',))
        h = reg.histogram('t_cc_lat_seconds', buckets=(0.01, 0.1, 1.0))
        w = f'w{worker}'
        for i in range(ROUNDS):
            if stop.is_set():
                break
            c.inc(worker=w)
            g.set(float(i), worker=w)
            h.observe(0.05)

    @staticmethod
    def _counter_values(text):
        out = {}
        for line in text.splitlines():
            m = re.match(r'^t_cc_events_total\{worker="(w\d+)"\} '
                         r'(\d+(?:\.\d+)?)$', line)
            if m:
                out[m.group(1)] = float(m.group(2))
        return out

    def test_scrapes_never_tear(self):
        reg = MetricsRegistry()
        hist = reg.enable_history(capacity=16)
        stop = threading.Event()
        errors = []
        seen = {}                       # worker -> last counter value

        def scrape_loop():
            try:
                while not stop.is_set():
                    text = reg.prometheus_text()
                    # no duplicate # TYPE lines (a torn two-pass render
                    # would repeat a metric's header)
                    types = [ln for ln in text.splitlines()
                             if ln.startswith('# TYPE')]
                    assert len(types) == len(set(types)), types
                    # counters are monotone across successive scrapes
                    for w, v in self._counter_values(text).items():
                        assert v >= seen.get(w, 0.0), (w, v, seen)
                        seen[w] = v
                    # snapshot agrees structurally: every series row
                    # carries a numeric value + age
                    snap = reg.snapshot()
                    for name, m in snap['metrics'].items():
                        for row in m['series']:
                            assert row['age_s'] is None or \
                                row['age_s'] >= 0.0, (name, row)
                    hist.tick()
            except Exception as e:      # noqa: BLE001
                errors.append(e)
                stop.set()

        threads = [threading.Thread(target=self._publish,
                                    args=(reg, i, stop))
                   for i in range(N_PUBLISHERS)]
        scraper = threading.Thread(target=scrape_loop)
        for th in threads:
            th.start()
        scraper.start()
        for th in threads:
            th.join(timeout=60)
        stop.set()
        scraper.join(timeout=60)
        assert not errors, errors
        # final totals exact: no lost increments under the race
        c = reg.counter('t_cc_events_total', labelnames=('worker',))
        for i in range(N_PUBLISHERS):
            assert c.value(worker=f'w{i}') == ROUNDS
        v = reg.histogram('t_cc_lat_seconds').value()
        assert v['count'] == N_PUBLISHERS * ROUNDS
        # history rings sampled concurrently: bounded, time-ordered,
        # counter streams monotone (no torn samples)
        for name in hist.series_names():
            for key, pts in hist.iter_series(name):
                assert len(pts) <= 16, (name, key)
                ts = [t for t, _v in pts]
                assert ts == sorted(ts), (name, key)
        for key, pts in hist.iter_series('t_cc_events_total'):
            vals = [v for _t, v in pts]
            assert vals == sorted(vals), (key, vals)

    def test_http_scrape_races_publishers(self):
        reg = MetricsRegistry()
        stop = threading.Event()
        srv = MetricsServer(port=0, registry=reg)
        threads = [threading.Thread(target=self._publish,
                                    args=(reg, i, stop))
                   for i in range(N_PUBLISHERS)]
        for th in threads:
            th.start()
        try:
            for _ in range(10):
                body = urllib.request.urlopen(
                    f'http://127.0.0.1:{srv.port}/metrics',
                    timeout=10).read().decode()
                types = [ln for ln in body.splitlines()
                         if ln.startswith('# TYPE')]
                assert len(types) == len(set(types))
        finally:
            stop.set()
            for th in threads:
                th.join(timeout=60)
            srv.close()
        vals = self._counter_values(
            reg.prometheus_text())
        assert set(vals) == {f'w{i}' for i in range(N_PUBLISHERS)}
        assert all(v == ROUNDS for v in vals.values()), vals

    def test_history_wraparound_deterministic_clock(self):
        """The ring keeps exactly `capacity` newest points under
        concurrent sampling on an injected clock."""
        t = {'now': 0.0}
        lock = threading.Lock()
        reg = MetricsRegistry()
        hist = reg.enable_history(capacity=8, clock=lambda: t['now'])
        g = reg.gauge('t_cc_wrap')

        def advance(base):
            for i in range(50):
                with lock:
                    t['now'] += 1.0
                    g.set(t['now'])
                    hist.sample(now=t['now'])

        threads = [threading.Thread(target=advance, args=(j,))
                   for j in range(4)]
        for th in threads:
            th.start()
        for th in threads:
            th.join(timeout=60)
        pts = hist.points('t_cc_wrap')
        assert len(pts) == 8
        assert [p[0] for p in pts] == list(range(193, 201))
        assert [p[1] for p in pts] == [p[0] for p in pts]
