"""core/monitor Histogram.percentile edge cases (ISSUE 16 satellite):
empty histogram, single sample, all-samples-in-overflow-bucket, and the
q=0 / q=100 bounds."""
import os
import sys

import pytest

HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(HERE))

from paddle_tpu.core.monitor import Histogram   # noqa: E402


def _hist(buckets=(1.0, 2.0, 4.0)):
    return Histogram('t_hist', help='t', buckets=buckets)


class TestPercentileEdges:
    def test_empty_histogram_is_none(self):
        h = _hist()
        assert h.percentile(0) is None
        assert h.percentile(50) is None
        assert h.percentile(100) is None

    def test_out_of_range_q_raises(self):
        h = _hist()
        h.observe(1.0)
        with pytest.raises(ValueError):
            h.percentile(-1)
        with pytest.raises(ValueError):
            h.percentile(101)

    def test_single_sample(self):
        h = _hist()
        h.observe(1.5)      # lands in the (1, 2] bucket
        # every quantile interpolates inside that one bucket
        for q in (0, 25, 50, 75, 100):
            p = h.percentile(q)
            assert 1.0 <= p <= 2.0, (q, p)
        assert h.percentile(100) == pytest.approx(2.0)

    def test_all_samples_in_overflow_bucket(self):
        h = _hist()
        for _ in range(5):
            h.observe(100.0)    # past the last finite bound
        # the estimator can't see past the last finite boundary: every
        # quantile degrades to it
        for q in (0, 50, 99, 100):
            assert h.percentile(q) == pytest.approx(4.0), q

    def test_q0_lands_in_first_occupied_bucket(self):
        h = _hist()
        h.observe(3.0)      # (2, 4] — the leading buckets stay empty
        # q=0 must NOT report the empty first bucket's upper bound (the
        # pre-fix behavior); it converges to the occupied bucket's
        # lower bound
        assert h.percentile(0) == pytest.approx(2.0)

    def test_q0_with_occupied_first_bucket(self):
        h = _hist()
        h.observe(0.5)
        assert h.percentile(0) == pytest.approx(0.0)

    def test_q100_is_last_occupied_upper_bound(self):
        h = _hist()
        h.observe(0.5)
        h.observe(1.5)
        assert h.percentile(100) == pytest.approx(2.0)

    def test_interpolation_monotone(self):
        h = _hist()
        for v in (0.5, 0.6, 1.2, 1.8, 3.0, 3.5):
            h.observe(v)
        qs = [h.percentile(q) for q in (0, 10, 25, 50, 75, 90, 100)]
        assert qs == sorted(qs), qs
        assert qs[0] == pytest.approx(0.0)
        assert qs[-1] == pytest.approx(4.0)

    def test_labeled_children_are_independent(self):
        h = Histogram('t_hist_l', help='t', labelnames=('site',),
                      buckets=(1.0, 2.0))
        h.observe(0.5, site='a')
        h.observe(1.5, site='b')
        assert h.percentile(100, site='a') == pytest.approx(1.0)
        assert h.percentile(0, site='b') == pytest.approx(1.0)
