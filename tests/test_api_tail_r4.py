"""Public API sheet remainder: 3-D pooling family, Conv3DTranspose,
bilinear, fleet datasets, entry attrs, jit TracedLayer, static program
state, top-level tail (add_n/t/inverse/...)."""
import os
import tempfile

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.core.tensor import Tensor
import paddle_tpu.nn.functional as F


def test_pool3d_functional_and_layers():
    rng = np.random.RandomState(0)
    x = Tensor(rng.rand(1, 2, 6, 6, 6).astype(np.float32))
    m = F.max_pool3d(x, 2, stride=2)
    a = F.avg_pool3d(x, 2, stride=2)
    assert m.shape == a.shape == [1, 2, 3, 3, 3]
    assert (np.asarray(m.data) >= np.asarray(a.data) - 1e-6).all()
    assert nn.MaxPool3D(2, 2)(x).shape == [1, 2, 3, 3, 3]
    assert nn.AvgPool3D(2, 2)(x).shape == [1, 2, 3, 3, 3]
    assert nn.AdaptiveAvgPool3D(2)(x).shape == [1, 2, 2, 2, 2]
    assert nn.AdaptiveMaxPool3D(2)(x).shape == [1, 2, 2, 2, 2]


def test_adaptive_pool1d_exact_bins():
    x = Tensor(np.arange(6, dtype=np.float32).reshape(1, 1, 6))
    avg = np.asarray(F.adaptive_avg_pool1d(x, 3).data)
    np.testing.assert_allclose(avg[0, 0], [0.5, 2.5, 4.5])
    mx = np.asarray(F.adaptive_max_pool1d(x, 2).data)
    np.testing.assert_allclose(mx[0, 0], [2.0, 5.0])
    assert nn.AdaptiveMaxPool1D(2)(x).shape == [1, 1, 2]
    # uneven split: bins [0,2),[1,4),[3,5): floor/ceil edges
    avg5 = np.asarray(F.adaptive_avg_pool1d(
        Tensor(np.arange(5, dtype=np.float32).reshape(1, 1, 5)), 3).data)
    np.testing.assert_allclose(avg5[0, 0], [0.5, 2.0, 3.5])


def test_conv_transpose_1d_3d():
    paddle.seed(0)
    rng = np.random.RandomState(1)
    m3 = nn.Conv3DTranspose(2, 3, 3, stride=2)
    x3 = Tensor(rng.rand(1, 2, 4, 4, 4).astype(np.float32))
    assert m3(x3).shape == [1, 3, 9, 9, 9]
    x1 = Tensor(rng.rand(1, 2, 5).astype(np.float32))
    w1 = Tensor(rng.rand(2, 3, 3).astype(np.float32))
    out = F.conv1d_transpose(x1, w1, stride=2)
    assert out.shape == [1, 3, 11]


def test_bilinear_matches_einsum():
    rng = np.random.RandomState(2)
    x1 = Tensor(rng.rand(4, 3).astype(np.float32))
    x2 = Tensor(rng.rand(4, 5).astype(np.float32))
    w = Tensor(rng.rand(2, 3, 5).astype(np.float32))
    b = Tensor(rng.rand(1, 2).astype(np.float32))
    out = np.asarray(F.bilinear(x1, x2, w, b).data)
    want = np.einsum('ni,oij,nj->no', np.asarray(x1.data),
                     np.asarray(w.data), np.asarray(x2.data)) \
        + np.asarray(b.data)
    np.testing.assert_allclose(out, want, rtol=1e-5)


def test_dropout3d_and_losses():
    rng = np.random.RandomState(3)
    x = Tensor(rng.rand(2, 4, 3, 3, 3).astype(np.float32))
    paddle.seed(5)
    y = np.asarray(F.dropout3d(x, 0.5).data)
    # whole channels dropped: each [c] block all-zero or scaled
    for n in range(2):
        for c in range(4):
            blk = y[n, c]
            assert (blk == 0).all() or (blk > 0).all()
    assert np.asarray(F.dropout3d(x, 0.5, training=False).data).sum() \
        == pytest.approx(np.asarray(x.data).sum())
    # dice loss: perfect prediction -> ~0
    p = Tensor(np.eye(4, dtype=np.float32)[None])
    l = Tensor(np.arange(4, dtype=np.int64).reshape(1, 4, 1))
    d = float(F.dice_loss(p, l).data)
    assert d < 0.01
    # modern sigmoid_focal_loss runs with one-hot labels
    logit = Tensor(rng.randn(6, 3).astype(np.float32))
    lab = Tensor(np.eye(3, dtype=np.float32)[rng.randint(0, 3, 6)])
    v = float(F.sigmoid_focal_loss(logit, lab).data)
    assert np.isfinite(v) and v > 0
    norm = Tensor(np.asarray([2.0], np.float32))
    v2 = float(F.sigmoid_focal_loss(logit, lab, normalizer=norm).data)
    assert abs(v2 - v / 2) < 1e-4
    assert nn.HSigmoidLoss(8, 6)(
        Tensor(rng.rand(3, 8).astype(np.float32)),
        Tensor(rng.randint(0, 6, (3, 1)).astype(np.int64))).shape[0] == 3
    assert nn.PairwiseDistance()(
        Tensor(rng.rand(3, 4).astype(np.float32)),
        Tensor(rng.rand(3, 4).astype(np.float32))).shape == [3]
    assert nn.Dropout3D(0.5)(x).shape == x.shape


def test_top_level_tail():
    a = Tensor(np.ones((2, 3), np.float32))
    s = paddle.add_n([a, a, a])
    assert float(np.asarray(s.data)[0, 0]) == 3.0
    assert int(paddle.rank(a).data) == 2
    assert not bool(paddle.is_empty(a).data)
    assert paddle.is_tensor(a) and not paddle.is_tensor(3)
    t = np.asarray(paddle.t(a).data)
    assert t.shape == (3, 2)
    with pytest.raises(ValueError, match='ndim'):
        paddle.t(Tensor(np.ones((2, 2, 2), np.float32)))
    m = np.array([[2.0, 0.0], [0.0, 4.0]], np.float32)
    inv = np.asarray(paddle.inverse(Tensor(m)).data)
    np.testing.assert_allclose(inv, np.linalg.inv(m), rtol=1e-5)
    np.testing.assert_allclose(
        np.asarray(paddle.linalg.inv(Tensor(m)).data),
        np.linalg.inv(m), rtol=1e-5)
    fm = np.asarray(paddle.floor_mod(
        Tensor(np.asarray([7, -7], np.int32)),
        Tensor(np.asarray([3, 3], np.int32))).data)
    assert fm[0] == 1
    r = np.asarray(paddle.reverse(
        Tensor(np.arange(3, dtype=np.float32)), 0).data)
    np.testing.assert_allclose(r, [2, 1, 0])
    # rng state round-trip
    st = paddle.get_cuda_rng_state()
    v1 = np.asarray(paddle.rand([3]).data)
    paddle.set_cuda_rng_state(st)
    v2 = np.asarray(paddle.rand([3]).data)
    np.testing.assert_allclose(v1, v2)
    # batch reader decorator
    rd = paddle.batch(lambda: iter(range(7)), batch_size=3)
    chunks = list(rd())
    assert [len(c) for c in chunks] == [3, 3, 1]
    assert repr(paddle.NPUPlace(0)) == 'NPUPlace(0)'
    paddle.set_printoptions(precision=4)


def test_scatter_inplace():
    x = Tensor(np.zeros((4, 2), np.float32))
    idx = Tensor(np.asarray([1, 3], np.int64))
    upd = Tensor(np.ones((2, 2), np.float32))
    out = paddle.scatter_(x, idx, upd)
    assert np.asarray(x.data)[1].sum() == 2.0   # x itself updated
    assert np.asarray(out.data)[3].sum() == 2.0


def test_entry_attrs():
    p = paddle.distributed.ProbabilityEntry(0.25)
    assert p._to_attr() == 'probability_entry:0.25'
    c = paddle.distributed.CountFilterEntry(10)
    assert c._to_attr() == 'count_filter_entry:10'
    with pytest.raises(ValueError):
        paddle.distributed.ProbabilityEntry(0)
    with pytest.raises(ValueError):
        paddle.distributed.CountFilterEntry(-1)


def _write_multislot(tmp, rows):
    path = os.path.join(tmp, 'part-0.txt')
    rng = np.random.RandomState(0)
    with open(path, 'w') as f:
        for _ in range(rows):
            feats = rng.rand(4)
            f.write(' '.join(f'{v:.4f}' for v in feats)
                    + f" | {rng.randint(0, 2)}\n")
    return [path]


class _Var:
    def __init__(self, shape, dtype):
        self.shape, self.dtype = shape, dtype


def test_queue_and_inmemory_datasets():
    from paddle_tpu.core.native import load_native
    if load_native(required=False) is None:
        pytest.skip('native lib not built')
    with tempfile.TemporaryDirectory() as tmp:
        files = _write_multislot(tmp, 50)
        ds = paddle.distributed.QueueDataset()
        ds.init(batch_size=16, thread_num=1,
                use_var=[_Var([4], 'float32'), _Var([1], 'int64')])
        ds.set_filelist(files)
        total = 0
        for feats, label in ds:
            assert feats.shape[1] == 4 and label.shape[1] == 1
            total += feats.shape[0]
        assert total == 50

        mem = paddle.distributed.InMemoryDataset()
        mem.init(batch_size=16, thread_num=1,
                 use_var=[_Var([4], 'float32'), _Var([1], 'int64')])
        mem.set_filelist(files)
        with pytest.raises(RuntimeError, match='load_into_memory'):
            next(iter(mem))
        mem.load_into_memory()
        assert mem.get_memory_data_size() == 50
        e1 = np.concatenate([np.asarray(f.data) for f, _ in mem])
        mem.global_shuffle()
        e2 = np.concatenate([np.asarray(f.data) for f, _ in mem])
        assert e1.shape == e2.shape == (50, 4)
        assert not np.allclose(e1, e2)          # reshuffled order
        np.testing.assert_allclose(sorted(e1[:, 0]), sorted(e2[:, 0]),
                                   rtol=1e-6)
        mem.release_memory()


def test_static_program_state_roundtrip(tmp_path):
    paddle.enable_static()
    try:
        from paddle_tpu import static
        main, start = static.Program(), static.Program()
        with static.program_guard(main, start):
            x = static.data('x', [2, 3], 'float32')
            w = static.create_parameter([3, 4], 'float32')
            y = paddle.matmul(x, w)
        exe = static.Executor()
        exe.run(start)
        # params materialize into the scope on the first main-program
        # run (the Executor's lazy-init contract)
        exe.run(main, feed={'x': np.ones((2, 3), np.float32)},
                fetch_list=[y])
        static.save(main, str(tmp_path / 'm'))
        state = static.load_program_state(str(tmp_path / 'm'))
        assert any(v.shape == (3, 4) for v in state.values())
        # perturb then restore
        static.set_program_state(main, state)
        blob = static.serialize_persistables([x], [y], program=main)
        static.save_to_file(str(tmp_path / 'p.bin'), blob)
        static.deserialize_persistables(
            main, static.load_from_file(str(tmp_path / 'p.bin')))
        out = exe.run(main, feed={'x': np.ones((2, 3), np.float32)},
                      fetch_list=[y])
        assert out[0].shape == (2, 4)
        assert static.WeightNormParamAttr(dim=0).dim == 0
        assert len(static.cpu_places(2)) == 2
    finally:
        paddle.disable_static()


def test_traced_layer_and_verbosity():
    lin = nn.Linear(3, 2)
    out, traced = paddle.jit.TracedLayer.trace(
        lin, [Tensor(np.ones((2, 3), np.float32))])
    again = traced(Tensor(np.ones((2, 3), np.float32)))
    np.testing.assert_allclose(np.asarray(out.data),
                               np.asarray(again.data), rtol=1e-6)
    paddle.jit.set_verbosity(3)
    paddle.jit.set_code_level(50)


def test_vision_image_backend(tmp_path):
    from PIL import Image
    img = np.zeros((4, 5, 3), np.uint8)
    Image.fromarray(img).save(str(tmp_path / 'a.png'))
    assert paddle.vision.get_image_backend() == 'pil'
    loaded = paddle.vision.image_load(str(tmp_path / 'a.png'))
    assert loaded.size == (5, 4)
    with pytest.raises(ValueError):
        paddle.vision.set_image_backend('bogus')


def test_avg_pool3d_divisor_override_is_sum_over_divisor():
    x = Tensor(np.ones((1, 1, 4, 4, 4), np.float32))
    out = np.asarray(F.avg_pool3d(x, 2, stride=2, padding=1,
                                  divisor_override=8).data)
    # corner window holds exactly 1 real element -> 1/8
    assert abs(out[0, 0, 0, 0, 0] - 0.125) < 1e-6
    # interior window holds 8 -> 8/8 = 1
    assert abs(out[0, 0, 1, 1, 1] - 1.0) < 1e-6


def test_conv_transpose_output_size_honored():
    rng = np.random.RandomState(4)
    x = Tensor(rng.rand(1, 2, 4, 4, 4).astype(np.float32))
    w = Tensor(rng.rand(2, 3, 3, 3, 3).astype(np.float32))
    base = F.conv3d_transpose(x, w, stride=2)
    assert base.shape == [1, 3, 9, 9, 9]
    bigger = F.conv3d_transpose(x, w, stride=2,
                                output_size=[10, 10, 10])
    assert bigger.shape == [1, 3, 10, 10, 10]
    with pytest.raises(ValueError, match='unreachable'):
        F.conv3d_transpose(x, w, stride=2, output_size=[12, 12, 12])
    x1 = Tensor(rng.rand(1, 2, 5).astype(np.float32))
    w1 = Tensor(rng.rand(2, 3, 3).astype(np.float32))
    assert F.conv1d_transpose(x1, w1, stride=2,
                              output_size=12).shape == [1, 3, 12]


def test_params_unique_across_programs():
    paddle.enable_static()
    try:
        from paddle_tpu import static
        names = []
        for _ in range(2):
            main, start = static.Program(), static.Program()
            with static.program_guard(main, start):
                static.create_parameter([2, 2], 'float32')
                names += [v.name for b in main.blocks
                          for v in b.all_parameters()]
        assert len(set(names)) == len(names), names
    finally:
        paddle.disable_static()
