"""Async step pipeline (ISSUE 13, docs/performance.md#async-dispatch).

Covers the DeviceLoader's sharded background prefetch (spec correctness
on the 8-dev mesh, staging-ring reuse without aliasing under donation),
the engines' windowed dispatch (window=1 == window=4 loss bit-identity
on all three engines, zero per-step host syncs in the fp32 hot loop via
the PR-3 sync-count harness), on-device LR schedules (traceable-fn vs
host get_lr equivalence incl. resume from state_dict mid-schedule), and
the GradScaler's deferred found-inf accounting (a NaN at step k skips
exactly step k's update with window=2, scaler state identical to the
per-step path).
"""
import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P, NamedSharding

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.core import async_step as A
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.distributed import topology_runtime
from paddle_tpu.io import DeviceLoader
import paddle_tpu.distributed.fleet as fm


def _mesh(axes, sizes):
    fm.fleet._hcg = None
    return topology_runtime.build_mesh(axes, sizes)


def _mlp():
    return nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))


def _mlp_loss(m, x, y):
    return nn.functional.cross_entropy(m(x), y)


def _batches(n, b=8, seed=0):
    rng = np.random.RandomState(seed)
    return [(rng.rand(b, 8).astype('float32'),
             rng.randint(0, 4, (b,)).astype('int64')) for _ in range(n)]


class TestKnobs:
    def test_dispatch_window_resolution(self, monkeypatch):
        monkeypatch.delenv('PTPU_DISPATCH_WINDOW', raising=False)
        assert A.resolve_dispatch_window() == 2
        monkeypatch.setenv('PTPU_DISPATCH_WINDOW', '5')
        assert A.resolve_dispatch_window() == 5
        assert A.resolve_dispatch_window(3) == 3     # kwarg beats env
        assert A.resolve_dispatch_window(0) == 1     # clamped

    def test_prefetch_depth_resolution(self, monkeypatch):
        monkeypatch.delenv('PTPU_DEVICE_PREFETCH', raising=False)
        assert A.resolve_prefetch_depth() == 2
        monkeypatch.setenv('PTPU_DEVICE_PREFETCH', '3')
        assert A.resolve_prefetch_depth() == 3
        assert A.resolve_prefetch_depth(1) == 1

    def test_device_lr_resolution(self, monkeypatch):
        monkeypatch.delenv('PTPU_DEVICE_LR', raising=False)
        assert A.resolve_device_lr() is False        # opt-in
        monkeypatch.setenv('PTPU_DEVICE_LR', '1')
        assert A.resolve_device_lr() is True
        assert A.resolve_device_lr(False) is False   # kwarg beats env


class TestDeviceLoader:
    def test_sharded_prefetch_dp2_mp2(self):
        """dp2×mp2 mesh: batches land dp-sharded on axis 0, replicated
        over mp — the hybrid engine's input spec."""
        mesh = _mesh(['dp', 'mp'], [2, 2])
        batches = _batches(3)
        loader = DeviceLoader(batches, mesh=mesh,
                              specs=[P('dp'), P('dp')])
        got = list(loader)
        assert len(got) == 3
        for (hx, hy), (dx, dy) in zip(batches, got):
            assert dx.sharding.is_equivalent_to(
                NamedSharding(mesh, P('dp')), dx.ndim)
            np.testing.assert_array_equal(np.asarray(jax.device_get(dx)),
                                          hx)
            np.testing.assert_array_equal(np.asarray(jax.device_get(dy)),
                                          hy)
            # dp shards are halves; mp replicas see the same rows
            shards = {d.device.id: np.asarray(d.data)
                      for d in dx.addressable_shards}
            assert all(s.shape[0] == hx.shape[0] // 2
                       for s in shards.values())

    def test_engine_spec_sources(self):
        """input_sharding contract across the three engines."""
        from paddle_tpu.distributed.fleet.meta_parallel.hybrid_engine \
            import HybridParallelTrainStep
        from paddle_tpu.jit import TrainStep
        mesh = _mesh(['dp', 'sharding'], [2, 2])
        paddle.seed(0)
        m = _mlp()
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=m.parameters())
        eng = HybridParallelTrainStep(m, _mlp_loss, opt)
        sh = eng.input_sharding(0, 2)
        assert sh.is_equivalent_to(
            NamedSharding(mesh, P(('dp', 'sharding'))), 2)
        eng.shutdown()
        step = TrainStep(_mlp(), _mlp_loss, paddle.optimizer.SGD(
            learning_rate=0.1, parameters=m.parameters()))
        assert step.input_sharding(0, 2) is None

    def test_pipeline_spec(self):
        mesh = _mesh(['dp', 'pp'], [2, 2])
        batches = _batches(2)
        loader = DeviceLoader(batches, mesh=mesh, specs=[P('dp'), P()])
        (dx, dy) = next(iter(loader))
        assert dx.sharding.is_equivalent_to(
            NamedSharding(mesh, P('dp')), dx.ndim)
        assert dy.sharding.is_equivalent_to(
            NamedSharding(mesh, P()), dy.ndim)

    def test_staging_ring_reuse_no_aliasing(self):
        """More batches than ring slots: the wrap reuses staging buffers
        but must never mutate a batch already delivered (the delivered
        arrays may sit in a donating engine's in-flight window)."""
        _mesh(['dp'], [2])
        batches = _batches(7, seed=3)
        loader = DeviceLoader(batches, depth=2)   # ring of 3 slots
        got = list(loader)
        st = loader.stats()
        assert st['batches'] == 7
        assert st['ring_reuses'] >= 4             # the ring really wrapped
        assert st['h2d_bytes'] > 0
        for (hx, hy), (dx, dy) in zip(batches, got):
            np.testing.assert_array_equal(np.asarray(jax.device_get(dx)),
                                          hx)
            np.testing.assert_array_equal(np.asarray(jax.device_get(dy)),
                                          hy)

    def test_reiteration_after_early_break(self):
        """An abandoned iteration's producer must stop (not race the
        next iteration's producer on the shared staging ring): break
        early, then re-iterate the same loader and get clean batches."""
        batches = _batches(6, seed=5)
        loader = DeviceLoader(batches, depth=2)
        for i, b in enumerate(loader):
            if i == 1:
                break
        got = list(loader)            # fresh full iteration
        assert len(got) == 6
        for (hx, hy), (dx, dy) in zip(batches, got):
            np.testing.assert_array_equal(np.asarray(jax.device_get(dx)),
                                          hx)
            np.testing.assert_array_equal(np.asarray(jax.device_get(dy)),
                                          hy)

    def test_close_unblocks_waiting_consumer(self):
        """close() from another thread must end a consumer blocked on
        an empty prefetch queue instead of deadlocking it (the stop
        signal suppresses the producer's sentinel)."""
        import threading
        import time as _t
        release = threading.Event()

        def slow_gen():
            yield _batches(1, seed=9)[0]
            release.wait(10)       # upstream stalls until released

        loader = DeviceLoader(slow_gen())
        it = iter(loader)
        next(it)
        done = threading.Event()

        def consume():
            list(it)               # blocks: upstream never yields again
            done.set()
        th = threading.Thread(target=consume, daemon=True)
        th.start()
        _t.sleep(0.3)
        loader.close()
        release.set()              # let the stalled producer exit too
        assert done.wait(timeout=5), 'consumer deadlocked after close()'

    def test_upstream_error_surfaces(self):
        def gen():
            yield _batches(1)[0]
            raise RuntimeError('boom')
        loader = DeviceLoader(gen())
        it = iter(loader)
        next(it)
        with pytest.raises(RuntimeError, match='boom'):
            list(it)


class TestWindowedBitIdentity:
    """fp32 windowed loop (DeviceLoader on) produces a loss sequence
    bit-identical to the synchronous loop — window changes when the host
    looks, not what the device computes."""

    N = 5

    def _run_jit(self, window):
        paddle.seed(0)
        from paddle_tpu.jit import TrainStep
        m = _mlp()
        opt = paddle.optimizer.Adam(parameters=m.parameters(),
                                    learning_rate=1e-2)
        step = TrainStep(m, _mlp_loss, opt, dispatch_window=window)
        data = _batches(self.N)
        if window is None:
            return [float(step(Tensor(x), Tensor(y))) for x, y in data]
        loader = DeviceLoader(data, engine=step)
        rs = [step.train_step(*b) for b in loader]
        step.flush()
        return [r.result() for r in rs]

    def test_jit_trainstep(self):
        sync = self._run_jit(None)
        w1 = self._run_jit(1)
        w4 = self._run_jit(4)
        assert sync == w1 == w4

    def _run_hybrid(self, window):
        from paddle_tpu.distributed.fleet.meta_parallel.hybrid_engine \
            import HybridParallelTrainStep
        _mesh(['dp', 'sharding'], [2, 2])
        paddle.seed(0)
        m = _mlp()
        opt = paddle.optimizer.Adam(parameters=m.parameters(),
                                    learning_rate=1e-2)
        eng = HybridParallelTrainStep(m, _mlp_loss, opt,
                                      dispatch_window=window)
        data = _batches(self.N)
        try:
            if window is None:
                return [float(eng(Tensor(x), Tensor(y)))
                        for x, y in data]
            loader = DeviceLoader(data, engine=eng)
            rs = [eng.train_step(*b) for b in loader]
            eng.flush()
            return [r.result() for r in rs]
        finally:
            eng.shutdown()

    def test_hybrid(self):
        sync = self._run_hybrid(None)
        w1 = self._run_hybrid(1)
        w4 = self._run_hybrid(4)
        assert sync == w1 == w4

    def _run_pipeline(self, window):
        from paddle_tpu.models.gpt import GPTConfig, build_gpt_pipeline
        from paddle_tpu.distributed.fleet.meta_parallel.spmd_pipeline \
            import SpmdPipelineEngine
        _mesh(['dp', 'pp'], [1, 2])
        paddle.seed(5)
        cfg = GPTConfig(vocab_size=64, hidden_size=16, num_layers=2,
                        num_heads=2, max_seq_len=32, hidden_dropout=0.0,
                        attn_dropout=0.0, use_flash_attention=False)
        embed, blocks, head = build_gpt_pipeline(cfg)
        opt = paddle.optimizer.SGD(learning_rate=1e-2, parameters=[])
        eng = SpmdPipelineEngine(embed, blocks, head, opt,
                                 accumulate_steps=2, use_remat=False,
                                 schedule='1F1B', dispatch_window=window)
        rng = np.random.RandomState(0)
        data = []
        for _ in range(3):
            ids = rng.randint(0, 64, (2, 32)).astype('int32')
            data.append((ids, np.roll(ids, -1, 1).astype('int32')))
        try:
            if window is None:
                return [float(eng.train_batch((Tensor(i), Tensor(l))))
                        for i, l in data]
            loader = DeviceLoader(data, engine=eng)
            rs = [eng.train_step(b) for b in loader]
            eng.flush()
            return [r.result() for r in rs]
        finally:
            eng.shutdown()

    def test_pipeline(self):
        sync = self._run_pipeline(None)
        w1 = self._run_pipeline(1)
        w4 = self._run_pipeline(4)
        assert sync == w1 == w4


class TestZeroHostSyncs:
    def test_windowed_loop_adds_no_host_syncs(self, monkeypatch):
        """The PR-3 sync-count harness: an fp32 windowed hot loop
        (DeviceLoader + train_step + flush) performs ZERO host fetches;
        the one fetch happens when the caller reads a loss."""
        from paddle_tpu.core import numerics as num
        from paddle_tpu.distributed.fleet.meta_parallel.hybrid_engine \
            import HybridParallelTrainStep
        _mesh(['dp', 'sharding'], [2, 2])
        paddle.seed(0)
        m = _mlp()
        opt = paddle.optimizer.Adam(parameters=m.parameters(),
                                    learning_rate=1e-2)
        eng = HybridParallelTrainStep(m, _mlp_loss, opt,
                                      dispatch_window=2)
        data = _batches(4)
        loader = DeviceLoader(data, engine=eng)
        real = num._host_fetch
        calls = []
        monkeypatch.setattr(num, '_host_fetch',
                            lambda t: (calls.append(1), real(t))[1])
        rs = [eng.train_step(*b) for b in loader]
        eng.flush()
        assert calls == [], f'hot loop performed {len(calls)} host syncs'
        losses = [r.result() for r in rs]
        assert len(calls) == len(rs)       # exactly one fetch per read
        assert all(np.isfinite(losses))
        eng.shutdown()


class TestDeviceLR:
    def _host_values(self, sched, n):
        out = []
        for _ in range(n):
            out.append(float(sched()))
            sched.step()
        return out

    def test_fn_matches_host_schedulers(self):
        from paddle_tpu.optimizer import device_lr as dlr
        from paddle_tpu.optimizer import lr as L
        scheds = [
            L.CosineAnnealingDecay(learning_rate=0.01, T_max=7),
            L.NoamDecay(d_model=64, warmup_steps=4, learning_rate=1.0),
            L.PolynomialDecay(learning_rate=0.01, decay_steps=6,
                              end_lr=1e-4, power=2.0),
            L.PolynomialDecay(learning_rate=0.01, decay_steps=4,
                              end_lr=1e-4, cycle=True),
            L.InverseTimeDecay(learning_rate=0.01, gamma=0.5),
            L.ExponentialDecay(learning_rate=0.01, gamma=0.9),
            L.NaturalExpDecay(learning_rate=0.01, gamma=0.1),
            L.StepDecay(learning_rate=0.01, step_size=3, gamma=0.5),
            L.MultiStepDecay(learning_rate=0.01, milestones=[2, 5]),
            L.LinearWarmup(learning_rate=0.02, warmup_steps=3,
                           start_lr=0.0, end_lr=0.02),
            L.LinearWarmup(
                learning_rate=L.CosineAnnealingDecay(
                    learning_rate=0.02, T_max=5),
                warmup_steps=3, start_lr=0.0, end_lr=0.02),
        ]
        for sched in scheds:
            fn = dlr.device_lr_fn(sched)
            assert fn is not None, type(sched).__name__
            host = self._host_values(sched, 10)
            dev = [float(fn(jnp.asarray(s, jnp.int32)))
                   for s in range(10)]
            np.testing.assert_allclose(dev, host, rtol=1e-5, atol=1e-9,
                                       err_msg=type(sched).__name__)
        # constant lr traces too
        fn = dlr.device_lr_fn(0.125)
        assert float(fn(jnp.asarray(3, jnp.int32))) == 0.125

    def test_exotic_schedulers_fall_back(self):
        from paddle_tpu.optimizer import device_lr as dlr
        from paddle_tpu.optimizer import lr as L
        assert dlr.device_lr_fn(
            L.LambdaDecay(0.01, lambda e: 1.0 / (e + 1))) is None
        assert dlr.device_lr_fn(
            L.ReduceOnPlateau(learning_rate=0.01)) is None

        class MyCosine(L.CosineAnnealingDecay):   # overridden get_lr?
            def get_lr(self):
                return 0.5
        # subclasses must NOT silently trace the parent's rule
        assert dlr.device_lr_fn(
            MyCosine(learning_rate=0.01, T_max=5)) is None

    def test_engine_device_lr_matches_host_feed(self):
        """TrainStep with the schedule traced on device vs the legacy
        host feed (scheduler stepped once per train step): same loss
        curve to fp32 schedule rounding."""
        from paddle_tpu.jit import TrainStep
        from paddle_tpu.optimizer.lr import CosineAnnealingDecay

        def run(device_lr):
            paddle.seed(0)
            m = _mlp()
            sched = CosineAnnealingDecay(learning_rate=0.05, T_max=6)
            opt = paddle.optimizer.SGD(learning_rate=sched,
                                       parameters=m.parameters())
            step = TrainStep(m, _mlp_loss, opt, device_lr=device_lr)
            assert (step._lr.fn is not None) == device_lr
            out = []
            for x, y in _batches(6):
                out.append(float(step(Tensor(x), Tensor(y))))
                sched.step()
            return out
        np.testing.assert_allclose(run(True), run(False), rtol=1e-5)

    def test_hybrid_resume_mid_schedule(self):
        """state_dict/set_state_dict resume: the device LR counter
        re-syncs to the restored host scheduler, so a resumed run
        replays the uninterrupted schedule exactly."""
        from paddle_tpu.distributed.fleet.meta_parallel.hybrid_engine \
            import HybridParallelTrainStep
        from paddle_tpu.optimizer.lr import CosineAnnealingDecay
        data = _batches(6)

        def fresh(sched_state=None):
            paddle.seed(0)
            m = _mlp()
            sched = CosineAnnealingDecay(learning_rate=0.05, T_max=6)
            if sched_state is not None:
                sched.set_state_dict(sched_state)
            opt = paddle.optimizer.SGD(learning_rate=sched,
                                       parameters=m.parameters())
            eng = HybridParallelTrainStep(m, _mlp_loss, opt,
                                          device_lr=True)
            assert eng._lr.fn is not None
            return eng, sched

        _mesh(['dp'], [2])
        eng, sched = fresh()
        uninterrupted = []
        for x, y in data:
            uninterrupted.append(float(eng(Tensor(x), Tensor(y))))
            sched.step()
        eng.shutdown()

        eng, sched = fresh()
        resumed = []
        for x, y in data[:3]:
            resumed.append(float(eng(Tensor(x), Tensor(y))))
            sched.step()
        sd = eng.state_dict()
        sched_sd = sched.state_dict()
        eng.shutdown()
        eng2, sched2 = fresh(sched_state=sched_sd)
        eng2.set_state_dict(sd)
        assert int(np.asarray(jax.device_get(eng2._lr.carry))) == 3
        for x, y in data[3:]:
            resumed.append(float(eng2(Tensor(x), Tensor(y))))
            sched2.step()
        eng2.shutdown()
        np.testing.assert_allclose(resumed, uninterrupted, rtol=1e-6)


class TestGradScalerDeferred:
    """Deferred found-inf accounting at window drain == the per-step
    path: a NaN injected at step k skips exactly step k's update with
    window=2, and the scaler's dynamic schedule lands on the same state."""

    class _Emb(nn.Layer):
        def __init__(self):
            super().__init__()
            self.lin = nn.Linear(4, 8)

        def forward(self, x):
            return self.lin(x)

    class _Blk(nn.Layer):
        def __init__(self):
            super().__init__()
            self.lin = nn.Linear(8, 8)

        def forward(self, x):
            return nn.functional.relu(self.lin(x)) + x

    class _Head(nn.Layer):
        def __init__(self):
            super().__init__()
            self.lin = nn.Linear(8, 1)

        def forward(self, h, y):
            diff = self.lin(h) - y
            return (diff * diff).mean()

    def _engine(self, window=None):
        from paddle_tpu.distributed.fleet.meta_parallel.spmd_pipeline \
            import SpmdPipelineEngine
        _mesh(['dp', 'pp'], [1, 1])
        paddle.seed(7)
        embed = self._Emb()
        blocks = [self._Blk(), self._Blk()]
        head = self._Head()
        opt = paddle.optimizer.SGD(learning_rate=0.05, parameters=[])
        return SpmdPipelineEngine(embed, blocks, head, opt,
                                  accumulate_steps=2, use_remat=False,
                                  schedule='1F1B',
                                  dispatch_window=window)

    def _data(self, nan_at=2, n=5):
        rng = np.random.RandomState(0)
        out = []
        for i in range(n):
            x = rng.rand(4, 6, 4).astype('float32')
            y = rng.rand(4, 6, 1).astype('float32')
            if i == nan_at:
                x = x.copy()
                x[0, 0, 0] = np.nan
            out.append((x, y))
        return out

    @staticmethod
    def _params_host(eng):
        out = {}
        for grp in ('embed', 'blocks', 'head'):
            for n, v in eng._params[grp].items():
                out[f'{grp}/{n}'] = np.asarray(jax.device_get(v))
        return out

    def test_nan_at_step_k_skips_exactly_step_k(self):
        # decr_every_n=2 with ONE injected NaN keeps the scale constant,
        # so the windowed and per-step paths feed identical scales and
        # the whole trajectory must match BIT-exactly. (A scale change
        # lands on the first step dispatched after its drain — up to
        # `window` steps later than the per-step path; the skip
        # accounting itself is what must be exact. docs/performance.md
        # #async-dispatch.)
        from paddle_tpu.amp import GradScaler
        data = self._data()

        # per-step reference (the pipeline_parallel.py driver sequence)
        eng = self._engine()
        scaler_s = GradScaler(init_loss_scaling=256.0,
                              decr_every_n_nan_or_inf=2,
                              incr_every_n_steps=1000)
        found_seq = []
        for x, y in data:
            eng.train_batch((Tensor(x), Tensor(y)),
                            scale=scaler_s._scale)
            f = bool(np.asarray(eng.last_found_inf))
            found_seq.append(f)
            scaler_s._found_inf = f
            scaler_s._update()
        ref_params = self._params_host(eng)
        eng.shutdown()
        assert found_seq == [False, False, True, False, False]

        # windowed: scaler accounting deferred to window drain
        eng2 = self._engine(window=2)
        scaler_a = GradScaler(init_loss_scaling=256.0,
                              decr_every_n_nan_or_inf=2,
                              incr_every_n_steps=1000)
        rs = [eng2.train_step((x, y), scaler=scaler_a)
              for x, y in data]
        eng2.flush()
        async_params = self._params_host(eng2)
        eng2.shutdown()

        # step k (and only step k) tripped found_inf
        founds = [bool(np.asarray(jax.device_get(r.found_inf)))
                  for r in rs]
        assert founds == found_seq
        # scaler schedule state identical to the per-step path
        assert scaler_a._scale == scaler_s._scale
        assert scaler_a._good_steps == scaler_s._good_steps
        assert scaler_a._bad_steps == scaler_s._bad_steps
        # the whole trajectory (skip at k, updates elsewhere) matches
        assert ref_params.keys() == async_params.keys()
        for k in ref_params:
            np.testing.assert_array_equal(ref_params[k],
                                          async_params[k], err_msg=k)


class TestHostGapObservability:
    def test_snapshot_and_telemetry(self):
        from paddle_tpu.jit import TrainStep
        A.reset_prefetch_totals()
        paddle.seed(0)
        m = _mlp()
        opt = paddle.optimizer.SGD(learning_rate=0.05,
                                   parameters=m.parameters())
        step = TrainStep(m, _mlp_loss, opt, dispatch_window=2)
        loader = DeviceLoader(_batches(4), engine=step)
        for b in loader:
            step.train_step(*b)
        step.flush()
        snap = step.host_gap_snapshot()
        assert snap['steps'] == 4 and snap['drained'] == 4
        assert snap['host_gap_seconds'] >= 0.0
        assert snap['host_bound_fraction'] is None or \
            0.0 <= snap['host_bound_fraction'] <= 1.0
        assert snap['dispatch_depth_max'] <= 2 + 1
        host = A.host_snapshot()
        assert 'jit' in host['sites']
        assert host['prefetch']['batches'] >= 4
        # the StepTelemetry contract: snapshot()['host'] carries it
        from paddle_tpu.profiler import StepTelemetry
        tel = StepTelemetry(publish=False).snapshot()
        assert tel['host'] and 'jit' in tel['host']['sites']

    def test_legacy_call_drains_pending_async_steps_first(self):
        """Mixing APIs: a legacy __call__ must drain queued async steps
        before dispatching, so deferred drain work keeps submission
        order."""
        paddle.seed(0)
        from paddle_tpu.jit import TrainStep
        m = _mlp()
        opt = paddle.optimizer.SGD(learning_rate=0.05,
                                   parameters=m.parameters())
        step = TrainStep(m, _mlp_loss, opt, dispatch_window=4)
        data = _batches(3)
        r1 = step.train_step(Tensor(data[0][0]), Tensor(data[0][1]))
        r2 = step.train_step(Tensor(data[1][0]), Tensor(data[1][1]))
        assert not r1.done() and not r2.done()   # window holds both
        step(Tensor(data[2][0]), Tensor(data[2][1]))
        assert r1.done() and r2.done()

    def test_shutdown_unregisters_monitor(self):
        from paddle_tpu.distributed.fleet.meta_parallel.hybrid_engine \
            import HybridParallelTrainStep
        _mesh(['dp'], [2])
        paddle.seed(0)
        m = _mlp()
        opt = paddle.optimizer.SGD(learning_rate=0.05,
                                   parameters=m.parameters())
        eng = HybridParallelTrainStep(m, _mlp_loss, opt)
        x, y = _batches(1)[0]
        eng(Tensor(x), Tensor(y))
        assert 'hybrid' in A.host_snapshot()['sites']
        eng.shutdown()
        assert 'hybrid' not in A.host_snapshot()['sites']

    def test_async_result_repr_and_tensor(self):
        paddle.seed(0)
        from paddle_tpu.jit import TrainStep
        m = _mlp()
        opt = paddle.optimizer.SGD(learning_rate=0.05,
                                   parameters=m.parameters())
        step = TrainStep(m, _mlp_loss, opt)
        x, y = _batches(1)[0]
        res = step.train_step(Tensor(x), Tensor(y))
        assert 'in-flight' in repr(res) or 'drained' in repr(res)
        t = res.tensor()
        assert float(t) == res.result()
        step.flush()
        assert res.done()
