"""Step-time ledger & MFU observatory (ISSUE 16): decomposition
reconciliation, analytic FLOPs/recompute factors, peak resolution,
gauge round-trip through the three-engine wiring, the 2-rank straggler
subprocess leg, and the bench_compare regression verdicts."""
import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(HERE))

os.environ.setdefault('JAX_PLATFORMS', 'cpu')

from paddle_tpu.core import ledger as L                    # noqa: E402


class _StubGap:
    """A HostGapMonitor stand-in with a fixed snapshot."""

    def __init__(self, wall=0.100, gap=0.010, residue=0.004,
                 blocked=0.0, steps=20):
        self.snap = {
            'steps': steps, 'drained': steps,
            'host_gap_seconds': gap, 'host_residue_seconds': residue,
            'blocked_wait_seconds': blocked,
            'step_interval_seconds': wall,
            'host_bound_fraction': gap / wall if wall else None,
            'dispatch_depth_mean': 1.0, 'dispatch_depth_max': 1,
        }

    def snapshot(self):
        return dict(self.snap)


# ---------------------------------------------------------------------------
# decomposition
# ---------------------------------------------------------------------------
class TestDecomposition:
    def test_components_sum_to_wall(self):
        led = L.StepLedger('unittest', gap=_StubGap())
        a = led.account()
        comps = a['components']
        assert set(comps) == {'compute', 'exposed_comm', 'bubble',
                              'host_gap', 'residue'}
        assert abs(sum(comps.values()) - a['wall_seconds']) < 1e-12
        assert abs(a['reconciled_fraction'] - 1.0) < 1e-9
        assert comps['host_gap'] == pytest.approx(0.010)
        assert comps['residue'] == pytest.approx(0.004)
        assert comps['compute'] == pytest.approx(0.086)

    def test_bubble_eats_device_busy_span_only(self):
        led = L.StepLedger('unittest', gap=_StubGap(),
                           bubble_fraction_fn=lambda: 0.25)
        a = led.account()
        comps = a['components']
        # bubble applies to wall - gap - residue - exposed, not wall
        busy = a['wall_seconds'] - comps['host_gap'] \
            - comps['residue'] - comps['exposed_comm']
        assert comps['bubble'] == pytest.approx(0.25 * busy)
        assert comps['compute'] == pytest.approx(0.75 * busy)
        assert abs(sum(comps.values()) - a['wall_seconds']) < 1e-12

    def test_no_interval_yet_returns_none(self):
        led = L.StepLedger('unittest', gap=_StubGap(wall=0.0))
        assert led.account() is None

    def test_gap_clamped_to_wall(self):
        led = L.StepLedger('unittest',
                           gap=_StubGap(wall=0.010, gap=0.050,
                                        residue=0.020))
        a = led.account()
        comps = a['components']
        assert comps['host_gap'] == pytest.approx(0.010)
        assert comps['residue'] == 0.0
        assert comps['compute'] == 0.0
        assert a['reconciled_fraction'] == pytest.approx(1.0)


# ---------------------------------------------------------------------------
# analytic FLOPs / recompute / peaks
# ---------------------------------------------------------------------------
class TestFlops:
    def test_model_flops_formula_matches_bench(self):
        n, t, l, h, s = 1_418_842_112, 16384, 24, 2048, 2048
        total, attn = L.model_flops_per_step(n, t, layers=l, hidden=h,
                                             seq_len=s)
        assert total == 6.0 * n * t + 12.0 * l * h * s * t
        assert attn == 12.0 * l * h * s * t

    def test_recompute_factors(self):
        total, attn = 100.0, 20.0
        assert L.recompute_factor('none', total, attn) == 0.0
        assert L.recompute_factor(None, total, attn) == 0.0
        assert L.recompute_factor('dots', total, attn) == 0.0
        assert L.recompute_factor('full', total, attn) == 1.0
        assert L.recompute_factor('attn_mlp_boundaries', total, attn) \
            == pytest.approx(0.2)

    def test_recompute_factor_scales_hardware_tflops(self):
        L.configure('unittest', layers=2, hidden=64, seq_len=128,
                    n_params=1000, remat_policy='full',
                    tokens_per_step=256)
        led = L.StepLedger('unittest', gap=_StubGap())
        a = led.account()
        assert a['flops']['recompute_factor'] == 1.0
        assert a['hardware_tflops'] == pytest.approx(
            a['model_tflops'] * 4.0 / 3.0)
        L._arch_hints.pop('unittest', None)

    def test_peak_table(self):
        assert L.resolve_peak_tflops('TPU v5 lite') == 197.0
        assert L.resolve_peak_tflops('TPU v5p') == 459.0
        assert L.resolve_peak_tflops('TPU v4') == 275.0
        assert L.resolve_peak_tflops('TPU v3') == 123.0
        assert L.resolve_peak_tflops('TPU v6e') == 918.0
        # CPU dryrun: no peak, no MFU — absolute TFLOP/s only
        assert L.resolve_peak_tflops('cpu') is None
        assert L.resolve_peak_tflops() is None   # local device is CPU

    def test_mfu_against_peak_hint(self):
        L.configure('unittest2', n_params=10 ** 9, tokens_per_step=1000,
                    peak_tflops=197.0)
        led = L.StepLedger('unittest2', gap=_StubGap(wall=0.100))
        a = led.account()
        # 6e12 flops / 0.1 s = 60 TFLOP/s -> 30.46% of 197
        assert a['model_tflops'] == pytest.approx(60.0)
        assert a['mfu'] == pytest.approx(60.0 / 197.0)
        L._arch_hints.pop('unittest2', None)

    def test_cpu_account_has_no_mfu(self):
        L.configure('unittest3', n_params=10 ** 6, tokens_per_step=100)
        led = L.StepLedger('unittest3', gap=_StubGap())
        a = led.account()
        assert a['model_tflops'] > 0.0
        assert a['peak_tflops'] is None and a['mfu'] is None
        L._arch_hints.pop('unittest3', None)


# ---------------------------------------------------------------------------
# gauges + engine wiring + telemetry
# ---------------------------------------------------------------------------
class TestWiring:
    def test_publish_and_snapshot_roundtrip(self):
        L.configure('unittest4', n_params=500, tokens_per_step=64,
                    remat_policy='full')
        led = L.StepLedger('unittest4', gap=_StubGap())
        acct = led.publish()
        assert acct is not None
        snap = L.ledger_snapshot('unittest4')
        assert snap and 'unittest4' in snap
        got = snap['unittest4']
        assert got['wall_seconds'] == pytest.approx(acct['wall_seconds'])
        for c, v in acct['components'].items():
            assert got['components'][c] == pytest.approx(v)
        assert got['recompute_factor'] == 1.0
        assert got['tokens_per_step'] == 64
        L._arch_hints.pop('unittest4', None)

    def test_jit_trainstep_end_to_end(self):
        import paddle_tpu as paddle
        import paddle_tpu.nn as nn
        from paddle_tpu import jit as pjit

        class M(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc = nn.Linear(8, 2)

            def forward(self, x):
                return self.fc(x)

        m = M()
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=m.parameters())
        ts = pjit.TrainStep(
            m, lambda mm, x, y: ((mm(x) - y) ** 2).mean(), opt)
        x = paddle.to_tensor(np.zeros((4, 8), 'float32'))
        y = paddle.to_tensor(np.zeros((4, 2), 'float32'))
        for _ in range(5):
            ts.train_step(x, y)
        ts.flush()
        a = ts._ledger.account()
        assert a is not None and a['engine'] == 'jit'
        comps = a['components']
        wall = a['wall_seconds']
        assert abs(sum(comps.values()) - wall) <= 0.10 * wall
        assert a['tokens_per_step'] == 4 * 8
        assert a['n_params'] == 8 * 2 + 2
        assert a['mfu'] is None          # CPU: absolute TFLOP/s only
        snap = L.ledger_snapshot()
        assert snap and 'jit' in snap
        # telemetry carries the account
        from paddle_tpu.profiler import StepTelemetry
        tel = StepTelemetry(publish=False).snapshot()
        assert tel.get('ledger') and 'jit' in tel['ledger']

    def test_render_ledger(self):
        led = L.StepLedger('unittest5', gap=_StubGap())
        led.publish()
        text = L.render_ledger(L.ledger_snapshot('unittest5'))
        assert 'engine: unittest5' in text
        for c in ('compute', 'exposed_comm', 'bubble', 'host_gap',
                  'residue'):
            assert c in text


# ---------------------------------------------------------------------------
# straggler detection
# ---------------------------------------------------------------------------
class TestStraggler:
    def test_noop_without_host_group(self):
        det = L.StragglerDetector(check_every=1)
        assert det.check(1, 0.5) is None
        assert det.maybe_check(1, _StubGap()) is None

    def test_two_rank_injected_slow_rank(self, tmp_path):
        """ISSUE 16 acceptance: a forced 2-rank slow-rank run triggers
        the straggler artifact naming the injected rank, on BOTH ranks,
        via the host-collective allgather."""
        s = socket.socket()
        s.bind(('127.0.0.1', 0))
        port = s.getsockname()[1] - 7     # host backend adds +7
        s.close()
        procs = []
        for rank in range(2):
            env = dict(os.environ)
            env.update({
                'PADDLE_TRAINER_ID': str(rank),
                'PADDLE_TRAINERS_NUM': '2',
                'PADDLE_MASTER': f'127.0.0.1:{port}',
                'JAX_PLATFORMS': 'cpu',
                'STRAGGLER_DUMP_DIR': str(tmp_path),
            })
            env.pop('XLA_FLAGS', None)
            procs.append(subprocess.Popen(
                [sys.executable, '-u',
                 os.path.join(HERE, 'dist_models', 'dist_straggler.py')],
                env=env, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT, text=True))
        outs = []
        for p in procs:
            out, _ = p.communicate(timeout=120)
            outs.append(out)
        assert all(p.returncode == 0 for p in procs), outs
        reports = [f for f in os.listdir(tmp_path)
                   if f.startswith('straggler_report.rank')]
        assert len(reports) == 2, (os.listdir(tmp_path), outs)
        with open(os.path.join(tmp_path, sorted(reports)[0])) as f:
            rep = json.load(f)
        assert rep['kind'] == 'straggler_report'
        assert rep['offending_ranks'] == [1]
        assert rep['world_size'] == 2
        assert rep['relative_wall']['1'] > rep['threshold']
        text = L.render_straggler_report(rep)
        assert 'STRAGGLER' in text and 'rank 1' in text


# ---------------------------------------------------------------------------
# bench_compare
# ---------------------------------------------------------------------------
class TestBenchCompare:
    def _bc(self):
        sys.path.insert(0, os.path.join(os.path.dirname(HERE), 'tools'))
        import bench_compare
        return bench_compare

    def test_normalize_legacy_record(self):
        bc = self._bc()
        rec = {'metric': 'gpt1.3b_trainstep_mfu', 'value': 0.64,
               'unit': 'fraction', 'vs_baseline': 1.4,
               'detail': {'ms_per_step': 1256.9,
                          'tokens_per_sec': 13035.1,
                          'host': {'dispatch_window': 4},
                          'bert_base_zero2_bf16': {'mfu': 0.46}}}
        n = bc.normalize(rec)
        assert n['schema_version'] == 1
        head = n['legs'][bc.HEADLINE_LEG]
        assert head['ms_per_step'] == 1256.9 and head['mfu'] == 0.64
        assert 'host' not in n['legs']           # record, not a leg
        assert 'bert_base_zero2_bf16' in n['legs']

    def test_normalize_v2_record_finds_ledger(self):
        bc = self._bc()
        led = {'wall_seconds': 0.1,
               'components': {'compute': 0.09, 'exposed_comm': 0.0,
                              'bubble': 0.0, 'host_gap': 0.005,
                              'residue': 0.005}}
        rec = {'schema_version': 2, 'round': 'r06', 'metric': 'm',
               'value': 0.5,
               'legs': {bc.HEADLINE_LEG: {'mfu': 0.5, 'ledger': led}},
               'detail': {}}
        n = bc.normalize(rec)
        assert n['round'] == 'r06' and n['ledger'] is led

    def test_verdict_directions(self):
        bc = self._bc()
        assert bc._verdict('higher', +0.05, 0.02) == 'improvement'
        assert bc._verdict('higher', -0.05, 0.02) == 'regression'
        assert bc._verdict('lower', -0.05, 0.02) == 'improvement'
        assert bc._verdict('lower', +0.05, 0.02) == 'regression'
        assert bc._verdict('higher', 0.01, 0.02) == 'flat'

    def test_repo_artifacts_r04_r05(self):
        bc = self._bc()
        root = os.path.dirname(HERE)
        a = bc.normalize(bc.load_record(
            os.path.join(root, 'BENCH_r04.json')))
        b = bc.normalize(bc.load_record(
            os.path.join(root, 'BENCH_r05.json')))
        doc = bc.compare(a, b)
        head = {m['name']: m for leg in doc['legs']
                for m in leg['metrics'] if leg['leg'] == bc.HEADLINE_LEG}
        assert head['mfu']['verdict'] == 'regression'
        assert doc['regressions'] >= 1
        assert 'regression' in bc.render(doc)

    def test_selftest_entrypoint(self):
        bc = self._bc()
        assert bc.selftest() == 0
