"""Tier-2/3 remainder ops vs numpy oracles (op_test.py pattern):
nce / hsigmoid / unpool / im2sequence / spp / row_conv / spectral_norm +
the static.nn parameterized wrappers.
"""
import math

import numpy as np
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.ops import contrib as C


def _t(a):
    return Tensor(jnp.asarray(a))


class TestHsigmoid:
    def test_vs_numpy_complete_tree(self):
        rng = np.random.RandomState(0)
        N, D, Cn = 4, 6, 8
        x = rng.randn(N, D).astype('float32')
        w = rng.randn(Cn - 1, D).astype('float32') * 0.3
        b = rng.randn(Cn - 1).astype('float32') * 0.1
        lb = rng.randint(0, Cn, (N,)).astype('int64')
        out = C.hsigmoid_loss(_t(x), _t(lb), Cn, _t(w), _t(b))

        def sigmoid(v):
            return 1 / (1 + np.exp(-v))
        exp = np.zeros((N, 1), 'float32')
        for i in range(N):
            node = lb[i] + Cn
            loss = 0.0
            while node > 1:
                parent = node // 2
                code = node % 2
                row = parent - 1
                z = x[i] @ w[row] + b[row]
                p = sigmoid(z) if code == 1 else 1 - sigmoid(z)
                loss += -math.log(max(p, 1e-20))
                node = parent
            exp[i, 0] = loss
        np.testing.assert_allclose(np.asarray(out.data), exp, rtol=1e-4,
                                   atol=1e-5)

    def test_trains(self):
        """hsigmoid as a classifier head: loss decreases and the tree
        route identifies the right class."""
        rng = np.random.RandomState(1)
        N, D, Cn = 32, 8, 8
        lb = rng.randint(0, Cn, (N,)).astype('int64')
        x = np.eye(Cn, D)[lb].astype('float32') + \
            0.1 * rng.randn(N, D).astype('float32')
        w = _t(rng.randn(Cn - 1, D).astype('float32') * 0.1)
        w.stop_gradient = False
        losses = []
        for _ in range(200):
            out = C.hsigmoid_loss(_t(x), _t(lb), Cn, w)
            loss = paddle.mean(out)
            loss.backward()
            w._data = w.data - 1.0 * w.grad.data
            w.grad = None
            losses.append(float(loss))
        assert losses[-1] < 0.35 * losses[0], (losses[0], losses[-1])


class TestNce:
    def test_loss_shape_and_direction(self):
        rng = np.random.RandomState(2)
        N, D, Cn = 8, 6, 20
        x = rng.randn(N, D).astype('float32')
        lb = rng.randint(0, Cn, (N,)).astype('int64')
        # weight aligned with the labels → much lower loss than random
        w_good = np.zeros((Cn, D), 'float32')
        for c in range(Cn):
            w_good[c] = 5.0 * np.eye(Cn, D)[c]
        x_good = np.eye(Cn, D)[lb].astype('float32')
        paddle.seed(3)
        l_good = float(paddle.mean(C.nce(_t(x_good), _t(lb), Cn,
                                         _t(w_good), num_neg_samples=5)))
        paddle.seed(3)
        l_rand = float(paddle.mean(C.nce(_t(x), _t(lb), Cn,
                                         _t(0.01 * w_good),
                                         num_neg_samples=5)))
        assert l_good < l_rand

    def test_grad_flows(self):
        rng = np.random.RandomState(3)
        x = _t(rng.randn(4, 5).astype('float32'))
        w = _t(rng.randn(10, 5).astype('float32'))
        x.stop_gradient = False
        w.stop_gradient = False
        lb = _t(rng.randint(0, 10, (4,)).astype('int64'))
        loss = paddle.mean(C.nce(x, lb, 10, w))
        loss.backward()
        assert np.isfinite(np.asarray(x.grad.data)).all()
        assert np.isfinite(np.asarray(w.grad.data)).all()


class TestUnpoolIm2SeqSpp:
    def test_unpool_inverts_maxpool(self):
        from paddle_tpu.ops import nn_ops as F
        rng = np.random.RandomState(4)
        x = rng.rand(2, 3, 4, 4).astype('float32')
        pooled, idx = F.max_pool2d(_t(x), 2, stride=2, return_mask=True)
        out = C.unpool(pooled, idx, 2, stride=2)
        o = np.asarray(out.data)
        assert o.shape == (2, 3, 4, 4)
        # every pooled max lands back at its argmax position
        p = np.asarray(pooled.data)
        assert np.allclose(np.sort(o[o != 0]), np.sort(p.reshape(-1)))
        mask = o != 0
        np.testing.assert_allclose(o[mask],
                                   x[mask])

    def test_im2sequence_vs_numpy(self):
        rng = np.random.RandomState(5)
        x = rng.rand(2, 3, 4, 4).astype('float32')
        out = C.im2sequence(_t(x), filter_size=2, stride=2)
        o = np.asarray(out.data)
        assert o.shape == (2 * 2 * 2, 3 * 2 * 2)
        # first patch of first image == top-left 2x2 block
        exp0 = x[0, :, 0:2, 0:2].reshape(-1)
        np.testing.assert_allclose(o[0], exp0, rtol=1e-6)

    def test_spp_shapes(self):
        rng = np.random.RandomState(6)
        x = rng.rand(2, 5, 8, 8).astype('float32')
        out = C.spp(_t(x), pyramid_height=3)
        assert tuple(out.shape) == (2, 5 * (1 + 4 + 16))
        # level-0 bin is the global max
        np.testing.assert_allclose(np.asarray(out.data)[:, :5],
                                   x.max((2, 3)), rtol=1e-6)


class TestRowConvSpectral:
    def test_row_conv_vs_numpy(self):
        rng = np.random.RandomState(7)
        x = rng.randn(2, 5, 3).astype('float32')
        w = rng.randn(3, 3).astype('float32')
        out = C.row_conv(_t(x), _t(w))
        exp = np.zeros_like(x)
        for t in range(5):
            for i in range(3):
                if t + i < 5:
                    exp[:, t] += x[:, t + i] * w[i]
        np.testing.assert_allclose(np.asarray(out.data), exp, rtol=1e-5,
                                   atol=1e-6)

    def test_spectral_norm_unit_sigma(self):
        rng = np.random.RandomState(8)
        w = rng.randn(6, 4).astype('float32')
        out = C.spectral_norm(_t(w), power_iters=50)
        sv = np.linalg.svd(np.asarray(out.data), compute_uv=False)
        np.testing.assert_allclose(sv[0], 1.0, rtol=1e-3)


class TestStaticSurface:
    def test_static_nn_wrappers_record_and_run(self):
        import paddle_tpu.static as static
        paddle.enable_static()
        try:
            main = static.Program()
            with static.program_guard(main):
                x = static.data('x', [4, 1, 8, 8])
                seqs = static.nn.im2sequence(x, filter_size=2, stride=2)
                h = static.nn.fc(seqs, 6, activation='relu')
                ln = static.nn.layer_norm(h)
                loss = paddle.mean(ln * ln)
            exe = static.Executor()
            with static.scope_guard(static.Scope()):
                r = exe.run(main,
                            feed={'x': np.random.RandomState(0)
                                  .rand(4, 1, 8, 8).astype('float32')},
                            fetch_list=[loss])
            assert np.isfinite(r[0]).all()
        finally:
            paddle.disable_static()

    def test_static_hsigmoid_nce_build(self):
        import paddle_tpu.static as static
        paddle.enable_static()
        try:
            paddle.seed(0)
            main = static.Program()
            with static.program_guard(main):
                x = static.data('x', [8, 6])
                lb = static.data('lb', [8], dtype='int64')
                l1 = static.nn.hsigmoid(x, lb, num_classes=10)
                l2 = static.nn.nce(x, lb, num_total_classes=10)
                loss = paddle.mean(l1) + paddle.mean(l2)
            assert len(main.all_parameters()) == 4  # 2 weights + 2 biases
            exe = static.Executor()
            rng = np.random.RandomState(1)
            with static.scope_guard(static.Scope()):
                r = exe.run(main,
                            feed={'x': rng.rand(8, 6).astype('float32'),
                                  'lb': rng.randint(0, 10, (8,))
                                  .astype('int64')},
                            fetch_list=[loss])
            assert np.isfinite(r[0]).all()
        finally:
            paddle.disable_static()
