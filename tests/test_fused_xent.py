"""Fused (chunked) linear+softmax-xent vs the composed oracle.

Reference parity: softmax_with_cross_entropy_op numerics tests
(test_softmax_with_cross_entropy_op.py pattern) applied to the LM-head
fusion.
"""
import numpy as np
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.ops import nn_ops as F
from paddle_tpu.ops import math as M


class TestFusedLinearXent:
    def _data(self, N=64, H=16, V=50, seed=0, ignore_frac=0.0):
        rng = np.random.RandomState(seed)
        x = rng.randn(N, H).astype('float32')
        w = rng.randn(V, H).astype('float32') * 0.1
        idx = rng.randint(0, V, (N,))
        if ignore_frac:
            mask = rng.rand(N) < ignore_frac
            idx = np.where(mask, -100, idx)
        return x, w, idx.astype('int64')

    def test_matches_unfused(self):
        x, w, idx = self._data()
        fused = F.fused_linear_cross_entropy(Tensor(jnp.asarray(x)),
                                             Tensor(jnp.asarray(w)),
                                             Tensor(jnp.asarray(idx)))
        logits = jnp.asarray(x) @ jnp.asarray(w).T
        ref = F.cross_entropy(Tensor(logits), Tensor(jnp.asarray(idx)))
        np.testing.assert_allclose(float(fused), float(ref), rtol=1e-5)

    def test_ignore_index(self):
        x, w, idx = self._data(ignore_frac=0.3, seed=1)
        fused = F.fused_linear_cross_entropy(Tensor(jnp.asarray(x)),
                                             Tensor(jnp.asarray(w)),
                                             Tensor(jnp.asarray(idx)),
                                             reduction='none')
        logits = jnp.asarray(x) @ jnp.asarray(w).T
        ref = F.cross_entropy(Tensor(logits), Tensor(jnp.asarray(idx)),
                              reduction='none')
        np.testing.assert_allclose(np.asarray(fused.data),
                                   np.asarray(ref.data)[:, 0], rtol=1e-5,
                                   atol=1e-6)
        assert np.all(np.asarray(fused.data)[np.asarray(idx) == -100] == 0)

    def test_grads_match_unfused(self):
        x, w, idx = self._data(seed=2, ignore_frac=0.2)

        def run(fused):
            xt = Tensor(jnp.asarray(x)); xt.stop_gradient = False
            wt = Tensor(jnp.asarray(w)); wt.stop_gradient = False
            lt = Tensor(jnp.asarray(idx))
            if fused:
                loss = F.fused_linear_cross_entropy(xt, wt, lt)
            else:
                logits = M.matmul(xt, wt, transpose_y=True)
                loss = F.cross_entropy(logits, lt)
            loss.backward()
            return (np.asarray(xt.grad.data), np.asarray(wt.grad.data),
                    float(loss))

        dxf, dwf, lf = run(True)
        dxu, dwu, lu = run(False)
        np.testing.assert_allclose(lf, lu, rtol=1e-5)
        np.testing.assert_allclose(dxf, dxu, rtol=1e-4, atol=1e-6)
        np.testing.assert_allclose(dwf, dwu, rtol=1e-4, atol=1e-6)

    def test_3d_input_and_bert_forward(self):
        from paddle_tpu.models.bert import BertConfig, BertForPretraining
        paddle.seed(0)
        cfg = BertConfig(vocab_size=64, hidden_size=32, num_layers=1,
                         num_heads=2, intermediate_size=64, max_seq_len=16,
                         hidden_dropout=0.0, attn_dropout=0.0)
        model = BertForPretraining(cfg)
        rng = np.random.RandomState(0)
        ids = Tensor(rng.randint(0, 64, (2, 16)).astype('int32'))
        mlm = Tensor(rng.randint(0, 64, (2, 16)).astype('int64'))
        nsp = Tensor(rng.randint(0, 2, (2,)).astype('int64'))
        loss = model(ids, masked_lm_labels=mlm, next_sentence_label=nsp)
        # oracle: explicit logits path
        from paddle_tpu.models.bert import bert_pretrain_loss
        mlm_logits, nsp_logits = model(ids)
        ref = bert_pretrain_loss(mlm_logits, nsp_logits, mlm, nsp)
        np.testing.assert_allclose(float(loss), float(ref), rtol=1e-5)

    def test_transpose_y_false_matches(self):
        """[H, V] Linear layout — the GPTLMHead fast-path branch."""
        x, w, idx = self._data(seed=3, ignore_frac=0.1)

        def run(fused):
            xt = Tensor(jnp.asarray(x)); xt.stop_gradient = False
            wt = Tensor(jnp.asarray(w.T.copy())); wt.stop_gradient = False
            lt = Tensor(jnp.asarray(idx))
            if fused:
                loss = F.fused_linear_cross_entropy(xt, wt, lt,
                                                    transpose_y=False)
            else:
                logits = M.matmul(xt, wt)
                loss = F.cross_entropy(logits, lt)
            loss.backward()
            return (np.asarray(xt.grad.data), np.asarray(wt.grad.data),
                    float(loss))

        dxf, dwf, lf = run(True)
        dxu, dwu, lu = run(False)
        np.testing.assert_allclose(lf, lu, rtol=1e-5)
        np.testing.assert_allclose(dxf, dxu, rtol=1e-4, atol=1e-6)
        np.testing.assert_allclose(dwf, dwu, rtol=1e-4, atol=1e-6)
