"""Program serialization round-trip tests (VERDICT r1 #7).

Reference pattern: save_inference_model / load_inference_model round-trips
through the filesystem into a FRESH process (framework.proto ProgramDesc +
fluid/io.py), asserting identical outputs."""
import json
import os
import subprocess
import sys
import tempfile

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.static as static

HERE = os.path.dirname(os.path.abspath(__file__))


@pytest.fixture(autouse=True)
def _static_mode():
    paddle.enable_static()
    yield
    paddle.disable_static()


def _build(train=True):
    main = static.Program()
    with static.program_guard(main):
        x = static.data('x', [8, 4])
        label = static.data('label', [8, 1])
        h = static.nn.fc(x, 8, activation='relu')
        pred = static.nn.fc(h, 1)
        loss = paddle.mean((pred - label) * (pred - label))
        if train:
            paddle.optimizer.Adam(learning_rate=0.05).minimize(loss)
    return main, pred, loss


def test_program_roundtrip_same_process():
    rng = np.random.RandomState(0)
    xs = rng.rand(8, 4).astype('float32')
    ys = (xs @ rng.rand(4, 1).astype('float32')).astype('float32')
    paddle.seed(0)
    main, pred, loss = _build()
    exe = static.Executor()
    scope = static.Scope()
    with static.scope_guard(scope):
        for _ in range(5):
            exe.run(main, feed={'x': xs, 'label': ys}, fetch_list=[loss])
        path = os.path.join(tempfile.mkdtemp(), 'model')
        static.save(main, path, scope=scope)   # snapshot BEFORE next step
        ref = exe.run(main, feed={'x': xs, 'label': ys},
                      fetch_list=[pred, loss])

    prog2 = static.load(path, scope=(s2 := static.Scope()))
    with static.scope_guard(s2):
        got = exe.run(prog2, feed={'x': xs, 'label': ys},
                      fetch_list=[pred.name, loss.name])
    # same params + same program -> identical first step
    np.testing.assert_allclose(got[0], ref[0], rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(got[1], ref[1], rtol=1e-5, atol=1e-6)


def test_dynamic_batch_roundtrip():
    """static.data('x', [-1, 4]) (dynamic batch) round-trips: loaded
    kernels run at ANY batch size (jax symbolic-shape export)."""
    paddle.seed(0)
    main = static.Program()
    with static.program_guard(main):
        x = static.data('x', [-1, 4])
        h = static.nn.fc(x, 8, activation='relu')
        pred = static.nn.fc(h, 1)
    exe = static.Executor()
    scope = static.Scope()
    xs8 = np.random.RandomState(0).rand(8, 4).astype('float32')
    with static.scope_guard(scope):
        ref8 = exe.run(main, feed={'x': xs8}, fetch_list=[pred])[0]
        ref4 = exe.run(main, feed={'x': xs8[:4]}, fetch_list=[pred])[0]
        path = os.path.join(tempfile.mkdtemp(), 'model')
        static.save(main, path, scope=scope)
    prog2 = static.load(path, scope=(s2 := static.Scope()))
    with static.scope_guard(s2):
        got8 = exe.run(prog2, feed={'x': xs8}, fetch_list=[pred.name])[0]
        got4 = exe.run(prog2, feed={'x': xs8[:4]},
                       fetch_list=[pred.name])[0]
    np.testing.assert_allclose(got8, ref8, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(got4, ref4, rtol=1e-5, atol=1e-6)


def test_save_load_signature_parity():
    """paddle.static positional signatures: save(prog, path, protocol) and
    load(prog, path, executor)."""
    paddle.seed(0)
    main = static.Program()
    with static.program_guard(main):
        x = static.data('x', [4, 4])
        pred = static.nn.fc(x, 2)
    exe = static.Executor()
    scope = static.Scope()
    xs = np.random.RandomState(0).rand(4, 4).astype('float32')
    with static.scope_guard(scope):
        ref = exe.run(main, feed={'x': xs}, fetch_list=[pred])[0]
        path = os.path.join(tempfile.mkdtemp(), 'model')
        static.save(main, path, 4, scope=scope)          # protocol arg
        static.load(main, path, exe)                      # executor arg
        got = exe.run(main, feed={'x': xs}, fetch_list=[pred])[0]
    np.testing.assert_allclose(got, ref)


def test_inference_artifact_excludes_training_state():
    paddle.seed(0)
    main, pred, loss = _build()
    exe = static.Executor()
    scope = static.Scope()
    with static.scope_guard(scope):
        exe.run(main, feed={'x': np.zeros((8, 4), 'float32'),
                            'label': np.zeros((8, 1), 'float32')},
                fetch_list=[])
        path = os.path.join(tempfile.mkdtemp(), 'model')
        static.save_inference_model(path, [main.global_block().var('x')],
                                    [pred], exe, program=main, scope=scope)
    import io as _io
    with open(path + '.pdiparams', 'rb') as f:
        state = np.load(_io.BytesIO(f.read()), allow_pickle=False)
    assert not any('moment' in k or '@GRAD' in k for k in state.files), \
        list(state.files)


def test_loaded_program_is_still_rewritable():
    """The deserialized Program is an editable op-level IR: the sharding
    pass operates on it like on a freshly recorded one."""
    from paddle_tpu.static.sharding_pass import shard_program
    paddle.seed(0)
    main, _, _ = _build()
    exe = static.Executor()
    scope = static.Scope()
    with static.scope_guard(scope):
        exe.run(main, feed={'x': np.zeros((8, 4), 'float32'),
                            'label': np.zeros((8, 1), 'float32')},
                fetch_list=[])
        path = os.path.join(tempfile.mkdtemp(), 'model')
        static.save(main, path, scope=scope)
    prog2 = static.load(path, scope=static.Scope())
    shard_program(prog2, 0, 2, stage=2)
    types = [op.type for op in prog2.global_block().ops]
    assert 'c_reduce_sum' in types and 'c_broadcast' in types


def test_inference_model_fresh_process_roundtrip():
    """build -> train -> save_inference_model -> FRESH PROCESS load ->
    identical outputs (the VERDICT 'done' criterion)."""
    rng = np.random.RandomState(1)
    xs = rng.rand(8, 4).astype('float32')
    ys = (xs @ rng.rand(4, 1).astype('float32')).astype('float32')
    paddle.seed(3)
    main, pred, loss = _build()
    exe = static.Executor()
    scope = static.Scope()
    with static.scope_guard(scope):
        for _ in range(5):
            exe.run(main, feed={'x': xs, 'label': ys}, fetch_list=[loss])
        ref = exe.run(main.clone(for_test=True),
                      feed={'x': xs, 'label': ys}, fetch_list=[pred])
        path = os.path.join(tempfile.mkdtemp(), 'model')
        static.save_inference_model(path, [main.global_block().var('x')],
                                    [pred], exe, program=main, scope=scope)

    script = f'''
import json, sys
import jax; jax.config.update('jax_platforms', 'cpu')
sys.path.insert(0, {HERE!r} + '/..')
import numpy as np
import paddle_tpu as paddle
import paddle_tpu.static as static
paddle.enable_static()
prog, feeds, fetches = static.load_inference_model({path!r})
exe = static.Executor()
xs = np.array({xs.tolist()!r}, 'float32')
with static.scope_guard(static.global_scope()):
    out = exe.run(prog, feed={{feeds[0]: xs}}, fetch_list=fetches)
print('OUT:' + json.dumps(np.asarray(out[0]).tolist()))
'''
    env = dict(os.environ, JAX_PLATFORMS='cpu')
    env.pop('XLA_FLAGS', None)
    r = subprocess.run([sys.executable, '-c', script], env=env,
                       capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stdout + r.stderr
    line = [l for l in r.stdout.splitlines() if l.startswith('OUT:')][-1]
    got = np.array(json.loads(line[len('OUT:'):]), 'float32')
    np.testing.assert_allclose(got, ref[0], rtol=1e-4, atol=1e-5)


def test_trained_program_resumes_in_fresh_process():
    """Full TRAIN program (backward + adam ops) round-trips: a fresh
    process continues training with identical losses."""
    rng = np.random.RandomState(2)
    xs = rng.rand(8, 4).astype('float32')
    ys = (xs @ rng.rand(4, 1).astype('float32')).astype('float32')
    paddle.seed(5)
    main, pred, loss = _build()
    exe = static.Executor()
    scope = static.Scope()
    with static.scope_guard(scope):
        for _ in range(3):
            exe.run(main, feed={'x': xs, 'label': ys}, fetch_list=[loss])
        path = os.path.join(tempfile.mkdtemp(), 'model')
        static.save(main, path, scope=scope)
        ref = [float(exe.run(main, feed={'x': xs, 'label': ys},
                             fetch_list=[loss])[0]) for _ in range(3)]

    script = f'''
import json, sys
import jax; jax.config.update('jax_platforms', 'cpu')
sys.path.insert(0, {HERE!r} + '/..')
import numpy as np
import paddle_tpu as paddle
import paddle_tpu.static as static
paddle.enable_static()
prog = static.load({path!r})
prog._optimizer = paddle.optimizer.Adam(learning_rate=0.05)  # lr source
exe = static.Executor()
xs = np.array({xs.tolist()!r}, 'float32')
ys = np.array({ys.tolist()!r}, 'float32')
losses = []
with static.scope_guard(static.global_scope()):
    for _ in range(3):
        losses.append(float(exe.run(prog, feed={{'x': xs, 'label': ys}},
                                    fetch_list=[{loss.name!r}])[0]))
print('LOSSES:' + json.dumps(losses))
'''
    env = dict(os.environ, JAX_PLATFORMS='cpu')
    env.pop('XLA_FLAGS', None)
    r = subprocess.run([sys.executable, '-c', script], env=env,
                       capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stdout + r.stderr
    line = [l for l in r.stdout.splitlines() if l.startswith('LOSSES:')][-1]
    got = json.loads(line[len('LOSSES:'):])
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)
