"""Behavioral tests for the strategy-driven static meta-optimizers
(VERDICT r3 #2: behavior, not attr checks).

Reference test pattern: fleet meta-optimizer unit tests
(test_fleet_gradient_merge_meta_optimizer.py,
test_fleet_localsgd_meta_optimizer.py, test_fleet_raw_program_meta_optimizer
.py) assert on rewritten op lists; the multi-rank numerics follow the
test_dist_base 2-process loss-comparison pattern, here in-process via
MultiRankShardingSimulator.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import static


@pytest.fixture(autouse=True)
def _static_mode():
    paddle.enable_static()
    yield
    paddle.disable_static()


def _mlp_program(lr=0.1, opt='sgd'):
    main = static.Program()
    with static.program_guard(main):
        x = static.data('x', [8, 4])
        label = static.data('label', [8, 1])
        h1 = static.nn.fc(x, 16, activation='relu')
        h2 = static.nn.fc(h1, 16, activation='relu')
        pred = static.nn.fc(h2, 1)
        loss = paddle.mean((pred - label) * (pred - label))
    opt_obj = (paddle.optimizer.SGD(learning_rate=lr) if opt == 'sgd'
               else paddle.optimizer.Adam(learning_rate=lr))
    return main, loss, (h1, h2), opt_obj


def _data():
    rng = np.random.RandomState(0)
    xs = rng.rand(8, 4).astype('float32')
    ys = (xs @ rng.rand(4, 1).astype('float32') + 0.1).astype('float32')
    return xs, ys


class _StubRole:
    def __init__(self, n):
        self._n = n

    def worker_num(self):
        return self._n

    def worker_index(self):
        return 0


def _strategy_minimize(strategy, loss, opt_obj, nranks=1):
    """Drive the real resolve-and-chain path with a stub role maker."""
    from paddle_tpu.distributed.fleet.meta_optimizers import (
        resolve_meta_optimizers)
    metas = resolve_meta_optimizers(strategy, opt_obj, _StubRole(nranks),
                                    loss=loss)
    assert metas, "strategy applied no meta optimizer"
    from paddle_tpu.distributed.fleet.base.strategy_compiler import (
        StrategyCompiler)
    try:
        chained = StrategyCompiler().generate_optimizer(
            loss, _StubRole(nranks), opt_obj, strategy, metas)
        if isinstance(chained, (list, tuple)):
            chained = chained[0]
        return chained.minimize(loss)
    except Exception:
        return metas[0].minimize(loss)


class TestRecompute:
    def test_rewrite_preserves_numerics(self):
        """Recompute is semantics-preserving: identical loss trajectory
        with and without the rewrite (reference RecomputeOptimizer trains
        the same model, just cheaper in memory)."""
        from paddle_tpu.static.recompute_pass import rewrite_recompute
        xs, ys = _data()

        def run(checkpoints):
            paddle.seed(3)
            main, loss, (h1, h2), opt = _mlp_program()
            opt.minimize(loss)
            if checkpoints:
                n = rewrite_recompute(main, [h1.name, h2.name])
                assert n >= 1
            exe = static.Executor()
            with static.scope_guard(static.Scope()):
                return [float(exe.run(main,
                                      feed={'x': xs, 'label': ys},
                                      fetch_list=[loss])[0])
                        for _ in range(10)]

        base = run(False)
        rc = run(True)
        np.testing.assert_allclose(rc, base, rtol=1e-5, atol=1e-7)
        assert base[-1] < 0.5 * base[0]    # and it actually trains

    def test_rewrite_inserts_real_ops(self):
        from paddle_tpu.static.recompute_pass import rewrite_recompute
        paddle.seed(0)
        main, loss, (h1, h2), opt = _mlp_program()
        opt.minimize(loss)
        rewrite_recompute(main, [h1.name])
        types = [op.type for op in main.global_block().ops]
        assert 'recompute_barrier' in types
        assert any(t.endswith('_recompute') for t in types)
        # grad consumers rewired to the recomputed names
        assert any('@RECOMPUTE@' in n
                   for op in main.global_block().ops
                   if op.type.endswith('_grad')
                   for n in op.input_names)

    def test_unknown_checkpoint_raises(self):
        from paddle_tpu.static.recompute_pass import rewrite_recompute
        main, loss, _, opt = _mlp_program()
        opt.minimize(loss)
        with pytest.raises(ValueError, match='not found'):
            rewrite_recompute(main, ['definitely_not_a_var'])

    def test_strategy_path_applies_rewrite(self):
        """fleet strategy.recompute drives the real pass (not an attr)."""
        from paddle_tpu.distributed.fleet import DistributedStrategy
        paddle.seed(0)
        main, loss, (h1, _), opt = _mlp_program()
        s = DistributedStrategy()
        s.recompute = True
        s.recompute_configs = {'checkpoints': [h1.name]}
        _strategy_minimize(s, loss, opt)
        types = [op.type for op in main.global_block().ops]
        assert 'recompute_barrier' in types

    def test_recomputation_lowers_as_real_compute(self):
        """The compute side of the memory trade is real: the lowered
        module carries the duplicated segment matmuls behind
        optimization_barriers (without which XLA would CSE them back into
        the stored forward, restoring the memory). On the TPU backend the
        barriers survive to the optimized binary — measured compiled-flops
        ratio 1.34x vs no-recompute for this exact program; the CPU test
        backend expands barriers before its CSE pass, so the suite
        asserts on the lowered StableHLO."""
        import jax
        import jax.numpy as jnp

        def lowered(recompute):
            paddle.seed(1)
            main = static.Program()
            cps = []
            with static.program_guard(main):
                x = static.data('x', [32, 256])
                h = x
                for i in range(8):
                    h = static.nn.fc(h, 256, activation='relu')
                    if i % 2 == 1:
                        cps.append(h.name)
                loss = paddle.mean(h * h)
                paddle.optimizer.SGD(learning_rate=0.01).minimize(loss)
            if recompute:
                from paddle_tpu.static.recompute_pass import (
                    rewrite_recompute)
                rewrite_recompute(main, cps)
            exe = static.Executor()
            with static.scope_guard(static.Scope()):
                sc = static.global_scope()
                exe._run_startup(main, sc)
                names, arrays = exe._collect_params(main, sc)
                fn = exe._make_replay(main, ('x',), names, [loss.name])
                xs = jnp.zeros((32, 256), jnp.float32)
                t = jax.jit(fn).lower(
                    (xs,), tuple(arrays),
                    jnp.asarray(0.01, jnp.float32)).as_text()
                return t.count('stablehlo.dot'), \
                    t.count('optimization_barrier')

        (d0, b0), (d1, b1) = lowered(False), lowered(True)
        assert b0 == 0 and b1 >= 3          # one barrier per segment
        assert d1 > d0, (d0, d1)            # duplicated segment matmuls


class TestGradientMerge:
    def test_k_merged_steps_equal_one_step(self):
        """With a constant batch and avg=True, k merged steps move params
        exactly like one plain step (grads at frozen params average to
        themselves) — the reference GradientMergeOptimizer semantics."""
        xs, ys = _data()
        from paddle_tpu.static.meta_passes import apply_gradient_merge

        def run(merge_k, steps):
            paddle.seed(7)
            # identical naming across the two independent builds (each
            # models its own process)
            with paddle.utils.unique_name.guard():
                main, loss, _, opt = _mlp_program(lr=0.05)
                opt.minimize(loss)
            if merge_k:
                apply_gradient_merge(main, merge_k, avg=True)
            exe = static.Executor()
            with static.scope_guard(static.Scope()):
                losses = [float(exe.run(main,
                                        feed={'x': xs, 'label': ys},
                                        fetch_list=[loss])[0])
                          for _ in range(steps)]
                sc = static.global_scope()
                params = {p.name: np.asarray(sc.find_var(p.name))
                          for p in main.all_parameters()}
            return losses, params

        merged_losses, merged_params = run(2, 4)
        plain_losses, plain_params = run(0, 2)
        # params after 4 merged steps == after 2 plain steps
        for n, v in plain_params.items():
            np.testing.assert_allclose(merged_params[n], v,
                                       rtol=1e-5, atol=1e-7)
        # loss is constant within each merge window, drops across them
        assert abs(merged_losses[0] - merged_losses[1]) < 1e-7
        assert merged_losses[2] < merged_losses[0]
        np.testing.assert_allclose(merged_losses[::2], plain_losses,
                                   rtol=1e-5, atol=1e-7)

    def test_strategy_path(self):
        from paddle_tpu.distributed.fleet import DistributedStrategy
        paddle.seed(0)
        main, loss, _, opt = _mlp_program()
        s = DistributedStrategy()
        s.gradient_merge = True
        s.gradient_merge_configs = {'k_steps': 4, 'avg': True}
        _strategy_minimize(s, loss, opt)
        types = [op.type for op in main.global_block().ops]
        assert 'conditional_block' in types
        assert types.count('gm_accumulate') == len(main._grad_map)
        # optimize ops moved inside the sub-block
        assert 'sgd' not in types
        assert any('sgd' in [o.type for o in b.ops]
                   for b in main.blocks[1:])


class TestLocalSGD:
    def test_two_ranks_sync_every_k(self):
        """Ranks with different data diverge between syncs and coincide
        exactly on every k-th step (localsgd_optimizer.py:63-79
        semantics)."""
        from paddle_tpu.static.meta_passes import apply_localsgd
        from paddle_tpu.static.sharding_pass import (
            MultiRankShardingSimulator)
        rng = np.random.RandomState(0)
        feeds = []
        for r in range(2):
            xs = rng.rand(8, 4).astype('float32')
            ys = (xs @ rng.rand(4, 1).astype('float32')).astype('float32')
            feeds.append({'x': xs, 'label': ys})

        k = 3
        progs = []
        pname = None
        for r in range(2):
            with paddle.utils.unique_name.guard():
                main, loss, _, opt = _mlp_program(lr=0.05)
                opt.minimize(loss)
            apply_localsgd(main, k, nranks=2)
            progs.append(main)
            pname = main.all_parameters()[0].name
        sim = MultiRankShardingSimulator(progs, seed=11)
        for step in range(1, 2 * k + 1):
            sim.run(feeds)
            a = np.asarray(sim.scopes[0][pname])
            b = np.asarray(sim.scopes[1][pname])
            if step % k == 0:
                np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-7)
            else:
                assert np.abs(a - b).max() > 1e-6, step

    def test_off_boundary_steps_run_zero_collectives(self):
        """VERDICT r5 #5: the comm saving LocalSGD exists for — k-1 of
        every k steps execute ZERO allreduces (host-gated sync tail),
        and the boundary step runs exactly one per parameter."""
        from paddle_tpu.static.meta_passes import apply_localsgd
        from paddle_tpu.static.sharding_pass import (
            MultiRankShardingSimulator)
        rng = np.random.RandomState(0)
        feeds = [{'x': rng.rand(8, 4).astype('float32'),
                  'label': rng.rand(8, 1).astype('float32')}
                 for _ in range(2)]
        k = 3
        progs = []
        for r in range(2):
            with paddle.utils.unique_name.guard():
                main, loss, _, opt = _mlp_program(lr=0.05)
                opt.minimize(loss)
            apply_localsgd(main, k, nranks=2)
            progs.append(main)
        n_params = len(progs[0].all_parameters())
        sim = MultiRankShardingSimulator(progs, seed=11)
        per_step = []
        for _ in range(2 * k):
            before = sim.collective_count
            sim.run(feeds)
            per_step.append(sim.collective_count - before)
        assert per_step == [0, 0, n_params, 0, 0, n_params], per_step

    def test_executor_single_rank_skips_tail_off_boundary(self):
        """The one-jit Executor picks the local-step executable off
        boundary: both cache variants exist after k steps and numerics
        equal plain training (nranks=1 avg is identity)."""
        from paddle_tpu.static.meta_passes import apply_localsgd
        xs, ys = _data()
        paddle.seed(7)
        main, loss, _, opt = _mlp_program(lr=0.1)
        opt.minimize(loss)
        apply_localsgd(main, 3, nranks=1)
        exe = static.Executor()
        with static.scope_guard(static.Scope()):
            for _ in range(4):
                exe.run(main, feed={'x': xs, 'label': ys},
                        fetch_list=[loss])
        # two executables: sync-step and local-step
        assert len(exe._cache) == 2

    def test_single_rank_is_plain_training(self):
        """nranks=1: the sync blend is the identity — trajectory equals
        the un-rewritten program's."""
        from paddle_tpu.static.meta_passes import apply_localsgd
        xs, ys = _data()

        def run(local):
            paddle.seed(5)
            main, loss, _, opt = _mlp_program(lr=0.1)
            opt.minimize(loss)
            if local:
                apply_localsgd(main, 2, nranks=1)
            exe = static.Executor()
            with static.scope_guard(static.Scope()):
                return [float(exe.run(main,
                                      feed={'x': xs, 'label': ys},
                                      fetch_list=[loss])[0])
                        for _ in range(6)]

        np.testing.assert_allclose(run(True), run(False),
                                   rtol=1e-5, atol=1e-7)

    def test_strategy_path(self):
        from paddle_tpu.distributed.fleet import DistributedStrategy
        paddle.seed(0)
        main, loss, _, opt = _mlp_program()
        s = DistributedStrategy()
        s.localsgd = True
        s.localsgd_configs = {'k_steps': 4}
        _strategy_minimize(s, loss, opt, nranks=2)
        types = [op.type for op in main.global_block().ops]
        n_params = len(main.all_parameters())
        assert types.count('c_allreduce_sum') == n_params
        assert types.count('localsgd_blend') == n_params


class TestRawProgramDP:
    def test_two_rank_grads_average(self):
        """raw_program dp exchange: two ranks on different halves match a
        single run on the full batch (loss-cotangent 1/n prescale +
        allreduce-sum == gradient mean)."""
        from paddle_tpu.static.meta_passes import insert_dp_grad_sync
        from paddle_tpu.static.sharding_pass import (
            MultiRankShardingSimulator)
        rng = np.random.RandomState(4)
        x_all = rng.rand(16, 4).astype('float32')
        y_all = (x_all @ rng.rand(4, 1).astype('float32')).astype('float32')

        def build():
            main = static.Program()
            with static.program_guard(main):
                x = static.data('x', [8, 4])
                label = static.data('label', [8, 1])
                h = static.nn.fc(x, 16, activation='relu')
                pred = static.nn.fc(h, 1)
                loss = paddle.mean((pred - label) * (pred - label))
                paddle.optimizer.SGD(learning_rate=0.1).minimize(loss)
            return main, loss

        progs = []
        for r in range(2):
            with paddle.utils.unique_name.guard():
                m, loss = build()
            insert_dp_grad_sync(m, 2)
            progs.append(m)
        sim = MultiRankShardingSimulator(progs, seed=9)
        for _ in range(10):
            sim.run([{'x': x_all[:8], 'label': y_all[:8]},
                     {'x': x_all[8:], 'label': y_all[8:]}])
        pname = progs[0].all_parameters()[0].name
        a = np.asarray(sim.scopes[0][pname])
        b = np.asarray(sim.scopes[1][pname])
        np.testing.assert_allclose(a, b, rtol=1e-6)   # ranks in sync

        # reference: single process, full batch (equal-size halves ->
        # full-batch grad == mean of half grads)
        paddle.seed(9)
        m3 = static.Program()
        with paddle.utils.unique_name.guard(), static.program_guard(m3):
            x = static.data('x', [16, 4])
            label = static.data('label', [16, 1])
            h = static.nn.fc(x, 16, activation='relu')
            pred = static.nn.fc(h, 1)
            loss3 = paddle.mean((pred - label) * (pred - label))
            paddle.optimizer.SGD(learning_rate=0.1).minimize(loss3)
        exe = static.Executor()
        with static.scope_guard(static.Scope()):
            for _ in range(10):
                exe.run(m3, feed={'x': x_all, 'label': y_all},
                        fetch_list=[loss3])
            ref = np.asarray(static.global_scope().find_var(pname))
        np.testing.assert_allclose(a, ref, rtol=1e-4, atol=1e-6)

    def test_strategy_path(self):
        from paddle_tpu.distributed.fleet import DistributedStrategy
        paddle.seed(0)
        main, loss, _, opt = _mlp_program()
        s = DistributedStrategy()
        s.without_graph_optimization = True
        _strategy_minimize(s, loss, opt, nranks=2)
        types = [op.type for op in main.global_block().ops]
        assert types.count('c_allreduce_sum') == len(main._grad_map)
        assert types.count('scale') >= 1        # loss-cotangent prescale


class TestTensorParallel:
    def test_dp_sync_inserted_over_outer_ranks(self):
        from paddle_tpu.distributed.fleet import DistributedStrategy
        paddle.seed(0)
        main, loss, _, opt = _mlp_program()
        s = DistributedStrategy()
        s.tensor_parallel = True
        s.tensor_parallel_configs = {'tensor_parallel_degree': 2}
        _strategy_minimize(s, loss, opt, nranks=4)   # dp_degree = 2
        assert main._mp_degree == 2
        ar = [op for op in main.global_block().ops
              if op.type == 'c_allreduce_sum']
        assert len(ar) == len(main._grad_map)
        assert all(op.attrs['ring_id'] == 2 for op in ar)   # dp ring

    def test_invalid_degree_raises(self):
        from paddle_tpu.distributed.fleet import DistributedStrategy
        main, loss, _, opt = _mlp_program()
        s = DistributedStrategy()
        s.tensor_parallel = True
        s.tensor_parallel_configs = {'tensor_parallel_degree': 3}
        with pytest.raises(ValueError, match='divide'):
            _strategy_minimize(s, loss, opt, nranks=4)


class TestParameterServerMeta:
    def test_a_sync_wires_push_ops(self):
        from paddle_tpu.distributed.fleet import DistributedStrategy
        from paddle_tpu.static.heter_pass import distributed_lookup
        paddle.seed(0)
        main = static.Program()
        with static.program_guard(main):
            ids = static.data('ids', [8], dtype='int32')
            emb = distributed_lookup(ids, table_id=0, dim=8)
            h = static.nn.fc(emb, 4, activation='relu')
            loss = paddle.mean(h * h)
        s = DistributedStrategy()
        s.a_sync = True
        opt = paddle.optimizer.SGD(learning_rate=0.1)
        _strategy_minimize(s, loss, opt)
        types = [op.type for op in main.global_block().ops]
        assert types.count('distributed_push') == 1
        assert main._ps_push_count == 1
        assert isinstance(main._ps_mode, dict)
