"""Op tier-3 tests vs numpy references (the op_test.py pattern):
sequence ops, linear-chain CRF, viterbi, beam search, roi_align/pool."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.ops import sequence as S


def _np(t):
    return np.asarray(t.data if isinstance(t, Tensor) else t)


class TestSequenceOps:
    def test_pad_unpad_roundtrip(self):
        rng = np.random.RandomState(0)
        lens = np.array([3, 1, 4], np.int64)
        packed = rng.rand(int(lens.sum()), 5).astype('float32')
        padded, _ = S.sequence_pad(Tensor(packed), Tensor(lens),
                                   pad_value=0.0)
        assert _np(padded).shape == (3, 4, 5)
        assert np.all(_np(padded)[1, 1:] == 0)
        back = S.sequence_unpad(padded, Tensor(lens))
        np.testing.assert_allclose(_np(back), packed)

    def test_expand_and_reverse(self):
        x = np.arange(6, dtype='float32').reshape(3, 2)
        out = S.sequence_expand(Tensor(x), Tensor(np.array([2, 0, 3])))
        ref = np.repeat(x, [2, 0, 3], axis=0)
        np.testing.assert_allclose(_np(out), ref)

        seq = np.arange(24, dtype='float32').reshape(2, 4, 3)
        lens = np.array([3, 4], np.int64)
        rev = S.sequence_reverse(Tensor(seq), Tensor(lens))
        ref = seq.copy()
        ref[0, :3] = seq[0, :3][::-1]
        ref[1] = seq[1][::-1]
        np.testing.assert_allclose(_np(rev), ref)


def _crf_ref_nll(emit, trans, label, lens):
    """Brute-force CRF NLL by path enumeration."""
    import itertools
    start, stop, sq = trans[0], trans[1], trans[2:]
    B, T, N = emit.shape
    out = np.zeros((B, 1), np.float64)
    for b in range(B):
        L = int(lens[b])
        scores = []
        for path in itertools.product(range(N), repeat=L):
            s = start[path[0]] + emit[b, 0, path[0]]
            for t in range(1, L):
                s += sq[path[t - 1], path[t]] + emit[b, t, path[t]]
            s += stop[path[L - 1]]
            scores.append(s)
        logz = np.log(np.sum(np.exp(np.array(scores))))
        y = label[b, :L]
        gold = start[y[0]] + emit[b, 0, y[0]]
        for t in range(1, L):
            gold += sq[y[t - 1], y[t]] + emit[b, t, y[t]]
        gold += stop[y[L - 1]]
        out[b, 0] = logz - gold
    return out


class TestCRF:
    def test_linear_chain_crf_matches_enumeration(self):
        rng = np.random.RandomState(0)
        B, T, N = 3, 4, 3
        emit = rng.randn(B, T, N).astype('float32')
        trans = rng.randn(N + 2, N).astype('float32')
        label = rng.randint(0, N, (B, T))
        lens = np.array([4, 2, 3], np.int64)
        nll = S.linear_chain_crf(Tensor(emit), Tensor(trans),
                                 Tensor(label.astype(np.int64)),
                                 Tensor(lens))
        ref = _crf_ref_nll(emit.astype(np.float64), trans.astype(np.float64),
                           label, lens)
        np.testing.assert_allclose(_np(nll), ref, rtol=1e-4, atol=1e-4)

    def test_crf_decoding_matches_enumeration(self):
        import itertools
        rng = np.random.RandomState(1)
        B, T, N = 2, 4, 3
        emit = rng.randn(B, T, N).astype('float32')
        trans = rng.randn(N + 2, N).astype('float32')
        lens = np.array([4, 3], np.int64)
        path = S.crf_decoding(Tensor(emit), Tensor(trans), Tensor(lens))
        start, stop, sq = trans[0], trans[1], trans[2:]
        for b in range(B):
            L = int(lens[b])
            best, best_s = None, -1e18
            for p in itertools.product(range(N), repeat=L):
                s = start[p[0]] + emit[b, 0, p[0]]
                for t in range(1, L):
                    s += sq[p[t - 1], p[t]] + emit[b, t, p[t]]
                s += stop[p[L - 1]]
                if s > best_s:
                    best, best_s = p, s
            np.testing.assert_array_equal(_np(path)[b, :L], best)

    @pytest.mark.slow   # ~70s convergence run: run_tests.sh tiers
    def test_crf_trains(self):
        """linear_chain_crf is differentiable: transitions learn a forced
        tag pattern."""
        rng = np.random.RandomState(0)
        B, T, N = 8, 6, 4
        emit_np = rng.randn(B, T, N).astype('float32') * 0.1
        label = np.tile(np.arange(T) % N, (B, 1)).astype(np.int64)
        lens = np.full((B,), T, np.int64)
        trans = paddle.to_tensor(
            rng.randn(N + 2, N).astype('float32') * 0.1)
        trans.stop_gradient = False
        opt = paddle.optimizer.Adam(learning_rate=0.1, parameters=[trans])
        first = None
        for i in range(40):
            nll = S.linear_chain_crf(Tensor(emit_np), trans,
                                     Tensor(label), Tensor(lens))
            loss = paddle.mean(nll)
            if first is None:
                first = float(loss)
            loss.backward()
            opt.step()
            opt.clear_grad()
        assert float(loss) < first * 0.5
        decoded = S.crf_decoding(Tensor(emit_np), trans, Tensor(lens))
        assert (_np(decoded) == label).mean() > 0.9

    def test_viterbi_decode_api(self):
        rng = np.random.RandomState(2)
        B, T, N = 2, 5, 4
        emit = rng.randn(B, T, N).astype('float32')
        trans = rng.randn(N, N).astype('float32')
        lens = np.array([5, 3], np.int64)
        scores, path = S.viterbi_decode(Tensor(emit), Tensor(trans),
                                        Tensor(lens),
                                        include_bos_eos_tag=False)
        # brute force
        import itertools
        for b in range(B):
            L = int(lens[b])
            best, best_s = None, -1e18
            for p in itertools.product(range(N), repeat=L):
                s = emit[b, 0, p[0]]
                for t in range(1, L):
                    s += trans[p[t - 1], p[t]] + emit[b, t, p[t]]
                if s > best_s:
                    best, best_s = p, s
            np.testing.assert_array_equal(_np(path)[b, :L], best)
            np.testing.assert_allclose(_np(scores)[b], best_s, rtol=1e-5)


class TestBeamSearch:
    def test_beam_matches_exhaustive(self):
        """Markov LM with fixed per-step log-probs: beam K=V recovers the
        exact best path of an exhaustive search."""
        rng = np.random.RandomState(0)
        V, T = 5, 4
        table = rng.randn(V, V).astype('float32')   # logp[next | cur]
        table = table - np.log(np.exp(table).sum(1, keepdims=True))

        def step_fn(ids, state):
            import jax.numpy as jnp
            return jnp.asarray(table)[ids], state

        seqs, scores = S.beam_search(step_fn, {}, bos_id=0, eos_id=99,
                                     beam_size=V, max_len=T, batch_size=1)
        # exhaustive best path from bos=0
        import itertools
        best_s, best_p = -1e18, None
        for p in itertools.product(range(V), repeat=T):
            s, cur = 0.0, 0
            for tok in p:
                s += table[cur, tok]
                cur = tok
            if s > best_s:
                best_s, best_p = s, p
        np.testing.assert_array_equal(_np(seqs)[0, 0], best_p)
        np.testing.assert_allclose(_np(scores)[0, 0], best_s, rtol=1e-5)

    def test_eos_freezes_beam(self):
        import jax.numpy as jnp
        V = 4

        def step_fn(ids, state):
            logp = jnp.full((ids.shape[0], V), -10.0)
            logp = logp.at[:, 1].set(-0.1)    # prefer eos=1
            return logp, state

        seqs, scores = S.beam_search(step_fn, {}, bos_id=0, eos_id=1,
                                     beam_size=2, max_len=5, batch_size=1)
        top = _np(seqs)[0, 0]
        assert top[0] == 1 and np.all(top == 1)   # eos then frozen padding
        np.testing.assert_allclose(_np(scores)[0, 0], -0.1, atol=1e-5)


class TestRoiOps:
    def test_roi_align_linear_field_exact(self):
        """Bilinear sampling of a linear field f(x,y)=x+10y is exact: any
        aligned ROI returns the value at its (shifted) center."""
        from paddle_tpu.vision.ops import roi_align
        xs, ys = np.meshgrid(np.arange(8), np.arange(8))
        feat = (xs + 10.0 * ys).astype('float32').reshape(1, 1, 8, 8)
        boxes = np.array([[1.0, 1.0, 3.0, 3.0],
                          [2.0, 0.0, 6.0, 4.0]], 'float32')
        out = roi_align(Tensor(feat), Tensor(boxes),
                        Tensor(np.array([2], np.int32)), output_size=1,
                        spatial_scale=1.0, aligned=True)
        # aligned center = ((x1+x2)/2 - 0.5, (y1+y2)/2 - 0.5)
        np.testing.assert_allclose(_np(out).reshape(-1),
                                   [1.5 + 10 * 1.5, 3.5 + 10 * 1.5],
                                   atol=1e-4)

    def test_roi_align_shape_and_grad(self):
        from paddle_tpu.vision.ops import roi_align
        rng = np.random.RandomState(0)
        feat = paddle.to_tensor(rng.rand(2, 3, 8, 8).astype('float32'))
        feat.stop_gradient = False
        boxes = np.array([[0, 0, 4, 4], [2, 2, 7, 7], [1, 0, 5, 3]],
                         'float32')
        out = roi_align(feat, Tensor(boxes),
                        Tensor(np.array([2, 1], np.int32)), output_size=2)
        assert _np(out).shape == (3, 3, 2, 2)
        paddle.sum(out).backward()
        assert feat.grad is not None
        assert float(np.abs(_np(feat.grad)).sum()) > 0

    def test_roi_pool_max(self):
        from paddle_tpu.vision.ops import roi_pool
        feat = np.arange(16, dtype='float32').reshape(1, 1, 4, 4)
        boxes = np.array([[0, 0, 4, 4]], 'float32')
        out = roi_pool(Tensor(feat), Tensor(boxes),
                       Tensor(np.array([1], np.int32)), output_size=2)
        np.testing.assert_allclose(
            _np(out).reshape(2, 2), [[5, 7], [13, 15]])
