"""Serving goodput ledger & decode roofline observatory (ISSUE 17):
ordered-clamp iteration-wall decomposition, the exact
delivered + wasted == emitted goodput identity across preemption /
speculative rejection / degrade shed / cluster drain-resubmit,
trace-v4 per-request pricing parity, the per-generation HBM peak
table (never faked on CPU), registry lifecycle, and the zero-extra-
host-syncs budget."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.serving import ServingConfig, ServingEngine
from paddle_tpu.serving import engine as engine_mod
from paddle_tpu.serving import ledger as ledger_mod
from paddle_tpu.serving.ledger import (HBM_GBPS, ServeLedger,
                                       render_serve_ledger,
                                       resolve_peak_hbm_gbps,
                                       serve_ledger_snapshot)


@pytest.fixture(scope='module')
def tiny_lm():
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM
    paddle.seed(7)
    cfg = GPTConfig(vocab_size=128, hidden_size=64, num_layers=2,
                    num_heads=2, max_seq_len=128, hidden_dropout=0.0,
                    attn_dropout=0.0, use_flash_attention=False)
    m = GPTForCausalLM(cfg)
    m.eval()
    return m


@pytest.fixture(scope='module')
def mixed_prompts():
    rng = np.random.RandomState(3)
    return [list(rng.randint(1, 128, int(n)))
            for n in (11, 5, 17, 8, 23, 6)]


@pytest.fixture
def clean_registry(monkeypatch):
    """Isolate the module ledger registry so engines leaked by other
    test files can't bleed into snapshot assertions."""
    monkeypatch.setattr(ledger_mod, '_ledgers', {})


# ---------------------------------------------------------------------------
# ServeLedger units: ordered clamps, goodput counters, lifecycle
# ---------------------------------------------------------------------------
class TestServeLedgerUnits:
    def test_ordered_clamp_components_sum_to_wall(self, clean_registry):
        led = ServeLedger(engine='u0')
        led.observe_iteration(wall=0.010, compute=0.004,
                              host_fetch=0.002, schedule=0.001)
        a = led.account()
        c = a['components']
        assert c['compute'] == pytest.approx(0.004)
        assert c['host_fetch'] == pytest.approx(0.002)
        assert c['schedule'] == pytest.approx(0.001)
        assert c['page_stream'] == 0.0
        assert c['residue'] == pytest.approx(0.003)
        assert sum(c.values()) == pytest.approx(a['wall_seconds'])
        assert a['reconciled_fraction'] == pytest.approx(1.0)
        assert a['iterations'] == 1

    def test_overrun_clamps_in_order_and_flags(self, clean_registry):
        # measured compute alone exceeds the wall: later components
        # clamp to zero, residue stays zero (never negative), and
        # reconciled_fraction > 1 surfaces the overrun instead of
        # silently eating it
        led = ServeLedger(engine='u1')
        led.observe_iteration(wall=0.010, compute=0.020,
                              host_fetch=0.004, schedule=0.002)
        a = led.account()
        c = a['components']
        assert c['compute'] == pytest.approx(0.010)
        assert c['host_fetch'] == 0.0 and c['schedule'] == 0.0
        assert c['residue'] == 0.0
        assert a['reconciled_fraction'] == pytest.approx(2.6)
        # raw means stay visible so the clamp is diagnosable
        assert a['measured']['compute'] == pytest.approx(0.020)
        assert a['measured']['host_fetch'] == pytest.approx(0.004)

    def test_page_stream_folds_into_next_iteration(self,
                                                   clean_registry):
        led = ServeLedger(engine='u2')
        led.note_page_stream(0.5)
        led.note_page_stream(0.25)     # accumulates until observed
        led.observe_iteration(wall=2.0, compute=0.5)
        led.observe_iteration(wall=2.0, compute=0.5)  # nothing pending
        a = led.account()
        assert a['components']['page_stream'] == pytest.approx(0.375)
        assert led._pending_stream == 0.0

    def test_goodput_identity_and_per_tenant(self, clean_registry):
        led = ServeLedger(engine='u3')
        led.account_prefill(5, 2, tenant_id='a')
        led.account_decode(3, 1, tenant_id='b')
        led.account_spec_shed(4)
        g = led.goodput()
        assert g['emitted_tokens'] == 11
        assert g['delivered_tokens'] == 8
        assert g['wasted_tokens'] == 3
        assert g['delivered_tokens'] + g['wasted_tokens'] \
            == g['emitted_tokens']
        assert g['wasted_by_cause'] == {'preempt_recompute': 2,
                                        'spec_rejected': 1,
                                        'drain_recompute': 0}
        # shed capacity sits OUTSIDE the identity: never computed
        assert g['spec_shed_tokens'] == 4
        assert g['goodput_fraction'] == pytest.approx(8 / 11)
        assert g['per_tenant'] == {
            'a': {'delivered_tokens': 5, 'wasted_tokens': 2},
            'b': {'delivered_tokens': 3, 'wasted_tokens': 1}}

    def test_reset_zeroes_everything(self, clean_registry):
        led = ServeLedger(engine='u4')
        led.observe_iteration(wall=1.0, compute=0.5)
        led.account_prefill(5, 2)
        led.account_spec_shed(3)
        led.reset()
        assert led.account() is None
        g = led.goodput()
        assert g['emitted_tokens'] == 0 and g['spec_shed_tokens'] == 0
        assert g['goodput_fraction'] is None

    def test_registry_latest_wins_and_unregister(self, clean_registry):
        assert serve_ledger_snapshot() is None
        l1 = ServeLedger(engine='site_x')
        l2 = ServeLedger(engine='site_x')   # newer engine, same site
        l2.observe_iteration(wall=1.0, compute=0.25)
        l1.unregister()                     # stale: must NOT evict l2
        snap = serve_ledger_snapshot()
        assert snap is not None
        assert snap['ledger']['site_x']['wall_seconds'] \
            == pytest.approx(1.0)
        l2.unregister()
        assert serve_ledger_snapshot() is None
        l2.unregister()                     # idempotent

    def test_render(self, clean_registry):
        led = ServeLedger(engine='site_r')
        led.observe_iteration(wall=0.010, compute=0.006,
                              host_fetch=0.001)
        led.account_prefill(10, 4, tenant_id='t0')
        led.account_spec_shed(2)
        text = render_serve_ledger(serve_ledger_snapshot())
        assert 'engine: site_r' in text
        assert 'residue' in text and 'page_stream' in text
        assert 'goodput: 10 delivered / 4 wasted of 14 emitted' in text
        assert 'preempt_recompute=4' in text
        assert 'spec capacity shed' in text
        assert 'tenant t0' in text
        led.unregister()


# ---------------------------------------------------------------------------
# HBM peak table — never faked off-TPU
# ---------------------------------------------------------------------------
class TestPeakTable:
    @pytest.mark.parametrize('kind,peak', [
        ('TPU v6e', 1638.0), ('Trillium', 1638.0), ('TPU v5p', 2765.0),
        ('TPU v5 lite', 819.0), ('TPU v5e', 819.0), ('TPU v4', 1228.0),
        ('TPU v3', 900.0), ('TPU v2', 700.0)])
    def test_known_generations(self, kind, peak):
        assert resolve_peak_hbm_gbps(kind) == peak

    def test_non_tpu_and_unknown_are_none(self):
        assert resolve_peak_hbm_gbps('cpu') is None
        assert resolve_peak_hbm_gbps('Radeon') is None
        assert resolve_peak_hbm_gbps('TPU v99') is None
        # the local device in this suite is CPU: no peak, no MBU
        assert resolve_peak_hbm_gbps() is None

    def test_table_entries_positive(self):
        assert all(p > 0 for _s, p in HBM_GBPS)


# ---------------------------------------------------------------------------
# roofline: analytic bytes-moved model, MBU/MFU only against real peaks
# ---------------------------------------------------------------------------
class TestRoofline:
    def test_decode_bytes_model_and_mbu(self, clean_registry):
        led = ServeLedger(engine='rf0', param_bytes=1000,
                          kv_bytes_per_token=10, peak_hbm_gbps=100.0)
        led.observe_iteration(wall=0.01, compute=0.008,
                              decode_seconds=0.004, kv_read_tokens=50)
        led.observe_iteration(wall=0.01, compute=0.008,
                              decode_seconds=0.004, kv_read_tokens=150)
        r = led.roofline()
        # bytes/iter = params + mean(kv tokens read) * bytes/token
        assert r['decode_bytes_per_iteration'] == pytest.approx(
            1000 + 100 * 10)
        gbps = 2000 / 0.004 / 1e9
        assert r['hbm_gbps'] == pytest.approx(gbps)
        assert r['mbu'] == pytest.approx(gbps / 100.0)
        led.unregister()

    def test_mbu_none_without_peak(self, clean_registry):
        # CPU dryrun: resolve_peak_hbm_gbps() is None here, so the
        # ledger reports absolute GB/s with mbu None — never a faked %
        led = ServeLedger(engine='rf1', param_bytes=64,
                          kv_bytes_per_token=4)
        led.observe_iteration(wall=0.01, decode_seconds=0.002,
                              kv_read_tokens=16)
        r = led.roofline()
        assert r['hbm_gbps'] > 0.0
        assert r['peak_hbm_gbps'] is None and r['mbu'] is None
        led.unregister()

    def test_prefill_tflops_and_mfu(self, clean_registry):
        led = ServeLedger(engine='rf2', n_params=10 ** 6, layers=2,
                          hidden=64, peak_tflops=1.0)
        led.observe_iteration(wall=0.05, prefill_tokens=32,
                              prefill_seconds=0.01,
                              prefill_ctx_tokens=32 * 20)
        r = led.roofline()
        from paddle_tpu.core.ledger import model_flops_per_step
        total, _ = model_flops_per_step(10 ** 6, 32, layers=2,
                                        hidden=64, seq_len=20)
        assert r['prefill_model_flops'] == pytest.approx(total / 3.0)
        assert r['prefill_tflops'] == pytest.approx(
            total / 3.0 / 0.01 / 1e12)
        assert r['prefill_mfu'] == pytest.approx(r['prefill_tflops'])
        led.unregister()

    def test_none_before_any_dispatch(self, clean_registry):
        led = ServeLedger(engine='rf3')
        assert led.roofline() is None
        led.observe_iteration(wall=0.01, compute=0.005)  # sched-only
        assert led.roofline() is None
        led.unregister()


# ---------------------------------------------------------------------------
# the real engine: identity under preemption + spec, trace-v4 parity,
# ledger reconciliation, host-bound fraction, snapshot lifecycle
# ---------------------------------------------------------------------------
class TestEngineGoodput:
    def test_baseline_matches_scheduler_ground_truth(self, tiny_lm,
                                                     mixed_prompts):
        # ample pool, no spec, no cache: every prompt position is
        # computed exactly once and every decode column lands — the
        # ledger must price delivered = sum(P_i + N_i - 1) (the first
        # token rides the final prefill column) and wasted = 0
        eng = ServingEngine(tiny_lm, ServingConfig(
            page_size=8, max_batch_size=3, prefill_chunk=16,
            prefix_cache=False))
        outs = eng.generate(mixed_prompts, max_new_tokens=6, top_k=0)
        assert eng.stats()['preemptions_total'] == 0
        g = eng.ledger.goodput()
        expect = sum(len(p) + (len(o) - len(p)) - 1
                     for p, o in zip(mixed_prompts, outs))
        assert g['delivered_tokens'] == expect, g
        assert g['wasted_tokens'] == 0 and g['spec_shed_tokens'] == 0
        assert g['emitted_tokens'] == expect
        eng.shutdown()

    def test_identity_under_preemption_and_spec_with_trace_parity(
            self, tiny_lm):
        # 4-page pool forces preempt/resume; repetitive prompts make
        # the n-gram proposer fire so drafts get rejected; the identity
        # must hold EXACTLY and the v4 trace must price every request
        # to the same delivered/wasted totals the engine charged
        from paddle_tpu.serving.request_trace import (load_trace,
                                                      reconstruct)
        import tempfile
        import os
        prompts = [[7, 8, 9] * 5, [3, 4] * 6, [5, 6, 7] * 6,
                   [9, 2] * 7]
        eng = ServingEngine(tiny_lm, ServingConfig(
            page_size=8, max_batch_size=3, prefill_chunk=8,
            num_pages=4, spec_k=4, trace=True))
        eng.generate(prompts, max_new_tokens=8, top_k=0)
        st = eng.stats()
        assert st['preemptions_total'] > 0       # pressure actually hit
        assert eng._spec_proposed > 0            # spec actually ran
        g = eng.ledger.goodput()
        assert g['delivered_tokens'] + g['wasted_tokens'] \
            == g['emitted_tokens']
        assert g['wasted_by_cause']['preempt_recompute'] > 0
        assert g['wasted_by_cause']['spec_rejected'] \
            >= eng._spec_proposed - eng._spec_accepted
        # trace ground truth: per-request v4 pricing sums to the
        # engine's lifetime account
        with tempfile.TemporaryDirectory() as td:
            p = os.path.join(td, 'serve.jsonl')
            eng.export_trace(jsonl_path=p)
            header, events = load_trace(p)
        assert header['schema'] == 'paddle_tpu.serve_trace/6'
        table = reconstruct(events)
        assert sum(r['delivered_tokens'] for r in table.values()) \
            == g['delivered_tokens']
        assert sum(r['wasted_tokens'] for r in table.values()) \
            == g['wasted_tokens']
        assert sum(r['recompute_tokens'] for r in table.values()) \
            == g['wasted_by_cause']['preempt_recompute']
        eng.shutdown()

    def test_degrade_shed_priced_outside_identity(self, tiny_lm):
        # forced stage 1 with spec configured on: drafts are shed, so
        # nothing spec-related is computed — shed capacity is reported
        # beside the identity, never inside wasted
        prompts = [[7, 8, 9] * 5, [3, 4] * 6]
        eng = ServingEngine(tiny_lm, ServingConfig(
            page_size=8, max_batch_size=2, prefill_chunk=8, spec_k=4,
            degrade=True, tenants={}, degrade_hold=10 ** 9))
        eng._ladder.stage = 1
        eng.generate(prompts, max_new_tokens=8, top_k=0)
        assert eng._spec_proposed == 0           # drafts actually shed
        g = eng.ledger.goodput()
        assert g['spec_shed_tokens'] > 0
        assert g['wasted_by_cause']['spec_rejected'] == 0
        assert g['delivered_tokens'] + g['wasted_tokens'] \
            == g['emitted_tokens']
        eng.shutdown()

    def test_ledger_reconciles_and_host_bound_real(self, tiny_lm,
                                                   mixed_prompts):
        eng = ServingEngine(tiny_lm, ServingConfig(
            page_size=8, max_batch_size=3, prefill_chunk=16))
        eng.generate(mixed_prompts, max_new_tokens=6, top_k=0)
        a = eng.ledger.account()
        assert a['iterations'] > 0
        wall = a['wall_seconds']
        assert wall > 0.0
        # clamped components reconcile by construction; the bench-leg
        # acceptance bound (10%) is asserted here on a live run too
        total = sum(a['components'].values())
        assert abs(total - wall) <= 0.10 * wall, a
        assert a['components']['compute'] > 0.0
        assert a['components']['host_fetch'] > 0.0
        # host_bound_fraction comes from the registered HostGapMonitor
        # fed by the real sampled-token fetches — present and sane
        hbf = a['host_bound_fraction']
        assert hbf is not None and 0.0 <= hbf <= 1.0
        roof = eng.ledger.roofline()
        assert roof['decode_bytes_per_iteration'] > 0
        assert roof['mbu'] is None               # CPU: never faked
        assert roof['prefill_tflops'] > 0.0
        eng.shutdown()

    def test_snapshot_merges_and_shutdown_unregisters(
            self, tiny_lm, mixed_prompts, clean_registry):
        from paddle_tpu.serving.metrics import serve_snapshot
        eng = ServingEngine(tiny_lm, ServingConfig(
            page_size=8, max_batch_size=3, prefill_chunk=16))
        eng.generate(mixed_prompts[:3], max_new_tokens=4, top_k=0)
        eng.publish_metrics()
        s = serve_snapshot()
        assert 'serve' in s['ledger'], s.keys()
        g = s['goodput']
        assert g['delivered_tokens'] + g['wasted_tokens'] \
            == g['emitted_tokens'] > 0
        assert 'serve' in s['roofline']
        assert s['ledger']['serve']['wall_seconds'] > 0
        assert s['ledger']['serve']['host_bound_fraction'] is not None
        # the published gauges land in the monitor registry
        from paddle_tpu.core import monitor as _m
        reg = _m.metrics()
        assert reg.get('ptpu_serve_ledger_wall_seconds').value(
            engine='serve') > 0
        assert reg.get('ptpu_serve_goodput_emitted_tokens').value(
            engine='serve') == g['emitted_tokens']
        # PR-13 discipline: shutdown unregisters ledger AND monitor,
        # so a dead engine stops reporting immediately
        eng.shutdown()
        assert serve_ledger_snapshot() is None
        from paddle_tpu.core.async_step import _monitors
        assert eng.ledger_site not in _monitors

    def test_zero_extra_host_syncs(self, tiny_lm, mixed_prompts,
                                   monkeypatch):
        # the PR-6 sync-count harness: the full goodput/ledger/roofline
        # observatory must not add a single host fetch — the budget
        # stays exactly one per token-yielding step
        counts = [0]
        real = engine_mod._host_fetch

        def counting(x):
            counts[0] += 1
            return real(x)
        monkeypatch.setattr(engine_mod, '_host_fetch', counting)
        try:
            eng = ServingEngine(tiny_lm, ServingConfig(
                page_size=8, max_batch_size=3, prefill_chunk=8,
                num_pages=4))
            outs = eng.generate(mixed_prompts, max_new_tokens=6,
                                top_k=0)
            st = eng.stats()
            n_gen = counts[0]
            # reading every account + publishing adds zero syncs
            eng.ledger.account()
            eng.ledger.goodput()
            eng.ledger.roofline()
            eng.publish_metrics()
            assert counts[0] == n_gen
            eng.shutdown()
        finally:
            monkeypatch.setattr(engine_mod, '_host_fetch', real)
        generated = sum(len(o) - len(p)
                        for o, p in zip(outs, mixed_prompts))
        prefill_fetches = generated - st['decode_tokens_total']
        assert n_gen == st['decode_steps_total'] + prefill_fetches, \
            (n_gen, st)


# ---------------------------------------------------------------------------
# cluster: drain-resubmit recompute priced wasted, identity preserved
# ---------------------------------------------------------------------------
class TestClusterDrainGoodput:
    def test_drain_resubmit_moves_delivered_to_wasted(self, tiny_lm,
                                                      mixed_prompts):
        from paddle_tpu.serving.cluster import (ClusterRouter,
                                                LocalReplica)
        reps = [LocalReplica(
            ServingEngine(tiny_lm, ServingConfig(
                page_size=8, max_batch_size=3, prefill_chunk=16)), rid)
            for rid in ('r0', 'r1')]
        router = ClusterRouter(reps, page_size=8, max_queue=32)
        reqs = [router.submit(p, max_new_tokens=12, top_k=0)
                for p in mixed_prompts]
        for _ in range(6):                       # partial progress
            router.pump()
        drained = reqs[0].replica_id
        router.drain(drained, reason='ledger test')
        router.run(timeout_s=120)
        assert all(r.done for r in reqs)
        router.refresh()
        snap = router.snapshot()
        g = snap['goodput']
        assert g is not None, snap
        # the resubmitted prefix a peer re-prefilled is priced wasted
        # (cause drain_recompute), NOT delivered — and the identity
        # stays exact at the cluster level
        assert g['drain_recompute_tokens'] > 0
        assert g['wasted_by_cause']['drain_recompute'] > 0
        assert g['delivered_tokens'] + g['wasted_tokens'] \
            == g['emitted_tokens']
        # move-not-add: cluster totals tie back to the replicas' own
        # accounts exactly
        rep_goodputs = [row['goodput']
                        for row in snap['replicas'].values()
                        if row.get('goodput')]
        rep_emitted = sum(r['emitted_tokens'] for r in rep_goodputs)
        rep_delivered = sum(r['delivered_tokens'] for r in rep_goodputs)
        rep_wasted = sum(r['wasted_tokens'] for r in rep_goodputs)
        moved = g['wasted_by_cause']['drain_recompute']
        assert g['emitted_tokens'] == rep_emitted
        assert g['delivered_tokens'] == rep_delivered - moved
        assert g['wasted_tokens'] == rep_wasted + moved
        assert moved == min(g['drain_recompute_tokens'], rep_delivered)
        # the lifetime counter reaches cluster_snapshot() for telemetry
        from paddle_tpu.serving.cluster.router import cluster_snapshot
        cs = cluster_snapshot()
        assert cs['ptpu_route_drain_recompute_tokens_total'] \
            >= g['drain_recompute_tokens']
        router.shutdown()
        assert all(rep.engine.ledger_site not in ledger_mod._ledgers
                   or ledger_mod._ledgers[rep.engine.ledger_site]
                   is not rep.engine.ledger for rep in reps)


# ---------------------------------------------------------------------------
# trace schema v6: old schemas still load
# ---------------------------------------------------------------------------
class TestSchemaCompat:
    @pytest.mark.parametrize('version', [1, 2, 3, 4, 5])
    def test_older_schemas_still_load(self, version, tmp_path):
        import json
        from paddle_tpu.serving.request_trace import (load_trace,
                                                      reconstruct)
        p = tmp_path / f'v{version}.jsonl'
        header = {'schema': f'paddle_tpu.serve_trace/{version}',
                  'dropped_events': 0}
        events = [
            {'event': 'submit', 'req': 0, 't': 1.0, 'prompt_tokens': 4},
            {'event': 'admit', 'req': 0, 't': 1.1},
            {'event': 'prefill_chunk', 'req': 0, 't': 1.2, 'tokens': 4},
            {'event': 'first_token', 'req': 0, 't': 1.3,
             'tokens_generated': 1},
            {'event': 'decode', 'req': 0, 't': 1.4,
             'tokens_generated': 2},
            {'event': 'retire', 'req': 0, 't': 1.5,
             'tokens_generated': 2},
        ]
        with open(p, 'w') as f:
            f.write(json.dumps(header) + '\n')
            for e in events:
                f.write(json.dumps(e) + '\n')
        hdr, evs = load_trace(str(p))
        assert hdr['schema'].endswith(f'/{version}')
        (r,) = reconstruct(evs).values()
        # pre-v4 journals reconstruct with zero waste — the delivered
        # column still prices what the journal does know
        assert r['recompute_tokens'] == 0 and r['spec_discarded'] == 0
        assert r['delivered_tokens'] == 4 + (2 - 1)
        assert r['wasted_tokens'] == 0
