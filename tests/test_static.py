"""Static-graph path tests.

Reference patterns: program construction + Executor (fluid tests),
meta-optimizer compile-only golden tests (§4.3 —
test_fleet_*_meta_optimizer.py assert on the rewritten program, no devices
needed)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.static as static


@pytest.fixture(autouse=True)
def _static_mode():
    paddle.enable_static()
    yield
    paddle.disable_static()


def test_program_records_ops():
    main = static.Program()
    with static.program_guard(main):
        x = static.data('x', [4, 8])
        y = static.nn.fc(x, 16, activation='relu')
        out = paddle.mean(y)
    types = [op.type for op in main.global_block().ops]
    assert 'matmul_v2' in types and 'relu' in types \
        and 'reduce_mean' in types
    assert out.shape == []
    assert len(main.all_parameters()) == 2  # w + b


def test_executor_forward():
    main = static.Program()
    with static.program_guard(main):
        x = static.data('x', [2, 4])
        y = static.nn.fc(x, 3)
    exe = static.Executor()
    with static.scope_guard(static.Scope()):
        res = exe.run(main, feed={'x': np.ones((2, 4), 'float32')},
                      fetch_list=[y])
    assert res[0].shape == (2, 3)


def test_minimize_trains_regression():
    """fit_a_line pattern (book test) through the static path."""
    paddle.seed(0)
    rng = np.random.RandomState(0)
    xs = rng.rand(64, 4).astype('float32')
    w_true = np.array([[1.0], [-2.0], [3.0], [0.5]], 'float32')
    ys = xs @ w_true + 0.1

    main = static.Program()
    with static.program_guard(main):
        x = static.data('x', [64, 4])
        label = static.data('label', [64, 1])
        pred = static.nn.fc(x, 1)
        loss = paddle.mean((pred - label) * (pred - label))
        opt = paddle.optimizer.SGD(learning_rate=0.1)
        opt.minimize(loss)

    exe = static.Executor()
    scope = static.Scope()
    losses = []
    with static.scope_guard(scope):
        for i in range(150):
            res = exe.run(main, feed={'x': xs, 'label': ys},
                          fetch_list=[loss])
            losses.append(float(res[0]))
    assert losses[-1] < 0.1 < losses[0]


def test_minimize_adam_state_persists():
    paddle.seed(1)
    main = static.Program()
    with static.program_guard(main):
        x = static.data('x', [8, 4])
        y = static.nn.fc(x, 2)
        loss = paddle.mean(y * y)
        paddle.optimizer.Adam(learning_rate=0.1).minimize(loss)
    exe = static.Executor()
    scope = static.Scope()
    with static.scope_guard(scope):
        xs = np.random.RandomState(0).rand(8, 4).astype('float32')
        l0 = exe.run(main, feed={'x': xs}, fetch_list=[loss])[0]
        for _ in range(5):
            l1 = exe.run(main, feed={'x': xs}, fetch_list=[loss])[0]
        keys = [k for k in scope.vars if k.startswith('__opt_states__')]
        assert keys, scope.vars.keys()
        states = scope.find_var(keys[0])
        first = next(iter(states.values()))
        assert 'moment1' in first  # adam state threaded through the scope
    assert float(l1) < float(l0)


def test_device_guard_records_op_device():
    """Pipeline stage marking (parity: device_guard → op_device attr,
    optimizer.py:4628 keys on it)."""
    main = static.Program()
    with static.program_guard(main):
        x = static.data('x', [2, 4])
        with static.device_guard('gpu:0'):
            h = static.nn.fc(x, 8)
        with static.device_guard('gpu:1'):
            y = static.nn.fc(h, 2)
    devices = [op.op_device for op in main.global_block().ops]
    assert 'gpu:0' in devices and 'gpu:1' in devices


class TestMetaOptimizerGolden:
    """Compile-only meta-optimizer tests (§4.3 pattern): apply a strategy,
    assert on the rewritten/annotated program — no devices needed."""

    def _toy(self):
        main = static.Program()
        with static.program_guard(main):
            x = static.data('x', [4, 8])
            y = static.nn.fc(x, 2)
            loss = paddle.mean(y * y)
        return main, loss

    def _minimize(self, strategy, loss):
        import paddle_tpu.distributed.fleet as fleet
        import os
        os.environ.setdefault('PADDLE_TRAINER_ID', '0')
        fleet.fleet._hcg = None
        fleet.init(is_collective=True, strategy=strategy)
        opt = paddle.optimizer.SGD(learning_rate=0.1)
        opt = fleet.fleet.distributed_optimizer(opt)
        fleet.fleet.minimize(loss)

    def test_amp_strategy_marks_program(self):
        from paddle_tpu.distributed.fleet import DistributedStrategy
        main, loss = self._toy()
        s = DistributedStrategy()
        s.amp = True
        s.amp_configs = {'init_loss_scaling': 1024.0}
        self._minimize(s, loss)
        assert getattr(main, '_amp', None) is not None
        assert main._amp['init_loss_scaling'] == 1024.0

    def test_recompute_strategy(self):
        from paddle_tpu.distributed.fleet import DistributedStrategy
        main, loss = self._toy()
        s = DistributedStrategy()
        s.recompute = True
        s.recompute_configs = {'checkpoints': ['fc_0.tmp']}
        self._minimize(s, loss)
        assert main._recompute_checkpoints == ['fc_0.tmp']

    def test_pipeline_strategy(self):
        from paddle_tpu.distributed.fleet import DistributedStrategy
        main, loss = self._toy()
        s = DistributedStrategy()
        s.pipeline = True
        s.pipeline_configs = {'accumulate_steps': 4,
                              'micro_batch_size': 2}
        self._minimize(s, loss)
        assert main._pipeline_opt['accumulate_steps'] == 4

    def test_sharding_strategy(self):
        from paddle_tpu.distributed.fleet import DistributedStrategy
        main, loss = self._toy()
        s = DistributedStrategy()
        s.sharding = True
        s.sharding_configs = {'sharding_degree': 4, 'stage': 2}
        self._minimize(s, loss)
        assert main._sharding['sharding_degree'] == 4
        assert main._sharding['stage'] == 2

    def test_strategy_unknown_key_raises(self):
        from paddle_tpu.distributed.fleet import DistributedStrategy
        s = DistributedStrategy()
        with pytest.raises(AttributeError):
            s.not_a_real_field = True
        with pytest.raises(ValueError):
            s.sharding_configs = {'bogus_key': 1}

    def test_strategy_prototxt_roundtrip(self):
        import tempfile
        import os
        from paddle_tpu.distributed.fleet import DistributedStrategy
        s = DistributedStrategy()
        s.amp = True
        s.hybrid_configs = {'dp_degree': 2, 'mp_degree': 4}
        path = os.path.join(tempfile.mkdtemp(), 's.prototxt')
        s.save_to_prototxt(path)
        s2 = DistributedStrategy()
        s2.load_from_prototxt(path)
        assert s2.amp is True
        assert s2.hybrid_configs['mp_degree'] == 4
