"""Static-graph path tests.

Reference patterns: program construction + Executor (fluid tests),
meta-optimizer compile-only golden tests (§4.3 —
test_fleet_*_meta_optimizer.py assert on the rewritten program, no devices
needed)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.static as static


@pytest.fixture(autouse=True)
def _static_mode():
    paddle.enable_static()
    yield
    paddle.disable_static()


def test_program_records_ops():
    main = static.Program()
    with static.program_guard(main):
        x = static.data('x', [4, 8])
        y = static.nn.fc(x, 16, activation='relu')
        out = paddle.mean(y)
    types = [op.type for op in main.global_block().ops]
    assert 'matmul_v2' in types and 'relu' in types \
        and 'reduce_mean' in types
    assert out.shape == []
    assert len(main.all_parameters()) == 2  # w + b


def test_executor_forward():
    main = static.Program()
    with static.program_guard(main):
        x = static.data('x', [2, 4])
        y = static.nn.fc(x, 3)
    exe = static.Executor()
    with static.scope_guard(static.Scope()):
        res = exe.run(main, feed={'x': np.ones((2, 4), 'float32')},
                      fetch_list=[y])
    assert res[0].shape == (2, 3)


def test_minimize_trains_regression():
    """fit_a_line pattern (book test) through the static path."""
    paddle.seed(0)
    rng = np.random.RandomState(0)
    xs = rng.rand(64, 4).astype('float32')
    w_true = np.array([[1.0], [-2.0], [3.0], [0.5]], 'float32')
    ys = xs @ w_true + 0.1

    main = static.Program()
    with static.program_guard(main):
        x = static.data('x', [64, 4])
        label = static.data('label', [64, 1])
        pred = static.nn.fc(x, 1)
        loss = paddle.mean((pred - label) * (pred - label))
        opt = paddle.optimizer.SGD(learning_rate=0.1)
        opt.minimize(loss)

    exe = static.Executor()
    scope = static.Scope()
    losses = []
    with static.scope_guard(scope):
        for i in range(150):
            res = exe.run(main, feed={'x': xs, 'label': ys},
                          fetch_list=[loss])
            losses.append(float(res[0]))
    assert losses[-1] < 0.1 < losses[0]


def test_minimize_adam_state_persists():
    paddle.seed(1)
    main = static.Program()
    with static.program_guard(main):
        x = static.data('x', [8, 4])
        y = static.nn.fc(x, 2)
        loss = paddle.mean(y * y)
        paddle.optimizer.Adam(learning_rate=0.1).minimize(loss)
    exe = static.Executor()
    scope = static.Scope()
    with static.scope_guard(scope):
        xs = np.random.RandomState(0).rand(8, 4).astype('float32')
        l0 = exe.run(main, feed={'x': xs}, fetch_list=[loss])[0]
        for _ in range(5):
            l1 = exe.run(main, feed={'x': xs}, fetch_list=[loss])[0]
        # adam state threaded through named persistable vars (parity:
        # _add_accumulator naming) and mutated across runs
        keys = [k for k in scope.vars if 'adam_moment1' in k]
        assert keys, scope.vars.keys()
        assert float(np.abs(np.asarray(scope.find_var(keys[0]))).sum()) > 0
    assert float(l1) < float(l0)


def test_device_guard_records_op_device():
    """Pipeline stage marking (parity: device_guard → op_device attr,
    optimizer.py:4628 keys on it)."""
    main = static.Program()
    with static.program_guard(main):
        x = static.data('x', [2, 4])
        with static.device_guard('gpu:0'):
            h = static.nn.fc(x, 8)
        with static.device_guard('gpu:1'):
            y = static.nn.fc(h, 2)
    devices = [op.op_device for op in main.global_block().ops]
    assert 'gpu:0' in devices and 'gpu:1' in devices


def test_executor_cache_invalidates_on_inplace_rewrite():
    """VERDICT r3 weak #4: an in-place op replacement that keeps the op
    count constant must recompile, not replay the stale trace (parity:
    CompiledProgram invalidation, fluid/compiler.py:88)."""
    import jax.numpy as jnp
    from paddle_tpu.static.program import Operator
    main = static.Program()
    with static.program_guard(main):
        x = static.data('x', [2, 3])
        y = x * 2.0
    exe = static.Executor()
    xs = np.ones((2, 3), np.float32)
    with static.scope_guard(static.Scope()):
        out1 = exe.run(main, feed={'x': xs}, fetch_list=[y])[0]
        np.testing.assert_allclose(out1, 2 * xs)
        # replace the scale op in place: same op count, same io names
        block = main.global_block()
        idx = next(i for i, op in enumerate(block.ops)
                   if y.name in op.output_names)
        old = block.ops[idx]
        block.ops[idx] = Operator(old.type, lambda *a: a[0] * 5.0,
                                  list(old.input_names),
                                  list(old.output_names), {'scale': 5.0})
        out2 = exe.run(main, feed={'x': xs}, fetch_list=[y])[0]
        np.testing.assert_allclose(out2, 5 * xs)


class TestProgramRewriteGolden:
    """Real program-rewrite golden tests (§4.3 pattern): the pass output's
    op list is asserted directly, the reference's cheapest, most portable
    test form (test_fleet_sharding_meta_optimizer.py /
    test_fleet_pipeline_meta_optimizer.py)."""

    def _pipeline_program(self, batch=4):
        main = static.Program()
        with static.program_guard(main):
            x = static.data('x', [batch, 4])
            label = static.data('label', [batch, 1])
            with static.device_guard('stage:0'):
                h = static.nn.fc(x, 8, activation='relu')
            with static.device_guard('stage:1'):
                h2 = static.nn.fc(h, 8, activation='relu')
            with static.device_guard('stage:2'):
                pred = static.nn.fc(h2, 1)
                loss = paddle.mean((pred - label) * (pred - label))
            paddle.optimizer.SGD(learning_rate=0.1).minimize(loss)
        return main, loss

    def test_backward_records_grad_ops(self):
        """append_backward appends real *_grad ops with Backward role and
        the forward op's device."""
        main, _ = self._pipeline_program()
        ops = main.global_block().ops
        types = [op.type for op in ops]
        assert 'matmul_v2_grad' in types and 'relu_grad' in types
        assert 'fill_any_like' in types          # d loss seed
        assert types.count('sgd') == 6           # one optimize op per param
        for op in ops:
            if op.type.endswith('_grad'):
                assert op.op_role & static.program.OpRole.Backward

    def test_pipeline_split_golden(self):
        from paddle_tpu.static.pipeline_pass import split_program
        main, loss = self._pipeline_program()
        progs, rings = split_program(main, 3)
        assert len(progs) == 3
        t = [[op.type for op in p.global_block().ops] for p in progs]
        # forward boundary sends on stages 0/1, recvs on 1/2
        assert 'send_v2' in t[0] and 'recv_v2' in t[1]
        assert 'send_v2' in t[1] and 'recv_v2' in t[2]
        # backward boundary: grads flow 2->1->0
        assert 'send_v2' in t[2] and 'recv_v2' in t[0]
        # loss + its seed only on the last stage
        assert 'reduce_mean' in t[2] and 'fill_any_like' in t[2]
        assert 'reduce_mean' not in t[0] and 'fill_any_like' not in t[0]
        # optimize ops follow their params' stages: 2 per stage here
        assert [tt.count('sgd') for tt in t] == [2, 2, 2]
        # reference pair_key ring convention src*1000+dst
        assert rings[(0, 1)] == 1 and rings[(1, 2)] == 1002
        assert rings[(2, 1)] == 2001 and rings[(1, 0)] == 1000
        # every op carries a stage device
        for p in progs:
            for op in p.global_block().ops:
                assert op.op_device, op.type

    def test_pipeline_runner_matches_single_program(self):
        """Split programs + microbatched runner == unsplit Executor,
        loss-trajectory-identical (pipeline_mnist_one_device pattern)."""
        from paddle_tpu.static.pipeline_pass import (split_program,
                                                     LocalPipelineRunner)
        rng = np.random.RandomState(0)
        xs = rng.rand(8, 4).astype('float32')
        ys = (xs @ rng.rand(4, 1).astype('float32') + 0.1).astype('float32')

        paddle.seed(0)
        main, loss = self._pipeline_program(batch=4)
        progs, _ = split_program(main, 3)
        scope = static.Scope()
        runner = LocalPipelineRunner(progs, scope)
        pl = [runner.run([{'x': xs[:4], 'label': ys[:4]},
                          {'x': xs[4:], 'label': ys[4:]}],
                         fetch_name=loss.name) for _ in range(20)]

        paddle.seed(0)
        main2, loss2 = self._pipeline_program(batch=8)
        exe = static.Executor()
        with static.scope_guard(static.Scope()):
            ref = [float(exe.run(main2, feed={'x': xs, 'label': ys},
                                 fetch_list=[loss2])[0])
                   for _ in range(20)]
        np.testing.assert_allclose(pl, ref, rtol=1e-4, atol=1e-5)

    def _sharding_program(self, minimize=True):
        main = static.Program()
        with static.program_guard(main):
            x = static.data('x', [8, 4])
            label = static.data('label', [8, 1])
            h = static.nn.fc(x, 8, activation='relu')
            pred = static.nn.fc(h, 1)
            loss = paddle.mean((pred - label) * (pred - label))
            if minimize:
                paddle.optimizer.Adam(learning_rate=0.05).minimize(loss)
        return main, loss

    def test_sharding_rewrite_golden(self):
        from paddle_tpu.static.sharding_pass import shard_program
        main, _ = self._sharding_program()
        n_params = 4
        p2r = shard_program(main, 0, 2, stage=2)
        types = [op.type for op in main.global_block().ops]
        owned = [p for p, r in p2r.items() if r == 0]
        # ZeRO-2: one reduce-to-owner + scale per grad
        assert types.count('c_reduce_sum') == n_params
        assert types.count('scale') >= n_params
        # optimize ops pruned to owned params only
        assert types.count('adam') == len(owned)
        # updated params broadcast from their owners
        assert types.count('c_broadcast') == n_params
        roots = [op.attrs['root'] for op in main.global_block().ops
                 if op.type == 'c_broadcast']
        assert set(roots) == {0, 1}
        # non-owned optimizer state vars deleted (the ZeRO memory saving)
        moments = [v for v in main.global_block().vars
                   if 'adam_moment1' in v]
        assert len(moments) == len(owned)

    def test_sharding_two_rank_matches_unsharded(self):
        """2-rank ZeRO-2 lockstep == single unsharded run (in-process
        stand-in for test_dist_base's 2-process loss comparison)."""
        from paddle_tpu.static.sharding_pass import (
            shard_program, MultiRankShardingSimulator)
        rng = np.random.RandomState(0)
        xs = rng.rand(8, 4).astype('float32')
        ys = (xs @ rng.rand(4, 1).astype('float32') + 0.1).astype('float32')

        rank_progs = []
        loss_name = None
        for r in range(2):
            with paddle.utils.unique_name.guard():
                m, loss = self._sharding_program()
            shard_program(m, r, 2, stage=2)
            rank_progs.append(m)
            loss_name = loss.name
        sim = MultiRankShardingSimulator(rank_progs, seed=0)
        losses = []
        for _ in range(25):
            ls = sim.run([{'x': xs, 'label': ys}, {'x': xs, 'label': ys}],
                         fetch_name=loss_name)
            assert abs(ls[0] - ls[1]) < 1e-6   # ranks stay in sync
            losses.append(ls[0])

        paddle.seed(0)
        m3, loss3 = self._sharding_program()
        exe = static.Executor()
        with static.scope_guard(static.Scope()):
            ref = [float(exe.run(m3, feed={'x': xs, 'label': ys},
                                 fetch_list=[loss3])[0])
                   for _ in range(25)]
        np.testing.assert_allclose(losses, ref, rtol=1e-3, atol=1e-5)

    def test_sharding_zero2_global_clip_matches_unsharded(self):
        """ZeRO-2 + ClipGradByGlobalNorm: the clip norm is computed over
        owned (reduced) grads and allreduced across shards (parity:
        sharding/gradient_clip_helper.py) — naive per-rank clipping over
        mixed reduced/unreduced grads diverges."""
        from paddle_tpu.static.sharding_pass import (
            shard_program, MultiRankShardingSimulator)
        from paddle_tpu.nn import ClipGradByGlobalNorm
        rng = np.random.RandomState(0)
        xs = rng.rand(8, 4).astype('float32')
        ys = (xs @ rng.rand(4, 1).astype('float32') + 0.1).astype('float32')

        def build():
            main = static.Program()
            with static.program_guard(main):
                x = static.data('x', [8, 4])
                label = static.data('label', [8, 1])
                h = static.nn.fc(x, 8, activation='relu')
                pred = static.nn.fc(h, 1)
                loss = paddle.mean((pred - label) * (pred - label))
                paddle.optimizer.Adam(
                    learning_rate=0.05,
                    grad_clip=ClipGradByGlobalNorm(0.5)).minimize(loss)
            return main, loss

        rank_progs = []
        for r in range(2):
            with paddle.utils.unique_name.guard():
                m, loss = build()
            shard_program(m, r, 2, stage=2)
            rank_progs.append(m)
        sim = MultiRankShardingSimulator(rank_progs, seed=0)
        losses = []
        for _ in range(20):
            ls = sim.run([{'x': xs, 'label': ys}, {'x': xs, 'label': ys}],
                         fetch_name=loss.name)
            assert abs(ls[0] - ls[1]) < 1e-6
            losses.append(ls[0])

        paddle.seed(0)
        m3, loss3 = build()
        exe = static.Executor()
        with static.scope_guard(static.Scope()):
            ref = [float(exe.run(m3, feed={'x': xs, 'label': ys},
                                 fetch_list=[loss3])[0])
                   for _ in range(20)]
        np.testing.assert_allclose(losses, ref, rtol=1e-3, atol=1e-5)

    def test_executor_refuses_sharded_program(self):
        """Running a rank-rewritten sharded program through the plain
        Executor would replay identity collectives and skip pruned
        updates — it must raise, not silently mistrain."""
        from paddle_tpu.static.sharding_pass import shard_program
        main, loss = self._sharding_program()
        shard_program(main, 0, 2, stage=2)
        exe = static.Executor()
        with static.scope_guard(static.Scope()):
            with pytest.raises(RuntimeError):
                exe.run(main, feed={'x': np.zeros((8, 4), 'float32'),
                                    'label': np.zeros((8, 1), 'float32')},
                        fetch_list=[loss])

    def test_backward_through_int_output_op(self):
        """Multi-output op with an integer output (top-k indices) on the
        grad path: integer cotangents become float0, not a trace error."""
        main = static.Program()
        with static.program_guard(main):
            x = static.data('x', [4, 8])
            h = static.nn.fc(x, 8)
            vals, idx = paddle.topk(h, k=3)
            loss = paddle.mean(vals)
            paddle.optimizer.SGD(learning_rate=0.1).minimize(loss)
        exe = static.Executor()
        with static.scope_guard(static.Scope()):
            xs = np.random.RandomState(0).rand(4, 8).astype('float32')
            l0 = exe.run(main, feed={'x': xs}, fetch_list=[loss])[0]
            for _ in range(5):
                l1 = exe.run(main, feed={'x': xs}, fetch_list=[loss])[0]
        assert np.isfinite(l0) and np.isfinite(l1)

    def test_sharding_meta_optimizer_rewrites(self):
        """Through the user-facing fleet path: strategy.sharding really
        rewrites the program (not just an annotation)."""
        import os
        import paddle_tpu.distributed.fleet as fleet
        os.environ.setdefault('PADDLE_TRAINER_ID', '0')
        fleet.fleet._hcg = None
        main, loss = self._sharding_program(minimize=False)
        s = fleet.DistributedStrategy()
        s.sharding = True
        s.sharding_configs = {'sharding_degree': 2, 'stage': 2}
        fleet.init(is_collective=True, strategy=s)
        opt = paddle.optimizer.SGD(learning_rate=0.1)
        opt = fleet.fleet.distributed_optimizer(opt)
        fleet.fleet.minimize(loss)
        types = [op.type for op in main.global_block().ops]
        assert 'c_reduce_sum' in types and 'c_broadcast' in types
        assert types.count('sgd') < 4   # some optimize ops pruned


class TestMetaOptimizerGolden:
    """Compile-only meta-optimizer tests (§4.3 pattern): apply a strategy,
    assert on the rewritten/annotated program — no devices needed."""

    def _toy(self):
        main = static.Program()
        with static.program_guard(main):
            x = static.data('x', [4, 8])
            y = static.nn.fc(x, 2)
            loss = paddle.mean(y * y)
        return main, loss

    def _minimize(self, strategy, loss):
        import paddle_tpu.distributed.fleet as fleet
        import os
        os.environ.setdefault('PADDLE_TRAINER_ID', '0')
        fleet.fleet._hcg = None
        fleet.init(is_collective=True, strategy=strategy)
        opt = paddle.optimizer.SGD(learning_rate=0.1)
        opt = fleet.fleet.distributed_optimizer(opt)
        fleet.fleet.minimize(loss)

    def test_amp_strategy_marks_program(self):
        from paddle_tpu.distributed.fleet import DistributedStrategy
        main, loss = self._toy()
        s = DistributedStrategy()
        s.amp = True
        s.amp_configs = {'init_loss_scaling': 1024.0}
        self._minimize(s, loss)
        assert getattr(main, '_amp', None) is not None
        assert main._amp['init_loss_scaling'] == 1024.0

    def test_recompute_strategy(self):
        """strategy.recompute drives the REAL segment-recompute rewrite
        (behavioral coverage in tests/test_meta_optimizers.py); an
        unknown checkpoint name raises instead of silently no-oping."""
        from paddle_tpu.distributed.fleet import DistributedStrategy
        main = static.Program()
        with static.program_guard(main):
            x = static.data('x', [4, 8])
            h = static.nn.fc(x, 8, activation='relu')
            y = static.nn.fc(h, 2)
            loss = paddle.mean(y * y)
        s = DistributedStrategy()
        s.recompute = True
        s.recompute_configs = {'checkpoints': [h.name]}
        self._minimize(s, loss)
        assert main._recompute_checkpoints == [h.name]
        types = [op.type for op in main.global_block().ops]
        assert 'recompute_barrier' in types

        main2, loss2 = self._toy()
        s2 = DistributedStrategy()
        s2.recompute = True
        s2.recompute_configs = {'checkpoints': ['not_a_var']}
        with pytest.raises(ValueError, match='not found'):
            self._minimize(s2, loss2)

    def test_pipeline_strategy(self):
        from paddle_tpu.distributed.fleet import DistributedStrategy
        main, loss = self._toy()
        s = DistributedStrategy()
        s.pipeline = True
        s.pipeline_configs = {'accumulate_steps': 4,
                              'micro_batch_size': 2}
        self._minimize(s, loss)
        assert main._pipeline_opt['accumulate_steps'] == 4

    def test_sharding_strategy(self):
        from paddle_tpu.distributed.fleet import DistributedStrategy
        main, loss = self._toy()
        s = DistributedStrategy()
        s.sharding = True
        s.sharding_configs = {'sharding_degree': 4, 'stage': 2}
        self._minimize(s, loss)
        assert main._sharding['sharding_degree'] == 4
        assert main._sharding['stage'] == 2

    def test_strategy_unknown_key_raises(self):
        from paddle_tpu.distributed.fleet import DistributedStrategy
        s = DistributedStrategy()
        with pytest.raises(AttributeError):
            s.not_a_real_field = True
        with pytest.raises(ValueError):
            s.sharding_configs = {'bogus_key': 1}

    def test_strategy_prototxt_roundtrip(self):
        import tempfile
        import os
        from paddle_tpu.distributed.fleet import DistributedStrategy
        s = DistributedStrategy()
        s.amp = True
        s.hybrid_configs = {'dp_degree': 2, 'mp_degree': 4}
        path = os.path.join(tempfile.mkdtemp(), 's.prototxt')
        s.save_to_prototxt(path)
        s2 = DistributedStrategy()
        s2.load_from_prototxt(path)
        assert s2.amp is True
        assert s2.hybrid_configs['mp_degree'] == 4


def test_fp16_allreduce_strategy_rewrites_and_runs():
    """FP16AllReduce meta-optimizer inserts the bf16 wire-cast per grad
    (fp16_allreduce_optimizer.py parity) and the program still trains."""
    import paddle_tpu.distributed.fleet as fleet
    import os
    os.environ.setdefault('PADDLE_TRAINER_ID', '0')
    fleet.fleet._hcg = None
    paddle.seed(0)
    main = static.Program()
    with static.program_guard(main):
        x = static.data('x', [8, 4])
        label = static.data('label', [8, 1])
        y = static.nn.fc(x, 1)
        loss = paddle.mean((y - label) * (y - label))
    s = fleet.DistributedStrategy()
    s.fp16_allreduce = True
    fleet.init(is_collective=True, strategy=s)
    opt = paddle.optimizer.SGD(learning_rate=0.1)
    opt = fleet.fleet.distributed_optimizer(opt)
    fleet.fleet.minimize(loss)
    types = [op.type for op in main.global_block().ops]
    n_grads = len(main._grad_map)
    assert types.count('cast_fp16_allreduce') == n_grads and n_grads >= 2
    # casts sit after backward, before the first optimize op
    first_cast = types.index('cast_fp16_allreduce')
    from paddle_tpu.static.program import OpRole
    first_opt = next(i for i, op in enumerate(main.global_block().ops)
                     if op.op_role & OpRole.Optimize)
    assert first_cast < first_opt
    exe = static.Executor()
    rng = np.random.RandomState(0)
    xs = rng.rand(8, 4).astype('float32')
    ys = (xs.sum(1, keepdims=True) * 0.5).astype('float32')
    with static.scope_guard(static.Scope()):
        losses = [float(exe.run(main, feed={'x': xs, 'label': ys},
                                fetch_list=[loss])[0])
                  for _ in range(40)]
    assert losses[-1] < 0.3 * losses[0]


def test_dataparallel_fp16_allreduce_wire_dtype():
    """DataParallel(fp16_allreduce=True) puts bf16 on the wire and
    restores the grad dtype."""
    import jax.numpy as jnp
    from paddle_tpu.distributed import parallel as par
    from paddle_tpu.distributed import collective as C
    paddle.disable_static()    # eager path (module fixture enables static)
    try:
        _dp_fp16_allreduce_body()
    finally:
        paddle.enable_static()   # restore for the module fixture


def _dp_fp16_allreduce_body():
    import jax.numpy as jnp
    import numpy as np
    from paddle_tpu.distributed import collective as C
    paddle.seed(1)
    model = paddle.nn.Linear(4, 2)
    dp = paddle.DataParallel(model, fp16_allreduce=True)
    x = paddle.to_tensor(np.random.RandomState(0)
                         .rand(4, 4).astype('float32'))
    loss = dp(x).sum()
    loss.backward()
    seen = {}
    orig = C.all_reduce

    def spy(tensor, *a, **k):
        seen['dtype'] = tensor.data.dtype
        return orig(tensor, *a, **k)
    # force the bucket path even at world_size 1
    import paddle_tpu.distributed.parallel as pmod
    orig_ws = pmod.get_world_size
    pmod.get_world_size = lambda g=None: 2
    C_orig = pmod.collective.all_reduce
    pmod.collective.all_reduce = spy
    try:
        dp.apply_collective_grads()
    finally:
        pmod.collective.all_reduce = C_orig
        pmod.get_world_size = orig_ws
    assert seen['dtype'] == jnp.bfloat16
    for p in model.parameters():
        assert p.grad.data.dtype == jnp.float32


def test_fp16_allreduce_casts_precede_collectives():
    """With sharding rewrites in the chain, the bf16 rounding must land
    BEFORE the c_reduce/c_allreduce consuming each grad — rounding after
    the exchange would model the wrong numerics (review r3)."""
    import paddle_tpu.distributed.fleet as fleet
    import os
    os.environ.setdefault('PADDLE_TRAINER_ID', '0')
    fleet.fleet._hcg = None
    paddle.seed(0)
    main = static.Program()
    with static.program_guard(main):
        x = static.data('x', [8, 4])
        y = static.nn.fc(x, 4)
        loss = paddle.mean(y * y)
    s = fleet.DistributedStrategy()
    s.fp16_allreduce = True
    s.sharding = True
    s.sharding_configs = {'sharding_degree': 2}
    fleet.init(is_collective=True, strategy=s)
    opt = paddle.optimizer.SGD(learning_rate=0.1)
    opt = fleet.fleet.distributed_optimizer(opt)
    fleet.fleet.minimize(loss)
    ops = main.global_block().ops
    checked = 0
    for gname in main._grad_map.values():
        cast_i = [i for i, op in enumerate(ops)
                  if op.type == 'cast_fp16_allreduce'
                  and gname in op.output_names]
        coll_i = [i for i, op in enumerate(ops)
                  if op.type in ('c_allreduce_sum', 'c_reduce_sum')
                  and gname in op.input_names]
        if cast_i and coll_i:
            assert max(cast_i) < min(coll_i), (gname, cast_i, coll_i)
            checked += 1
    assert checked >= 1        # the assertion above must not be vacuous
