"""Fused Pallas primitives library (ISSUE 8, TPP arXiv:2104.05755).

Interpret-mode parity for every primitive vs its pure-jnp reference —
fp32 and bf16, odd shapes that don't divide the block sizes, grad
checks for the custom-VJP LayerNorm / bias+GELU / dropout+residual —
plus the fused-vs-unfused engine-step equivalence on tiny models (all
three compiled engines), the found-inf exact-no-op contract, the
one-host-sync taps invariant on the fused route, and the routing
counters. All kernels run under Pallas interpret mode on the CPU mesh
(flags force the kernel route), covering the bodies that lower on TPU.
"""
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax                                                  # noqa: E402
import jax.numpy as jnp                                     # noqa: E402

import paddle_tpu as paddle                                 # noqa: E402
from paddle_tpu import nn                                   # noqa: E402
from paddle_tpu.core import bucketing as B                  # noqa: E402
from paddle_tpu.core import flags                           # noqa: E402
from paddle_tpu.core.tensor import Tensor                   # noqa: E402
from paddle_tpu.ops.pallas import (                         # noqa: E402
    scaffold, fused_optimizer as FO, fused_norm as FN,
    fused_elementwise as FE)

FUSED_FLAGS = ('FLAGS_fused_optimizer', 'FLAGS_fused_layer_norm',
               'FLAGS_fused_elementwise')


@pytest.fixture(autouse=True)
def _reset_flags():
    yield
    flags.set_flags({f: None for f in FUSED_FLAGS})


def _force(on):
    flags.set_flags({f: bool(on) for f in FUSED_FLAGS})


# ---------------------------------------------------------------------------
# scaffolding
# ---------------------------------------------------------------------------
class TestScaffold:
    def test_to_rows_round_trip_odd_length(self):
        x = jnp.arange(1003, dtype=jnp.float32)
        x2 = scaffold.to_rows(x)
        assert x2.shape[1] == scaffold.LANES
        assert x2.shape[0] % scaffold.ROW_BLOCK == 0
        np.testing.assert_array_equal(
            np.asarray(scaffold.from_rows(x2, 1003)), np.asarray(x))
        # pad region is zeros
        assert float(jnp.sum(jnp.abs(x2))) == float(jnp.sum(jnp.abs(x)))

    def test_fit_block_divides(self):
        assert scaffold.fit_block(512, 2048) == 512
        assert scaffold.fit_block(512, 96) == 96 or \
            96 % scaffold.fit_block(512, 96) == 0

    def test_route_counters(self):
        before = scaffold.routes_snapshot().get('_t_prim',
                                                {'kernel': 0,
                                                 'fallback': 0})
        scaffold.record_route('_t_prim', True)
        scaffold.record_route('_t_prim', False)
        scaffold.record_route('_t_prim', False)
        after = scaffold.routes_snapshot()['_t_prim']
        assert after['kernel'] - before.get('kernel', 0) == 1
        assert after['fallback'] - before.get('fallback', 0) == 2
        assert '_t_prim' in scaffold.active_primitives()
        snap = scaffold.snapshot()
        assert snap and '_t_prim' in snap['routes']

    def test_use_kernel_respects_flag_and_support(self):
        flags.set_flags({'FLAGS_fused_optimizer': True})
        assert scaffold.use_kernel('_t_prim2', 'FLAGS_fused_optimizer')
        # unsupported pins the fallback even when forced on
        assert not scaffold.use_kernel('_t_prim2',
                                       'FLAGS_fused_optimizer',
                                       supported=False)
        flags.set_flags({'FLAGS_fused_optimizer': False})
        assert not scaffold.use_kernel('_t_prim2',
                                       'FLAGS_fused_optimizer')


# ---------------------------------------------------------------------------
# grad stats
# ---------------------------------------------------------------------------
class TestGradStats:
    def test_parity_odd_length(self):
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randn(7777), jnp.float32)
        s, c = FO.grad_stats_pallas(x)
        np.testing.assert_allclose(float(s), float(jnp.sum(x * x)),
                                   rtol=1e-6)
        assert float(c) == 0.0

    def test_nonfinite_poisons_sum_and_counts(self):
        x = jnp.zeros((300,), jnp.float32).at[7].set(jnp.inf) \
            .at[123].set(jnp.nan)
        s, c = FO.grad_stats_pallas(x)
        assert not np.isfinite(float(s))
        assert float(c) == 2.0

    def test_bucketing_entry_routes(self):
        flags.set_flags({'FLAGS_fused_optimizer': True})
        x = jnp.asarray(np.random.RandomState(1).randn(500), jnp.float32)
        s, c = B.grad_stats(x)
        flags.set_flags({'FLAGS_fused_optimizer': False})
        s2, c2 = B.grad_stats(x)
        np.testing.assert_allclose(float(s), float(s2), rtol=1e-6)
        assert float(c) == float(c2) == 0.0


# ---------------------------------------------------------------------------
# fused optimizer step
# ---------------------------------------------------------------------------
def _optimizers():
    return [
        ('adamw', lambda: paddle.optimizer.AdamW(
            learning_rate=0.01, weight_decay=0.01, parameters=[])),
        ('adam_bf16_moments', lambda: paddle.optimizer.Adam(
            learning_rate=0.01, parameters=[], moment_dtype='bfloat16')),
        ('momentum_wd', lambda: paddle.optimizer.Momentum(
            learning_rate=0.05, weight_decay=1e-4, parameters=[])),
        ('sgd', lambda: paddle.optimizer.SGD(
            learning_rate=0.1, parameters=[])),
        ('rmsprop_centered', lambda: paddle.optimizer.RMSProp(
            learning_rate=0.01, centered=True, parameters=[])),
        ('adamax', lambda: paddle.optimizer.Adamax(
            learning_rate=0.01, parameters=[])),
        ('adadelta', lambda: paddle.optimizer.Adadelta(
            learning_rate=0.1, parameters=[])),
        ('decayed_adagrad', lambda: paddle.optimizer.DecayedAdagrad(
            learning_rate=0.01, parameters=[])),
    ]


class TestFusedShardUpdate:
    # 1000 elements: not a multiple of LANES (128) nor the row block
    L = 1000

    def _state(self, opt, p):
        st = opt.init_state(Tensor(jnp.zeros((self.L,), jnp.float32)))
        st = {k: jnp.asarray(v) for k, v in st.items()}
        if p.dtype != jnp.float32:
            st['master'] = p.astype(jnp.float32)
        return st

    @pytest.mark.parametrize('name', [n for n, _ in _optimizers()])
    @pytest.mark.parametrize('dtype', ['float32', 'bfloat16'])
    def test_parity_vs_reference(self, name, dtype):
        mk = dict(_optimizers())[name]
        rng = np.random.RandomState(0)
        pdt = jnp.dtype(dtype)
        opt = mk()
        assert FO.fusible(opt)
        p = jnp.asarray(rng.randn(self.L), jnp.float32).astype(pdt)
        g = jnp.asarray(rng.randn(self.L), jnp.float32)
        st = self._state(opt, p)
        lr = jnp.asarray(0.01, jnp.float32)
        pref = jnp.asarray(0.7, jnp.float32)
        for fi in (None, jnp.asarray(False)):
            flags.set_flags({'FLAGS_fused_optimizer': False})
            ref_p, ref_s = B.shard_update(opt, p, g, dict(st), lr,
                                          prefactor=pref, found_inf=fi)
            fz_p, fz_s = FO.fused_shard_update(opt, p, g, dict(st), lr,
                                               prefactor=pref,
                                               found_inf=fi)
            assert set(ref_s) == set(fz_s)
            tol = dict(rtol=2e-6, atol=5e-7) if dtype == 'float32' \
                else dict(rtol=1e-2, atol=1e-2)
            np.testing.assert_allclose(np.asarray(fz_p, np.float32),
                                       np.asarray(ref_p, np.float32),
                                       **tol)
            for k in ref_s:
                np.testing.assert_allclose(
                    np.asarray(fz_s[k], np.float32),
                    np.asarray(ref_s[k], np.float32),
                    err_msg=f'{name} state {k}', **tol)

    @pytest.mark.parametrize('name', ['adamw', 'momentum_wd'])
    def test_found_inf_is_exact_noop(self, name):
        mk = dict(_optimizers())[name]
        rng = np.random.RandomState(1)
        opt = mk()
        p = jnp.asarray(rng.randn(self.L), jnp.float32)
        g = jnp.full((self.L,), jnp.nan, jnp.float32)
        st = self._state(opt, p)
        new_p, ns = FO.fused_shard_update(
            opt, p, g, dict(st), jnp.asarray(0.01, jnp.float32),
            prefactor=jnp.asarray(1.0, jnp.float32),
            found_inf=jnp.asarray(True))
        np.testing.assert_array_equal(np.asarray(new_p), np.asarray(p))
        for k in st:
            np.testing.assert_array_equal(
                np.asarray(ns[k], np.float32),
                np.asarray(st[k], np.float32), err_msg=k)

    def test_unfusible_optimizer_falls_back(self):
        opt = paddle.optimizer.Lamb(learning_rate=0.01, parameters=[])
        flags.set_flags({'FLAGS_fused_optimizer': True})
        assert not FO.use_fused_update(opt)


# ---------------------------------------------------------------------------
# fused LayerNorm
# ---------------------------------------------------------------------------
def _ln_ref(x, w, b, eps):
    mean = jnp.mean(x.astype(jnp.float32), axis=-1, keepdims=True)
    var = jnp.var(x.astype(jnp.float32), axis=-1, keepdims=True)
    out = ((x.astype(jnp.float32) - mean)
           * jax.lax.rsqrt(var + eps)).astype(x.dtype)
    return out * w + b


class TestFusedLayerNorm:
    # odd row/feature counts that divide neither ROW_BLOCK nor LANES
    SHAPES = [(7, 33), (3, 5, 129), (130, 64)]

    @pytest.mark.parametrize('shape', SHAPES)
    @pytest.mark.parametrize('dtype', ['float32', 'bfloat16'])
    def test_forward_parity(self, shape, dtype):
        rng = np.random.RandomState(0)
        dt = jnp.dtype(dtype)
        x = jnp.asarray(rng.randn(*shape), jnp.float32).astype(dt)
        w = jnp.asarray(1 + 0.1 * rng.randn(shape[-1]),
                        jnp.float32).astype(dt)
        b = jnp.asarray(0.1 * rng.randn(shape[-1]),
                        jnp.float32).astype(dt)
        got = FN.fused_layer_norm(x, w, b, 1e-5)
        ref = _ln_ref(x, w, b, 1e-5)
        assert got.dtype == ref.dtype and got.shape == ref.shape
        tol = dict(rtol=2e-6, atol=2e-6) if dtype == 'float32' \
            else dict(rtol=2e-2, atol=2e-2)
        np.testing.assert_allclose(np.asarray(got, np.float32),
                                   np.asarray(ref, np.float32), **tol)

    @pytest.mark.parametrize('shape', [(7, 33), (130, 64)])
    def test_grads_match_reference_vjp(self, shape):
        rng = np.random.RandomState(1)
        x = jnp.asarray(rng.randn(*shape), jnp.float32)
        w = jnp.asarray(1 + 0.1 * rng.randn(shape[-1]), jnp.float32)
        b = jnp.asarray(0.1 * rng.randn(shape[-1]), jnp.float32)
        dy = jnp.asarray(rng.randn(*shape), jnp.float32)
        _, vjp_ref = jax.vjp(lambda *a: _ln_ref(*a, 1e-5), x, w, b)
        _, vjp_fus = jax.vjp(
            lambda *a: FN.fused_layer_norm(*a, 1e-5), x, w, b)
        for got, ref, nm in zip(vjp_fus(dy), vjp_ref(dy),
                                ('dx', 'dw', 'db')):
            np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                       rtol=2e-5, atol=2e-5, err_msg=nm)

    def test_functional_routes_and_matches(self):
        rng = np.random.RandomState(2)
        x = Tensor(jnp.asarray(rng.randn(9, 31), jnp.float32))
        w = Tensor(jnp.ones((31,), jnp.float32))
        b = Tensor(jnp.zeros((31,), jnp.float32))
        from paddle_tpu.nn import functional as F
        before = scaffold.routes_snapshot().get('layer_norm', {})
        flags.set_flags({'FLAGS_fused_layer_norm': True})
        got = F.layer_norm(x, [31], w, b)
        flags.set_flags({'FLAGS_fused_layer_norm': False})
        ref = F.layer_norm(x, [31], w, b)
        np.testing.assert_allclose(np.asarray(got.data),
                                   np.asarray(ref.data), rtol=2e-6,
                                   atol=2e-6)
        after = scaffold.routes_snapshot()['layer_norm']
        assert after.get('kernel', 0) > before.get('kernel', 0)
        assert after.get('fallback', 0) > before.get('fallback', 0)

    def test_multi_axis_norm_keeps_reference_path(self):
        # 2-axis normalization is outside the kernel's shape contract —
        # must not route (and must still be correct)
        from paddle_tpu.nn import functional as F
        x = Tensor(jnp.ones((4, 3, 5), jnp.float32))
        flags.set_flags({'FLAGS_fused_layer_norm': True})
        out = F.layer_norm(x, [3, 5])
        assert tuple(out.shape) == (4, 3, 5)

    def test_mixed_dtype_affine_keeps_reference_path(self):
        # bf16 x with fp32 weight/bias PROMOTES on the reference path
        # (bf16 xhat * fp32 w -> fp32); the kernel stores in x.dtype,
        # so the mixed case must not route — output dtype must match
        # the unfused result
        from paddle_tpu.nn import functional as F
        x = Tensor(jnp.ones((4, 8), jnp.bfloat16))
        w = Tensor(jnp.ones((8,), jnp.float32))
        b = Tensor(jnp.zeros((8,), jnp.float32))
        flags.set_flags({'FLAGS_fused_layer_norm': True})
        got = F.layer_norm(x, [8], w, b)
        flags.set_flags({'FLAGS_fused_layer_norm': False})
        ref = F.layer_norm(x, [8], w, b)
        assert got.data.dtype == ref.data.dtype
        np.testing.assert_allclose(np.asarray(got.data, np.float32),
                                   np.asarray(ref.data, np.float32))

    def test_zero_row_input_on_kernel_route(self):
        # zero-size batch must not crash the grid construction (one
        # all-pad block) and must return the empty result
        flags.set_flags({'FLAGS_fused_layer_norm': True,
                         'FLAGS_fused_elementwise': True})
        x = jnp.zeros((0, 16), jnp.float32)
        out = FN.fused_layer_norm(x, jnp.ones((16,)), jnp.zeros((16,)),
                                  1e-5)
        assert out.shape == (0, 16)
        out = FE.bias_gelu(x, jnp.ones((16,)), True)
        assert out.shape == (0, 16)
        s, c = FO.grad_stats_pallas(jnp.zeros((0,), jnp.float32))
        assert float(s) == 0.0 and float(c) == 0.0


# ---------------------------------------------------------------------------
# fused bias+GELU and dropout+residual
# ---------------------------------------------------------------------------
class TestBiasGelu:
    @pytest.mark.parametrize('approximate', [True, False])
    @pytest.mark.parametrize('dtype', ['float32', 'bfloat16'])
    def test_forward_parity(self, approximate, dtype):
        rng = np.random.RandomState(0)
        dt = jnp.dtype(dtype)
        x = jnp.asarray(rng.randn(7, 33), jnp.float32).astype(dt)
        b = jnp.asarray(rng.randn(33), jnp.float32).astype(dt)
        got = FE.bias_gelu(x, b, approximate)
        ref = FE.bias_gelu_reference(x, b, approximate)
        assert got.dtype == ref.dtype
        tol = dict(rtol=1e-6, atol=1e-6) if dtype == 'float32' \
            else dict(rtol=2e-2, atol=2e-2)
        np.testing.assert_allclose(np.asarray(got, np.float32),
                                   np.asarray(ref, np.float32), **tol)

    @pytest.mark.parametrize('approximate', [True, False])
    def test_grads_match_reference_vjp(self, approximate):
        rng = np.random.RandomState(1)
        x = jnp.asarray(rng.randn(9, 31), jnp.float32)
        b = jnp.asarray(rng.randn(31), jnp.float32)
        dy = jnp.asarray(rng.randn(9, 31), jnp.float32)
        _, vjp_ref = jax.vjp(
            lambda *a: FE.bias_gelu_reference(*a, approximate), x, b)
        _, vjp_fus = jax.vjp(
            lambda *a: FE.bias_gelu(*a, approximate), x, b)
        for got, ref, nm in zip(vjp_fus(dy), vjp_ref(dy), ('dx', 'db')):
            np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                       rtol=2e-5, atol=2e-5, err_msg=nm)


class TestDropoutAdd:
    @pytest.mark.parametrize('dtype', ['float32', 'bfloat16'])
    def test_same_mask_matches_reference(self, dtype):
        rng = np.random.RandomState(0)
        dt = jnp.dtype(dtype)
        x = jnp.asarray(rng.randn(13, 29), jnp.float32).astype(dt)
        r = jnp.asarray(rng.randn(13, 29), jnp.float32).astype(dt)
        keep = jax.random.bernoulli(jax.random.PRNGKey(7), 0.9,
                                    x.shape).astype(jnp.float32)
        got = FE.dropout_add(x, r, keep, 0.1)
        ref = FE.dropout_add_reference(x, r, keep, 0.1)
        # same drop PATTERN (same key/shape draw); values to 1 ulp (XLA
        # contracts the divide/add chain differently inside one body)
        np.testing.assert_array_equal(
            np.asarray(got, np.float32) == 0,
            np.asarray(ref, np.float32) == 0)
        np.testing.assert_allclose(np.asarray(got, np.float32),
                                   np.asarray(ref, np.float32),
                                   rtol=1e-6, atol=1e-6)

    def test_grads(self):
        rng = np.random.RandomState(1)
        x = jnp.asarray(rng.randn(5, 17), jnp.float32)
        r = jnp.asarray(rng.randn(5, 17), jnp.float32)
        keep = jax.random.bernoulli(jax.random.PRNGKey(3), 0.8,
                                    x.shape).astype(jnp.float32)
        dy = jnp.asarray(rng.randn(5, 17), jnp.float32)
        _, vjp = jax.vjp(lambda a, b: FE.dropout_add(a, b, keep, 0.2),
                         x, r)
        dx, dr = vjp(dy)
        np.testing.assert_allclose(
            np.asarray(dx),
            np.asarray(jnp.where(keep > 0.5, dy / 0.8, 0.0)),
            rtol=1e-6)
        np.testing.assert_array_equal(np.asarray(dr), np.asarray(dy))

    def test_functional_same_seed_same_result_across_routes(self):
        from paddle_tpu.nn import functional as F
        rng = np.random.RandomState(2)
        x = Tensor(jnp.asarray(rng.randn(6, 21), jnp.float32))
        r = Tensor(jnp.asarray(rng.randn(6, 21), jnp.float32))
        flags.set_flags({'FLAGS_fused_elementwise': True})
        paddle.seed(123)
        got = F.dropout_add(x, r, p=0.3, training=True)
        flags.set_flags({'FLAGS_fused_elementwise': False})
        paddle.seed(123)
        ref = F.dropout_add(x, r, p=0.3, training=True)
        # same seed -> same bernoulli draw -> same drop pattern
        np.testing.assert_array_equal(np.asarray(got.data) == 0,
                                      np.asarray(ref.data) == 0)
        np.testing.assert_allclose(np.asarray(got.data),
                                   np.asarray(ref.data),
                                   rtol=1e-6, atol=1e-6)
        # eval: plain add, no RNG draw
        out = F.dropout_add(x, r, p=0.3, training=False)
        np.testing.assert_allclose(np.asarray(out.data),
                                   np.asarray(x.data) + np.asarray(r.data))


# ---------------------------------------------------------------------------
# engine-step equivalence: fused vs unfused on tiny models
# ---------------------------------------------------------------------------
def _mesh(axes, sizes):
    from paddle_tpu.distributed import topology_runtime
    return topology_runtime.build_mesh(axes, sizes)


class TestEngineFusedEquivalence:
    def _data(self):
        rng = np.random.RandomState(0)
        return (Tensor(rng.rand(16, 8).astype('float32')),
                Tensor(rng.rand(16, 1).astype('float32')))

    def test_trainstep_fused_matches_unfused(self):
        from paddle_tpu.jit import TrainStep
        rng = np.random.RandomState(0)
        x = paddle.to_tensor(rng.rand(8, 8).astype('float32'))
        y = paddle.to_tensor(rng.randint(0, 2, (8,)).astype('int64'))

        def run(fused):
            _force(fused)
            paddle.seed(0)
            net = nn.Sequential(nn.Linear(8, 16), nn.Tanh(),
                                nn.Linear(16, 2))
            opt = paddle.optimizer.Adam(
                learning_rate=0.01, parameters=net.parameters(),
                grad_clip=nn.ClipGradByGlobalNorm(1.0))
            step = TrainStep(net, lambda m, a, b: nn.functional
                             .cross_entropy(m(a), b), opt)
            return [float(step(x, y)) for _ in range(3)]
        got = run(True)
        ref = run(False)
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)

    def test_hybrid_fused_matches_unfused(self):
        from paddle_tpu.distributed.fleet.meta_parallel.hybrid_engine \
            import HybridParallelTrainStep
        X, Y = self._data()

        def run(fused):
            _force(fused)
            _mesh(['dp', 'sharding'], [2, 4])
            paddle.seed(0)
            net = nn.Sequential(nn.Linear(8, 16), nn.Tanh(),
                                nn.Linear(16, 1))
            opt = paddle.optimizer.AdamW(
                learning_rate=0.01, weight_decay=0.01,
                parameters=net.parameters(),
                grad_clip=nn.ClipGradByGlobalNorm(1.0))
            eng = HybridParallelTrainStep(
                net, lambda m, a, b: nn.functional.mse_loss(m(a), b),
                opt)
            assert eng._bucketed
            return [float(eng(X, Y)) for _ in range(3)]
        got = run(True)
        ref = run(False)
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)

    def test_pipeline_fused_matches_unfused_with_scaler(self):
        """Pipeline engine with the loss-scaling path active: the fused
        route folds unscale + found-inf into the optimizer kernel; the
        losses must match the reference route."""
        from paddle_tpu.models.gpt import GPTConfig, build_gpt_pipeline
        from paddle_tpu.distributed.fleet.meta_parallel.spmd_pipeline \
            import SpmdPipelineEngine
        cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                        num_heads=4, max_seq_len=16, hidden_dropout=0.0,
                        attn_dropout=0.0, use_flash_attention=False)
        rng = np.random.RandomState(0)
        A, mb, dp = 2, 1, 2
        ids = rng.randint(0, 64, (dp * A * mb, 16)).astype('int32')
        lab = np.roll(ids, -1, 1).astype('int32')

        def run(fused):
            _force(fused)
            _mesh(['dp', 'pp'], [dp, 2])
            paddle.seed(0)
            embed, blocks, head = build_gpt_pipeline(cfg)
            opt = paddle.optimizer.AdamW(learning_rate=0.01,
                                         weight_decay=0.01,
                                         parameters=[])
            eng = SpmdPipelineEngine(embed, blocks, head, opt,
                                     accumulate_steps=A,
                                     use_remat=False)
            out = [float(eng.train_batch((Tensor(ids), Tensor(lab)),
                                         scale=4.0))
                   for _ in range(2)]
            eng.shutdown()
            return out
        got = run(True)
        ref = run(False)
        np.testing.assert_allclose(got, ref, rtol=2e-4, atol=1e-5)

    def test_fused_route_keeps_one_sync_taps(self, monkeypatch):
        """PR-3 invariant: with numerics taps enabled the fused-route
        hybrid step still reports per-param stats at the same boundary
        with exactly ONE host sync per step."""
        from paddle_tpu.core import numerics as num
        from paddle_tpu.distributed.fleet.meta_parallel.hybrid_engine \
            import HybridParallelTrainStep
        _force(True)
        flags.set_flags({'FLAGS_tensor_stats': True})
        try:
            _mesh(['dp', 'sharding'], [2, 4])
            paddle.seed(0)
            net = nn.Sequential(nn.Linear(8, 16), nn.Tanh(),
                                nn.Linear(16, 1))
            opt = paddle.optimizer.AdamW(learning_rate=0.01,
                                         parameters=net.parameters())
            eng = HybridParallelTrainStep(
                net, lambda m, a, b: nn.functional.mse_loss(m(a), b),
                opt)
            X, Y = self._data()
            float(eng(X, Y))     # compile step outside the counter
            calls = []
            real = num._host_fetch
            monkeypatch.setattr(
                num, '_host_fetch',
                lambda tree: calls.append(1) or real(tree))
            float(eng(X, Y))
            assert len(calls) == 1, f'{len(calls)} host syncs'
            assert eng.last_numerics is not None
            stats = eng.last_numerics.get('grads') or {}
            assert len(stats) == len(list(eng._params))
        finally:
            flags.set_flags({'FLAGS_tensor_stats': False})

    def test_engine_records_optimizer_step_route(self):
        before = scaffold.routes_snapshot().get(
            'optimizer_step', {'kernel': 0})
        self.test_hybrid_fused_matches_unfused()
        after = scaffold.routes_snapshot()['optimizer_step']
        assert after.get('kernel', 0) > before.get('kernel', 0)
