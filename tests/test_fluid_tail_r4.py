"""fluid.layers wave-3 tail: conv3d_transpose, resizes, RNN-op
wrappers, TensorArray, Print/Assert, chunk_eval, decode helpers,
retinanet_target_assign, roi_perspective_transform, filter_by_instag."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.static import fluid_layers as fl
from paddle_tpu.vision import detection as det
from paddle_tpu.ops import recsys


def test_conv3d_transpose_shape_and_grad():
    paddle.enable_static()
    try:
        from paddle_tpu import static
        main, start = static.Program(), static.Program()
        with static.program_guard(main, start):
            x = static.data('x', [1, 2, 4, 4, 4], 'float32')
            y = fl.conv3d_transpose(x, num_filters=3, filter_size=3,
                                    stride=2, padding=1)
        exe = static.Executor()
        exe.run(start)
        out = exe.run(main, feed={
            'x': np.random.RandomState(0).rand(1, 2, 4, 4, 4)
            .astype(np.float32)}, fetch_list=[y])
        assert out[0].shape == (1, 3, 7, 7, 7)
    finally:
        paddle.disable_static()


def test_resize_wrappers():
    rng = np.random.RandomState(1)
    x3 = Tensor(rng.rand(1, 2, 4, 4, 4).astype(np.float32))
    out = fl.resize_trilinear(x3, out_shape=[8, 8, 8])
    assert out.shape == [1, 2, 8, 8, 8]
    x1 = Tensor(rng.rand(1, 2, 6).astype(np.float32))
    assert fl.resize_linear(x1, out_shape=[12]).shape == [1, 2, 12]
    img = Tensor(rng.rand(1, 3, 20, 30).astype(np.float32))
    short = fl.image_resize_short(img, 10)
    assert short.shape == [1, 3, 10, 15]


def test_rnn_op_wrappers_run():
    rng = np.random.RandomState(2)
    x = Tensor(rng.rand(2, 5, 4).astype(np.float32))
    out = fl.dynamic_gru(x, size=6)
    assert out.shape == [2, 5, 6]
    o, _ = fl.dynamic_lstm(x, size=24)     # 4 * hidden(6)
    assert o.shape == [2, 5, 6]
    proj, hid = fl.dynamic_lstmp(x, size=24, proj_size=3)
    assert proj.shape == [2, 5, 3]
    h0 = Tensor(np.zeros((1, 2, 6), np.float32))
    c0 = Tensor(np.zeros((1, 2, 6), np.float32))
    o, h, c = fl.lstm(x, h0, c0, max_len=5, hidden_size=6,
                      num_layers=1)
    assert o.shape == [2, 5, 6]
    ht = Tensor(np.zeros((2, 6), np.float32))
    nh, _, _ = fl.gru_unit(Tensor(rng.rand(2, 4).astype(np.float32)),
                           ht, size=18)
    assert nh.shape == [2, 6]
    hh, cc = fl.lstm_unit(Tensor(rng.rand(2, 4).astype(np.float32)),
                          ht, ht)
    assert hh.shape == [2, 6] and cc.shape == [2, 6]


def test_tensor_array_ops():
    arr = fl.create_array()
    i0 = Tensor(np.asarray(0))
    arr = fl.array_write(Tensor(np.ones((2, 3), np.float32)), i0, arr)
    arr = fl.array_write(Tensor(np.full((2, 3), 2.0, np.float32)),
                         Tensor(np.asarray(1)), arr)
    assert int(fl.array_length(arr).data) == 2
    r = fl.array_read(arr, Tensor(np.asarray(1)))
    assert float(np.asarray(r.data)[0, 0]) == 2.0
    cat, sizes = fl.tensor_array_to_tensor(arr, axis=0)
    assert np.asarray(cat.data).shape == (4, 3)
    np.testing.assert_array_equal(np.asarray(sizes.data), [2, 2])
    st, _ = fl.tensor_array_to_tensor(arr, axis=0, use_stack=True)
    assert np.asarray(st.data).shape == (2, 2, 3)


def test_print_assert_eager(capsys):
    x = Tensor(np.arange(4, dtype=np.float32))
    y = fl.Print(x, message='dbg')
    out = capsys.readouterr().out
    assert 'dbg' in out and 'shape=(4,)' in out
    np.testing.assert_array_equal(np.asarray(y.data),
                                  np.asarray(x.data))
    assert fl.Assert(Tensor(np.asarray(True)))
    with pytest.raises(ValueError, match='Assert failed'):
        fl.Assert(Tensor(np.asarray(False)),
                  data=[Tensor(np.asarray([1.0, 2.0]))])


def test_imperative_cf_raisers_guide():
    with pytest.raises(NotImplementedError, match='while_loop'):
        fl.While(cond=None)
    with pytest.raises(NotImplementedError, match='cond'):
        fl.IfElse(None)
    with pytest.raises(NotImplementedError, match='RNN'):
        fl.StaticRNN()
    with pytest.raises(NotImplementedError, match='DataLoader'):
        fl.py_reader()
    with pytest.raises(NotImplementedError, match='lengths'):
        fl.lod_reset(None, None)


def test_chunk_eval_iob():
    # IOB, 1 chunk type: tags B=0, I=1, O=2
    lab = np.array([[0, 1, 2, 0, 1, 1]], np.int64)   # chunks (0,1),(3,5)
    inf = np.array([[0, 1, 2, 0, 2, 2]], np.int64)   # chunks (0,1),(3,3)
    p, r, f1, ni, nl, nc = fl.chunk_eval(
        Tensor(inf), Tensor(lab), 'IOB', 1)
    assert int(ni.data) == 2 and int(nl.data) == 2
    assert int(nc.data) == 1                     # only (0,1) matches
    assert abs(float(p.data) - 0.5) < 1e-6
    assert abs(float(r.data) - 0.5) < 1e-6


def test_basic_decoder_with_training_helper():
    paddle.seed(0)
    B, T, H, V = 2, 4, 8, 10
    cell = nn.GRUCell(H, H)
    proj = nn.Linear(H, V)
    seq = Tensor(np.random.RandomState(3).rand(B, T, H)
                 .astype(np.float32))
    helper = nn.TrainingHelper(seq, Tensor(np.array([4, 3], np.int64)))
    dec = nn.BasicDecoder(cell, helper, output_fn=proj)
    h0 = Tensor(np.zeros((B, H), np.float32))
    out, final = nn.dynamic_decode(dec, inits=h0, max_step_num=T)
    co = np.asarray(out['cell_outputs'].data)
    assert co.shape[0] == B and co.shape[2] == V
    ids = np.asarray(out['sample_ids'].data)
    assert ((ids >= 0) & (ids < V)).all()


def test_greedy_and_sample_helpers():
    paddle.seed(0)
    B, H, V = 2, 6, 8
    emb = nn.Embedding(V, H)
    cell = nn.GRUCell(H, H)
    proj = nn.Linear(H, V)
    for helper_cls in (nn.GreedyEmbeddingHelper,):
        helper = helper_cls(emb, Tensor(np.full((B,), 1, np.int64)), 2)
        dec = nn.BasicDecoder(cell, helper, output_fn=proj)
        out, _ = nn.dynamic_decode(
            dec, inits=Tensor(np.zeros((B, H), np.float32)),
            max_step_num=5)
        assert np.asarray(out['sample_ids'].data).shape[0] == B
    helper = nn.SampleEmbeddingHelper(
        emb, Tensor(np.full((B,), 1, np.int64)), 2, seed=7)
    dec = nn.BasicDecoder(cell, helper, output_fn=proj)
    out, _ = nn.dynamic_decode(
        dec, inits=Tensor(np.zeros((B, H), np.float32)),
        max_step_num=5)
    assert np.asarray(out['sample_ids'].data).shape[0] == B


def test_retinanet_target_assign_contract():
    rng = np.random.RandomState(5)
    N, A, G, C = 1, 32, 2, 4
    anchors = np.sort(rng.rand(A, 4).astype(np.float32) * 40, -1)
    anchors = np.stack([anchors[:, 0], anchors[:, 1],
                        anchors[:, 0] + 8, anchors[:, 1] + 8], -1)
    gt = np.stack([anchors[3], anchors[17]])[None].astype(np.float32)
    gl = np.array([[1, 3]], np.int64)
    sc, lc, lab, tb, inw, fg = det.retinanet_target_assign(
        Tensor(rng.randn(N, A, 4).astype(np.float32)),
        Tensor(rng.randn(N, A, C).astype(np.float32)),
        Tensor(anchors), None, Tensor(gt), Tensor(gl), None,
        Tensor(np.array([[64.0, 64.0, 1.0]], np.float32)))
    labv = np.asarray(lab.data).reshape(-1)
    assert int(np.asarray(fg.data)[0]) >= 2
    # positives carry their gt class labels (1 and 3)
    pos_labels = labv[labv > 0]
    assert set(pos_labels.tolist()) <= {1, 3}
    assert len(pos_labels) >= 2
    assert np.asarray(lc.data).shape == np.asarray(tb.data).shape


def test_roi_perspective_transform_identity_quad():
    rng = np.random.RandomState(6)
    x = rng.rand(1, 1, 8, 8).astype(np.float32)
    # quad == axis-aligned rect covering [1,6]x[1,6]
    quad = np.array([[1.0, 1.0, 6.0, 1.0, 6.0, 6.0, 1.0, 6.0]],
                    np.float32)
    out, mask, h = det.roi_perspective_transform(
        Tensor(x), Tensor(quad), 6, 6, spatial_scale=1.0)
    o = np.asarray(out.data)
    assert o.shape == (1, 1, 6, 6)
    # axis-aligned identity-scale quad: output == the cropped region
    np.testing.assert_allclose(o[0, 0], x[0, 0, 1:7, 1:7], atol=1e-4)
    assert (np.asarray(mask.data) == 1).all()


def test_filter_by_instag():
    x = np.arange(12, dtype=np.float32).reshape(4, 3)
    tags = np.array([[1, -1], [2, 3], [4, -1], [3, -1]], np.int64)
    rows, lw, idx = recsys.filter_by_instag(
        Tensor(x), Tensor(tags), Tensor(np.array([3], np.int64)))
    np.testing.assert_array_equal(np.asarray(idx.data), [1, 3])
    np.testing.assert_allclose(np.asarray(rows.data), x[[1, 3]])
    np.testing.assert_allclose(np.asarray(lw.data), 1.0)
    # no match: single fill row, zero weight
    rows2, lw2, _ = recsys.filter_by_instag(
        Tensor(x), Tensor(tags), Tensor(np.array([99], np.int64)),
        out_val_if_empty=7)
    assert np.asarray(rows2.data).shape == (1, 3)
    assert (np.asarray(rows2.data) == 7).all()
    assert float(np.asarray(lw2.data).reshape(())) == 0.0


def test_beam_search_decode_fn():
    ids = np.array([[[2, 2], [6, 1]], [[3, 9], [6, 1]],
                    [[0, 1], [9, 0]]], np.int64)
    parents = np.array([[[0, 0], [1, 1]], [[1, 0], [1, 0]],
                        [[0, 0], [0, 1]]], np.int64)
    seqs, _ = fl.beam_search_decode(Tensor(ids), Tensor(parents))
    want = np.array([[[2, 2], [1, 6]], [[3, 3], [6, 1]],
                     [[0, 1], [9, 0]]], np.int64)
    np.testing.assert_array_equal(np.asarray(seqs.data), want)


def test_conv3d_transpose_paddle_shape_convention():
    paddle.enable_static()
    try:
        from paddle_tpu import static
        for pad, want in ((0, 9), (2, 5)):
            main, start = static.Program(), static.Program()
            with static.program_guard(main, start):
                x = static.data('x', [1, 2, 4, 4, 4], 'float32')
                y = fl.conv3d_transpose(x, num_filters=3, filter_size=3,
                                        stride=2, padding=pad)
            exe = static.Executor()
            exe.run(start)
            out = exe.run(main, feed={
                'x': np.ones((1, 2, 4, 4, 4), np.float32)},
                fetch_list=[y])
            # paddle: (in-1)*stride - 2*pad + k
            assert out[0].shape == (1, 3, want, want, want), \
                (pad, out[0].shape)
    finally:
        paddle.disable_static()


def test_dynamic_lstm_cell_sequence_is_distinct():
    rng = np.random.RandomState(7)
    x = Tensor(rng.rand(2, 5, 4).astype(np.float32))
    h_seq, c_seq = fl.dynamic_lstm(x, size=24)
    hv, cv = np.asarray(h_seq.data), np.asarray(c_seq.data)
    assert hv.shape == cv.shape == (2, 5, 6)
    assert not np.allclose(hv, cv)           # cell state != hidden
    # tanh(c) bounds h: |h| <= |tanh(c)| elementwise for LSTM
    assert (np.abs(hv) <= np.abs(np.tanh(cv)) + 1e-5).all()


def test_pool3d_ceil_exclusive_mean():
    x = Tensor(np.ones((1, 1, 6, 6, 6), np.float32))
    out = fl.pool3d(x, pool_size=3, pool_type='avg', pool_stride=2,
                    ceil_mode=True, exclusive=True)
    # all-ones input: exclusive mean is exactly 1 even at clipped edges
    np.testing.assert_allclose(np.asarray(out.data), 1.0, rtol=1e-6)


def test_resize_align_corners_endpoints():
    x = np.arange(4, dtype=np.float32).reshape(1, 1, 4)
    out = np.asarray(fl.resize_linear(Tensor(x), out_shape=[7]).data)
    # align_corners=True keeps the endpoints exact and spacing uniform
    np.testing.assert_allclose(out[0, 0, 0], 0.0, atol=1e-6)
    np.testing.assert_allclose(out[0, 0, -1], 3.0, atol=1e-6)
    np.testing.assert_allclose(out[0, 0], np.linspace(0, 3, 7),
                               atol=1e-5)


def test_gru_unit_full_outputs():
    rng = np.random.RandomState(8)
    x = Tensor(rng.rand(2, 4).astype(np.float32))
    h = Tensor(rng.rand(2, 6).astype(np.float32))
    nh, rhp, gate = fl.gru_unit(x, h, size=18)
    assert nh.shape == [2, 6]
    assert rhp.shape == [2, 6]
    assert gate.shape == [2, 18]             # [u, r, c-hat]
    g = np.asarray(gate.data)
    assert ((g[:, :12] >= 0) & (g[:, :12] <= 1)).all()   # sigmoids
    # reset_hidden_pre = r * h_prev
    np.testing.assert_allclose(np.asarray(rhp.data),
                               g[:, 6:12] * np.asarray(h.data),
                               rtol=1e-5)


def test_auc_pr_curve_differs_from_roc():
    from paddle_tpu.static import nn as snn
    rng = np.random.RandomState(9)
    # imbalanced: 10% positives, moderately separable
    n = 200
    lab = (rng.rand(n) < 0.1).astype(np.int64)
    score = np.clip(0.3 * lab + rng.rand(n) * 0.7, 0, 1) \
        .astype(np.float32)
    p2 = np.stack([1 - score, score], -1)
    roc = float(snn.auc(Tensor(p2), Tensor(lab[:, None])).data)
    pr = float(snn.auc(Tensor(p2), Tensor(lab[:, None]),
                       curve='PR').data)
    assert 0 < pr < 1 and 0 < roc < 1
    assert abs(roc - pr) > 0.05              # genuinely different metrics
