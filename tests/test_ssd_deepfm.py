"""SSD detector + DeepFM model families (detection tier / CTR tier
end-to-end): forward shapes, loss decreases, decode path emits boxes.

Reference parity: the SSD assembly (ssd_loss + detection_output over the
detection op tier) and the DeepFM CTR topology of the PS examples.
"""
import numpy as np
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.vision.models.ssd import (TinySSD, ssd_loss,
                                          ssd_detection_output)
from paddle_tpu.models.deepfm import DeepFM, deepfm_loss


def _t(a):
    return Tensor(jnp.asarray(a))


def _toy_scene(n=4, seed=0):
    """Images with one bright box each; gt = that box, class 1..3."""
    rng = np.random.RandomState(seed)
    imgs = rng.rand(n, 3, 64, 64).astype('float32') * 0.1
    boxes = np.zeros((n, 2, 4), 'float32')
    labels = np.zeros((n, 2), 'int64')
    for i in range(n):
        x0, y0 = rng.randint(4, 28, 2)
        w, h = rng.randint(16, 32, 2)
        x1, y1 = min(x0 + w, 63), min(y0 + h, 63)
        cls = rng.randint(1, 4)
        imgs[i, cls - 1, y0:y1, x0:x1] += 1.0
        boxes[i, 0] = [x0 / 64, y0 / 64, x1 / 64, y1 / 64]
        labels[i, 0] = cls
    return imgs, boxes, labels


class TestSSD:
    def test_forward_shapes_and_priors(self):
        paddle.seed(0)
        m = TinySSD(num_classes=4)
        imgs, _, _ = _toy_scene()
        loc, conf, priors, pvars = m(_t(imgs))
        P = priors.shape[0]
        assert tuple(loc.shape) == (4, P, 4)
        assert tuple(conf.shape) == (4, P, 4)
        pr = np.asarray(priors.data)
        assert (pr >= 0).all() and (pr <= 1).all()     # normalized, clipped
        assert tuple(np.asarray(pvars.data).shape) == (P, 4)

    @pytest.mark.slow   # ~35s convergence run: run_tests.sh tiers
    def test_loss_decreases(self):
        paddle.seed(1)
        m = TinySSD(num_classes=4)
        opt = paddle.optimizer.Adam(learning_rate=2e-3,
                                    parameters=m.parameters())
        imgs, boxes, labels = _toy_scene()
        losses = []
        for _ in range(25):
            loc, conf, priors, pvars = m(_t(imgs))
            loss = ssd_loss(loc, conf, priors, pvars, _t(boxes),
                            _t(labels))
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss))
        assert losses[-1] < 0.6 * losses[0], (losses[0], losses[-1])

    def test_detection_output_emits_boxes(self):
        paddle.seed(2)
        m = TinySSD(num_classes=4)
        imgs, boxes, labels = _toy_scene()
        loc, conf, priors, pvars = m(_t(imgs))
        out, idx, cnt = ssd_detection_output(loc, conf, priors, pvars,
                                             score_threshold=0.01,
                                             keep_top_k=10)
        o = np.asarray(out.data)
        assert o.shape == (4, 10, 6)
        c = np.asarray(cnt.data)
        assert (c > 0).all()
        # rows: [label, score, x1, y1, x2, y2]; labels within range, never
        # background
        valid = o[0, :int(c[0])]
        assert ((valid[:, 0] >= 1) & (valid[:, 0] <= 3)).all()


class TestDeepFM:
    def test_trains_on_synthetic_ctr(self):
        paddle.seed(3)
        rng = np.random.RandomState(0)
        F_, N = 6, 256
        ids = rng.randint(0, 100, (N, F_)).astype('int64')
        # clicky features: label depends on presence of low ids
        y = (ids < 12).sum(1, keepdims=True) >= 2
        m = DeepFM(num_features=100, fields=F_, embed_dim=8)
        opt = paddle.optimizer.Adam(learning_rate=5e-3,
                                    parameters=m.parameters())
        losses = []
        for _ in range(40):
            logits = m(_t(ids))
            loss = deepfm_loss(logits, _t(y.astype('float32')))
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss))
        assert losses[-1] < 0.5 * losses[0], (losses[0], losses[-1])
        # AUC sanity: predictions separate the classes
        p = 1 / (1 + np.exp(-np.asarray(m(_t(ids)).data)))
        assert p[y].mean() > p[~y].mean() + 0.2

    def test_fm_interaction_matches_bruteforce(self):
        paddle.seed(4)
        rng = np.random.RandomState(1)
        m = DeepFM(num_features=50, fields=4, embed_dim=3)
        ids = rng.randint(0, 50, (5, 4)).astype('int64')
        emb = np.asarray(m.embedding(_t(ids)).data)        # [5, 4, 3]
        # brute force pairwise dot
        exp = np.zeros((5, 1), 'float32')
        for n in range(5):
            for i in range(4):
                for j in range(i + 1, 4):
                    exp[n, 0] += emb[n, i] @ emb[n, j]
        s = emb.sum(1)
        trick = 0.5 * ((s * s).sum(-1) - (emb * emb).sum(2).sum(1))
        np.testing.assert_allclose(trick, exp[:, 0], rtol=1e-4)
