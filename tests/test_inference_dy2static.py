"""Inference export (StableHLO AOT) + dy2static tests (reference patterns:
save_inference_model round-trips; dygraph_to_static output-equality)."""
import os
import tempfile

import numpy as np

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.core.tensor import Tensor


def test_save_load_inference_model():
    from paddle_tpu.static.inference import (export_layer,
                                             load_predictor)
    paddle.seed(0)
    net = nn.Sequential(nn.Linear(4, 16), nn.ReLU(), nn.Linear(16, 3))
    x = paddle.randn([2, 4])
    ref = net(x).numpy()
    with tempfile.TemporaryDirectory() as tmp:
        prefix = os.path.join(tmp, 'model')
        export_layer(prefix, net, [x])
        assert os.path.exists(prefix + '.stablehlo')
        pred = load_predictor(prefix)
        out = pred.run(x)
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)


def test_to_static_matches_eager():
    paddle.seed(1)
    net = nn.Sequential(nn.Linear(8, 8), nn.Tanh(), nn.Linear(8, 2))
    x = paddle.randn([4, 8])
    eager_out = net(x).numpy()
    snet = paddle.jit.to_static(net)
    static_out = snet(x)
    np.testing.assert_allclose(static_out.numpy(), eager_out, rtol=1e-5,
                               atol=1e-6)


def test_to_static_function():
    @paddle.jit.to_static
    def f(a, b):
        return paddle.tanh(a @ b) * 2
    a = paddle.randn([3, 3])
    b = paddle.randn([3, 3])
    np.testing.assert_allclose(
        f(a, b).numpy(),
        np.tanh(a.numpy() @ b.numpy()) * 2, rtol=1e-5, atol=1e-6)


def test_localsgd_gradient_merge():
    from paddle_tpu.distributed.fleet.utils import LocalSGD, GradientMerge
    paddle.seed(0)
    net = nn.Linear(4, 2)
    base = paddle.optimizer.SGD(learning_rate=0.1,
                                parameters=net.parameters())
    opt = LocalSGD(base, k_steps=2)
    for _ in range(4):
        net(paddle.randn([4, 4])).sum().backward()
        opt.step()
        opt.clear_grad()

    net2 = nn.Linear(4, 2)
    w0 = net2.weight.numpy().copy()
    gm = GradientMerge(paddle.optimizer.SGD(learning_rate=0.1,
                                            parameters=net2.parameters()),
                       k_steps=2, avg=True)
    x = paddle.ones([2, 4])
    for i in range(2):
        net2(x).sum().backward()
        gm.step()
    # after k=2 steps exactly one update with averaged grad happened
    w1 = net2.weight.numpy()
    assert not np.allclose(w0, w1)
