"""Observability v2 tests: span tracer, scheduler state machine,
chrome-trace/JSON export (with and without the native recorder),
executor compile-cache counters, Prometheus exposition, and the
end-to-end acceptance run (training under Profiler produces nested
executor/compile/dataloader/collective spans + a metrics snapshot with
compile-cache hit/miss, step throughput and per-collective bytes)."""
import json
import os
import threading
import urllib.request

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.profiler as prof
import paddle_tpu.static as static
from paddle_tpu.core import monitor
from paddle_tpu.core.tensor import Tensor

S = prof.ProfilerState


@pytest.fixture
def python_recorder():
    """Force the pure-Python ring-buffer fallback (native lib off)."""
    prof.use_native_recorder(False)
    yield
    prof.use_native_recorder(True)


@pytest.fixture
def fresh_metrics():
    monitor.registry().reset()
    monitor.metrics().reset()
    yield


def _record_window(body):
    """Run `body` inside a one-window Profiler; return its result."""
    out = []
    p = prof.Profiler(scheduler=None, on_trace_ready=out.append)
    p.start()
    body()
    p.stop()
    assert len(out) == 1
    return out[0].profiler_result


# ---------------------------------------------------------------------------
# span tracer
# ---------------------------------------------------------------------------
class TestSpans:
    def test_nesting_and_args(self, python_recorder):
        def body():
            with prof.RecordEvent('outer', batch=3):
                with prof.RecordEvent('mid', event_type='op'):
                    with prof.RecordEvent('leaf'):
                        pass
        res = _record_window(body)
        by_name = {s['name']: s for s in res.spans}
        assert set(by_name) == {'outer', 'mid', 'leaf'}
        assert by_name['outer']['depth'] == 0
        assert by_name['mid']['parent'] == by_name['outer']['id']
        assert by_name['leaf']['parent'] == by_name['mid']['id']
        assert by_name['leaf']['depth'] == 2
        assert by_name['outer']['args'] == {'batch': 3}
        assert by_name['mid']['cat'] == 'op'
        # spans close inside-out: child intervals nest in the parent
        assert by_name['outer']['ts'] <= by_name['leaf']['ts']
        assert (by_name['leaf']['ts'] + by_name['leaf']['dur']
                <= by_name['outer']['ts'] + by_name['outer']['dur'])

    def test_thread_awareness(self, python_recorder):
        def body():
            def worker():
                with prof.RecordEvent('in_thread'):
                    pass
            t = threading.Thread(target=worker, name='feeder')
            with prof.RecordEvent('in_main'):
                t.start()
                t.join()
        res = _record_window(body)
        by_name = {s['name']: s for s in res.spans}
        assert by_name['in_thread']['tid'] != by_name['in_main']['tid']
        assert by_name['in_thread']['tname'] == 'feeder'
        # a thread's spans don't parent into another thread's stack
        assert by_name['in_thread']['parent'] == 0

    def test_no_recording_when_closed(self, python_recorder):
        with prof.RecordEvent('outside_any_window'):
            pass
        res = _record_window(lambda: None)
        assert all(s['name'] != 'outside_any_window' for s in res.spans)


# ---------------------------------------------------------------------------
# scheduler state machine
# ---------------------------------------------------------------------------
class TestScheduler:
    def test_full_cycle(self):
        sch = prof.make_scheduler(closed=1, ready=1, record=2, repeat=2,
                                  skip_first=1)
        got = [sch(i) for i in range(10)]
        assert got == [S.CLOSED, S.CLOSED, S.READY, S.RECORD,
                       S.RECORD_AND_RETURN, S.CLOSED, S.READY, S.RECORD,
                       S.RECORD_AND_RETURN, S.CLOSED]

    def test_torch_aliases_and_repeat_forever(self):
        sch = prof.make_scheduler(wait=1, warmup=0, active=1, repeat=0)
        assert [sch(i) for i in range(4)] == [
            S.CLOSED, S.RECORD_AND_RETURN, S.CLOSED, S.RECORD_AND_RETURN]

    def test_validation(self):
        with pytest.raises(ValueError):
            prof.make_scheduler(record=0)
        with pytest.raises(ValueError):
            prof.make_scheduler(closed=-1, record=1)

    def test_profiler_windows_and_handler(self, python_recorder):
        windows = []
        p = prof.Profiler(
            scheduler=prof.make_scheduler(closed=1, ready=0, record=2,
                                          repeat=2),
            on_trace_ready=lambda pr: windows.append(
                [s['name'] for s in pr.profiler_result.spans]))
        p.start()
        for i in range(8):
            with prof.RecordEvent(f'step{i}'):
                pass
            p.step()
        p.stop()
        assert len(windows) == 2
        assert windows[0] == ['step1', 'step2']
        assert windows[1] == ['step4', 'step5']

    def test_tuple_scheduler(self, python_recorder):
        windows = []
        p = prof.Profiler(scheduler=(2, 4),
                          on_trace_ready=lambda pr: windows.append(
                              len(pr.profiler_result.spans)))
        p.start()
        for i in range(6):
            with prof.RecordEvent('s'):
                pass
            p.step()
        p.stop()
        assert windows == [2]


# ---------------------------------------------------------------------------
# exporters
# ---------------------------------------------------------------------------
class TestExport:
    def _trace(self, tmp_path, fmt, fname):
        def body():
            with prof.RecordEvent('work', bytes=128):
                with prof.RecordEvent('sub'):
                    pass
        res = _record_window(body)
        path = str(tmp_path / fname)
        if fmt == 'chrome':
            res.export_chrome_tracing(path)
        else:
            res.export_json(path)
        with open(path) as f:
            return json.load(f)

    def test_chrome_trace_without_native(self, tmp_path, python_recorder):
        doc = self._trace(tmp_path, 'chrome', 't.trace.json')
        evs = [e for e in doc['traceEvents'] if e['ph'] == 'X']
        assert {e['name'] for e in evs} == {'work', 'sub'}
        work = next(e for e in evs if e['name'] == 'work')
        assert work['args']['bytes'] == 128
        metas = [e for e in doc['traceEvents'] if e['ph'] == 'M']
        assert any(m['name'] == 'process_name' for m in metas)
        assert doc['metadata']['schema'] == 'paddle_tpu.profiler/2'

    def test_json_export(self, tmp_path, python_recorder):
        doc = self._trace(tmp_path, 'json', 'raw.json')
        assert [s['name'] for s in doc['spans']] == ['sub', 'work']

    def test_chrome_trace_with_native_recorder(self, tmp_path):
        """Default path: the native lib (when present) keeps serving the
        legacy flat export; the v2 exporter is unaffected."""
        doc = self._trace(tmp_path, 'chrome', 'n.trace.json')
        assert {e['name'] for e in doc['traceEvents']
                if e['ph'] == 'X'} == {'work', 'sub'}

    def test_export_handler_writes_file(self, tmp_path, python_recorder):
        handler = prof.export_chrome_tracing_handler(str(tmp_path / 'd'))
        p = prof.Profiler(on_trace_ready=handler)
        p.start()
        with prof.RecordEvent('x'):
            pass
        p.stop()
        files = os.listdir(tmp_path / 'd')
        assert len(files) == 1 and files[0].endswith('.paddle_trace.json')

    def test_legacy_fallback_summary_and_export(self, tmp_path,
                                                python_recorder):
        """fluid-era API on the pure-Python recorder (.so absent)."""
        prof.reset_profiler()
        prof.start_profiler()
        try:
            with prof.RecordEvent('legacy_op'):
                pass
            with prof.RecordEvent('legacy_op'):
                pass
            s = prof.summary()
            assert 'legacy_op' in s and '\t2\t' in s
            path = str(tmp_path / 'legacy.json')
            prof.export_chrome_tracing(path)
            doc = json.load(open(path))
            evs = [e for e in doc['traceEvents'] if e['ph'] == 'X']
            assert len(evs) == 2
        finally:
            prof.stop_profiler(profile_path=None)


# ---------------------------------------------------------------------------
# executor compile cache + metrics registry
# ---------------------------------------------------------------------------
class TestExecutorCounters:
    def test_compile_cache_hit_miss(self, fresh_metrics):
        paddle.enable_static()
        try:
            main = static.Program()
            with static.program_guard(main):
                x = static.data('x', [2, 4])
                y = static.nn.fc(x, 3)
            exe = static.Executor()
            with static.scope_guard(static.Scope()):
                feed = {'x': np.ones((2, 4), 'float32')}
                exe.run(main, feed=feed, fetch_list=[y])
                exe.run(main, feed=feed, fetch_list=[y])
                exe.run(main, feed=feed, fetch_list=[y])
            stats = monitor.get_int_stats()
            assert stats['STAT_executor_cache_miss'] == 1
            assert stats['STAT_executor_cache_hit'] == 2
            assert stats['STAT_executor_runs'] == 3
            # the XLA compile was counted and timed
            reg = monitor.metrics()
            assert reg.get('ptpu_compiles_total').value(
                site='executor') >= 1
            assert reg.get('ptpu_compile_seconds_total').value(
                site='executor') > 0
        finally:
            paddle.disable_static()


class TestPrometheus:
    def test_exposition_format(self, fresh_metrics):
        c = monitor.counter('ptpu_collective_bytes_total',
                            help='bytes', labelnames=('op',))
        c.inc(1024, op='all_reduce')
        monitor.gauge('ptpu_examples_per_sec').set(10.5)
        h = monitor.histogram('ptpu_step_seconds', buckets=(0.1, 1.0))
        h.observe(0.05)
        h.observe(5.0)
        monitor.stat_add('STAT_executor_runs', 7)
        text = monitor.prometheus_text()
        assert '# TYPE ptpu_collective_bytes_total counter' in text
        assert 'ptpu_collective_bytes_total{op="all_reduce"} 1024' in text
        assert 'ptpu_examples_per_sec 10.5' in text
        assert 'ptpu_step_seconds_bucket{le="0.1"} 1' in text
        assert 'ptpu_step_seconds_bucket{le="+Inf"} 2' in text
        assert 'ptpu_step_seconds_count 2' in text
        assert 'STAT_executor_runs 7' in text

    def test_snapshot_and_http_endpoint(self, fresh_metrics):
        monitor.counter('ptpu_x_total').inc(3)
        snap = monitor.metrics_snapshot()
        assert snap['metrics']['ptpu_x_total']['series'][0]['value'] == 3
        srv = monitor.start_metrics_server(port=0)
        try:
            base = f'http://127.0.0.1:{srv.port}'
            text = urllib.request.urlopen(base + '/metrics').read().decode()
            assert 'ptpu_x_total 3' in text
            js = json.load(urllib.request.urlopen(base + '/metrics.json'))
            assert js['metrics']['ptpu_x_total']['series'][0]['value'] == 3
        finally:
            srv.close()

    def test_metric_type_conflicts(self, fresh_metrics):
        monitor.counter('ptpu_y_total')
        with pytest.raises(TypeError):
            monitor.gauge('ptpu_y_total')
        with pytest.raises(ValueError):
            monitor.counter('ptpu_y_total', labelnames=('op',))


# ---------------------------------------------------------------------------
# end-to-end acceptance: training under Profiler (pure-Python recorder)
# ---------------------------------------------------------------------------
class TestEndToEndTrace:
    def test_training_trace_and_metrics(self, tmp_path, python_recorder,
                                        fresh_metrics):
        import paddle_tpu.distributed as dist
        from paddle_tpu.io import DataLoader, Dataset

        rng = np.random.RandomState(0)
        xs = rng.rand(64, 4).astype('float32')
        ys = (xs @ np.array([[1.], [-2.], [3.], [.5]], 'float32')
              + 0.1).astype('float32')

        class _DS(Dataset):
            def __getitem__(self, i):
                return xs[i], ys[i]

            def __len__(self):
                return len(xs)

        paddle.enable_static()
        try:
            main = static.Program()
            with static.program_guard(main):
                x = static.data('x', [16, 4])
                label = static.data('label', [16, 1])
                pred = static.nn.fc(x, 1)
                loss = paddle.mean((pred - label) * (pred - label))
                opt = paddle.optimizer.SGD(learning_rate=0.1)
                opt.minimize(loss)
            exe = static.Executor()

            telem = prof.StepTelemetry(window=8)
            traces = []
            p = prof.Profiler(
                on_trace_ready=lambda pr: traces.append(
                    pr.profiler_result))
            loader = DataLoader(_DS(), batch_size=16, drop_last=True)
            losses = []
            with static.scope_guard(static.Scope()), p:
                for xb, yb in loader:
                    with telem.step(examples=16):
                        out = exe.run(main,
                                      feed={'x': xb.numpy(),
                                            'label': yb.numpy()},
                                      fetch_list=[loss])
                        # eager collective on the fetched loss
                        # (world_size 1: identity, still instrumented)
                        dist.all_reduce(Tensor(out[0]))
                        losses.append(float(out[0]))
                    p.step()
        finally:
            paddle.disable_static()

        assert losses[-1] < losses[0]          # it actually trained

        # -- trace assertions ------------------------------------------------
        res = traces[-1]
        path = res.export_chrome_tracing(str(tmp_path / 'e2e.trace.json'))
        doc = json.load(open(path))
        evs = [e for e in doc['traceEvents'] if e['ph'] == 'X']
        names = {e['name'] for e in evs}
        assert {'executor::build_program', 'executor::lower',
                'executor::compile', 'executor::run',
                'dataloader::next', 'dataloader::produce',
                'collective::all_reduce'} <= names
        # nesting: the XLA compile span sits under the program build
        spans = {s['id']: s for s in res.spans}
        xla = next(s for s in res.spans if s['name'] == 'executor::compile')
        assert spans[xla['parent']]['name'] == 'executor::build_program'
        produce = next(s for s in res.spans
                       if s['name'] == 'dataloader::produce')
        assert spans[produce['parent']]['name'] == 'dataloader::next'
        coll = next(s for s in res.spans
                    if s['name'] == 'collective::all_reduce')
        assert coll['args']['bytes'] == 4      # one f32 scalar

        # -- metrics snapshot ------------------------------------------------
        stats = monitor.get_int_stats()
        assert stats['STAT_executor_cache_miss'] == 1
        assert stats['STAT_executor_cache_hit'] == len(losses) - 1
        reg = monitor.metrics()
        assert reg.get('ptpu_collective_calls_total').value(
            op='all_reduce') == len(losses)
        assert reg.get('ptpu_collective_bytes_total').value(
            op='all_reduce') == 4 * len(losses)
        assert reg.get('ptpu_dataloader_batches_total').value() \
            == len(losses)

        snap = telem.snapshot()
        assert snap['steps'] == len(losses)
        assert snap['examples_per_sec'] > 0    # step throughput
        assert snap['compile_cache_misses'] == 1
        assert snap['compile_cache_hits'] == len(losses) - 1
        assert snap['compile_seconds_total'] > 0
        # gauges published for the /metrics endpoint
        assert reg.get('ptpu_examples_per_sec').value() > 0
        # and the whole registry renders
        text = monitor.prometheus_text()
        assert 'ptpu_collective_bytes_total{op="all_reduce"}' in text


class TestDeviceTrace:
    def test_device_trace_bracket_and_metadata(self, tmp_path,
                                               python_recorder):
        """targets=[TPU] brackets RECORD windows with the jax.profiler
        (xplane) and stamps the logdir into the export metadata."""
        import jax.numpy as jnp
        d = str(tmp_path / 'xla')
        results = []
        p = prof.Profiler(targets=[prof.ProfilerTarget.TPU],
                          device_trace_dir=d,
                          on_trace_ready=lambda pr: results.append(
                              pr.profiler_result))
        p.start()
        with prof.RecordEvent('devwork'):
            (jnp.ones((8, 8)) @ jnp.ones((8, 8))).block_until_ready()
        p.stop()
        res = results[0]
        if res.device_trace_dir is None:
            pytest.skip("device tracer unavailable in this environment")
        assert res.device_trace_dir == d
        path = res.export_chrome_tracing(str(tmp_path / 'dev.trace.json'))
        doc = json.load(open(path))
        assert doc['metadata']['device_trace_dir'] == d
        assert os.path.isdir(d)          # xplane dump landed
        assert any(e['name'] == 'devwork' for e in doc['traceEvents'])


class TestHapiTelemetryCallback:
    def test_fit_publishes_telemetry(self, fresh_metrics):
        from paddle_tpu import nn
        from paddle_tpu.hapi import Model, StepTelemetry
        from paddle_tpu.metric import Accuracy

        paddle.seed(0)
        net = nn.Sequential(nn.Flatten(), nn.Linear(16, 4))
        model = Model(net)
        model.prepare(
            optimizer=paddle.optimizer.Adam(learning_rate=1e-3,
                                            parameters=net.parameters()),
            loss=nn.CrossEntropyLoss(), metrics=Accuracy())
        xs = np.random.RandomState(0).rand(32, 16).astype('float32')
        ys = np.random.RandomState(1).randint(0, 4, (32, 1))
        from paddle_tpu.io import TensorDataset
        ds = TensorDataset([Tensor(xs), Tensor(ys.astype('int64'))])
        cb = StepTelemetry(window=8)
        model.fit(ds, epochs=1, batch_size=8, verbose=0, callbacks=[cb])
        snap = cb.snapshot()
        assert snap['steps'] == 4
        assert snap['examples_per_sec'] > 0
        assert monitor.metrics().get('ptpu_examples_per_sec') is not None
