"""Control-flow + distribution + hapi-jit regression tests."""
import numpy as np
import jax

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.core.tensor import Tensor


def test_while_cond_switch_case():
    i = paddle.to_tensor(0)
    s = paddle.to_tensor(0)
    out = paddle.static.while_loop(lambda i, s: i < 5,
                                   lambda i, s: [i + 1, s + i], [i, s])
    assert int(out[1]) == 10
    assert float(paddle.static.cond(paddle.to_tensor(True),
                                    lambda: paddle.to_tensor(1.0),
                                    lambda: paddle.to_tensor(2.0))) == 1.0
    # declared-index branches + default routing
    assert float(paddle.static.switch_case(
        paddle.to_tensor(2),
        {1: lambda: paddle.to_tensor(10.0),
         3: lambda: paddle.to_tensor(30.0)},
        default=lambda: paddle.to_tensor(-1.0))) == -1.0
    assert float(paddle.static.switch_case(
        paddle.to_tensor(1),
        [(1, lambda: paddle.to_tensor(100.0)),
         (2, lambda: paddle.to_tensor(200.0))])) == 100.0
    # case without default: last fn is fallback
    assert float(paddle.static.case(
        [(paddle.to_tensor(False), lambda: paddle.to_tensor(1.0)),
         (paddle.to_tensor(False), lambda: paddle.to_tensor(2.0))])) == 2.0


def test_distributions():
    paddle.seed(0)
    d = paddle.distribution.Normal(0.0, 2.0)
    s = d.sample([2000])
    assert abs(float(s.numpy().std()) - 2.0) < 0.15
    np.testing.assert_allclose(float(d.entropy()),
                               0.5 + 0.5 * np.log(2 * np.pi) + np.log(2.0),
                               rtol=1e-5)
    c = paddle.distribution.Categorical(
        paddle.to_tensor(np.zeros((4, 5), 'float32')))
    assert c.sample((10,)).shape == [10, 4]
    lp = c.log_prob(paddle.to_tensor(np.zeros((4,), 'int64')))
    np.testing.assert_allclose(lp.numpy(), np.log(0.2), rtol=1e-5)
    u = paddle.distribution.Uniform(0.0, 4.0)
    assert float(u.entropy()) == np.log(4.0).astype('float32')
    kl = paddle.distribution.kl_divergence(
        paddle.distribution.Normal(0.0, 1.0),
        paddle.distribution.Normal(0.0, 1.0))
    assert abs(float(kl)) < 1e-6


def test_hapi_jit_fit_eval():
    from paddle_tpu.hapi import Model
    from paddle_tpu.vision.datasets import MNIST
    paddle.seed(0)
    net = nn.Sequential(nn.Flatten(), nn.Linear(784, 32), nn.ReLU(),
                        nn.Linear(32, 10))
    m = Model(net)
    m.prepare(optimizer=paddle.optimizer.Adam(learning_rate=1e-3,
                                              parameters=net.parameters()),
              loss=nn.CrossEntropyLoss(), jit=True)
    m.fit(MNIST(mode='train'), epochs=1, batch_size=64, verbose=0,
          num_iters=8)
    res = m.evaluate(MNIST(mode='test'), batch_size=128, verbose=0)
    assert np.isfinite(res['loss'])


def test_flags_nan_check():
    paddle.set_flags({'FLAGS_check_nan_inf': True})
    try:
        import pytest
        with pytest.raises(FloatingPointError):
            paddle.log(paddle.to_tensor([-1.0]))
    finally:
        paddle.set_flags({'FLAGS_check_nan_inf': False})


def test_gpt_kv_cache_decode_matches_full():
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM
    paddle.seed(0)
    m = GPTForCausalLM(GPTConfig(vocab_size=64, hidden_size=32,
                                 num_layers=2, num_heads=4, max_seq_len=64,
                                 hidden_dropout=0.0, attn_dropout=0.0,
                                 use_flash_attention=False))
    m.eval()
    prompt = paddle.to_tensor(np.array([[5, 9, 2]], 'int32'))
    cached = m.generate(prompt, max_new_tokens=5, use_cache=True)
    full = m.generate(prompt, max_new_tokens=5, use_cache=False)
    assert cached.numpy().tolist() == full.numpy().tolist()


def test_gpt_generate_eos_early_exit_per_row():
    """generate(eos_token_id=...) must stop once EVERY row has emitted
    EOS at least once — not only when all rows emit it on the same step
    — while keeping the emitted tokens identical to the prefix of a
    run-to-max_new_tokens decode (ISSUE 5 satellite)."""
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM
    paddle.seed(0)
    m = GPTForCausalLM(GPTConfig(vocab_size=64, hidden_size=32,
                                 num_layers=2, num_heads=4, max_seq_len=64,
                                 hidden_dropout=0.0, attn_dropout=0.0,
                                 use_flash_attention=False))
    m.eval()
    EOS = 63
    # script the sampler: row 0 emits EOS at step 1, row 1 at step 2 —
    # never simultaneously, so the old `.all()`-on-one-step check would
    # run all 8 steps; the per-row check must stop after step 2
    script = [np.array([5, 7]), np.array([EOS, 9]), np.array([3, EOS]),
              np.array([1, 1]), np.array([1, 1]), np.array([1, 1]),
              np.array([1, 1]), np.array([1, 1])]
    calls = []

    def scripted_sample(step_logits, temperature, top_k):
        calls.append(1)
        return script[len(calls) - 1]

    m._sample_next = scripted_sample       # instance shadows staticmethod
    prompt = paddle.to_tensor(np.array([[5, 9, 2], [7, 1, 4]], 'int32'))
    out = m.generate(prompt, max_new_tokens=8, eos_token_id=EOS,
                     use_cache=True)
    # stopped after 3 sampled steps (row 1's EOS), tokens = the scripted
    # prefix — rows that finished early kept emitting until the break
    assert len(calls) == 3
    assert out.numpy()[:, 3:].tolist() == [[5, EOS, 3], [7, 9, EOS]]
    # uncached path: same early-exit contract
    calls.clear()
    out2 = m.generate(prompt, max_new_tokens=8, eos_token_id=EOS,
                      use_cache=False)
    assert len(calls) == 3
    assert out2.numpy().tolist() == out.numpy().tolist()
    del m._sample_next
    # no EOS in the stream -> still runs to max_new_tokens
    out3 = m.generate(prompt, max_new_tokens=4, eos_token_id=62,
                      use_cache=True)
    assert out3.shape[-1] == 3 + 4


def test_gpt_generate_scan_matches_greedy():
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM
    paddle.seed(0)
    m = GPTForCausalLM(GPTConfig(vocab_size=64, hidden_size=32,
                                 num_layers=2, num_heads=4, max_seq_len=64,
                                 hidden_dropout=0.0, attn_dropout=0.0,
                                 use_flash_attention=False))
    m.eval()
    p = paddle.to_tensor(np.array([[7, 1, 4]], 'int32'))
    scan_out = m.generate_scan(p, max_new_tokens=6)
    ref = m.generate(p, max_new_tokens=6, use_cache=False)
    assert scan_out.numpy().tolist() == ref.numpy().tolist()
    # cached fn reused on second call (no recompile)
    assert len(m._gen_cache) == 1
    m.generate_scan(p, max_new_tokens=6)
    assert len(m._gen_cache) == 1
    # overflow guard
    import pytest as _pt
    with _pt.raises(ValueError):
        m.generate_scan(p, max_new_tokens=100)
