"""Monitor stats registry (N5) + enforce machinery (N2).

Reference parity: platform/monitor.h StatRegistry / get_int_stats and
platform/enforce.h (+errors.h taxonomy)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core import monitor, enforce, flags


class TestMonitor:
    def test_registry_counts(self):
        monitor.registry().reset()
        monitor.stat_add('STAT_x', 3)
        monitor.stat_add('STAT_x', 2)
        monitor.stat_set('STAT_y', 7)
        snap = monitor.get_int_stats()
        assert snap['STAT_x'] == 5 and snap['STAT_y'] == 7

    def test_ps_and_executor_report(self):
        from paddle_tpu.distributed.ps.service import PsServer, PsClient
        monitor.registry().reset()
        srv = PsServer(port=0)
        srv.add_table(0, 4)
        srv.start()
        try:
            cl = PsClient([f'127.0.0.1:{srv.port}'])
            cl.pull(0, np.arange(6, dtype=np.int64), 4)
            cl.push(0, np.arange(6, dtype=np.int64),
                    np.ones((6, 4), np.float32), 0.1)
            cl.close()
        finally:
            srv.stop()
        stats = monitor.get_int_stats()
        assert stats['STAT_ps_client_pull_ids'] == 6
        assert stats['STAT_ps_client_push_ids'] == 6

        import paddle_tpu.static as static
        paddle.enable_static()
        try:
            main = static.Program()
            with static.program_guard(main):
                x = static.data('x', [2, 3])
                y = static.nn.fc(x, 2)
            exe = static.Executor()
            with static.scope_guard(static.Scope()):
                exe.run(main, feed={'x': np.ones((2, 3), 'float32')},
                        fetch_list=[y])
        finally:
            paddle.disable_static()
        assert monitor.get_int_stats()['STAT_executor_runs'] == 1


class TestEnforce:
    def test_taxonomy(self):
        with pytest.raises(enforce.InvalidArgumentError):
            enforce.enforce_eq(1, 2)
        with pytest.raises(enforce.NotFoundError):
            enforce.enforce_not_none(None)
        with pytest.raises(enforce.EnforceNotMet, match='boom'):
            enforce.enforce(False, 'boom')
        e = enforce.UnimplementedError('later')
        assert 'UnimplementedError' in str(e)

    def test_op_error_context_flag(self):
        from paddle_tpu.core.tensor import Tensor
        import jax.numpy as jnp
        a = Tensor(jnp.ones((2, 3)))
        b = Tensor(jnp.ones((4, 5)))
        # default: the original exception type surfaces
        with pytest.raises(Exception) as ei:
            paddle.matmul(a, b)
        assert not isinstance(ei.value, enforce.EnforceNotMet)
        # flag on: wrapped with [operator < name > error] context
        flags.set_flags({'FLAGS_op_error_context': True})
        try:
            with pytest.raises(enforce.EnforceNotMet,
                               match=r'operator < matmul'):
                paddle.matmul(a, b)
        finally:
            flags.set_flags({'FLAGS_op_error_context': False})
