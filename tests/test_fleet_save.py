"""fleet save/state_dict/shrink behavior (VERDICT r3 #5: the reference's
fleet_base.py:654-780 delegates saving to the runtime — PS table snapshot
or collective persistable save; these were empty stubs before)."""
import os
from types import SimpleNamespace

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import static
from paddle_tpu.distributed.fleet.base.fleet_base import Fleet


@pytest.fixture(autouse=True)
def _static_mode():
    paddle.enable_static()
    yield
    paddle.disable_static()


def _trained_program(steps=3):
    paddle.seed(2)
    main = static.Program()
    with static.program_guard(main):
        x = static.data('x', [8, 4])
        label = static.data('label', [8, 1])
        h = static.nn.fc(x, 8, activation='relu')
        pred = static.nn.fc(h, 1)
        loss = paddle.mean((pred - label) * (pred - label))
        paddle.optimizer.Adam(learning_rate=0.05).minimize(loss)
    rng = np.random.RandomState(0)
    xs = rng.rand(8, 4).astype('float32')
    ys = (xs @ rng.rand(4, 1).astype('float32')).astype('float32')
    exe = static.Executor()
    for _ in range(steps):
        exe.run(main, feed={'x': xs, 'label': ys}, fetch_list=[loss])
    return main, loss, (xs, ys)


def test_collective_save_load_roundtrip(tmp_path):
    f = Fleet()
    with static.scope_guard(static.Scope()):
        main, loss, _ = _trained_program()
        out = f.save_persistables(dirname=str(tmp_path), main_program=main)
        assert out['vars'] > 0 and out['tables'] == []
        want = {v.name: np.asarray(static.global_scope().find_var(v.name))
                for v in main.list_vars()
                if getattr(v, 'persistable', False) and v.name != '@LR'
                and static.global_scope().find_var(v.name) is not None}
    # fresh scope: load restores every value bit-exactly
    with static.scope_guard(static.Scope()):
        n = f.load_persistables(dirname=str(tmp_path))
        assert n == out['vars']
        for name, val in want.items():
            got = np.asarray(static.global_scope().find_var(name))
            np.testing.assert_array_equal(got, val)


def test_sharded_save_writes_owned_only_and_merges(tmp_path):
    f = Fleet()
    with static.scope_guard(static.Scope()):
        main, loss, _ = _trained_program()
        params = [p.name for p in main.all_parameters()]
        assert len(params) >= 4
        p2r = {n: i % 2 for i, n in enumerate(sorted(params))}
        main._sharding_param2rank = p2r
        full = {v.name: np.asarray(static.global_scope().find_var(v.name))
                for v in main.list_vars()
                if getattr(v, 'persistable', False) and v.name != '@LR'
                and static.global_scope().find_var(v.name) is not None}
        for r in range(2):
            main._sharding_rank = r
            out = f.save_persistables(dirname=str(tmp_path),
                                      main_program=main)
            assert out['vars'] < len(full)    # strictly a shard
    # each rank file holds only its owned params
    z0 = np.load(tmp_path / '__persistables__.rank0.npz')
    assert all(p2r.get(n, 0) == 0 for n in z0.files)
    # merged load restores everything
    with static.scope_guard(static.Scope()):
        n = f.load_persistables(dirname=str(tmp_path))
        assert n == len(full)
        for name, val in full.items():
            np.testing.assert_array_equal(
                np.asarray(static.global_scope().find_var(name)), val)


def test_state_dict_exposes_persistables():
    f = Fleet()
    with static.scope_guard(static.Scope()):
        main, loss, _ = _trained_program(steps=1)
        sd = f.state_dict(main_program=main)
        pnames = {p.name for p in main.all_parameters()}
        assert pnames <= set(sd)
        # optimizer state (adam moments) is persistable state too
        assert any('adam' in k for k in sd)


def test_fleet_save_writes_model_files(tmp_path):
    f = Fleet()
    with static.scope_guard(static.Scope()):
        main, loss, _ = _trained_program(steps=1)
        f.save(str(tmp_path), main_program=main)
    assert (tmp_path / 'model.pdmodel').exists()
    assert (tmp_path / 'model.pdiparams').exists()


def test_ps_snapshot_and_shrink(tmp_path):
    from paddle_tpu.distributed.ps.service import PsServer, PsClient
    from paddle_tpu.distributed.ps import ps_runtime
    from paddle_tpu.distributed.fleet.runtime import the_one_ps

    srv = PsServer().start()
    srv.add_table(0, dim=4, optimizer='sgd', seed=3)
    client = PsClient([f'127.0.0.1:{srv.port}'])
    try:
        ids = np.arange(20, dtype=np.int64)
        client.pull(0, ids, 4)                     # materialize rows
        assert client.table_size(0) == 20

        ps_runtime.set_table_configs([{'table_id': 0, 'embedx_dim': 4}])
        the_one_ps.runtime()._worker = SimpleNamespace(client=client)
        f = Fleet()
        with static.scope_guard(static.Scope()):
            main, loss, _ = _trained_program(steps=1)
            out = f.save_persistables(dirname=str(tmp_path),
                                      main_program=main)
        assert out['tables'] == [0]
        assert (tmp_path / 'sparse_table_0.part0').exists()

        # push rows toward zero, then shrink drops the small ones
        rows = client.pull(0, ids, 4)
        client.push(0, ids[:10], rows[:10] / 0.5, lr=0.5)  # rows[:10] -> 0
        dropped = f.shrink(threshold=1e-3)
        assert dropped == 10
        assert client.table_size(0) == 10
    finally:
        the_one_ps.runtime()._worker = None
        ps_runtime.set_table_configs(None)
        try:
            client.shutdown()
            client.close()
        except Exception:
            pass
