"""BeamSearchDecoder + dynamic_decode (fluid/layers/rnn.py parity)."""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.core.tensor import Tensor


class _ScriptedCell:
    """Deterministic 'cell': logits depend only on the input token —
    makes the best sequence analytically known."""

    def __init__(self, table):
        self.table = np.asarray(table, np.float32)  # [V, V] next-logits

    def __call__(self, inputs, states):
        import jax.numpy as jnp
        toks = np.asarray(inputs.data).astype(int).reshape(-1)
        return Tensor(jnp.asarray(self.table[toks])), states


def test_beam_search_finds_best_path():
    # vocab {0=start-ish, 1, 2, 3=end}; from any token, token 2 is much
    # likelier, and from 2 the end token dominates
    V = 4
    table = np.full((V, V), -5.0, np.float32)
    table[:, 2] = 2.0       # go to 2
    table[2, 3] = 6.0       # then end
    cell = _ScriptedCell(table)
    dec = nn.BeamSearchDecoder(cell, start_token=0, end_token=3,
                               beam_size=2)
    states = {'h': Tensor(np.zeros((3, 1), np.float32))}  # batch 3
    out, final = nn.dynamic_decode(dec, inits=states, max_step_num=8)
    ids = np.asarray(out['predicted_ids'].data)   # [B, T, W]
    assert ids.shape[0] == 3 and ids.shape[2] == 2
    best = ids[:, :, 0]
    # best hypothesis: 2 then 3(end) for every batch row
    assert (best[:, 0] == 2).all()
    assert (best[:, 1] == 3).all()
    lengths = np.asarray(final['lengths'])
    assert (lengths[:, 0] == 2).all()     # 2 real tokens incl. end


def test_beam_search_with_gru_cell_runs():
    paddle.seed(0)
    V, H, B, W = 12, 8, 2, 3
    emb = nn.Embedding(V, H)
    cell = nn.GRUCell(H, H)
    proj = nn.Linear(H, V)
    dec = nn.BeamSearchDecoder(
        cell, start_token=1, end_token=2, beam_size=W,
        embedding_fn=lambda ids: emb(ids),
        output_fn=lambda h: proj(h))
    h0 = Tensor(np.zeros((B, H), np.float32))
    out, final = nn.dynamic_decode(dec, inits=h0, max_step_num=5)
    ids = np.asarray(out['predicted_ids'].data)
    assert ids.shape[0] == B and ids.shape[2] == W
    sc = np.asarray(out['scores'].data)
    # scores are sorted within each beam expansion step
    assert (np.diff(sc[:, -1, :], axis=-1) <= 1e-5).all()
