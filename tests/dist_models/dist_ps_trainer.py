"""PS trainer process: 2 trainers share one server, training an
embedding-sum regression through PsClient pull/push (parity: the trainer
half of the dist fleet PS convergence tests). Prints its loss curve."""
import json
import os
import sys

import jax
jax.config.update('jax_platforms', 'cpu')

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

import numpy as np                                    # noqa: E402
from paddle_tpu.distributed.ps.service import PsClient  # noqa: E402


def main():
    rank = int(os.environ['PADDLE_TRAINER_ID'])
    endpoint = os.environ['PS_ENDPOINT']
    client = PsClient([endpoint])

    dim = 8
    rng = np.random.RandomState(100 + rank)
    # fixed ground truth shared by both trainers
    w_true = np.random.RandomState(0).rand(32, dim).astype('float32')

    losses = []
    for step in range(60):
        ids = rng.randint(0, 32, (16,)).astype(np.int64)
        rows = client.pull(0, ids, dim)            # [16, dim]
        target = w_true[ids]
        err = rows - target
        losses.append(float((err * err).mean()))
        client.push(0, ids, 2.0 * err / err.size * len(ids), lr=0.5)
    print("LOSSES:" + json.dumps(losses), flush=True)
    client.close()


if __name__ == '__main__':
    main()
