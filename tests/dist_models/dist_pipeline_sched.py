"""2-rank interleaved-vs-1F1B pipeline schedule equivalence (ISSUE 14).

Runs in a subprocess with XLA_FLAGS=--xla_force_host_platform_device_count=2
so the 'pp' mesh axis is exactly 2 ranks — the true-2-rank twin of the
8-device in-process tests in tests/test_pipeline_schedule.py:

  * schedule='interleaved' with virtual_stages=2 (each rank holds 2
    round-robin model chunks) must be BIT-IDENTICAL in fp32 — losses
    AND per-layer params — to the v=1 '1F1B' baseline in the default
    activation-stashing memory mode: the interleaved tick table
    reorders WHEN each (chunk, microbatch) job runs, never what it
    computes, and per-parameter gradient contributions accumulate in
    the same ascending-microbatch order;
  * the ptpu_pp_* schedule census must report the modeled bubble
    shrink: (pp-1)/(A*v+pp-1) < (pp-1)/(A+pp-1) at iso (pp, A).

Exits 0 on success; prints the failing comparison otherwise.
"""
import os
import sys

os.environ['JAX_PLATFORMS'] = 'cpu'
os.environ['XLA_FLAGS'] = (os.environ.get('XLA_FLAGS', '')
                           + ' --xla_force_host_platform_device_count=2')

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

import numpy as np                                         # noqa: E402
import jax                                                 # noqa: E402


def main():
    import paddle_tpu as paddle
    from paddle_tpu.core.tensor import Tensor
    from paddle_tpu.distributed import topology_runtime
    from paddle_tpu.models.gpt import GPTConfig, build_gpt_pipeline
    from paddle_tpu.distributed.fleet.meta_parallel.spmd_pipeline import (
        SpmdPipelineEngine, pipeline_snapshot, schedule_model)
    import paddle_tpu.distributed.fleet as fleet_mod
    fleet_mod.fleet._hcg = None

    assert len(jax.devices()) == 2, jax.devices()
    cfg = GPTConfig(vocab_size=64, hidden_size=16, num_layers=4,
                    num_heads=2, max_seq_len=32, hidden_dropout=0.0,
                    attn_dropout=0.0, use_flash_attention=False)
    A = 4
    ids = np.random.RandomState(7).randint(
        0, cfg.vocab_size, (A * 2, 32)).astype('int32')
    labels = np.roll(ids, -1, 1).astype('int32')

    def run(schedule, v=None):
        paddle.seed(11)
        topology_runtime.build_mesh(['pp'], [2])
        embed, blocks, head = build_gpt_pipeline(cfg)
        opt = paddle.optimizer.Adam(learning_rate=3e-3, parameters=[])
        eng = SpmdPipelineEngine(embed, blocks, head, opt,
                                 accumulate_steps=A, use_remat=False,
                                 schedule=schedule, virtual_stages=v)
        losses = [float(eng.train_batch((Tensor(ids), Tensor(labels))))
                  for _ in range(3)]
        eng.sync_model()
        params = {f'{i}/{n}': np.asarray(p.data)
                  for i, b in enumerate(blocks)
                  for n, p in b.named_parameters()}
        snap = pipeline_snapshot()
        eng.shutdown()
        return losses, params, snap

    l1, p1, _ = run('1F1B')
    l2, p2, snap2 = run('interleaved', v=2)
    assert l1 == l2, f'loss mismatch: {l1} vs {l2}'
    for k in p1:
        np.testing.assert_array_equal(
            p1[k], p2[k], err_msg=f'param {k} not bit-identical')

    assert snap2['schedule'] == 'interleaved' \
        and snap2['virtual_stages'] == 2, snap2
    m1 = schedule_model('1F1B', 2, A)
    assert snap2['bubble_fraction'] < m1['bubble_fraction'], \
        (snap2, m1)
    print('dist_pipeline_sched: 2-rank interleaved v2 == 1F1B '
          f'BIT-IDENTICAL, bubble {snap2["bubble_fraction"]:.3f} < '
          f'{m1["bubble_fraction"]:.3f}')


if __name__ == '__main__':
    main()
