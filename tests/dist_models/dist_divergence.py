"""2-process forced-desync scenario for the divergence sentinel.

Both ranks hold bit-identical params and check a per-step fingerprint
(grad global-norm + param checksum) through the DivergenceSentinel's
host-collective allgather. Before step 2 rank 1 perturbs one parameter
— the silent data-parallel drift the sentinel exists to catch. Both
ranks must detect the mismatch at step 2, name rank 1 as the offender
(consensus ties break toward rank 0), write a divergence_report
artifact, and journal the event in the flight recorder.
"""
import os
import sys

import jax
jax.config.update('jax_platforms', 'cpu')

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

import numpy as np                                         # noqa: E402
from paddle_tpu.distributed import host_collectives as HC  # noqa: E402
from paddle_tpu.distributed import flight_recorder as fr   # noqa: E402
from paddle_tpu.core import numerics as num                # noqa: E402


def main():
    rank = int(os.environ['PADDLE_TRAINER_ID'])
    dump_dir = os.environ['DIVERGENCE_DUMP_DIR']
    group = HC.init_host_collectives(timeout=60)
    assert group is not None

    sentinel = num.DivergenceSentinel(group=group, dump_dir=dump_dir)
    params = {'w': np.full((8,), 1.5, np.float32),
              'b': np.zeros((4,), np.float32)}
    for step in range(4):
        if step == 2 and rank == 1:
            params['w'] = params['w'] + 0.125      # the silent desync
        rep = sentinel.check(step, grad_norm=0.5, params=params)
        if step < 2:
            assert rep is None, f'false positive at step {step}: {rep}'
        elif rep is None:
            print(f'RANK{rank}: divergence NOT detected at step {step}',
                  flush=True)
            sys.exit(9)

    assert sentinel.first_divergent_step == 2, \
        sentinel.first_divergent_step
    rep = sentinel.report
    assert rep['offending_ranks'] == [1], rep
    assert rep['consensus_ranks'] == [0], rep
    assert sentinel.report_path and os.path.exists(sentinel.report_path)
    # the mismatch is journaled beside the allgathers that found it
    ops = [e['op'] for e in fr.recorder().entries()]
    assert 'divergence_detected' in ops, ops
    assert 'all_gather' in ops, ops
    print(f'RANK{rank}: OK first_divergent_step='
          f'{sentinel.first_divergent_step}', flush=True)
    sys.exit(0)


if __name__ == '__main__':
    main()
