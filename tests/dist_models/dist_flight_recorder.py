"""2-process forced-hang scenario for the flight recorder + watchdog.

Both ranks run 3 lockstep host-backend all_reduces (journaled under
group seq 0..2), then rank 0 enters a 4th all_reduce while rank 1 goes
silent — the classic "one rank never reaches the collective" hang. Each
rank's HangWatchdog must fire within its deadline, publish its journal
over the collective TCPStore, gather the peer's, and write a combined
cross-rank report naming rank 1 as the rank that never entered
all_reduce gseq=3. `abort=True` turns the wedge into exit code 3 so the
parent test (and fleetrun's watch loop in production) regains control.
"""
import os
import sys
import time

import jax
jax.config.update('jax_platforms', 'cpu')

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

import numpy as np                                        # noqa: E402
from paddle_tpu.distributed import host_collectives as HC  # noqa: E402
from paddle_tpu.distributed import flight_recorder as fr  # noqa: E402


def main():
    rank = int(os.environ['PADDLE_TRAINER_ID'])
    dump_dir = os.environ['FLIGHT_DUMP_DIR']
    group = HC.init_host_collectives(timeout=60)
    assert group is not None

    dog = fr.HangWatchdog(
        timeout=2.0, store=group.store, rank=rank, world_size=2,
        job_id='hangtest', dump_dir=dump_dir, gather_timeout=10.0,
        abort=True).start()

    x = np.ones(8, np.float32) * (rank + 1)
    for step in range(3):
        fr.heartbeat()
        out = group.all_reduce(x)
        assert float(out[0]) == 3.0, out
    print(f'RANK{rank}: 3 lockstep collectives done', flush=True)

    if rank == 0:
        group.all_reduce(x)          # blocks: rank 1 never arrives
        print('RANK0: unexpected all_reduce completion', flush=True)
        dog.stop()
        sys.exit(9)
    else:
        time.sleep(60)               # silent rank: stale heartbeat
        print('RANK1: unexpected wake', flush=True)
        dog.stop()
        sys.exit(9)


if __name__ == '__main__':
    main()
