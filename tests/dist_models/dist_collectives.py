"""Per-collective 2-process checks over the host backend (parity:
test_collective_base.py:32 — each collective verified against its
definition from both ranks)."""
import json
import os
import sys

import jax
jax.config.update('jax_platforms', 'cpu')

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

import numpy as np                                    # noqa: E402
import paddle_tpu as paddle                           # noqa: E402
import paddle_tpu.distributed as dist                 # noqa: E402


def main():
    rank = int(os.environ['PADDLE_TRAINER_ID'])
    ws = int(os.environ['PADDLE_TRAINERS_NUM'])
    dist.init_parallel_env()
    results = {}

    # all_reduce sum / max
    t = paddle.to_tensor(np.arange(4, dtype='float32') + rank * 10)
    dist.all_reduce(t)
    results['all_reduce_sum'] = np.asarray(t.data).tolist()

    t = paddle.to_tensor(np.arange(4, dtype='float32') + rank * 10)
    dist.all_reduce(t, op=dist.ReduceOp.MAX)
    results['all_reduce_max'] = np.asarray(t.data).tolist()

    # broadcast from rank 1
    t = paddle.to_tensor(np.full((3,), float(rank), 'float32'))
    dist.broadcast(t, src=1)
    results['broadcast'] = np.asarray(t.data).tolist()

    # all_gather
    outs = []
    t = paddle.to_tensor(np.asarray([float(rank), rank + 0.5], 'float32'))
    dist.all_gather(outs, t)
    results['all_gather'] = [np.asarray(o.data).tolist() for o in outs]

    # reduce_scatter: each rank contributes [ws*k] rows, gets its slice
    src = paddle.to_tensor(
        (np.arange(ws * 2, dtype='float32') + rank).reshape(ws, 2))
    out = paddle.to_tensor(np.zeros((2,), 'float32'))
    dist.reduce_scatter(out, src)
    results['reduce_scatter'] = np.asarray(out.data).reshape(-1).tolist()

    # scatter from rank 0
    if rank == 0:
        parts = [paddle.to_tensor(np.full((2,), float(i + 1), 'float32'))
                 for i in range(ws)]
    else:
        parts = None
    t = paddle.to_tensor(np.zeros((2,), 'float32'))
    dist.scatter(t, parts, src=0)
    results['scatter'] = np.asarray(t.data).tolist()

    dist.barrier()
    print("RESULTS:" + json.dumps(results))


if __name__ == '__main__':
    main()
