"""2-rank sharded-vs-replicated weight-update equivalence (ISSUE 4 +
ISSUE 10 overlap).

Runs in a subprocess with XLA_FLAGS=--xla_force_host_platform_device_count=2
so the dp mesh is exactly 2 ranks. Legs (``--leg base|overlap|all``):

``base`` — trains the same model over a dp=2 mesh:

  * legacy per-param psum path (`use_buckets=False`) — the reference;
  * bucketed reduce-scatter + sharded update + all-gather
    (`use_buckets=True`): must be BIT-IDENTICAL in fp32 — over 2 ranks
    every reduction is a single commutative addition, and the optimizer
    update is per-element, so flat-shard application can't drift;
  * bucketed with `comm_dtype='bfloat16'` (compressed wire, fp32
    accumulate): tolerance-level equivalence;
  * bucketed with `comm_dtype='int8'` (block-scaled int8 wire +
    scale-carrying param all-gather, fp32 accumulate): tolerance-level
    equivalence — the stated ISSUE-7 bar (docs/performance.md#int8-wire):
    losses within rtol 5e-2 / atol 5e-3 and params within rtol 5e-2 /
    atol 5e-2 of the fp32 reference after 4 Adam steps.

``overlap`` (ISSUE 10, docs/performance.md#comm-overlap):

  * `comm_overlap=True` (layer-grouped buckets + eager reduce-scatter +
    deferred/prefetched param all-gather) must be BIT-IDENTICAL in fp32
    to the barrier bucketed path — the gathers move, the arithmetic
    does not;
  * the chunked-collective variant (`comm_chunk`) is bit-identical too
    (pieces reduce the same elements across the same ranks);
  * bf16 / int8 wires under overlap: tolerance legs vs the fp32
    reference (same bars as the barrier wires);
  * peak-param-memory: the deferred-gather engine's resident param
    state (flat 1/dp shards) must occupy FEWER device bytes than the
    barrier engine's replicated params — measured with the
    core/memory census (`device_nbytes`: replication-aware, which is
    exactly what `.nbytes` hides);
  * `comm_snapshot()['comm_overlap']['hybrid']`: enabled, >1 group,
    exposed-comm < total-comm seconds.

Exits 0 on success; prints the failing comparison otherwise.
"""
import os
import sys

os.environ['JAX_PLATFORMS'] = 'cpu'
os.environ['XLA_FLAGS'] = (os.environ.get('XLA_FLAGS', '')
                           + ' --xla_force_host_platform_device_count=2')

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

import numpy as np                                         # noqa: E402
import jax                                                 # noqa: E402


def _setup():
    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.core.tensor import Tensor

    assert len(jax.devices()) == 2, jax.devices()

    def loss_fn(m, x, y):
        d = m(x) - y
        return (d * d).mean()

    rng = np.random.RandomState(0)
    X = Tensor(rng.rand(8, 16).astype('float32'))
    Y = Tensor(rng.rand(8, 1).astype('float32'))

    def run(use_buckets, comm_dtype=None, steps=4, **engine_kw):
        from paddle_tpu.core import memory as M
        from paddle_tpu.distributed import topology_runtime
        from paddle_tpu.distributed.fleet.meta_parallel.hybrid_engine \
            import HybridParallelTrainStep
        topology_runtime.build_mesh(['dp'], [2])
        paddle.seed(0)
        net = nn.Sequential(nn.Linear(16, 32), nn.Tanh(),
                            nn.Linear(32, 1))
        opt = paddle.optimizer.Adam(learning_rate=0.01,
                                    parameters=net.parameters())
        eng = HybridParallelTrainStep(net, loss_fn, opt,
                                      use_buckets=use_buckets,
                                      comm_dtype=comm_dtype,
                                      **engine_kw)
        assert eng._bucketed == bool(use_buckets), (
            use_buckets, eng._bucketed)
        losses = [float(eng(X, Y)) for _ in range(steps)]
        sd = eng.state_dict()
        # resident param-state census: replication-aware device bytes
        # of everything the engine keeps alive BETWEEN steps for params
        # (full replicas on the barrier path, flat 1/dp shards +
        # legacy on the deferred-gather path)
        pbytes = sum(M.device_nbytes(a) for a in eng._params.values())
        pbytes += sum(M.device_nbytes(a)
                      for a in getattr(eng, '_param_shards', None) or [])
        return losses, sd['params'], sd['states'], pbytes, eng

    return run, X, Y


def leg_base(run):
    ref_l, ref_p, ref_s, _, _ = run(False)
    got_l, got_p, got_s, _, _ = run(True)

    # fp32 sharded vs replicated: BIT-level
    assert got_l == ref_l, f'losses differ: {got_l} vs {ref_l}'
    for n in ref_p:
        if not np.array_equal(got_p[n], ref_p[n]):
            diff = np.abs(got_p[n].astype(np.float64)
                          - ref_p[n].astype(np.float64)).max()
            print(f'param {n} not bit-identical (max abs diff {diff})',
                  flush=True)
            sys.exit(3)
    for n in ref_s:
        for k in ('moment1', 'moment2'):
            if not np.array_equal(np.asarray(got_s[n][k]),
                                  np.asarray(ref_s[n][k])):
                print(f'state {n}.{k} not bit-identical', flush=True)
                sys.exit(4)

    # bf16 compressed wire: tolerance-level
    bf_l, bf_p, _, _, _ = run(True, comm_dtype='bfloat16')
    np.testing.assert_allclose(bf_l, ref_l, rtol=5e-2, atol=1e-3)
    for n in ref_p:
        np.testing.assert_allclose(bf_p[n], ref_p[n], rtol=5e-2,
                                   atol=2e-3, err_msg=n)

    # int8 block-scaled wire: tolerance-level (the forward consumes
    # the int8-rounded working copy from the scale-carrying all-gather,
    # so the bound is looser than bf16 — stated in docs/performance.md)
    i8_l, i8_p, i8_s, _, _ = run(True, comm_dtype='int8')
    np.testing.assert_allclose(i8_l, ref_l, rtol=5e-2, atol=5e-3)
    for n in ref_p:
        np.testing.assert_allclose(i8_p[n], ref_p[n], rtol=5e-2,
                                   atol=5e-2, err_msg=n)
    # int8 comm forces the sharded fp32 master even for fp32 buckets
    # (wire rounding must never feed back into the optimizer state)
    assert any('master' in st for st in i8_s.values()), \
        'int8 comm ran without a sharded fp32 master'

    # the comm gauges must show the compression: int8 payload is 4x
    # smaller than the fp32 per-param psum baseline, with the scale +
    # pad overhead reported separately (ISSUE-7 acceptance)
    from paddle_tpu.core import bucketing as B
    snap = B.comm_snapshot()
    factor = snap['comm_payload_factor_vs_per_param_psum']['hybrid']
    assert factor >= 4.0, f'payload factor {factor} < 4x'
    wb = snap['comm_wire_breakdown']['hybrid']
    assert wb['scale_bytes'] > 0 and wb['total_bytes'] > \
        wb['payload_bytes'], wb
    assert snap['comm_bytes_drop_enabled']['hybrid'] is True
    total_drop = snap['comm_bytes_drop_vs_per_param_psum']['hybrid']
    assert total_drop >= 0.70, total_drop

    print('OK: sharded==replicated (fp32 bit-level), '
          'bf16 comm within tolerance, int8 block-scaled comm within '
          f'tolerance (payload {factor:.2f}x below fp32 psum)',
          flush=True)


def leg_overlap(run):
    ref_l, ref_p, ref_s, _, _ = run(False)
    bar_l, bar_p, bar_s, bar_bytes, _ = run(True)
    ov_l, ov_p, ov_s, ov_bytes, ov_eng = run(True, comm_overlap=True,
                                             prefetch_depth=1)
    assert ov_eng._overlap, 'comm_overlap=True did not engage'
    assert len(ov_eng._layout.buckets) > 1, \
        'layer grouping produced a single bucket — nothing to overlap'

    # fp32 overlap == barrier == replicated: BIT-level (the deferred
    # gather only moves the all-gather; fp32 collectives are exact)
    assert ov_l == bar_l == ref_l, (ov_l, bar_l, ref_l)
    for n in ref_p:
        if not np.array_equal(ov_p[n], ref_p[n]):
            print(f'overlap param {n} not bit-identical', flush=True)
            sys.exit(5)
    for n in ref_s:
        for k in ('moment1', 'moment2'):
            if not np.array_equal(np.asarray(ov_s[n][k]),
                                  np.asarray(ref_s[n][k])):
                print(f'overlap state {n}.{k} not bit-identical',
                      flush=True)
                sys.exit(6)

    # chunked collectives: still bit-identical (same elements reduced
    # across the same ranks, pieces concatenate to the same layout)
    ch_l, ch_p, _, _, _ = run(True, comm_overlap=True, comm_chunk=64)
    assert ch_l == ref_l, (ch_l, ref_l)
    for n in ref_p:
        assert np.array_equal(ch_p[n], ref_p[n]), n

    # deferred-gather peak param memory: the overlap engine's resident
    # param state (1/dp shards) must be strictly smaller than the
    # barrier engine's replicated params (census-measured, ISSUE-10
    # acceptance)
    assert ov_bytes < bar_bytes, (ov_bytes, bar_bytes)
    ratio = ov_bytes / max(bar_bytes, 1)

    # compressed wires under overlap: same bars as the barrier wires
    bf_l, bf_p, _, _, _ = run(True, comm_dtype='bfloat16',
                              comm_overlap=True)
    np.testing.assert_allclose(bf_l, ref_l, rtol=5e-2, atol=1e-3)
    for n in ref_p:
        np.testing.assert_allclose(bf_p[n], ref_p[n], rtol=5e-2,
                                   atol=2e-3, err_msg=n)
    i8_l, i8_p, _, _, _ = run(True, comm_dtype='int8',
                              comm_overlap=True)
    np.testing.assert_allclose(i8_l, ref_l, rtol=5e-2, atol=5e-3)
    for n in ref_p:
        np.testing.assert_allclose(i8_p[n], ref_p[n], rtol=5e-2,
                                   atol=5e-2, err_msg=n)

    # overlap telemetry: enabled, exposed < total modeled comm seconds
    from paddle_tpu.core import bucketing as B
    co = B.comm_snapshot()['comm_overlap']['hybrid']
    assert co['enabled'] and co['groups'] > 1, co
    assert co['exposed_comm_seconds'] < co['total_comm_seconds'], co
    assert co['hidden_comm_seconds'] > 0, co

    print('OK: overlap==barrier (fp32 bit-level, chunked too), '
          'bf16/int8 overlap wires within tolerance, resident param '
          f'bytes {ov_bytes} < barrier {bar_bytes} '
          f'({ratio:.2f}x), exposed '
          f"{co['exposed_comm_seconds']:.2e}s < total "
          f"{co['total_comm_seconds']:.2e}s", flush=True)


def main():
    leg = 'all'
    if '--leg' in sys.argv:
        leg = sys.argv[sys.argv.index('--leg') + 1]
    if leg not in ('base', 'overlap', 'all'):
        # a typo must not become a zero-assertion silent pass
        print(f'unknown --leg {leg!r}: expected base|overlap|all',
              flush=True)
        sys.exit(2)
    run, _, _ = _setup()
    if leg in ('base', 'all'):
        leg_base(run)
    if leg in ('overlap', 'all'):
        leg_overlap(run)
    sys.exit(0)


if __name__ == '__main__':
    main()
