"""Elastic scale-down drill (parity: elastic.py watch-loop tests): both
ranks register heartbeats in the TCPStore; rank 1 exits mid-run; rank 0's
watch tick flips HOLD → RESTART (scale event) and it reports the
surviving membership."""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

from paddle_tpu.core.native import TCPStore             # noqa: E402
from paddle_tpu.distributed.fleet.elastic import (      # noqa: E402
    ElasticManager, ElasticStatus)


def main():
    rank = int(os.environ['PADDLE_TRAINER_ID'])
    master = os.environ['PADDLE_MASTER']
    host, port = master.rsplit(':', 1)
    hosts = ['127.0.0.1:7001', '127.0.0.1:7002']
    os.environ['PADDLE_CURRENT_ENDPOINT'] = hosts[rank]
    store = TCPStore(host, int(port), is_master=(rank == 0))
    mgr = ElasticManager(store=store, job_id='drill', np_min=1,
                         heartbeat_interval=0.2, dead_after=1.5)
    mgr.register()

    # both ranks wait until both heartbeats are visible
    deadline = time.time() + 20
    while time.time() < deadline:
        if len(mgr.hosts(hosts)) == 2:
            break
        time.sleep(0.1)
    assert mgr.watch(hosts) == ElasticStatus.HOLD

    if rank == 1:
        mgr.exit(completed=True)     # stop heartbeating and leave
        print("RANK1_EXIT", flush=True)
        return

    # rank 0: wait for the scale-down signal
    status = None
    deadline = time.time() + 20
    while time.time() < deadline:
        status = mgr.watch(hosts)
        if status == ElasticStatus.RESTART:
            break
        time.sleep(0.2)
    alive = mgr.hosts(hosts)
    print("ELASTIC:" + json.dumps({'status': status, 'alive': alive}),
          flush=True)
    mgr.exit()


if __name__ == '__main__':
    main()
