"""PS server process for the 2-trainer+1-server subprocess drill (parity:
the server half of test_dist_fleet_ps tests). Hosts one sparse embedding
table + one dense table; announces its port through stdout; serves until
killed."""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

from paddle_tpu.distributed.ps.service import PsServer   # noqa: E402


def main():
    srv = PsServer(port=int(os.environ.get('PS_PORT', '0')))
    srv.add_table(0, 8, optimizer='adagrad', seed=3)
    srv.add_dense_table(1, 4, optimizer='sgd')
    srv.start()
    print(f"PORT:{srv.port}", flush=True)
    while True:
        time.sleep(0.2)


if __name__ == '__main__':
    main()
