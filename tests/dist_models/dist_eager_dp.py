"""2-process eager DataParallel model script (parity: the dist_mnist.py
model files run by test_dist_base.py:744). Each rank trains on its shard;
grads sync through the host collective backend; losses print as JSON."""
import json
import os
import sys

import jax
jax.config.update('jax_platforms', 'cpu')

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

import numpy as np                                    # noqa: E402
import paddle_tpu as paddle                           # noqa: E402
from paddle_tpu import nn                             # noqa: E402
import paddle_tpu.distributed as dist                 # noqa: E402


def main():
    rank = int(os.environ['PADDLE_TRAINER_ID'])
    ws = int(os.environ['PADDLE_TRAINERS_NUM'])
    dist.init_parallel_env()

    paddle.seed(7)
    model = nn.Sequential(
        nn.Linear(4, 16), nn.ReLU(), nn.Linear(16, 1))
    # exercise the eager broadcast path: params synced from rank 0
    for p in model.parameters():
        dist.broadcast(p, src=0)
    dp = paddle.DataParallel(model)
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=model.parameters())

    rng = np.random.RandomState(0)
    xs = rng.rand(16, 4).astype('float32')
    ys = (xs @ rng.rand(4, 1).astype('float32') + 0.1).astype('float32')
    n = 16 // ws
    x_r = paddle.to_tensor(xs[rank * n:(rank + 1) * n])
    y_r = paddle.to_tensor(ys[rank * n:(rank + 1) * n])

    losses = []
    for _ in range(20):
        pred = dp(x_r)
        loss = ((pred - y_r) * (pred - y_r)).mean()
        loss.backward()
        dp.apply_collective_grads()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    print("LOSSES:" + json.dumps(losses))


if __name__ == '__main__':
    main()
