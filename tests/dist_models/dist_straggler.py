"""2-process slow-rank scenario for the straggler detector.

Both ranks drive a HostGapMonitor through the same number of simulated
dispatch intervals; rank 1's intervals carry an injected sleep ~3x the
base, the slow-chip / noisy-neighbor profile the detector exists to
catch. At the periodic check both ranks allgather their rolling mean
step wall; rank 1 lands past threshold x median, so BOTH ranks must
flag it, write a straggler_report artifact naming rank 1, and journal
the event in the flight recorder.
"""
import os
import sys
import time

import jax
jax.config.update('jax_platforms', 'cpu')

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

from paddle_tpu.distributed import host_collectives as HC  # noqa: E402
from paddle_tpu.distributed import flight_recorder as fr   # noqa: E402
from paddle_tpu.core import async_step as A_               # noqa: E402
from paddle_tpu.core import ledger as L                    # noqa: E402


def main():
    rank = int(os.environ['PADDLE_TRAINER_ID'])
    dump_dir = os.environ['STRAGGLER_DUMP_DIR']
    group = HC.init_host_collectives(timeout=60)
    assert group is not None

    det = L.StragglerDetector(engine='test', group=group,
                              threshold=1.25, check_every=4,
                              dump_dir=dump_dir)
    gap = A_.HostGapMonitor('test')
    base = 0.02
    sleep = base * (3.0 if rank == 1 else 1.0)   # the injected slowdown
    report = None
    for step in range(1, 9):
        gap.dispatch_begin()
        time.sleep(sleep)                        # the "step"
        gap.dispatch_end(depth=1)
        rep = det.maybe_check(step, gap)
        if rep is not None:
            report = rep
    assert det.checks >= 1, 'periodic check never ran'
    if report is None:
        print(f'RANK{rank}: straggler NOT detected', flush=True)
        sys.exit(9)
    assert report['offending_ranks'] == [1], report
    assert report['world_size'] == 2, report
    assert report['relative_wall']['1'] > 1.25, report
    assert det.report_path and os.path.exists(det.report_path)
    # the event is journaled beside the allgathers that found it
    ops = [e['op'] for e in fr.recorder().entries()]
    assert 'straggler_detected' in ops, ops
    assert 'all_gather' in ops, ops
    print(f'RANK{rank}: OK offending={report["offending_ranks"]}',
          flush=True)
    sys.exit(0)


if __name__ == '__main__':
    main()
