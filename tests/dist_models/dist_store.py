"""TCPStore cross-process KV drill (parity: test_gen_comm_id /
gloo-store tests): set/get/add/wait across 2 processes."""
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

from paddle_tpu.core.native import TCPStore             # noqa: E402


def main():
    rank = int(os.environ['PADDLE_TRAINER_ID'])
    master = os.environ['PADDLE_MASTER']
    host, port = master.rsplit(':', 1)
    store = TCPStore(host, int(port), is_master=(rank == 0))
    results = {}
    if rank == 0:
        store.set('k0', 'hello-from-0')
        v = store.get('k1')                  # blocks until rank1 sets
        results['peer_value'] = v.decode()
    else:
        v = store.get('k0')
        results['peer_value'] = v.decode()
        store.set('k1', 'hello-from-1')
    total = store.add('counter', rank + 1)   # 1 + 2 in some order
    results['add_seen'] = int(total)
    # rendezvous: both wait for both marks
    store.set(f'done{rank}', 'x')
    store.get(f'done{1 - rank}')
    results['final_counter'] = int(store.add('counter', 0))
    print("RESULTS:" + json.dumps(results), flush=True)


if __name__ == '__main__':
    main()
