"""Gauge-registry <-> docs consistency (ISSUE 16 satellite): the
metrics-reference appendix in docs/observability.md must list exactly
the set of `ptpu_*` names the code publishes — a metric added without
a docs row (or a docs row for a metric that no longer exists) fails
here, not in review.
"""
import os
import re
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
sys.path.insert(0, REPO)

DOCS = os.path.join(REPO, 'docs', 'observability.md')
PKG = os.path.join(REPO, 'paddle_tpu')

# quoted full metric names; a trailing underscore marks a PREFIX
# (startswith checks, reader-side f-string stems) — not a metric
_CODE_RE = re.compile(r"""['"](ptpu_[a-z0-9_]+)['"]""")
_DOCS_RE = re.compile(r'`(ptpu_[a-z0-9_]+)`')
_BEGIN = '<!-- metrics-reference:begin -->'
_END = '<!-- metrics-reference:end -->'

# names the docs mention as REMOVED — allowed in prose, banned from
# the reference table
_RETIRED = {'ptpu_serve_ttft_ms'}


def _code_names():
    names = set()
    for root, _dirs, files in os.walk(PKG):
        for fn in files:
            if not fn.endswith('.py'):
                continue
            with open(os.path.join(root, fn)) as f:
                for m in _CODE_RE.findall(f.read()):
                    if not m.endswith('_'):
                        names.add(m)
    return names


def _docs_sections():
    with open(DOCS) as f:
        text = f.read()
    assert _BEGIN in text and _END in text, \
        'metrics-reference markers missing from docs/observability.md'
    ref = text.split(_BEGIN, 1)[1].split(_END, 1)[0]
    return text, ref


class TestMetricsDocsConsistency:
    def test_reference_table_matches_code_exactly(self):
        code = _code_names()
        _, ref = _docs_sections()
        docs = set(_DOCS_RE.findall(ref))
        undocumented = code - docs
        stale = docs - code
        assert not undocumented, (
            'published but missing from the docs metrics reference: '
            f'{sorted(undocumented)}')
        assert not stale, (
            'in the docs metrics reference but published nowhere: '
            f'{sorted(stale)}')

    def test_reference_rows_are_table_entries(self):
        # every name sits in a `| \`name\` | module |` row — the
        # appendix stays machine-parseable, not prose
        _, ref = _docs_sections()
        row_names = set()
        for line in ref.splitlines():
            m = re.match(r'\|\s*`(ptpu_[a-z0-9_]+)`\s*\|', line)
            if m:
                row_names.add(m.group(1))
        assert row_names == set(_DOCS_RE.findall(ref))

    def test_prose_mentions_are_real_or_retired(self):
        # full literal names in the prose half must exist in code
        # (brace patterns like ptpu_comm_{a,b} don't match the regex
        # and carry their own meaning)
        text, ref = _docs_sections()
        prose = text.replace(ref, '')
        code = _code_names()
        ghosts = {n for n in _DOCS_RE.findall(prose)
                  if n not in code and n not in _RETIRED}
        assert not ghosts, (
            f'docs prose references unpublished metrics: {sorted(ghosts)}')

    def test_retired_names_not_resurrected(self):
        code = _code_names()
        _, ref = _docs_sections()
        docs = set(_DOCS_RE.findall(ref))
        for name in _RETIRED:
            assert name not in code, f'{name} was removed in ISSUE 7'
            assert name not in docs, \
                f'{name} is retired and must stay out of the reference'
