"""dy2static control-flow conversion tests.

Reference pattern: dygraph_to_static/test_*.py — run the same function
eagerly (Python control flow over concrete values) and through
@to_static (converted to lax.cond/while_loop under jit), assert equal
outputs. Parity: program_translator.py:232 + ifelse/loop/logical
transformers."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.core.tensor import Tensor


def _np(t):
    return np.asarray(t.data if isinstance(t, Tensor) else t)


class TestIfConversion:
    def test_data_dependent_if(self):
        def f(x):
            if (x > 0).all():
                y = x * 2
            else:
                y = x - 1
            return y

        st = paddle.jit.to_static(f)
        for v in ([1.0, 2.0], [-1.0, 2.0]):
            x = paddle.to_tensor(np.array(v, 'float32'))
            np.testing.assert_allclose(_np(st(x)), _np(f(x)))

    def test_if_defines_var_in_both_branches(self):
        def f(x):
            if x.sum() > 1:
                s = x.max()
            else:
                s = x.min()
            return s * 3

        st = paddle.jit.to_static(f)
        for v in ([2.0, 3.0], [-5.0, 0.1]):
            x = paddle.to_tensor(np.array(v, 'float32'))
            np.testing.assert_allclose(_np(st(x)), _np(f(x)), rtol=1e-6)

    def test_elif_chain(self):
        def f(x):
            s = x.sum()
            if s > 10:
                out = x * 10
            elif s > 0:
                out = x + 100
            else:
                out = -x
            return out

        st = paddle.jit.to_static(f)
        for v in ([20.0, 1.0], [0.5, 0.2], [-3.0, -1.0]):
            x = paddle.to_tensor(np.array(v, 'float32'))
            np.testing.assert_allclose(_np(st(x)), _np(f(x)))

    def test_python_condition_stays_python(self):
        def f(x, flag=True):
            if flag:                       # plain Python bool
                return x + 1
            return x - 1

        st = paddle.jit.to_static(f)
        x = paddle.to_tensor(np.ones(3, 'float32'))
        np.testing.assert_allclose(_np(st(x)), _np(f(x)))

    def test_logical_ops_on_tensors(self):
        def f(x):
            if (x.sum() > 0) and (x.max() < 10):
                return x * 2
            if (x.min() < -5) or ((x == 0).all()):
                return x - 7
            return x

        st = paddle.jit.to_static(f)
        for v in ([1.0, 2.0], [-9.0, 1.0], [0.0, 0.0], [11.0, 12.0]):
            x = paddle.to_tensor(np.array(v, 'float32'))
            np.testing.assert_allclose(_np(st(x)), _np(f(x)))


class TestLoopConversion:
    def test_tensor_while(self):
        def f(x):
            s = x.sum()
            n = paddle.to_tensor(np.float32(0.0))
            while s < 100:
                s = s * 2
                n = n + 1
            return s, n

        st = paddle.jit.to_static(f)
        x = paddle.to_tensor(np.array([1.5, 2.0], 'float32'))
        es, en = f(x)
        ss, sn = st(x)
        np.testing.assert_allclose(_np(ss), _np(es))
        np.testing.assert_allclose(_np(sn), _np(en))

    def test_for_over_tensor_range(self):
        def f(x, n):
            acc = paddle.zeros_like(x)
            for i in range(n):
                acc = acc + x * (i + 1)
            return acc

        st = paddle.jit.to_static(f)
        x = paddle.to_tensor(np.array([1.0, 2.0], 'float32'))
        n = paddle.to_tensor(np.int32(5))
        np.testing.assert_allclose(_np(st(x, n)), _np(f(x, 5)))

    def test_python_for_unrolls(self):
        def f(x):
            for i in range(3):        # static bound: unrolled or converted,
                x = x + i             # result must match either way
            return x

        st = paddle.jit.to_static(f)
        x = paddle.to_tensor(np.zeros(2, 'float32'))
        np.testing.assert_allclose(_np(st(x)), _np(f(x)))


class TestModelConversion:
    def test_layer_with_control_flow(self):
        """Reference pattern: dy2static test on a real Layer forward with
        data-dependent branching + loop."""
        class GatedNet(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc1 = nn.Linear(4, 8)
                self.fc2 = nn.Linear(8, 4)

            def forward(self, x):
                h = self.fc1(x)
                if h.mean() > 0:
                    h = paddle.nn.functional.relu(h)
                else:
                    h = h * 0.1
                steps = paddle.to_tensor(np.int32(0))
                s = h.sum()
                while s > 1:
                    s = s * 0.5
                    steps = steps + 1
                return self.fc2(h) * s, steps

        paddle.seed(0)
        net = GatedNet()
        x = paddle.to_tensor(
            np.random.RandomState(0).rand(2, 4).astype('float32'))
        eager_out, eager_steps = net(x)
        st_net = paddle.jit.to_static(GatedNet())   # fresh params
        paddle.seed(0)
        st_net2 = GatedNet()
        st_net2.set_state_dict(net.state_dict())
        st_fwd = paddle.jit.to_static(st_net2)
        out, steps = st_fwd(x)
        np.testing.assert_allclose(_np(out), _np(eager_out), rtol=1e-5,
                                   atol=1e-6)
        assert int(_np(steps)) == int(_np(eager_steps))

    def test_diverging_static_state_raises(self):
        def f(x):
            tag = 'none'
            if x.sum() > 0:
                tag = 'pos'       # python str diverges under traced cond
            else:
                tag = 'neg'
            return x, tag

        st = paddle.jit.to_static(f)
        with pytest.raises(Exception):
            st(paddle.to_tensor(np.ones(2, 'float32')))

    def test_closures_sharing_code_keep_own_cells(self):
        def make(k):
            def f(x):
                if (x > 0).all():
                    return x * k
                return x
            return f

        a = paddle.jit.to_static(make(2))
        b = paddle.jit.to_static(make(3))
        x = paddle.to_tensor(np.ones(2, 'float32'))
        np.testing.assert_allclose(_np(a(x)), [2.0, 2.0])
        np.testing.assert_allclose(_np(b(x)), [3.0, 3.0])

    def test_loop_var_reassignment_and_postvalue(self):
        def f(x, n):
            c = x * 0
            last = -1
            for i in range(n):
                c = c + 1
                i = i + 100          # must not corrupt iteration
                last = i
            return c, last

        st = paddle.jit.to_static(f)
        x = paddle.to_tensor(np.zeros(2, 'float32'))
        n = paddle.to_tensor(np.int32(3))
        c, last = st(x, n)
        np.testing.assert_allclose(_np(c), [3.0, 3.0])
        assert int(_np(last)) == 102   # python semantics: last i + 100

    def test_attribute_store_not_converted(self):
        """Object side effects in a branch bail out of conversion: Python
        conditions keep exact Python semantics."""
        class Box:
            val = 1.0

        def f(x, box, flag):
            if flag:                 # python bool: stays python
                box.val = 2.0
                y = x * 2
            else:
                box.val = 3.0
                y = x * 3
            return y * box.val

        st = paddle.jit.to_static(f)
        x = paddle.to_tensor(np.ones(2, 'float32'))
        b = Box()
        np.testing.assert_allclose(_np(st(x, b, True)), [4.0, 4.0])
        assert b.val == 2.0

    def test_kwargs_change_recompiles(self):
        def f(x, scale=1.0):
            return x * scale

        st = paddle.jit.to_static(f)
        x = paddle.to_tensor(np.ones(2, 'float32'))
        np.testing.assert_allclose(_np(st(x, scale=2.0)), [2.0, 2.0])
        np.testing.assert_allclose(_np(st(x, scale=5.0)), [5.0, 5.0])
        t = paddle.to_tensor(np.float32(7.0))
        np.testing.assert_allclose(_np(st(x, scale=t)), [7.0, 7.0])

    def test_enable_to_static_flag(self):
        calls = []

        def f(x):
            calls.append(1)
            if (x > 0).all():
                return x * 2
            return x

        st = paddle.jit.to_static(f)
        paddle.jit.enable_to_static(False)
        try:
            x = paddle.to_tensor(np.ones(2, 'float32'))
            out = st(x)     # runs the original eagerly
            np.testing.assert_allclose(_np(out), [2.0, 2.0])
        finally:
            paddle.jit.enable_to_static(True)
