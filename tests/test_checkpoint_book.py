"""Auto-checkpoint + book-style e2e tests (reference: fluid/tests/book/ —
word2vec, uci_housing regression; incubate/checkpoint tests)."""
import os
import tempfile

import numpy as np

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.io import DataLoader


def test_auto_checkpoint_resume():
    from paddle_tpu.incubate.checkpoint import TrainEpochRange
    with tempfile.TemporaryDirectory() as tmp:
        paddle.seed(0)
        net = nn.Linear(4, 2)
        opt = paddle.optimizer.Adam(parameters=net.parameters())
        r = TrainEpochRange(5, 'job_x', model=net, optimizer=opt,
                            checkpoint_dir=tmp)
        seen = []
        for epoch in r.get():
            loss = net(paddle.randn([8, 4])).sum()
            loss.backward()
            opt.step()
            opt.clear_grad()
            seen.append(epoch)
            if epoch == 2:
                break  # simulate crash after epoch-2 checkpoint... not saved
        # epochs 0,1 were checkpointed (save happens after yield completes);
        # the break skips epoch 2's save
        assert seen == [0, 1, 2]

        # "restart": fresh objects restore from the checkpoint
        paddle.seed(123)
        net2 = nn.Linear(4, 2)
        opt2 = paddle.optimizer.Adam(parameters=net2.parameters())
        r2 = TrainEpochRange(5, 'job_x', model=net2, optimizer=opt2,
                             checkpoint_dir=tmp)
        assert r2.restored_from == 1
        remaining = list(r2.get())
        assert remaining == [2, 3, 4]
        np.testing.assert_allclose(net2.weight.numpy().shape, (4, 2))


def test_engine_checkpoint_roundtrip():
    from paddle_tpu.distributed import topology_runtime
    from paddle_tpu.distributed.fleet.meta_parallel.hybrid_engine import (
        HybridParallelTrainStep)
    topology_runtime.build_mesh(['dp'], [8])
    paddle.seed(0)
    net = nn.Sequential(nn.Linear(4, 8), nn.Tanh(), nn.Linear(8, 1))
    opt = paddle.optimizer.Adam(learning_rate=0.05,
                                parameters=net.parameters())
    eng = HybridParallelTrainStep(
        net, lambda m, x, y: nn.functional.mse_loss(m(x), y), opt)
    X = Tensor(np.random.RandomState(0).randn(16, 4).astype('float32'))
    Y = Tensor(np.random.RandomState(1).randn(16, 1).astype('float32'))
    for _ in range(3):
        eng(X, Y)
    sd = eng.state_dict()
    l_after3 = float(eng(X, Y))

    # fresh engine restored to the 3-step state reproduces step 4's loss
    paddle.seed(7)
    net2 = nn.Sequential(nn.Linear(4, 8), nn.Tanh(), nn.Linear(8, 1))
    opt2 = paddle.optimizer.Adam(learning_rate=0.05,
                                 parameters=net2.parameters())
    eng2 = HybridParallelTrainStep(
        net2, lambda m, x, y: nn.functional.mse_loss(m(x), y), opt2)
    eng2.set_state_dict(sd)
    l2 = float(eng2(X, Y))
    np.testing.assert_allclose(l2, l_after3, rtol=1e-5)


def test_book_uci_housing():
    """fit_a_line (book) through dygraph + paddle.text dataset."""
    from paddle_tpu.text import UCIHousing
    paddle.seed(0)
    train = UCIHousing(mode='train')
    net = nn.Linear(13, 1)
    opt = paddle.optimizer.SGD(learning_rate=0.05,
                               parameters=net.parameters())
    loader = DataLoader(train, batch_size=64, shuffle=True)
    losses = []
    for epoch in range(4):
        for x, y in loader:
            loss = nn.functional.mse_loss(net(x), y)
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss))
    assert np.mean(losses[-5:]) < np.mean(losses[:5])


def test_book_word2vec():
    """word2vec (book): n-gram next-word prediction with Imikolov."""
    from paddle_tpu.text import Imikolov
    paddle.seed(0)
    ds = Imikolov(window_size=5, mode='train')
    vocab = 64

    class W2V(nn.Layer):
        def __init__(self):
            super().__init__()
            self.emb = nn.Embedding(vocab, 32)
            self.fc = nn.Linear(32 * 4, vocab)

        def forward(self, words):
            e = self.emb(words)  # B, 4, 32
            from paddle_tpu.ops import manip
            flat = manip.reshape(e, [e.shape[0], 128])
            return self.fc(flat)

    net = W2V()
    opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                parameters=net.parameters())
    loader = DataLoader(ds, batch_size=128, shuffle=True)
    losses = []
    for i, batch in enumerate(loader):
        if i >= 20:
            break
        *ctx, target = batch
        words = paddle.concat(list(ctx), axis=1)
        loss = nn.functional.cross_entropy(net(words),
                                           target.squeeze(-1))
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    assert losses[-1] < losses[0]


def test_book_imdb_lstm():
    """Sentiment LSTM over padded Imdb docs (book: understand_sentiment)."""
    from paddle_tpu.text import Imdb
    paddle.seed(0)
    ds = Imdb(mode='train')

    def collate(batch):
        docs, labels = zip(*batch)
        L = max(len(d) for d in docs)
        arr = np.zeros((len(docs), L), np.int64)
        for i, d in enumerate(docs):
            arr[i, :len(d)] = d
        return Tensor(arr), Tensor(np.asarray(labels, np.int64))

    class SentLSTM(nn.Layer):
        def __init__(self):
            super().__init__()
            self.emb = nn.Embedding(64, 32)
            self.lstm = nn.LSTM(32, 32)
            self.fc = nn.Linear(32, 2)

        def forward(self, x):
            e = self.emb(x)
            out, (h, c) = self.lstm(e)
            return self.fc(h[-1])

    net = SentLSTM()
    opt = paddle.optimizer.Adam(learning_rate=2e-3,
                                parameters=net.parameters())
    loader = DataLoader(ds, batch_size=32, shuffle=True,
                        collate_fn=collate)
    losses = []
    for i, (x, y) in enumerate(loader):
        if i >= 10:
            break
        loss = nn.functional.cross_entropy(net(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    assert np.isfinite(losses).all()
