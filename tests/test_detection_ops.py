"""Detection op tier vs independent numpy references.

Reference parity: the op_test.py pattern of fluid's detection op tests
(test_yolo_box_op.py, test_box_coder_op.py, test_prior_box_op.py,
test_bipartite_match_op.py, test_multiclass_nms_op.py,
test_generate_proposals_v2_op.py, test_iou_similarity_op.py,
test_deformable_conv_op.py) — each op checked against a from-scratch
numpy implementation of the documented semantics.
"""
import math

import numpy as np
import jax.numpy as jnp
import pytest

from paddle_tpu.core.tensor import Tensor
from paddle_tpu.vision import detection as D


def _t(a):
    return Tensor(jnp.asarray(a))


# ---- numpy oracles ---------------------------------------------------------

def np_iou(a, b, normalized=True):
    off = 0.0 if normalized else 1.0
    out = np.zeros((len(a), len(b)), 'float32')
    for i, p in enumerate(a):
        for j, q in enumerate(b):
            ix1, iy1 = max(p[0], q[0]), max(p[1], q[1])
            ix2, iy2 = min(p[2], q[2]), min(p[3], q[3])
            iw, ih = max(ix2 - ix1 + off, 0), max(iy2 - iy1 + off, 0)
            inter = iw * ih
            ua = ((p[2] - p[0] + off) * (p[3] - p[1] + off)
                  + (q[2] - q[0] + off) * (q[3] - q[1] + off) - inter)
            out[i, j] = inter / ua if ua > 0 else 0.0
    return out


def np_encode(target, prior, variance, normalized=True):
    off = 0.0 if normalized else 1.0
    M, N = len(target), len(prior)
    out = np.zeros((M, N, 4), 'float32')
    for j in range(N):
        pw = prior[j, 2] - prior[j, 0] + off
        ph = prior[j, 3] - prior[j, 1] + off
        pcx = prior[j, 0] + pw / 2
        pcy = prior[j, 1] + ph / 2
        for i in range(M):
            tw = target[i, 2] - target[i, 0] + off
            th = target[i, 3] - target[i, 1] + off
            tcx = (target[i, 0] + target[i, 2]) / 2
            tcy = (target[i, 1] + target[i, 3]) / 2
            e = [(tcx - pcx) / pw, (tcy - pcy) / ph,
                 math.log(abs(tw / pw)), math.log(abs(th / ph))]
            out[i, j] = [e[k] / variance[k] for k in range(4)]
    return out


def np_decode(deltas, prior, variance, normalized=True):
    off = 0.0 if normalized else 1.0
    M = deltas.shape[0]
    N = prior.shape[0]
    out = np.zeros((M, N, 4), 'float32')
    for j in range(N):
        pw = prior[j, 2] - prior[j, 0] + off
        ph = prior[j, 3] - prior[j, 1] + off
        pcx = prior[j, 0] + pw / 2
        pcy = prior[j, 1] + ph / 2
        for i in range(M):
            d = deltas[i, j]
            cx = variance[0] * d[0] * pw + pcx
            cy = variance[1] * d[1] * ph + pcy
            w = math.exp(variance[2] * d[2]) * pw
            h = math.exp(variance[3] * d[3]) * ph
            out[i, j] = [cx - w / 2, cy - h / 2,
                         cx + w / 2 - off, cy + h / 2 - off]
    return out


def np_greedy_nms(boxes, scores, thresh, score_thresh=None, normalized=True):
    order = np.argsort(-scores)
    keep = []
    suppressed = np.zeros(len(boxes), bool)
    for idx in order:
        if suppressed[idx]:
            continue
        if score_thresh is not None and scores[idx] <= score_thresh:
            continue
        keep.append(idx)
        ious = np_iou(boxes[idx:idx + 1], boxes, normalized)[0]
        suppressed |= ious > thresh
        suppressed[idx] = True
    return keep


# ---- tests -----------------------------------------------------------------

class TestIouBoxCoder:
    def test_iou_similarity(self):
        rng = np.random.RandomState(0)
        a = np.sort(rng.rand(5, 4).astype('float32'), -1)[:, [0, 1, 2, 3]]
        a = np.stack([a[:, 0], a[:, 1], a[:, 0] + a[:, 2] + 0.1,
                      a[:, 1] + a[:, 3] + 0.1], 1)
        b = np.stack([a[:, 0] + 0.05, a[:, 1] + 0.05, a[:, 2], a[:, 3]],
                     1)[:3]
        out = D.iou_similarity(_t(a), _t(b))
        np.testing.assert_allclose(np.asarray(out.data), np_iou(a, b),
                                   rtol=1e-5, atol=1e-6)

    @pytest.mark.parametrize('normalized', [True, False])
    def test_box_coder_encode(self, normalized):
        rng = np.random.RandomState(1)
        prior = np.abs(rng.rand(6, 4).astype('float32')) * 10
        prior[:, 2:] += prior[:, :2] + 1
        target = np.abs(rng.rand(4, 4).astype('float32')) * 10
        target[:, 2:] += target[:, :2] + 1
        var = [0.1, 0.1, 0.2, 0.2]
        out = D.box_coder(_t(prior), var, _t(target),
                          code_type='encode_center_size',
                          box_normalized=normalized)
        ref = np_encode(target, prior, var, normalized)
        np.testing.assert_allclose(np.asarray(out.data), ref, rtol=1e-4,
                                   atol=1e-5)

    def test_box_coder_decode_roundtrip(self):
        rng = np.random.RandomState(2)
        prior = np.abs(rng.rand(5, 4).astype('float32')) * 10
        prior[:, 2:] += prior[:, :2] + 1
        target = np.abs(rng.rand(5, 4).astype('float32')) * 10
        target[:, 2:] += target[:, :2] + 1
        var = [0.1, 0.1, 0.2, 0.2]
        enc = D.box_coder(_t(prior), var, _t(target),
                          code_type='encode_center_size')
        # decode with axis=0 expects deltas [M, N, 4]; take the diagonal
        # pairing (each target with its own prior)
        deltas = np.asarray(enc.data)
        dec = D.box_coder(_t(prior), var, _t(deltas),
                          code_type='decode_center_size', axis=0)
        rec = np.asarray(dec.data)[np.arange(5), np.arange(5)]
        np.testing.assert_allclose(rec, target, rtol=1e-3, atol=1e-3)
        ref = np_decode(deltas, prior, var)
        np.testing.assert_allclose(np.asarray(dec.data), ref, rtol=1e-4,
                                   atol=1e-4)


class TestPriorAnchor:
    def test_prior_box_values(self):
        x = np.zeros((1, 8, 4, 4), 'float32')
        img = np.zeros((1, 3, 32, 32), 'float32')
        boxes, var = D.prior_box(_t(x), _t(img), min_sizes=[4.0],
                                 max_sizes=[8.0], aspect_ratios=[2.0],
                                 flip=True, clip=True)
        b = np.asarray(boxes.data)
        # ladder: ar=1 (min), ar=2, ar=1/2, then max-size box
        assert b.shape == (4, 4, 4, 4)
        step = 32 / 4
        cx = (0 + 0.5) * step
        ms = 4.0
        exp0 = [(cx - ms / 2) / 32, (cx - ms / 2) / 32,
                (cx + ms / 2) / 32, (cx + ms / 2) / 32]
        np.testing.assert_allclose(b[0, 0, 0], exp0, rtol=1e-5)
        sq = math.sqrt(4.0 * 8.0)
        exp_max = [(cx - sq / 2) / 32, (cx - sq / 2) / 32,
                   (cx + sq / 2) / 32, (cx + sq / 2) / 32]
        np.testing.assert_allclose(b[0, 0, 3], exp_max, rtol=1e-5)
        w2 = ms * math.sqrt(2.0)
        np.testing.assert_allclose(
            b[0, 0, 1],
            [(cx - w2 / 2) / 32, (cx - ms / math.sqrt(2) / 2) / 32,
             (cx + w2 / 2) / 32, (cx + ms / math.sqrt(2) / 2) / 32],
            rtol=1e-5)
        v = np.asarray(var.data)
        assert v.shape == (4, 4, 4, 4)
        np.testing.assert_allclose(v[2, 3, 1], [0.1, 0.1, 0.2, 0.2])

    def test_anchor_generator_shapes(self):
        x = np.zeros((1, 8, 3, 5), 'float32')
        anchors, var = D.anchor_generator(
            _t(x), anchor_sizes=[32.0, 64.0], aspect_ratios=[0.5, 1.0],
            variances=[0.1, 0.1, 0.2, 0.2], stride=[16.0, 16.0])
        a = np.asarray(anchors.data)
        assert a.shape == (3, 5, 4, 4)
        # centers at i*stride + offset*(stride-1) — anchor_generator_op.h:68
        cx = (np.asarray(a[..., 0]) + np.asarray(a[..., 2])) / 2
        exp_cx = np.arange(5) * 16.0 + 0.5 * 15.0
        np.testing.assert_allclose(cx[0, :, 0], exp_cx, rtol=1e-5)
        # ar=0.5 → wide box: base_w=round(sqrt(256/0.5))=23, base_h=round(
        # 23*0.5)=12, scaled by 32/16 → w=46, h=24; corners span (size-1)
        w0 = a[0, 0, 0, 2] - a[0, 0, 0, 0]
        h0 = a[0, 0, 0, 3] - a[0, 0, 0, 1]
        np.testing.assert_allclose([w0, h0], [45.0, 23.0], rtol=1e-5)


class TestYoloBox:
    def test_vs_numpy(self):
        rng = np.random.RandomState(3)
        N, an, cls, H, W = 1, 2, 3, 2, 2
        anchors = [10, 14, 23, 27]
        x = rng.randn(N, an * (5 + cls), H, W).astype('float32')
        img = np.array([[64, 96]], 'int32')
        ds = 32
        boxes, scores = D.yolo_box(_t(x), _t(img), anchors, cls,
                                   conf_thresh=0.0, downsample_ratio=ds,
                                   clip_bbox=False)

        def sigmoid(v):
            return 1 / (1 + np.exp(-v))
        xr = x.reshape(N, an, 5 + cls, H, W)
        exp_boxes = np.zeros((N, an, H, W, 4))
        exp_scores = np.zeros((N, an, H, W, cls))
        for a in range(an):
            for j in range(H):
                for i in range(W):
                    t = xr[0, a, :, j, i]
                    cx = (i + sigmoid(t[0])) * 96 / W
                    cy = (j + sigmoid(t[1])) * 64 / H
                    bw = math.exp(t[2]) * anchors[2 * a] * 96 / (ds * W)
                    bh = math.exp(t[3]) * anchors[2 * a + 1] * 64 / (ds * H)
                    conf = sigmoid(t[4])
                    exp_boxes[0, a, j, i] = [cx - bw / 2, cy - bh / 2,
                                             cx + bw / 2, cy + bh / 2]
                    exp_scores[0, a, j, i] = conf * sigmoid(t[5:])
        np.testing.assert_allclose(
            np.asarray(boxes.data), exp_boxes.reshape(N, -1, 4), rtol=1e-4,
            atol=1e-4)
        np.testing.assert_allclose(
            np.asarray(scores.data), exp_scores.reshape(N, -1, cls),
            rtol=1e-4, atol=1e-5)

    def test_conf_thresh_zeroes(self):
        rng = np.random.RandomState(4)
        x = rng.randn(1, 2 * 7, 2, 2).astype('float32')
        img = np.array([[64, 64]], 'int32')
        boxes, scores = D.yolo_box(_t(x), _t(img), [10, 14, 23, 27], 2,
                                   conf_thresh=0.99)
        conf = 1 / (1 + np.exp(-x.reshape(1, 2, 7, 2, 2)[:, :, 4]))
        dead = (conf < 0.99).reshape(-1)
        b = np.asarray(boxes.data)[0]
        assert np.all(b[dead] == 0)


class TestBipartiteMatch:
    def test_greedy_global_max(self):
        dist = np.array([[0.9, 0.1, 0.3],
                         [0.8, 0.7, 0.2]], 'float32')
        idx, d = D.bipartite_match(_t(dist))
        # global max 0.9 → col0=row0; then 0.7 → col1=row1; col2 unmatched
        np.testing.assert_array_equal(np.asarray(idx.data), [0, 1, -1])
        np.testing.assert_allclose(np.asarray(d.data), [0.9, 0.7, 0.0])

    def test_per_prediction_fill(self):
        dist = np.array([[0.9, 0.1, 0.6],
                         [0.8, 0.7, 0.2]], 'float32')
        idx, d = D.bipartite_match(_t(dist), match_type='per_prediction',
                                   dist_threshold=0.5)
        # bipartite: col0=0 (0.9), col1=1 (0.7); col2 best row=0 at 0.6>=0.5
        np.testing.assert_array_equal(np.asarray(idx.data), [0, 1, 0])
        np.testing.assert_allclose(np.asarray(d.data), [0.9, 0.7, 0.6])

    def test_batched_matches_per_image_greedy(self):
        rng = np.random.RandomState(5)
        dist = rng.rand(3, 6, 4).astype('float32')
        idx, d = D.bipartite_match(_t(dist))
        for b in range(3):
            # numpy greedy oracle
            dd = dist[b].copy()
            midx = -np.ones(4, int)
            row_used = np.zeros(6, bool)
            for _ in range(4):
                masked = dd.copy()
                masked[row_used, :] = -1
                masked[:, midx >= 0] = -1
                r, c = np.unravel_index(np.argmax(masked), masked.shape)
                if masked[r, c] <= 1e-6:
                    break
                midx[c] = r
                row_used[r] = True
            np.testing.assert_array_equal(np.asarray(idx.data)[b], midx)


class TestNMS:
    def test_multiclass_nms_vs_numpy(self):
        rng = np.random.RandomState(6)
        N, M, C = 1, 12, 3
        boxes = np.zeros((N, M, 4), 'float32')
        for m in range(M):
            x1, y1 = rng.rand(2) * 0.5
            boxes[0, m] = [x1, y1, x1 + 0.3 + rng.rand() * 0.2,
                           y1 + 0.3 + rng.rand() * 0.2]
        scores = rng.rand(N, C, M).astype('float32')
        out, index, count = D.multiclass_nms(
            _t(boxes), _t(scores), score_threshold=0.3, nms_threshold=0.4,
            keep_top_k=10, background_label=0)
        # numpy oracle
        rows = []
        for c in range(1, C):
            keep = np_greedy_nms(boxes[0], scores[0, c], 0.4,
                                 score_thresh=0.3)
            for k in keep:
                rows.append((float(c), scores[0, c, k], k))
        rows.sort(key=lambda r: -r[1])
        rows = rows[:10]
        got = np.asarray(out.data)[0]
        cnt = int(np.asarray(count.data)[0])
        assert cnt == len(rows)
        for i, (label, score, k) in enumerate(rows):
            assert got[i, 0] == label
            np.testing.assert_allclose(got[i, 1], score, rtol=1e-5)
            np.testing.assert_allclose(got[i, 2:], boxes[0, k], rtol=1e-5)
            assert int(np.asarray(index.data)[0, i]) == k
        assert np.all(got[cnt:, 0] == -1)

    def test_matrix_nms_decay(self):
        # two heavily-overlapping boxes, one clear winner: loser's score
        # decays below the winner but stays positive (soft suppression)
        boxes = np.array([[[0.0, 0.0, 1.0, 1.0],
                           [0.05, 0.0, 1.05, 1.0],
                           [3.0, 3.0, 4.0, 4.0]]], 'float32')
        scores = np.array([[[0.9, 0.8, 0.6]]], 'float32')
        out, idx, cnt = D.matrix_nms(_t(boxes), _t(scores),
                                     score_threshold=0.1, keep_top_k=3,
                                     background_label=-1)
        got = np.asarray(out.data)[0]
        assert int(np.asarray(cnt.data)[0]) == 3
        assert got[0, 1] == pytest.approx(0.9)          # winner untouched
        assert got[1, 1] == pytest.approx(0.6)          # isolated box
        assert got[2, 1] < 0.5                           # decayed overlap


class TestGenerateProposals:
    def test_vs_numpy(self):
        rng = np.random.RandomState(7)
        N, A, H, W = 1, 3, 4, 4
        scores = rng.rand(N, A, H, W).astype('float32')
        deltas = (rng.randn(N, 4 * A, H, W) * 0.2).astype('float32')
        img = np.array([[64.0, 64.0]], 'float32')
        anchors = np.zeros((H, W, A, 4), 'float32')
        sizes = [8.0, 16.0, 24.0]
        for j in range(H):
            for i in range(W):
                for a in range(A):
                    cx, cy = (i + 0.5) * 16, (j + 0.5) * 16
                    s = sizes[a]
                    anchors[j, i, a] = [cx - s / 2, cy - s / 2,
                                        cx + s / 2, cy + s / 2]
        var = np.ones((H, W, A, 4), 'float32')
        rois, rscores, rnum = D.generate_proposals(
            _t(scores), _t(deltas), _t(img), _t(anchors), _t(var),
            pre_nms_top_n=20, post_nms_top_n=8, nms_thresh=0.6,
            min_size=2.0)
        # numpy oracle
        s_f = scores[0].transpose(1, 2, 0).reshape(-1)
        d_f = deltas[0].reshape(A, 4, H, W).transpose(2, 3, 0, 1) \
            .reshape(-1, 4)
        a_f = anchors.reshape(-1, 4)
        order = np.argsort(-s_f)[:20]
        dec = []
        for k in order:
            aw = a_f[k, 2] - a_f[k, 0] + 1
            ah = a_f[k, 3] - a_f[k, 1] + 1
            acx, acy = a_f[k, 0] + aw / 2, a_f[k, 1] + ah / 2
            clip = math.log(1000 / 16)
            cx = d_f[k, 0] * aw + acx
            cy = d_f[k, 1] * ah + acy
            w = math.exp(min(d_f[k, 2], clip)) * aw
            h = math.exp(min(d_f[k, 3], clip)) * ah
            box = [cx - w / 2, cy - h / 2, cx + w / 2 - 1, cy + h / 2 - 1]
            box = [min(max(box[0], 0), 63), min(max(box[1], 0), 63),
                   min(max(box[2], 0), 63), min(max(box[3], 0), 63)]
            dec.append(box)
        dec = np.array(dec, 'float32')
        sc = s_f[order]
        big = ((dec[:, 2] - dec[:, 0] + 1) >= 2.0) \
            & ((dec[:, 3] - dec[:, 1] + 1) >= 2.0)
        sc2 = np.where(big, sc, -np.inf)
        keep = np_greedy_nms(dec, sc2, 0.6, normalized=False)
        keep = [k for k in keep if big[k]][:8]
        got_rois = np.asarray(rois.data)[0]
        got_n = int(np.asarray(rnum.data)[0])
        assert got_n == len(keep)
        for i, k in enumerate(keep):
            np.testing.assert_allclose(got_rois[i], dec[k], rtol=1e-4,
                                       atol=1e-3)


class TestDeformConv:
    def test_zero_offset_equals_conv(self):
        import jax
        rng = np.random.RandomState(8)
        N, Cin, H, W = 2, 4, 6, 6
        Cout, kh, kw = 5, 3, 3
        x = rng.randn(N, Cin, H, W).astype('float32')
        wgt = rng.randn(Cout, Cin, kh, kw).astype('float32')
        offset = np.zeros((N, 2 * kh * kw, H, W), 'float32')
        out = D.deform_conv2d(_t(x), _t(offset), _t(wgt), padding=1)
        ref = jax.lax.conv_general_dilated(
            jnp.asarray(x), jnp.asarray(wgt), (1, 1), [(1, 1), (1, 1)],
            dimension_numbers=('NCHW', 'OIHW', 'NCHW'))
        np.testing.assert_allclose(np.asarray(out.data), np.asarray(ref),
                                   rtol=1e-4, atol=1e-4)

    def test_mask_and_offset_vs_numpy(self):
        rng = np.random.RandomState(9)
        N, Cin, H, W = 1, 2, 5, 5
        Cout, kh, kw = 3, 3, 3
        x = rng.randn(N, Cin, H, W).astype('float32')
        wgt = rng.randn(Cout, Cin, kh, kw).astype('float32')
        offset = (rng.randn(N, 2 * kh * kw, H, W) * 0.7).astype('float32')
        mask = rng.rand(N, kh * kw, H, W).astype('float32')
        out = D.deform_conv2d(_t(x), _t(offset), _t(wgt), padding=1,
                              mask=_t(mask))

        def bilinear(img, y, xx):
            if y <= -1 or y >= H or xx <= -1 or xx >= W:
                return 0.0
            y0, x0 = math.floor(y), math.floor(xx)
            wy, wx = y - y0, xx - x0
            val = 0.0
            for dy, dx, wt in [(0, 0, (1 - wy) * (1 - wx)),
                               (0, 1, (1 - wy) * wx),
                               (1, 0, wy * (1 - wx)), (1, 1, wy * wx)]:
                yy, xc = y0 + dy, x0 + dx
                if 0 <= yy < H and 0 <= xc < W:
                    val += wt * img[yy, xc]
            return val

        exp = np.zeros((N, Cout, H, W), 'float32')
        off_r = offset.reshape(N, kh * kw, 2, H, W)
        for oy in range(H):
            for ox in range(W):
                for co in range(Cout):
                    acc = 0.0
                    for ci in range(Cin):
                        for i in range(kh):
                            for j in range(kw):
                                kk = i * kw + j
                                py = oy - 1 + i + off_r[0, kk, 0, oy, ox]
                                px = ox - 1 + j + off_r[0, kk, 1, oy, ox]
                                v = bilinear(x[0, ci], py, px) \
                                    * mask[0, kk, oy, ox]
                                acc += v * wgt[co, ci, i, j]
                    exp[0, co, oy, ox] = acc
        np.testing.assert_allclose(np.asarray(out.data), exp, rtol=1e-3,
                                   atol=1e-3)

    def test_differentiable(self):
        import paddle_tpu as paddle
        rng = np.random.RandomState(10)
        x = _t(rng.randn(1, 2, 4, 4).astype('float32'))
        x.stop_gradient = False
        wgt = _t(rng.randn(2, 2, 3, 3).astype('float32'))
        wgt.stop_gradient = False
        offset = _t((rng.randn(1, 18, 4, 4) * 0.3).astype('float32'))
        offset.stop_gradient = False
        out = D.deform_conv2d(x, offset, wgt, padding=1)
        loss = paddle.sum(out * out)
        loss.backward()
        for t in (x, wgt, offset):
            assert t.grad is not None
            assert np.isfinite(np.asarray(t.grad.data)).all()


class TestFpnOps:
    def test_distribute_fpn_proposals(self):
        rois = np.array([[0, 0, 16, 16],        # small → low level
                         [0, 0, 112, 112],      # ~refer scale
                         [0, 0, 450, 450],      # big → high level
                         [0, 0, 60, 60]], 'float32')
        multi, counts, restore = D.distribute_fpn_proposals(
            _t(rois), min_level=2, max_level=5, refer_level=4,
            refer_scale=224)
        m = np.asarray(multi.data)
        c = np.asarray(counts.data)
        r = np.asarray(restore.data)
        # numpy oracle of the level rule
        exp_lvl = []
        for b in rois:
            s = np.sqrt((b[2] - b[0]) * (b[3] - b[1]))
            exp_lvl.append(int(np.clip(np.floor(4 + np.log2(s / 224
                                                            + 1e-12)),
                                       2, 5)) - 2)
        for li in range(4):
            assert c[li] == exp_lvl.count(li)
        # each roi appears in its level at the position restore encodes
        flat = []
        for li in range(4):
            flat.extend(m[li][:c[li]].tolist())
        flat = np.asarray(flat)
        for i, b in enumerate(rois):
            np.testing.assert_allclose(flat[r[i]], b)

    def test_collect_fpn_proposals(self):
        multi_rois = np.zeros((2, 3, 4), 'float32')
        multi_scores = np.full((2, 3), -np.inf, 'float32')
        multi_rois[0, 0] = [1, 1, 2, 2]
        multi_scores[0, 0] = 0.9
        multi_rois[1, 0] = [3, 3, 4, 4]
        multi_scores[1, 0] = 0.7
        multi_rois[1, 1] = [5, 5, 6, 6]
        multi_scores[1, 1] = 0.95
        rois, scores, cnt = D.collect_fpn_proposals(
            _t(multi_rois), _t(multi_scores), post_nms_top_n=2)
        assert int(np.asarray(cnt.data)) == 2
        np.testing.assert_allclose(np.asarray(scores.data), [0.95, 0.9])
        np.testing.assert_allclose(np.asarray(rois.data)[0], [5, 5, 6, 6])

    def test_psroi_pool_position_sensitivity(self):
        # channel value = its index; a 2x2 psroi over a full-image roi
        # must read channel c*4+i*2+j in bin (i, j)
        oc, ph, pw = 3, 2, 2
        x = np.zeros((1, oc * ph * pw, 4, 4), 'float32')
        for ch in range(oc * ph * pw):
            x[0, ch] = ch
        boxes = np.array([[0, 0, 4, 4]], 'float32')
        out = D.psroi_pool(_t(x), _t(boxes), oc, 1.0, ph, pw)
        o = np.asarray(out.data)
        assert o.shape == (1, oc, ph, pw)
        for c in range(oc):
            for i in range(ph):
                for j in range(pw):
                    np.testing.assert_allclose(o[0, c, i, j],
                                               c * 4 + i * 2 + j)

    def test_density_prior_box_shapes_and_centers(self):
        x = np.zeros((1, 8, 4, 4), 'float32')
        img = np.zeros((1, 3, 32, 32), 'float32')
        boxes, var = D.density_prior_box(
            _t(x), _t(img), densities=[2], fixed_sizes=[8.0],
            fixed_ratios=[1.0], clip=True)
        b = np.asarray(boxes.data)
        assert b.shape == (4, 4, 4, 4)      # 2x2 density grid per cell
        # the 2x2 sub-centers straddle the cell center symmetrically
        cx = (b[1, 1, :, 0] + b[1, 1, :, 2]) / 2 * 32
        assert cx.min() < 12.0 < cx.max()


class TestDetectionMAP:
    def test_perfect_predictions(self):
        from paddle_tpu.vision.detection import DetectionMAP
        m = DetectionMAP(class_num=2)
        gt = np.array([[0, 0, 10, 10], [20, 20, 30, 30]], 'float32')
        m.update(gt, np.array([0.9, 0.8]), np.array([0, 1]),
                 gt, np.array([0, 1]))
        assert abs(m.accumulate() - 1.0) < 1e-6

    def test_false_positive_lowers_map(self):
        from paddle_tpu.vision.detection import DetectionMAP
        m = DetectionMAP(class_num=1)
        gt = np.array([[0, 0, 10, 10]], 'float32')
        preds = np.array([[50, 50, 60, 60], [0, 0, 10, 10]], 'float32')
        m.update(preds, np.array([0.9, 0.8]), np.array([0, 0]),
                 gt, np.array([0]))
        # the high-score FP precedes the TP: AP = integral with
        # precision 0.5 at recall 1
        assert abs(m.accumulate() - 0.5) < 1e-6

    def test_difficult_excluded(self):
        from paddle_tpu.vision.detection import DetectionMAP
        m = DetectionMAP(class_num=1)
        gt = np.array([[0, 0, 10, 10], [20, 20, 30, 30]], 'float32')
        m.update(np.array([[0, 0, 10, 10]], 'float32'),
                 np.array([0.9]), np.array([0]),
                 gt, np.array([0, 0]), difficult=np.array([0, 1]))
        assert abs(m.accumulate() - 1.0) < 1e-6   # difficult gt ignored

    def test_11point(self):
        from paddle_tpu.vision.detection import DetectionMAP
        m = DetectionMAP(class_num=1, ap_version='11point')
        gt = np.array([[0, 0, 10, 10]], 'float32')
        m.update(gt, np.array([0.9]), np.array([0]), gt, np.array([0]))
        assert abs(m.accumulate() - 1.0) < 1e-6


def test_sampled_softmax_xent_bounds_full_softmax():
    from paddle_tpu.ops import contrib as C
    from paddle_tpu.core.tensor import Tensor
    import jax.numpy as jnp
    rng = np.random.RandomState(0)
    N, D, Cn = 8, 16, 100
    x = rng.randn(N, D).astype('float32') * 0.3
    w = rng.randn(Cn, D).astype('float32') * 0.3
    b = rng.randn(Cn).astype('float32') * 0.1
    y = rng.randint(0, Cn, (N, 1)).astype('int64')
    loss = C.sampled_softmax_with_cross_entropy(
        input=Tensor(jnp.asarray(x)), label=Tensor(jnp.asarray(y)),
        weight=Tensor(jnp.asarray(w)), bias=Tensor(jnp.asarray(b)),
        num_samples=Cn, seed=1)   # unique sampler covers every class
    got = np.asarray(loss.data).reshape(-1)
    # with ALL classes sampled (uniq, S=C) the loss EQUALS full softmax
    # (the accidental hit of the true class is masked; the true logit
    # itself occupies column 0)
    z = x @ w.T + b
    full = (np.log(np.exp(z).sum(1)) - z[np.arange(N), y.reshape(-1)])
    np.testing.assert_allclose(got, full, rtol=1e-4, atol=1e-5)
    # a strict subset only ever lowers the bound
    loss_sub = C.sampled_softmax_with_cross_entropy(
        input=Tensor(jnp.asarray(x)), label=Tensor(jnp.asarray(y)),
        weight=Tensor(jnp.asarray(w)), bias=Tensor(jnp.asarray(b)),
        num_samples=30, seed=2)
    got_sub = np.asarray(loss_sub.data).reshape(-1)
    assert (got_sub <= full + 1e-5).all()
    assert (got_sub >= 0).all()
