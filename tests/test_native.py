"""C++ native runtime tests (csrc/): data feed, TCP store, sparse table,
profiler. Reference parity: C++ gtest tier (framework/data_feed_test,
gen_comm_id, table tests) driven through the ctypes surface."""
import os
import tempfile
import threading

import numpy as np
import pytest

from paddle_tpu.core.native import (load_native, NativeDataFeed, TCPStore,
                                    NativeSparseTable)

pytestmark = pytest.mark.skipif(load_native() is None,
                                reason="native lib unavailable")


class TestDataFeed:
    def _write_files(self, tmp, n_files=3, rows=50):
        files = []
        rng = np.random.RandomState(0)
        expect = []
        for fi in range(n_files):
            path = os.path.join(tmp, f"part-{fi}")
            with open(path, 'w') as f:
                for r in range(rows):
                    feats = rng.rand(4)
                    label = rng.randint(0, 2)
                    f.write(' '.join(f"{v:.6f}" for v in feats) +
                            f" | {label}\n")
                    expect.append((feats, label))
            files.append(path)
        return files, expect

    def test_streaming_batches(self):
        with tempfile.TemporaryDirectory() as tmp:
            files, expect = self._write_files(tmp)
            feed = NativeDataFeed([(4, 'float'), (1, 'int64')],
                                  batch_size=32, num_threads=2)
            feed.set_filelist(files)
            feed.start()
            total = 0
            for f, i in feed:
                assert f.shape[1] == 4 and i.shape[1] == 1
                assert np.all((i >= 0) & (i <= 1))
                total += len(f)
            assert total == 150

    def test_in_memory_shuffle_epochs(self):
        with tempfile.TemporaryDirectory() as tmp:
            files, _ = self._write_files(tmp, n_files=2, rows=40)
            feed = NativeDataFeed([(4, 'float'), (1, 'int64')],
                                  batch_size=16)
            feed.set_filelist(files)
            feed.load_into_memory(seed=7)
            assert feed.memory_size() == 80
            e1 = np.concatenate([f for f, _ in feed.iter_memory()])
            feed.rewind(reshuffle=False)
            e2 = np.concatenate([f for f, _ in feed.iter_memory()])
            np.testing.assert_allclose(e1, e2)
            feed.rewind(reshuffle=True, seed=99)
            e3 = np.concatenate([f for f, _ in feed.iter_memory()])
            assert not np.allclose(e1, e3)
            assert np.allclose(np.sort(e1.ravel()), np.sort(e3.ravel()))


class TestTCPStore:
    def test_set_get_add(self):
        master = TCPStore(is_master=True)
        client = TCPStore(port=master.port)
        client.set('nccl_id_equiv', b'\x01\x02\x03coordinator:1234')
        assert master.get('nccl_id_equiv') == b'\x01\x02\x03coordinator:1234'
        assert client.get('missing', wait=False) is None
        assert client.add('counter', 5) == 5
        assert master.add('counter', 2) == 7
        client.close()
        master.close()

    def test_wait_blocks_until_set(self):
        master = TCPStore(is_master=True)
        client = TCPStore(port=master.port)
        result = {}

        def waiter():
            result['v'] = client.get('late_key', wait=True)

        t = threading.Thread(target=waiter)
        t.start()
        import time
        time.sleep(0.2)
        assert 'v' not in result
        master.set('late_key', b'hello')
        t.join(timeout=5)
        assert result.get('v') == b'hello'
        client.close()
        master.close()

    def test_barrier_releases_together(self):
        """2-party barrier (parity: gloo barrier / role_maker rendezvous)."""
        master = TCPStore(is_master=True)
        c2 = TCPStore(port=master.port)
        order = []

        def party(store, name):
            store.barrier('b1', 2)
            order.append(name)

        t1 = threading.Thread(target=party, args=(master, 'a'))
        t2 = threading.Thread(target=party, args=(c2, 'b'))
        t1.start()
        import time
        time.sleep(0.2)
        assert not order  # first party still blocked
        t2.start()
        t1.join(5)
        t2.join(5)
        assert sorted(order) == ['a', 'b']
        c2.close()
        master.close()


class TestSparseTable:
    def test_pull_push_adagrad(self):
        table = NativeSparseTable(dim=8, optimizer='adagrad', seed=42)
        ids = np.array([1, 5, 9, 5])
        rows = table.pull(ids)
        assert rows.shape == (4, 8)
        np.testing.assert_allclose(rows[1], rows[3])  # same id, same row
        assert len(table) == 3
        # deterministic on-miss init by (seed, id)
        table2 = NativeSparseTable(dim=8, optimizer='adagrad', seed=42)
        np.testing.assert_allclose(table2.pull(np.array([1]))[0], rows[0])

        grads = np.ones((4, 8), np.float32)
        table.push(ids, grads, lr=0.1)
        after = table.pull(ids)
        assert np.all(after < rows)  # positive grads decrease weights

    def test_save_load_shrink(self):
        with tempfile.TemporaryDirectory() as tmp:
            t = NativeSparseTable(dim=4, optimizer='sgd')
            ids = np.arange(100)
            rows = t.pull(ids)
            path = os.path.join(tmp, 'table.bin')
            t.save(path)
            t2 = NativeSparseTable(dim=4, optimizer='sgd')
            t2.load(path)
            assert len(t2) == 100
            np.testing.assert_allclose(t2.pull(ids), rows)
            dropped = t2.shrink(threshold=1e9)
            assert dropped == 100 and len(t2) == 0

    def test_scale_1m_ids(self):
        """Throughput sanity on 1M-row pulls (trillion-scale is sharded
        across hosts; per-host throughput is what matters here)."""
        import time
        t = NativeSparseTable(dim=16, optimizer='adagrad')
        ids = np.random.RandomState(0).randint(0, 10_000_000, 100_000)
        t0 = time.time()
        out = t.pull(ids)
        dt = time.time() - t0
        assert out.shape == (100_000, 16)
        assert dt < 5.0, f"pull too slow: {dt}s"


class TestProfiler:
    def test_record_summary_export(self):
        import paddle_tpu.profiler as prof
        prof.reset_profiler()
        prof.start_profiler()
        with prof.RecordEvent("matmul_dispatch"):
            sum(range(1000))
        with prof.RecordEvent("matmul_dispatch"):
            sum(range(1000))
        with prof.RecordEvent("data_feed"):
            pass
        s = prof.summary()
        assert "matmul_dispatch" in s and "data_feed" in s
        with tempfile.TemporaryDirectory() as tmp:
            p = os.path.join(tmp, 'trace.json')
            prof.export_chrome_tracing(p)
            import json
            with open(p) as f:
                trace = json.load(f)
            assert len(trace['traceEvents']) == 3
        lib = load_native()
        lib.ptpu_profiler_enable(0)


def test_cpp_extension_custom_op():
    """Parity: utils.cpp_extension.load — user C++ op JIT-built + called."""
    import tempfile
    from paddle_tpu.utils import cpp_extension
    from paddle_tpu.core.tensor import Tensor
    src = os.path.join(tempfile.mkdtemp(), 'my_ops.cc')
    with open(src, 'w') as f:
        f.write('''
#include <cstdint>
extern "C" void my_relu6(const float* in, float* out, int64_t n) {
  for (int64_t i = 0; i < n; ++i) {
    float v = in[i] < 0 ? 0 : in[i];
    out[i] = v > 6 ? 6 : v;
  }
}
''')
    mod = cpp_extension.load('my_ext', [src])
    x = Tensor(np.array([-1.0, 3.0, 9.0], np.float32))
    out = mod.my_relu6(x)
    np.testing.assert_allclose(out.numpy(), [0.0, 3.0, 6.0])
