"""Communication/compute overlap (ISSUE 10, docs/performance.md#comm-overlap).

Covers the overlap building blocks in core/bucketing.py (layer-grouped
buckets, knob resolution, chunked collectives, exposed/hidden comm
gauges), the engines' deferred/prefetched param all-gather (hybrid
in-process on the virtual mesh; true 2-rank bit-level + census memory
assertions via the dist_models subprocess), the dp=1 no-op invariant
(nothing to overlap => compiled program unchanged), and the XLA
latency-hiding flag plumbing in core/flags.py.
"""
import os
import subprocess
import sys

import numpy as np
import pytest
import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.core import bucketing as B
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.distributed import topology_runtime


def _mesh(axes, sizes):
    return topology_runtime.build_mesh(axes, sizes)


class TestOverlapConfig:
    def test_layer_group_fn(self):
        assert B.layer_group_fn('gpt.decoder.layers.3.w') == 'layer00003'
        assert B.layer_group_fn('blocks.11.attn.q.weight') == \
            'layer00011'
        assert B.layer_group_fn('embedding.weight') == 'stem'
        assert B.layer_group_fn('head.bias') == 'stem'
        # zero-padded keys sort in layer order
        assert B.layer_group_fn('l.2.w') < B.layer_group_fn('l.10.w')

    def test_grouped_layout_buckets_in_layer_order(self):
        layout = B.BucketLayout.build(
            {'emb.w': ((4, 4), 'float32'),
             'l.0.w': ((8, 4), 'float32'),
             'l.0.b': ((4,), 'float32'),
             'l.1.w': ((8, 4), 'float32'),
             'head.w': ((4,), 'float32')},
            group_fn=B.layer_group_fn, pad_to=8)
        groups = [b.group for b in layout.buckets]
        assert groups == ['stem', 'layer00000', 'layer00001']
        # stem bucket stays open and takes the head too
        stem = layout.buckets[0]
        assert {s.name for s in stem.slots} == {'emb.w', 'head.w'}
        # describe() carries the group key (layout contract)
        desc = layout.describe()
        assert [b['group'] for b in desc['buckets']] == groups

    def test_resolution_order(self, monkeypatch):
        monkeypatch.delenv('PTPU_COMM_OVERLAP', raising=False)
        monkeypatch.delenv('PTPU_COMM_PREFETCH', raising=False)
        monkeypatch.delenv('PTPU_COMM_CHUNK', raising=False)
        assert B.resolve_overlap_config() == (
            False, B.DEFAULT_PREFETCH_DEPTH, 0)
        monkeypatch.setenv('PTPU_COMM_OVERLAP', '1')
        monkeypatch.setenv('PTPU_COMM_PREFETCH', '3')
        monkeypatch.setenv('PTPU_COMM_CHUNK', '512')
        assert B.resolve_overlap_config() == (True, 3, 512)
        # kwargs beat env
        assert B.resolve_overlap_config(overlap=False, prefetch=1,
                                        chunk=64) == (False, 1, 64)

    def test_falsy_env_overrides_strategy(self, monkeypatch):
        """PTPU_COMM_CHUNK=0 must be able to switch OFF chunking a
        fleet strategy enabled — a present env var wins even when its
        value is falsy."""
        from paddle_tpu.distributed.fleet import fleet as fleet_mod
        from paddle_tpu.distributed.fleet.base.distributed_strategy \
            import DistributedStrategy
        strat = DistributedStrategy()
        strat.sharding_configs = {'comm_overlap': True,
                                  'comm_overlap_prefetch': 4,
                                  'comm_chunk': 4096}
        saved = fleet_mod._user_defined_strategy
        monkeypatch.setattr(fleet_mod, '_user_defined_strategy', strat)
        monkeypatch.delenv('PTPU_COMM_OVERLAP', raising=False)
        monkeypatch.delenv('PTPU_COMM_PREFETCH', raising=False)
        monkeypatch.delenv('PTPU_COMM_CHUNK', raising=False)
        assert B.resolve_overlap_config() == (True, 4, 4096)
        monkeypatch.setenv('PTPU_COMM_CHUNK', '0')
        monkeypatch.setenv('PTPU_COMM_OVERLAP', '0')
        overlap, _, chunk = B.resolve_overlap_config()
        assert overlap is False and chunk == 0
        assert fleet_mod._user_defined_strategy is strat
        monkeypatch.setattr(fleet_mod, '_user_defined_strategy', saved)


class TestChunkedCollectives:
    def test_chunk_spans(self):
        assert B._chunk_spans(64, 2, 0) is None
        assert B._chunk_spans(8, 2, 32) is None      # already fits
        spans = B._chunk_spans(64, 2, 32)            # width 16
        assert spans == [(0, 16), (16, 16), (32, 16), (48, 16)]
        # ragged tail
        assert B._chunk_spans(10, 2, 8)[-1] == (8, 2)

    def test_chunked_rs_ag_bit_exact(self):
        from jax.sharding import PartitionSpec as P
        from jax.experimental.shard_map import shard_map
        mesh = _mesh(['dp'], [8])
        rng = np.random.RandomState(0)
        flat = jnp.asarray(rng.randn(8, 64), jnp.float32)

        def mk(chunk):
            def body(x):
                x = x[0]
                sh = B.reduce_scatter(x, ('dp',), 8, mean=True,
                                      chunk=chunk)
                full = B.all_gather(sh, ('dp',), chunk=chunk,
                                    n_shards=8)
                return sh[None], full[None]
            return shard_map(body, mesh=mesh, in_specs=P('dp'),
                             out_specs=(P('dp'), P('dp')),
                             check_rep=False)

        base_sh, base_full = mk(None)(flat)
        for chunk in (16, 24):
            sh, full = mk(chunk)(flat)
            assert np.array_equal(np.asarray(sh), np.asarray(base_sh))
            assert np.array_equal(np.asarray(full),
                                  np.asarray(base_full))


class TestOverlapGauges:
    def _layout(self):
        return B.BucketLayout.build(
            {'l.0.w': ((64, 4), 'float32'),
             'l.1.w': ((64, 4), 'float32'),
             'head.w': ((16,), 'float32')},
            group_fn=B.layer_group_fn, pad_to=8)

    def test_snapshot_exposed_lt_total_when_enabled(self):
        layout = self._layout()
        B.publish_overlap_gauges(layout, engine='ov_t', n_shards=2,
                                 enabled=True, prefetch=2, chunk=128)
        co = B.comm_snapshot()['comm_overlap']['ov_t']
        assert co['enabled'] and co['groups'] == 3
        assert co['groups_in_flight'] == 2
        assert co['chunk_elements'] == 128
        assert 0 < co['exposed_comm_seconds'] < co['total_comm_seconds']
        assert co['hidden_comm_seconds'] == pytest.approx(
            co['total_comm_seconds'] - co['exposed_comm_seconds'],
            abs=1e-12)

    def test_snapshot_disabled_everything_exposed(self):
        layout = self._layout()
        B.publish_overlap_gauges(layout, engine='ov_off', n_shards=2,
                                 enabled=False)
        co = B.comm_snapshot()['comm_overlap']['ov_off']
        assert not co['enabled'] and co['groups_in_flight'] == 0
        assert co['exposed_comm_seconds'] == co['total_comm_seconds']
        assert co['hidden_comm_seconds'] == 0


class TestHybridOverlap:
    def _data(self):
        rng = np.random.RandomState(0)
        return (Tensor(rng.rand(16, 8).astype('float32')),
                Tensor(rng.rand(16, 1).astype('float32')))

    def _run(self, steps=4, **kw):
        from paddle_tpu.distributed.fleet.meta_parallel.hybrid_engine \
            import HybridParallelTrainStep
        _mesh(['dp', 'sharding'], [2, 4])
        paddle.seed(0)
        net = nn.Sequential(nn.Linear(8, 16), nn.Tanh(),
                            nn.Linear(16, 1))
        opt = paddle.optimizer.AdamW(learning_rate=0.01,
                                     weight_decay=0.01,
                                     parameters=net.parameters())
        eng = HybridParallelTrainStep(
            net, lambda m, x, y: nn.functional.mse_loss(m(x), y), opt,
            **kw)
        X, Y = self._data()
        losses = [float(eng(X, Y)) for _ in range(steps)]
        return losses, eng

    def test_overlap_bit_identical_and_sharded_resident_set(self):
        from paddle_tpu.core import memory as M
        ref, ref_eng = self._run(use_buckets=True)
        got, eng = self._run(use_buckets=True, comm_overlap=True)
        assert eng._overlap and not ref_eng._overlap
        assert got == ref
        sd, ref_sd = eng.state_dict(), ref_eng.state_dict()
        for n in ref_sd['params']:
            assert np.array_equal(sd['params'][n], ref_sd['params'][n])
        # deferred gather: bucketed params live as 1/n flat shards, so
        # the engine's resident param set occupies fewer device bytes
        # than the barrier engine's full replicas (census-measured)
        def pbytes(e):
            return (sum(M.device_nbytes(a) for a in e._params.values())
                    + sum(M.device_nbytes(a)
                          for a in getattr(e, '_param_shards', [])
                          or []))
        assert pbytes(eng) < pbytes(ref_eng)

    def test_overlap_chunked_bit_identical(self):
        ref, _ = self._run(use_buckets=True)
        got, eng = self._run(use_buckets=True, comm_overlap=True,
                             comm_chunk=32)
        assert eng._comm_chunk == 32 and got == ref

    def test_checkpoint_crosses_overlap_layouts(self):
        ref, ref_eng = self._run(use_buckets=True)
        sd = ref_eng.state_dict()
        _, eng = self._run(steps=1, use_buckets=True, comm_overlap=True)
        eng.set_state_dict(sd)
        X, Y = self._data()
        assert float(eng(X, Y)) == float(ref_eng(X, Y))

    def test_dp1_nothing_to_overlap_is_noop(self):
        from paddle_tpu.distributed.fleet.meta_parallel.hybrid_engine \
            import HybridParallelTrainStep
        _mesh(['dp'], [1])
        paddle.seed(0)
        net = nn.Sequential(nn.Linear(8, 16), nn.Tanh(),
                            nn.Linear(16, 1))
        opt = paddle.optimizer.Adam(learning_rate=0.01,
                                    parameters=net.parameters())
        eng = HybridParallelTrainStep(
            net, lambda m, x, y: nn.functional.mse_loss(m(x), y), opt,
            comm_overlap=True)
        # no comm to overlap: knob must not change the engine shape
        assert not eng._overlap and not eng._param_shards
        X, Y = self._data()
        assert np.isfinite(float(eng(X, Y)))


class TestTrainStepOverlapNoop:
    def test_program_unchanged(self):
        """jit.TrainStep has no collectives (n_shards=1): comm_overlap
        on must leave losses bit-identical and buckets ungrouped."""
        from paddle_tpu.jit import TrainStep
        rng = np.random.RandomState(0)
        x = paddle.to_tensor(rng.rand(8, 8).astype('float32'))
        y = paddle.to_tensor(rng.randint(0, 2, (8,)).astype('int64'))

        def run(**kw):
            paddle.seed(0)
            net = nn.Sequential(nn.Linear(8, 16), nn.Tanh(),
                                nn.Linear(16, 2))
            opt = paddle.optimizer.Adam(learning_rate=0.01,
                                        parameters=net.parameters())
            step = TrainStep(net, lambda m, a, b: nn.functional
                             .cross_entropy(m(a), b), opt, **kw)
            return [float(step(x, y)) for _ in range(3)], step
        ref, _ = run()
        got, st = run(comm_overlap=True)
        assert got == ref
        assert all(b.group is None for b in st._layout.buckets)


class TestXlaFlagPlumbing:
    def test_set_flags_edits_xla_flags_env_on_tpu(self, monkeypatch):
        from paddle_tpu.core import flags
        saved_env = os.environ.get('XLA_FLAGS')
        saved = flags.get_flags(['FLAGS_xla_latency_hiding_scheduler',
                                 'FLAGS_xla_async_collectives'])
        try:
            # the xla_tpu_* tokens only exist in TPU builds: they are
            # exported on a TPU-plausible platform only (a CPU jaxlib
            # ABORTS on unknown XLA_FLAGS, and children inherit env)
            monkeypatch.setenv('JAX_PLATFORMS', 'tpu')
            flags.set_flags({'FLAGS_xla_latency_hiding_scheduler': True})
            assert '--xla_tpu_enable_latency_hiding_scheduler=true' \
                in os.environ.get('XLA_FLAGS', '')
            flags.set_flags(
                {'FLAGS_xla_latency_hiding_scheduler': False})
            env = os.environ.get('XLA_FLAGS', '')
            assert '--xla_tpu_enable_latency_hiding_scheduler=false' \
                in env
            assert env.count('xla_tpu_enable_latency_hiding_scheduler')\
                == 1
        finally:
            # restore the registry FIRST (it may re-edit XLA_FLAGS
            # while the platform monkeypatch is still active), then
            # put the env back exactly as found
            flags.set_flags(saved)
            if saved_env is None:
                os.environ.pop('XLA_FLAGS', None)
            else:
                os.environ['XLA_FLAGS'] = saved_env

    def test_cpu_platform_never_exports_tpu_tokens(self, monkeypatch):
        from paddle_tpu.core import flags
        saved_env = os.environ.get('XLA_FLAGS')
        saved = flags.get_flags(['FLAGS_xla_latency_hiding_scheduler'])
        try:
            monkeypatch.setenv('JAX_PLATFORMS', 'cpu')
            flags.set_flags({'FLAGS_xla_latency_hiding_scheduler': True})
            # registry records the intent; env stays clean (a CPU-only
            # jaxlib would fatally abort on the unknown token)
            assert flags.flag('FLAGS_xla_latency_hiding_scheduler') \
                is True
            assert 'xla_tpu_enable_latency_hiding_scheduler' not in \
                os.environ.get('XLA_FLAGS', '')
        finally:
            flags.set_flags(saved)
            if saved_env is None:
                os.environ.pop('XLA_FLAGS', None)
            else:
                os.environ['XLA_FLAGS'] = saved_env

    def test_import_time_overlap_env_export(self, monkeypatch):
        """PTPU_COMM_OVERLAP=1 is honored at flags-module import —
        the only point early enough to reach the backend's one-shot
        XLA_FLAGS read (engine builds always run after init)."""
        import importlib.util
        monkeypatch.setenv('JAX_PLATFORMS', 'tpu')
        monkeypatch.setenv('PTPU_COMM_OVERLAP', '1')
        monkeypatch.setenv('XLA_FLAGS', '')

        def load(name):
            path = os.path.join(os.path.dirname(__file__), '..',
                                'paddle_tpu', 'core', 'flags.py')
            spec = importlib.util.spec_from_file_location(name, path)
            mod = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(mod)
            return mod

        mod = load('ptpu_flags_isolated')
        assert mod.flag('FLAGS_xla_latency_hiding_scheduler') is True
        assert mod.flag('FLAGS_xla_async_collectives') is True
        assert '--xla_tpu_enable_latency_hiding_scheduler=true' in \
            os.environ['XLA_FLAGS']
        # an explicit FLAGS_xla_* env pin beats the overlap default
        monkeypatch.setenv('FLAGS_xla_latency_hiding_scheduler', '0')
        monkeypatch.setenv('XLA_FLAGS', '')
        mod2 = load('ptpu_flags_isolated2')
        assert mod2.flag('FLAGS_xla_latency_hiding_scheduler') is False
        assert '--xla_tpu_enable_latency_hiding_scheduler=false' in \
            os.environ['XLA_FLAGS']

    def test_ensure_overlap_flags_respects_user_pin(self):
        from paddle_tpu.core import flags
        saved_env = os.environ.get('XLA_FLAGS')
        saved = flags.get_flags(['FLAGS_xla_latency_hiding_scheduler',
                                 'FLAGS_xla_async_collectives'])
        try:
            flags.set_flags(
                {'FLAGS_xla_latency_hiding_scheduler': False,
                 'FLAGS_xla_async_collectives': None})
            B.ensure_overlap_xla_flags()
            got = flags.get_flags(
                ['FLAGS_xla_latency_hiding_scheduler',
                 'FLAGS_xla_async_collectives'])
            # pinned False survives; unset flips on
            assert got['FLAGS_xla_latency_hiding_scheduler'] is False
            assert got['FLAGS_xla_async_collectives'] is True
        finally:
            if saved_env is None:
                os.environ.pop('XLA_FLAGS', None)
            else:
                os.environ['XLA_FLAGS'] = saved_env
            flags.set_flags(saved)


class TestCensusDeviceBytes:
    def test_replicated_vs_sharded_device_bytes(self):
        from jax.sharding import NamedSharding, PartitionSpec as P
        from paddle_tpu.core import memory as M
        mesh = _mesh(['dp'], [8])
        arr = jnp.zeros((64, 4), jnp.float32)
        repl = jax.device_put(arr, NamedSharding(mesh, P()))
        shrd = jax.device_put(arr, NamedSharding(mesh, P('dp')))
        assert M.device_nbytes(repl) == 8 * arr.nbytes
        assert M.device_nbytes(shrd) == arr.nbytes
        sample = M.accountant().sample(count_buffers=True)
        assert sample['live_device_bytes'] >= sample['live_bytes']


class TestTwoRankOverlapSubprocess:
    def test_overlap_equals_barrier_bit_level(self):
        """ISSUE 10 acceptance: true 2-rank overlap==barrier BIT-level
        fp32 (chunked too), bf16/int8 overlap wires within tolerance,
        deferred-gather resident param bytes below the barrier path's
        (census-measured), exposed-comm < total-comm in the model."""
        script = os.path.join(os.path.dirname(__file__), 'dist_models',
                              'dist_bucket_equiv.py')
        env = dict(os.environ)
        env.pop('XLA_FLAGS', None)   # script pins its own device count
        p = subprocess.run([sys.executable, '-u', script,
                            '--leg', 'overlap'], env=env,
                           capture_output=True, text=True, timeout=600)
        assert p.returncode == 0, (p.stdout or '') + (p.stderr or '')
        assert 'OK: overlap==barrier' in p.stdout


@pytest.mark.slow
class TestPipelineOverlapSlow:
    def test_pipeline_overlap_bit_identical(self):
        """dp2 x pp4 pipeline: overlap (deferred gather over 'dp') is
        bit-identical to the barrier bucketed path, including a
        loss-scaled (GradScaler) step."""
        from paddle_tpu.models.gpt import GPTConfig, build_gpt_pipeline
        from paddle_tpu.distributed.fleet.meta_parallel.spmd_pipeline \
            import SpmdPipelineEngine
        cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=4,
                        num_heads=4, max_seq_len=32, hidden_dropout=0.0,
                        attn_dropout=0.0, use_flash_attention=False)
        rng = np.random.RandomState(0)
        A, mb, dp = 2, 2, 2
        ids = rng.randint(0, 64, (dp * A * mb, 32)).astype('int32')
        lab = np.roll(ids, -1, 1).astype('int32')

        def run(**kw):
            _mesh(['dp', 'pp'], [dp, 4])
            paddle.seed(0)
            embed, blocks, head = build_gpt_pipeline(cfg)
            opt = paddle.optimizer.AdamW(learning_rate=0.01,
                                         weight_decay=0.01,
                                         parameters=[])
            eng = SpmdPipelineEngine(embed, blocks, head, opt,
                                     accumulate_steps=A,
                                     use_remat=False, **kw)
            data = (Tensor(ids), Tensor(lab))
            out = [float(eng.train_batch(data)) for _ in range(2)]
            out.append(float(eng.train_batch(data, scale=1024.0)))
            eng.sync_model()
            params = {n: np.asarray(jax.device_get(p.data))
                      for layer in ([embed, head] + blocks)
                      for n, p in layer.named_parameters()}
            eng.shutdown()
            return out, params

        ref, ref_p = run(use_buckets=True)
        got, got_p = run(use_buckets=True, comm_overlap=True)
        assert got == ref
        for n in ref_p:
            assert np.array_equal(got_p[n], ref_p[n]), n
