"""Gradient bucketing + sharded weight update (ISSUE 4).

Covers: layout-map round-trip (param -> bucket/offset -> param), padding
correctness, mixed-dtype bucket separation, size-cap splitting, the
bucketed TrainStep / hybrid-engine / pipeline equivalence on the virtual
mesh, fp32 bit-level sharded-vs-replicated equivalence on a true 2-rank
mesh (subprocess), GradScaler.unscale_ / clip_grad_norm_ on flat buckets
with sync-count assertions, ptpu_comm_* gauges, and the persistent
compilation cache.
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax                                                  # noqa: E402
import jax.numpy as jnp                                     # noqa: E402

import paddle_tpu as paddle                                 # noqa: E402
from paddle_tpu import nn                                   # noqa: E402
from paddle_tpu.core import bucketing as B                  # noqa: E402
from paddle_tpu.core.tensor import Tensor                   # noqa: E402


class TestBucketLayout:
    def _shapes(self):
        return {
            'a': ((4, 3), jnp.float32),
            'b': ((7,), jnp.float32),
            'c': ((2, 2, 2), jnp.bfloat16),
            'd': ((5,), jnp.float32),
            'e': ((3,), jnp.bfloat16),
        }

    def test_roundtrip_param_bucket_param(self):
        layout = B.BucketLayout.build(self._shapes(), pad_to=4)
        rng = np.random.RandomState(0)
        tree = {n: jnp.asarray(rng.randn(*shp).astype('float32'),
                               dtype=dt)
                for n, (shp, dt) in self._shapes().items()}
        flats = layout.flatten(tree)
        back = layout.unflatten(flats)
        assert set(back) == set(tree)
        for n in tree:
            assert back[n].shape == tree[n].shape
            assert back[n].dtype == tree[n].dtype
            np.testing.assert_array_equal(np.asarray(back[n]),
                                          np.asarray(tree[n]))

    def test_layout_map_is_stable_and_explicit(self):
        layout = B.BucketLayout.build(self._shapes(), pad_to=4)
        desc = layout.describe()
        json.dumps(desc)   # JSON-ready
        # the map: every param knows (bucket, offset, size); offsets are
        # contiguous in insertion order within a bucket
        for b in desc['buckets']:
            off = 0
            for s in b['slots']:
                assert s['offset'] == off, s
                off += s['size']
            assert b['used'] == off
            assert b['size'] >= b['used'] and b['size'] % 4 == 0

    def test_padding_is_zero_and_dropped(self):
        layout = B.BucketLayout.build({'w': ((3,), jnp.float32)},
                                      pad_to=8)
        (flat,) = layout.flatten({'w': jnp.ones((3,), jnp.float32)})
        assert flat.shape == (8,)
        np.testing.assert_array_equal(np.asarray(flat[3:]), 0.0)
        back = layout.unflatten([flat])
        assert back['w'].shape == (3,)

    def test_mixed_dtype_buckets_separate(self):
        layout = B.BucketLayout.build(self._shapes(), pad_to=1)
        for b in layout.buckets:
            assert len({s.dtype for s in b.slots}) == 1
            assert all(s.dtype == b.dtype for s in b.slots)
        # fp32 params share one bucket, bf16 params another
        assert len(layout.buckets) == 2

    def test_size_cap_splits_buckets(self):
        shapes = {f'p{i}': ((256,), jnp.float32) for i in range(8)}
        layout = B.BucketLayout.build(shapes, bucket_bytes=1024, pad_to=1)
        # 256 fp32 = 1024 bytes: one param per bucket
        assert len(layout.buckets) == 8
        # a single param bigger than the cap still gets a bucket
        layout2 = B.BucketLayout.build({'big': ((4096,), jnp.float32)},
                                       bucket_bytes=1024)
        assert len(layout2.buckets) == 1

    def test_group_fn_separates(self):
        layout = B.BucketLayout.build(
            {'x/a': ((4,), jnp.float32), 'y/b': ((4,), jnp.float32)},
            group_fn=lambda n, s, d: n.split('/')[0])
        assert len(layout.buckets) == 2

    def test_flat_state_conversion_roundtrip(self):
        layout = B.BucketLayout.build(self._shapes(), pad_to=4)
        rng = np.random.RandomState(1)
        flat_states = []
        for b in layout.buckets:
            flat_states.append({
                'moment1': rng.randn(b.size).astype(np.float32),
                'beta1_pow': np.float32(0.9),
            })
        named = B.flat_states_to_named(layout, flat_states)
        assert set(named) == set(self._shapes())
        for n, (shp, _) in self._shapes().items():
            assert named[n]['moment1'].shape == shp
            assert named[n]['beta1_pow'] == np.float32(0.9)
        back = B.named_states_to_flat(layout, named, flat_states)
        for st, st0, b in zip(back, flat_states, layout.buckets):
            # real-slot region round-trips exactly; padding untouched
            np.testing.assert_array_equal(st['moment1'][:b.used],
                                          st0['moment1'][:b.used])

    def test_elementwise_classification(self):
        assert B.elementwise(paddle.optimizer.Adam(parameters=[]))
        assert B.elementwise(paddle.optimizer.SGD(parameters=[]))
        assert not B.elementwise(paddle.optimizer.Lamb(parameters=[]))
        assert not B.elementwise(paddle.optimizer.Lars(parameters=[]))


class TestCommGauges:
    def test_publish_and_snapshot(self):
        # the bf16-training shape the acceptance bar targets: bf16
        # params, bf16 wire, fp32-accuracy reduction
        layout = B.BucketLayout.build(
            {'w': ((1024,), jnp.bfloat16), 'v': ((1024,), jnp.bfloat16)},
            pad_to=8)
        B.publish_comm_gauges(layout, engine='testeng', n_shards=8,
                              comm_dtype=jnp.bfloat16, enabled=True)
        snap = B.comm_snapshot()
        assert snap['ptpu_comm_buckets']['engine=testeng'] == 1
        rs = snap['ptpu_comm_bytes_per_step'][
            'engine=testeng,op=reduce_scatter']
        ag = snap['ptpu_comm_bytes_per_step'][
            'engine=testeng,op=all_gather']
        assert rs == 2048 * 2              # bf16 wire
        assert ag == 2048 * 2              # params gather in their dtype
        base = snap['ptpu_comm_modeled_bytes_per_step'][
            'engine=testeng,scheme=per_param_psum_fp32']
        new = snap['ptpu_comm_modeled_bytes_per_step'][
            'engine=testeng,scheme=bucketed']
        assert base == 2 * 2048 * 4
        assert new == rs + ag
        drop = snap['comm_bytes_drop_vs_per_param_psum']['testeng']
        assert drop >= 0.40, drop          # the ISSUE 4 acceptance bar
        assert snap['ptpu_comm_enabled']['engine=testeng'] == 1
        assert snap['ptpu_comm_compressed_fraction'][
            'engine=testeng'] == 0.5


class TestInt8Wire:
    """ISSUE 7: block-scaled int8 quantization helpers and the real
    wire-byte accounting (payload vs scale vs pad)."""

    def test_block_len_divides(self):
        assert B.block_len(592, 256) == 148    # 592 = 4 * 148
        assert B.block_len(1024, 256) == 256
        assert B.block_len(296, 32) == 8
        assert B.block_len(7, 256) == 7
        for n, want in ((592, 256), (1024, 256), (296, 32), (11, 4)):
            b = B.block_len(n, want)
            assert n % b == 0 and b <= max(want, 1)

    def test_quantize_blocks_roundtrip_bound(self):
        rng = np.random.RandomState(0)
        flat = jnp.asarray((rng.randn(1024) * 3).astype('float32'))
        q, s = B.quantize_blocks(flat, 128)
        assert q.dtype == jnp.int8 and s.shape == (8,)
        back = np.asarray(B.dequantize_blocks(q, s, 128))
        # per-block bound: half a bin of that block's abs-max scale
        err = np.abs(back - np.asarray(flat)).reshape(8, 128).max(1)
        bound = np.asarray(s) / 2 + 1e-7
        assert (err <= bound).all(), (err, bound)

    def test_int8_gauges_payload_factor_and_breakdown(self):
        # deliberately pad-heavy layout so the pad accounting shows
        layout = B.BucketLayout.build(
            {'w': ((1000,), jnp.float32), 'v': ((500,), jnp.float32)},
            pad_to=64)
        B.publish_comm_gauges(layout, engine='int8eng', n_shards=8,
                              comm_dtype='int8', enabled=True,
                              block=256)
        snap = B.comm_snapshot()
        elems, padded = 1500, layout.total_padded()
        rs = snap['ptpu_comm_bytes_per_step'][
            'engine=int8eng,op=reduce_scatter']
        ag = snap['ptpu_comm_bytes_per_step'][
            'engine=int8eng,op=all_gather']
        wb = snap['comm_wire_breakdown']['int8eng']
        # payload: 1 byte/elem on BOTH legs; overhead carries the fp32
        # block scales and the zero-padding
        assert wb['payload_bytes'] == 2 * elems
        assert wb['pad_bytes'] == 2 * (padded - elems)
        assert wb['scale_bytes'] > 0
        assert wb['total_bytes'] == rs + ag
        # the ISSUE-7 acceptance bar: >= 4x payload drop vs the fp32
        # per-param psum (2x payload ring convention), overhead visible
        factor = snap['comm_payload_factor_vs_per_param_psum'][
            'int8eng']
        assert factor >= 4.0, factor
        assert snap['comm_bytes_drop_vs_per_param_psum'][
            'int8eng'] >= 0.70
        assert snap['ptpu_comm_block_elements']['engine=int8eng'] > 0
        assert snap['ptpu_comm_compressed_fraction'][
            'engine=int8eng'] == 0.75

    def test_wire_bytes_bf16_matches_legacy_model(self):
        layout = B.BucketLayout.build(
            {'w': ((2048,), jnp.bfloat16)}, pad_to=8)
        wires = B.wire_bytes(layout, 8, jnp.bfloat16)
        assert wires['reduce_scatter']['total'] == 2048 * 2
        assert wires['all_gather']['total'] == 2048 * 2
        assert wires['reduce_scatter']['scale'] == 0

    def test_force_master_overrides_multi_precision_off(self):
        # int8 comm NEEDS the sharded fp32 master even when the
        # optimizer opts out of multi_precision: without it the
        # int8-rounded gathered params would BE the optimizer state
        # and wire rounding would compound into the trajectory
        layout = B.BucketLayout.build({'w': ((64,), jnp.float32)},
                                      pad_to=8)
        opt = paddle.optimizer.Adam(learning_rate=0.01)
        opt._multi_precision = False
        st = B.init_bucket_state(opt, layout.buckets[0],
                                 np.zeros(layout.buckets[0].size,
                                          np.float32),
                                 force_master=True)
        assert 'master' in st
        # and fp32 buckets without the int8 wire still skip it
        st2 = B.init_bucket_state(opt, layout.buckets[0],
                                  np.zeros(layout.buckets[0].size,
                                           np.float32))
        assert 'master' not in st2

    def test_effective_block_gauge_honest(self):
        # shard_len 16 has no divisor of 256 above 16 — the gauge must
        # report the EFFECTIVE block (16), not the requested 256
        layout = B.BucketLayout.build({'w': ((120,), jnp.float32)},
                                      pad_to=16)   # size 128, 8 shards
        B.publish_comm_gauges(layout, engine='blkeng', n_shards=8,
                              comm_dtype='int8', enabled=True,
                              block=256)
        snap = B.comm_snapshot()
        assert snap['ptpu_comm_block_elements']['engine=blkeng'] == 16


def _mesh(axes, sizes):
    from paddle_tpu.distributed import topology_runtime
    return topology_runtime.build_mesh(axes, sizes)


class TestEngineEquivalence:
    """In-process equivalence on the 8-virtual-device mesh (the true
    2-rank bit-level check runs in the subprocess test below)."""

    def _data(self):
        rng = np.random.RandomState(0)
        return (Tensor(rng.rand(16, 8).astype('float32')),
                Tensor(rng.rand(16, 1).astype('float32')))

    def _run_hybrid(self, use_buckets, comm_dtype=None, opt_name='adamw',
                    steps=4):
        from paddle_tpu.distributed.fleet.meta_parallel.hybrid_engine \
            import HybridParallelTrainStep
        _mesh(['dp', 'sharding'], [2, 4])
        paddle.seed(0)
        net = nn.Sequential(nn.Linear(8, 16), nn.Tanh(),
                            nn.Linear(16, 1))
        if opt_name == 'adamw':
            opt = paddle.optimizer.AdamW(learning_rate=0.01,
                                         weight_decay=0.01,
                                         parameters=net.parameters())
        else:
            opt = paddle.optimizer.Momentum(learning_rate=0.05,
                                            parameters=net.parameters())
        eng = HybridParallelTrainStep(net, lambda m, x, y: nn.functional
                                      .mse_loss(m(x), y), opt,
                                      use_buckets=use_buckets,
                                      comm_dtype=comm_dtype)
        X, Y = self._data()
        losses = [float(eng(X, Y)) for _ in range(steps)]
        return losses, eng

    def test_hybrid_bucketed_matches_legacy(self):
        for opt_name in ('adamw', 'momentum'):
            got, eng = self._run_hybrid(True, opt_name=opt_name)
            assert eng._bucketed
            ref, _ = self._run_hybrid(False, opt_name=opt_name)
            np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)

    def test_hybrid_bf16_comm_within_tolerance(self):
        got, eng = self._run_hybrid(True, comm_dtype='bfloat16')
        assert eng.comm_dtype == jnp.bfloat16
        ref, _ = self._run_hybrid(False)
        np.testing.assert_allclose(got, ref, rtol=5e-2, atol=1e-3)

    def test_hybrid_lamb_keeps_per_param_path(self):
        from paddle_tpu.distributed.fleet.meta_parallel.hybrid_engine \
            import HybridParallelTrainStep
        _mesh(['dp'], [8])
        paddle.seed(0)
        net = nn.Sequential(nn.Linear(8, 4), nn.Tanh(), nn.Linear(4, 1))
        opt = paddle.optimizer.Lamb(learning_rate=0.01,
                                    parameters=net.parameters())
        eng = HybridParallelTrainStep(
            net, lambda m, x, y: nn.functional.mse_loss(m(x), y), opt)
        assert not eng._bucketed
        X, Y = self._data()
        assert np.isfinite(float(eng(X, Y)))

    def test_hybrid_checkpoint_crosses_layouts(self):
        """A bucketed engine's checkpoint restores into a legacy engine
        (and back): the state_dict schema stays per-parameter."""
        got, eng = self._run_hybrid(True)
        sd = eng.state_dict()
        ref, eng_legacy = self._run_hybrid(False)
        sd_legacy = eng_legacy.state_dict()
        assert set(sd['states']) == set(sd_legacy['states'])
        for n in sd['states']:
            assert set(sd['states'][n]) == set(sd_legacy['states'][n])
            np.testing.assert_allclose(
                sd['states'][n]['moment1'],
                sd_legacy['states'][n]['moment1'], rtol=1e-4, atol=1e-6)
        # legacy checkpoint -> bucketed engine reproduces the next loss
        _, eng2 = self._run_hybrid(True, steps=1)
        eng2.set_state_dict(sd_legacy)
        X, Y = self._data()
        l_next_legacy = float(eng_legacy(X, Y))
        l_next = float(eng2(X, Y))
        np.testing.assert_allclose(l_next, l_next_legacy, rtol=1e-5)

    def test_trainstep_bucketed_matches_legacy(self):
        from paddle_tpu.jit import TrainStep
        rng = np.random.RandomState(0)
        x = paddle.to_tensor(rng.rand(8, 8).astype('float32'))
        y = paddle.to_tensor(rng.randint(0, 2, (8,)).astype('int64'))

        def run(use_buckets):
            paddle.seed(0)
            net = nn.Sequential(nn.Linear(8, 16), nn.Tanh(),
                                nn.Linear(16, 2))
            opt = paddle.optimizer.Adam(learning_rate=0.01,
                                        parameters=net.parameters())
            step = TrainStep(net, lambda m, a, b: nn.functional
                             .cross_entropy(m(a), b), opt,
                             use_buckets=use_buckets)
            return [float(step(x, y)) for _ in range(4)], step
        got, st = run(True)
        assert st._use_buckets and st._layout is not None
        ref, st2 = run(False)
        assert not st2._use_buckets
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-7)

    def test_pipeline_bucketed_matches_legacy(self):
        from paddle_tpu.models.gpt import GPTConfig, build_gpt_pipeline
        from paddle_tpu.distributed.fleet.meta_parallel.spmd_pipeline \
            import SpmdPipelineEngine
        cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=4,
                        num_heads=4, max_seq_len=32, hidden_dropout=0.0,
                        attn_dropout=0.0, use_flash_attention=False)
        rng = np.random.RandomState(0)
        A, mb, dp = 2, 2, 2
        ids = rng.randint(0, 64, (dp * A * mb, 32)).astype('int32')
        lab = np.roll(ids, -1, 1).astype('int32')

        def run(use_buckets):
            _mesh(['dp', 'pp'], [dp, 4])
            paddle.seed(0)
            embed, blocks, head = build_gpt_pipeline(cfg)
            opt = paddle.optimizer.AdamW(learning_rate=0.01,
                                         weight_decay=0.01,
                                         parameters=[])
            eng = SpmdPipelineEngine(embed, blocks, head, opt,
                                     accumulate_steps=A, use_remat=False,
                                     use_buckets=use_buckets)
            out = [float(eng.train_batch((Tensor(ids), Tensor(lab))))
                   for _ in range(3)]
            eng.shutdown()
            return out
        got = run(True)
        ref = run(False)
        np.testing.assert_allclose(got, ref, rtol=2e-4, atol=1e-5)


class TestTwoRankSubprocess:
    def test_sharded_vs_replicated_bit_level(self):
        """ISSUE 4 acceptance: on a true 2-rank mesh the bucketed
        sharded update is bit-identical (fp32) to the replicated one,
        and the bf16 compressed wire stays within tolerance."""
        script = os.path.join(os.path.dirname(__file__), 'dist_models',
                              'dist_bucket_equiv.py')
        env = dict(os.environ)
        env.pop('XLA_FLAGS', None)   # script pins its own device count
        # base leg only: the overlap leg runs from tests/test_overlap.py
        p = subprocess.run([sys.executable, '-u', script,
                            '--leg', 'base'], env=env,
                           capture_output=True, text=True, timeout=600)
        assert p.returncode == 0, (p.stdout or '') + (p.stderr or '')
        assert 'OK: sharded==replicated' in p.stdout


class TestBucketedAmpAndClip:
    def _net_with_grads(self, grads):
        paddle.seed(0)
        net = nn.Linear(2, len(grads))
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=net.parameters())
        for p, g in zip(net.parameters(), grads):
            p.grad = Tensor(np.full(p.shape, g, np.float32))
        return net, opt

    def test_unscale_one_fused_sync(self, monkeypatch):
        """unscale_ must flatten grads into buckets and read found_inf
        with ONE host sync (routed through the numerics fetch hook)."""
        from paddle_tpu.core import numerics as num
        from paddle_tpu.amp import GradScaler
        net, opt = self._net_with_grads([1.0, 2.0])
        scaler = GradScaler(init_loss_scaling=4.0)
        calls = []
        real = num._host_fetch
        monkeypatch.setattr(num, '_host_fetch',
                            lambda tree: (calls.append(1) or real(tree)))
        scaler.unscale_(opt)
        assert len(calls) == 1
        assert not scaler._found_inf
        for p, g in zip(net.parameters(), [1.0, 2.0]):
            np.testing.assert_allclose(np.asarray(p.grad.data),
                                       np.full(p.shape, g / 4.0),
                                       rtol=1e-6)

    def test_unscale_found_inf_on_buckets(self):
        from paddle_tpu.amp import GradScaler
        net, opt = self._net_with_grads([1.0, np.inf])
        scaler = GradScaler(init_loss_scaling=4.0)
        scaler.unscale_(opt)
        assert scaler._found_inf
        finite = [p for p in net.parameters()
                  if np.isfinite(np.asarray(p.grad.data)).all()]
        assert finite and np.allclose(np.asarray(finite[0].grad.data),
                                      0.25)

    def test_clip_grad_norm_bucketed_single_reduction(self, monkeypatch):
        """clip_grad_norm_ computes the global norm over flat buckets;
        with error_if_nonfinite its one host sync routes through the
        numerics fetch hook (and the PR-3 publish dedup still holds)."""
        from paddle_tpu.core import numerics as num
        paddle.seed(0)
        net = nn.Sequential(nn.Linear(4, 8), nn.Tanh(), nn.Linear(8, 2))
        x = paddle.to_tensor(np.random.RandomState(0)
                             .rand(4, 4).astype('float32'))
        loss = net(x).sum()
        loss.backward()
        params = [p for p in net.parameters() if p.grad is not None]
        ref = np.sqrt(sum(
            float(jnp.sum(p.grad.data.astype(jnp.float32) ** 2))
            for p in params))
        calls = []
        real = num._host_fetch
        monkeypatch.setattr(num, '_host_fetch',
                            lambda tree: (calls.append(1) or real(tree)))
        total = nn.clip_grad_norm_(params, max_norm=0.5,
                                   error_if_nonfinite=True)
        assert len(calls) == 1
        np.testing.assert_allclose(float(total), ref, rtol=1e-5)
        got = np.sqrt(sum(
            float(jnp.sum(p.grad.data.astype(jnp.float32) ** 2))
            for p in params))
        np.testing.assert_allclose(got, min(ref, 0.5), rtol=1e-5)

    def test_clip_grad_norm_nonfinite_raises(self):
        net, _ = self._net_with_grads([np.nan, 1.0])
        with pytest.raises(RuntimeError, match='non-finite'):
            nn.clip_grad_norm_(list(net.parameters()), max_norm=1.0,
                               error_if_nonfinite=True)

    def test_clip_grad_norm_inf_norm(self):
        net, _ = self._net_with_grads([3.0, -7.0])
        total = nn.clip_grad_norm_(list(net.parameters()),
                                   max_norm=100.0,
                                   norm_type=float('inf'))
        np.testing.assert_allclose(float(total), 7.0, rtol=1e-6)


class TestCompileCache:
    def test_persistent_cache_hits_and_gauges(self, tmp_path):
        """Second compile of the same program in a fresh process must
        hit the on-disk cache and bump ptpu_compile_cache_* gauges."""
        code = r'''
import json, os, sys
os.environ['JAX_PLATFORMS'] = 'cpu'
sys.path.insert(0, %(root)r)
from paddle_tpu.core import compile_cache
assert compile_cache.enable_from_env()
assert compile_cache.enabled()
import jax, jax.numpy as jnp
f = jax.jit(lambda x: (x * 3 + jnp.sin(x)).sum())
f(jnp.arange(1717, dtype=jnp.float32)).block_until_ready()
print('SNAP:' + json.dumps(compile_cache.snapshot()))
'''
        env = dict(os.environ)
        env['PTPU_COMPILE_CACHE_DIR'] = str(tmp_path)
        env['PTPU_COMPILE_CACHE_MIN_COMPILE_SECS'] = '0'
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

        def run():
            p = subprocess.run(
                [sys.executable, '-c', code % {'root': root}], env=env,
                capture_output=True, text=True, timeout=300)
            assert p.returncode == 0, (p.stdout or '') + (p.stderr or '')
            line = [l for l in p.stdout.splitlines()
                    if l.startswith('SNAP:')][-1]
            return json.loads(line[len('SNAP:'):])
        first = run()
        assert first['enabled'] and first['dir'] == str(tmp_path)
        assert first['requests'] >= 1
        second = run()
        assert second['hits'] >= 1, second
        assert second['seconds_saved'] >= 0.0
        assert second['misses'] == second['requests'] - second['hits']
