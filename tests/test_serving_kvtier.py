"""Tiered KV cache (ISSUE 20): host-RAM spill/prefetch under the
paged pool. The bars: allocator invariants hold ACROSS tiers (every
page released exactly once, COW refcounts and int8 scale siblings
survive a spill+resurrect round trip bit-identically, LRU subtrees
spill oldest-first), preempt->spill->resume streams stay
token-identical, the fused-decode `try_reserve` gate treats
spill-in-flight pages as unavailable until landed, the router's
prefix-affinity prefetch hint warms a replica's host tier end-to-end,
and a tierless (or never-spilling) config keeps PR-19's compiled
shapes, host-sync count and gauge set exactly."""
import threading

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.serving.engine as engine_mod
from paddle_tpu.core import monitor
from paddle_tpu.serving import (KVPagePool, ServingConfig, ServingEngine)
from paddle_tpu.serving.host_tier import HostTier
from paddle_tpu.serving.request_trace import load_trace, reconstruct

MODEL_KW = dict(vocab_size=128, hidden_size=64, num_layers=2,
                num_heads=2, max_seq_len=160, hidden_dropout=0.0,
                attn_dropout=0.0, use_flash_attention=False)


@pytest.fixture(scope='module')
def tiny_lm():
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM
    paddle.seed(7)
    m = GPTForCausalLM(GPTConfig(**MODEL_KW))
    m.eval()
    return m


@pytest.fixture(scope='module')
def prompts():
    rng = np.random.RandomState(3)
    return [list(rng.randint(1, 128, n)) for n in (5, 11, 3, 8)]


def _pool(num_pages=8, page_size=4, host_pages=8, dtype=None, **tier_kw):
    pool = KVPagePool(num_pages=num_pages, page_size=page_size,
                      num_layers=2, num_heads=2, head_dim=4,
                      dtype=dtype, prefix_cache=True)
    pool.materialize()
    pool.attach_host_tier(HostTier(host_pages, **tier_kw))
    return pool


def _fill_random(pool, seed=0):
    """Give every pool row distinguishable contents so round trips
    can be checked bit-for-bit."""
    import jax.numpy as jnp
    rng = np.random.RandomState(seed)
    kv = []
    for layer in pool.kv:
        bufs = []
        for b in layer:
            if np.dtype(b.dtype) == np.int8:
                a = rng.randint(-128, 128, size=b.shape).astype(np.int8)
            else:
                a = rng.rand(*b.shape).astype(b.dtype)
            bufs.append(jnp.asarray(a))
        kv.append(tuple(bufs))
    pool.kv = kv


def _rows(pool, pages):
    """Snapshot the given page rows of every layer buffer as numpy."""
    return [[np.asarray(b)[list(pages)] for b in layer]
            for layer in pool.kv]


def _park_chain(pool, seq, toks):
    """Prefill-register a chain and release it into the cached set."""
    pool.ensure_capacity(seq, len(toks))
    pool.register_prefix(seq, toks, written=len(toks))
    pool.release(seq)


def _partition_ok(pool):
    """free + cached + mapped + spill-pinned partitions the pool."""
    return (len(pool._free) + len(pool._cached) + len(pool._ref)
            + len(pool._spilling) == pool.num_pages)


# ---------------------------------------------------------------------------
# allocator invariants across tiers
# ---------------------------------------------------------------------------
class TestTierAllocator:
    def test_exact_once_release_across_tiers(self):
        pool = _pool(num_pages=8, page_size=4)
        _fill_random(pool)
        toks = list(range(10, 22))                 # 3 pages
        _park_chain(pool, 'a', toks)
        assert _partition_ok(pool) and len(pool._cached) == 3
        assert pool.spill_lru(sync=True) == 3
        # markers index the chain; no device page holds it anymore
        assert pool.host_resident_pages() == 3
        assert pool.free_pages == 8 and _partition_ok(pool)
        assert pool.host_tier.used_slots == 3
        # resurrect maps the chain into 'b' -- each page exactly once
        assert pool.match_and_map('b', toks, limit=11) == 8
        assert pool.host_tier.used_slots == 1      # tail page stays
        assert pool.pages_in_use == 2 and _partition_ok(pool)
        assert pool.release('b') == 2
        assert pool.free_pages == 8 and _partition_ok(pool)
        # nothing double-freed, nothing leaked: a full reset returns
        # every slot on both tiers
        pool.reset()
        assert pool.host_tier.used_slots == 0
        assert pool.free_pages == 8 and _partition_ok(pool)

    def test_cow_refcount_survives_spill_resurrect(self):
        pool = _pool(num_pages=8, page_size=4)
        _fill_random(pool)
        toks = list(range(30, 38))                 # 2 pages
        _park_chain(pool, 'a', toks)
        assert pool.spill_lru(sync=True) == 2
        # two sequences share the resurrected pages copy-on-write
        assert pool.match_and_map('b', toks + [1], limit=8) == 8
        pages = list(pool.page_table('b'))
        assert pool.match_and_map('c', toks + [2], limit=8) == 8
        assert list(pool.page_table('c')) == pages
        assert all(pool._ref[p] == 2 for p in pages)
        # releases decrement; the second one parks the pages cached
        pool.release('b')
        assert all(pool._ref[p] == 1 for p in pages)
        pool.release('c')
        assert all(p in pool._cached for p in pages)
        assert _partition_ok(pool)

    @pytest.mark.parametrize('dtype', [None, 'int8'])
    def test_round_trip_bit_identical(self, dtype):
        # fp32 pages AND int8 pages with their fp32 scale siblings
        # come back from the host tier bit-for-bit (the page_stream
        # contract: rows move as stored, nothing re-quantizes)
        pool = _pool(num_pages=8, page_size=4, dtype=dtype,
                     chunk_pages=2)                # exercise chunking
        if dtype == 'int8':
            assert pool.quantized and len(pool.kv[0]) == 4
        _fill_random(pool, seed=3)
        toks = list(range(50, 62))                 # 3 pages
        _park_chain(pool, 'a', toks)
        before = _rows(pool, pool._match_pages(toks))
        assert pool.spill_lru(sync=True) == 3
        assert pool.match_and_map('b', toks, limit=11) == 8
        after = _rows(pool, pool.page_table('b'))
        for lb, la in zip(before, after):
            for bb, ba in zip(lb, la):
                assert bb.dtype == ba.dtype
                np.testing.assert_array_equal(bb[:2], ba)

    def test_lru_subtree_spill_ordering(self):
        pool = _pool(num_pages=8, page_size=4, host_pages=4)
        _fill_random(pool)
        a_toks = list(range(10, 18))               # 2 pages, oldest
        b_toks = list(range(40, 48))               # 2 pages, newest
        _park_chain(pool, 'a', a_toks)
        _park_chain(pool, 'b', b_toks)
        # bounded spill takes the LRU subtree (a), not the newest
        assert pool.spill_lru(max_pages=1, sync=True) == 2
        assert all(m <= -2 for m in pool._match_pages(a_toks))
        assert all(p >= 0 for p in pool._match_pages(b_toks))
        # next round takes b; a 4-slot tier is now full, so further
        # pressure falls back to eviction instead of spilling
        _park_chain(pool, 'c', list(range(70, 78)))
        assert pool.spill_lru(max_pages=1, sync=True) == 2
        assert all(m <= -2 for m in pool._match_pages(b_toks))
        assert pool.spill_lru(sync=True) == 0      # tier full
        assert pool.host_tier.free_slots == 0

    def test_try_reserve_sees_inflight_spill_as_unavailable(self):
        # the fused-decode reservation gate (PR-19) must not hand out
        # pages whose device->host transfer is still in flight
        pool = _pool(num_pages=4, page_size=4, window=2)
        _fill_random(pool)
        _park_chain(pool, 'a', list(range(10, 26)))    # all 4 pages
        gate = threading.Event()
        tier = pool.host_tier
        real_land = tier._land

        def gated_land(staged, spans, slots):
            gate.wait(10)
            real_land(staged, spans, slots)
        tier._land = gated_land
        try:
            assert pool.spill_lru(sync=False) == 4
            # pinned: not free, not cached, not reservable
            assert pool.free_pages == 0
            assert len(pool._spilling) == 4 and _partition_ok(pool)
            assert not pool.try_reserve('x', 4)
            gate.set()
            tier.drain()
            for _ in range(500):
                if pool.free_pages == 4:
                    break
                threading.Event().wait(0.01)
            assert pool.free_pages == 4 and not pool._spilling
            assert pool.try_reserve('x', 4)
        finally:
            tier._land = real_land
            tier.shutdown()


# ---------------------------------------------------------------------------
# engine: preempt -> spill -> resume token identity; trace + ledger
# ---------------------------------------------------------------------------
class TestTieredEngine:
    def test_preempt_spill_resume_token_identity(self, tiny_lm,
                                                 prompts):
        ref_eng = ServingEngine(tiny_lm, ServingConfig(
            page_size=8, max_batch_size=3, prefill_chunk=8, seed=11))
        ref = ref_eng.generate(prompts, max_new_tokens=8, top_k=0)
        ref_eng.shutdown()
        # 5 pages cannot hold the concurrent contexts: the scheduler
        # preempts, released pages spill to host under the aggressive
        # watermark, and resumes resurrect -- outputs must not change
        eng = ServingEngine(tiny_lm, ServingConfig(
            page_size=8, max_batch_size=3, prefill_chunk=8, seed=11,
            num_pages=5, host_tier_pages=16, spill_watermark=0.5))
        outs = eng.generate(prompts, max_new_tokens=8, top_k=0)
        assert outs == ref
        st = eng.stats()
        ps = st['pool']
        assert st['preemptions_total'] > 0
        assert ps['tier_spilled_pages_total'] > 0
        assert eng.pool.pages_in_use == 0
        eng.shutdown()

    def test_resurrect_skips_prefill_and_lands_in_trace(self, tiny_lm,
                                                        tmp_path):
        eng = ServingEngine(tiny_lm, ServingConfig(
            page_size=8, max_batch_size=2, prefill_chunk=8, seed=11,
            host_tier_pages=16))
        prompt = list(range(1, 21))                # 2 full pages
        base = eng.generate([prompt], max_new_tokens=6, top_k=0)
        assert eng.pool.spill_lru(sync=True) >= 2
        outs = eng.generate([prompt], max_new_tokens=6, top_k=0)
        assert outs == base                        # resurrected, not
        ps = eng.pool.stats()                      # re-prefilled
        assert ps['tier_resurrected_pages_total'] >= 2
        assert ps['tier_resurrected_tokens_total'] >= 16
        assert ps['tier_fetched_pages_total'] >= 2
        assert ps['tier_fetched_bytes_total'] > 0
        # trace schema v6: engine-scope spill + per-request resurrect
        paths = eng.export_trace(jsonl_path=str(tmp_path / 't.jsonl'))
        header, events = load_trace(paths['jsonl'])
        assert header['schema'] == 'paddle_tpu.serve_trace/6'
        spills = [e for e in events if e['event'] == 'spill']
        assert spills and all(e['req'] == -1 for e in spills)
        res = [e for e in events if e['event'] == 'resurrect']
        assert res and res[0]['pages'] >= 2
        table = reconstruct(events)
        assert sum(r['resurrected_tokens']
                   for r in table.values()) >= 16
        assert sum(r['resurrected_pages']
                   for r in table.values()) >= 2
        # ledger ordered-clamp identity holds with the page_stream
        # component carrying the transfer wall
        a = eng.ledger.account()
        assert a['components']['page_stream'] > 0
        assert sum(a['components'].values()) \
            == pytest.approx(a['wall_seconds'])
        eng.shutdown()

    def test_no_spill_config_is_inert(self, tiny_lm, prompts,
                                      monkeypatch):
        # a tierless engine and a tier-enabled engine that never
        # spills must match PR-19 exactly: same compiled step shapes,
        # same host-sync count, zero transfers; the tierless gauge
        # set carries no tier series at all
        counts = [0]
        real = engine_mod._host_fetch

        def counting(x):
            counts[0] += 1
            return real(x)
        monkeypatch.setattr(engine_mod, '_host_fetch', counting)
        runs = {}
        for name, kw in (('plain', {}),
                         ('tiered', dict(host_tier_pages=32))):
            counts[0] = 0
            eng = ServingEngine(tiny_lm, ServingConfig(
                page_size=8, max_batch_size=4, prefill_chunk=8,
                seed=11, **kw))
            outs = eng.generate(prompts, max_new_tokens=8, top_k=0)
            runs[name] = (outs, counts[0],
                          sorted(map(str, eng._step_fns.keys())),
                          dict(eng.pool.stats()))
            eng.shutdown()
        (o1, n1, shapes1, ps1), (o2, n2, shapes2, ps2) = \
            runs['plain'], runs['tiered']
        assert o1 == o2
        assert n1 == n2                    # zero extra host syncs
        assert shapes1 == shapes2          # same compiled shapes
        assert 'tier_host_pages' not in ps1
        assert ps2['tier_spilled_pages_total'] == 0
        assert ps2['tier_fetched_pages_total'] == 0
        assert ps2['tier_host_used_pages'] == 0

    def test_tierless_gauge_set_matches_pr19(self, tiny_lm):
        monitor.metrics().reset()
        eng = ServingEngine(tiny_lm, ServingConfig(
            page_size=8, max_batch_size=2, prefill_chunk=8))
        eng.generate([[1, 2, 3]], max_new_tokens=4, top_k=0)
        eng.publish_metrics()
        from paddle_tpu.serving.metrics import (scalar_series,
                                                serve_snapshot)
        snap = serve_snapshot()
        assert snap and not any('tier' in k for k in snap)
        assert not any('tier' in m.name
                       for m in monitor.metrics().metrics_list())
        assert not any('tier' in k
                       for k in scalar_series(eng.stats()))
        eng.shutdown()

    def test_spill_pressure_feeds_degrade_ladder(self):
        from paddle_tpu.serving.scheduler import DegradeLadder
        lad = DegradeLadder(window=2)
        # spill pressure alone (tier nearly full) can drive the
        # signal even when the device pool looks healthy
        p = lad.pressure_of(0.2, 0, 4, spill=0.95)
        assert p == pytest.approx(0.95)
        assert lad.pressure_of(0.2, 0, 4) == pytest.approx(0.2)


# ---------------------------------------------------------------------------
# cluster: router prefetch hint warms the replica's host tier
# ---------------------------------------------------------------------------
class TestClusterPrefetchHint:
    def test_router_hint_warms_host_tier_e2e(self, tiny_lm):
        from paddle_tpu.serving.cluster import (ClusterRouter,
                                                LocalReplica)
        kw = dict(page_size=8, max_batch_size=3, prefill_chunk=16,
                  host_tier_pages=16, seed=11)
        reps = [LocalReplica(
            ServingEngine(tiny_lm, ServingConfig(**kw)), rid)
            for rid in ('r0', 'r1')]
        router = ClusterRouter(reps, page_size=8, max_queue=32)
        shared = list(range(1, 20))                # 2+ pages shared
        prompts = [shared + [50 + i] for i in range(4)]
        outs = router.serve(prompts, max_new_tokens=4, top_k=0)
        # everything parked spills to host on both replicas
        for r in reps:
            r.engine.pool.spill_lru(sync=True)
        resurrected0 = [r.engine.pool.stats()
                        ['tier_resurrected_pages_total'] for r in reps]
        outs2 = router.serve(prompts, max_new_tokens=4, top_k=0)
        assert outs2 == outs
        snap = router.snapshot()
        assert snap['placements']['prefetch_hint'] > 0
        assert snap['prefetch_warmed_pages'] > 0
        # the hint resurrected pages on the affinity replica BEFORE
        # its requests arrived (warm_prefix parks them cached)
        warmed = [r.engine.pool.stats()
                  ['tier_resurrected_pages_total'] - b
                  for r, b in zip(reps, resurrected0)]
        assert sum(warmed) >= snap['prefetch_warmed_pages'] > 0
        from paddle_tpu.serving.cluster.router import cluster_snapshot
        cs = cluster_snapshot()
        assert cs.get('ptpu_route_prefetch_hints_total', 0) > 0
        router.shutdown()

    def test_hint_is_advisory_on_tierless_replica(self, tiny_lm):
        from paddle_tpu.serving.cluster import (ClusterRouter,
                                                LocalReplica)
        kw = dict(page_size=8, max_batch_size=3, prefill_chunk=16,
                  seed=11)
        reps = [LocalReplica(
            ServingEngine(tiny_lm, ServingConfig(**kw)), rid)
            for rid in ('r0', 'r1')]
        router = ClusterRouter(reps, page_size=8, max_queue=32)
        shared = list(range(1, 20))
        prompts = [shared + [50 + i] for i in range(3)]
        outs = router.serve(prompts, max_new_tokens=4, top_k=0)
        outs2 = router.serve(prompts, max_new_tokens=4, top_k=0)
        assert outs2 == outs
        snap = router.snapshot()
        # hints fire on affinity placements but warm nothing -- and
        # nothing breaks
        assert snap['prefetch_warmed_pages'] == 0
        assert reps[0].prefetch(shared) == {'warmed_pages': 0}
        router.shutdown()
