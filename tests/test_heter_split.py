"""Heter program split: host sparse segments + TPU dense segments
(VERDICT r2 #7).

Reference parity: trainer_pass.py find_heter_ops:441 segmentation tests +
the heterPS wide&deep convergence pattern — the split run must be
loss-IDENTICAL to the monolithic model (same math, different placement).
"""
import numpy as np
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle
import paddle_tpu.static as static
from paddle_tpu.static import heter_pass as H
from paddle_tpu.static.program import Parameter, device_guard
from paddle_tpu.static.backward import append_backward
from paddle_tpu.core.native import NativeSparseTable


@pytest.fixture(autouse=True)
def _static_mode():
    paddle.enable_static()
    yield
    paddle.disable_static()


VOCAB, DIM = 50, 8


def _build_split_program():
    main = static.Program()
    with static.program_guard(main):
        ids = static.data('ids', [16], dtype='int64')
        dense_x = static.data('dense_x', [16, 4])
        label = static.data('label', [16, 1])
        emb = H.distributed_lookup(ids, table_id=0, dim=DIM)   # host
        h = static.nn.fc(paddle.concat([emb, dense_x], axis=1), 16,
                         activation='relu')
        pred = static.nn.fc(h, 1)
        loss = paddle.mean((pred - label) * (pred - label))
    return main, emb, loss


class TestSegmentation:
    def test_find_heter_ops_segments_by_device(self):
        main, emb, loss = _build_split_program()
        segments, heter_ops, default_ops = H.find_heter_ops(main)
        devs = [d for d, _ in segments]
        assert devs[0] == 'cpu'            # lookup opens a host segment
        assert 'tpu' in devs               # dense tower on device
        assert 'cpu' in heter_ops
        assert all(op.type == 'distributed_lookup'
                   for op in heter_ops['cpu'][0])

    def test_wire_sparse_grads_appends_push(self):
        main, emb, loss = _build_split_program()
        params = main.all_parameters()
        append_backward(loss, parameter_list=params + [emb])
        n = H.wire_sparse_grads(main)
        assert n == 1
        push = [op for op in main.global_block().ops
                if op.type == 'distributed_push']
        assert len(push) == 1
        assert push[0].op_device == 'cpu'
        assert push[0].input_names[0] == 'ids'


class TestLossParity:
    def _data(self, steps=15, seed=0):
        rng = np.random.RandomState(seed)
        w_emb = (rng.rand(VOCAB, DIM).astype('float32') - 0.5) * 0.2
        batches = []
        for _ in range(steps):
            ids = rng.randint(0, VOCAB, (16,)).astype('int64')
            dense = rng.rand(16, 4).astype('float32')
            label = rng.rand(16, 1).astype('float32')
            batches.append((ids, dense, label))
        return w_emb, batches

    def test_split_matches_monolithic(self):
        """wide_deep-style model end-to-end through the heter split ==
        the monolithic model, step for step (SGD both sides)."""
        lr = 0.1
        w_emb, batches = self._data()

        # ---- split run: PS table (host) + jitted dense tower ----------
        paddle.seed(42)
        main, emb, loss = _build_split_program()
        params = main.all_parameters()
        pg = append_backward(loss, parameter_list=params + [emb])
        opt = paddle.optimizer.SGD(learning_rate=lr)
        main._optimizer = opt
        opt._append_optimize_ops(
            main, [(p, g) for p, g in pg if isinstance(p, Parameter)])
        H.wire_sparse_grads(main)

        table = NativeSparseTable(DIM, optimizer='sgd', seed=9)
        table.set(np.arange(VOCAB, dtype=np.int64), w_emb)
        runner = H.HeterProgramRunner(
            main, H.InProcessPsAdapter({0: table}))
        scope = static.Scope()
        split_losses = []
        with static.scope_guard(scope):
            for ids, dense, label in batches:
                out = runner.run({'ids': ids, 'dense_x': dense,
                                  'label': label}, [loss], lr=lr)
                split_losses.append(float(out[0]))

        # ---- monolithic oracle: same params, in-process embedding -----
        paddle.seed(42)          # identical dense init
        mono = static.Program()
        with static.program_guard(mono):
            ids_v = static.data('ids', [16], dtype='int64')
            dense_x = static.data('dense_x', [16, 4])
            label_v = static.data('label', [16, 1])
            emb_p = mono.global_block().create_parameter(
                name='emb_w', shape=[VOCAB, DIM], dtype='float32')
            emb_v = paddle.gather(emb_p, ids_v)
            h = static.nn.fc(paddle.concat([emb_v, dense_x], axis=1), 16,
                             activation='relu')
            pred = static.nn.fc(h, 1)
            loss_m = paddle.mean((pred - label_v) * (pred - label_v))
            opt_m = paddle.optimizer.SGD(learning_rate=lr)
            opt_m.minimize(loss_m)
        exe = static.Executor()
        scope_m = static.Scope()
        mono_losses = []
        with static.scope_guard(scope_m):
            scope_m.set('emb_w', jnp.asarray(w_emb))
            for ids, dense, label in batches:
                r = exe.run(mono, feed={'ids': ids, 'dense_x': dense,
                                        'label': label},
                            fetch_list=[loss_m])
                mono_losses.append(float(r[0]))

        np.testing.assert_allclose(split_losses, mono_losses, rtol=2e-4,
                                   atol=1e-6)
        assert split_losses[-1] < split_losses[0]   # actually trains
