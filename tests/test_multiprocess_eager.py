"""True 2-process eager collective tests (parity: test_dist_base.py:744 —
launch trainer subprocesses on localhost, compare losses vs the
single-process run). These pin the r1-VERDICT weak #3 fix: eager
multi-process grad sync does REAL cross-process work through the TCPStore
host backend, and a multi-process eager collective with no backend raises
instead of silently no-opping."""
import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn

HERE = os.path.dirname(os.path.abspath(__file__))


def _free_port():
    s = socket.socket()
    s.bind(('127.0.0.1', 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _launch(rank, ws, port, script):
    env = dict(os.environ)
    env.update({
        'PADDLE_TRAINER_ID': str(rank),
        'PADDLE_TRAINERS_NUM': str(ws),
        'PADDLE_MASTER': f'127.0.0.1:{port}',
        'JAX_PLATFORMS': 'cpu',
    })
    env.pop('XLA_FLAGS', None)
    return subprocess.Popen(
        [sys.executable, '-u', os.path.join(HERE, 'dist_models', script)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True)


class TestEagerMultiProcess:
    def test_two_process_dp_matches_single(self):
        """2-process DataParallel == single-process full-batch training:
        the average of the rank losses equals the full-batch loss and
        both ranks march in lockstep."""
        port = _free_port() - 7   # backend adds +7
        procs = [_launch(r, 2, port, 'dist_eager_dp.py') for r in range(2)]
        outs = []
        for p in procs:
            out, _ = p.communicate(timeout=300)
            assert p.returncode == 0, out
            outs.append(out)
        rank_losses = []
        for out in outs:
            line = [l for l in out.splitlines()
                    if l.startswith('LOSSES:')][-1]
            rank_losses.append(json.loads(line[len('LOSSES:'):]))

        # single-process reference on the full batch
        paddle.seed(7)
        model = nn.Sequential(
            nn.Linear(4, 16), nn.ReLU(), nn.Linear(16, 1))
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=model.parameters())
        rng = np.random.RandomState(0)
        xs = rng.rand(16, 4).astype('float32')
        ys = (xs @ rng.rand(4, 1).astype('float32') + 0.1).astype('float32')
        x, y = paddle.to_tensor(xs), paddle.to_tensor(ys)
        ref = []
        for _ in range(20):
            pred = model(x)
            loss = ((pred - y) * (pred - y)).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
            ref.append(float(loss))

        avg = [(a + b) / 2 for a, b in zip(*rank_losses)]
        np.testing.assert_allclose(avg, ref, rtol=1e-4, atol=1e-5)

    def test_eager_collective_without_backend_raises(self):
        """world_size>1 with no host backend must raise, not silently
        no-op (the r1 silent 1/N-scaled-grads bug)."""
        import paddle_tpu.distributed as dist
        from paddle_tpu.distributed import collective as C
        from paddle_tpu.distributed import host_collectives as HC
        saved = dict(os.environ)
        try:
            os.environ['PADDLE_TRAINER_ID'] = '0'
            os.environ['PADDLE_TRAINERS_NUM'] = '2'
            os.environ.pop('PADDLE_MASTER', None)
            os.environ.pop('PADDLE_TRAINER_ENDPOINTS', None)
            assert HC.host_group() is None
            t = paddle.to_tensor(np.ones(4, 'float32'))
            with pytest.raises(RuntimeError):
                C.all_reduce(t)
        finally:
            os.environ.clear()
            os.environ.update(saved)
