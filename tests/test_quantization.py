"""Quantization slim-lite: fake-quant numerics, QAT, static pass golden,
int8 export.

Reference parity: test_fake_quantize_op.py (numpy-oracle op checks),
test_quantization_pass.py (golden rewrite), test_imperative_qat.py
(LeNet QAT accuracy survives), post_training_quantization int8 export.
"""
import os
import tempfile

import numpy as np
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle
from paddle_tpu.core.tensor import Tensor
from paddle_tpu import quantization as Q


def _t(a):
    return Tensor(jnp.asarray(a))


def np_qdq(a, s, bits=8):
    bin_cnt = 2 ** (bits - 1) - 1
    s = max(s, 1e-8)
    return np.round(np.clip(a, -s, s) * (bin_cnt / s)) * (s / bin_cnt)


class TestFakeQuantOps:
    def test_abs_max_matches_numpy(self):
        rng = np.random.RandomState(0)
        a = (rng.randn(4, 6) * 3).astype('float32')
        out, scale = Q.fake_quantize_dequantize_abs_max(_t(a))
        s = np.max(np.abs(a))
        np.testing.assert_allclose(float(scale), s, rtol=1e-6)
        np.testing.assert_allclose(np.asarray(out.data), np_qdq(a, s),
                                   rtol=1e-5, atol=1e-6)

    def test_channel_wise_matches_numpy(self):
        rng = np.random.RandomState(1)
        a = (rng.randn(3, 5) * 2).astype('float32')
        for axis in (0, 1):
            out, scales = \
                Q.fake_channel_wise_quantize_dequantize_abs_max(
                    _t(a), quant_axis=axis)
            s = np.max(np.abs(a), axis=1 - axis)
            np.testing.assert_allclose(np.asarray(scales.data), s,
                                       rtol=1e-6)
            exp = np.stack([np_qdq(np.take(a, i, axis), s[i])
                            for i in range(a.shape[axis])], axis=axis)
            np.testing.assert_allclose(np.asarray(out.data), exp,
                                       rtol=1e-5, atol=1e-6)

    def test_moving_average_state(self):
        rng = np.random.RandomState(2)
        a1 = rng.randn(8).astype('float32')
        a2 = (rng.randn(8) * 2).astype('float32')
        st = _t(np.zeros((), 'float32'))
        out1, st1 = Q.fake_quantize_dequantize_moving_average_abs_max(
            _t(a1), st, moving_rate=0.9)
        # first batch: state was 0 → scale = cur
        np.testing.assert_allclose(float(st1), np.max(np.abs(a1)),
                                   rtol=1e-6)
        out2, st2 = Q.fake_quantize_dequantize_moving_average_abs_max(
            _t(a2), st1, moving_rate=0.9)
        exp = 0.9 * float(st1) + 0.1 * np.max(np.abs(a2))
        np.testing.assert_allclose(float(st2), exp, rtol=1e-6)
        # eval mode: state unchanged
        _, st3 = Q.fake_quantize_dequantize_moving_average_abs_max(
            _t(a2), st2, training=False)
        np.testing.assert_allclose(float(st3), float(st2))

    def test_straight_through_gradient(self):
        a = np.array([-5.0, -0.5, 0.2, 3.0], 'float32')
        x = _t(a)
        x.stop_gradient = False
        out, scale = Q.fake_quantize_dequantize_abs_max(x)
        loss = paddle.sum(out)
        loss.backward()
        # STE: all inside |x| <= s (s == 5) → grad ones
        np.testing.assert_allclose(np.asarray(x.grad.data),
                                   np.ones(4), rtol=1e-6)

    def test_int8_roundtrip(self):
        rng = np.random.RandomState(3)
        a = (rng.randn(6, 4) * 1.7).astype('float32')
        q, s = Q.quantize_to_int8(a, quant_axis=1)
        assert q.dtype == np.int8
        back = Q.dequantize_from_int8(q, s, quant_axis=1)
        assert np.max(np.abs(back - a)) < np.max(np.abs(a)) / 100


class TestQuantNumerics:
    """ISSUE-7 coverage for the (previously dormant) op numerics: STE
    gradients against finite differences, moving-average scale-state
    round-trip, and the int8 export inverse bound."""

    def test_ste_gradient_matches_finite_difference(self):
        # STE says d(fake_qdq)/dx == 1 inside the clip range, 0 outside.
        # The true function is a staircase, so finite-difference with a
        # step MUCH larger than one quantization bin (s/127) recovers
        # the envelope slope the STE claims. A fixed sentinel (4.0)
        # pins the abs-max scale so perturbing other elements never
        # moves it.
        a = np.array([4.0, -3.3, 0.3, -1.7, 2.2, 1.5, 0.0,
                      -0.01], 'float32')
        x = _t(a)
        x.stop_gradient = False
        out, scale = Q.fake_quantize_dequantize_abs_max(x)
        paddle.sum(out).backward()
        analytic = np.asarray(x.grad.data)
        # h spans ~16 bins (bin = 4/127 ~ 0.03), so the staircase FD
        # quantizes the slope to multiples of bin/2h ~ 0.03; x +- h
        # stays inside the clip range for every perturbed element
        h = 0.5
        fd = np.zeros_like(a)
        for i in range(1, len(a)):        # skip the scale sentinel
            ap, am = a.copy(), a.copy()
            ap[i] += h
            am[i] -= h
            op, _ = Q.fake_quantize_dequantize_abs_max(_t(ap))
            om, _ = Q.fake_quantize_dequantize_abs_max(_t(am))
            fd[i] = (float(paddle.sum(op)) - float(paddle.sum(om))) \
                / (2 * h)
        np.testing.assert_allclose(analytic[1:], fd[1:], atol=0.05)
        assert analytic[0] == 1.0         # sentinel inside clip range

    def test_channel_wise_ste_gradient_matches_finite_difference(self):
        # per-channel scales: same envelope argument, one sentinel per
        # channel row (quant_axis=0)
        a = np.array([[4.0, -1.3, 0.7, 2.2],
                      [8.0, 3.1, -5.5, 0.4]], 'float32')
        x = _t(a)
        x.stop_gradient = False
        out, _ = Q.fake_channel_wise_quantize_dequantize_abs_max(
            x, quant_axis=0)
        paddle.sum(out).backward()
        analytic = np.asarray(x.grad.data)
        h = 0.5
        for (i, j) in ((0, 1), (0, 2), (1, 1), (1, 2), (1, 3)):
            ap, am = a.copy(), a.copy()
            ap[i, j] += h
            am[i, j] -= h
            op, _ = Q.fake_channel_wise_quantize_dequantize_abs_max(
                _t(ap), quant_axis=0)
            om, _ = Q.fake_channel_wise_quantize_dequantize_abs_max(
                _t(am), quant_axis=0)
            fd = (float(paddle.sum(op)) - float(paddle.sum(om))) \
                / (2 * h)
            np.testing.assert_allclose(analytic[i, j], fd, atol=0.08,
                                       err_msg=f'({i},{j})')

    def test_moving_average_state_roundtrip(self):
        # the EMA scale is an ordinary buffer: exporting it to numpy
        # and rebuilding the Tensor must continue the schedule exactly
        rng = np.random.RandomState(5)
        batches = [rng.randn(16).astype('float32') * (1 + k)
                   for k in range(6)]
        st_cont = _t(np.zeros((), 'float32'))
        for b in batches:
            _, st_cont = \
                Q.fake_quantize_dequantize_moving_average_abs_max(
                    _t(b), st_cont, moving_rate=0.9)
        st_rt = _t(np.zeros((), 'float32'))
        for k, b in enumerate(batches):
            _, st_rt = \
                Q.fake_quantize_dequantize_moving_average_abs_max(
                    _t(b), st_rt, moving_rate=0.9)
            if k == 2:   # checkpoint round-trip mid-schedule
                st_rt = _t(np.asarray(st_rt.data).copy())
        np.testing.assert_allclose(float(st_rt), float(st_cont),
                                   rtol=1e-6)

    def test_int8_inverse_within_half_bin(self):
        # |dequant(quant(a)) - a| <= scale/(2*127) elementwise — the
        # tightest bound symmetric round-to-nearest can promise
        rng = np.random.RandomState(7)
        a = (rng.randn(32, 24) * 2.5).astype('float32')
        for axis in (None, 0, 1):
            q, s = Q.quantize_to_int8(a, quant_axis=axis)
            back = Q.dequantize_from_int8(q, s, quant_axis=axis)
            step = np.asarray(s, np.float32) / 127.0
            if axis is None:
                bound = np.full_like(a, step / 2)
            else:
                shape = [1, 1]
                shape[axis] = a.shape[axis]
                bound = np.broadcast_to(step.reshape(shape) / 2,
                                        a.shape)
            assert (np.abs(back - a) <= bound + 1e-7).all(), axis


class TestStaticQuantPass:
    def test_golden_rewrite(self):
        import paddle_tpu.static as static
        paddle.enable_static()
        try:
            main = static.Program()
            with static.program_guard(main):
                x = static.data('x', [4, 8])
                y = static.nn.fc(x, 3)
                out = paddle.mean(y)
            before = [op.type for op in main.global_block().ops]
            n = Q.QuantizationTransformPass().apply(main)
            after = [op.type for op in main.global_block().ops]
            # matmul_v2 has two float inputs (x, w) → 2 quant ops inserted
            # immediately before it
            assert n == 2
            assert after.count('fake_quantize_dequantize_abs_max') == 2
            mm = after.index('matmul_v2')
            assert after[mm - 1] == 'fake_quantize_dequantize_abs_max'
            assert after[mm - 2] == 'fake_quantize_dequantize_abs_max'
            assert [t for t in after
                    if t != 'fake_quantize_dequantize_abs_max'] == before
            mm_op = next(op for op in main.global_block().ops
                         if op.type == 'matmul_v2')
            assert all(i.endswith('.quantized') for i in mm_op.input_names)
            # rewritten program still executes
            exe = static.Executor()
            with static.scope_guard(static.Scope()):
                r = exe.run(main,
                            feed={'x': np.ones((4, 8), 'float32')},
                            fetch_list=[out])
            assert np.isfinite(r[0]).all()
        finally:
            paddle.disable_static()


class TestQATLeNet:
    def _data(self, n=256):
        rng = np.random.RandomState(0)
        # synthetic 2-class 'images': class mean patterns + noise
        y = rng.randint(0, 2, n)
        x = rng.randn(n, 1, 28, 28).astype('float32') * 0.3
        x[y == 1, :, 7:21, 7:21] += 1.0
        return x, y.astype('int64')

    def _acc(self, model, x, y):
        model.eval()
        logits = model(_t(x))
        pred = np.argmax(np.asarray(logits.data), -1)
        model.train()
        return float((pred == y).mean())

    def test_lenet_qat_accuracy_survives(self):
        from paddle_tpu.vision.models import LeNet
        paddle.seed(0)
        x, y = self._data()
        model = LeNet(num_classes=2)
        opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                    parameters=model.parameters())

        def steps(k):
            for i in range(k):
                b = slice((i * 32) % 224, (i * 32) % 224 + 32)
                loss = paddle.nn.functional.cross_entropy(
                    model(_t(x[b])), _t(y[b]))
                loss.backward()
                opt.step()
                opt.clear_grad()

        steps(20)
        acc_fp32 = self._acc(model, x, y)
        assert acc_fp32 > 0.9
        # QAT wrap + brief fine-tune
        Q.ImperativeQuantAware().quantize(model)
        steps(10)
        acc_qat = self._acc(model, x, y)
        assert acc_qat >= acc_fp32 - 0.05, (acc_fp32, acc_qat)

    def test_int8_export_predictions_close(self):
        from paddle_tpu.vision.models import LeNet
        paddle.seed(1)
        x, y = self._data(64)
        model = LeNet(num_classes=2)
        model.eval()
        ref = np.asarray(model(_t(x[:8])).data)
        d = tempfile.mkdtemp()
        path = os.path.join(d, 'lenet_int8')
        Q.export_quantized_layer(path, model, [_t(x[:8])])
        pred = Q.load_quantized_predictor(path)
        out = np.asarray(pred.run(_t(x[:8])))
        # int8 weight quantization: predictions close, argmax identical
        assert np.max(np.abs(out - ref)) < 0.15 * max(np.max(np.abs(ref)),
                                                      1.0)
        np.testing.assert_array_equal(np.argmax(out, -1),
                                      np.argmax(ref, -1))
