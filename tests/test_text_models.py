"""Text model families (hapi sentiment/bow example parity):
LSTM classifier with padding-robust pooling + bag-of-embeddings."""
import numpy as np
import pytest
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.core.tensor import Tensor


def _t(a):
    return Tensor(jnp.asarray(a))


class TestTextModels:
    def _toy_text(self, n=128, T=16, seed=0):
        """Synthetic sentiment: class 1 iff 'positive' tokens (<50)
        outnumber 'negative' ones (>=50); 0 is padding."""
        rng = np.random.RandomState(seed)
        ids = rng.randint(1, 100, (n, T)).astype('int64')
        ids[:, T - 4:] = 0                      # padded tail
        y = ((ids < 50) & (ids > 0)).sum(1) > ((ids >= 50).sum(1))
        return ids, y.astype('int64')

    @pytest.mark.slow   # ~70s convergence run: run_tests.sh tiers
    def test_lstm_sentiment_trains(self):
        from paddle_tpu.text import LSTMSentiment
        paddle.seed(5)
        ids, y = self._toy_text()
        m = LSTMSentiment(vocab_size=100, embed_dim=16, hidden=16,
                          direction='bidirect')
        opt = paddle.optimizer.Adam(learning_rate=5e-3,
                                    parameters=m.parameters())
        losses = []
        for _ in range(30):
            logits = m(_t(ids))
            loss = paddle.nn.functional.cross_entropy(logits, _t(y))
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss))
        assert losses[-1] < 0.6 * losses[0], (losses[0], losses[-1])
        pred = np.argmax(np.asarray(m(_t(ids)).data), -1)
        assert (pred == y).mean() > 0.8

    def test_bow_classifier_trains(self):
        from paddle_tpu.text import BoWClassifier
        paddle.seed(6)
        ids, y = self._toy_text(seed=1)
        m = BoWClassifier(vocab_size=100, embed_dim=16)
        opt = paddle.optimizer.Adam(learning_rate=1e-2,
                                    parameters=m.parameters())
        for _ in range(40):
            loss = paddle.nn.functional.cross_entropy(m(_t(ids)), _t(y))
            loss.backward()
            opt.step()
            opt.clear_grad()
        pred = np.argmax(np.asarray(m(_t(ids)).data), -1)
        assert (pred == y).mean() > 0.85
