"""Interleaved virtual-stage pipeline schedule (ISSUE 14;
arXiv:2104.04473, docs/performance.md#pipeline-schedules).

Covers: round-robin chunk partitioning + uneven-layer/accumulate-step
rejection, virtual-stage knob resolution (kwarg / PTPU_PP_VIRTUAL /
PipelineLayer(num_virtual_pipeline_stages=)), v=2 == v=1 equivalence on
the 8-device mesh (pp2 and dp2xpp2, stash + recompute memory modes,
GradScaler found-inf path, remat-policy composition, sync_model
cross-restore v2<->v1), the static bubble model + ptpu_pp_* census, the
named batch-validation errors, and a true 2-rank subprocess leg.
"""
import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.distributed import topology_runtime
from paddle_tpu.distributed.fleet.meta_parallel.spmd_pipeline import (
    SpmdPipelineEngine, PipelineScheduleError, PipelineBatchError,
    chunk_layer_order, schedule_model, publish_schedule_gauges,
    pipeline_snapshot, resolve_virtual_stages, engine_from_pipeline_layer)
from paddle_tpu.models.gpt import GPTConfig, build_gpt_pipeline

TINY = dict(vocab_size=64, hidden_size=16, num_layers=4, num_heads=2,
            max_seq_len=32, hidden_dropout=0.0, attn_dropout=0.0,
            use_flash_attention=False)


def _reset():
    import paddle_tpu.distributed.fleet as fleet_mod
    fleet_mod.fleet._hcg = None


def _data(n, vocab=64, seq=32, seed=7):
    ids = np.random.RandomState(seed).randint(
        0, vocab, (n, seq)).astype('int32')
    return ids, np.roll(ids, -1, 1).astype('int32')


def _build(schedule='1F1B', v=None, memory_mode='stash', dp=1, pp=2,
           A=4, opt_name='adam', num_layers=4, use_remat=False,
           remat_policy=None, seed=11):
    _reset()
    paddle.seed(seed)
    topology_runtime.build_mesh(['dp', 'pp'], [dp, pp])
    cfg = GPTConfig(**{**TINY, 'num_layers': num_layers})
    embed, blocks, head = build_gpt_pipeline(cfg)
    opt = (paddle.optimizer.Adam(learning_rate=3e-3, parameters=[])
           if opt_name == 'adam'
           else paddle.optimizer.SGD(learning_rate=0.05, parameters=[]))
    eng = SpmdPipelineEngine(embed, blocks, head, opt,
                             accumulate_steps=A, use_remat=use_remat,
                             schedule=schedule, virtual_stages=v,
                             memory_mode=memory_mode,
                             remat_policy=remat_policy)
    return eng, blocks


def _run(steps=3, scale=None, **kw):
    """Train, sync back, return (losses, per-LAYER param dict) — the
    layer-indexed view is stacking-order independent, so it compares
    across schedules."""
    eng, blocks = _build(**kw)
    dp, A = kw.get('dp', 1), kw.get('A', 4)
    ids, labels = _data(dp * A * 2)
    losses = [float(eng.train_batch((Tensor(ids), Tensor(labels)),
                                    scale=scale))
              for _ in range(steps)]
    eng.sync_model()
    params = {f'{i}/{n}': np.asarray(p.data)
              for i, b in enumerate(blocks)
              for n, p in b.named_parameters()}
    for n, p in eng.embed.named_parameters():
        params[f'embed/{n}'] = np.asarray(p.data)
    for n, p in eng.head.named_parameters():
        params[f'head/{n}'] = np.asarray(p.data)
    eng.shutdown()
    return losses, params


def _assert_bit_identical(a, b, what=''):
    la, pa = a
    lb, pb = b
    assert la == lb, f'{what} losses differ: {la} vs {lb}'
    for k in pa:
        np.testing.assert_array_equal(pa[k], pb[k],
                                      err_msg=f'{what} param {k}')


class TestChunkPartition:
    def test_round_robin_assignment(self):
        # L=8, pp=2, v=2: chunks g=c*pp+s -> stage 0 holds layers
        # [0,1] (chunk 0) + [4,5] (chunk 2), stage 1 holds [2,3]+[6,7]
        assert chunk_layer_order(8, 2, 2) == [0, 1, 4, 5, 2, 3, 6, 7]
        assert chunk_layer_order(8, 4, 2) == [0, 4, 1, 5, 2, 6, 3, 7]
        # v=1 is the identity (existing schedules unchanged)
        assert chunk_layer_order(8, 4, 1) == list(range(8))
        # a permutation: every layer exactly once
        assert sorted(chunk_layer_order(12, 2, 3)) == list(range(12))

    def test_uneven_layers_rejected(self):
        with pytest.raises(PipelineScheduleError, match='round-robin'):
            chunk_layer_order(6, 2, 2)
        with pytest.raises(PipelineScheduleError, match='non-empty'):
            _build(schedule='interleaved', v=2, num_layers=2, pp=2)

    def test_accumulate_steps_must_divide_pp(self):
        # microbatches advance in groups of pp per chunk
        with pytest.raises(PipelineScheduleError,
                           match='accumulate_steps'):
            _build(schedule='interleaved', v=2, A=3, pp=2)

    def test_fthenb_refuses_virtual_stages(self):
        with pytest.raises(PipelineScheduleError, match='F-then-B'):
            _build(schedule='F-then-B', v=2)

    def test_1f1b_auto_upgrades_to_interleaved(self):
        eng, _ = _build(schedule='1F1B', v=2)
        assert eng.schedule == 'interleaved' and eng.vp == 2
        eng.shutdown()

    def test_env_resolution(self, monkeypatch):
        monkeypatch.setenv('PTPU_PP_VIRTUAL', '2')
        assert resolve_virtual_stages() == 2
        # kwarg wins over env
        assert resolve_virtual_stages(1) == 1
        eng, _ = _build(schedule='1F1B')
        assert eng.schedule == 'interleaved' and eng.vp == 2
        eng.shutdown()
        monkeypatch.setenv('PTPU_PP_VIRTUAL', 'nope')
        with pytest.raises(PipelineScheduleError, match='PTPU_PP_VIRTUAL'):
            resolve_virtual_stages()

    def test_pipeline_layer_wiring(self):
        """PipelineLayer(num_virtual_pipeline_stages=) reaches the
        engine (it was accepted-and-dropped before ISSUE 14)."""
        from paddle_tpu.distributed.fleet.meta_parallel import (
            LayerDesc, PipelineLayer)
        from paddle_tpu.models.gpt import (GPTEmbeddings, GPTDecoderLayer,
                                           GPTLMHead)
        _reset()
        topology_runtime.build_mesh(['dp', 'pp'], [1, 2])
        paddle.seed(0)
        cfg = GPTConfig(**TINY)
        pipe = PipelineLayer(
            [LayerDesc(GPTEmbeddings, cfg)]
            + [LayerDesc(GPTDecoderLayer, cfg) for _ in range(4)],
            loss_fn=GPTLMHead(cfg), num_virtual_pipeline_stages=2)
        assert pipe._num_virtual_pipeline_stages == 2
        opt = paddle.optimizer.SGD(learning_rate=0.05, parameters=[])
        eng = engine_from_pipeline_layer(pipe, opt, accumulate_steps=4)
        assert eng.schedule == 'interleaved' and eng.vp == 2
        eng.shutdown()
        # a value the block run cannot honor names the knob loudly
        pipe3 = PipelineLayer(
            [LayerDesc(GPTEmbeddings, cfg)]
            + [LayerDesc(GPTDecoderLayer, cfg) for _ in range(4)],
            loss_fn=GPTLMHead(cfg), num_virtual_pipeline_stages=3)
        with pytest.raises(PipelineScheduleError, match='chunks'):
            engine_from_pipeline_layer(pipe3, opt, accumulate_steps=4)
        with pytest.raises(ValueError, match='>= 1'):
            PipelineLayer([LayerDesc(GPTDecoderLayer, cfg)],
                          loss_fn=GPTLMHead(cfg),
                          num_virtual_pipeline_stages=0)


class TestBatchValidation:
    def test_batch_not_divisible_named_error(self):
        eng, _ = _build(A=4)
        ids, labels = _data(7)
        with pytest.raises(PipelineBatchError, match='accumulate_steps'):
            eng.train_batch((Tensor(ids), Tensor(labels)))
        eng.shutdown()

    def test_label_mismatch_named_error(self):
        eng, _ = _build(A=4)
        ids, labels = _data(8)
        with pytest.raises(PipelineBatchError, match='disagree'):
            eng.train_batch((Tensor(ids), Tensor(labels[:4])))
        eng.shutdown()


class TestBubbleModel:
    def test_1f1b_closed_forms(self):
        m = schedule_model('1F1B', 4, 8)
        assert m['ticks'] == 8 + 2 * 3
        assert m['slots_per_chunk'] == 7          # min(A, 2pp-1)
        assert m['inflight_peak'] == 7
        assert abs(m['bubble_fraction'] - 3 / 11) < 1e-12
        # slot census matches the engine's circular window for a spread
        # of shapes
        for pp, A in ((2, 4), (4, 8), (4, 32), (8, 8)):
            assert schedule_model('1F1B', pp, A)['slots_per_chunk'] \
                == min(A, 2 * pp - 1), (pp, A)

    def test_interleaved_closed_forms(self):
        m = schedule_model('interleaved', 4, 8, 2)
        D = 2 * 3 + 1 * 4
        assert m['ticks'] == 8 * 2 + D
        assert abs(m['bubble_fraction'] - 3 / 19) < 1e-12
        # v=1 degenerates to the 1F1B table
        m1 = schedule_model('interleaved', 4, 8, 1)
        ref = schedule_model('1F1B', 4, 8)
        assert {k: v for k, v in m1.items() if k != 'schedule'} \
            == {k: v for k, v in ref.items() if k != 'schedule'}

    def test_bubble_monotone_in_v(self):
        for pp, A in ((2, 4), (4, 8)):
            fracs = [schedule_model('interleaved', pp, A, v)
                     ['bubble_fraction'] for v in (1, 2, 4)]
            assert fracs[0] > fracs[1] > fracs[2], (pp, A, fracs)

    def test_gauge_round_trip(self):
        m = schedule_model('interleaved', 2, 4, 2)
        publish_schedule_gauges(m, engine='pipeline')
        snap = pipeline_snapshot()
        assert snap['schedule'] == 'interleaved'
        assert snap['virtual_stages'] == 2
        assert snap['ticks'] == m['ticks']
        assert abs(snap['bubble_fraction'] - m['bubble_fraction']) < 1e-9


class TestInterleavedEquivalence:
    """fp32 bit-identity bars for the v=2 interleaved schedule vs the
    v=1 1F1B baseline: the tick table only reorders WHEN each (chunk,
    microbatch) job runs; per-parameter contributions accumulate in the
    same ascending-microbatch order, so stash-mode results are
    BIT-identical. The recompute mode re-runs each chunk's forward
    inside the backward: XLA fuses that per-chunk subgraph differently
    from the per-stage one (different dot tilings), so params carry
    ~1-ulp fp32 reassociation noise — the PR-12 finding; losses stay
    bit-identical."""

    def test_pp2_stash_bit_identical(self):
        base = _run(schedule='1F1B')
        got = _run(schedule='interleaved', v=2)
        _assert_bit_identical(base, got, 'pp2 stash')
        assert base[0][-1] < base[0][0]       # it actually trains

    def test_dp2_pp2_stash_bit_identical(self):
        base = _run(schedule='1F1B', dp=2)
        got = _run(schedule='interleaved', v=2, dp=2)
        _assert_bit_identical(base, got, 'dp2xpp2 stash')

    def test_pp2_recompute_loss_bit_identical(self):
        base = _run(schedule='1F1B', memory_mode='recompute')
        got = _run(schedule='interleaved', v=2, memory_mode='recompute')
        assert base[0] == got[0], (base[0], got[0])
        for k in base[1]:
            np.testing.assert_allclose(
                base[1][k], got[1][k], rtol=5e-5, atol=1e-8,
                err_msg=f'recompute param {k}')

    @pytest.mark.slow
    def test_sgd_recompute_step_bit_identical(self):
        # one SGD step has no rsqrt amplification: fully bit-identical
        base = _run(schedule='1F1B', memory_mode='recompute',
                    opt_name='sgd', steps=1)
        got = _run(schedule='interleaved', v=2, memory_mode='recompute',
                   opt_name='sgd', steps=1)
        _assert_bit_identical(base, got, 'pp2 sgd recompute')

    @pytest.mark.slow
    def test_scaler_path_bit_identical(self):
        base = _run(schedule='1F1B', scale=1024.0)
        got = _run(schedule='interleaved', v=2, scale=1024.0)
        _assert_bit_identical(base, got, 'pp2 scaled')

    def test_scaler_found_inf_skips_update(self):
        # a loss scale that overflows the fp32 grads must trip
        # found_inf and skip the update on BOTH schedules (an inf scale
        # makes the overflow deterministic on this tiny model)
        for sched, v in (('1F1B', None), ('interleaved', 2)):
            eng, blocks = _build(schedule=sched, v=v)
            ids, labels = _data(8)
            before = {n: np.asarray(p.data).copy()
                      for n, p in blocks[0].named_parameters()}
            eng.train_batch((Tensor(ids), Tensor(labels)),
                            scale=float('inf'))
            assert bool(np.asarray(eng.last_found_inf)), sched
            eng.sync_model()
            for n, p in blocks[0].named_parameters():
                np.testing.assert_array_equal(
                    before[n], np.asarray(p.data),
                    err_msg=f'{sched}: update not skipped for {n}')
            eng.shutdown()

    @pytest.mark.slow
    def test_remat_policy_composes(self):
        base = _run(schedule='1F1B', use_remat=True,
                    remat_policy='attn_mlp_boundaries')
        got = _run(schedule='interleaved', v=2, use_remat=True,
                   remat_policy='attn_mlp_boundaries')
        _assert_bit_identical(base, got, 'pp2 attn_mlp_boundaries')

    @pytest.mark.slow
    def test_sync_model_cross_restore_v2_v1(self):
        """Train under one schedule, sync_model, rebuild the engine
        under the other and continue: the round-robin stacking maps
        back to the same per-layer weights, so both continuation
        orders land on identical losses and params."""
        def train_then_continue(first, second):
            eng, blocks = _build(**first)
            ids, labels = _data(8)
            data = (Tensor(ids), Tensor(labels))
            l0 = [float(eng.train_batch(data)) for _ in range(2)]
            eng.sync_model()
            eng.shutdown()
            # rebuild on the SAME trained layers (no reseed)
            opt = paddle.optimizer.Adam(learning_rate=3e-3,
                                        parameters=[])
            eng2 = SpmdPipelineEngine(
                eng.embed, blocks, eng.head, opt, accumulate_steps=4,
                use_remat=False, **second)
            l1 = [float(eng2.train_batch(data))]
            eng2.sync_model()
            params = {f'{i}/{n}': np.asarray(p.data)
                      for i, b in enumerate(blocks)
                      for n, p in b.named_parameters()}
            eng2.shutdown()
            return l0 + l1, params

        v2_to_v1 = train_then_continue(
            dict(schedule='interleaved', v=2),
            dict(schedule='1F1B'))
        v1_to_v2 = train_then_continue(
            dict(schedule='1F1B'),
            dict(schedule='interleaved', virtual_stages=2))
        v1_to_v1 = train_then_continue(
            dict(schedule='1F1B'), dict(schedule='1F1B'))
        _assert_bit_identical(v2_to_v1, v1_to_v1, 'v2->v1')
        _assert_bit_identical(v1_to_v2, v1_to_v1, 'v1->v2')

    def test_engine_publishes_schedule_census(self):
        eng, _ = _build(schedule='interleaved', v=2)
        snap = pipeline_snapshot()
        assert snap['schedule'] == 'interleaved' \
            and snap['virtual_stages'] == 2
        m = eng._sched_model
        assert snap['ticks'] == m['ticks']
        assert snap['bubble_fraction'] < \
            schedule_model('1F1B', 2, 4)['bubble_fraction']
        # telemetry surfaces the same census
        from paddle_tpu.profiler import StepTelemetry
        tel = StepTelemetry(publish=False).snapshot()
        assert tel['pipeline'] and \
            tel['pipeline']['schedule'] == 'interleaved'
        eng.shutdown()


@pytest.mark.slow
class TestTwoRank:
    def test_two_rank_subprocess_equivalence(self):
        """True 2-rank pp mesh in a fresh process: interleaved v=2 ==
        1F1B bit-identical + bubble census (dist_pipeline_sched.py)."""
        script = os.path.join(os.path.dirname(__file__), 'dist_models',
                              'dist_pipeline_sched.py')
        env = dict(os.environ)
        env.pop('XLA_FLAGS', None)
        p = subprocess.run([sys.executable, '-u', script],
                           capture_output=True, text=True, timeout=600,
                           env=env)
        assert p.returncode == 0, \
            f'STDOUT:\n{p.stdout}\nSTDERR:\n{p.stderr}'
        assert 'BIT-IDENTICAL' in p.stdout
