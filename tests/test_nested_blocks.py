"""Nested-block Program IR: conditional_block / while ops with sub-blocks.

Reference parity: framework.proto BlockDesc:178 nesting +
operators/controlflow/conditional_block_op.cc / while_op.cc — recorded
Programs carry data-dependent control flow, execute through the Executor,
and round-trip through serialization in a fresh process.
"""
import os
import subprocess
import sys
import tempfile

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.static as static
from paddle_tpu.static import control_flow as CF


@pytest.fixture(autouse=True)
def _static_mode():
    paddle.enable_static()
    yield
    paddle.disable_static()


def _build_cond_program():
    main = static.Program()
    with static.program_guard(main):
        x = static.data('x', [4])
        flag = static.data('flag', [], dtype='bool')
        out = CF.cond(flag, lambda: x * 2.0, lambda: x - 1.0)
    return main, out


def _build_while_program():
    main = static.Program()
    with static.program_guard(main):
        x = static.data('x', [3])
        n = static.data('n', [], dtype='int32')
        i0 = paddle.zeros([], dtype='int32')
        i, acc = CF.while_loop(lambda i, a: i < n,
                               lambda i, a: [i + 1, a + x],
                               [i0, x * 0.0])
    return main, i, acc


class TestRecordedControlFlow:
    def test_cond_records_sub_blocks(self):
        main, out = _build_cond_program()
        ops = main.global_block().ops
        cb = next(op for op in ops if op.type == 'conditional_block')
        assert main.num_blocks >= 3
        tb = main.blocks[cb.attrs['sub_block_true']]
        fb = main.blocks[cb.attrs['sub_block_false']]
        assert any(o.type for o in tb.ops) and any(o.type for o in fb.ops)
        assert tb.parent_idx == 0 and fb.parent_idx == 0
        # captured outer var listed as input (pruning keeps producers)
        assert 'x' in cb.input_names

    def test_cond_executes_both_ways(self):
        main, out = _build_cond_program()
        exe = static.Executor()
        x = np.array([1.0, 2.0, 3.0, 4.0], 'float32')
        with static.scope_guard(static.Scope()):
            r_t = exe.run(main, feed={'x': x, 'flag': np.array(True)},
                          fetch_list=[out])
            r_f = exe.run(main, feed={'x': x, 'flag': np.array(False)},
                          fetch_list=[out])
        np.testing.assert_allclose(r_t[0], x * 2.0)
        np.testing.assert_allclose(r_f[0], x - 1.0)

    def test_while_executes(self):
        main, i, acc = _build_while_program()
        exe = static.Executor()
        x = np.array([1.0, 0.5, -2.0], 'float32')
        with static.scope_guard(static.Scope()):
            r = exe.run(main, feed={'x': x, 'n': np.array(5, 'int32')},
                        fetch_list=[i, acc])
        assert int(r[0]) == 5
        np.testing.assert_allclose(r[1], 5 * x)

    def test_backward_through_control_flow_raises_clearly(self):
        main = static.Program()
        with static.program_guard(main):
            x = static.data('x', [2, 4])
            y = static.nn.fc(x, 4)
            flag = static.data('flag', [], dtype='bool')
            out = CF.cond(flag, lambda: y * 2.0, lambda: y * 3.0)
            loss = paddle.mean(out)
            opt = paddle.optimizer.SGD(learning_rate=0.1)
            with pytest.raises(NotImplementedError,
                               match='conditional_block'):
                opt.minimize(loss)


class TestSerializationRoundTrip:
    def _roundtrip_in_fresh_process(self, build, feeds, fetch_idx):
        """Serialize here; deserialize + run in a subprocess; compare."""
        from paddle_tpu.static.serialization import serialize_program
        main, *fetches = build()
        data = serialize_program(main)
        d = tempfile.mkdtemp()
        path = os.path.join(d, 'prog.pdmodel')
        with open(path, 'wb') as f:
            f.write(data)
        fetch_names = [fetches[i].name for i in fetch_idx]

        # run locally for the oracle
        exe = static.Executor()
        with static.scope_guard(static.Scope()):
            ref = exe.run(main, feed=dict(feeds),
                          fetch_list=[fetches[i] for i in fetch_idx])

        feed_reprs = {k: (v.tolist(), str(v.dtype))
                      for k, v in feeds.items()}
        script = f"""
import sys; sys.path.insert(0, {repr(os.getcwd())})
import os
os.environ['JAX_PLATFORMS'] = 'cpu'
import numpy as np
import paddle_tpu as paddle
import paddle_tpu.static as static
from paddle_tpu.static.serialization import deserialize_program
paddle.enable_static()
with open({repr(path)}, 'rb') as f:
    prog = deserialize_program(f.read())
feeds = {{k: np.asarray(v, d) for k, (v, d) in {feed_reprs!r}.items()}}
exe = static.Executor()
with static.scope_guard(static.Scope()):
    out = exe.run(prog, feed=feeds, fetch_list={fetch_names!r})
for o in out:
    print(repr(np.asarray(o).tolist()))
"""
        res = subprocess.run([sys.executable, '-c', script],
                             capture_output=True, text=True, timeout=300)
        assert res.returncode == 0, res.stderr[-2000:]
        lines = [l for l in res.stdout.strip().splitlines() if l]
        got = [np.asarray(eval(l)) for l in lines[-len(fetch_idx):]]
        for g, r in zip(got, ref):
            np.testing.assert_allclose(g, np.asarray(r), rtol=1e-5,
                                       atol=1e-6)

    def test_while_program_roundtrip(self):
        self._roundtrip_in_fresh_process(
            _build_while_program,
            {'x': np.array([1.0, 0.5, -2.0], 'float32'),
             'n': np.array(4, 'int32')},
            fetch_idx=[0, 1])

    def test_cond_program_roundtrip(self):
        self._roundtrip_in_fresh_process(
            _build_cond_program,
            {'x': np.array([1.0, 2.0, 3.0, 4.0], 'float32'),
             'flag': np.array(True)},
            fetch_idx=[0])


class TestDy2StaticLowering:
    def test_converted_fn_records_control_flow_ops(self):
        """A @to_static-converted function with data-dependent if/while
        records conditional_block/while ops when traced into a Program —
        so dy2static output exports via save_inference_model."""
        from paddle_tpu.jit.dy2static import convert_function
        from paddle_tpu.core.tensor import Tensor

        def f(x, n):
            acc = x * 0.0
            i = paddle.zeros([], dtype='int32')
            while i < n:
                acc = acc + x
                i = i + 1
            if paddle.sum(acc) > 0:
                acc = acc * 2.0
            else:
                acc = acc - 1.0
            return acc

        conv = convert_function(f)
        main = static.Program()
        with static.program_guard(main):
            x = static.data('x', [3])
            n = static.data('n', [], dtype='int32')
            out = conv(x, n)
        types = [op.type for op in main.global_block().ops]
        assert 'while' in types and 'conditional_block' in types, types
        exe = static.Executor()
        xv = np.array([1.0, 2.0, 3.0], 'float32')
        with static.scope_guard(static.Scope()):
            r = exe.run(main, feed={'x': xv, 'n': np.array(3, 'int32')},
                        fetch_list=[out])
        np.testing.assert_allclose(r[0], xv * 3 * 2)

    def test_dy2static_control_flow_exports_inference_model(self):
        from paddle_tpu.jit.dy2static import convert_function

        def f(x, n):
            acc = x * 0.0
            i = paddle.zeros([], dtype='int32')
            while i < n:
                acc = acc + x
                i = i + 1
            return acc

        conv = convert_function(f)
        main = static.Program()
        with static.program_guard(main):
            x = static.data('x', [3])
            n = static.data('n', [], dtype='int32')
            out = conv(x, n)
        exe = static.Executor()
        scope = static.Scope()
        d = tempfile.mkdtemp()
        path = os.path.join(d, 'model')
        with static.scope_guard(scope):
            static.save_inference_model(path, [x, n], [out], exe,
                                        program=main, scope=scope)
        prog2, feed_names, fetch_names = \
            static.load_inference_model(path, exe)
        assert set(feed_names) == {'x', 'n'}
        xv = np.array([2.0, -1.0, 0.5], 'float32')
        with static.scope_guard(static.Scope()):
            r = exe.run(prog2, feed={'x': xv, 'n': np.array(4, 'int32')},
                        fetch_list=fetch_names)
        np.testing.assert_allclose(r[0], xv * 4)
