"""End-to-end training tests (reference pattern: fluid/tests/book/ —
train a few iterations, assert loss decreases)."""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.io import DataLoader
from paddle_tpu.vision.datasets import MNIST
from paddle_tpu.vision.models import LeNet


def test_mnist_lenet_converges():
    """BASELINE config 1 (recognize_digits parity)."""
    paddle.seed(0)
    model = LeNet(num_classes=10)
    opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                parameters=model.parameters())
    ds = MNIST(mode='train')
    loader = DataLoader(ds, batch_size=64, shuffle=True)
    losses = []
    for i, (img, label) in enumerate(loader):
        if i >= 12:
            break
        logits = model(img)
        loss = nn.functional.cross_entropy(logits, label.squeeze(-1))
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    assert np.mean(losses[-3:]) < np.mean(losses[:3])


def test_jitted_trainstep_mlp():
    """Whole-step jit (forward+backward+adam fused into one XLA program)."""
    from paddle_tpu.jit import TrainStep
    paddle.seed(1)
    net = nn.Sequential(nn.Flatten(), nn.Linear(784, 128), nn.ReLU(),
                        nn.Linear(128, 10))
    opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                parameters=net.parameters())

    def loss_fn(model, x, y):
        return nn.functional.cross_entropy(model(x), y)

    step = TrainStep(net, loss_fn, opt)
    rng = np.random.RandomState(0)
    xs = rng.rand(64, 1, 28, 28).astype('float32')
    ys = rng.randint(0, 10, 64)
    losses = [float(step(paddle.to_tensor(xs), paddle.to_tensor(ys)))
              for _ in range(15)]
    assert losses[-1] < losses[0]
    # sync back into the eager layer and check eval consistency
    step.sync_model()
    out = net(paddle.to_tensor(xs))
    assert out.shape == [64, 10]


def test_hapi_model_fit():
    from paddle_tpu.hapi import Model
    from paddle_tpu.metric import Accuracy
    paddle.seed(2)
    net = nn.Sequential(nn.Flatten(), nn.Linear(784, 32), nn.ReLU(),
                        nn.Linear(32, 10))
    model = Model(net)
    model.prepare(
        optimizer=paddle.optimizer.Adam(learning_rate=1e-3,
                                        parameters=net.parameters()),
        loss=nn.CrossEntropyLoss(),
        metrics=Accuracy())
    ds = MNIST(mode='train')
    model.fit(ds, epochs=1, batch_size=64, verbose=0, num_iters=8)
    res = model.evaluate(MNIST(mode='test'), batch_size=64, verbose=0)
    assert 'loss' in res and 'acc' in res


def test_save_load_checkpoint_resume():
    import tempfile
    import os
    net = nn.Linear(4, 2)
    opt = paddle.optimizer.Adam(parameters=net.parameters())
    loss = net(paddle.randn([4, 4])).sum()
    loss.backward()
    opt.step()
    d = tempfile.mkdtemp()
    paddle.save(net.state_dict(), os.path.join(d, 'm.pdparams'))
    paddle.save(opt.state_dict(), os.path.join(d, 'm.pdopt'))

    net2 = nn.Linear(4, 2)
    opt2 = paddle.optimizer.Adam(parameters=net2.parameters())
    net2.set_state_dict(paddle.load(os.path.join(d, 'm.pdparams')))
    opt2.set_state_dict(paddle.load(os.path.join(d, 'm.pdopt')))
    np.testing.assert_allclose(net2.weight.numpy(), net.weight.numpy())
    assert opt2._step_count == 1
