"""Distributed engine tests on the 8-device virtual CPU mesh (SURVEY.md §4:
multi-controller simulation replaces the reference's 2-process NCCL
subprocess tests, strictly stronger for CI)."""
import numpy as np
import pytest
import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.distributed import topology_runtime
from paddle_tpu.distributed.fleet.meta_parallel.hybrid_engine import (
    HybridParallelTrainStep)


def make_mlp(seed=0, mp_layers=False):
    paddle.seed(seed)
    if mp_layers:
        from paddle_tpu.distributed.fleet.meta_parallel import (
            ColumnParallelLinear, RowParallelLinear)
        from paddle_tpu.distributed.collective import new_group

        class MLP(nn.Layer):
            def __init__(self):
                super().__init__()
                g = new_group(list(range(4)), axis_name='mp')
                self.fc1 = ColumnParallelLinear(8, 16, gather_output=False,
                                                mp_group=g)
                self.fc2 = RowParallelLinear(16, 8, input_is_parallel=True,
                                             mp_group=g)
                self.out = nn.Linear(8, 1)

            def forward(self, x):
                return self.out(paddle.tanh(self.fc2(self.fc1(x))))
        return MLP()
    return nn.Sequential(nn.Linear(8, 16), nn.Tanh(), nn.Linear(16, 8),
                         nn.Tanh(), nn.Linear(8, 1))


def mse_loss_fn(model, x, y):
    return nn.functional.mse_loss(model(x), y)


BATCH = 16
RNG = np.random.RandomState(0)
X = RNG.randn(BATCH, 8).astype('float32')
Y = RNG.randn(BATCH, 1).astype('float32')


def run_steps(engine, n=5):
    losses = []
    for _ in range(n):
        losses.append(float(engine(Tensor(X), Tensor(Y))))
    return losses


def baseline_losses(seed=0, n=5, lr=0.1):
    """Single-device eager reference."""
    net = make_mlp(seed)
    opt = paddle.optimizer.SGD(learning_rate=lr,
                               parameters=net.parameters())
    losses = []
    for _ in range(n):
        loss = mse_loss_fn(net, Tensor(X), Tensor(Y))
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    return losses


class TestHybridEngine:
    def test_dp_matches_single_device(self):
        """dp=8 SPMD step == single-device training on the same global
        batch (allreduce-mean of shard grads == full-batch grad)."""
        topology_runtime.build_mesh(['dp'], [8])
        net = make_mlp(0)
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=net.parameters())
        eng = HybridParallelTrainStep(net, mse_loss_fn, opt)
        got = run_steps(eng)
        ref = baseline_losses(0)
        np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)

    def test_zero_sharding_matches_dp(self):
        """dp=2 × sharding=4 (ZeRO-1 reduce-scatter/all-gather update) must
        produce identical training to plain dp."""
        topology_runtime.build_mesh(['dp', 'sharding'], [2, 4])
        net = make_mlp(0)
        opt = paddle.optimizer.Adam(learning_rate=0.01,
                                    parameters=net.parameters())
        eng = HybridParallelTrainStep(net, mse_loss_fn, opt)
        got = run_steps(eng)

        topology_runtime.build_mesh(['dp'], [8])
        net2 = make_mlp(0)
        opt2 = paddle.optimizer.Adam(learning_rate=0.01,
                                     parameters=net2.parameters())
        eng2 = HybridParallelTrainStep(net2, mse_loss_fn, opt2)
        ref = run_steps(eng2)
        np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)

    def test_sharding_axis_is_data_parallel(self):
        """ZeRO ranks ARE dp ranks (dygraph_sharding_optimizer.py:27): the
        batch must be sharded over ('dp','sharding') so sharding_degree=k
        scales per-step throughput — not replicate compute k times."""
        topology_runtime.build_mesh(['dp', 'sharding'], [2, 4])
        net = make_mlp(0)
        opt = paddle.optimizer.Adam(learning_rate=0.01,
                                    parameters=net.parameters())
        eng = HybridParallelTrainStep(net, mse_loss_fn, opt)
        eng(Tensor(X), Tensor(Y))
        for spec in eng._batch_specs:
            assert spec[0] == ('dp', 'sharding'), spec
        # each device sees BATCH/(dp*sharding) rows, not BATCH/dp
        from jax.sharding import NamedSharding
        ns = NamedSharding(eng.mesh, eng._batch_specs[0])
        assert ns.shard_shape(X.shape) == (BATCH // 8, 8)

    def test_tp_matches_dense(self):
        """mp=4 TP layers (column→row with explicit collectives) match the
        dense equivalent run on one device."""
        import paddle_tpu.distributed.fleet as fleet_mod
        topology_runtime.build_mesh(['dp', 'mp'], [2, 4])
        net = make_mlp(1, mp_layers=True)
        dense = make_mlp(1)
        # copy TP weights into dense equivalent
        dense[0].weight.set_value(net.fc1.weight)
        dense[0].bias.set_value(net.fc1.bias)
        dense[2].weight.set_value(net.fc2.weight)
        dense[2].bias.set_value(net.fc2.bias)
        dense[4].weight.set_value(net.out.weight)
        dense[4].bias.set_value(net.out.bias)

        class DenseNet(nn.Layer):
            def __init__(self):
                super().__init__()
                self.seq = dense

            def forward(self, x):
                return self.seq[4](paddle.tanh(
                    nn.functional.linear(
                        nn.functional.linear(x, self.seq[0].weight,
                                             self.seq[0].bias),
                        self.seq[2].weight, self.seq[2].bias)))

        opt = paddle.optimizer.SGD(learning_rate=0.05,
                                   parameters=net.parameters())
        eng = HybridParallelTrainStep(net, mse_loss_fn, opt)
        got = run_steps(eng)

        dn = DenseNet()
        opt2 = paddle.optimizer.SGD(learning_rate=0.05,
                                    parameters=dn.parameters())
        ref = []
        for _ in range(5):
            loss = mse_loss_fn(dn, Tensor(X), Tensor(Y))
            loss.backward()
            opt2.step()
            opt2.clear_grad()
            ref.append(float(loss))
        np.testing.assert_allclose(got, ref, rtol=1e-3, atol=1e-4)


class TestSpmdPipeline:
    def _data(self, config, dp, A, mb):
        rng = np.random.RandomState(7)
        n = dp * A * mb
        ids = rng.randint(0, config.vocab_size, (n, 32)).astype('int32')
        labels = np.roll(ids, -1, axis=1).astype('int32')
        return ids, labels

    def test_pp_dp_mp_gpt_trains(self):
        """GPT-tiny on dp=2 × pp=2 × mp=2: one compiled step, loss falls."""
        from paddle_tpu.models.gpt import GPTConfig, build_gpt_pipeline
        from paddle_tpu.distributed.fleet.meta_parallel.spmd_pipeline \
            import SpmdPipelineEngine
        import paddle_tpu.distributed.fleet as fleet_mod
        from paddle_tpu.distributed.fleet.base.topology import (
            CommunicateTopology, HybridCommunicateGroup)
        import os
        os.environ['PADDLE_TRAINER_ID'] = '0'

        topology_runtime.build_mesh(['dp', 'pp', 'mp'], [2, 2, 2])
        # minimal hcg so mp_layers see mp degree 2
        topo = CommunicateTopology(["data", "pipe", "sharding", "model"],
                                   [2, 2, 1, 2])
        fleet_mod.fleet._topology = topo
        fleet_mod.fleet._hcg = HybridCommunicateGroup(topo)
        topology_runtime.build_mesh(['dp', 'pp', 'mp'], [2, 2, 2])

        paddle.seed(3)
        config = GPTConfig(vocab_size=128, hidden_size=32, num_layers=4,
                           num_heads=4, max_seq_len=64, hidden_dropout=0.0,
                           attn_dropout=0.0, use_flash_attention=False)
        embed, blocks, head = build_gpt_pipeline(config)
        opt = paddle.optimizer.Adam(learning_rate=3e-3, parameters=[])
        eng = SpmdPipelineEngine(embed, blocks, head, opt,
                                 accumulate_steps=2, use_remat=True)
        ids, labels = self._data(config, dp=2, A=2, mb=2)
        losses = []
        for _ in range(8):
            losses.append(float(eng.train_batch((Tensor(ids),
                                                 Tensor(labels)))))
        assert losses[-1] < losses[0], losses
        fleet_mod.fleet._hcg = None

    def test_pp_matches_single_stage(self):
        """pp=2 pipelined schedule == pp=1 on identical weights/data."""
        from paddle_tpu.models.gpt import GPTConfig, build_gpt_pipeline
        from paddle_tpu.distributed.fleet.meta_parallel.spmd_pipeline \
            import SpmdPipelineEngine
        import paddle_tpu.distributed.fleet as fleet_mod
        fleet_mod.fleet._hcg = None  # no mp

        config = GPTConfig(vocab_size=64, hidden_size=16, num_layers=2,
                           num_heads=2, max_seq_len=64, hidden_dropout=0.0,
                           attn_dropout=0.0, use_flash_attention=False)
        ids, labels = self._data(config, dp=1, A=2, mb=2)

        def run(pp):
            paddle.seed(11)
            topology_runtime.build_mesh(['dp', 'pp'], [1, pp])
            embed, blocks, head = build_gpt_pipeline(config)
            opt = paddle.optimizer.SGD(learning_rate=0.05, parameters=[])
            eng = SpmdPipelineEngine(embed, blocks, head, opt,
                                     accumulate_steps=2, use_remat=False)
            return [float(eng.train_batch((Tensor(ids), Tensor(labels))))
                    for _ in range(4)]

        l1 = run(1)
        l2 = run(2)
        np.testing.assert_allclose(l1, l2, rtol=2e-4, atol=1e-5)

    def test_1f1b_matches_fthenb(self):
        """1F1B schedule is loss-identical to F-then-B (section_worker.cc
        schedule_mode 1 vs 0 compute the same gradients)."""
        from paddle_tpu.models.gpt import GPTConfig, build_gpt_pipeline
        from paddle_tpu.distributed.fleet.meta_parallel.spmd_pipeline \
            import SpmdPipelineEngine
        import paddle_tpu.distributed.fleet as fleet_mod
        fleet_mod.fleet._hcg = None

        config = GPTConfig(vocab_size=64, hidden_size=16, num_layers=4,
                           num_heads=2, max_seq_len=64, hidden_dropout=0.0,
                           attn_dropout=0.0, use_flash_attention=False)
        ids, labels = self._data(config, dp=2, A=4, mb=2)

        def run(schedule):
            paddle.seed(11)
            topology_runtime.build_mesh(['dp', 'pp'], [2, 4])
            embed, blocks, head = build_gpt_pipeline(config)
            opt = paddle.optimizer.Adam(learning_rate=3e-3, parameters=[])
            eng = SpmdPipelineEngine(embed, blocks, head, opt,
                                     accumulate_steps=4, use_remat=False,
                                     schedule=schedule)
            return [float(eng.train_batch((Tensor(ids), Tensor(labels))))
                    for _ in range(4)]

        np.testing.assert_allclose(run('1F1B'), run('F-then-B'),
                                   rtol=2e-4, atol=1e-5)

    def test_1f1b_memory_bounded_by_pp_not_A(self):
        """VERDICT r1 #3 'done' criterion: compiled temp memory is O(pp)
        under 1F1B (flat as accumulate_steps grows) but O(A) under
        F-then-B (the scan-transposition path stores every tick's
        boundary activation)."""
        import jax
        import jax.numpy as jnp
        from paddle_tpu.models.gpt import GPTConfig, build_gpt_pipeline
        from paddle_tpu.distributed.fleet.meta_parallel.spmd_pipeline \
            import SpmdPipelineEngine
        import paddle_tpu.distributed.fleet as fleet_mod
        fleet_mod.fleet._hcg = None

        config = GPTConfig(vocab_size=128, hidden_size=64, num_layers=8,
                           num_heads=4, max_seq_len=64, hidden_dropout=0.0,
                           attn_dropout=0.0, use_flash_attention=False)

        def temp_bytes(schedule, A, memory_mode='stash'):
            paddle.seed(5)
            topology_runtime.build_mesh(['dp', 'pp'], [2, 4])
            embed, blocks, head = build_gpt_pipeline(config)
            opt = paddle.optimizer.SGD(learning_rate=0.01, parameters=[])
            eng = SpmdPipelineEngine(embed, blocks, head, opt,
                                     accumulate_steps=A, use_remat=True,
                                     schedule=schedule,
                                     memory_mode=memory_mode)
            rng = np.random.RandomState(0)
            ids = jnp.asarray(rng.randint(0, 128, (2 * A * 2, 32)),
                              jnp.int32)
            comp = eng._build().lower(
                eng._params, eng._states, jnp.asarray(0.01, jnp.float32),
                jnp.asarray(1.0, jnp.float32), jax.random.PRNGKey(0),
                ids, ids).compile()
            return comp.memory_analysis().temp_size_in_bytes

        one_8, one_32 = temp_bytes('1F1B', 8), temp_bytes('1F1B', 32)
        rec_32 = temp_bytes('1F1B', 32, memory_mode='recompute')
        ftb_8, ftb_32 = temp_bytes('F-then-B', 8), temp_bytes('F-then-B', 32)
        # 1F1B: flat in A (buffer is min(A, 2pp-1) slots of residuals)
        assert one_32 < 1.2 * one_8, (one_8, one_32)
        # F-then-B: grows with A
        assert ftb_32 > 1.8 * ftb_8, (ftb_8, ftb_32)
        # at large A, stash-1F1B still uses less scratch than F-then-B
        # (it buffers save-dots residuals per in-flight microbatch)...
        assert one_32 < ftb_32, (one_32, ftb_32)
        # ...and the opt-in recompute mode (stage-input buffer only) uses
        # far less
        assert rec_32 < 0.5 * ftb_32, (rec_32, ftb_32)


class TestCollectiveAPI:
    """Parity: test_collective_base.py pattern — each collective vs numpy,
    inside a shard_map region."""

    def test_allreduce_allgather_inside_spmd(self):
        from jax.sharding import PartitionSpec as P
        from jax.experimental.shard_map import shard_map
        from paddle_tpu.distributed import collective as C
        mesh = topology_runtime.build_mesh(['x'], [8])
        data = np.arange(32, dtype='float32').reshape(8, 4)

        def f(a):
            with C.spmd_region(('x',)):
                t = Tensor(a[0])
                C.all_reduce(t, group=C.new_group(list(range(8)),
                                                  axis_name='x'))
                return t.data[None]
        out = jax.jit(shard_map(f, mesh=mesh, in_specs=P('x'),
                                out_specs=P('x'), check_rep=False))(data)
        ref = data.sum(0)
        for row in np.asarray(out):
            np.testing.assert_allclose(row, ref)

    def test_ppermute_ring(self):
        from jax.sharding import PartitionSpec as P
        from jax.experimental.shard_map import shard_map
        from paddle_tpu.distributed import collective as C
        mesh = topology_runtime.build_mesh(['x'], [8])
        data = np.arange(8, dtype='float32').reshape(8, 1)

        def f(a):
            with C.spmd_region(('x',)):
                t = C.shift(Tensor(a), offset=1)
                return t.data
        out = jax.jit(shard_map(f, mesh=mesh, in_specs=P('x'),
                                out_specs=P('x'), check_rep=False))(data)
        np.testing.assert_allclose(np.asarray(out).ravel(),
                                   np.roll(np.arange(8), 1))


class TestGPTTPParity:
    def test_gpt_mp_matches_dense(self):
        """GPT forward+CE under mp∈{1,2,4} matches the dense eager model
        bit-for-bit-ish (guards the Megatron (head,3,hd) qkv packing)."""
        import os
        import paddle_tpu.distributed.fleet as fm
        from paddle_tpu.distributed.fleet.base.topology import (
            CommunicateTopology, HybridCommunicateGroup)
        from paddle_tpu.models.gpt import (GPTConfig, GPTForCausalLM,
                                           GPTPretrainingCriterion)
        from paddle_tpu.distributed.fleet.meta_parallel.hybrid_engine \
            import HybridParallelTrainStep
        os.environ.setdefault('PADDLE_TRAINER_ID', '0')

        cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=2,
                        num_heads=4, max_seq_len=32, hidden_dropout=0.0,
                        attn_dropout=0.0, use_flash_attention=False)
        rng = np.random.RandomState(0)
        ids = rng.randint(0, 128, (4, 32)).astype('int32')
        lab = np.roll(ids, -1, 1).astype('int32')

        for mp in (2, 4):
            fm.fleet._hcg = None
            paddle.seed(5)
            topo = CommunicateTopology(
                ["data", "pipe", "sharding", "model"], [1, 1, 1, mp])
            fm.fleet._topology = topo
            fm.fleet._hcg = HybridCommunicateGroup(topo)
            topology_runtime.build_mesh(['dp', 'mp'], [1, mp])
            m = GPTForCausalLM(cfg)
            crit = GPTPretrainingCriterion(cfg)
            eng = HybridParallelTrainStep(
                m, lambda mm, i, l: crit(mm(i), l),
                paddle.optimizer.SGD(learning_rate=0.0, parameters=[]))
            l_mp = float(eng(Tensor(ids), Tensor(lab)))
            fm.fleet._hcg = None
            logits = m(Tensor(ids))
            l_dense = float(nn.functional.softmax_with_cross_entropy(
                logits, Tensor(lab)).mean())
            np.testing.assert_allclose(l_mp, l_dense, rtol=1e-5)
        fm.fleet._hcg = None


class TestSequenceParallel:
    """Ring attention / Ulysses — NET-NEW vs the reference (SURVEY.md §5.7)."""

    def test_ring_attention_matches_dense(self):
        from jax.sharding import PartitionSpec as P
        from jax.experimental.shard_map import shard_map
        from paddle_tpu.ops import ring_attention as ra
        from paddle_tpu.ops.pallas.flash_attention import (
            _reference_attention)
        mesh = topology_runtime.build_mesh(['sp'], [8])
        rng = np.random.RandomState(0)
        B, nh, L, hd = 2, 2, 64, 8
        q = rng.randn(B, nh, L, hd).astype('float32')
        k = rng.randn(B, nh, L, hd).astype('float32')
        v = rng.randn(B, nh, L, hd).astype('float32')

        def f(q_, k_, v_):
            return ra._ring_attention_arrays(q_, k_, v_, 'sp', causal=True,
                                             sp=8)
        out = jax.jit(shard_map(f, mesh=mesh,
                                in_specs=(P(None, None, 'sp'),) * 3,
                                out_specs=P(None, None, 'sp'),
                                check_rep=False))(q, k, v)
        ref = _reference_attention(
            jnp.asarray(q).reshape(B * nh, L, hd),
            jnp.asarray(k).reshape(B * nh, L, hd),
            jnp.asarray(v).reshape(B * nh, L, hd),
            causal=True).reshape(B, nh, L, hd)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-5)

    def test_ring_attention_grads_match(self):
        from jax.sharding import PartitionSpec as P
        from jax.experimental.shard_map import shard_map
        from paddle_tpu.ops import ring_attention as ra
        from paddle_tpu.ops.pallas.flash_attention import (
            _reference_attention)
        mesh = topology_runtime.build_mesh(['sp'], [4])
        rng = np.random.RandomState(1)
        B, nh, L, hd = 1, 2, 32, 8
        q = rng.randn(B, nh, L, hd).astype('float32')
        k = rng.randn(B, nh, L, hd).astype('float32')
        v = rng.randn(B, nh, L, hd).astype('float32')

        def loss_ring(q_, k_, v_):
            def inner(qq, kk, vv):
                o = ra._ring_attention_arrays(qq, kk, vv, 'sp', causal=True,
                                              sp=4)
                return jnp.sum(o * o)
            f = shard_map(lambda a, b, c: jnp.array([inner(a, b, c)]),
                          mesh=mesh, in_specs=(P(None, None, 'sp'),) * 3,
                          out_specs=P('sp'), check_rep=False)
            return jnp.sum(f(q_, k_, v_))

        g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)

        def loss_ref(q_, k_, v_):
            o = _reference_attention(q_.reshape(B * nh, L, hd),
                                     k_.reshape(B * nh, L, hd),
                                     v_.reshape(B * nh, L, hd), causal=True)
            return jnp.sum(o * o)
        g_ref = jax.jit(jax.grad(loss_ref, argnums=(0, 1, 2)))(q, k, v)
        for a, b in zip(g_ring, g_ref):
            np.testing.assert_allclose(np.asarray(a),
                                       np.asarray(b).reshape(a.shape),
                                       rtol=5e-4, atol=5e-5)

    def test_ulysses_matches_dense(self):
        from jax.sharding import PartitionSpec as P
        from jax.experimental.shard_map import shard_map
        from paddle_tpu.ops import ring_attention as ra
        from paddle_tpu.ops.pallas.flash_attention import (
            _reference_attention)
        mesh = topology_runtime.build_mesh(['sp'], [4])
        rng = np.random.RandomState(2)
        B, L, nh, hd = 2, 32, 4, 8
        # (head,3,hd) packed qkv
        qkv = rng.randn(B, L, nh * 3 * hd).astype('float32')

        def f(a):
            from paddle_tpu.distributed import collective as C
            with C.spmd_region(('sp',)):
                t = ra.ulysses_attention(Tensor(a), nh, hd, axis_name='sp',
                                         sp=4)
            return t.data
        out = jax.jit(shard_map(f, mesh=mesh, in_specs=P(None, 'sp'),
                                out_specs=P(None, 'sp'),
                                check_rep=False))(qkv)
        x5 = jnp.asarray(qkv).reshape(B, L, nh, 3, hd)
        q = x5[:, :, :, 0].transpose(0, 2, 1, 3).reshape(B * nh, L, hd)
        k = x5[:, :, :, 1].transpose(0, 2, 1, 3).reshape(B * nh, L, hd)
        v = x5[:, :, :, 2].transpose(0, 2, 1, 3).reshape(B * nh, L, hd)
        ref = _reference_attention(q, k, v, causal=True)
        ref = ref.reshape(B, nh, L, hd).transpose(0, 2, 1, 3).reshape(
            B, L, nh * hd)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-5)

    def test_gpt_sequence_parallel_trains(self):
        """GPT under dp=2 × sp=4: sequence dim sharded, ring attention,
        loss matches the dense run and decreases."""
        import os
        import paddle_tpu.distributed.fleet as fm
        from paddle_tpu.models.gpt import (GPTConfig, GPTForCausalLM,
                                           GPTPretrainingCriterion)
        os.environ.setdefault('PADDLE_TRAINER_ID', '0')
        fm.fleet._hcg = None

        cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                        num_heads=4, max_seq_len=64, hidden_dropout=0.0,
                        attn_dropout=0.0, use_flash_attention=False)
        rng = np.random.RandomState(0)
        ids = rng.randint(0, 64, (4, 64)).astype('int32')
        lab = np.roll(ids, -1, 1).astype('int32')

        def run(axes, sizes, lr=0.01, steps=3):
            paddle.seed(7)
            topology_runtime.build_mesh(axes, sizes)
            m = GPTForCausalLM(cfg)
            crit = GPTPretrainingCriterion(cfg)
            opt = paddle.optimizer.Adam(learning_rate=lr, parameters=[])
            eng = HybridParallelTrainStep(
                m, lambda mm, i, l: crit(mm(i), l), opt)
            return [float(eng(Tensor(ids), Tensor(lab)))
                    for _ in range(steps)]

        sp_losses = run(['dp', 'sp'], [2, 4])
        ref_losses = run(['dp'], [2])
        np.testing.assert_allclose(sp_losses, ref_losses, rtol=2e-4)
        assert sp_losses[-1] < sp_losses[0]


class TestPipelineLayerSpmd:
    def test_pipeline_layer_train_batch(self):
        """The dygraph parity path: PipelineLayer (LayerDesc/SharedLayerDesc)
        + fleet.distributed_model + train_batch drives the SPMD engine."""
        import os
        import paddle_tpu.distributed.fleet as fm
        from paddle_tpu.distributed.fleet.base.topology import (
            CommunicateTopology, HybridCommunicateGroup)
        from paddle_tpu.distributed.fleet.meta_parallel import (
            LayerDesc, SharedLayerDesc, PipelineLayer, PipelineParallel)
        from paddle_tpu.models.gpt import (GPTConfig, GPTEmbeddings,
                                           GPTDecoderLayer, GPTLMHead)
        os.environ.setdefault('PADDLE_TRAINER_ID', '0')
        fm.fleet._hcg = None
        topo = CommunicateTopology(["data", "pipe", "sharding", "model"],
                                   [2, 2, 1, 2])
        fm.fleet._topology = topo
        fm.fleet._hcg = HybridCommunicateGroup(topo)
        topology_runtime.build_mesh(['dp', 'pp', 'mp'], [2, 2, 2])

        paddle.seed(0)
        cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=4,
                        num_heads=4, max_seq_len=64, hidden_dropout=0.0,
                        attn_dropout=0.0, use_flash_attention=False)
        head = GPTLMHead(cfg)
        descs = ([LayerDesc(GPTEmbeddings, cfg)]
                 + [LayerDesc(GPTDecoderLayer, cfg) for _ in range(4)])

        # loss_fn is a Layer (GPTLMHead: final norm + vocab head + CE) so
        # the engine lifts its params into the trainable head tree
        pipe = PipelineLayer(descs, loss_fn=head)
        # make the tail's params visible to the engine: append head desc…
        # engine treats trailing non-uniform funcs as the head tail; here
        # the tail is inside loss_fn, so funcs = embed + 4 uniform blocks
        engine_model = PipelineParallel(pipe, fm.fleet._hcg,
                                        strategy=None)
        engine_model.accumulate_steps = 2
        engine_model.micro_batch_size = 2
        opt = paddle.optimizer.Adam(learning_rate=3e-3, parameters=[])
        rng = np.random.RandomState(0)
        ids = rng.randint(0, 128, (8, 32)).astype('int32')
        labels = np.roll(ids, -1, 1).astype('int32')
        losses = [float(engine_model.train_batch(
            (Tensor(ids), Tensor(labels)), opt)) for _ in range(4)]
        assert np.isfinite(losses).all()
        assert losses[-1] < losses[0]
        fm.fleet._hcg = None


    def test_pipeline_layer_state_dict_reflects_training(self):
        """state_dict after train_batch returns TRAINED weights (the engine
        syncs back), and SharedLayerDesc reuse across segments is refused."""
        import os
        import paddle_tpu.distributed.fleet as fm
        from paddle_tpu.distributed.fleet.base.topology import (
            CommunicateTopology, HybridCommunicateGroup)
        from paddle_tpu.distributed.fleet.meta_parallel import (
            LayerDesc, SharedLayerDesc, PipelineLayer, PipelineParallel)
        from paddle_tpu.models.gpt import (GPTConfig, GPTEmbeddings,
                                           GPTDecoderLayer, GPTLMHead)
        os.environ.setdefault('PADDLE_TRAINER_ID', '0')
        fm.fleet._hcg = None
        topo = CommunicateTopology(["data", "pipe", "sharding", "model"],
                                   [1, 2, 1, 1])
        fm.fleet._topology = topo
        fm.fleet._hcg = HybridCommunicateGroup(topo)
        topology_runtime.build_mesh(['dp', 'pp'], [1, 2])
        paddle.seed(0)
        cfg = GPTConfig(vocab_size=64, hidden_size=16, num_layers=2,
                        num_heads=2, max_seq_len=32, hidden_dropout=0.0,
                        attn_dropout=0.0, use_flash_attention=False)
        pipe = PipelineLayer(
            [LayerDesc(GPTEmbeddings, cfg)]
            + [LayerDesc(GPTDecoderLayer, cfg) for _ in range(2)],
            loss_fn=GPTLMHead(cfg))
        model = PipelineParallel(pipe, fm.fleet._hcg, strategy=None)
        model.accumulate_steps = 2
        model.micro_batch_size = 2
        opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=[])
        rng = np.random.RandomState(0)
        ids = rng.randint(0, 64, (4, 32)).astype('int32')
        lab = np.roll(ids, -1, 1).astype('int32')
        model.train_batch((Tensor(ids), Tensor(lab)), opt)
        sd0 = {k: v.numpy().copy() for k, v in model.state_dict().items()}
        model.train_batch((Tensor(ids), Tensor(lab)), opt)
        sd1 = model.state_dict()
        changed = sum(not np.allclose(sd0[k], sd1[k].numpy())
                      for k in sd0)
        assert changed > 0, "state_dict did not reflect training"

        # batch-size contract enforced
        try:
            model.train_batch((Tensor(ids[:3]), Tensor(lab[:3])), opt)
            assert False, "expected batch-size mismatch error"
        except ValueError as e:
            assert 'micro_batch_size' in str(e)

        # tied weights across segments refused
        pipe2 = PipelineLayer(
            [SharedLayerDesc('emb', GPTEmbeddings, config=cfg),
             LayerDesc(GPTDecoderLayer, cfg),
             LayerDesc(GPTDecoderLayer, cfg),
             SharedLayerDesc('emb', GPTEmbeddings, config=cfg)],
            loss_fn=GPTLMHead(cfg))
        m2 = PipelineParallel(pipe2, fm.fleet._hcg, strategy=None)
        m2.accumulate_steps = 2
        m2.micro_batch_size = 2
        import pytest as _pt
        with _pt.raises(NotImplementedError):
            m2.train_batch((Tensor(ids), Tensor(lab)), opt)
        fm.fleet._hcg = None


class TestPipelineGradScaler:
    """fp16 GradScaler through the SPMD pipeline engine (VERDICT r2 #10;
    parity: hybrid_parallel_gradscaler.py — found_inf psum'd inside the
    step, update skipped, dynamic scale driven by the flag)."""

    def _setup(self, pp=2):
        from paddle_tpu.models.gpt import GPTConfig, build_gpt_pipeline
        from paddle_tpu.distributed.fleet.meta_parallel.spmd_pipeline \
            import SpmdPipelineEngine
        import paddle_tpu.distributed.fleet as fleet_mod
        fleet_mod.fleet._hcg = None
        paddle.seed(5)
        config = GPTConfig(vocab_size=64, hidden_size=16, num_layers=2,
                           num_heads=2, max_seq_len=32, hidden_dropout=0.0,
                           attn_dropout=0.0, use_flash_attention=False)
        topology_runtime.build_mesh(['dp', 'pp'], [1, pp])
        embed, blocks, head = build_gpt_pipeline(config)
        opt = paddle.optimizer.SGD(learning_rate=1e-2, parameters=[])
        eng = SpmdPipelineEngine(embed, blocks, head, opt,
                                 accumulate_steps=2, use_remat=False,
                                 schedule='1F1B')
        rng = np.random.RandomState(0)
        ids = rng.randint(0, 64, (2, 32)).astype('int32')
        labels = np.roll(ids, -1, 1).astype('int32')
        return eng, (Tensor(ids), Tensor(labels))

    def test_scaled_step_matches_unscaled(self):
        eng, data = self._setup()
        l0 = float(eng.train_batch(data, scale=1024.0))
        assert not bool(np.asarray(eng.last_found_inf))
        eng2, data2 = self._setup()
        l0u = float(eng2.train_batch(data2))
        np.testing.assert_allclose(l0, l0u, rtol=1e-4)
        # second scaled step: loss decreased (update actually applied,
        # grads correctly unscaled)
        l1 = float(eng.train_batch(data, scale=1024.0))
        l1u = float(eng2.train_batch(data2))
        np.testing.assert_allclose(l1, l1u, rtol=1e-3)
        assert l1 < l0

    def test_overflow_skips_update_and_scaler_backs_off(self):
        from paddle_tpu.amp import GradScaler
        import jax.numpy as jnp
        eng, data = self._setup()
        # poison one embed param with NaN: grads go non-finite, which is
        # exactly what found_inf must catch and the update must skip
        name = next(iter(eng._params['embed']))
        eng._params['embed'][name] = (eng._params['embed'][name]
                                      * jnp.nan)
        params_before = {n: np.asarray(v)
                         for n, v in eng._params['head'].items()}
        loss = eng.train_batch(data, scale=1024.0)
        assert bool(np.asarray(eng.last_found_inf))
        for n, v in eng._params['head'].items():
            np.testing.assert_array_equal(np.asarray(v),
                                          params_before[n])
        # the scaler's dynamic schedule consumes the flag
        scaler = GradScaler(init_loss_scaling=1024.0,
                            decr_every_n_nan_or_inf=1)
        scaler._found_inf = bool(np.asarray(eng.last_found_inf))
        scaler._update()
        assert scaler._scale < 1024.0

    def test_pipeline_layer_train_batch_with_scaler(self):
        """The PipelineParallel FRONT-END drives the scaler end-to-end
        through _train_batch_spmd (the r2 NotImplementedError is gone):
        train_batch(data, optimizer, scaler=...) scales/unscales inside
        the engine and feeds the scaler's dynamic schedule."""
        from paddle_tpu.amp import GradScaler
        from paddle_tpu.models.gpt import (GPTConfig, GPTEmbeddings,
                                           GPTDecoderLayer, GPTLMHead)
        import paddle_tpu.distributed.fleet as fm
        from paddle_tpu.distributed.fleet.meta_parallel import (
            LayerDesc, PipelineLayer, PipelineParallel)
        from paddle_tpu.distributed.fleet.base.topology import (
            CommunicateTopology, HybridCommunicateGroup)
        old_hcg = fm.fleet._hcg
        try:
            topo = CommunicateTopology(
                hybrid_group_names=['data', 'pipe', 'sharding', 'model'],
                dims=[1, 2, 1, 1])
            fm.fleet._hcg = HybridCommunicateGroup(topo)
            topology_runtime.build_mesh(['dp', 'pp'], [1, 2])
            paddle.seed(6)
            config = GPTConfig(vocab_size=64, hidden_size=16,
                               num_layers=2, num_heads=2, max_seq_len=32,
                               hidden_dropout=0.0, attn_dropout=0.0,
                               use_flash_attention=False)
            head = GPTLMHead(config)
            descs = ([LayerDesc(GPTEmbeddings, config)]
                     + [LayerDesc(GPTDecoderLayer, config)
                        for _ in range(2)])
            pipe = PipelineLayer(descs, loss_fn=head)
            model = PipelineParallel(pipe, fm.fleet._hcg, strategy=None)
            model.accumulate_steps = 2
            model.micro_batch_size = 1
            opt = paddle.optimizer.SGD(learning_rate=1e-2, parameters=[])
            scaler = GradScaler(init_loss_scaling=256.0,
                                incr_every_n_steps=2)
            rng = np.random.RandomState(1)
            ids = rng.randint(0, 64, (2, 32)).astype('int32')
            labels = np.roll(ids, -1, 1).astype('int32')
            losses = [
                float(model.train_batch((Tensor(ids), Tensor(labels)),
                                        opt, scaler=scaler))
                for _ in range(3)]
            assert losses[-1] < losses[0]
            assert scaler._scale >= 256.0       # grew (no infs)
            assert not scaler._found_inf
        finally:
            fm.fleet._hcg = old_hcg
