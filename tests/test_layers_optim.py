"""Layer + optimizer tests (reference pattern: unittests/test_layers.py,
test_adam_op.py etc.)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn


def test_linear_shapes_and_state_dict():
    l = nn.Linear(4, 3)
    out = l(paddle.randn([2, 4]))
    assert out.shape == [2, 3]
    sd = l.state_dict()
    assert set(sd) == {'weight', 'bias'}
    l2 = nn.Linear(4, 3)
    l2.set_state_dict(sd)
    np.testing.assert_allclose(l2.weight.numpy(), l.weight.numpy())


def test_sublayer_registration():
    class M(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc1 = nn.Linear(2, 2)
            self.seq = nn.Sequential(nn.Linear(2, 2), nn.ReLU())

        def forward(self, x):
            return self.seq(self.fc1(x))

    m = M()
    names = [n for n, _ in m.named_parameters()]
    assert 'fc1.weight' in names and 'seq.0.weight' in names
    assert len(m.parameters()) == 4


def test_train_eval_propagation():
    m = nn.Sequential(nn.Linear(2, 2), nn.Dropout(0.5))
    m.eval()
    assert not m[1].training
    m.train()
    assert m[1].training


def test_batchnorm_running_stats():
    bn = nn.BatchNorm2D(3, momentum=0.5)
    x = paddle.randn([4, 3, 5, 5]) * 2 + 1
    bn.train()
    bn(x)
    assert not np.allclose(bn._mean.numpy(), 0.0)
    bn.eval()
    y = bn(x)
    assert y.shape == [4, 3, 5, 5]


def test_lstm_forward_backward():
    lstm = nn.LSTM(4, 8, num_layers=2, direction='bidirect')
    x = paddle.randn([2, 5, 4])
    y, (h, c) = lstm(x)
    assert y.shape == [2, 5, 16]
    assert h.shape == [4, 2, 8]
    y.sum().backward()
    assert lstm.weight_ih_l0.grad is not None


def test_transformer_encoder():
    layer = nn.TransformerEncoderLayer(d_model=16, nhead=4,
                                       dim_feedforward=32, dropout=0.0)
    enc = nn.TransformerEncoder(layer, 2)
    x = paddle.randn([2, 6, 16])
    out = enc(x)
    assert out.shape == [2, 6, 16]
    out.sum().backward()


def test_mha_cache_decoding():
    mha = nn.MultiHeadAttention(16, 4)
    q = paddle.randn([2, 1, 16])
    cache = mha.gen_cache(q)
    out, cache = mha(q, q, q, cache=cache)
    assert cache.k.shape[2] == 1
    out, cache = mha(q, q, q, cache=cache)
    assert cache.k.shape[2] == 2


@pytest.mark.parametrize('opt_cls,kw', [
    (paddle.optimizer.SGD, {}),
    (paddle.optimizer.Momentum, {'momentum': 0.9}),
    (paddle.optimizer.Adam, {}),
    (paddle.optimizer.AdamW, {'weight_decay': 0.01}),
    (paddle.optimizer.Adagrad, {}),
    (paddle.optimizer.RMSProp, {}),
    (paddle.optimizer.Lamb, {}),
    (paddle.optimizer.Adamax, {}),
])
def test_optimizer_reduces_loss(opt_cls, kw):
    paddle.seed(0)
    net = nn.Sequential(nn.Linear(4, 16), nn.Tanh(), nn.Linear(16, 1))
    opt = opt_cls(learning_rate=0.05, parameters=net.parameters(), **kw)
    x = paddle.randn([32, 4])
    y = paddle.randn([32, 1])
    first = None
    for i in range(15):
        loss = nn.functional.mse_loss(net(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        if first is None:
            first = float(loss)
    assert float(loss) < first


def test_optimizer_state_dict_roundtrip():
    net = nn.Linear(2, 2)
    opt = paddle.optimizer.Adam(parameters=net.parameters())
    loss = net(paddle.randn([4, 2])).sum()
    loss.backward()
    opt.step()
    sd = opt.state_dict()
    opt2 = paddle.optimizer.Adam(parameters=net.parameters())
    opt2.set_state_dict(sd)
    assert opt2._step_count == opt._step_count


def test_lr_schedulers():
    from paddle_tpu.optimizer import lr
    s = lr.StepDecay(0.1, step_size=2, gamma=0.5)
    vals = []
    for _ in range(5):
        vals.append(s())
        s.step()
    np.testing.assert_allclose(vals, [0.1, 0.1, 0.05, 0.05, 0.025])

    warm = lr.LinearWarmup(0.1, warmup_steps=4, start_lr=0.0, end_lr=0.1)
    got = []
    for _ in range(5):
        got.append(warm())
        warm.step()
    np.testing.assert_allclose(got[:4], [0.0, 0.025, 0.05, 0.075])

    cos = lr.CosineAnnealingDecay(1.0, T_max=10)
    assert abs(cos() - 1.0) < 1e-6

    noam = lr.NoamDecay(d_model=64, warmup_steps=10, learning_rate=1.0)
    noam.step()
    assert noam() > 0


def test_grad_clip_global_norm():
    net = nn.Linear(2, 2)
    clip = nn.ClipGradByGlobalNorm(0.1)
    opt = paddle.optimizer.SGD(learning_rate=1.0,
                               parameters=net.parameters(), grad_clip=clip)
    (net(paddle.ones([4, 2])) * 100).sum().backward()
    before = [p.numpy().copy() for p in net.parameters()]
    opt.step()
    total_move = sum(np.abs(p.numpy() - b).sum()
                     for p, b in zip(net.parameters(), before))
    assert total_move < 0.5  # clipped to 0.1 norm * lr 1.0


def test_amp_autocast_bf16():
    import jax.numpy as jnp
    with paddle.amp.auto_cast(dtype='bfloat16'):
        a = paddle.randn([4, 4])
        b = paddle.randn([4, 4])
        out = paddle.matmul(a, b)
        assert out.dtype == jnp.bfloat16
        s = paddle.nn.functional.softmax(out.astype('float32'))
        assert s.dtype == jnp.float32


def test_grad_scaler():
    net = nn.Linear(2, 2)
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=net.parameters())
    scaler = paddle.amp.GradScaler(init_loss_scaling=2.0)
    loss = net(paddle.ones([2, 2])).sum()
    scaled = scaler.scale(loss)
    scaled.backward()
    scaler.step(opt)
    opt.clear_grad()
    assert scaler._scale >= 2.0


def test_adam_bf16_moment_dtype():
    """VERDICT r5 #1: moment_dtype='bfloat16' stores Adam state
    low-precision (how 1.3B-param AdamW fits one 16G v5e) while the
    update math runs fp32 — numerics must track fp32-moment Adam."""
    import jax.numpy as jnp
    from paddle_tpu.core.tensor import Tensor
    opt_b = paddle.optimizer.AdamW(learning_rate=0.01, parameters=[],
                                   weight_decay=0.01,
                                   multi_precision=False,
                                   moment_dtype='bfloat16')
    opt_f = paddle.optimizer.AdamW(learning_rate=0.01, parameters=[],
                                   weight_decay=0.01,
                                   multi_precision=False)
    rng = np.random.RandomState(0)
    p = jnp.asarray(rng.randn(64), jnp.float32)
    g = jnp.asarray(rng.randn(64), jnp.float32)
    sb, sf = opt_b.init_state(Tensor(p)), opt_f.init_state(Tensor(p))
    assert sb['moment1'].dtype == jnp.bfloat16
    assert sf['moment1'].dtype == jnp.float32
    pb = pf = p
    lr = jnp.float32(0.01)
    for _ in range(5):
        pb, sb = opt_b.update(pb, g, sb, lr)
        pf, sf = opt_f.update(pf, g, sf, lr)
    assert sb['moment1'].dtype == jnp.bfloat16   # stays low-precision
    assert float(jnp.max(jnp.abs(pb - pf))) < 1e-2


def test_eager_step_keeps_bf16_param_dtype():
    """multi_precision=False + bf16 params: the eager step's fp32 update
    math must not upcast the stored params (that would double HBM and
    retrace dtype-keyed jits)."""
    import jax.numpy as jnp
    m = nn.Linear(4, 4)
    for p in m.parameters():
        p.data = p.data.astype(jnp.bfloat16)
    opt = paddle.optimizer.AdamW(parameters=m.parameters(),
                                 multi_precision=False,
                                 moment_dtype='bfloat16')
    x = paddle.to_tensor(np.random.RandomState(0).rand(2, 4)
                         .astype(np.float32))
    m(x).sum().backward()
    opt.step()
    opt.clear_grad()
    for p in m.parameters():
        assert p.data.dtype == jnp.bfloat16
