"""Static AMP pass: golden rewrite assertions + execution parity.

Reference parity: the compile-only rewrite tests of
test_fleet_amp_meta_optimizer.py / fp16_utils.rewrite_program:484 —
assert on the rewritten op list (cast count and positions), then run the
rewritten program and check the bf16 loss tracks fp32.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.static as static
from paddle_tpu.static.amp_pass import (rewrite_program_amp,
                                        AutoMixedPrecisionLists)


@pytest.fixture(autouse=True)
def _static_mode():
    paddle.enable_static()
    yield
    paddle.disable_static()


def _toy(seed=0):
    paddle.seed(seed)
    main = static.Program()
    with static.program_guard(main):
        x = static.data('x', [8, 16])
        y = static.nn.fc(x, 4, activation='relu')
        loss = paddle.mean(y)
    return main, loss


class TestAmpRewriteGolden:
    def test_cast_ops_inserted_at_white_boundaries(self):
        main, _ = _toy()
        before = [op.type for op in main.global_block().ops]
        n = rewrite_program_amp(main)
        after = [op.type for op in main.global_block().ops]
        # the only white op is matmul_v2 (fc): its two float inputs (x, w)
        # each get one bf16 cast, inserted immediately before it
        assert n == 2
        assert after.count('cast') == 2
        mm = after.index('matmul_v2')
        assert after[mm - 2] == 'cast' and after[mm - 1] == 'cast'
        # everything else is unchanged and in order
        assert [t for t in after if t != 'cast'] == before

    def test_white_op_consumes_cast_vars(self):
        main, _ = _toy()
        rewrite_program_amp(main)
        block = main.global_block()
        mm = next(op for op in block.ops if op.type == 'matmul_v2')
        assert all(n.endswith('.cast_bfloat16') for n in mm.input_names), \
            mm.input_names
        for n in mm.input_names:
            assert str(block.vars[n].dtype) == 'bfloat16'

    def test_gray_op_mixed_inputs_record_promoted_dtype(self):
        """elementwise_add(bf16 matmul out, f32 bias) promotes to f32 at
        replay — the recorded var dtype must say f32, not bf16 (the
        pre-eval_shape heuristic's failure mode, ADVICE r2)."""
        main, _ = _toy()
        rewrite_program_amp(main)
        block = main.global_block()
        add = next(op for op in block.ops
                   if op.type in ('elementwise_add', 'add'))
        in_dts = {str(block.vars[n].dtype) for n in add.input_names}
        assert in_dts == {'bfloat16', 'float32'}
        for o in add.output_names:
            assert str(block.vars[o].dtype) == 'float32'

    def test_black_varnames_respected(self):
        main, _ = _toy()
        block = main.global_block()
        w_name = main.all_parameters()[0].name   # fc weight
        lists = AutoMixedPrecisionLists(custom_black_varnames=[w_name])
        n = rewrite_program_amp(main, lists)
        assert n == 1            # only x cast; w pinned
        mm = next(op for op in block.ops if op.type == 'matmul_v2')
        assert w_name in mm.input_names

    def test_custom_lists_shift_boundary(self):
        main, _ = _toy()
        lists = AutoMixedPrecisionLists(custom_black_list=['matmul_v2'])
        n = rewrite_program_amp(main, lists)
        # matmul black (inputs already f32 — no casts), nothing white
        assert n == 0
        types = [op.type for op in main.global_block().ops]
        assert 'cast' not in types

    def test_noop_would_fail(self):
        """The golden test is not satisfiable by a no-op pass."""
        main, _ = _toy()
        types_before = [op.type for op in main.global_block().ops]
        rewrite_program_amp(main)
        assert [op.type for op in main.global_block().ops] != types_before


class TestAmpExecution:
    def test_bf16_loss_tracks_fp32(self):
        rng = np.random.RandomState(0)
        feed = {'x': rng.rand(8, 16).astype('float32')}

        def run(amp):
            main, loss = _toy(seed=3)
            if amp:
                assert rewrite_program_amp(main) > 0
            exe = static.Executor()
            with static.scope_guard(static.Scope()):
                res = exe.run(main, feed=dict(feed), fetch_list=[loss])
            return float(res[0])

        l32 = run(False)
        l16 = run(True)
        assert abs(l16 - l32) <= max(2e-2 * abs(l32), 2e-3), (l16, l32)

    def test_bf16_training_converges(self):
        """fit_a_line through the rewritten program: minimize still works
        end-to-end after cast insertion (rewrite runs before backward, so
        grads differentiate through the casts)."""
        paddle.seed(0)
        rng = np.random.RandomState(0)
        xs = rng.rand(64, 4).astype('float32')
        ys = (xs @ np.array([[1.0], [-2.0], [3.0], [0.5]], 'float32')
              + 0.1)
        main = static.Program()
        with static.program_guard(main):
            x = static.data('x', [64, 4])
            label = static.data('label', [64, 1])
            pred = static.nn.fc(x, 1)
            loss = paddle.mean((pred - label) * (pred - label))
            rewrite_program_amp(main)
            opt = paddle.optimizer.SGD(learning_rate=0.1)
            opt.minimize(loss)
        exe = static.Executor()
        losses = []
        with static.scope_guard(static.Scope()):
            for _ in range(150):
                res = exe.run(main, feed={'x': xs, 'label': ys},
                              fetch_list=[loss])
                losses.append(float(res[0]))
        assert losses[-1] < 0.15 < losses[0]
