"""Fleet data surface (VERDICT r5 #6): the dataset/data_generator
export sheet of paddle.distributed.fleet (reference fleet/__init__.py:
16-38) and the generator -> pipe_command -> InMemoryDataset -> train
ingestion path (reference fleet/data_generator/data_generator.py:20)."""
import io
import os
import sys
import types

import numpy as np
import pytest

import paddle_tpu as paddle


def test_fleet_export_sheet_parity():
    """Every name the reference exports from paddle.distributed.fleet
    resolves here (fleet/__init__.py:16-44)."""
    import paddle_tpu.distributed.fleet as fleet
    for name in [
            # classes (reference import block :16-31)
            'Role', 'UserDefinedRoleMaker', 'PaddleCloudRoleMaker',
            'DistributedStrategy', 'Fleet', 'UtilBase',
            'DatasetBase', 'InMemoryDataset', 'QueueDataset',
            'FileInstantDataset', 'BoxPSDataset',
            'MultiSlotDataGenerator', 'MultiSlotStringDataGenerator',
            'metrics', 'CommunicateTopology', 'HybridCommunicateGroup',
            # singleton re-bindings (:46-80)
            'fleet', 'init', 'is_first_worker', 'worker_index',
            'worker_num', 'is_worker', 'worker_endpoints', 'server_num',
            'server_endpoints', 'is_server', 'barrier_worker',
            'init_worker', 'init_server', 'run_server', 'stop_worker',
            'distributed_optimizer', 'save_persistables', 'minimize']:
        assert hasattr(fleet, name), f"fleet.{name} missing"
    # the generator submodule import style the reference docs use
    import paddle_tpu.distributed.fleet.data_generator as dg
    assert issubclass(dg.MultiSlotDataGenerator, dg.DataGenerator)


def test_multislot_generator_wire_protocol():
    """Byte-parity with the reference protocol: '<n> v1..vn' per slot,
    one sample per line (data_generator.py _gen_str)."""
    from paddle_tpu.distributed.fleet import MultiSlotDataGenerator

    class G(MultiSlotDataGenerator):
        def generate_sample(self, line):
            def it():
                yield [("words", [1926, 8, 17]), ("label", [1])]
            return it

    g = G()
    out = io.StringIO()
    g._run(['x'], out)
    assert out.getvalue() == "3 1926 8 17 1 1\n"
    assert g._proto_info == [("words", "uint64"), ("label", "uint64")]
    # float promotes the slot kind, mismatched slot set raises
    out2 = io.StringIO()
    out2.write(g._gen_str([("words", [1.5, 2, 3]), ("label", [0])]))
    assert g._proto_info[0] == ("words", "float")
    with pytest.raises(ValueError, match='inconsistent'):
        g._gen_str([("words", [1])])


def test_multislot_string_generator():
    from paddle_tpu.distributed.fleet import MultiSlotStringDataGenerator

    class G(MultiSlotStringDataGenerator):
        def generate_sample(self, line):
            def it():
                yield [("q", line.split()), ("label", ["1"])]
            return it

    g = G()
    out = io.StringIO()
    g._run(["ab cd\n"], out)
    assert out.getvalue() == "2 ab cd 1 1\n"


def _slot_vars():
    return [types.SimpleNamespace(shape=[4], dtype='float32'),
            types.SimpleNamespace(shape=[1], dtype='int64')]


_GEN_SCRIPT = """
import sys, os
sys.path.insert(0, {repo!r})
from paddle_tpu.distributed.fleet import MultiSlotDataGenerator

class CtrGen(MultiSlotDataGenerator):
    def generate_sample(self, line):
        def it():
            parts = line.split(',')
            yield [("feat", [float(x) for x in parts[1:]]),
                   ("label", [int(parts[0])])]
        return it

CtrGen().run_from_stdin()
"""


def test_pipe_command_ingestion_to_training(tmp_path):
    """The full reference flow: raw CSV file -> pipe_command running a
    DataGenerator subclass -> InMemoryDataset -> shuffled batches ->
    a train step (the DeepFM-family ingestion path)."""
    from paddle_tpu.distributed.fleet import InMemoryDataset
    from paddle_tpu import nn

    rng = np.random.RandomState(0)
    raw = tmp_path / 'part-0.csv'
    rows = []
    with open(raw, 'w') as f:
        for _ in range(64):
            feats = rng.rand(4)
            label = int(rng.randint(0, 2))
            rows.append((feats, label))
            f.write(f"{label}," + ",".join(f"{x:.6f}" for x in feats)
                    + "\n")
    script = tmp_path / 'gen.py'
    script.write_text(_GEN_SCRIPT.format(
        repo=os.path.dirname(os.path.dirname(os.path.abspath(
            paddle.__file__)))))

    ds = InMemoryDataset()
    ds.init(batch_size=16, thread_num=1, use_var=_slot_vars(),
            pipe_command=f"{sys.executable} {script}")
    ds.set_filelist([str(raw)])
    ds.load_into_memory()
    ds.local_shuffle()
    assert ds.get_memory_data_size() == 64

    model = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=model.parameters())
    seen = 0
    for feat, label in ds:
        loss = nn.functional.cross_entropy(model(feat),
                                           label.squeeze(-1))
        loss.backward()
        opt.step()
        opt.clear_grad()
        seen += feat.shape[0]
        assert np.isfinite(float(loss))
    assert seen == 64
    # round-trip integrity: the multiset of labels survives the pipe
    got = sorted(int(r[1]) for r in rows)
    ds2 = InMemoryDataset()
    ds2.init(batch_size=64, thread_num=1, use_var=_slot_vars(),
             pipe_command=f"{sys.executable} {script}")
    ds2.set_filelist([str(raw)])
    ds2.load_into_memory()
    for feat, label in ds2:
        assert sorted(np.asarray(label.data).ravel().tolist()) == got


def test_pipe_width_mismatch_is_loud(tmp_path):
    """The TPU feed is dense/no-LoD: a slot count that disagrees with
    the declared width must error, not silently pad."""
    from paddle_tpu.distributed.fleet import QueueDataset
    ds = QueueDataset()
    ds.init(batch_size=4, use_var=_slot_vars())
    with pytest.raises(ValueError, match='fixed width'):
        ds._multislot_to_dense(["3 1.0 2.0 3.0 1 1"], tmp_path / 'o')


def test_file_instant_and_boxps_datasets(tmp_path):
    from paddle_tpu.distributed.fleet import (FileInstantDataset,
                                              BoxPSDataset)
    p = tmp_path / 'd.txt'
    with open(p, 'w') as f:
        for i in range(8):
            f.write(f"{i}.0 {i}.5 1.0 2.0 | {i % 2}\n")
    fi = FileInstantDataset()
    fi.init(batch_size=4, thread_num=4, use_var=_slot_vars())
    assert fi._thread_num == 1          # instant = one ordered pass
    fi.set_filelist([str(p)])
    feats = np.concatenate([np.asarray(f.data) for f, _ in fi])
    np.testing.assert_allclose(feats[:, 0], np.arange(8))  # file order

    bx = BoxPSDataset()
    bx.init(batch_size=4, use_var=_slot_vars())
    bx.set_filelist([str(p)])
    bx.begin_pass()
    bx.preload_into_memory()
    bx.wait_preload_done()
    assert bx.get_memory_data_size() == 8
    n = sum(f.shape[0] for f, _ in bx)
    assert n == 8
    bx.end_pass()
    with pytest.raises(NotImplementedError, match='slots_shuffle'):
        bx.slots_shuffle(['feat'])
