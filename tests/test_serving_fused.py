"""Fused decode windows (ISSUE 19): k decode iterations inside ONE
compiled dispatch (lax.scan over the [B, 1] step) with ONE host fetch
per window. The bar is token identity — fused k must emit exactly what
k serial iterations emit, greedy AND sampled, with eos / budget cuts
truncating precisely where serial decode stops — plus exact ledger
accounting, per-iteration observability, and a zero-extra-host-sync
budget counted through the PR-3/PR-6 `engine._host_fetch` harness."""
import json

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.serving.engine as engine_mod
from paddle_tpu.core import monitor
from paddle_tpu.serving import (KVPagePool, PoolExhausted, RequestState,
                                ServingConfig, ServingEngine)
from paddle_tpu.serving.request_trace import load_trace, reconstruct
from paddle_tpu.serving.scheduler import DegradeLadder, Scheduler

MODEL_KW = dict(vocab_size=128, hidden_size=64, num_layers=2,
                num_heads=2, max_seq_len=160, hidden_dropout=0.0,
                attn_dropout=0.0, use_flash_attention=False)


@pytest.fixture(scope='module')
def tiny_lm():
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM
    paddle.seed(7)
    m = GPTForCausalLM(GPTConfig(**MODEL_KW))
    m.eval()
    return m


@pytest.fixture(scope='module')
def prompts():
    rng = np.random.RandomState(3)
    return [list(rng.randint(1, 128, n)) for n in (5, 11, 3, 8)]


def _engine(model, fused_k, **kw):
    base = dict(page_size=8, max_batch_size=4, prefill_chunk=8,
                fused_k=fused_k, seed=11)
    base.update(kw)
    return ServingEngine(model, ServingConfig(**base))


def _run(model, fused_k, prompts, max_new=12, top_k=0, eos=None, **kw):
    eng = _engine(model, fused_k, **kw)
    outs = eng.generate(prompts, max_new_tokens=max_new, top_k=top_k,
                        eos_token_id=eos)
    st = eng.stats()
    eng.shutdown()
    return outs, st


# ---------------------------------------------------------------------------
# token identity: fused k == k serial iterations
# ---------------------------------------------------------------------------
class TestFusedTokenIdentity:
    def test_greedy_k8_matches_serial(self, tiny_lm, prompts):
        ref, st1 = _run(tiny_lm, 1, prompts)
        out, st8 = _run(tiny_lm, 8, prompts)
        assert out == ref
        # the serial engine never fuses, the k=8 engine actually did
        assert st1['fused_windows_total'] == 0
        assert st8['fused_windows_total'] > 0
        assert st8['fused_k'] == 8
        # iteration accounting survives fusing: both engines ran the
        # same decode stream, so the iteration/token counters agree
        assert st8['decode_tokens_total'] == st1['decode_tokens_total']
        assert st8['decode_steps_total'] == st1['decode_steps_total']

    def test_sampled_same_seed_identical(self, tiny_lm, prompts):
        # the RNG folds per (request ordinal, absolute position), so a
        # fused window consumes exactly the randomness its serial
        # iterations would have — same seed -> same tokens
        ref, _ = _run(tiny_lm, 1, prompts, top_k=5)
        out, st = _run(tiny_lm, 8, prompts, top_k=5)
        assert out == ref
        assert st['fused_windows_total'] > 0
        # and sampling is actually doing something
        greedy, _ = _run(tiny_lm, 1, prompts)
        assert out != greedy

    def test_eos_mid_window_truncates_exactly(self, tiny_lm, prompts):
        # pick an eos id straight out of the reference stream so it
        # falls mid-window (not at a window edge) for at least one row
        base, _ = _run(tiny_lm, 1, prompts)
        eos = base[0][len(prompts[0]) + 2]      # 3rd generated token
        ref, _ = _run(tiny_lm, 1, prompts, eos=eos)
        out, st = _run(tiny_lm, 8, prompts, eos=eos)
        assert out == ref
        assert any(o[-1] == eos and len(o) - len(p) < 12
                   for o, p in zip(out, prompts)), \
            'eos never cut a row short — test lost its bite'
        assert st['fused_windows_total'] > 0

    def test_budget_cut_mid_window(self, tiny_lm, prompts):
        # max_new not a multiple of k: the last window must stop at
        # the budget, not round up to the window edge
        for k, max_new in ((8, 6), (4, 11)):
            ref, _ = _run(tiny_lm, 1, prompts, max_new=max_new)
            out, st = _run(tiny_lm, k, prompts, max_new=max_new)
            assert out == ref, (k, max_new)
            assert all(len(o) - len(p) == max_new
                       for o, p in zip(out, prompts))
            assert st['fused_windows_total'] > 0

    def test_page_boundary_crossing_inside_window(self, tiny_lm,
                                                  prompts):
        # page_size 2: one 8-iteration window crosses several page
        # boundaries, exercising the pre-reserved pages + on-device
        # scatter across the whole span
        kw = dict(page_size=2, num_pages=256, prefill_chunk=8)
        ref, _ = _run(tiny_lm, 1, prompts, **kw)
        out, st = _run(tiny_lm, 8, prompts, **kw)
        assert out == ref
        assert st['fused_windows_total'] > 0

    def test_preempt_resume_identity(self, tiny_lm, prompts):
        # a pool too small for the concurrent contexts: reservation
        # failures fall back to the serial step, which preempts and
        # resumes — outputs still match the unconstrained reference
        ref, _ = _run(tiny_lm, 1, prompts, max_new=6)
        out, st = _run(tiny_lm, 8, prompts, max_new=6,
                       max_batch_size=3, num_pages=4)
        assert out == ref
        assert st['preemptions_total'] > 0

    def test_trim_returns_window_tail(self, tiny_lm, prompts):
        # early eos inside a window: the reserved-but-unused tail is
        # trimmed back, and the drained pool holds zero pages
        base, _ = _run(tiny_lm, 1, prompts)
        eos = base[0][len(prompts[0]) + 2]
        eng = _engine(tiny_lm, 8)
        eng.generate(prompts, max_new_tokens=12, top_k=0,
                     eos_token_id=eos)
        assert eng.stats()['fused_windows_total'] > 0
        assert eng.pool.pages_in_use == 0
        eng.shutdown()


# ---------------------------------------------------------------------------
# ledger accounting + the host-sync budget
# ---------------------------------------------------------------------------
class TestFusedLedgerAndSyncs:
    def test_goodput_identity_exact(self, tiny_lm, prompts):
        # the delivered/wasted/emitted stream a fused run reports must
        # be EXACTLY what k serial iterations would have reported
        ref_eng = _engine(tiny_lm, 1)
        ref_eng.generate(prompts, max_new_tokens=12, top_k=0)
        ref = ref_eng.ledger.goodput()
        ref_eng.shutdown()
        eng = _engine(tiny_lm, 8)
        eng.generate(prompts, max_new_tokens=12, top_k=0)
        st = eng.stats()
        assert st['fused_windows_total'] > 0
        g = eng.ledger.goodput()
        assert (g['delivered_tokens'] + g['wasted_tokens']
                == g['emitted_tokens'])
        for k in ('emitted_tokens', 'delivered_tokens',
                  'wasted_tokens', 'goodput_fraction'):
            assert g[k] == ref[k], k
        assert g['wasted_tokens'] == 0          # preemption-free run
        # ledger window counters mirror the engine's
        acct = eng.ledger.account()
        assert acct['fused_windows'] == st['fused_windows_total']
        assert acct['fused_iterations'] == st['fused_iterations_total']
        assert acct['fused_tokens'] == st['fused_tokens_total']
        assert 0 < st['fused_tokens_total'] <= st['decode_tokens_total']
        eng.shutdown()

    def test_one_host_fetch_per_window(self, tiny_lm, prompts,
                                       monkeypatch):
        # the PR-3/PR-6 sync-count harness: serial decode pays one
        # fetch per iteration; a fused window pays ONE for all its
        # iterations. Nothing else in the engine may add a sync.
        counts = [0]
        real = engine_mod._host_fetch

        def counting(x):
            counts[0] += 1
            return real(x)
        monkeypatch.setattr(engine_mod, '_host_fetch', counting)
        try:
            eng = _engine(tiny_lm, 8)
            outs = eng.generate(prompts, max_new_tokens=12, top_k=0)
            st = eng.stats()
            n = counts[0]
            eng.ledger.account()
            eng.ledger.goodput()
            eng.publish_metrics()
            assert counts[0] == n       # observability adds zero
            eng.shutdown()
        finally:
            monkeypatch.setattr(engine_mod, '_host_fetch', real)
        generated = sum(len(o) - len(p) for o, p in zip(outs, prompts))
        prefill_fetches = generated - st['decode_tokens_total']
        serial_iters = (st['decode_steps_total']
                        - st['fused_iterations_total'])
        assert n == (prefill_fetches + serial_iters
                     + st['fused_windows_total']), (n, st)
        # and the budget actually shrank vs one-fetch-per-token
        assert n < prefill_fetches + st['decode_steps_total']


# ---------------------------------------------------------------------------
# per-iteration observability: timeline, metrics, trace
# ---------------------------------------------------------------------------
class TestFusedObservability:
    def test_timeline_records_per_iteration(self, tiny_lm, prompts):
        eng = _engine(tiny_lm, 8)
        eng.generate(prompts, max_new_tokens=12, top_k=0)
        st = eng.stats()
        assert st['fused_windows_total'] > 0
        rows = eng.timeline.snapshot()
        fused_rows = [r for r in rows if r.get('fused')]
        # one timeline entry per fused ITERATION, not per dispatch
        assert len(fused_rows) == st['fused_iterations_total']
        assert all(r['fused_k'] == 8 for r in fused_rows)
        assert (eng.timeline.summary()['fused_iterations']
                == st['fused_iterations_total'])
        # the per-iteration decode stream is complete: tokens across
        # all rows (fused or not) add up to the engine counter
        assert (sum(r.get('decode_tokens', 0) for r in rows)
                == st['decode_tokens_total'])
        eng.shutdown()

    def test_trace_v5_roundtrip_carries_fused_events(self, tiny_lm,
                                                     prompts,
                                                     tmp_path):
        eng = _engine(tiny_lm, 8)
        eng.generate(prompts, max_new_tokens=12, top_k=0)
        st = eng.stats()
        assert st['fused_windows_total'] > 0
        paths = eng.export_trace(jsonl_path=str(tmp_path / 'f.jsonl'))
        header, events = load_trace(paths['jsonl'])
        assert header['schema'] == 'paddle_tpu.serve_trace/6'
        fde = [e for e in events if e['event'] == 'fused_decode']
        assert fde and all('k' in e and 'accepted' in e for e in fde)
        assert sum(e['accepted'] for e in fde) \
            == st['fused_tokens_total']
        # reconstruction parity: fused events count as the decode
        # steps they ran, and the JSONL roundtrip is bit-exact
        table = reconstruct(events)
        assert table == eng.request_table()
        for rid, row in table.items():
            assert row['decode_steps'] + 1 == row['tokens_generated'] \
                or row['decode_steps'] == row['tokens_generated']
            assert row['fused_windows'] > 0 or row['fused_tokens'] == 0
        assert (sum(r['fused_tokens'] for r in table.values())
                == st['fused_tokens_total'])
        eng.shutdown()

    def test_stats_and_gauges_expose_fused_counters(self, tiny_lm,
                                                    prompts):
        from paddle_tpu.serving import metrics as serve_metrics
        eng = _engine(tiny_lm, 4)
        eng.generate(prompts, max_new_tokens=8, top_k=0)
        st = eng.stats()
        assert st['fused_k'] == 4
        assert st['fused_windows_total'] > 0
        series = serve_metrics.scalar_series(st)
        assert series['ptpu_serve_fused_k'] == 4
        assert (series['ptpu_serve_fused_windows_total']
                == st['fused_windows_total'])
        assert (series['ptpu_serve_fused_iterations_total']
                == st['fused_iterations_total'])
        eng.reset_stats()
        assert eng.stats()['fused_windows_total'] == 0
        eng.shutdown()


# ---------------------------------------------------------------------------
# quiescence predicate + degrade interaction (unit level)
# ---------------------------------------------------------------------------
class TestQuiescence:
    def _req(self, state):
        from paddle_tpu.serving.scheduler import Request
        r = Request([1, 2], max_new_tokens=4)
        r.state = state
        return r

    def test_scheduler_quiescent_predicate(self):
        s = Scheduler(num_slots=2)
        assert not s.quiescent()                # empty: nothing to fuse
        s.slots[0] = self._req(RequestState.RUNNING)
        assert s.quiescent()
        s.slots[1] = self._req(RequestState.PREFILL)
        assert not s.quiescent()                # prefill due mid-window
        s.slots[1] = self._req(RequestState.RUNNING)
        assert s.quiescent()
        s.waiting.append(self._req(RequestState.WAITING))
        assert not s.quiescent()                # admission due

    def test_ladder_would_transition_simulates_without_mutating(self):
        lad = DegradeLadder(window=4, hold=2)
        for _ in range(4):
            lad.observe(0.2, 0, 4)
        before = (lad.stage, list(lad._ring), lad._calm)
        assert not lad.would_transition(0.2, steps=8)
        # pressure that would cross up[0] within the window
        assert lad.would_transition(1.0, steps=8)
        assert (lad.stage, list(lad._ring), lad._calm) == before
        # a ladder sitting at stage 1 over a calming signal would
        # step DOWN mid-window — that is also a transition
        lad2 = DegradeLadder(window=2, hold=2)
        lad2.observe(1.0, 8, 2)
        assert lad2.stage == 1
        assert lad2.would_transition(0.1, steps=8)

    def test_effective_fused_k_sheds_at_stage_1(self, tiny_lm):
        eng = _engine(tiny_lm, 8, degrade=True)
        assert eng._effective_fused_k() == 8
        eng._ladder.stage = 1       # stage 1 sheds fused BEFORE spec
        assert eng._effective_fused_k() == 1
        eng._ladder.stage = 0
        assert eng._effective_fused_k() == 8
        eng.shutdown()

    def test_pool_try_reserve_all_or_nothing(self):
        pool = KVPagePool(num_pages=3, page_size=4)
        pool.ensure_capacity('a', 4)            # 1 page held
        assert pool.try_reserve('a', 12)        # grows to 3: fits
        assert pool.pages_in_use == 3
        assert not pool.try_reserve('b', 12)    # needs 3, 0 free
        # the failed reservation rolled back its own fresh pages
        assert pool.pages_in_use == 3 and pool.free_pages == 0
        pool.release('a')
        assert pool.try_reserve('b', 12)
        assert pool.pages_in_use == 3

    def test_config_knob_env_and_validation(self, monkeypatch):
        assert ServingConfig(fused_k=4).fused_k == 4
        with pytest.raises(ValueError, match='fused_k'):
            ServingConfig(fused_k=0)
        monkeypatch.setenv('PTPU_SERVE_FUSED_K', '16')
        assert ServingConfig().fused_k == 16
        assert ServingConfig(fused_k=2).fused_k == 2    # explicit wins


# ---------------------------------------------------------------------------
# publish cadence keys to the monitor wall clock (satellite 2)
# ---------------------------------------------------------------------------
class TestPublishCadence:
    def test_periodic_publish_uses_wall_clock(self, tiny_lm):
        # frozen config clock + controllable monitor time: mid-stream
        # steps must publish on WALL cadence, so gauge freshness can't
        # lapse into metrics_stale alerts on a healthy fused engine
        t = [100.0]
        prev = monitor.set_time_fn(lambda: t[0])
        try:
            eng = ServingEngine(tiny_lm, ServingConfig(
                page_size=8, max_batch_size=2, prefill_chunk=8,
                fused_k=4, clock=lambda: 0.0))
            pubs = [0]
            real = eng.publish_metrics

            def counting():
                pubs[0] += 1
                return real()
            eng.publish_metrics = counting
            eng.submit(list(range(1, 6)), max_new_tokens=64)
            eng.step()                  # prefill
            base = pubs[0]
            eng.step()                  # mid-stream, wall frozen
            eng.step()
            assert pubs[0] == base      # no retire, no cadence due
            t[0] += eng.PUBLISH_INTERVAL_S + 0.01
            eng.step()
            assert pubs[0] == base + 1  # wall cadence fired
            eng.step()
            assert pubs[0] == base + 1  # and re-armed, not every step
            eng.shutdown()
        finally:
            monitor.set_time_fn(prev)


# ---------------------------------------------------------------------------
# mp-sharded serving: the fused shape shards like the [B, 1] step
# ---------------------------------------------------------------------------
class TestFusedMpSharded:
    def test_mp2_fused_token_identical(self, prompts):
        import os
        import paddle_tpu.distributed.fleet as fleet_mod
        from paddle_tpu.distributed import topology_runtime
        from paddle_tpu.distributed.fleet.base.topology import (
            CommunicateTopology, HybridCommunicateGroup)
        from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM
        os.environ.setdefault('PADDLE_TRAINER_ID', '0')
        kw = dict(MODEL_KW, hidden_size=32, num_heads=2)
        paddle.seed(0)
        ref_model = GPTForCausalLM(GPTConfig(**kw))
        ref_model.eval()
        ref, _ = _run(ref_model, 1, prompts[:2], max_new=8)
        topo = CommunicateTopology(
            ["data", "pipe", "sharding", "model"], [1, 1, 1, 2])
        fleet_mod.fleet._topology = topo
        fleet_mod.fleet._hcg = HybridCommunicateGroup(topo)
        try:
            mesh = topology_runtime.build_mesh(['mp'], [2])
            paddle.seed(0)
            mp_model = GPTForCausalLM(GPTConfig(**kw))
            mp_model.eval()
            eng = ServingEngine(
                mp_model,
                ServingConfig(page_size=8, max_batch_size=4,
                              prefill_chunk=8, fused_k=4, seed=11),
                mesh=mesh)
            outs = eng.generate(prompts[:2], max_new_tokens=8, top_k=0)
            assert outs == ref
            assert eng.stats()['fused_windows_total'] > 0
            eng.shutdown()
        finally:
            fleet_mod.fleet._hcg = None
            fleet_mod.fleet._topology = None
