"""Multi-tenant SLO-aware serving (ISSUE 15): priority/quota/deadline
admission units over a deterministic clock, charged-preemption
accounting, degradation-ladder walk-up/walk-down hysteresis with
stage-transition trace events, weighted prefix eviction, no-tenant
token-identity vs the untenanted engine, structured router rejections,
and the adversarial heavy+light mix bar (light-tenant p99 e2e near
solo at near-FCFS aggregate throughput)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.serving import (AdmissionRejected, DegradeLadder,
                                KVPagePool, Request, Scheduler,
                                ServingConfig, ServingEngine,
                                TenantTable, TokenBucket)


class FakeClock:
    """Deterministic monotonic clock: every read advances `tick`, and
    tests jump it explicitly (bucket refills, deadline aging)."""

    def __init__(self, tick=1e-6):
        self.now = 0.0
        self.tick = tick

    def __call__(self):
        self.now += self.tick
        return self.now


# ---------------------------------------------------------------------------
# token bucket + tenant table units
# ---------------------------------------------------------------------------
class TestTokenBucket:
    def test_refill_debit_and_defer(self):
        clk = FakeClock(tick=0.0)
        b = TokenBucket(rate=2.0, burst=10.0, clock=clk)
        assert b.level == 10.0                  # starts full
        assert b.try_debit(8)
        assert abs(b.level - 2.0) < 1e-9
        assert not b.try_debit(8)               # defer
        assert abs(b.seconds_until(8) - 3.0) < 1e-9
        clk.now += 3.0                          # refill 6 tokens
        assert b.try_debit(8)
        assert abs(b.level) < 1e-9

    def test_burst_cap_and_oversized_bill_debt(self):
        clk = FakeClock(tick=0.0)
        b = TokenBucket(rate=1.0, burst=4.0, clock=clk)
        clk.now += 100.0
        assert b.level == 4.0                   # capped at burst
        # a bill larger than the burst admits from a FULL bucket and
        # leaves debt — over-quota tenants defer, never starve
        assert b.try_debit(10)
        assert b.level == -6.0
        assert not b.try_debit(1)
        assert abs(b.seconds_until(1) - 7.0) < 1e-9

    def test_charge_is_unconditional(self):
        b = TokenBucket(rate=1.0, burst=2.0, clock=FakeClock(tick=0.0))
        b.charge(5)
        assert b.level == -3.0


class TestTenantTable:
    def test_policy_resolution_and_defaults(self):
        t = TenantTable({'a': {'priority': 3,
                               'quota_tokens_per_s': 5.0,
                               'burst_tokens': 7.0, 'weight': 0.5},
                         'b': {}}, clock=FakeClock())
        assert t.priority_of('a') == 3 and t.priority_of('b') == 0
        assert t.priority_of('unknown') == 0
        assert t.bucket('a').burst == 7.0
        assert t.bucket('b') is None and t.bucket(None) is None
        assert t.weight_of('a') == 0.5 and t.weight_of('zzz') == 1.0
        assert t.eviction_weights() == {'a': 0.5, 'b': 1.0}

    def test_unknown_policy_key_raises(self):
        with pytest.raises(ValueError, match='unknown policy keys'):
            TenantTable({'a': {'prio': 1}})


# ---------------------------------------------------------------------------
# scheduler: priority order + priority-aware victim
# ---------------------------------------------------------------------------
class TestPrioritySchedule:
    def test_admission_order_priority_then_fcfs(self):
        s = Scheduler(2, clock=FakeClock())
        lo1 = Request([1], priority=0)
        hi = Request([2], priority=2)
        lo2 = Request([3], priority=0)
        mid = Request([4], priority=1)
        for r in (lo1, hi, lo2, mid):
            s.submit(r)
        assert s.admission_order() == [hi, mid, lo1, lo2]
        # no priorities -> arrival order exactly (the FCFS identity)
        s2 = Scheduler(2, clock=FakeClock())
        rs = [Request([i + 1]) for i in range(4)]
        for r in rs:
            s2.submit(r)
        assert s2.admission_order() == rs

    def test_preempted_request_rejoins_front_of_class(self):
        s = Scheduler(2, clock=FakeClock())
        a, b = Request([1], priority=0), Request([2], priority=0)
        hi = Request([3], priority=1)
        s.submit(a)
        s.admit()
        s.submit(b)
        s.preempt(a)
        s.submit(hi)
        # hi outranks; a (preempted) precedes b within class 0
        assert s.admission_order() == [hi, a, b]

    def test_victim_is_youngest_of_lowest_class_below(self):
        s = Scheduler(3, clock=FakeClock())
        lo_old = Request([1], priority=0)
        lo_young = Request([2], priority=0)
        mid = Request([3], priority=1)
        for r in (lo_old, lo_young, mid):
            s.submit(r)
        s.admit()
        assert s.preempt_victim(below_priority=2) is lo_young
        assert s.preempt_victim(below_priority=1) is lo_young
        assert s.preempt_victim(below_priority=0) is None
        # untenanted rule: youngest overall
        assert s.preempt_victim() is mid
        # exclusion still applies
        assert s.preempt_victim(exclude=lo_young,
                                below_priority=2) is lo_old


# ---------------------------------------------------------------------------
# degradation ladder hysteresis (pure controller)
# ---------------------------------------------------------------------------
class TestDegradeLadder:
    def test_walks_up_in_order_and_down_hysteretically(self):
        clk = FakeClock()
        lad = DegradeLadder(window=2, up=(0.5, 0.7, 0.9),
                            down=(0.3, 0.5, 0.7), hold=3, clock=clk)
        stages = []
        for _ in range(6):
            ev = lad.observe(1.0, 8, 2)
            if ev:
                stages.append((ev['from'], ev['to']))
        assert stages == [(0, 1), (1, 2), (2, 3)]
        assert lad.stage == 3
        # calm signal: each step-down needs `hold` consecutive calm
        # observations — never more than one stage per dwell
        downs = []
        for _ in range(3 * 3 + 2):
            ev = lad.observe(0.0, 0, 2)
            if ev:
                downs.append((ev['from'], ev['to']))
        assert downs == [(3, 2), (2, 1), (1, 0)]
        assert lad.stage == 0
        assert lad.transitions == 6
        assert [h['to'] for h in lad.history] == [1, 2, 3, 2, 1, 0]

    def test_hysteresis_band_prevents_oscillation(self):
        # pressure sitting BETWEEN down[0] and up[0] must hold the
        # current stage forever — neither climbs nor drops
        lad = DegradeLadder(window=1, up=(0.8, 0.9, 0.95),
                            down=(0.4, 0.6, 0.8), hold=2,
                            clock=FakeClock())
        lad.observe(0.85, 0, 4)                 # 0 -> 1
        assert lad.stage == 1
        for _ in range(20):
            lad.observe(0.6, 0, 4)              # inside the band
        assert lad.stage == 1 and lad.transitions == 1

    def test_threshold_validation(self):
        with pytest.raises(ValueError, match='below its up-threshold'):
            DegradeLadder(up=(0.5, 0.6, 0.7), down=(0.5, 0.5, 0.6))
        with pytest.raises(ValueError, match='one threshold'):
            DegradeLadder(up=(0.5,), down=(0.4,))

    def test_pressure_signal_combines_pool_and_queue(self):
        assert DegradeLadder.pressure_of(0.9, 0, 4) == 0.9
        assert DegradeLadder.pressure_of(0.1, 8, 4) == 1.0
        assert DegradeLadder.pressure_of(0.2, 2, 4) == 0.25


# ---------------------------------------------------------------------------
# weighted prefix eviction (pool level)
# ---------------------------------------------------------------------------
class TestWeightedEviction:
    def _cache_chain(self, pool, seq, tokens, owner):
        pool.ensure_capacity(seq, len(tokens))
        pool.register_prefix(seq, tokens, len(tokens), owner=owner)
        pool.release(seq)                       # park in cached set

    def test_lightest_tenant_evicts_first(self):
        pool = KVPagePool(num_pages=4, page_size=2, prefix_cache=True)
        light_toks = [1, 2, 3, 4]
        heavy_toks = [9, 8, 7, 6]
        self._cache_chain(pool, 'L', light_toks, owner='light')
        self._cache_chain(pool, 'H', heavy_toks, owner='heavy')
        assert pool.cached_pages == 4
        pool.set_eviction_weights({'heavy': 0.1, 'light': 1.0})
        # pure LRU would evict LIGHT (older); weights pick heavy
        pool.ensure_capacity('new', 2)
        assert pool._match_pages(heavy_toks) == []
        assert len(pool._match_pages(light_toks)) == 2
        assert pool.stats()['weighted_eviction'] is True
        # disarmed -> back to LRU: the next squeeze (one page free,
        # two needed) evicts light's subtree, oldest cached root
        pool.set_eviction_weights(None)
        pool.ensure_capacity('new2', 4)
        assert pool._match_pages(light_toks) == []

    def test_lru_unchanged_without_weights(self):
        pool = KVPagePool(num_pages=4, page_size=2, prefix_cache=True)
        self._cache_chain(pool, 'A', [1, 2, 3, 4], owner='a')
        self._cache_chain(pool, 'B', [5, 6, 7, 8], owner='b')
        pool.ensure_capacity('new', 2)          # LRU: A evicts first
        assert pool._match_pages([1, 2, 3, 4]) == []
        assert len(pool._match_pages([5, 6, 7, 8])) == 2


# ---------------------------------------------------------------------------
# engine fixtures
# ---------------------------------------------------------------------------
@pytest.fixture(scope='module')
def tiny_lm():
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM
    paddle.seed(11)
    cfg = GPTConfig(vocab_size=128, hidden_size=64, num_layers=2,
                    num_heads=2, max_seq_len=128, hidden_dropout=0.0,
                    attn_dropout=0.0, use_flash_attention=False)
    m = GPTForCausalLM(cfg)
    m.eval()
    return m


def _drain(eng, cap=2000):
    steps = 0
    while eng.scheduler.has_work:
        eng.step()
        steps += 1
        assert steps < cap, "engine did not drain"
    return steps


def _events(eng, name, req_id=None):
    return [e for e in eng.tracer.events(req_id)
            if e['event'] == name]


# ---------------------------------------------------------------------------
# quota admission (engine)
# ---------------------------------------------------------------------------
class TestQuotaAdmission:
    def test_over_quota_defers_then_admits_on_refill(self, tiny_lm):
        clk = FakeClock()
        eng = ServingEngine(tiny_lm, ServingConfig(
            page_size=8, max_batch_size=2, prefill_chunk=8, clock=clk,
            tenants={'bulk': {'quota_tokens_per_s': 1.0,
                              'burst_tokens': 10.0}}))
        rng = np.random.RandomState(0)
        p = list(rng.randint(1, 128, 4))
        r1 = eng.submit(p, max_new_tokens=4, top_k=0,
                        tenant_id='bulk')       # bill 8 <= burst 10
        r2 = eng.submit(list(rng.randint(1, 128, 4)), max_new_tokens=4,
                        top_k=0, tenant_id='bulk')  # bill 8 > level 2
        for _ in range(6):
            eng.step()
        assert r1.state in ('running', 'finished', 'prefill')
        assert r2.state == 'waiting' and r2.quota_deferred
        assert r2.quota_defers == 1             # edge-counted, not
                                                # once per sweep
        assert eng.stats()['quota_deferrals_total'] == 1
        ev = _events(eng, 'quota_defer', r2.id)
        assert len(ev) == 1 and ev[0]['retry_after_s'] > 0, ev
        clk.now += 20.0                         # refill the bucket
        _drain(eng)
        assert r2.state == 'finished'
        st = eng.stats()['tenancy']['tenants']['bulk']
        assert st['quota_deferrals'] == 1
        assert st['tokens_billed'] == 16
        eng.shutdown()

    @pytest.mark.slow
    def test_resume_after_preempt_never_redebits(self, tiny_lm):
        clk = FakeClock()
        eng = ServingEngine(tiny_lm, ServingConfig(
            page_size=8, max_batch_size=2, prefill_chunk=8, clock=clk,
            tenants={'t': {'quota_tokens_per_s': 1.0,
                           'burst_tokens': 50.0}}))
        rng = np.random.RandomState(1)
        r = eng.submit(list(rng.randint(1, 128, 4)), max_new_tokens=4,
                       top_k=0, tenant_id='t')
        for _ in range(2):
            eng.step()
        billed = eng.stats()['tenancy']['tenants']['t']['tokens_billed']
        assert billed == 8 and r.quota_charged
        # simulate a preemption round-trip: release + requeue
        eng.pool.release(r.id)
        eng.scheduler.preempt(r)
        _drain(eng)
        assert r.state == 'finished'
        assert eng.stats()['tenancy']['tenants']['t']['tokens_billed'] \
            == 8                                # unchanged
        eng.shutdown()


# ---------------------------------------------------------------------------
# deadline-aware admission + deadline_miss
# ---------------------------------------------------------------------------
class TestDeadlineAdmission:
    def test_cold_engine_admits_then_warm_engine_rejects(self, tiny_lm):
        clk = FakeClock(tick=1e-3)
        eng = ServingEngine(tiny_lm, ServingConfig(
            page_size=8, max_batch_size=2, prefill_chunk=8, clock=clk,
            tenants={}))
        rng = np.random.RandomState(2)
        # cold: no decode rate observed -> a tight deadline still admits
        r = eng.submit(list(rng.randint(1, 128, 4)), max_new_tokens=4,
                       top_k=0, deadline_s=1e-9, tenant_id='t')
        _drain(eng)
        assert r.state == 'finished'
        # ... but it finished past its own deadline: deadline_miss
        assert eng.stats()['deadline_misses_total'] == 1
        assert _events(eng, 'deadline_miss', r.id)
        assert eng.tracer.request_table()[r.id]['deadline_miss'] is True
        # warm: decode rate known; queue a backlog, then an impossible
        # deadline rejects AT SUBMIT with a structured hint
        assert eng.decode_rate() > 0
        backlog = [eng.submit(list(rng.randint(1, 128, 8)),
                              max_new_tokens=16, top_k=0)
                   for _ in range(3)]
        with pytest.raises(AdmissionRejected) as ei:
            eng.submit(list(rng.randint(1, 128, 4)), max_new_tokens=4,
                       top_k=0, deadline_s=1e-9, tenant_id='t')
        e = ei.value
        assert e.reason == 'deadline_unmet'
        assert e.retry_after_s is not None and e.retry_after_s > 0
        assert e.estimated_s > e.deadline_s
        st = eng.stats()
        assert st['deadline_rejects_total'] == 1
        assert st['tenancy']['tenants']['t']['deadline_rejects'] == 1
        # a generous deadline admits against the same backlog
        ok = eng.submit(list(rng.randint(1, 128, 4)), max_new_tokens=4,
                        top_k=0, deadline_s=1e9)
        _drain(eng)
        assert ok.state == 'finished'
        assert all(b.state == 'finished' for b in backlog)
        eng.shutdown()


# ---------------------------------------------------------------------------
# charged priority preemption
# ---------------------------------------------------------------------------
class TestChargedPreemption:
    def test_high_priority_admit_preempts_below_and_pays(self, tiny_lm):
        clk = FakeClock()
        # pool sized so two running requests cannot BOTH grow: the
        # high-priority request's growth must preempt the low one
        eng = ServingEngine(tiny_lm, ServingConfig(
            page_size=8, max_batch_size=2, prefill_chunk=8,
            num_pages=3, max_pages_per_seq=3, clock=clk,
            degrade=False,
            tenants={'low': {'priority': 0},
                     'high': {'priority': 2,
                              'quota_tokens_per_s': 1000.0,
                              'burst_tokens': 1000.0}}))
        rng = np.random.RandomState(3)
        lo = eng.submit(list(rng.randint(1, 128, 8)),
                        max_new_tokens=12, top_k=0, tenant_id='low')
        for _ in range(3):
            eng.step()                          # lo occupies the pool
        hi = eng.submit(list(rng.randint(1, 128, 8)),
                        max_new_tokens=12, top_k=0, tenant_id='high')
        _drain(eng)
        assert lo.state == 'finished' and hi.state == 'finished'
        assert lo.preemptions >= 1              # lo was the victim
        assert hi.preemptions == 0              # never preempted upward
        st = eng.stats()
        assert st['preemptions_charged_total'] >= 1
        trow = st['tenancy']['tenants']['high']
        assert trow['preemptions_charged'] >= 1
        assert trow['charge_tokens'] >= 1
        # the charge debited high's bucket beyond its own bill
        assert trow['bucket_level'] < 1000.0 - trow['tokens_billed']
        ev = _events(eng, 'preempt', lo.id)
        assert ev and ev[0]['charged_to'] == 'high', ev
        assert ev[0]['charge_tokens'] >= 1
        eng.shutdown()


class TestYieldToHigherPriority:
    def test_low_priority_yields_instead_of_crashing(self, tiny_lm):
        # the pool cannot hold both requests; every other slot-holder
        # outranks the low request when ITS growth hits exhaustion —
        # the untenanted engine would preempt upward, the tenancy
        # rules forbid that, and raising PoolExhausted would kill the
        # serve loop. The low request must YIELD (re-queue) and finish
        # after the high one drains.
        # hi peaks at exactly 4 pages (32 tokens) and never shrinks;
        # lo's growth to its own 3rd/4th page hits exhaustion while hi
        # needs nothing — lo finds no victim at-or-below and must yield
        eng = ServingEngine(tiny_lm, ServingConfig(
            page_size=8, max_batch_size=2, prefill_chunk=8,
            num_pages=6, max_pages_per_seq=4, clock=FakeClock(),
            degrade=False,
            tenants={'hi': {'priority': 2}, 'lo': {'priority': 0}}))
        rng = np.random.RandomState(13)
        hi = eng.submit(list(rng.randint(1, 128, 24)),
                        max_new_tokens=8, top_k=0, tenant_id='hi')
        lo = eng.submit(list(rng.randint(1, 128, 8)),
                        max_new_tokens=17, top_k=0, tenant_id='lo')
        _drain(eng)                     # must not raise PoolExhausted
        assert hi.state == 'finished' and lo.state == 'finished'
        assert hi.preemptions == 0
        assert lo.preemptions >= 1
        ev = _events(eng, 'preempt', lo.id)
        assert any(e.get('reason') == 'yield_to_higher_priority'
                   for e in ev), ev
        eng.shutdown()


# ---------------------------------------------------------------------------
# degradation ladder in the engine (forced overload)
# ---------------------------------------------------------------------------
class TestEngineDegradation:
    def test_forced_overload_walks_all_stages_and_recovers(self, tiny_lm):
        clk = FakeClock()
        eng = ServingEngine(tiny_lm, ServingConfig(
            page_size=8, max_batch_size=2, prefill_chunk=16,
            num_pages=6, max_pages_per_seq=4, clock=clk,
            tenants={'a': {'weight': 0.2}, 'b': {'weight': 2.0}},
            degrade=True, degrade_window=2,
            degrade_up=(0.5, 0.7, 0.9), degrade_down=(0.2, 0.3, 0.4),
            degrade_hold=2))
        rng = np.random.RandomState(4)
        reqs = [eng.submit(list(rng.randint(1, 128, 8)),
                           max_new_tokens=8, top_k=0,
                           tenant_id='a' if i % 2 else 'b')
                for i in range(8)]              # deep queue, small pool
        _drain(eng)
        assert all(r.state == 'finished' for r in reqs)
        ups = [h for h in eng.ladder_history() if h['to'] > h['from']]
        assert [h['to'] for h in ups] == [1, 2, 3], \
            eng.ladder_history()                # all three, in order
        assert eng.pool._evict_weights is not None  # stage-3 lever on
        # stage-2 prefill shrink compiled the halved chunk shape
        assert any(k[1] == 8 for k in eng._step_fns
                   if k[0] == 1), sorted(eng._step_fns)
        # every transition is a trace event with stage + pressure
        ev = _events(eng, 'degrade_stage')
        assert len(ev) == len(eng.ladder_history())
        assert [e['stage'] for e in ev[:3]] == [1, 2, 3]
        assert all('pressure' in e and 'stage_name' in e for e in ev)
        # pressure cleared: idle sweeps walk it back to 0 without
        # oscillation (monotone descent, hold-gated)
        for _ in range(20):
            eng.step()
        assert eng.degrade_stage() == 0
        assert eng.pool._evict_weights is None  # lever disarmed
        tos = [h['to'] for h in eng.ladder_history()]
        assert tos == sorted(tos[:3]) + sorted(tos[3:], reverse=True), \
            tos                                 # up 1,2,3 then down
        from paddle_tpu.serving.metrics import serve_snapshot
        eng.publish_metrics()
        assert serve_snapshot()['ptpu_serve_degrade_stage'] == 0
        eng.shutdown()

    def test_spec_shed_is_token_invariant(self, tiny_lm):
        # repetitive prompts so the n-gram proposer actually fires
        prompts = [[7, 8, 9] * 5, [3, 4] * 6]
        base = ServingEngine(tiny_lm, ServingConfig(
            page_size=8, max_batch_size=2, prefill_chunk=8, spec_k=4))
        ref = base.generate(prompts, max_new_tokens=8, top_k=0)
        assert base._spec_proposed > 0          # spec actually ran
        base.shutdown()
        # degrade_hold huge: the forced stage cannot walk back down
        # mid-run on the idle-looking pressure signal
        shed = ServingEngine(tiny_lm, ServingConfig(
            page_size=8, max_batch_size=2, prefill_chunk=8, spec_k=4,
            degrade=True, tenants={}, degrade_hold=10 ** 9))
        shed._ladder.stage = 1                  # force stage 1
        outs = shed.generate(prompts, max_new_tokens=8, top_k=0)
        assert shed._spec_proposed == 0         # drafts shed
        assert outs == ref                      # tokens identical
        shed.shutdown()

    def test_disagg_stage3_arms_both_pools(self, tiny_lm):
        # regression (ISSUE 16 satellite): the disaggregated pipeline
        # shares ONE ladder, but the observing engine used to arm the
        # stage-3 weighted-eviction lever only on its own pool — the
        # other side kept evicting pure-LRU under overload
        from paddle_tpu.serving.cluster.disagg import (
            DisaggregatedEngine)
        d = DisaggregatedEngine(tiny_lm, ServingConfig(
            page_size=8, max_batch_size=2, prefill_chunk=8,
            disaggregate=True, clock=FakeClock(),
            tenants={'a': {'weight': 0.2}, 'b': {'weight': 2.0}},
            degrade=True, degrade_window=1,
            degrade_up=(0.1, 0.2, 0.3),
            degrade_down=(0.01, 0.02, 0.03), degrade_hold=1))
        assert d.prefill._ladder is d.decode._ladder
        d.decode.pool.utilization = lambda: 0.95    # forced pressure
        for _ in range(3):
            d.decode._observe_pressure()
        assert d.decode.degrade_stage() == 3
        assert d.decode.pool._evict_weights is not None
        assert d.prefill.pool._evict_weights is not None
        # calm signal walks back down: BOTH levers disarm on 3 -> 2
        d.decode.pool.utilization = lambda: 0.0
        for _ in range(12):
            d.decode._observe_pressure()
        assert d.decode.degrade_stage() < 3
        assert d.decode.pool._evict_weights is None
        assert d.prefill.pool._evict_weights is None
        # symmetric: a PREFILL-side observation arms the decode pool
        d.prefill.pool.utilization = lambda: 0.95
        while d.prefill.degrade_stage() < 3:
            d.prefill._observe_pressure()
        assert d.prefill.pool._evict_weights is not None
        assert d.decode.pool._evict_weights is not None
        d.shutdown()


# ---------------------------------------------------------------------------
# no-tenant identity: default config is the PR-9 engine, bit for bit
# ---------------------------------------------------------------------------
class TestNoTenantIdentity:
    def test_outputs_and_compiled_shapes_unchanged(self, tiny_lm):
        rng = np.random.RandomState(5)
        prompts = [list(rng.randint(1, 128, n)) for n in (5, 11, 3)]
        seq = []
        for p in prompts:
            out = tiny_lm.generate(Tensor(np.asarray([p], 'int32')),
                                   max_new_tokens=6, top_k=0,
                                   use_cache=True)
            seq.append(np.asarray(out.data)[0].tolist())
        eng = ServingEngine(tiny_lm, ServingConfig(
            page_size=8, max_batch_size=3, prefill_chunk=8))
        assert eng._tenants is None and eng._ladder is None
        outs = eng.generate(prompts, max_new_tokens=6, top_k=0)
        assert outs == seq                      # greedy token identity
        # exactly the two untenanted compiled shapes: (1, chunk)
        # prefill and (B, 1) decode — no ladder shapes, no extras
        assert sorted(eng._step_fns) == [(1, 8, False, False),
                                         (3, 1, False, False)], \
            sorted(eng._step_fns)
        st = eng.stats()
        assert st['quota_deferrals_total'] == 0
        assert st['degrade_stage'] == 0
        assert st['tenancy']['enabled'] is False
        eng.shutdown()


# ---------------------------------------------------------------------------
# structured router rejection + tenancy forwarding (cluster)
# ---------------------------------------------------------------------------
class TestClusterTenancy:
    def _cluster(self, tiny_lm, max_queue=1, **router_kw):
        from paddle_tpu.serving.cluster import (ClusterRouter,
                                                LocalReplica)
        eng = ServingEngine(tiny_lm, ServingConfig(
            page_size=8, max_batch_size=2, prefill_chunk=8))
        rep = LocalReplica(eng, 'r0')
        router = ClusterRouter([rep], page_size=8, max_queue=max_queue,
                               **router_kw)
        return router, rep, eng

    @pytest.mark.slow
    def test_backpressure_reject_carries_retry_hint(self, tiny_lm):
        from paddle_tpu.serving.cluster import RouterRejected
        # refresh every submit so the hint sees the queued backlog
        router, rep, eng = self._cluster(tiny_lm, max_queue=1,
                                         refresh_interval_s=0.0)
        rng = np.random.RandomState(6)
        # warm the engine so a decode rate exists (the hint's input)
        eng.generate([list(rng.randint(1, 128, 4))], max_new_tokens=4,
                     top_k=0)
        router.submit(list(rng.randint(1, 128, 6)), max_new_tokens=8,
                      top_k=0)                  # fills the queue bound
        with pytest.raises(RouterRejected) as ei:
            router.submit(list(rng.randint(1, 128, 6)),
                          max_new_tokens=8, top_k=0)
        assert ei.value.reason == 'backpressure'
        assert ei.value.retry_after_s is not None
        assert ei.value.retry_after_s > 0
        assert router.snapshot()['rejects'] == 1
        router.run(timeout_s=120)
        router.shutdown()

    @pytest.mark.slow
    def test_engine_deadline_reject_passes_through_without_drain(
            self, tiny_lm):
        from paddle_tpu.serving.cluster import RouterRejected
        router, rep, eng = self._cluster(tiny_lm, max_queue=64)
        rng = np.random.RandomState(7)
        eng.generate([list(rng.randint(1, 128, 4))], max_new_tokens=4,
                     top_k=0)                   # decode rate observed
        router.submit(list(rng.randint(1, 128, 8)), max_new_tokens=16,
                      top_k=0)                  # backlog, unpumped
        with pytest.raises(RouterRejected) as ei:
            router.submit(list(rng.randint(1, 128, 4)),
                          max_new_tokens=4, top_k=0, deadline_s=1e-9)
        assert ei.value.reason == 'deadline_unmet'
        assert ei.value.retry_after_s > 0
        # a healthy replica refusing one deadline is NOT a hang
        assert router.healthy_replicas() == ['r0']
        assert not router.snapshot()['drain_events']
        router.run(timeout_s=120)
        router.shutdown()

    @pytest.mark.slow
    def test_tenant_opts_reach_engine_and_spills_account(self, tiny_lm):
        router, rep, eng = self._cluster(tiny_lm, max_queue=64)
        rng = np.random.RandomState(8)
        r = router.submit(list(rng.randint(1, 128, 4)),
                          max_new_tokens=4, top_k=0, tenant_id='gold',
                          priority=2)
        engine_req = rep._reqs[r.remote_rid]
        assert engine_req.tenant_id == 'gold'
        assert engine_req.priority == 2
        assert 'tenant_spills' in router.snapshot()
        router.run(timeout_s=120)
        assert r.done and len(r.tokens) == 4
        router.shutdown()

    @pytest.mark.slow
    def test_serve_backs_off_by_hint_and_completes(self, tiny_lm):
        router, rep, eng = self._cluster(tiny_lm, max_queue=2)
        rng = np.random.RandomState(9)
        prompts = [list(rng.randint(1, 128, 4)) for _ in range(6)]
        outs = router.serve(prompts, max_new_tokens=4, top_k=0,
                            timeout_s=300)
        assert [len(o) for o in outs] == [8] * 6
        assert router.snapshot()['requests_done'] == 6
        router.shutdown()


# ---------------------------------------------------------------------------
# the adversarial mix bar (ISSUE 15 acceptance)
# ---------------------------------------------------------------------------
class TestAdversarialMix:
    """One heavy tenant saturating the pool + N light tenants: light
    p99 e2e must hold within 1.5x of its solo baseline under the SLO
    scheduler, while aggregate decode throughput (tokens per engine
    sweep — the deterministic-clock stand-in for tokens/sec) stays
    within ~10% of FCFS on the same stream."""

    HEAVY_N, HEAVY_LEN, HEAVY_NEW = 6, 12, 16
    LIGHT_N, LIGHT_LEN, LIGHT_NEW = 6, 4, 4

    def _mk_prompts(self):
        rng = np.random.RandomState(10)
        heavy = [list(rng.randint(1, 128, self.HEAVY_LEN))
                 for _ in range(self.HEAVY_N)]
        light = [list(rng.randint(1, 128, self.LIGHT_LEN))
                 for _ in range(self.LIGHT_N)]
        return heavy, light

    def _run(self, tiny_lm, tenants, heavy, light):
        clk = FakeClock()
        eng = ServingEngine(tiny_lm, ServingConfig(
            page_size=8, max_batch_size=2, prefill_chunk=8, clock=clk,
            tenants=tenants))
        hreqs = [eng.submit(p, max_new_tokens=self.HEAVY_NEW, top_k=0,
                            tenant_id='heavy') for p in heavy]
        for _ in range(3):
            eng.step()          # heavy saturates the slots first
        lreqs = [eng.submit(p, max_new_tokens=self.LIGHT_NEW, top_k=0,
                            tenant_id=f'light{i % 3}')
                 for i, p in enumerate(light)]
        steps = _drain(eng)
        assert all(r.state == 'finished' for r in hreqs + lreqs)
        light_e2e = sorted(r.finish_time - r.submit_time
                           for r in lreqs)
        tokens = sum(len(r.generated) for r in hreqs + lreqs)
        eng.shutdown()
        # p99 over a small set = the max; steps+3 counts every sweep
        return light_e2e[-1], tokens / (steps + 3)

    def test_light_p99_holds_at_near_fcfs_throughput(self, tiny_lm):
        heavy, light = self._mk_prompts()
        # solo baseline: the light stream alone
        clk = FakeClock()
        solo = ServingEngine(tiny_lm, ServingConfig(
            page_size=8, max_batch_size=2, prefill_chunk=8, clock=clk))
        sreqs = [solo.submit(p, max_new_tokens=self.LIGHT_NEW, top_k=0)
                 for p in light]
        _drain(solo)
        solo_p99 = sorted(r.finish_time - r.submit_time
                          for r in sreqs)[-1]
        solo.shutdown()
        # FCFS: the untenanted scheduler on the adversarial stream
        fcfs_p99, fcfs_tps = self._run(tiny_lm, None, heavy, light)
        # SLO: lights outrank the heavy class
        ten = {'heavy': {'priority': 0},
               'light0': {'priority': 1}, 'light1': {'priority': 1},
               'light2': {'priority': 1}}
        slo_p99, slo_tps = self._run(tiny_lm, ten, heavy, light)
        # the bar: lights near solo, aggregate within ~10% of FCFS
        assert slo_p99 <= 1.5 * solo_p99, (slo_p99, solo_p99)
        assert slo_tps >= 0.9 * fcfs_tps, (slo_tps, fcfs_tps)
        # and the scheduler actually mattered: FCFS starved the lights
        assert fcfs_p99 > slo_p99, (fcfs_p99, slo_p99)


# ---------------------------------------------------------------------------
# schema v3 export round-trip from a tenanted engine
# ---------------------------------------------------------------------------
class TestTenantTraceExport:
    @pytest.mark.slow
    def test_v3_roundtrip_carries_tenant_columns(self, tiny_lm,
                                                 tmp_path):
        from paddle_tpu.serving.request_trace import (load_trace,
                                                      reconstruct)
        clk = FakeClock()
        eng = ServingEngine(tiny_lm, ServingConfig(
            page_size=8, max_batch_size=2, prefill_chunk=8, clock=clk,
            tenants={'bulk': {'priority': 0,
                              'quota_tokens_per_s': 1.0,
                              'burst_tokens': 10.0},
                     'gold': {'priority': 2}}))
        rng = np.random.RandomState(12)
        reqs = [eng.submit(list(rng.randint(1, 128, 4)),
                           max_new_tokens=4, top_k=0, tenant_id=tid)
                for tid in ('bulk', 'bulk', 'gold')]
        for _ in range(4):
            eng.step()
        clk.now += 30.0
        _drain(eng)
        path = str(tmp_path / 'tenants.jsonl')
        eng.export_trace(jsonl_path=path)
        header, events = load_trace(path)
        assert header['schema'] == 'paddle_tpu.serve_trace/6'
        table = reconstruct(events)
        assert table[reqs[2].id]['tenant_id'] == 'gold'
        assert table[reqs[2].id]['priority'] == 2
        assert table[reqs[1].id]['quota_defers'] == 1
        assert reqs[1].id not in [e['req'] for e in events
                                  if e['event'] == 'degrade_stage']
        # engine-scope rows never appear in the per-request table
        assert all(k >= 0 for k in table)
        eng.shutdown()
