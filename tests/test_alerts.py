"""Alert-rules engine & cluster metrics federation (ISSUE 18): rule
kinds over history rings, the ok -> pending -> firing -> resolved
state machine with sustain + hysteretic clear on an injected clock,
transition emissions (gauges/counters, flight-recorder journal,
alert_report artifact), the built-in rule packs, and the ISSUE-18
acceptance legs on a 2-replica LocalReplica cluster: ONE federated
scrape with both replicas' series under `replica` labels, forced
overload firing the pool-pressure rule (sustained, then hysteretically
clearing), and an injected replica hang tripping the heartbeat-
staleness rule BEFORE the PR-11 watchdog drains it."""
import json
import os
import sys

import pytest

HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(HERE))

import paddle_tpu as paddle                              # noqa: E402
from paddle_tpu.core import monitor                      # noqa: E402
from paddle_tpu.core.alerts import (AlertManager,        # noqa: E402
                                    AlertRule, default_rules,
                                    router_rules)
from paddle_tpu.core.monitor import MetricsRegistry      # noqa: E402


def _rig(capacity=64):
    """Private registry + history + alert registry on one injected
    clock dict."""
    t = {'now': 0.0}
    reg = MetricsRegistry()
    hist = reg.enable_history(capacity=capacity,
                              clock=lambda: t['now'])
    alert_reg = MetricsRegistry()
    return reg, hist, alert_reg, t


# ---------------------------------------------------------------------------
# rule construction & kinds
# ---------------------------------------------------------------------------
class TestRuleValidation:
    def test_bad_severity(self):
        with pytest.raises(ValueError):
            AlertRule('r', metric='m', severity='fatal')

    def test_metric_required(self):
        with pytest.raises(ValueError):
            AlertRule('r')

    def test_predicate_requires_fn(self):
        with pytest.raises(ValueError):
            AlertRule('r', kind='predicate')

    def test_unknown_op(self):
        with pytest.raises(ValueError):
            AlertRule('r', metric='m', op='~')

    def test_duplicate_rule_names_rejected(self):
        reg, hist, alert_reg, t = _rig()
        rules = [AlertRule('dup', metric='m', value=1.0),
                 AlertRule('dup', metric='m', value=2.0)]
        with pytest.raises(ValueError):
            AlertManager(hist, rules=rules, registry=alert_reg)


class TestRuleKinds:
    def _hist(self, name, values, step=1.0, kind='gauge',
              labelled=None):
        t = {'now': 0.0}
        reg = MetricsRegistry()
        hist = reg.enable_history(capacity=64, clock=lambda: t['now'])
        for i, v in enumerate(values):
            t['now'] = i * step
            if labelled:
                g = reg.gauge(name, labelnames=('replica',))
                for rep, vv in v.items():
                    g.set(vv, replica=rep)
            elif kind == 'counter':
                c = reg.counter(name)
                c.inc(v - c.value())
            else:
                reg.gauge(name).set(float(v))
            hist.sample()
        return hist, t

    def test_threshold(self):
        hist, t = self._hist('m', [0.1, 0.5, 0.98])
        rule = AlertRule('r', metric='m', op='>=', value=0.95)
        breach, info = rule.check(hist, t['now'])
        assert breach and info['value'] == pytest.approx(0.98)
        assert not AlertRule('r', metric='m', op='>=',
                             value=0.99).check(hist, t['now'])[0]

    def test_delta_counter_storm(self):
        hist, t = self._hist('c_total', [0, 1, 1, 4], kind='counter')
        rule = AlertRule('r', metric='c_total', kind='delta',
                         value=3.0, window_s=60.0)
        assert rule.check(hist, t['now'])[0]
        assert not AlertRule('r', metric='c_total', kind='delta',
                             value=5.0,
                             window_s=60.0).check(hist, t['now'])[0]

    def test_rate(self):
        hist, t = self._hist('m', [0, 10, 20, 30])
        rule = AlertRule('r', metric='m', kind='rate', op='>=',
                         value=9.0, window_s=10.0)
        breach, info = rule.check(hist, t['now'])
        assert breach and info['value'] == pytest.approx(10.0)

    def test_spread_needs_two_series(self):
        hist, t = self._hist('m', [{'r0': 0.9, 'r1': 0.2}],
                             labelled=True)
        rule = AlertRule('r', metric='m', kind='spread', value=0.5)
        breach, info = rule.check(hist, t['now'])
        assert breach and info['value'] == pytest.approx(0.7)
        assert info['series'] == ['r0']     # the high side named
        one, t1 = self._hist('m', [{'r0': 0.9}], labelled=True)
        assert not rule.check(one, t1['now'])[0]

    def test_ewma_drop(self):
        hist, t = self._hist('m', [100.0] * 20 + [10.0])
        rule = AlertRule('r', metric='m', kind='ewma_drop', value=0.5,
                         tau_s=30.0)
        breach, info = rule.check(hist, t['now'])
        assert breach and info['value'] < 0.5
        flat, tf = self._hist('m', [100.0] * 20)
        assert not rule.check(flat, tf['now'])[0]

    def test_staleness_reads_publish_stamps(self):
        t = {'now': 0.0}
        prev = monitor.set_time_fn(lambda: t['now'])
        try:
            reg = MetricsRegistry()
            hist = reg.enable_history(capacity=8,
                                      clock=lambda: t['now'])
            reg.gauge('m').set(1.0)         # stamped at t=0
            hist.sample()
            rule = AlertRule('r', metric='m', kind='staleness',
                             value=30.0)
            assert not rule.check(hist, 10.0)[0]
            t['now'] = 40.0
            breach, info = rule.check(hist, 40.0)
            assert breach and info['value'] == pytest.approx(40.0)
        finally:
            monitor.set_time_fn(prev)

    def test_predicate(self):
        hist, t = self._hist('m', [1.0, 2.0])
        rule = AlertRule('r', kind='predicate',
                         predicate=lambda h, now:
                         (h.last('m') or 0) > 1.5)
        assert rule.check(hist, t['now'])[0]


# ---------------------------------------------------------------------------
# the state machine
# ---------------------------------------------------------------------------
class TestStateMachine:
    RULE_KW = dict(metric='util', op='>=', value=0.95,
                   clear_value=0.8, for_s=2.0, clear_for_s=1.0,
                   severity='critical')

    def _mgr(self, tmp_path=None, **overrides):
        reg, hist, alert_reg, t = _rig()
        kw = dict(self.RULE_KW, **overrides)
        mgr = AlertManager(
            hist, rules=[AlertRule('pressure', **kw)],
            clock=lambda: t['now'], registry=alert_reg,
            source='test',
            report_dir=str(tmp_path) if tmp_path else None)
        g = reg.gauge('util')
        return reg, hist, mgr, g, t, alert_reg

    def _step(self, hist, g, t, now, value):
        t['now'] = now
        g.set(value)
        return hist.tick()      # sample + attached-manager evaluate

    def test_fire_sustain_hysteretic_clear(self, tmp_path):
        reg, hist, mgr, g, t, alert_reg = self._mgr(tmp_path)
        events = []
        # breach must SUSTAIN for_s before firing
        events += self._step(hist, g, t, 0.0, 0.98)
        assert mgr.snapshot()['rules'][0]['state'] == 'pending'
        events += self._step(hist, g, t, 1.0, 0.99)
        assert not events                   # 1.0s < for_s=2.0
        events += self._step(hist, g, t, 2.5, 0.97)
        assert [e['event'] for e in events] == ['fired']
        assert mgr.active()[0]['rule'] == 'pressure'
        # 0.9 clears the FIRING bound but not the 0.8 clear bound:
        # hysteresis keeps the alert up (no flapping around 0.95)
        events += self._step(hist, g, t, 3.0, 0.9)
        assert mgr.active(), 'hysteretic clear band must hold firing'
        # below clear_value, held clear_for_s -> resolved
        events += self._step(hist, g, t, 4.0, 0.5)
        assert mgr.active()                 # clear window just opened
        events += self._step(hist, g, t, 5.5, 0.5)
        assert [e['event'] for e in events] == ['fired', 'resolved']
        assert not mgr.active()

    def test_dip_resets_sustain(self):
        reg, hist, mgr, g, t, _ = self._mgr()
        self._step(hist, g, t, 0.0, 0.98)
        self._step(hist, g, t, 1.0, 0.5)    # breach broke: back to ok
        assert mgr.snapshot()['rules'][0]['state'] == 'ok'
        ev = self._step(hist, g, t, 2.5, 0.98)
        assert not ev                       # sustain restarted

    def test_gauge_and_counter_transitions(self, tmp_path):
        reg, hist, mgr, g, t, alert_reg = self._mgr(tmp_path)
        self._step(hist, g, t, 0.0, 0.98)
        self._step(hist, g, t, 2.5, 0.98)   # fired
        kw = dict(rule='pressure', severity='critical')
        assert alert_reg.get('ptpu_alert_active').value(**kw) == 1
        assert alert_reg.get('ptpu_alert_fired_total').value(**kw) == 1
        self._step(hist, g, t, 3.0, 0.1)
        self._step(hist, g, t, 4.5, 0.1)    # resolved
        assert alert_reg.get('ptpu_alert_active').value(**kw) == 0
        assert alert_reg.get(
            'ptpu_alert_resolved_total').value(**kw) == 1

    def test_report_artifact_and_flight_recorder(self, tmp_path):
        from paddle_tpu.distributed import flight_recorder as fr
        reg, hist, mgr, g, t, _ = self._mgr(tmp_path)
        self._step(hist, g, t, 0.0, 0.98)
        self._step(hist, g, t, 2.5, 0.98)   # fired
        path = os.path.join(str(tmp_path), 'alert_report.test.json')
        assert mgr.last_report_path == path
        doc = json.load(open(path))
        assert doc['kind'] == 'alert_report'
        assert doc['events'][-1]['event'] == 'fired'
        assert doc['rules'][0]['state'] == 'firing'
        ops = [e['op'] for e in fr.recorder().entries()]
        assert 'alert_fired:pressure' in ops

    def test_snapshot_and_summary_shapes(self):
        reg, hist, mgr, g, t, _ = self._mgr()
        self._step(hist, g, t, 0.0, 0.98)
        self._step(hist, g, t, 2.5, 0.98)
        snap = mgr.snapshot()
        assert snap['source'] == 'test' and snap['evals'] == 2
        row = snap['rules'][0]
        assert row['state'] == 'firing' and row['fired'] == 1
        assert row['last_value'] == pytest.approx(0.98)
        s = mgr.summary()
        assert s['fired_total'] == s['fired_critical'] == 1
        assert s['active'] == ['pressure']

    def test_detach_stops_evaluation(self):
        reg, hist, mgr, g, t, _ = self._mgr()
        mgr.detach()
        self._step(hist, g, t, 0.0, 0.98)
        self._step(hist, g, t, 5.0, 0.98)
        assert mgr.summary()['evals'] == 0


class TestRulePacks:
    def test_packs_construct_and_validate(self):
        for pack in (default_rules(), router_rules()):
            names = [r.name for r in pack]
            assert len(set(names)) == len(names)
            for r in pack:
                d = r.describe()
                assert d['severity'] in ('info', 'warn', 'critical')
                assert d['description']

    def test_heartbeat_bound_precedes_default_watchdog(self):
        # the acceptance invariant: the staleness alert must lead the
        # PR-11 drain, so the rule's bound sits under the router's
        # default hang_timeout_s
        from paddle_tpu.serving.cluster.router import ClusterRouter
        import inspect
        default_hang = inspect.signature(
            ClusterRouter.__init__).parameters['hang_timeout_s'].default
        beat = [r for r in router_rules()
                if r.name == 'replica_heartbeat_stale'][0]
        assert beat.value < default_hang
        assert beat.severity == 'critical'


# ---------------------------------------------------------------------------
# the 2-replica cluster acceptance legs (deterministic injected clock)
# ---------------------------------------------------------------------------
@pytest.fixture(scope='module')
def tiny_model():
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM
    paddle.seed(11)
    cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=2,
                    num_heads=4, max_seq_len=128, hidden_dropout=0.0,
                    attn_dropout=0.0, use_flash_attention=False)
    m = GPTForCausalLM(cfg)
    m.eval()
    return m


def _cluster(tiny_model, clk, n=2, report_dir=None, **engine_kw):
    from paddle_tpu.serving import ServingConfig
    from paddle_tpu.serving.cluster import ClusterRouter, LocalReplica
    from paddle_tpu.serving.cluster.disagg import build_engine
    kw = dict(page_size=8, max_batch_size=3, prefill_chunk=16)
    kw.update(engine_kw)
    reps = [LocalReplica(build_engine(tiny_model, ServingConfig(**kw)),
                         f'r{i}', clock=clk) for i in range(n)]
    router = ClusterRouter(reps, page_size=kw['page_size'],
                           hang_timeout_s=20.0, refresh_interval_s=0.0,
                           clock=clk, report_dir=report_dir)
    return router, reps


class TestClusterFederation:
    def test_one_scrape_carries_both_replicas(self, tiny_model):
        t = [0.0]
        router, reps = _cluster(tiny_model, lambda: t[0])
        try:
            outs = router.serve([[1, 2, 3], [4, 5, 6], [7, 8, 9],
                                 [2, 4, 6]], max_new_tokens=4, top_k=0)
            assert len(outs) == 4
            # both replicas actually took traffic (affinity spreads
            # distinct prompts; guard the test's own premise)
            assert all(router._routed_count[r.replica_id] > 0
                       for r in reps)
            text = router.cluster_prometheus_text()
            for rid in ('r0', 'r1'):
                assert f'replica="{rid}"' in text
                # engine-truth series federated via the metrics op
                assert (f'ptpu_serve_decode_tokens_total'
                        f'{{replica="{rid}"}}') in text
                assert (f'ptpu_cluster_replica_beat_age_seconds'
                        f'{{replica="{rid}"}}') in text
            # per-series staleness ages ride the cluster scrape
            assert '# age ' in text
            # the federated registry is router-local: the process-
            # global scrape does NOT grow replica-labeled serve series
            assert 'ptpu_serve_decode_tokens_total{replica=' \
                not in monitor.prometheus_text()
            # snapshot carries the alert summary + cluster tenant view
            snap = router.cluster_snapshot()
            assert snap['alerts']['rules'] == len(router_rules())
            assert 'tenants' in snap
        finally:
            for r in reps:
                r.shutdown()

    def test_metrics_http_endpoint(self, tiny_model):
        import urllib.request
        t = [0.0]
        router, reps = _cluster(tiny_model, lambda: t[0])
        try:
            router.serve([[5, 6, 7]], max_new_tokens=2, top_k=0)
            srv = router.serve_metrics_http(port=0)
            try:
                body = urllib.request.urlopen(
                    f'http://127.0.0.1:{srv.port}/metrics',
                    timeout=10).read().decode()
                assert 'replica="r0"' in body
                jbody = json.loads(urllib.request.urlopen(
                    f'http://127.0.0.1:{srv.port}/metrics.json',
                    timeout=10).read().decode())
                assert 'series' in jbody    # cluster history rides it
            finally:
                srv.close()
        finally:
            for r in reps:
                r.shutdown()

    def test_overload_fires_pool_pressure_then_clears(self, tiny_model,
                                                      tmp_path):
        """Forced overload: a prompt sized to the whole KV pool holds
        utilization at 1.0 across refreshes -> cluster_pool_pressure
        fires (sustained for_s), with the artifact + journal + gauge
        emissions; finishing the request drops utilization under the
        hysteretic clear bound -> resolved."""
        from paddle_tpu.distributed import flight_recorder as fr
        t = [0.0]
        router, reps = _cluster(tiny_model, lambda: t[0], n=1,
                                report_dir=str(tmp_path),
                                num_pages=4, prefix_cache=False)
        try:
            # 25 prompt tokens -> 4 of 4 pages once prefill finishes
            router.submit(list(range(1, 26)), max_new_tokens=4,
                          top_k=0)
            router.pump()                   # prefill chunk 1 (16 tok)
            router.pump()                   # prefill chunk 2 -> 4/4
            router.refresh(max_age_s=0.0)
            snap = router.alerts.snapshot()
            rule = [r for r in snap['rules']
                    if r['rule'] == 'cluster_pool_pressure'][0]
            assert rule['state'] == 'pending'   # breach, not sustained
            t[0] += 1.2                     # past for_s=1.0, still held
            router.refresh(max_age_s=0.0)
            active = router.alerts.active()
            assert [a['rule'] for a in active] == \
                ['cluster_pool_pressure']
            assert active[0]['value'] == pytest.approx(1.0)
            kw = dict(rule='cluster_pool_pressure', severity='critical')
            g_active = monitor.metrics().get('ptpu_alert_active')
            assert g_active.value(**kw) == 1
            assert monitor.metrics().get(
                'ptpu_alert_fired_total').value(**kw) == 1
            # artifact + flight-recorder journal emitted on the fire
            rep_path = os.path.join(str(tmp_path),
                                    'alert_report.router.json')
            doc = json.load(open(rep_path))
            assert doc['events'][-1]['event'] == 'fired'
            assert doc['events'][-1]['rule'] == 'cluster_pool_pressure'
            ops = [e['op'] for e in fr.recorder().entries()]
            assert 'alert_fired:cluster_pool_pressure' in ops
            # drain the request; pages free -> under clear_value=0.75
            while router.pump():
                pass
            router.refresh(max_age_s=0.0)   # clear window opens
            assert router.alerts.active()   # held hysteretically
            t[0] += 1.2                     # past clear_for_s
            router.refresh(max_age_s=0.0)
            assert not router.alerts.active()
            assert g_active.value(**kw) == 0
            assert monitor.metrics().get(
                'ptpu_alert_resolved_total').value(**kw) == 1
        finally:
            for r in reps:
                r.shutdown()

    def test_hang_alert_precedes_watchdog_drain(self, tiny_model):
        """An injected replica hang stops the heartbeat: the
        replica_heartbeat_stale rule (bound 5s) must fire while the
        replica is still in the cluster, BEFORE the PR-11 watchdog
        (hang_timeout_s=20) drains it. The healthy replica keeps
        pumping so only the hung one's beat ages."""
        t = [0.0]
        router, reps = _cluster(tiny_model, lambda: t[0])
        try:
            router.serve([[1, 2, 3], [4, 5, 6]], max_new_tokens=2,
                         top_k=0)
            reps[1].inject_hang()
            t[0] += 6.0                     # stale > 5s, < 20s timeout
            router.pump()                   # healthy r0 re-stamps beat
            router.refresh(max_age_s=0.0)
            active = router.alerts.active()
            assert [a['rule'] for a in active] == \
                ['replica_heartbeat_stale']
            assert active[0]['series'] == ['r1']    # r1, not r0
            assert active[0]['value'] == pytest.approx(6.0)
            assert 'r1' not in router._drained, \
                'the alert must PRECEDE the watchdog drain'
            assert monitor.metrics().get('ptpu_alert_active').value(
                rule='replica_heartbeat_stale',
                severity='critical') == 1
            # past hang_timeout_s the watchdog takes over and drains
            t[0] += 20.0
            router.pump()
            router.refresh(max_age_s=0.0)
            assert 'r1' in router._drained
            assert 'r0' not in router._drained
        finally:
            for r in reps:
                r.shutdown()
