"""Test config: run on an 8-device virtual CPU mesh (SURVEY.md §4 —
multi-controller simulation replaces the reference's 2-process NCCL tests).
"""
import os
import sys

os.environ['JAX_PLATFORMS'] = 'cpu'  # override the session's axon default
flags = os.environ.get('XLA_FLAGS', '')
if 'xla_force_host_platform_device_count' not in flags:
    os.environ['XLA_FLAGS'] = \
        flags + ' --xla_force_host_platform_device_count=8'

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# The axon sitecustomize force-registers the TPU plugin regardless of env;
# re-pin the platform at the config level so tests run on the virtual
# 8-device CPU mesh.
import jax  # noqa: E402

jax.config.update('jax_platforms', 'cpu')
