"""Test config: run on an 8-device virtual CPU mesh (SURVEY.md §4 —
multi-controller simulation replaces the reference's 2-process NCCL tests).
"""
import os
import sys

os.environ.setdefault('JAX_PLATFORMS', 'cpu')
flags = os.environ.get('XLA_FLAGS', '')
if 'xla_force_host_platform_device_count' not in flags:
    os.environ['XLA_FLAGS'] = \
        flags + ' --xla_force_host_platform_device_count=8'

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
