"""Appendix-B operator-family audit (VERDICT r5 #8): every root
`paddle/fluid/operators/*_op.cc` family in SURVEY.md Appendix B either
RESOLVES to a public callable here, or carries an explicit disposition
(loud raiser with guidance / XLA-subsumed infrastructure / superseded
plumbing). A family that is neither is a silent gap and fails the test.

Plus value tests for the two formerly-absent families bilateral_slice
and correlation (operators/bilateral_slice_op.cc, correlation_op.cc)
against independent numpy oracles.
"""
import math

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.static.nn as L
import paddle_tpu.nn.functional as F
from paddle_tpu.core.tensor import Tensor


# ---------------------------------------------------------------------------
# the full Appendix-B root-family list (SURVEY.md:847-881)
# ---------------------------------------------------------------------------
FAMILIES = """
abs activation addmm affine_channel affine_grid allclose arg_max arg_min
argsort array_to_lod_tensor assert assign assign_value atan2 attention_lstm
average_accumulates batch_fc batch_norm bce_loss beam_search
beam_search_decode bernoulli bilateral_slice bilinear_tensor_product bmm
bpr_loss broadcast_tensors cast center_loss cholesky chunk_eval clip
clip_by_norm coalesce_tensor concat conv2d conv3d conv_shift conv_transpose
correlation cos_sim crf_decoding crop crop_tensor cross cross_entropy
cross_entropy2 ctc_align cudnn_lstm cumsum cvm data_norm decode_jpeg
deformable_conv deformable_conv_v1 deformable_psroi_pooling dequantize
detection_map dgc dgc_clip_by_norm diag diag_embed diag_v2 diagonal digamma
dist dot dropout edit_distance empty erf expand expand_as expand_v2 eye
fake_dequantize fake_quantize fc fill fill_any_like fill_constant
fill_zeros_like filter_by_instag flatten flip fsp
fused_softmax_mask_upper_triangle gather gather_nd gather_tree
gaussian_random gelu get_tensor_from_selected_rows grid_sampler group_norm
gru gru_unit hash hierarchical_sigmoid hinge_loss histogram huber_loss
im2sequence imag real increment index_sample index_select inplace_abn
instance_norm interpolate interpolate_v2 inverse is_empty isfinite
isfinite_v2 kldiv_loss kron l1_norm label_smooth layer_norm lgamma
linear_chain_crf linspace load load_combine lod_array_length
lod_rank_table lod_reset lod_tensor_to_array log_loss log_softmax
lookup_table lookup_table_v2 lookup_table_dequant lrn lstm lstm_unit lstmp
margin_rank_loss marker masked_select match_matrix_tensor matmul matmul_v2
max_sequence_len maxout mean mean_iou memcpy merge_lod_tensor
merge_selected_rows meshgrid minus mish modified_huber_loss mul
multinomial multiplex mv nce nll_loss nop norm one_hot one_hot_v2 p_norm
pad pad2d pad3d pad_constant_like partial_concat partial_sum pixel_shuffle
pool2d pool3d pool_with_index positive_negative_pair prelu print
prroi_pool psroi_pool pull_box_sparse pull_sparse pull_sparse_v2
push_dense py_func py_layer pyramid_hash quantize requantize
queue_generator randint random_crop randperm range rank_attention
rank_loss read_file recurrent reorder_lod_tensor_by_rank reshape reverse
rnn rnn_memory_helper roi_align roi_pool roll row_conv run_program
sample_logits sampling_id save save_combine scale scatter scatter_nd_add
seed segment_pool select_input select_output selu set_value shape
shard_index share_data shrink_rnn_memory shuffle_batch shuffle_channel
sigmoid_cross_entropy_with_logits sign similarity_focus size slice
smooth_l1_loss softmax softmax_with_cross_entropy space_to_depth
spectral_norm split split_lod_tensor spp squared_l2_distance
squared_l2_norm squeeze stack strided_slice sum sync_batch_norm tdm_child
tdm_sampler teacher_student_sigmoid_loss temporal_shift
tensor_array_to_tensor tile top_k top_k_v2 trace transpose tree_conv
tril_triu trunc truncated_gaussian_random unbind unfold uniform_random
unique unique_with_counts unpool unsqueeze unstack var_conv_2d warpctc
where where_index
""".split()


# families whose public spelling differs from the op name
ALIASES = {
    'activation': 'F.relu',            # the ~40-activation family file
    'arg_max': 'paddle.argmax', 'arg_min': 'paddle.argmin',
    'assign_value': 'C.assign_value',
    'average_accumulates': 'paddle.incubate.ModelAverage',
    'batch_norm': 'F.batch_norm',
    'bce_loss': 'F.binary_cross_entropy',
    'set_value': 'paddle.Tensor.__setitem__',
    'beam_search': 'L.beam_search',
    'beam_search_decode': 'L.beam_search_decode',
    'bilateral_slice': 'L.bilateral_slice',
    'bilinear_tensor_product': 'L.bilinear_tensor_product',
    'correlation': 'L.correlation',
    'batch_fc': 'L.batch_fc',
    'bpr_loss': 'L.bpr_loss',
    'center_loss': 'L.center_loss',
    'chunk_eval': 'L.chunk_eval',
    'clip_by_norm': 'L.clip_by_norm',
    'conv2d': 'F.conv2d', 'conv3d': 'F.conv3d',
    'conv_shift': 'C.conv_shift',
    'conv_transpose': 'F.conv2d_transpose',
    'cos_sim': 'L.cos_sim',
    'crf_decoding': 'L.crf_decoding',
    'crop': 'paddle.crop', 'crop_tensor': 'paddle.crop',
    'cross_entropy': 'F.cross_entropy',
    'cross_entropy2': 'F.cross_entropy',
    'ctc_align': 'L.ctc_align',
    'cvm': 'L.continuous_value_model',
    'data_norm': 'L.data_norm',
    'decode_jpeg': 'paddle.vision.ops.decode_jpeg',
    'deformable_conv': 'paddle.vision.ops.deform_conv2d',
    'deformable_conv_v1': 'paddle.vision.ops.deform_conv2d',
    'deformable_psroi_pooling': 'L.deformable_roi_pooling',
    'detection_map': 'D.DetectionMAP',
    'dgc': 'paddle.optimizer.DGCMomentumOptimizer',
    'dgc_clip_by_norm': 'paddle.optimizer.DGCMomentumOptimizer',
    'diag_embed': 'F.diag_embed', 'diag_v2': 'paddle.diag',
    'dist': 'paddle.dist',
    'edit_distance': 'L.edit_distance',
    'expand_v2': 'paddle.expand',
    'fake_dequantize': 'mod:paddle_tpu.quantization',
    'fake_quantize': 'mod:paddle_tpu.quantization',
    'fc': 'L.fc',
    'fill': 'paddle.full', 'fill_constant': 'paddle.full',
    'fill_any_like': 'paddle.full_like',
    'fill_zeros_like': 'paddle.zeros_like',
    'filter_by_instag': 'L.filter_by_instag',
    'fsp': 'L.fsp_matrix',
    'fused_softmax_mask_upper_triangle':
        'F.fused_softmax_mask_upper_triangle',
    'gather_tree': 'L.gather_tree',
    'gaussian_random': 'paddle.normal',
    'get_tensor_from_selected_rows': 'L.get_tensor_from_selected_rows',
    'grid_sampler': 'F.grid_sample',
    'gru': 'paddle.nn.GRU', 'gru_unit': 'L.gru_unit',
    'hash': 'L.hash',
    'hierarchical_sigmoid': 'F.hsigmoid_loss',
    'hinge_loss': 'F.hinge_loss',
    'histogram': 'paddle.histogram',
    'huber_loss': 'L.huber_loss',
    'im2sequence': 'L.im2sequence',
    'imag': 'paddle.imag', 'real': 'paddle.real',
    'index_sample': 'paddle.index_sample',
    'inplace_abn': 'F.batch_norm',
    'instance_norm': 'F.instance_norm',
    'interpolate': 'F.interpolate', 'interpolate_v2': 'F.interpolate',
    'isfinite': 'paddle.isfinite', 'isfinite_v2': 'paddle.isfinite',
    'kldiv_loss': 'F.kl_div',
    'l1_norm': 'C.l1_norm',
    'label_smooth': 'F.label_smooth',
    'linear_chain_crf': 'L.linear_chain_crf',
    'load': 'paddle.load', 'load_combine': 'paddle.load',
    'log_loss': 'F.log_loss',
    'lookup_table': 'F.embedding', 'lookup_table_v2': 'F.embedding',
    'lrn': 'L.lrn',
    'lstm': 'paddle.nn.LSTM', 'lstm_unit': 'L.lstm_unit',
    'lstmp': 'paddle.nn.LSTM',
    'margin_rank_loss': 'L.margin_rank_loss',
    'match_matrix_tensor': 'L.match_matrix_tensor',
    'matmul_v2': 'paddle.matmul',
    'maxout': 'F.maxout',
    'mean_iou': 'L.mean_iou',
    'merge_selected_rows': 'L.merge_selected_rows',
    'minus': 'paddle.subtract',
    'mish': 'F.mish',
    'modified_huber_loss': 'C.modified_huber_loss',
    'mul': 'L.mul',
    'nce': 'L.nce',
    'nll_loss': 'F.nll_loss',
    'norm': 'paddle.norm', 'p_norm': 'paddle.norm',
    'one_hot': 'F.one_hot', 'one_hot_v2': 'F.one_hot',
    'pad': 'F.pad', 'pad2d': 'F.pad', 'pad3d': 'F.pad',
    'pad_constant_like': 'L.pad_constant_like',
    'partial_concat': 'C.partial_concat',
    'partial_sum': 'C.partial_sum',
    'pixel_shuffle': 'F.pixel_shuffle',
    'pool2d': 'F.max_pool2d', 'pool3d': 'F.max_pool3d',
    'pool_with_index': 'F.max_pool2d',
    'positive_negative_pair': 'L.positive_negative_pair',
    'prelu': 'F.prelu',
    'print': 'L.Print',
    'prroi_pool': 'L.prroi_pool',
    'psroi_pool': 'paddle.vision.ops.psroi_pool',
    'py_func': 'L.py_func',
    'py_layer': 'paddle.autograd.PyLayer',
    'pyramid_hash': 'L.search_pyramid_hash',
    'quantize': 'mod:paddle_tpu.quantization',
    'requantize': 'mod:paddle_tpu.quantization',
    'dequantize': 'mod:paddle_tpu.quantization',
    'randint': 'paddle.randint',
    'random_crop': 'L.random_crop',
    'randperm': 'paddle.randperm',
    'range': 'paddle.arange',
    'rank_attention': 'L.rank_attention',
    'rank_loss': 'L.rank_loss',
    'read_file': 'paddle.vision.ops.read_file',
    'recurrent': 'L.StaticRNN',
    'rnn': 'paddle.nn.SimpleRNN',
    'roi_align': 'paddle.vision.ops.roi_align',
    'roi_pool': 'paddle.vision.ops.roi_pool',
    'row_conv': 'L.row_conv',
    'run_program': 'paddle.jit.to_static',
    'sample_logits': 'L.sample_logits',
    'sampling_id': 'L.sampling_id',
    'save': 'paddle.save', 'save_combine': 'paddle.save',
    'scatter_nd_add': 'paddle.scatter_nd_add',
    'seed': 'paddle.seed',
    'segment_pool': 'paddle.incubate.segment_sum',
    'shard_index': 'paddle.shard_index',
    'share_data': 'paddle.assign',
    'shuffle_batch': 'L.shuffle_batch',
    'shuffle_channel': 'L.shuffle_channel',
    'sigmoid_cross_entropy_with_logits':
        'F.binary_cross_entropy_with_logits',
    'similarity_focus': 'L.similarity_focus',
    'size': 'paddle.numel',
    'smooth_l1_loss': 'F.smooth_l1_loss',
    'softmax_with_cross_entropy': 'F.softmax_with_cross_entropy',
    'space_to_depth': 'L.space_to_depth',
    'spectral_norm': 'L.spectral_norm',
    'spp': 'L.spp',
    'squared_l2_distance': 'L.square_error_cost',
    'sum': 'paddle.add_n',
    'sync_batch_norm': 'paddle.nn.SyncBatchNorm',
    'tdm_child': 'L.tdm_child', 'tdm_sampler': 'L.tdm_sampler',
    'teacher_student_sigmoid_loss': 'L.teacher_student_sigmoid_loss',
    'temporal_shift': 'F.temporal_shift',
    'top_k': 'paddle.topk', 'top_k_v2': 'paddle.topk',
    'tree_conv': 'L.tree_conv',
    'tril_triu': 'paddle.tril',
    'truncated_gaussian_random': 'paddle.nn.initializer.TruncatedNormal',
    'uniform_random': 'paddle.uniform',
    'unique_with_counts': 'paddle.unique',
    'unpool': 'C.unpool',
    'var_conv_2d': 'L.var_conv_2d',
    'warpctc': 'F.ctc_loss',
    'where_index': 'paddle.nonzero',
    'is_empty': 'L.is_empty',
    'increment': 'paddle.increment',
    'multiplex': 'paddle.multiplex',
}

# families that are infrastructure the TPU/XLA architecture replaces —
# each with the subsuming mechanism (SURVEY §1-L2/L4 dispositions)
SUBSUMED = {
    'array_to_lod_tensor': 'no LoD: dense fixed-width layout + masks',
    'lod_array_length': 'TensorArray length — L.array_length',
    'lod_rank_table': 'LoD plumbing: dense layout + explicit lengths',
    'lod_reset': 'no LoD: dense layout',
    'lod_tensor_to_array': 'no LoD: dense layout',
    'max_sequence_len': 'LoD plumbing: lengths are explicit tensors',
    'merge_lod_tensor': 'LoD control flow: jnp.where on dense tensors',
    'split_lod_tensor': 'LoD control flow: jnp.where on dense tensors',
    'reorder_lod_tensor_by_rank': 'LoD plumbing: argsort + gather',
    'shrink_rnn_memory': 'StaticRNN internals: lax.scan carries',
    'rnn_memory_helper': 'StaticRNN internals: lax.scan carries',
    'coalesce_tensor': 'grad-fusion buffer: XLA fuses/plans memory',
    'memcpy': 'device copies: PJRT owns placement',
    'marker': 'profiler marker: xplane annotations',
    'nop': 'scheduler no-op: XLA schedules',
    'queue_generator': 'pipeline queues: SpmdPipelineEngine ring buffer',
    'select_input': 'control-flow plumbing of cond: lax.cond replay',
    'select_output': 'control-flow plumbing of cond: lax.cond replay',
    'tensor_array_to_tensor': 'L.tensor_array_to_tensor (TensorArray stack)',
    'attention_lstm': 'fused CPU kernel: composed nn ops reach the '
                      'same HLO after XLA fusion',
    'cudnn_lstm': 'cuDNN binding: paddle.nn.LSTM lowers to XLA',
    'fake_dequantize': 'QAT sim ops: paddle.quantization pass',
    'assert': 'L.Assert',
    'get_tensor_from_selected_rows': 'no SelectedRows: dense grads '
                                     '(rows live in the PS tables)',
    'lookup_table_dequant': 'int8 embedding pull: quantization fake-'
                            'quant + F.embedding cover the semantics',
    'pull_box_sparse': 'PS wire: PsClient.pull (distributed/ps/service.py)',
    'pull_sparse': 'PS wire: PsClient.pull (distributed/ps/service.py)',
    'pull_sparse_v2': 'PS wire: PsClient.pull (distributed/ps/service.py)',
    'push_dense': 'PS wire: PsClient.push (distributed/ps/service.py)',
    'squared_l2_norm': 'grad-clip plumbing: ClipGradByGlobalNorm inlines '
                       'it (sharding_pass.py:107 records the op)',
}


def _resolve(path):
    import importlib
    import paddle_tpu.ops.contrib as C
    import paddle_tpu.vision.detection as D
    if path.startswith('mod:'):
        try:
            return importlib.import_module(path[4:])
        except ImportError:
            return None
    ns = {'paddle': paddle, 'L': L, 'F': F, 'C': C, 'D': D}
    obj = ns[path.split('.')[0]]
    for part in path.split('.')[1:]:
        obj = getattr(obj, part, None)
        if obj is None:
            return None
    return obj


def test_appendix_b_families_all_accounted():
    missing, dead_alias = [], []
    for fam in FAMILIES:
        if fam in SUBSUMED:
            # disposition strings name the replacing mechanism; spot
            # resolvable ones (L.xxx) must actually resolve
            target = SUBSUMED[fam].split()[0]
            if target.startswith('L.') and _resolve(target) is None:
                dead_alias.append((fam, target))
            continue
        path = ALIASES.get(fam)
        if path is not None:
            if _resolve(path) is None:
                dead_alias.append((fam, path))
            continue
        # default: the op name itself on paddle / F / L
        if any(_resolve(f'{ns}.{fam}') is not None
               for ns in ('paddle', 'F', 'L')):
            continue
        missing.append(fam)
    assert not dead_alias, f"alias points nowhere: {dead_alias}"
    assert not missing, f"unaccounted op families: {missing}"


# ---------------------------------------------------------------------------
# bilateral_slice vs an independent numpy oracle
# ---------------------------------------------------------------------------
def _np_bilateral_slice(x, guide, grid, has_offset):
    N, Cin, H, W = x.shape
    _, Cg, D, Hg, Wg = grid.shape
    stride = Cin + 1 if has_offset else Cin
    Cout = Cg // stride
    out = np.zeros((N, Cout, H, W), np.float64)
    for b in range(N):
        for oc in range(Cout):
            for yy in range(H):
                for xx in range(W):
                    gx = (xx + 0.5) * Wg / W
                    gy = (yy + 0.5) * Hg / H
                    gz = guide[b, yy, xx] * D
                    fx = int(math.floor(gx - 0.5))
                    fy = int(math.floor(gy - 0.5))
                    fz = int(math.floor(gz - 0.5))
                    val = 0.0
                    for ic in range(stride):
                        c = stride * oc + ic
                        s = 0.0
                        for dz in (0, 1):
                            z = min(max(fz + dz, 0), D - 1)
                            wz = max(1.0 - math.sqrt(
                                (fz + dz + 0.5 - gz) ** 2 + 1e-8), 0.0)
                            for dy in (0, 1):
                                yq = min(max(fy + dy, 0), Hg - 1)
                                wy = max(1.0 - abs(fy + dy + 0.5 - gy),
                                         0.0)
                                for dx in (0, 1):
                                    xq = min(max(fx + dx, 0), Wg - 1)
                                    wx = max(
                                        1.0 - abs(fx + dx + 0.5 - gx),
                                        0.0)
                                    s += grid[b, c, z, yq, xq] \
                                        * wx * wy * wz
                        if ic < Cin:
                            val += s * x[b, ic, yy, xx]
                        else:
                            val += s
                    out[b, oc, yy, xx] = val
    return out.astype(np.float32)


@pytest.mark.parametrize('has_offset', [False, True])
def test_bilateral_slice_matches_oracle(has_offset):
    from paddle_tpu.ops.contrib import bilateral_slice
    rng = np.random.RandomState(0)
    N, Cin, H, W = 2, 3, 6, 5
    D, Hg, Wg = 4, 3, 3
    Cout = 3
    Cg = Cout * (Cin + 1) if has_offset else Cout * Cin
    x = rng.rand(N, Cin, H, W).astype('float32')
    guide = rng.rand(N, H, W).astype('float32')
    grid = rng.randn(N, Cg, D, Hg, Wg).astype('float32')
    got = np.asarray(bilateral_slice(Tensor(x), Tensor(guide),
                                     Tensor(grid), has_offset).data)
    want = _np_bilateral_slice(x, guide, grid, has_offset)
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


def test_bilateral_slice_grad_flows():
    from paddle_tpu.ops.contrib import bilateral_slice
    rng = np.random.RandomState(1)
    x = Tensor(rng.rand(1, 2, 4, 4).astype('float32'),
               stop_gradient=False)
    guide = Tensor(rng.rand(1, 4, 4).astype('float32'),
                   stop_gradient=False)
    grid = Tensor(rng.randn(1, 4, 3, 2, 2).astype('float32'),
                  stop_gradient=False)
    bilateral_slice(x, guide, grid, False).sum().backward()
    assert x.grad is not None and grid.grad is not None
    assert np.isfinite(np.asarray(guide.grad.data)).all()


# ---------------------------------------------------------------------------
# correlation vs an independent numpy oracle
# ---------------------------------------------------------------------------
def _np_correlation(x1, x2, pad, K, d):
    """Reference semantics (correlation_op InferShape + centered kernel):
    out[o] centers at padded coord o + border, border = d + (K-1)//2,
    output size H + 2*pad - 2*border."""
    N, C, H, W = x1.shape
    D = 2 * d + 1
    rad = (K - 1) // 2
    border = d + rad
    Ho, Wo = H + 2 * pad - 2 * border, W + 2 * pad - 2 * border
    p1 = np.pad(x1, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    p2 = np.pad(x2, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    out = np.zeros((N, D * D, Ho, Wo), np.float32)
    for b in range(N):
        for i in range(Ho):
            for j in range(Wo):
                ci, cj = i + border, j + border       # patch center
                for k in range(-d, d + 1):
                    for l in range(-d, d + 1):
                        a = p1[b, :, ci - rad:ci + rad + 1,
                               cj - rad:cj + rad + 1]
                        v = p2[b, :, ci + k - rad:ci + k + rad + 1,
                               cj + l - rad:cj + l + rad + 1]
                        out[b, (l + d) + D * (k + d), i, j] = \
                            (a * v).mean()
    return out


def test_correlation_matches_oracle():
    from paddle_tpu.ops.contrib import correlation
    rng = np.random.RandomState(13)
    x1 = rng.randn(2, 3, 4, 5).astype('float32')
    x2 = rng.randn(2, 3, 4, 5).astype('float32')
    got = np.asarray(correlation(Tensor(x1), Tensor(x2), pad_size=4,
                                 kernel_size=1, max_displacement=4).data)
    want = _np_correlation(x1, x2, 4, 1, 4)
    assert got.shape == (2, 81, 4, 5)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_correlation_kernel3_centered_and_guards():
    from paddle_tpu.ops.contrib import correlation
    rng = np.random.RandomState(3)
    x1 = rng.randn(1, 2, 5, 5).astype('float32')
    x2 = rng.randn(1, 2, 5, 5).astype('float32')
    # K=3: centered window, output size from the InferShape formula
    # (5 + 2*3 - 2*(2+1) = 5)
    got = np.asarray(correlation(Tensor(x1), Tensor(x2), pad_size=3,
                                 kernel_size=3, max_displacement=2).data)
    want = _np_correlation(x1, x2, 3, 3, 2)
    assert got.shape == want.shape == (1, 25, 5, 5)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
    # relaxed pad guard: pad < d + K - 1 is legal while output is
    # non-empty (5 + 2*1 - 2*2 = 3)
    got2 = np.asarray(correlation(Tensor(x1), Tensor(x2), pad_size=1,
                                  kernel_size=1, max_displacement=2).data)
    np.testing.assert_allclose(got2, _np_correlation(x1, x2, 1, 1, 2),
                               rtol=1e-5, atol=1e-6)
    with pytest.raises(NotImplementedError, match='stride'):
        correlation(Tensor(x1), Tensor(x2), 4, 1, 4, stride1=2)
    with pytest.raises(NotImplementedError, match='odd'):
        correlation(Tensor(x1), Tensor(x2), 3, 2, 2)
    with pytest.raises(ValueError, match='pad_size'):
        correlation(Tensor(x1), Tensor(x2), 1, 1, 4)


def test_correlation_grad_flows():
    from paddle_tpu.ops.contrib import correlation
    rng = np.random.RandomState(5)
    x1 = Tensor(rng.randn(1, 2, 4, 4).astype('float32'),
                stop_gradient=False)
    x2 = Tensor(rng.randn(1, 2, 4, 4).astype('float32'),
                stop_gradient=False)
    correlation(x1, x2, 2, 1, 2).sum().backward()
    assert x1.grad is not None and x2.grad is not None
