"""Diagnostics layer (ISSUE 2): flight-recorder ring semantics, hang
watchdog (single-process and true 2-rank forced hang), device-memory
forensics / structured OOM reports, rank-aware JSON-lines logging, and
engine teardown verified by the memory accountant."""
import json
import os
import socket
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core import memory as mem
from paddle_tpu.distributed import flight_recorder as fr
from paddle_tpu.distributed.fleet.utils import log_util

HERE = os.path.dirname(os.path.abspath(__file__))


# ---------------------------------------------------------------------------
# ring journal semantics
# ---------------------------------------------------------------------------
class TestFlightRecorderRing:
    def test_wraparound_keeps_newest_and_counts_dropped(self):
        r = fr.FlightRecorder(capacity=8, rank=0)
        for i in range(20):
            with r.span('all_reduce', gseq=i, nbytes=4 * i):
                pass
        entries = r.entries()
        assert len(entries) == 8
        assert [e['gseq'] for e in entries] == list(range(12, 20))
        assert r.dropped() == 12
        seqs = [e['seq'] for e in entries]
        assert seqs == sorted(seqs)              # monotonic
        assert seqs[-1] == r.seq() == 20

    def test_seq_monotonic_across_threads(self):
        r = fr.FlightRecorder(capacity=64, rank=0)

        def worker():
            for _ in range(50):
                s = r.record_enqueue('barrier')
                r.record_complete(s)
        ts = [threading.Thread(target=worker) for _ in range(4)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert r.seq() == 200
        seqs = [e['seq'] for e in r.entries()]
        assert seqs == sorted(seqs) and len(set(seqs)) == len(seqs)

    def test_pending_entries_pinned_against_eviction(self):
        """An incomplete entry is the hang evidence — later enqueues
        evict completed entries around it, never the pending one (an
        evicted pending entry would disarm the watchdog mid-hang)."""
        r = fr.FlightRecorder(capacity=2, rank=0)
        s0 = r.record_enqueue('all_gather', gseq=0)      # stays pending
        for i in range(1, 5):
            with r.span('all_gather', gseq=i):
                pass
        gseqs = [e['gseq'] for e in r.entries()]
        assert 0 in gseqs                                # pinned
        pend = r.first_incomplete()
        assert pend is not None and pend['gseq'] == 0
        r.record_complete(s0)       # late completion: unpins, monotonic
        assert r.first_incomplete() is None
        assert r.last_completed_seq() == 5

    def test_all_pending_still_bounds_memory(self):
        r = fr.FlightRecorder(capacity=2, rank=0)
        for g in range(4):
            r.record_enqueue('barrier', gseq=g)          # none complete
        assert len(r.entries()) == 2 and r.dropped() == 2
        r.record_complete(1)         # evicted seq: safe no-op
        assert len(r.entries()) == 2

    def test_first_incomplete_and_dump_frontier(self):
        r = fr.FlightRecorder(capacity=16, rank=3)
        for i in range(3):
            with r.span('all_reduce', gseq=i):
                pass
        r.record_enqueue('broadcast', gseq=3, nbytes=128)
        pend = r.first_incomplete()
        assert pend['op'] == 'broadcast' and pend['gseq'] == 3
        d = r.dump()
        assert d['rank'] == 3
        assert d['last_completed_gseq'] == 2
        assert d['first_incomplete_gseq'] == 3
        assert d['first_incomplete_op'] == 'broadcast'

    def test_capacity_validated(self):
        with pytest.raises(ValueError):
            fr.FlightRecorder(capacity=0)

    def test_collectives_journal_through_public_api(self):
        """The eager collective API journals into the process recorder."""
        import paddle_tpu.distributed as dist
        rec = fr.recorder()
        before = rec.seq()
        t = paddle.to_tensor(np.ones(4, 'float32'))
        dist.all_reduce(t)
        entries = rec.entries()
        assert rec.seq() > before
        assert entries[-1]['op'] == 'all_reduce'
        assert entries[-1]['t_complete'] is not None


# ---------------------------------------------------------------------------
# cross-rank analysis
# ---------------------------------------------------------------------------
class TestAnalyze:
    def _dumps(self):
        r0 = fr.FlightRecorder(capacity=8, rank=0)
        r1 = fr.FlightRecorder(capacity=8, rank=1)
        for g in range(3):
            for r in (r0, r1):
                with r.span('all_reduce', gseq=g):
                    pass
        r0.record_enqueue('all_reduce', gseq=3)
        return {0: r0.dump(), 1: r1.dump()}

    def test_names_stalled_rank_and_missing_seq(self):
        ana = fr.analyze(self._dumps())
        assert ana['frontier_gseq'] == 3
        assert ana['stalled_ranks'] == [1]
        assert ana['ranks'][0]['first_incomplete_gseq'] == 3
        assert ana['ranks'][1]['last_completed_gseq'] == 2
        assert any('rank 1 never entered all_reduce gseq=3' in s
                   for s in ana['summary'])

    def test_missing_dump_is_reported_dead(self):
        dumps = self._dumps()
        dumps[1] = None
        ana = fr.analyze(dumps)
        assert 1 in ana['stalled_ranks']
        assert any('no dump received' in s for s in ana['summary'])

    def test_render_dump_mentions_pending(self):
        doc = {'kind': 'hang_report', 'reason': 'test',
               'ranks': {str(k): v for k, v in self._dumps().items()},
               'analysis': fr.analyze(self._dumps())}
        text = fr.render_dump(doc)
        assert 'PENDING' in text and 'never entered' in text


# ---------------------------------------------------------------------------
# watchdog — single process
# ---------------------------------------------------------------------------
class TestWatchdogLocal:
    def test_fires_on_stalled_collective(self, tmp_path):
        r = fr.FlightRecorder(capacity=8, rank=0)
        with r.span('all_reduce', gseq=0):
            pass
        r.record_enqueue('all_reduce', gseq=1)
        reports = []
        dog = fr.HangWatchdog(timeout=0.4, interval=0.1, recorder=r,
                              world_size=1, dump_dir=str(tmp_path),
                              on_dump=reports.append).start()
        try:
            assert dog.fired.wait(5.0), "watchdog never fired"
        finally:
            dog.stop()
        rep = reports[0]
        assert rep['reason'].startswith('collective all_reduce gseq=1')
        assert rep['ranks']['0']['first_incomplete_gseq'] == 1
        assert any('MainThread' in k for k in
                   rep['ranks']['0']['stacks'])
        assert os.path.exists(dog.report_path)
        with open(dog.report_path) as f:
            assert json.load(f)['kind'] == 'hang_report'

    def test_fires_on_stale_heartbeat(self):
        r = fr.FlightRecorder(capacity=8, rank=0)
        r.heartbeat()
        reports = []
        dog = fr.HangWatchdog(timeout=0.4, interval=0.1, recorder=r,
                              world_size=1, dump_dir='/tmp',
                              on_dump=reports.append).start()
        try:
            assert dog.fired.wait(5.0)
        finally:
            dog.stop()
        assert 'heartbeat stale' in reports[0]['reason']

    def test_quiet_when_progressing(self):
        r = fr.FlightRecorder(capacity=8, rank=0)
        dog = fr.HangWatchdog(timeout=0.5, interval=0.1, recorder=r,
                              world_size=1, dump_dir='/tmp').start()
        try:
            for g in range(6):
                r.heartbeat()
                with r.span('all_reduce', gseq=g):
                    pass
                time.sleep(0.1)
            assert not dog.fired.is_set()
        finally:
            dog.stop()

    def test_daemonized_and_stop_idempotent(self):
        dog = fr.HangWatchdog(timeout=30, interval=0.1,
                              recorder=fr.FlightRecorder(8),
                              world_size=1).start()
        assert dog._thread.daemon
        dog.stop()
        assert dog._thread is None
        dog.stop()                      # idempotent

    def test_published_dump_bounded_under_store_cap(self):
        """The cross-rank copy must fit the TCPStore 1 MiB get cap (a
        truncated JSON would make a HEALTHY rank look dead to peers):
        stacks stay local-only, the journal tail shrinks to fit."""
        r = fr.FlightRecorder(capacity=512, rank=0)
        blob = 'x' * 4000
        for g in range(512):
            with r.span(f'all_reduce_{blob}', gseq=g):
                pass
        local = r.dump()
        local['stacks'] = fr._thread_stacks()
        data = fr.HangWatchdog._publish_payload(local)
        assert len(data) <= 900_000
        doc = json.loads(data.decode())
        assert 'stacks' not in doc
        assert doc['last_completed_gseq'] == 511
        assert doc['entries'][-1]['gseq'] == 511

    def test_start_watchdog_env_gated_singleton(self, monkeypatch):
        fr.stop_watchdog()
        monkeypatch.delenv('PADDLE_HANG_TIMEOUT', raising=False)
        assert fr.start_watchdog() is None
        monkeypatch.setenv('PADDLE_HANG_TIMEOUT', '30')
        dog = fr.start_watchdog()
        try:
            assert dog is not None and dog.timeout == 30.0
            assert fr.start_watchdog() is dog     # singleton
        finally:
            fr.stop_watchdog()


# ---------------------------------------------------------------------------
# watchdog — true 2-rank forced hang (ISSUE 2 acceptance scenario)
# ---------------------------------------------------------------------------
class TestWatchdogCrossRank:
    def test_forced_hang_produces_cross_rank_report(self, tmp_path):
        """Rank 1 goes silent before the 4th all_reduce; both ranks'
        watchdogs dump via the TCPStore and the combined report names
        the last completed and first missing collective seq per rank."""
        s = socket.socket()
        s.bind(('127.0.0.1', 0))
        port = s.getsockname()[1] - 7     # host backend adds +7
        s.close()
        procs = []
        for rank in range(2):
            env = dict(os.environ)
            env.update({
                'PADDLE_TRAINER_ID': str(rank),
                'PADDLE_TRAINERS_NUM': '2',
                'PADDLE_MASTER': f'127.0.0.1:{port}',
                'JAX_PLATFORMS': 'cpu',
                'FLIGHT_DUMP_DIR': str(tmp_path),
            })
            env.pop('XLA_FLAGS', None)
            procs.append(subprocess.Popen(
                [sys.executable, '-u',
                 os.path.join(HERE, 'dist_models',
                              'dist_flight_recorder.py')],
                env=env, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT, text=True))
        outs = []
        for p in procs:
            out, _ = p.communicate(timeout=120)
            outs.append(out)
            assert p.returncode == 3, \
                f"expected watchdog abort (3), got {p.returncode}: {out}"
        rep_path = [f for f in os.listdir(tmp_path)
                    if f.startswith('flight_recorder.rank0')]
        assert rep_path, (os.listdir(tmp_path), outs)
        with open(os.path.join(tmp_path, rep_path[0])) as f:
            rep = json.load(f)
        ana = rep['analysis']
        # rank 0 entered gseq=3 and is blocked; rank 1 never arrived
        assert rep['ranks']['0']['first_incomplete_gseq'] == 3
        assert rep['ranks']['0']['first_incomplete_op'] == 'all_reduce'
        assert rep['ranks']['1'] is not None, \
            "rank 1's journal missing from the cross-rank dump"
        assert rep['ranks']['1']['last_completed_gseq'] == 2
        assert ana['stalled_ranks'] == [1]
        assert any('rank 1 never entered all_reduce gseq=3' in s
                   for s in ana['summary']), ana['summary']
        # both ranks' journals carry the 3 completed lockstep collectives
        for rk in ('0', '1'):
            done = [e for e in rep['ranks'][rk]['entries']
                    if e['gseq'] is not None and e['t_complete']]
            assert {e['gseq'] for e in done} >= {0, 1, 2}


# ---------------------------------------------------------------------------
# memory forensics
# ---------------------------------------------------------------------------
class TestMemoryAccountant:
    def test_phase_census_tracks_live_buffers_and_delta(self):
        import jax.numpy as jnp
        mem.reset()
        with mem.phase('engine.init'):          # census phase
            keep = jnp.ones((64, 64), jnp.float32) * 2
            float(keep.sum())                   # materialize
        ph = mem.accountant().phases()['engine.init']
        assert ph['calls'] == 1
        assert ph['live_buffers'] >= 1
        assert ph['high_water'] >= ph['bytes_exit'] > 0
        tl = mem.accountant().timeline()
        assert tl[-1]['phase'] == 'engine.init'
        del keep

    def test_oom_report_structure_and_suspect(self):
        import jax.numpy as jnp
        mem.reset()
        with mem.phase('pipeline.build', census=True):
            keep = jnp.ones((128, 128), jnp.float32) + 1
            float(keep.sum())
        rep = mem.oom_report(RuntimeError('RESOURCE_EXHAUSTED: boom'))
        assert rep['kind'] == 'oom_report'
        assert rep['suspect_phase'] == 'pipeline.build'
        assert rep['live_buffer_count'] >= 1
        assert rep['top_buffers'][0]['bytes'] > 0
        text = mem.render_oom_report(rep)
        assert 'suspect phase: pipeline.build' in text
        assert 'top live buffers' in text
        del keep

    def test_oom_guard_converts_resource_exhausted(self, tmp_path):
        mem.reset()
        path = str(tmp_path / 'oom.json')
        with pytest.raises(mem.DeviceOOMError) as ei:
            with mem.oom_guard('test.site', report_path=path):
                raise RuntimeError(
                    'RESOURCE_EXHAUSTED: Out of memory allocating '
                    '8589934592 bytes')
        err = ei.value
        assert err.report['site'] == 'test.site'
        assert 'device OOM report' in str(err)
        assert os.path.exists(path)
        with open(path) as f:
            assert json.load(f)['kind'] == 'oom_report'

    def test_oom_guard_passes_other_errors_through(self):
        with pytest.raises(ValueError):
            with mem.oom_guard('test.site'):
                raise ValueError('not an oom')

    def test_is_oom_error(self):
        assert mem.is_oom_error(RuntimeError('RESOURCE_EXHAUSTED: x'))
        assert not mem.is_oom_error(RuntimeError('bad shape'))
        assert not mem.is_oom_error(None)


class TestEngineShutdown:
    def test_hybrid_engine_shutdown_releases_buffers(self):
        import jax
        from paddle_tpu import nn
        from paddle_tpu.core.tensor import Tensor
        from paddle_tpu.distributed import topology_runtime
        from paddle_tpu.distributed.fleet.meta_parallel.hybrid_engine \
            import HybridParallelTrainStep

        topology_runtime.build_mesh(['dp'], [1])
        paddle.seed(0)
        net = nn.Sequential(nn.Linear(32, 64), nn.ReLU(),
                            nn.Linear(64, 1))
        opt = paddle.optimizer.Adam(parameters=net.parameters())

        def loss_fn(m, x, y):
            d = m(x) - y
            return (d * d).mean()

        eng = HybridParallelTrainStep(net, loss_fn, opt)
        rng = np.random.RandomState(0)
        x = Tensor(rng.rand(8, 32).astype('float32'))
        y = Tensor(rng.rand(8, 1).astype('float32'))
        float(eng(x, y))
        before = len(jax.live_arrays())
        sample = eng.shutdown()
        after = len(jax.live_arrays())
        assert after < before, (before, after)
        assert sample['live_buffers'] == after
        assert eng._params is None and eng._compiled is None
        # idempotent + closed-engine guards
        eng.shutdown()
        with pytest.raises(RuntimeError, match='shut down'):
            eng(x, y)
        with pytest.raises(RuntimeError, match='shut down'):
            eng.sync_model()
        ph = mem.accountant().phases()
        assert 'engine.shutdown' in ph
        # teardown disarms the step heartbeat (no false hang after a
        # deliberate stop) and stops the env-gated watchdog
        assert fr.recorder().last_beat() is None

    def test_pipeline_engine_shutdown(self):
        import jax
        from paddle_tpu import nn
        from paddle_tpu.core.tensor import Tensor
        from paddle_tpu.distributed import topology_runtime
        from paddle_tpu.distributed.fleet.meta_parallel.spmd_pipeline \
            import SpmdPipelineEngine

        topology_runtime.build_mesh(['dp', 'pp'], [1, 1])
        paddle.seed(0)
        H, V = 16, 11

        class Embed(nn.Layer):
            def __init__(self):
                super().__init__()
                self.emb = nn.Embedding(V, H)

            def forward(self, ids):
                return self.emb(ids)

        class Head(nn.Layer):
            def __init__(self):
                super().__init__()
                self.proj = nn.Linear(H, V)

            def forward(self, h, labels):
                logits = self.proj(h)
                return nn.functional.cross_entropy(
                    logits.reshape([-1, V]), labels.reshape([-1])).mean()

        blocks = [nn.Linear(H, H) for _ in range(2)]
        embed, head = Embed(), Head()
        opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=[])
        eng = SpmdPipelineEngine(embed, blocks, head, opt,
                                 accumulate_steps=2)
        rng = np.random.RandomState(0)
        ids = Tensor(rng.randint(0, V, (4, 6)).astype('int32'))
        labels = Tensor(rng.randint(0, V, (4, 6)).astype('int64'))
        float(eng.train_batch((ids, labels)).data)
        before = len(jax.live_arrays())
        eng.shutdown()
        assert len(jax.live_arrays()) < before
        with pytest.raises(RuntimeError, match='shut down'):
            eng.train_batch((ids, labels))
        with pytest.raises(RuntimeError, match='shut down'):
            eng.sync_model()


# ---------------------------------------------------------------------------
# structured JSON-lines logging
# ---------------------------------------------------------------------------
class TestJsonLog:
    def test_schema_round_trip_with_rank_role_step(self, tmp_path,
                                                   monkeypatch):
        monkeypatch.setenv('FLEET_LOG_DIR', str(tmp_path))
        monkeypatch.setenv('PADDLE_TRAINER_ID', '5')
        log_util.configure(force=True)
        try:
            log_util.set_role('trainer')
            log_util.set_step(42)
            log_util.log_json('step_done', level='info', loss=0.5,
                              tokens=1024, shape=(2, 3))
            log_util.set_step(None)
            path = tmp_path / 'workerlog.5.jsonl'
            assert path.exists()
            lines = path.read_text().strip().splitlines()
            doc = log_util.parse_line(lines[-1])
            assert doc['event'] == 'step_done'
            assert doc['rank'] == 5
            assert doc['role'] == 'trainer'
            assert doc['step'] == 42
            assert doc['level'] == 'INFO'
            assert doc['fields']['loss'] == 0.5
            assert doc['fields']['tokens'] == 1024
            # non-JSON-able values are repr'd, never dropped
            assert doc['fields']['shape'] in ([2, 3], '(2, 3)')
            assert isinstance(doc['ts'], float) and 'iso' in doc
        finally:
            log_util.configure(force=True)

    def test_child_logger_keeps_rank_role_step(self, tmp_path,
                                               monkeypatch):
        """log_json(..., logger_name=...) routes through a CHILD logger;
        the rank/role/step context must survive propagation (filters on
        handlers, not the parent logger)."""
        monkeypatch.setenv('FLEET_LOG_DIR', str(tmp_path))
        monkeypatch.setenv('PADDLE_TRAINER_ID', '7')
        log_util.configure(force=True)
        try:
            log_util.set_step(9)
            log_util.log_json('child_event', logger_name='elastic', x=1)
            log_util.set_step(None)
            lines = (tmp_path / 'workerlog.7.jsonl').read_text() \
                .strip().splitlines()
            doc = log_util.parse_line(lines[-1])
            assert doc['rank'] == 7
            assert doc['step'] == 9
            assert doc['logger'].endswith('elastic')
        finally:
            log_util.configure(force=True)

    def test_parse_line_rejects_garbage(self):
        with pytest.raises(ValueError):
            log_util.parse_line('{"no_msg": 1}')
        with pytest.raises(ValueError):
            log_util.parse_line('not json')

    def test_level_env_filtering(self, tmp_path, monkeypatch):
        monkeypatch.setenv('FLEET_LOG_DIR', str(tmp_path))
        monkeypatch.setenv('FLEET_LOG_LEVEL', 'ERROR')
        monkeypatch.setenv('PADDLE_TRAINER_ID', '0')
        log_util.configure(force=True)
        try:
            log_util.log_json('quiet', level='info')
            log_util.log_json('loud', level='error')
            text = (tmp_path / 'workerlog.0.jsonl').read_text()
            assert 'loud' in text and 'quiet' not in text
        finally:
            log_util.configure(force=True)

    def test_layer_to_str_kept(self):
        assert log_util.layer_to_str('Linear', 4, 8, bias=True) == \
            'Linear(4, 8, bias=True)'


# ---------------------------------------------------------------------------
# health_dump CLI
# ---------------------------------------------------------------------------
class TestHealthDumpCli:
    def test_renders_hang_and_oom_artifacts(self, tmp_path):
        sys.path.insert(0, os.path.join(os.path.dirname(HERE), 'tools'))
        import health_dump

        r = fr.FlightRecorder(capacity=8, rank=0)
        with r.span('all_reduce', gseq=0):
            pass
        p1 = tmp_path / 'dump.json'
        p1.write_text(json.dumps(r.dump()))
        out = health_dump.render(json.loads(p1.read_text()))
        assert 'flight recorder' in out

        mem.reset()
        p2 = tmp_path / 'oom.json'
        p2.write_text(json.dumps(mem.oom_report(
            RuntimeError('RESOURCE_EXHAUSTED'))))
        out = health_dump.render(json.loads(p2.read_text()))
        assert 'device OOM report' in out

        with pytest.raises(ValueError):
            health_dump.render({'something': 'else'})
