"""paddle.inference deployment sheet over the StableHLO-AOT predictor
(reference: python/paddle/inference/__init__.py surface)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.core.tensor import Tensor


def test_inference_config_predictor_roundtrip(tmp_path):
    paddle.seed(0)
    model = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    x = Tensor(np.random.RandomState(0).rand(3, 4).astype(np.float32))
    want = np.asarray(model(x).data)

    from paddle_tpu.static.inference import export_layer
    prefix = str(tmp_path / 'm')
    export_layer(prefix, model, [x])

    cfg = paddle.inference.Config(prefix + '.pdmodel')
    cfg.switch_ir_optim(True)
    cfg.enable_memory_optim()
    pred = paddle.inference.create_predictor(cfg)
    assert pred.get_input_names() == ['x0']
    with pytest.raises(RuntimeError, match='first'):
        pred.get_output_names()              # arity known after run()

    # handle-style serving loop (the reference's documented flow)
    h = pred.get_input_handle('x0')
    h.copy_from_cpu(np.asarray(x.data))
    pred.run()
    assert pred.get_output_names() == ['out_0']
    out = pred.get_output_handle('out_0').copy_to_cpu()
    np.testing.assert_allclose(out, want, rtol=1e-5)
    with pytest.raises(KeyError, match='unknown output'):
        pred.get_output_handle('bogus').copy_to_cpu()

    # list-style call
    out2 = pred.run([np.asarray(x.data)])[0]
    np.testing.assert_allclose(np.asarray(out2), want, rtol=1e-5)


def test_predictor_pool_and_dtypes(tmp_path):
    paddle.seed(1)
    model = nn.Linear(3, 3)
    x = Tensor(np.ones((2, 3), np.float32))
    from paddle_tpu.static.inference import export_layer
    prefix = str(tmp_path / 'p')
    export_layer(prefix, model, [x])
    pool = paddle.inference.PredictorPool(
        paddle.inference.Config(prefix), size=2)
    # pool slots share ONE loaded model (reference weight sharing)
    assert pool.retrive(0)._inner is pool.retrive(1)._inner
    a = pool.retrive(0).run([np.ones((2, 3), np.float32)])[0]
    b = pool.retrieve(1).run([np.ones((2, 3), np.float32)])[0]
    np.testing.assert_allclose(np.asarray(a), np.asarray(b))
    assert paddle.inference.get_num_bytes_of_data_type('int64') == 8
    assert paddle.inference.get_version() == paddle.__version__
    assert paddle.inference.PlaceType.CPU.value == 'cpu'


def test_utils_sysconfig_onnx():
    assert paddle.utils.require_version('0.0.1')
    assert paddle.utils.require_version('0.0.1', max_version='0.1')
    assert paddle.utils.require_version('0.1.0rc0')
    with pytest.raises(Exception, match='required'):
        paddle.utils.require_version('999.0.0')
    assert paddle.sysconfig.get_include().endswith('csrc')
    with pytest.raises(NotImplementedError, match='StableHLO'):
        paddle.onnx.export(None, '/tmp/x')
