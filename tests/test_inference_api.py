"""paddle.inference deployment sheet over the StableHLO-AOT predictor
(reference: python/paddle/inference/__init__.py surface)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.core.tensor import Tensor


def test_inference_config_predictor_roundtrip(tmp_path):
    paddle.seed(0)
    model = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    x = Tensor(np.random.RandomState(0).rand(3, 4).astype(np.float32))
    want = np.asarray(model(x).data)

    from paddle_tpu.static.inference import export_layer
    prefix = str(tmp_path / 'm')
    export_layer(prefix, model, [x])

    cfg = paddle.inference.Config(prefix + '.pdmodel')
    cfg.switch_ir_optim(True)
    cfg.enable_memory_optim()
    pred = paddle.inference.create_predictor(cfg)
    assert pred.get_input_names() == ['x0']
    # arity comes from the StableHLO module at LOAD time (reference
    # parity: serving code enumerates fetch targets before feeding data)
    assert pred.get_output_names() == ['out_0']

    # handle-style serving loop (the reference's documented flow)
    h = pred.get_input_handle('x0')
    h.copy_from_cpu(np.asarray(x.data))
    pred.run()
    assert pred.get_output_names() == ['out_0']
    out = pred.get_output_handle('out_0').copy_to_cpu()
    np.testing.assert_allclose(out, want, rtol=1e-5)
    with pytest.raises(KeyError, match='unknown output'):
        pred.get_output_handle('bogus').copy_to_cpu()

    # list-style call
    out2 = pred.run([np.asarray(x.data)])[0]
    np.testing.assert_allclose(np.asarray(out2), want, rtol=1e-5)


def test_predictor_pool_and_dtypes(tmp_path):
    paddle.seed(1)
    model = nn.Linear(3, 3)
    x = Tensor(np.ones((2, 3), np.float32))
    from paddle_tpu.static.inference import export_layer
    prefix = str(tmp_path / 'p')
    export_layer(prefix, model, [x])
    pool = paddle.inference.PredictorPool(
        paddle.inference.Config(prefix), size=2)
    # pool slots share ONE loaded model (reference weight sharing)
    assert pool.retrive(0)._inner is pool.retrive(1)._inner
    a = pool.retrive(0).run([np.ones((2, 3), np.float32)])[0]
    b = pool.retrieve(1).run([np.ones((2, 3), np.float32)])[0]
    np.testing.assert_allclose(np.asarray(a), np.asarray(b))
    assert paddle.inference.get_num_bytes_of_data_type('int64') == 8
    assert paddle.inference.get_version() == paddle.__version__
    assert paddle.inference.PlaceType.CPU.value == 'cpu'


def test_utils_sysconfig_onnx():
    assert paddle.utils.require_version('0.0.1')
    assert paddle.utils.require_version('0.0.1', max_version='0.1')
    assert paddle.utils.require_version('0.1.0rc0')
    with pytest.raises(Exception, match='required'):
        paddle.utils.require_version('999.0.0')
    assert paddle.sysconfig.get_include().endswith('csrc')
    with pytest.raises(NotImplementedError, match='StableHLO'):
        paddle.onnx.export(None, '/tmp/x')


def test_inert_config_knobs_warn_once():
    """VERDICT r4 weak #6: accepted-but-inert Config switches must warn
    so nobody believes enable_tensorrt_engine() did anything."""
    import warnings as _w
    cfg = paddle.inference.Config()
    with _w.catch_warnings(record=True) as rec:
        _w.simplefilter('always')
        cfg.enable_tensorrt_engine(workspace_size=1 << 20)
        cfg.enable_mkldnn()
        cfg.enable_use_gpu(100, 0)
        # second call of an already-warned knob stays silent
        cfg.enable_tensorrt_engine()
    msgs = [str(w.message) for w in rec]
    assert len(msgs) == 3
    assert any('enable_tensorrt_engine' in m and 'NO effect' in m
               for m in msgs)
    assert any('enable_mkldnn' in m for m in msgs)
    assert any('enable_use_gpu' in m for m in msgs)
    # the XLA-subsumed switches are genuinely satisfied: no warning
    with _w.catch_warnings(record=True) as rec2:
        _w.simplefilter('always')
        cfg.switch_ir_optim(True)
        cfg.enable_memory_optim()
    assert not rec2


def test_inplace_functional_rebinds_input():
    """ADVICE r4: F.relu_/tanh_/softmax_ must honor the in-place
    contract — callers that keep using x see the new value."""
    from paddle_tpu.nn import functional as F
    x = Tensor(np.asarray([-1.0, 2.0], np.float32))
    out = F.relu_(x)
    np.testing.assert_allclose(np.asarray(x.data), [0.0, 2.0])
    np.testing.assert_allclose(np.asarray(out.data), np.asarray(x.data))
    x2 = Tensor(np.asarray([0.5, -0.5], np.float32))
    F.tanh_(x2)
    np.testing.assert_allclose(np.asarray(x2.data), np.tanh([0.5, -0.5]),
                               rtol=1e-6)
    x3 = Tensor(np.asarray([[1.0, 2.0]], np.float32))
    F.softmax_(x3)
    np.testing.assert_allclose(np.asarray(x3.data).sum(), 1.0, rtol=1e-6)


def test_unique_name_guard_exact_restore_and_optin_merge():
    """ADVICE r5: guard() restores counters EXACTLY (reference
    semantics — checkpoint-name parity for programs built after a
    guard); the r4 anti-aliasing high-water merge is opt-in."""
    from paddle_tpu.utils import unique_name
    before = unique_name.generate('advtest_param')
    inside = []
    with unique_name.guard():
        inside.append(unique_name.generate('advtest_param'))
        inside.append(unique_name.generate('advtest_param'))
    after = unique_name.generate('advtest_param')
    # exact restore: the post-guard name continues the pre-guard
    # sequence as if the guard never ran (and thus repeats a guarded
    # name — the documented alias tradeoff)
    b_n = int(before.rsplit('_', 1)[1])
    assert after == before.replace(f'_{b_n}', f'_{b_n + 1}')
    assert after in inside

    merged = []
    with unique_name.guard(merge_high_water=True):
        merged.append(unique_name.generate('advtest_param'))
        merged.append(unique_name.generate('advtest_param'))
        merged.append(unique_name.generate('advtest_param'))
    after2 = unique_name.generate('advtest_param')
    assert after2 not in merged


def test_inplace_leaf_raises_under_autograd():
    """A grad-requiring LEAF can't be in-placed (reference: 'Leaf Var
    that doesn't stop gradient can't use inplace strategy')."""
    from paddle_tpu.nn import functional as F
    x = Tensor(np.asarray([-1.0, 2.0], np.float32), stop_gradient=False)
    with pytest.raises(RuntimeError, match='leaf'):
        F.relu_(x)
    # out-of-place on the same tensor is fine
    F.relu(x)
    # and under no_grad the rebind goes through
    with paddle.no_grad():
        F.relu_(x)
    np.testing.assert_allclose(np.asarray(x.data), [0.0, 2.0])


def test_inplace_nonleaf_grads_exact():
    """In-place on a NON-leaf is grafted into the tape: gradients
    through later uses of the rebound tensor include the op's
    derivative (h = relu_(h) — the standard paddle memory idiom)."""
    from paddle_tpu.nn import functional as F
    x = Tensor(np.asarray([-1.0, 2.0], np.float32), stop_gradient=False)
    h = x * 2.0
    out = F.relu_(h)
    assert out is h                       # the in-place result IS h
    (h * 3.0).sum().backward()
    # d/dx 3*relu(2x) = 3 * relu'(2x) * 2 = [0, 6]
    np.testing.assert_allclose(np.asarray(x.grad.data), [0.0, 6.0])


def test_inplace_after_consume_raises_at_backward():
    """Mutating a tensor an EARLIER op recorded for backward errors
    loudly at backward() (version-counter contract), instead of
    silently mis-routing that op's cotangent."""
    from paddle_tpu.nn import functional as F
    x = Tensor(np.asarray([-1.0, 2.0], np.float32), stop_gradient=False)
    h = x * 2.0
    y = h * 3.0                           # op records h (version 0)
    F.relu_(h)                            # then h is rebound in place
    with pytest.raises(RuntimeError, match='in-place'):
        y.sum().backward()


def test_unique_name_guard_prefix():
    """guard(new_generator=str) prefixes guarded names (reference
    UniqueNameGenerator prefix) — twin Programs can opt out of the
    intentional name sharing."""
    from paddle_tpu.utils import unique_name
    with unique_name.guard('rankA_'):
        a = unique_name.generate('w')
    with unique_name.guard('rankB_'):
        b = unique_name.generate('w')
    assert a.startswith('rankA_') and b.startswith('rankB_')
    assert a != b
    assert not unique_name.generate('w').startswith('rank')


def test_unique_name_nested_guard_and_switch_prefix():
    """A nested plain guard() resets the prefix (reference guard(None)
    installs a fresh generator); switch() round-trips prefix state."""
    from paddle_tpu.utils import unique_name
    with unique_name.guard('rankA_'):
        with unique_name.guard():
            assert not unique_name.generate('w').startswith('rankA_')
        assert unique_name.generate('w').startswith('rankA_')
    old = unique_name.switch('pfx_')
    try:
        assert unique_name.generate('w').startswith('pfx_')
    finally:
        unique_name.switch(old)
    assert not unique_name.generate('w').startswith('pfx_')
