"""Launcher + elastic tests (reference pattern: test_fleet_launch_*.sh,
test_fleet_elastic_manager.py — CLI-level, single host)."""
import os
import subprocess
import sys
import tempfile
import time

import numpy as np
import pytest

from paddle_tpu.core.native import load_native, TCPStore

pytestmark = pytest.mark.skipif(load_native() is None,
                                reason="native lib unavailable")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_launch_single_node_env():
    """fleetrun single-node: trainer sees the PADDLE_* env."""
    with tempfile.TemporaryDirectory() as tmp:
        script = os.path.join(tmp, 'train.py')
        with open(script, 'w') as f:
            f.write(
                "import os\n"
                "assert os.environ['PADDLE_TRAINER_ID'] == '0'\n"
                "assert os.environ['PADDLE_TRAINERS_NUM'] == '1'\n"
                "print('TRAINER_OK')\n")
        out = subprocess.run(
            [sys.executable, '-m', 'paddle_tpu.distributed.launch', script],
            capture_output=True, text=True, cwd=REPO,
            env={**os.environ, 'PYTHONPATH': REPO})
        assert 'TRAINER_OK' in out.stdout, out.stderr


def test_launch_two_node_rendezvous():
    """Two fleetrun pods on localhost rendezvous via the TCP store and each
    trainer learns the full endpoint list (reference: 2-proc dist tests)."""
    with tempfile.TemporaryDirectory() as tmp:
        script = os.path.join(tmp, 'train.py')
        with open(script, 'w') as f:
            f.write(
                "import os\n"
                "eps = os.environ['PADDLE_TRAINER_ENDPOINTS'].split(',')\n"
                "assert len(eps) == 2, eps\n"
                "print('RANK', os.environ['PADDLE_TRAINER_ID'], 'OK')\n")
        port = 17170 + np.random.RandomState().randint(500)
        env = {**os.environ, 'PYTHONPATH': REPO}
        p0 = subprocess.Popen(
            [sys.executable, '-m', 'paddle_tpu.distributed.launch',
             '--nnodes', '2', '--node_rank', '0',
             '--master', f'127.0.0.1:{port}', script],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            cwd=REPO, env=env)
        p1 = subprocess.Popen(
            [sys.executable, '-m', 'paddle_tpu.distributed.launch',
             '--nnodes', '2', '--node_rank', '1',
             '--master', f'127.0.0.1:{port}', script],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            cwd=REPO, env=env)
        out0, _ = p0.communicate(timeout=60)
        out1, _ = p1.communicate(timeout=60)
        assert 'RANK 0 OK' in out0, out0
        assert 'RANK 1 OK' in out1, out1
        assert p0.returncode == 0 and p1.returncode == 0


def test_launch_elastic_restart():
    """--elastic restarts a crashing trainer up to max_restarts."""
    with tempfile.TemporaryDirectory() as tmp:
        marker = os.path.join(tmp, 'count')
        script = os.path.join(tmp, 'train.py')
        with open(script, 'w') as f:
            f.write(
                f"import os, sys\n"
                f"p = {marker!r}\n"
                f"n = int(open(p).read()) if os.path.exists(p) else 0\n"
                f"open(p, 'w').write(str(n + 1))\n"
                f"sys.exit(1 if n < 2 else 0)\n")
        out = subprocess.run(
            [sys.executable, '-m', 'paddle_tpu.distributed.launch',
             '--nnodes', '1', '--elastic', '--max_restarts', '5', script],
            capture_output=True, text=True, cwd=REPO, timeout=90,
            env={**os.environ, 'PYTHONPATH': REPO})
        assert out.returncode == 0, out.stdout + out.stderr
        assert open(marker).read() == '3'  # crashed twice, then succeeded


def test_elastic_manager_membership():
    from paddle_tpu.distributed.fleet.elastic import (ElasticManager,
                                                      ElasticStatus)
    master = TCPStore(is_master=True)
    os.environ['PADDLE_CURRENT_ENDPOINT'] = 'hostA:1'
    m1 = ElasticManager(store=master, job_id='j1', np_min=1,
                        heartbeat_interval=0.2, dead_after=1.5)
    m1.register()
    c2 = TCPStore(port=master.port)
    os.environ['PADDLE_CURRENT_ENDPOINT'] = 'hostB:1'
    m2 = ElasticManager(store=c2, job_id='j1', np_min=1,
                        heartbeat_interval=0.2, dead_after=1.5)
    m2.register()
    time.sleep(0.5)
    known = ['hostA:1', 'hostB:1']
    assert m1.watch(known) == ElasticStatus.HOLD
    # hostB dies: stop its heartbeat, wait past dead_after
    m2.exit()
    time.sleep(2.0)
    assert m1.watch(known) == ElasticStatus.RESTART
    assert m1.hosts(known) == ['hostA:1']
    m1.exit()
    c2.close()
    master.close()


def test_multihost_world_via_fleetrun():
    """The full DCN deployment shape: two fleetrun pods rendezvous over the
    TCP store, form ONE jax.distributed world (2 procs x 4 virtual chips),
    and run a cross-process psum (reference: multi-node NCCL world; here
    PJRT multi-controller)."""
    with tempfile.TemporaryDirectory() as tmp:
        script = os.path.join(tmp, 'train.py')
        with open(script, 'w') as f:
            f.write(f'''
import sys, os
sys.path.insert(0, {REPO!r})
os.environ['XLA_FLAGS'] = '--xla_force_host_platform_device_count=4'
os.environ['JAX_PLATFORMS'] = 'cpu'
import jax
jax.config.update('jax_platforms', 'cpu')
import paddle_tpu as paddle
paddle.distributed.init_parallel_env()
assert jax.process_count() == 2
assert jax.device_count() == 8
import numpy as np_, jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
from jax.experimental.shard_map import shard_map
from jax.experimental import multihost_utils
mesh = Mesh(np_.array(jax.devices()).reshape(8), ('dp',))
arr = multihost_utils.host_local_array_to_global_array(
    np_.full((4, 1), float(os.environ['PADDLE_TRAINER_ID']) + 1.0,
             np_.float32), mesh, P('dp'))
out = jax.jit(shard_map(lambda x: jax.lax.psum(x, 'dp'), mesh=mesh,
                        in_specs=P('dp'), out_specs=P('dp')))(arr)
local = multihost_utils.global_array_to_host_local_array(out, mesh,
                                                         P('dp'))
assert float(np_.asarray(local.addressable_data(0))[0, 0]) == 12.0
print('MULTIHOST_OK', flush=True)
''')
        port = 18400 + np.random.RandomState().randint(400)
        # strip the axon sitecustomize so jax.distributed owns backend init
        env = {**os.environ, 'PYTHONPATH': REPO}
        procs = []
        for rank in (1, 0):
            procs.append(subprocess.Popen(
                [sys.executable, '-m', 'paddle_tpu.distributed.launch',
                 '--nnodes', '2', '--node_rank', str(rank),
                 '--master', f'127.0.0.1:{port}', script],
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True, cwd=REPO, env=env))
        outs = [p.communicate(timeout=120)[0] for p in procs]
        if any('Multiprocess computations aren\'t implemented on the CPU '
               'backend' in o for o in outs):
            # this image's jaxlib has no cross-process CPU collective
            # backend (gloo plugin absent) — the launch/rendezvous path
            # itself worked up to the psum, which is all we can check
            pytest.skip("jaxlib CPU backend lacks multiprocess "
                        "collectives in this image")
        for p, o in zip(procs, outs):
            assert 'MULTIHOST_OK' in o, o[-800:]
            assert p.returncode == 0
