"""Subprocess test matrix (parity: test_collective_base.py:32 +
test_dist_base.py:744 — real multi-process drills, one scenario per
dist_models script): per-collective checks, 2-trainer+1-server PS
convergence, elastic scale-down, TCPStore KV."""
import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

HERE = os.path.dirname(os.path.abspath(__file__))


def _free_port():
    s = socket.socket()
    s.bind(('127.0.0.1', 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _launch(script, rank, ws, port, extra_env=None):
    env = dict(os.environ)
    env.update({
        'PADDLE_TRAINER_ID': str(rank),
        'PADDLE_TRAINERS_NUM': str(ws),
        'PADDLE_MASTER': f'127.0.0.1:{port}',
        'JAX_PLATFORMS': 'cpu',
    })
    env.pop('XLA_FLAGS', None)
    env.update(extra_env or {})
    return subprocess.Popen(
        [sys.executable, '-u', os.path.join(HERE, 'dist_models', script)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True)


def _gather(procs, timeout=300):
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=timeout)
        assert p.returncode == 0, out[-3000:]
        outs.append(out)
    return outs


def _json_line(out, tag):
    line = [l for l in out.splitlines() if l.startswith(tag)][-1]
    return json.loads(line[len(tag):])


class TestCollectiveMatrix:
    def test_each_collective_two_process(self):
        port = _free_port() - 7       # host backend derives its own +7
        procs = [_launch('dist_collectives.py', r, 2, port)
                 for r in range(2)]
        outs = _gather(procs)
        res = [_json_line(o, 'RESULTS:') for o in outs]

        base = np.arange(4, dtype='float32')
        for r in range(2):
            np.testing.assert_allclose(res[r]['all_reduce_sum'],
                                       (base + 0) + (base + 10))
            np.testing.assert_allclose(res[r]['all_reduce_max'], base + 10)
            np.testing.assert_allclose(res[r]['broadcast'], [1.0] * 3)
            np.testing.assert_allclose(res[r]['all_gather'],
                                       [[0.0, 0.5], [1.0, 1.5]])
            # reduce_scatter: sum of both ranks' row r
            full = (np.arange(4, dtype='float32').reshape(2, 2)
                    + (np.arange(4, dtype='float32').reshape(2, 2) + 1))
            np.testing.assert_allclose(res[r]['reduce_scatter'], full[r])
            np.testing.assert_allclose(res[r]['scatter'],
                                       [float(r + 1)] * 2)


class TestPsSubprocess:
    def test_two_trainers_one_server_converge(self):
        srv = _launch('dist_ps_server.py', 0, 1, _free_port(),
                      extra_env={'PS_PORT': '0'})
        try:
            port_line = srv.stdout.readline()
            assert port_line.startswith('PORT:'), port_line
            ps_port = int(port_line.strip().split(':')[1])
            trainers = [
                _launch('dist_ps_trainer.py', r, 2, _free_port(),
                        extra_env={'PS_ENDPOINT':
                                   f'127.0.0.1:{ps_port}'})
                for r in range(2)]
            outs = _gather(trainers)
            for out in outs:
                losses = _json_line(out, 'LOSSES:')
                # shared table: both trainers converge toward w_true
                assert losses[-1] < 0.1 * losses[0], (losses[0],
                                                      losses[-1])
        finally:
            srv.kill()
            srv.wait(timeout=30)


class TestElasticScaleDown:
    def test_rank0_detects_scale_down(self):
        port = _free_port()
        procs = [_launch('dist_elastic.py', r, 2, port) for r in range(2)]
        outs = _gather(procs)
        r0 = next(o for o, p in zip(outs, procs))
        info = _json_line(outs[0], 'ELASTIC:')
        assert info['status'] == 'restart'
        assert info['alive'] == ['127.0.0.1:7001']
        assert 'RANK1_EXIT' in outs[1]


class TestStoreKV:
    def test_cross_process_kv(self):
        # retries: _free_port can race with another drill's lingering
        # listener between probe and the child's bind, and a loaded
        # 1-core host can starve the children past the timeout (the
        # full-suite flake from VERDICT r3 weak #5) — kill stragglers
        # and redo the drill on a fresh port
        last = None
        for attempt in range(3):
            procs = []
            try:
                port = _free_port()
                procs = [_launch('dist_store.py', r, 2, port)
                         for r in range(2)]
                outs = _gather(procs, timeout=120 * (attempt + 1))
                res = [_json_line(o, 'RESULTS:') for o in outs]
                assert res[0]['peer_value'] == 'hello-from-1'
                assert res[1]['peer_value'] == 'hello-from-0'
                for r in res:
                    assert r['final_counter'] == 3      # 1 + 2
                return
            except (AssertionError, IndexError, json.JSONDecodeError,
                    subprocess.TimeoutExpired) as e:
                last = e
                for p in procs:
                    if p.poll() is None:
                        p.kill()
                        p.communicate()
        raise last
