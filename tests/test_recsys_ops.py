"""Recsys/PS op tier vs numpy oracles (VERDICT r3 #6; op_test.py
pattern). Each oracle re-derives the reference kernel's loop semantics
independently of the jax implementation."""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

from paddle_tpu.core.tensor import Tensor
from paddle_tpu.ops import recsys as R


def _t(a):
    return Tensor(jnp.asarray(a))


class TestTdm:
    def _tree(self):
        # nodes 1..6: 1 root (children 2,3); 2 has children 4,5; 3 has
        # child 6; 4,5,6 leaves (item_id != 0)
        # row: [item_id, layer_id, ancestor, child0, child1]
        info = np.array([
            [0, 0, 0, 0, 0],     # node 0 = padding
            [0, 0, 0, 2, 3],     # 1
            [0, 1, 1, 4, 5],     # 2
            [0, 1, 1, 6, 0],     # 3
            [9, 2, 2, 0, 0],     # 4 (leaf, item 9)
            [8, 2, 2, 0, 0],     # 5 (leaf, item 8)
            [7, 2, 3, 0, 0],     # 6 (leaf, item 7)
        ], np.int32)
        return info

    def test_tdm_child_oracle(self):
        info = self._tree()
        x = np.array([1, 2, 3, 4, 0], np.int32)
        child, leaf = R.tdm_child(_t(x), _t(info), child_nums=2)
        # oracle: reference loop (tdm_child_op.h:53-92)
        want_c, want_m = [], []
        for nid in x:
            if nid != 0 and info[nid, 3] != 0:
                cs = [info[nid, 3 + j] for j in range(2)]
                want_c.append(cs)
                want_m.append([1 if (c > 0 and info[c, 0] != 0) else 0
                               for c in cs])
            else:
                want_c.append([0, 0])
                want_m.append([0, 0])
        np.testing.assert_array_equal(np.asarray(child.data), want_c)
        np.testing.assert_array_equal(np.asarray(leaf.data), want_m)

    def test_tdm_sampler_layerwise(self):
        # travel paths: item -> [layer0 node, layer1 node]
        travel = np.array([[0, 0], [1, 2], [1, 3]], np.int32)
        layer = np.array([1, 2, 3, 4, 5, 6], np.int32)   # l0: [1], l1: 2..6
        offs = [0, 1, 6]
        out, lab, msk = R.tdm_sampler(
            _t(np.array([1, 2], np.int32)), _t(travel), _t(layer),
            neg_samples_num_list=[0, 2], layer_offset_lod=offs,
            output_positive=True, seed=3)
        o, l, m = (np.asarray(v.data) for v in (out, lab, msk))
        assert o.shape == (2, 4)                 # (0+1) + (2+1)
        # item 1: path [1, 2] — positive rows labeled 1, negatives from
        # layer 1 nodes excluding the positive, no duplicates
        assert o[0, 0] == 1 and l[0, 0] == 1
        assert o[0, 1] == 2 and l[0, 1] == 1
        negs = o[0, 2:]
        assert len(set(negs)) == 2 and all(n in (3, 4, 5, 6) for n in negs)
        assert (m[0] == 1).all()
        # item 2's layer-1 positive is 3
        assert o[1, 1] == 3 and (o[1, 2:] != 3).all()

    def test_tdm_sampler_padding_masks(self):
        travel = np.array([[0, 0], [1, 0]], np.int32)   # truncated path
        layer = np.array([1, 2, 3], np.int32)
        out, lab, msk = R.tdm_sampler(
            _t(np.array([1], np.int32)), _t(travel), _t(layer),
            neg_samples_num_list=[0, 1], layer_offset_lod=[0, 1, 3],
            output_positive=True, seed=0)
        m = np.asarray(msk.data)
        assert m[0, 0] == 1 and (m[0, 1:] == 0).all()


class TestCvm:
    def test_forward_use_cvm(self):
        x = np.abs(np.random.RandomState(0).rand(4, 6)).astype('float32')
        y = R.continuous_value_model(_t(x), _t(x[:, :2]), use_cvm=True)
        got = np.asarray(y.data)
        want = x.copy()
        want[:, 0] = np.log(x[:, 0] + 1)
        want[:, 1] = np.log(x[:, 1] + 1) - want[:, 0]
        np.testing.assert_allclose(got, want, rtol=1e-6)

    def test_forward_no_cvm_drops_prefix(self):
        x = np.random.RandomState(1).rand(3, 5).astype('float32')
        y = R.continuous_value_model(_t(x), _t(x[:, :2]), use_cvm=False)
        np.testing.assert_allclose(np.asarray(y.data), x[:, 2:])

    def test_grad_lead_columns_from_cvm(self):
        # reference CvmGradComputeKernel: DX[:, :2] = CVM values
        x = jnp.asarray(np.random.RandomState(2).rand(3, 5), jnp.float32)
        cvm = jnp.asarray([[0.5, 0.25]] * 3, jnp.float32)
        g = jax.grad(lambda a: R._cvm_use(a, cvm).sum())(x)
        np.testing.assert_allclose(np.asarray(g[:, :2]),
                                   np.asarray(cvm), rtol=1e-6)
        np.testing.assert_allclose(np.asarray(g[:, 2:]),
                                   np.ones((3, 3)), rtol=1e-6)


class TestDataNorm:
    def test_normalize_and_update(self):
        rng = np.random.RandomState(3)
        x = rng.rand(8, 4).astype('float32') * 3
        bsize = np.full(4, 10.0, np.float32)
        bsum = rng.rand(4).astype('float32') * 10
        bsq = np.full(4, 12.0, np.float32)
        y, means, scales = R.data_norm(_t(x), _t(bsize), _t(bsum), _t(bsq))
        np.testing.assert_allclose(np.asarray(means.data), bsum / bsize,
                                   rtol=1e-6)
        np.testing.assert_allclose(np.asarray(scales.data),
                                   np.sqrt(bsize / bsq), rtol=1e-6)
        np.testing.assert_allclose(
            np.asarray(y.data), (x - bsum / bsize) * np.sqrt(bsize / bsq),
            rtol=1e-5)
        ns, nsum, nsq = R.data_norm_update(_t(x), _t(bsize), _t(bsum),
                                           _t(bsq), summary_decay=0.99)
        np.testing.assert_allclose(np.asarray(ns.data),
                                   bsize * 0.99 + 8, rtol=1e-6)
        np.testing.assert_allclose(np.asarray(nsum.data),
                                   bsum * 0.99 + x.sum(0), rtol=1e-5)
        np.testing.assert_allclose(np.asarray(nsq.data),
                                   bsq * 0.99 + (x * x).sum(0), rtol=1e-5)


class TestBatchFc:
    def test_vs_numpy(self):
        rng = np.random.RandomState(4)
        x = rng.rand(3, 5, 4).astype('float32')
        w = rng.rand(3, 4, 2).astype('float32')
        b = rng.rand(3, 2).astype('float32')
        out = R.batch_fc(_t(x), _t(w), _t(b))
        want = np.stack([x[s] @ w[s] + b[s] for s in range(3)])
        np.testing.assert_allclose(np.asarray(out.data), want, rtol=1e-5)


class TestRankAttention:
    def test_vs_reference_loops(self):
        rng = np.random.RandomState(5)
        N, D, P, K = 4, 3, 2, 2
        x = rng.rand(N, D).astype('float32')
        param = rng.rand(K * K * D, P).astype('float32')
        # rank_offset rows: [ins_rank, faster_0, idx_0, faster_1, idx_1]
        ro = np.array([
            [1, 1, 0, 2, 1],
            [2, 1, 0, 0, 0],     # slot 1 invalid (faster=0)
            [0, 1, 2, 2, 3],     # whole instance invalid (rank=0)
            [2, 2, 3, 1, 2],
        ], np.int32)
        out = np.asarray(R.rank_attention(
            _t(x), _t(ro), _t(param), max_rank=K).data)
        # oracle: expand loops from rank_attention.cu.h:28-92
        want = np.zeros((N, P), np.float32)
        for i in range(N):
            lower = ro[i, 0] - 1
            ih = np.zeros((K, D), np.float32)
            pm = np.zeros((K, D, P), np.float32)
            for k in range(K):
                faster = ro[i, 2 * k + 1] - 1
                if lower < 0 or faster < 0:
                    continue
                ih[k] = x[ro[i, 2 * k + 2]]
                start = lower * K + faster
                pm[k] = param.reshape(K * K, D, P)[start]
            want[i] = np.einsum('kd,kdp->p', ih, pm)
        np.testing.assert_allclose(out, want, rtol=1e-5)


class TestShuffleBatch:
    def test_is_permutation_and_grad_unshuffles(self):
        rng = np.random.RandomState(6)
        x = rng.rand(8, 3).astype('float32')
        out, idx = R.shuffle_batch(_t(x), seed=4)
        o, i = np.asarray(out.data), np.asarray(idx.data)
        assert sorted(i.tolist()) == list(range(8))
        np.testing.assert_allclose(o, x[i])


class TestMatchMatrix:
    def test_vs_numpy(self):
        rng = np.random.RandomState(7)
        B, Lx, Ly, D, T = 2, 3, 4, 5, 2
        x = rng.rand(B, Lx, D).astype('float32')
        y = rng.rand(B, Ly, D).astype('float32')
        w = rng.rand(D, T, D).astype('float32')
        out = np.asarray(R.match_matrix_tensor(_t(x), _t(y), _t(w)).data)
        for b in range(B):
            for t in range(T):
                want = x[b] @ w[:, t, :] @ y[b].T
                np.testing.assert_allclose(out[b, t], want, rtol=1e-5)

    def test_length_masking(self):
        rng = np.random.RandomState(8)
        x = rng.rand(1, 3, 4).astype('float32')
        y = rng.rand(1, 3, 4).astype('float32')
        w = rng.rand(4, 1, 4).astype('float32')
        out = np.asarray(R.match_matrix_tensor(
            _t(x), _t(y), _t(w), x_len=_t(np.array([2])),
            y_len=_t(np.array([1]))).data)
        assert (out[0, 0, 2, :] == 0).all()
        assert (out[0, 0, :, 1:] == 0).all()
        assert out[0, 0, 0, 0] != 0


class TestVarConv2d:
    def test_valid_region_matches_plain_conv(self):
        from jax import lax
        rng = np.random.RandomState(9)
        x = rng.rand(2, 1, 6, 6).astype('float32')
        w = rng.rand(2, 1, 3, 3).astype('float32')
        rl = np.array([6, 4])
        cl = np.array([6, 3])
        out = np.asarray(R.var_conv_2d(
            _t(x), _t(w), 1, 2, 3, row_lens=_t(rl), col_lens=_t(cl)).data)
        # sample 1: full-size — interior (pad-free region) matches a
        # plain conv computed on the true (cropped) image
        crop = x[1:2, :, :4, :3]
        ref = np.asarray(lax.conv_general_dilated(
            jnp.asarray(crop), jnp.asarray(w), (1, 1), 'SAME',
            dimension_numbers=('NCHW', 'OIHW', 'NCHW')))
        np.testing.assert_allclose(out[1, :, 1:3, 1:2],
                                   ref[0][:, 1:3, 1:2], rtol=1e-5)
        assert (out[1, :, 4:, :] == 0).all()
        assert (out[1, :, :, 3:] == 0).all()


class TestTreeConv:
    def test_single_node_tree(self):
        # one node, no edges: patch = root alone, depth 0 ->
        # eta_t = 1, eta_l = 0, eta_r = 0
        F, O, M = 3, 2, 1
        feats = np.random.RandomState(10).rand(1, 1, F).astype('float32')
        edges = np.zeros((1, 1, 2), np.int32)
        w = np.random.RandomState(11).rand(F, 3, O, M).astype('float32')
        out = np.asarray(R.tree_conv(_t(feats), _t(edges), _t(w),
                                     max_depth=2).data)
        want = np.einsum('f,fom->om', feats[0, 0], w[:, 2])
        np.testing.assert_allclose(out[0, 0], want, rtol=1e-5)

    def test_star_tree_oracle(self):
        # root 1 with children 2,3 (depth 1); max_depth=2
        F, O, M = 2, 2, 2
        rng = np.random.RandomState(12)
        feats = rng.rand(1, 3, F).astype('float32')
        edges = np.array([[[1, 2], [1, 3], [0, 0]]], np.int32)
        w = rng.rand(F, 3, O, M).astype('float32')
        out = np.asarray(R.tree_conv(_t(feats), _t(edges), _t(w),
                                     max_depth=2).data)
        fd = 2.0

        def etas(idx, pclen, depth):
            et = (fd - depth) / fd
            tmp = 0.5 if pclen == 1 else (idx - 1.0) / (pclen - 1.0)
            return (1 - et) * tmp, (1 - et) * (1 - tmp), et

        # patch of root: root(idx1,len1,d0) + child2(idx1,len2,d1)
        # + child3(idx2,len2,d1)
        members = [(0, *etas(1, 1, 0)), (1, *etas(1, 2, 1)),
                   (2, *etas(2, 2, 1))]
        patch = np.zeros((F, 3), np.float32)
        for nid, el, er, et in members:
            patch[:, 0] += el * feats[0, nid]
            patch[:, 1] += er * feats[0, nid]
            patch[:, 2] += et * feats[0, nid]
        want = np.einsum('fs,fsom->om', patch, w)
        np.testing.assert_allclose(out[0, 0], want, rtol=1e-5)
        # leaves' patches: themselves only (depth+1 == max_depth stops)
        for leaf in (1, 2):
            wl = np.einsum('f,fom->om', feats[0, leaf], w[:, 2])
            np.testing.assert_allclose(out[0, leaf], wl, rtol=1e-5)


class TestPyramidHash:
    def test_pooled_grams_and_grad(self):
        rng = np.random.RandomState(13)
        space, rand_len, num_emb = 64, 4, 8
        w = rng.rand(space + rand_len, 1).astype('float32')
        x = np.array([[3, 5, 7, 0]], np.int64)
        out = R.pyramid_hash(_t(x), _t(w), num_emb=num_emb,
                             space_len=space, pyramid_layer=2,
                             rand_len=rand_len,
                             seq_lens=_t(np.array([3])), seed=1)
        o = np.asarray(out.data)
        assert o.shape == (1, num_emb)
        # oracle: 2 bigrams of the 3-token sequence, each = concat of
        # num_emb/rand_len hashed slices of w
        import hashlib

        def h32(data, seed):
            return int.from_bytes(hashlib.blake2s(
                data, digest_size=4,
                salt=seed.to_bytes(8, 'little')).digest(), 'little')

        want = np.zeros(num_emb, np.float32)
        for s in range(2):
            gram = np.ascontiguousarray(
                x[0, s:s + 2].astype(np.int32)).tobytes()
            vec = []
            for j in range(num_emb // rand_len):
                pos = h32(gram, 1 + j) % space
                vec.append(w[pos:pos + rand_len, 0])
            want += np.concatenate(vec)
        np.testing.assert_allclose(o[0], want, rtol=1e-5)
        # differentiable w.r.t. the hash table
        g = jax.grad(lambda wa: R.pyramid_hash(
            _t(x), Tensor(wa), num_emb=num_emb, space_len=space,
            pyramid_layer=2, rand_len=rand_len,
            seq_lens=_t(np.array([3])), seed=1).data.sum())(
                jnp.asarray(w))
        assert float(jnp.abs(g).sum()) > 0


class TestTapeGradients:
    """Framework-tape gradients (loss.backward()) reach the trainable
    weights of the run_op-routed recsys ops — a plain Tensor() return
    would silently never train them."""

    def test_tree_conv_filter_gets_grad(self):
        import paddle_tpu as paddle
        paddle.seed(0)
        feats = _t(np.random.RandomState(1).rand(1, 3, 2)
                   .astype('float32'))
        edges = _t(np.array([[[1, 2], [1, 3], [0, 0]]], np.int32))
        w = paddle.to_tensor(np.random.RandomState(2)
                             .rand(2, 3, 2, 1).astype('float32'))
        w.stop_gradient = False
        out = R.tree_conv(feats, edges, w, max_depth=2)
        out.sum().backward()
        assert w.grad is not None
        assert float(np.abs(np.asarray(w.grad.data)).sum()) > 0

    def test_pyramid_hash_table_gets_grad(self):
        import paddle_tpu as paddle
        paddle.seed(0)
        w = paddle.to_tensor(np.random.RandomState(3)
                             .rand(68, 1).astype('float32'))
        w.stop_gradient = False
        x = _t(np.array([[3, 5, 7, 0]], np.int64))
        out = R.pyramid_hash(x, w, num_emb=8, space_len=64,
                             pyramid_layer=2, rand_len=4,
                             seq_lens=_t(np.array([3])), seed=1)
        out.sum().backward()
        assert w.grad is not None
        assert float(np.abs(np.asarray(w.grad.data)).sum()) > 0

    def test_tdm_sampler_insufficient_negatives_raises(self):
        travel = np.array([[0], [1]], np.int32)
        layer = np.array([1, 2], np.int32)
        with pytest.raises(ValueError, match='distinct'):
            R.tdm_sampler(_t(np.array([1], np.int32)), _t(travel),
                          _t(layer), neg_samples_num_list=[3],
                          layer_offset_lod=[0, 2], output_positive=True)
