"""Serving request observatory (ISSUE 6): per-request lifecycle
tracing with deterministic-clock event ordering across preempt/resume,
Histogram bucket-interpolated percentiles vs a numpy oracle, scheduler
timeline, stalled-request watchdog report schema, and the zero-extra-
host-syncs contract for the decode hot path."""
import json
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core import monitor
from paddle_tpu.serving import (RequestState, ServingConfig,
                                ServingEngine, load_trace, reconstruct)
from paddle_tpu.serving import engine as engine_mod
from paddle_tpu.serving import metrics as serve_metrics
from paddle_tpu.serving.request_trace import RequestTracer


# ---------------------------------------------------------------------------
# Histogram percentiles (core.monitor) vs numpy oracle
# ---------------------------------------------------------------------------
class TestHistogramPercentiles:
    def test_vs_numpy_oracle(self):
        rng = np.random.RandomState(0)
        vals = rng.gamma(2.0, 0.05, 2000)        # skewed, latency-like
        edges = [float(b) for b in np.linspace(0.0, 1.0, 101)[1:]]
        h = monitor.Histogram('t_pct_oracle', buckets=edges)
        for v in vals:
            h.observe(float(v))
        width = edges[1] - edges[0]
        for q in (50, 90, 99):
            est = h.percentile(q)
            ref = np.percentile(vals, q)
            # bucket interpolation is exact to within one bucket width
            assert abs(est - ref) <= width + 1e-12, (q, est, ref)

    def test_uniform_interpolation_exact(self):
        # 10 observations at 0.5, 1.5, ..., 9.5 with unit buckets:
        # uniform-within-bucket interpolation is exact at every decile
        h = monitor.Histogram('t_pct_uniform',
                              buckets=[float(i) for i in range(1, 11)])
        for i in range(10):
            h.observe(i + 0.5)
        assert abs(h.percentile(50) - 5.0) < 1e-12
        assert abs(h.percentile(90) - 9.0) < 1e-12
        assert abs(h.percentile(10) - 1.0) < 1e-12

    def test_edges_and_inf_bucket(self):
        h = monitor.Histogram('t_pct_edges', buckets=[1.0, 2.0])
        assert h.percentile(50) is None          # empty
        h.observe(100.0)                         # lands in +Inf only
        # the estimator can't see past the last finite boundary
        assert h.percentile(99) == 2.0
        with pytest.raises(ValueError):
            h.percentile(101)
        p = h.percentiles((50, 90, 99))
        assert set(p) == {'p50', 'p90', 'p99'}

    def test_snapshot_carries_percentiles(self):
        monitor.metrics().reset()
        serve_metrics.publish({
            'pool': {}, '_new_ttfts_s': [0.02, 0.04, 0.2],
            '_new_slo': {'queue_wait_s': [0.001], 'tpot_s': [0.003],
                         'e2e_s': [0.5], 'preemptions': [2]},
            'timeline': {'iterations': 3, 'window': 3},
        })
        snap = serve_metrics.serve_snapshot()
        ttft = snap['ptpu_serve_ttft_seconds']
        assert ttft['count'] == 3
        assert ttft['p50_ms'] is not None and ttft['p99_ms'] is not None
        assert ttft['p50_ms'] <= ttft['p90_ms'] <= ttft['p99_ms']
        assert snap['ptpu_serve_tpot_seconds']['count'] == 1
        assert snap['ptpu_serve_preemptions_per_request']['p99'] >= 1.0
        assert snap['timeline']['iterations'] == 3
        # the deprecated ptpu_serve_ttft_ms mean gauge is GONE (its
        # one-release grace ended with ISSUE 7) — percentiles only
        assert 'ptpu_serve_ttft_ms' not in snap


# ---------------------------------------------------------------------------
# engine fixtures: tiny model + deterministic clock
# ---------------------------------------------------------------------------
@pytest.fixture(scope='module')
def tiny_lm():
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM
    paddle.seed(7)
    cfg = GPTConfig(vocab_size=128, hidden_size=64, num_layers=2,
                    num_heads=2, max_seq_len=128, hidden_dropout=0.0,
                    attn_dropout=0.0, use_flash_attention=False)
    m = GPTForCausalLM(cfg)
    m.eval()
    return m


@pytest.fixture(scope='module')
def mixed_prompts():
    rng = np.random.RandomState(3)
    return [list(rng.randint(1, 128, n)) for n in (5, 11, 3, 17, 8)]


def _fake_clock(step=0.001):
    """Deterministic strictly-increasing clock; returns (clock, state)
    — bump state['now'] to jump time (watchdog tests)."""
    state = {'now': 0.0}

    def clock():
        state['now'] += step
        return state['now']
    return clock, state


# ---------------------------------------------------------------------------
# lifecycle tracing
# ---------------------------------------------------------------------------
class TestRequestTracing:
    def test_event_ordering_across_preempt_resume(self, tiny_lm,
                                                  mixed_prompts):
        clock, _ = _fake_clock()
        # 4 pages of 8 can't hold the concurrent contexts: preemption
        # and resume must show up in the journals, in causal order
        eng = ServingEngine(tiny_lm, ServingConfig(
            page_size=8, max_batch_size=3, prefill_chunk=8, num_pages=4,
            clock=clock))
        eng.generate(mixed_prompts, max_new_tokens=6, top_k=0)
        assert eng.stats()['preemptions_total'] > 0
        preempted = [r for r in eng.scheduler.finished if r.preemptions]
        assert preempted
        for req in eng.scheduler.finished:
            evs = eng.tracer.events(req.id)
            names = [e['event'] for e in evs]
            times = [e['t'] for e in evs]
            assert times == sorted(times), names
            assert names[0] == 'submit' and names[-1] == 'retire'
            assert names[1] == 'admit'
            assert 'first_token' in names
            # a preempt is always followed by a resume (never a second
            # admit), and the request still retires
            for i, n in enumerate(names):
                if n == 'preempt':
                    later = names[i + 1:]
                    assert 'resume' in later, names
                    assert 'admit' not in later, names
            assert names.count('preempt') == req.preemptions
            assert names.count('resume') == req.preemptions
        eng.shutdown()

    def test_reconstruction_matches_engine_exactly(self, tiny_lm,
                                                   mixed_prompts):
        clock, _ = _fake_clock()
        eng = ServingEngine(tiny_lm, ServingConfig(
            page_size=8, max_batch_size=3, prefill_chunk=8, num_pages=4,
            clock=clock))
        outs = eng.generate(mixed_prompts, max_new_tokens=6, top_k=0)
        table = eng.request_table()
        assert len(table) == len(mixed_prompts)
        for req, out in zip(sorted(eng.scheduler.finished,
                                   key=lambda r: r.id), outs):
            r = table[req.id]
            assert r['prompt_tokens'] == len(req.prompt)
            assert r['tokens_generated'] == len(req.generated)
            assert r['preemptions'] == req.preemptions
            assert r['state'] == 'finished'
            # timestamps are the engine's own stamps — exact equality
            assert r['ttft_s'] == req.first_token_time - req.submit_time
            assert r['queue_wait_s'] == (req.admit_time
                                         - req.submit_time)
            assert r['e2e_s'] == req.finish_time - req.submit_time
            if len(req.generated) > 1:
                # same formula engine._observe_slo feeds the histogram
                assert r['tpot_s'] == (
                    (req.finish_time - req.first_token_time)
                    / (len(req.generated) - 1))
            assert r['pages_high_water'] >= 1
        eng.shutdown()

    def test_jsonl_roundtrip_and_chrome_export(self, tiny_lm,
                                               mixed_prompts, tmp_path):
        import paddle_tpu.profiler as prof
        clock, _ = _fake_clock()
        eng = ServingEngine(tiny_lm, ServingConfig(
            page_size=8, max_batch_size=3, prefill_chunk=8, clock=clock))
        # record the engine-phase spans so the chrome export carries
        # both requests (tracks) and serve::* steps
        prof.use_native_recorder(False)
        p = prof.Profiler(scheduler=None, timer_only=True)
        p.start()
        eng.generate(mixed_prompts[:3], max_new_tokens=4, top_k=0)
        jsonl = str(tmp_path / 'serve.jsonl')
        chrome = str(tmp_path / 'serve.trace.json')
        paths = eng.export_trace(jsonl_path=jsonl, chrome_path=chrome)
        p.stop()
        prof.use_native_recorder(True)

        header, events = load_trace(paths['jsonl'])
        assert header['schema'] == 'paddle_tpu.serve_trace/6'
        assert header['dropped_events'] == 0
        # JSON round trip preserves the reconstruction bit-for-bit
        assert reconstruct(events) == eng.request_table()

        with open(paths['chrome']) as f:
            doc = json.load(f)
        evs = doc['traceEvents']
        # structurally Perfetto-loadable: X events with ts/dur plus
        # process/thread metadata; one track (virtual tid) per request
        req_tids = {e['tid'] for e in evs
                    if e.get('cat') == 'serve_request'}
        assert len(req_tids) == 3
        assert all(('ts' in e and 'dur' in e) for e in evs
                   if e.get('ph') == 'X')
        tnames = [e for e in evs if e.get('name') == 'thread_name']
        assert any(e['args']['name'].startswith('req ')
                   for e in tnames)
        # request tracks group under their own named pseudo-process,
        # beside the host process carrying the engine spans
        pnames = {e['args']['name'] for e in evs
                  if e.get('name') == 'process_name'}
        assert 'serving requests' in pnames and len(pnames) == 2
        assert any(e.get('cat') == 'serve' for e in evs), \
            'engine serve::* phase spans missing from chrome export'
        eng.shutdown()

    def test_journal_caps_bound_memory(self, tiny_lm):
        clock, _ = _fake_clock()
        eng = ServingEngine(tiny_lm, ServingConfig(
            page_size=8, max_batch_size=2, prefill_chunk=8,
            trace_events_per_request=4, trace_requests=2, clock=clock))
        eng.generate([[1, 2, 3], [4, 5], [6, 7, 8]], max_new_tokens=5,
                     top_k=0)
        for tr in eng.tracer.traces():
            assert len(tr.events) <= 4
            # the terminal event survives the cap (an interior event
            # is evicted instead), so reconstruction keeps end state,
            # e2e and the authoritative token count
            assert tr.events[-1]['event'] == 'retire'
        assert sum(tr.dropped for tr in eng.tracer.traces()) > 0
        assert len(eng.tracer.traces()) == 2       # retired ring cap
        assert eng.tracer.dropped_requests == 1
        for r in eng.request_table().values():
            assert r['state'] == 'finished'
            assert r['tokens_generated'] == 5
            assert r['e2e_s'] is not None
        eng.shutdown()

    def test_trace_off_engine_still_serves(self, tiny_lm):
        eng = ServingEngine(tiny_lm, ServingConfig(
            page_size=8, max_batch_size=2, prefill_chunk=8,
            trace=False))
        outs = eng.generate([[1, 2, 3]], max_new_tokens=3, top_k=0)
        assert len(outs[0]) == 6
        assert eng.request_table() == {}
        with pytest.raises(RuntimeError, match='tracing is off'):
            eng.export_trace(jsonl_path='/tmp/nope.jsonl')
        eng.shutdown()


# ---------------------------------------------------------------------------
# scheduler timeline
# ---------------------------------------------------------------------------
class TestSchedulerTimeline:
    def test_timeline_records_batch_composition(self, tiny_lm,
                                                mixed_prompts):
        clock, _ = _fake_clock()
        eng = ServingEngine(tiny_lm, ServingConfig(
            page_size=8, max_batch_size=3, prefill_chunk=8, num_pages=4,
            clock=clock))
        eng.generate(mixed_prompts, max_new_tokens=6, top_k=0)
        rows = eng.timeline.snapshot()
        st = eng.stats()
        assert len(rows) == eng.timeline.iterations
        assert [r['iter'] for r in rows] == list(range(len(rows)))
        # the timeline's token/admission/preemption sums are the
        # engine's own totals, re-derived per iteration
        assert sum(r['decode_tokens'] for r in rows) == \
            st['decode_tokens_total']
        assert sum(r['prefill_tokens'] for r in rows) == \
            st['prefill_tokens_total']
        assert sum(r['preemptions'] for r in rows) == \
            st['preemptions_total']
        assert sum(r['admissions'] for r in rows) == \
            len(mixed_prompts) + st['preemptions_total']
        assert all(0 <= r['pool_pages_in_use'] <= r['pool_pages_total']
                   for r in rows)
        summ = eng.timeline.summary()
        assert summ['iterations'] == len(rows)
        assert 0 < summ['mean_occupancy'] <= 1
        assert summ['preemptions'] == st['preemptions_total']
        eng.shutdown()

    def test_ring_capacity(self, tiny_lm):
        clock, _ = _fake_clock()
        eng = ServingEngine(tiny_lm, ServingConfig(
            page_size=8, max_batch_size=2, prefill_chunk=8,
            timeline_capacity=4, clock=clock))
        eng.generate([[1, 2, 3], [4, 5]], max_new_tokens=6, top_k=0)
        assert eng.timeline.iterations > 4
        assert len(eng.timeline.snapshot()) == 4
        assert len(eng.timeline.tail(2)) == 2
        eng.shutdown()


# ---------------------------------------------------------------------------
# stalled-request watchdog
# ---------------------------------------------------------------------------
class TestStalledWatchdog:
    def test_report_schema_and_once_semantics(self, tiny_lm, tmp_path):
        clock, state = _fake_clock()
        eng = ServingEngine(tiny_lm, ServingConfig(
            page_size=8, max_batch_size=2, prefill_chunk=8,
            request_deadline_s=5.0, report_dir=str(tmp_path),
            clock=clock))
        req = eng.submit([1, 2, 3], max_new_tokens=4)
        state['now'] += 10.0              # age past the deadline
        eng.step()
        report = eng.last_serve_report
        assert report is not None
        assert report['kind'] == 'serve_report'
        assert report['schema'] == 'paddle_tpu.serve_trace/6'
        assert report['request']['req'] == req.id
        assert report['request']['age_s'] > 5.0
        assert report['request']['deadline_s'] == 5.0
        assert {'trace', 'timeline_tail', 'pool', 'pool_census',
                'engine'} <= set(report)
        assert any(e['event'] == 'submit' for e in report['trace'])
        assert report['pool']['num_pages'] == eng.pool.num_pages
        path = report['path']
        assert path and os.path.exists(path)
        with open(path) as f:
            assert json.load(f)['kind'] == 'serve_report'
        # one report per request: draining does not re-report
        eng.last_serve_report = None
        while eng.scheduler.has_work:
            eng.step()
        assert eng.last_serve_report is None
        assert req.state == RequestState.FINISHED
        eng.shutdown()

    def test_deadline_abort_action(self, tiny_lm, tmp_path):
        clock, state = _fake_clock()
        eng = ServingEngine(tiny_lm, ServingConfig(
            page_size=8, max_batch_size=2, prefill_chunk=8,
            request_deadline_s=5.0, deadline_action='abort',
            report_dir=str(tmp_path), clock=clock))
        reqs = [eng.submit(p, max_new_tokens=4)
                for p in ([1, 2, 3], [4, 5])]
        state['now'] += 10.0
        while eng.scheduler.has_work:
            eng.step()
        # both requests were older than the deadline: aborted, pages
        # released, journals closed with an abort event
        assert all(r.state == RequestState.ABORTED for r in reqs)
        assert eng.pool.pages_in_use == 0
        assert eng.stats()['requests_aborted_total'] == 2
        for r in reqs:
            evs = [e['event'] for e in eng.tracer.events(r.id)]
            assert evs[-1] == 'abort'
            assert eng.request_table()[r.id]['state'] == 'aborted'
        eng.shutdown()

    def test_abort_is_terminal_idempotent(self, tiny_lm):
        clock, _ = _fake_clock()
        eng = ServingEngine(tiny_lm, ServingConfig(
            page_size=8, max_batch_size=2, prefill_chunk=8,
            clock=clock))
        req = eng.submit([1, 2, 3], max_new_tokens=3)
        while eng.scheduler.has_work:
            eng.step()
        assert req.state == RequestState.FINISHED
        finish = req.finish_time
        n_slo = len(eng._new_slo['e2e_s']) + \
            sum(1 for _ in eng.scheduler.finished)
        # aborting a retired request is a no-op: no double count, no
        # restamped finish_time, no duplicate SLO samples
        assert eng.abort(req) is False
        assert eng.abort(req) is False
        assert req.state == RequestState.FINISHED
        assert req.finish_time == finish
        assert eng.stats()['requests_aborted_total'] == 0
        assert eng.scheduler.finished.count(req) == 1
        assert len(eng._new_slo['e2e_s']) + \
            sum(1 for _ in eng.scheduler.finished) == n_slo
        eng.shutdown()

    def test_no_deadline_no_reports(self, tiny_lm):
        clock, state = _fake_clock()
        eng = ServingEngine(tiny_lm, ServingConfig(
            page_size=8, max_batch_size=2, prefill_chunk=8,
            clock=clock))
        eng.submit([1, 2, 3], max_new_tokens=2)
        state['now'] += 1e6
        while eng.scheduler.has_work:
            eng.step()
        assert eng.last_serve_report is None
        eng.shutdown()


# ---------------------------------------------------------------------------
# the observability tax: zero extra host syncs in the decode hot path
# ---------------------------------------------------------------------------
class TestSyncBudget:
    def _count_fetches(self, tiny_lm, prompts, trace, monkeypatch):
        counts = [0]
        real = engine_mod._host_fetch

        def counting(x):
            counts[0] += 1
            return real(x)
        monkeypatch.setattr(engine_mod, '_host_fetch', counting)
        try:
            eng = ServingEngine(tiny_lm, ServingConfig(
                page_size=8, max_batch_size=3, prefill_chunk=8,
                num_pages=4, trace=trace))
            outs = eng.generate(prompts, max_new_tokens=6, top_k=0)
            st = eng.stats()
            eng.shutdown()
        finally:
            monkeypatch.setattr(engine_mod, '_host_fetch', real)
        return counts[0], outs, st

    def test_tracing_adds_no_host_syncs(self, tiny_lm, mixed_prompts,
                                        monkeypatch):
        """Every host sync the engine performs funnels through
        engine._host_fetch (the PR-3/4 convention); the full
        observatory — journals, timeline, SLO accounting, watchdog
        sweep — must not add a single one."""
        n_off, outs_off, st_off = self._count_fetches(
            tiny_lm, mixed_prompts, False, monkeypatch)
        n_on, outs_on, st_on = self._count_fetches(
            tiny_lm, mixed_prompts, True, monkeypatch)
        assert outs_on == outs_off          # identical serving results
        assert n_on == n_off, (n_on, n_off)
        # and the budget is exactly one fetch per token-yielding step:
        # each batched decode step fetches once (len(active) tokens);
        # each completed prefill fetches its first token — i.e. every
        # generated token NOT accounted to a decode step
        generated = sum(len(o) - len(p)
                        for o, p in zip(outs_on, mixed_prompts))
        prefill_fetches = generated - st_on['decode_tokens_total']
        assert n_on == st_on['decode_steps_total'] + prefill_fetches, \
            (n_on, st_on)
