"""Wave-4 detection tail, part 2: deformable_roi_pooling vs the
reference oracle (test_deformable_psroi_pooling.py), ssd_loss pipeline
behavior (fluid/layers/detection.py ssd_loss), host-side label
generation (test_rpn_target_assign_op.py,
test_generate_proposal_labels_op.py), multi_box_head static graph."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.vision import detection as det


def _dmc_bilinear(img, H, W, ph_, pw_):
    hl, wl = int(np.floor(ph_)), int(np.floor(pw_))
    hh, wh = hl + 1, wl + 1
    lh, lw = ph_ - hl, pw_ - wl
    hh_w, hw_w = 1 - lh, 1 - lw
    v1 = img[hl, wl] if hl >= 0 and wl >= 0 else 0
    v2 = img[hl, wh] if hl >= 0 and wh <= W - 1 else 0
    v3 = img[hh, wl] if hh <= H - 1 and wl >= 0 else 0
    v4 = img[hh, wh] if hh <= H - 1 and wh <= W - 1 else 0
    return hh_w * hw_w * v1 + hh_w * lw * v2 + lh * hw_w * v3 \
        + lh * lw * v4


def _py_deform_psroi(x, rois, batch_idx, trans, no_trans, scale,
                     out_c, group, ph, pw, part, sp, trans_std):
    R = rois.shape[0]
    _, C, H, W = x.shape
    out = np.zeros((R, out_c, ph, pw))
    for n in range(R):
        roi = rois[n]
        b = batch_idx[n]
        x1 = np.round(roi[0]) * scale - 0.5
        y1 = np.round(roi[1]) * scale - 0.5
        x2 = np.round(roi[2] + 1) * scale - 0.5
        y2 = np.round(roi[3] + 1) * scale - 0.5
        rw, rh = max(x2 - x1, 0.1), max(y2 - y1, 0.1)
        bw, bh = rw / pw, rh / ph
        sw, sh = bw / sp, bh / sp
        for c in range(out_c):
            for i in range(ph):
                for j in range(pw):
                    part_h = int(np.floor(i) / ph * part[0])
                    part_w = int(np.floor(j) / pw * part[1])
                    if no_trans:
                        tx = ty = 0.0
                    else:
                        tx = trans[n][0][part_h][part_w] * trans_std
                        ty = trans[n][1][part_h][part_w] * trans_std
                    ws = j * bw + x1 + tx * rw
                    hs = i * bh + y1 + ty * rh
                    gw = min(max(int(np.floor(j * group[0] / ph)), 0),
                             group[0] - 1)
                    gh = min(max(int(np.floor(i * group[1] / pw)), 0),
                             group[1] - 1)
                    cs = int((c * group[0] + gh) * group[1] + gw) \
                        if C != out_c else c
                    acc, cnt = 0.0, 0
                    for iw in range(sp):
                        for ih in range(sp):
                            wss = ws + iw * sw
                            hss = hs + ih * sh
                            if wss < -0.5 or wss > W - 0.5 or \
                                    hss < -0.5 or hss > H - 0.5:
                                continue
                            wss = min(max(wss, 0.), W - 1.)
                            hss = min(max(hss, 0.), H - 1.)
                            acc += _dmc_bilinear(x[b, cs], H, W, hss, wss)
                            cnt += 1
                    out[n, c, i, j] = acc / cnt if cnt else 0.0
    return out


@pytest.mark.parametrize('ps', [False, True])
def test_deformable_roi_pooling_oracle(ps):
    rng = np.random.RandomState(0)
    group = (2, 2)
    out_c = 3
    C = out_c * group[0] * group[1] if ps else out_c
    x = rng.rand(2, C, 10, 12).astype(np.float32)
    rois = np.array([[1.0, 1.0, 16.0, 14.0],
                     [3.0, 2.0, 20.0, 18.0]], np.float32)
    rois_num = np.array([1, 1], np.int32)
    ph = pw = 3
    part = (3, 3)
    sp = 2
    trans = rng.rand(2, 2, part[0], part[1]).astype(np.float32)
    out = det.deformable_roi_pooling(
        Tensor(x), Tensor(rois), Tensor(trans), no_trans=False,
        spatial_scale=0.5, group_size=group, pooled_height=ph,
        pooled_width=pw, part_size=part, sample_per_part=sp,
        trans_std=0.1, position_sensitive=ps,
        rois_num=Tensor(rois_num))
    want = _py_deform_psroi(x, rois, [0, 1], trans, False, 0.5, out_c,
                            group, ph, pw, part, sp, 0.1)
    got = np.asarray(out.data)
    if not ps:
        want = want[:, :C]  # non-PS keeps every channel
        assert got.shape[1] == C
        got_cmp, want_cmp = got[:, :out_c], want[:, :out_c]
    else:
        got_cmp, want_cmp = got, want
    np.testing.assert_allclose(got_cmp, want_cmp, rtol=1e-4, atol=1e-5)


def test_deformable_roi_pooling_grad():
    rng = np.random.RandomState(1)
    x = Tensor(rng.rand(1, 4, 8, 8).astype(np.float32))
    x.stop_gradient = False
    trans = Tensor(rng.rand(1, 2, 2, 2).astype(np.float32))
    trans.stop_gradient = False
    rois = Tensor(np.array([[0.0, 0.0, 7.0, 7.0]], np.float32))
    out = det.deformable_roi_pooling(
        x, rois, trans, spatial_scale=1.0, group_size=(2, 2),
        pooled_height=2, pooled_width=2, part_size=(2, 2),
        sample_per_part=2, position_sensitive=True)
    out.sum().backward()
    assert np.isfinite(np.asarray(x.grad.data)).all()
    assert np.isfinite(np.asarray(trans.grad.data)).all()


def test_ssd_loss_behavior():
    rng = np.random.RandomState(2)
    N, P, G, C = 2, 16, 3, 4
    prior = np.sort(rng.rand(P, 4).astype(np.float32), axis=-1)
    prior = np.stack([prior[:, 0], prior[:, 1],
                      prior[:, 0] + 0.3, prior[:, 1] + 0.3], -1)
    # gt overlapping some priors
    gt = np.stack([prior[1], prior[5], prior[9]])[None] \
        .repeat(N, 0).astype(np.float32)
    gl = rng.randint(1, C, (N, G)).astype(np.int64)
    loc = rng.randn(N, P, 4).astype(np.float32) * 0.1
    conf = rng.randn(N, P, C).astype(np.float32)
    out = det.ssd_loss(Tensor(loc), Tensor(conf), Tensor(gt),
                       Tensor(gl), Tensor(prior))
    o = np.asarray(out.data)
    assert o.shape == (N, P, 1)
    assert np.isfinite(o).all() and (o >= 0).all()
    # matched priors must carry loss; faraway priors without negative
    # selection may be zero
    assert o.sum() > 0
    # gradient flows to both heads
    loc_t = Tensor(loc)
    loc_t.stop_gradient = False
    conf_t = Tensor(conf)
    conf_t.stop_gradient = False
    loss = det.ssd_loss(loc_t, conf_t, Tensor(gt), Tensor(gl),
                        Tensor(prior))
    loss.sum().backward()
    assert np.abs(np.asarray(loc_t.grad.data)).sum() > 0
    assert np.abs(np.asarray(conf_t.grad.data)).sum() > 0


def test_ssd_loss_mining_respects_ratio():
    # all-background image: with zero positives, loss only counts
    # matched+mined priors -> total conf weight 0
    N, P, G, C = 1, 8, 1, 3
    prior = np.tile(np.array([[0.8, 0.8, 0.9, 0.9]], np.float32),
                    (P, 1))
    gt = np.zeros((N, G, 4), np.float32)          # invalid (zero area)
    gl = np.zeros((N, G), np.int64)
    loc = np.zeros((N, P, 4), np.float32)
    conf = np.random.RandomState(3).randn(N, P, C).astype(np.float32)
    out = np.asarray(det.ssd_loss(
        Tensor(loc), Tensor(conf), Tensor(gt), Tensor(gl),
        Tensor(prior)).data)
    assert out.sum() == 0.0


def test_rpn_target_assign_contract():
    rng = np.random.RandomState(4)
    N, A, G = 2, 64, 3
    anchors = np.sort(rng.rand(A, 4).astype(np.float32) * 50, -1)
    anchors = np.stack([anchors[:, 0], anchors[:, 1],
                        anchors[:, 0] + 8, anchors[:, 1] + 8], -1)
    gt = np.stack([anchors[3], anchors[17], anchors[33]])[None] \
        .repeat(N, 0).astype(np.float32)
    bbox_pred = rng.randn(N, A, 4).astype(np.float32)
    cls_logits = rng.randn(N, A, 1).astype(np.float32)
    im_info = np.tile(np.array([[100.0, 100.0, 1.0]], np.float32),
                      (N, 1))
    sc, lc, lab, tb, inw = det.rpn_target_assign(
        Tensor(bbox_pred), Tensor(cls_logits), Tensor(anchors), None,
        Tensor(gt), im_info=Tensor(im_info), rpn_batch_size_per_im=32,
        rpn_straddle_thresh=-1, use_random=False)
    labv = np.asarray(lab.data).reshape(-1)
    assert set(np.unique(labv)) <= {0, 1}
    assert (labv == 1).sum() >= 2 * G        # exact-overlap anchors fg
    assert np.asarray(lc.data).shape == np.asarray(tb.data).shape
    assert np.asarray(inw.data).shape == np.asarray(tb.data).shape
    assert len(labv) == len(np.asarray(sc.data))


def test_generate_proposal_labels_contract():
    rng = np.random.RandomState(5)
    N, R, G, C = 2, 40, 4, 5
    rois = rng.rand(N * R, 4).astype(np.float32) * 60
    rois[:, 2:] += rois[:, :2] + 5
    gt = rng.rand(N, G, 4).astype(np.float32) * 60
    gt[..., 2:] += gt[..., :2] + 5
    # plant exact matches so fg sampling has candidates
    rois[0] = gt[0, 0]
    rois[R] = gt[1, 1]
    gcls = rng.randint(1, C, (N, G)).astype(np.int32)
    crowd = np.zeros((N, G), np.int32)
    im_info = np.tile(np.array([[64.0, 64.0, 1.0]], np.float32), (N, 1))
    out = det.generate_proposal_labels(
        Tensor(rois), Tensor(gcls), Tensor(crowd), Tensor(gt),
        Tensor(im_info), batch_size_per_im=16, fg_fraction=0.5,
        fg_thresh=0.6, bg_thresh_hi=0.5, bg_thresh_lo=0.0,
        class_nums=C, use_random=False,
        rois_num=Tensor(np.array([R, R], np.int32)))
    srois, labs, tgt, inw, onw, lens = out
    S = np.asarray(srois.data).shape[0]
    assert S == int(np.asarray(lens.data).sum())
    labv = np.asarray(labs.data).reshape(-1)
    assert ((labv >= 0) & (labv < C)).all()
    assert (labv > 0).any()                     # planted fg sampled
    t = np.asarray(tgt.data)
    w = np.asarray(inw.data)
    assert t.shape == (S, 4 * C) and w.shape == t.shape
    # targets only at the labeled class's 4-slot
    for i in range(S):
        nz = np.where(w[i] > 0)[0]
        if labv[i] > 0:
            assert set(nz) == set(range(4 * labv[i], 4 * labv[i] + 4))
        else:
            assert len(nz) == 0
    np.testing.assert_array_equal(np.asarray(onw.data), w > 0)


def test_generate_mask_labels_contract():
    rng = np.random.RandomState(6)
    N, G, H, W = 1, 2, 32, 32
    masks = np.zeros((N, G, H, W), np.float32)
    masks[0, 0, 4:16, 4:16] = 1
    masks[0, 1, 20:30, 20:30] = 1
    rois = np.array([[4.0, 4.0, 15.0, 15.0],
                     [20.0, 20.0, 29.0, 29.0],
                     [0.0, 0.0, 3.0, 3.0]], np.float32)
    labels = np.array([2, 3, 0], np.int32)
    gcls = np.array([[2, 3]], np.int32)
    crowd = np.zeros((N, G), np.int32)
    im_info = np.array([[32.0, 32.0, 1.0]], np.float32)
    mrois, has, m, lens = det.generate_mask_labels(
        Tensor(im_info), Tensor(gcls), Tensor(crowd), Tensor(masks),
        Tensor(rois), Tensor(labels), num_classes=4, resolution=8,
        rois_num=Tensor(np.array([3], np.int32)))
    assert int(np.asarray(lens.data)[0]) == 2      # two fg rois
    mv = np.asarray(m.data).reshape(2, 4, 64)
    # roi 0 (class 2): fully inside its instance -> all ones there
    assert (mv[0, 2] == 1).all()
    assert (mv[0, 0] == -1).all()                  # other classes -1
    assert (mv[1, 3] == 1).all()


def test_multi_box_head_static():
    paddle.enable_static()
    try:
        from paddle_tpu import static
        from paddle_tpu.static import nn as snn
        main, start = static.Program(), static.Program()
        with static.program_guard(main, start):
            f1 = static.data('f1', [2, 8, 8, 8], 'float32')
            f2 = static.data('f2', [2, 16, 4, 4], 'float32')
            img = static.data('img', [2, 3, 64, 64], 'float32')
            locs, confs, boxes, vars_ = snn.multi_box_head(
                [f1, f2], img, base_size=64, num_classes=5,
                aspect_ratios=[[2.0], [2.0, 3.0]], min_ratio=20,
                max_ratio=90, offset=0.5, flip=True)
        exe = static.Executor()
        exe.run(start)
        rng = np.random.RandomState(7)
        out = exe.run(main, feed={
            'f1': rng.rand(2, 8, 8, 8).astype(np.float32),
            'f2': rng.rand(2, 16, 4, 4).astype(np.float32),
            'img': rng.rand(2, 3, 64, 64).astype(np.float32)},
            fetch_list=[locs, confs, boxes, vars_])
        P = out[2].shape[0]
        assert out[0].shape == (2, P, 4)
        assert out[1].shape == (2, P, 5)
        assert out[3].shape == (P, 4)
        assert P == out[0].shape[1]
    finally:
        paddle.disable_static()


def test_rpn_target_assign_excludes_crowd():
    rng = np.random.RandomState(8)
    A = 32
    anchors = np.sort(rng.rand(A, 4).astype(np.float32) * 40, -1)
    anchors = np.stack([anchors[:, 0], anchors[:, 1],
                        anchors[:, 0] + 8, anchors[:, 1] + 8], -1)
    gt = np.stack([anchors[3], anchors[17]])[None].astype(np.float32)
    crowd = np.array([[0, 1]], np.int32)      # second gt is crowd
    _, _, lab, tb, _ = det.rpn_target_assign(
        Tensor(rng.randn(1, A, 4).astype(np.float32)),
        Tensor(rng.randn(1, A, 1).astype(np.float32)),
        Tensor(anchors), None, Tensor(gt), is_crowd=Tensor(crowd),
        rpn_batch_size_per_im=16, rpn_straddle_thresh=-1,
        use_random=False)
    # only the non-crowd gt's box may appear as a regression target
    t = np.asarray(tb.data)
    for row in t:
        np.testing.assert_allclose(row, gt[0, 0], rtol=1e-6)


def test_target_assign_requires_neg_lod_when_batched():
    enc = np.ones((2, 4, 1), np.float32)
    mi = -np.ones((2, 4), np.int32)
    neg = np.array([[0], [1]], np.int32)
    with pytest.raises(ValueError, match='neg_lod'):
        det.target_assign(Tensor(enc), Tensor(mi),
                          negative_indices=Tensor(neg), input_lod=[1, 1])


def test_generate_mask_labels_class_aware_and_empty():
    # roi labeled class 2 overlaps a class-3 mask more; must still take
    # the class-2 instance
    N, G, H, W = 1, 2, 32, 32
    masks = np.zeros((N, G, H, W), np.float32)
    masks[0, 0, 0:8, 0:8] = 1        # class 2 instance (small)
    masks[0, 1, 0:28, 0:28] = 1      # class 3 instance (covers roi)
    gcls = np.array([[2, 3]], np.int32)
    rois = np.array([[0.0, 0.0, 20.0, 20.0]], np.float32)
    labels = np.array([2], np.int32)
    crowd = np.zeros((N, G), np.int32)
    im_info = np.array([[32.0, 32.0, 1.0]], np.float32)
    mrois, has, m, lens = det.generate_mask_labels(
        Tensor(im_info), Tensor(gcls), Tensor(crowd), Tensor(masks),
        Tensor(rois), Tensor(labels), num_classes=4, resolution=4,
        rois_num=Tensor(np.array([1], np.int32)))
    mv = np.asarray(m.data).reshape(1, 4, 16)
    # mask comes from the class-2 instance: top-left corner on, rest off
    assert mv[0, 2, 0] == 1 and mv[0, 2, -1] == 0
    # all-background image -> empty but correctly-shaped outputs
    mrois2, has2, m2, lens2 = det.generate_mask_labels(
        Tensor(im_info), Tensor(gcls), Tensor(crowd), Tensor(masks),
        Tensor(rois), Tensor(np.array([0], np.int32)), num_classes=4,
        resolution=4, rois_num=Tensor(np.array([1], np.int32)))
    assert np.asarray(mrois2.data).shape == (0, 4)
    assert np.asarray(m2.data).shape == (0, 4 * 16)
    assert int(np.asarray(lens2.data)[0]) == 0
