"""Op unit tests vs numpy — the OpTest pattern (reference:
python/paddle/fluid/tests/unittests/op_test.py:270): run op, compare against
a numpy reference, check gradients against jax.grad (replacing the
perturbation-based get_numeric_gradient:110 with the exact reference
gradient, which jax provides)."""
import numpy as np
import pytest
import jax
import jax.numpy as jnp

import paddle_tpu as paddle

RNG = np.random.RandomState(0)


def check_grad(op_fn, jax_fn, *arrays):
    tensors = [paddle.to_tensor(a, stop_gradient=False) for a in arrays]
    out = op_fn(*tensors)
    loss = paddle.sum(out * out)
    loss.backward()
    ref_grads = jax.grad(
        lambda *xs: jnp.sum(jax_fn(*xs) ** 2), argnums=tuple(
            range(len(arrays))))(*[jnp.asarray(a) for a in arrays])
    for t, g in zip(tensors, ref_grads):
        np.testing.assert_allclose(t.grad.numpy(), np.asarray(g),
                                   rtol=2e-4, atol=2e-4)


class TestElementwise:
    def test_add_broadcast(self):
        a = RNG.randn(3, 4).astype('float32')
        b = RNG.randn(4).astype('float32')
        out = paddle.add(paddle.to_tensor(a), paddle.to_tensor(b))
        np.testing.assert_allclose(out.numpy(), a + b, rtol=1e-6)
        check_grad(paddle.add, jnp.add, a, b)

    def test_mul_div_sub(self):
        a = RNG.rand(2, 3).astype('float32') + 0.5
        b = RNG.rand(2, 3).astype('float32') + 0.5
        np.testing.assert_allclose(
            paddle.multiply(paddle.to_tensor(a), paddle.to_tensor(b)).numpy(),
            a * b, rtol=1e-6)
        np.testing.assert_allclose(
            paddle.divide(paddle.to_tensor(a), paddle.to_tensor(b)).numpy(),
            a / b, rtol=1e-5)
        check_grad(paddle.divide, jnp.divide, a, b)

    def test_pow_scalar(self):
        a = RNG.rand(4).astype('float32') + 0.1
        out = paddle.to_tensor(a) ** 2
        np.testing.assert_allclose(out.numpy(), a ** 2, rtol=1e-6)

    def test_maximum_minimum(self):
        a = RNG.randn(5).astype('float32')
        b = RNG.randn(5).astype('float32')
        np.testing.assert_allclose(
            paddle.maximum(paddle.to_tensor(a), paddle.to_tensor(b)).numpy(),
            np.maximum(a, b))


class TestUnary:
    @pytest.mark.parametrize('name,npfn', [
        ('exp', np.exp), ('log', np.log), ('sqrt', np.sqrt),
        ('tanh', np.tanh), ('abs', np.abs), ('floor', np.floor),
        ('ceil', np.ceil), ('square', np.square), ('sin', np.sin),
        ('cos', np.cos),
    ])
    def test_unary(self, name, npfn):
        a = (RNG.rand(3, 4).astype('float32') + 0.1)
        out = getattr(paddle, name)(paddle.to_tensor(a))
        np.testing.assert_allclose(out.numpy(), npfn(a), rtol=1e-5,
                                   atol=1e-6)

    def test_sigmoid_grad(self):
        a = RNG.randn(3, 3).astype('float32')
        check_grad(paddle.sigmoid, jax.nn.sigmoid, a)


class TestMatmul:
    def test_matmul(self):
        a = RNG.randn(3, 4).astype('float32')
        b = RNG.randn(4, 5).astype('float32')
        out = paddle.matmul(paddle.to_tensor(a), paddle.to_tensor(b))
        np.testing.assert_allclose(out.numpy(), a @ b, rtol=1e-5)
        check_grad(paddle.matmul, jnp.matmul, a, b)

    def test_matmul_transpose(self):
        a = RNG.randn(4, 3).astype('float32')
        b = RNG.randn(4, 5).astype('float32')
        out = paddle.matmul(paddle.to_tensor(a), paddle.to_tensor(b),
                            transpose_x=True)
        np.testing.assert_allclose(out.numpy(), a.T @ b, rtol=1e-5)

    def test_batched(self):
        a = RNG.randn(2, 3, 4).astype('float32')
        b = RNG.randn(2, 4, 5).astype('float32')
        out = paddle.bmm(paddle.to_tensor(a), paddle.to_tensor(b))
        np.testing.assert_allclose(out.numpy(), a @ b, rtol=1e-5)


class TestReduce:
    def test_sum_axis(self):
        a = RNG.randn(2, 3, 4).astype('float32')
        out = paddle.sum(paddle.to_tensor(a), axis=[1, 2])
        np.testing.assert_allclose(out.numpy(), a.sum(axis=(1, 2)),
                                   rtol=1e-5)

    def test_mean_keepdim(self):
        a = RNG.randn(2, 3).astype('float32')
        out = paddle.mean(paddle.to_tensor(a), axis=1, keepdim=True)
        np.testing.assert_allclose(out.numpy(), a.mean(1, keepdims=True),
                                   rtol=1e-6)

    def test_max_min_prod(self):
        a = RNG.rand(3, 4).astype('float32')
        np.testing.assert_allclose(paddle.max(paddle.to_tensor(a),
                                              axis=0).numpy(), a.max(0))
        np.testing.assert_allclose(paddle.min(paddle.to_tensor(a)).numpy(),
                                   a.min())
        np.testing.assert_allclose(paddle.prod(paddle.to_tensor(a),
                                               axis=1).numpy(),
                                   a.prod(1), rtol=1e-5)


class TestManip:
    def test_reshape_zero_dim(self):
        a = RNG.randn(2, 3, 4).astype('float32')
        out = paddle.reshape(paddle.to_tensor(a), [0, 12])
        assert out.shape == [2, 12]

    def test_concat_split(self):
        a = RNG.randn(2, 3).astype('float32')
        b = RNG.randn(2, 5).astype('float32')
        cat = paddle.concat([paddle.to_tensor(a), paddle.to_tensor(b)],
                            axis=1)
        assert cat.shape == [2, 8]
        xs = paddle.split(cat, [3, 5], axis=1)
        np.testing.assert_allclose(xs[0].numpy(), a)
        np.testing.assert_allclose(xs[1].numpy(), b)

    def test_transpose_squeeze(self):
        a = RNG.randn(2, 1, 3).astype('float32')
        out = paddle.transpose(paddle.to_tensor(a), [2, 1, 0])
        assert out.shape == [3, 1, 2]
        sq = paddle.squeeze(paddle.to_tensor(a), axis=1)
        assert sq.shape == [2, 3]

    def test_gather_scatter(self):
        a = RNG.randn(5, 3).astype('float32')
        idx = np.array([0, 2, 4])
        out = paddle.gather(paddle.to_tensor(a), paddle.to_tensor(idx))
        np.testing.assert_allclose(out.numpy(), a[idx])
        upd = np.ones((3, 3), dtype='float32')
        s = paddle.scatter(paddle.to_tensor(a), paddle.to_tensor(idx),
                           paddle.to_tensor(upd))
        ref = a.copy()
        ref[idx] = 1.0
        np.testing.assert_allclose(s.numpy(), ref)

    def test_tile_expand(self):
        a = RNG.randn(1, 3).astype('float32')
        assert paddle.tile(paddle.to_tensor(a), [2, 2]).shape == [2, 6]
        assert paddle.expand(paddle.to_tensor(a), [4, 3]).shape == [4, 3]

    def test_topk_argsort(self):
        a = RNG.randn(3, 8).astype('float32')
        vals, idx = paddle.topk(paddle.to_tensor(a), k=3)
        np.testing.assert_allclose(vals.numpy(),
                                   np.sort(a, axis=1)[:, ::-1][:, :3],
                                   rtol=1e-6)

    def test_getitem(self):
        a = RNG.randn(4, 5).astype('float32')
        t = paddle.to_tensor(a)
        np.testing.assert_allclose(t[1].numpy(), a[1])
        np.testing.assert_allclose(t[1:3, 2:].numpy(), a[1:3, 2:])


class TestNNOps:
    def test_softmax_ce(self):
        logits = RNG.randn(4, 10).astype('float32')
        labels = RNG.randint(0, 10, (4,))
        loss = paddle.nn.functional.softmax_with_cross_entropy(
            paddle.to_tensor(logits), paddle.to_tensor(labels))
        # numpy reference
        e = np.exp(logits - logits.max(1, keepdims=True))
        p = e / e.sum(1, keepdims=True)
        ref = -np.log(p[np.arange(4), labels])
        np.testing.assert_allclose(loss.numpy().squeeze(), ref, rtol=1e-5)

    def test_layer_norm(self):
        x = RNG.randn(2, 5).astype('float32')
        w = np.ones(5, dtype='float32')
        b = np.zeros(5, dtype='float32')
        out = paddle.nn.functional.layer_norm(
            paddle.to_tensor(x), [5], paddle.to_tensor(w),
            paddle.to_tensor(b))
        mu = x.mean(-1, keepdims=True)
        var = x.var(-1, keepdims=True)
        ref = (x - mu) / np.sqrt(var + 1e-5)
        np.testing.assert_allclose(out.numpy(), ref, rtol=1e-4, atol=1e-5)

    def test_conv2d(self):
        x = RNG.randn(1, 2, 5, 5).astype('float32')
        w = RNG.randn(3, 2, 3, 3).astype('float32')
        out = paddle.nn.functional.conv2d(paddle.to_tensor(x),
                                          paddle.to_tensor(w), padding=1)
        assert out.shape == [1, 3, 5, 5]
        ref = jax.lax.conv_general_dilated(
            jnp.asarray(x), jnp.asarray(w), (1, 1), [(1, 1), (1, 1)],
            dimension_numbers=('NCHW', 'OIHW', 'NCHW'))
        np.testing.assert_allclose(out.numpy(), np.asarray(ref), rtol=1e-4,
                                   atol=1e-5)

    def test_pool(self):
        x = RNG.randn(1, 1, 4, 4).astype('float32')
        out = paddle.nn.functional.max_pool2d(paddle.to_tensor(x), 2)
        ref = x.reshape(1, 1, 2, 2, 2, 2).max(axis=(3, 5))
        np.testing.assert_allclose(out.numpy(), ref)

    def test_dropout_train_eval(self):
        x = paddle.ones([100, 100])
        paddle.seed(1)
        out = paddle.nn.functional.dropout(x, p=0.5, training=True)
        frac = float((out.numpy() == 0).mean())
        assert 0.4 < frac < 0.6
        out_eval = paddle.nn.functional.dropout(x, p=0.5, training=False)
        np.testing.assert_allclose(out_eval.numpy(), np.ones((100, 100)))

    def test_embedding(self):
        w = RNG.randn(10, 4).astype('float32')
        idx = np.array([[1, 2], [3, 4]])
        out = paddle.nn.functional.embedding(paddle.to_tensor(idx),
                                             paddle.to_tensor(w))
        np.testing.assert_allclose(out.numpy(), w[idx])


class TestComparisonLogic:
    def test_compare(self):
        a = paddle.to_tensor([1.0, 2.0, 3.0])
        b = paddle.to_tensor([2.0, 2.0, 2.0])
        np.testing.assert_array_equal((a < b).numpy(),
                                      [True, False, False])
        np.testing.assert_array_equal(paddle.equal(a, b).numpy(),
                                      [False, True, False])

    def test_where(self):
        c = paddle.to_tensor([True, False])
        x = paddle.to_tensor([1.0, 1.0])
        y = paddle.to_tensor([2.0, 2.0])
        np.testing.assert_allclose(paddle.where(c, x, y).numpy(), [1.0, 2.0])


class TestCreation:
    def test_creation_family(self):
        assert paddle.zeros([2, 3]).numpy().sum() == 0
        assert paddle.ones([2]).numpy().sum() == 2
        assert paddle.full([2], 7.0).numpy().tolist() == [7.0, 7.0]
        assert paddle.arange(5).numpy().tolist() == [0, 1, 2, 3, 4]
        assert paddle.eye(3).numpy().trace() == 3.0

    def test_random_reproducible(self):
        paddle.seed(42)
        a = paddle.randn([4]).numpy()
        paddle.seed(42)
        b = paddle.randn([4]).numpy()
        np.testing.assert_allclose(a, b)
