"""py_func host-callback op, vision IO (read_file/decode_jpeg), and
incubate segment pooling (reference: py_func_op.cc, read_file_op.cc,
decode_jpeg_op.cu, segment_pool_op.cc)."""
import os
import tempfile

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core.tensor import Tensor


def test_py_func_static_forward_and_backward():
    paddle.enable_static()
    try:
        from paddle_tpu import static
        from paddle_tpu.static import nn as snn
        main, start = static.Program(), static.Program()
        with static.program_guard(main, start):
            x = static.data('x', [3, 4], 'float32')
            x.stop_gradient = False

            def double_it(a):
                return a * 2.0

            def back(a, o, do):
                return do * 2.0

            y = snn.py_func(double_it, x, ([3, 4], 'float32'),
                            backward_func=back)
            loss = paddle.mean(y)
            grads = static.append_backward(loss)
        exe = static.Executor()
        xv = np.arange(12, dtype=np.float32).reshape(3, 4)
        out = exe.run(main, feed={'x': xv}, fetch_list=[y, loss])
        np.testing.assert_allclose(out[0], xv * 2, rtol=1e-6)
        assert abs(float(out[1]) - float((xv * 2).mean())) < 1e-5
    finally:
        paddle.disable_static()


def test_py_func_eager_no_grad():
    from paddle_tpu.static import nn as snn
    x = Tensor(np.ones((2, 2), np.float32))
    y = snn.py_func(lambda a: a + 1, x, ([2, 2], 'float32'))
    np.testing.assert_allclose(np.asarray(y.data), 2.0)


def test_read_file_decode_jpeg_roundtrip():
    from PIL import Image
    from paddle_tpu.vision import ops as vo
    # smooth gradient — JPEG preserves it closely (noise wouldn't be)
    yy, xx = np.mgrid[0:16, 0:20]
    img = np.stack([yy * 8, xx * 8, (yy + xx) * 4], -1).astype(np.uint8)
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, 'x.jpg')
        Image.fromarray(img).save(path, quality=95)
        raw = vo.read_file(path)
        assert raw.data.dtype == np.uint8 and raw.data.ndim == 1
        dec = vo.decode_jpeg(raw, mode='rgb')
        a = np.asarray(dec.data)
        assert a.shape == (3, 16, 20)
        # lossy codec: content close, not exact
        assert np.abs(a.transpose(1, 2, 0).astype(int)
                      - img.astype(int)).mean() < 12
        g = vo.decode_jpeg(raw, mode='gray')
        assert np.asarray(g.data).shape == (1, 16, 20)


def test_segment_ops():
    data = Tensor(np.array([[1., 2.], [3., 4.], [10., 20.], [30., 40.]],
                           np.float32))
    ids = Tensor(np.array([0, 0, 1, 1], np.int32))
    s = np.asarray(paddle.incubate.segment_sum(data, ids).data)
    np.testing.assert_allclose(s, [[4., 6.], [40., 60.]])
    m = np.asarray(paddle.incubate.segment_mean(data, ids).data)
    np.testing.assert_allclose(m, [[2., 3.], [20., 30.]])
    mx = np.asarray(paddle.incubate.segment_max(data, ids).data)
    np.testing.assert_allclose(mx, [[3., 4.], [30., 40.]])
    mn = np.asarray(paddle.incubate.segment_min(data, ids).data)
    np.testing.assert_allclose(mn, [[1., 2.], [10., 20.]])


def test_segment_sum_grad_and_validation():
    data = Tensor(np.ones((4, 2), np.float32))
    data.stop_gradient = False
    ids = Tensor(np.array([0, 1, 1, 2], np.int32))
    out = paddle.incubate.segment_sum(data, ids)
    out.sum().backward()
    np.testing.assert_allclose(np.asarray(data.grad.data), 1.0)
    with pytest.raises(ValueError, match='sorted'):
        paddle.incubate.segment_sum(
            Tensor(np.ones((3, 1), np.float32)),
            Tensor(np.array([1, 0, 2], np.int32)))


def test_segment_max_empty_segment_yields_zero():
    out = paddle.incubate.segment_max(
        Tensor(np.array([[1.], [2.]], np.float32)),
        Tensor(np.array([0, 2], np.int32)))
    np.testing.assert_allclose(np.asarray(out.data),
                               [[1.], [0.], [2.]])
    out = paddle.incubate.segment_min(
        Tensor(np.array([[1.], [2.]], np.float32)),
        Tensor(np.array([0, 2], np.int32)))
    np.testing.assert_allclose(np.asarray(out.data),
                               [[1.], [0.], [2.]])


def test_py_func_rejects_dynamic_dims():
    from paddle_tpu.static import nn as snn
    x = Tensor(np.ones((2, 2), np.float32))
    with pytest.raises(ValueError, match='dynamic'):
        snn.py_func(lambda a: a, x, ([-1, 2], 'float32'))
