"""DGC optimizer, fleet distributed metrics, multiprocess DataLoader."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.core.tensor import Tensor


class TestDGC:
    def test_dgc_momentum_converges(self):
        """Top-k sparsified updates + residual accumulation still solve
        the regression (parity: DGCMomentumOptimizer semantics)."""
        paddle.seed(0)
        rng = np.random.RandomState(0)
        xs = rng.rand(64, 8).astype('float32')
        w_true = rng.randn(8, 1).astype('float32')
        ys = xs @ w_true
        net = nn.Linear(8, 1)
        opt = paddle.optimizer.DGCMomentumOptimizer(
            learning_rate=0.2, momentum=0.9, sparsity=[0.75],
            rampup_begin_step=0, parameters=net.parameters())
        x, y = Tensor(xs), Tensor(ys)
        losses = []
        for _ in range(120):
            loss = ((net(x) - y) * (net(x) - y)).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss))
        assert losses[-1] < 0.05 * losses[0], (losses[0], losses[-1])

    def test_dgc_update_is_sparse(self):
        w = paddle.to_tensor(np.zeros(100, 'float32'))
        w.stop_gradient = False
        opt = paddle.optimizer.DGCMomentumOptimizer(
            learning_rate=1.0, momentum=0.0, sparsity=[0.9],
            rampup_begin_step=0, parameters=[w])
        g = np.random.RandomState(0).randn(100).astype('float32')
        loss = (w * Tensor(g)).sum()
        loss.backward()
        opt.step()
        # ~10% of entries updated, the rest accumulate locally
        changed = (np.asarray(w.data) != 0).sum()
        assert changed <= 15, changed

    def test_dgc_meta_optimizer_applies(self):
        import os
        import paddle_tpu.distributed.fleet as fleet
        import paddle_tpu.static as static
        os.environ.setdefault('PADDLE_TRAINER_ID', '0')
        paddle.enable_static()
        try:
            fleet.fleet._hcg = None
            main = static.Program()
            with static.program_guard(main):
                x = static.data('x', [4, 8])
                yv = static.nn.fc(x, 1)
                loss = paddle.mean(yv * yv)
            s = fleet.DistributedStrategy()
            s.dgc = True
            fleet.init(is_collective=True, strategy=s)
            opt = paddle.optimizer.Momentum(learning_rate=0.1, momentum=0.9)
            opt = fleet.fleet.distributed_optimizer(opt)
            fleet.fleet.minimize(loss)
            types = [op.type for op in main.global_block().ops]
            assert 'dgcmomentumoptimizer' in types, types
        finally:
            paddle.disable_static()


class TestFleetMetrics:
    def test_local_aggregates(self):
        from paddle_tpu.distributed.fleet import metrics as M
        assert M.sum(np.array([1.0, 2.0, 3.0])) == 6.0
        assert M.max(np.array([1.0, 5.0])) == 5.0
        assert M.min(Tensor(np.array([2.0, 7.0], 'float32'))) == 2.0
        assert abs(M.acc(np.array([8.0]), np.array([10.0])) - 0.8) < 1e-9

    def test_auc_from_buckets(self):
        from paddle_tpu.distributed.fleet import metrics as M
        # perfect separation: positives in the top bucket
        pos = np.array([0.0, 0.0, 0.0, 10.0])
        neg = np.array([10.0, 0.0, 0.0, 0.0])
        assert M.auc(pos, neg) == 1.0
        # identical distributions -> 0.5
        same = np.array([5.0, 5.0, 5.0, 5.0])
        assert abs(M.auc(same, same) - 0.5) < 1e-9


class _SquareDataset:
    def __len__(self):
        return 32

    def __getitem__(self, i):
        return (np.full((3,), i, np.float32),
                np.array([i * i], np.float32))


class TestMultiprocessDataLoader:
    def test_worker_processes_match_single(self):
        from paddle_tpu.io import DataLoader
        ds = _SquareDataset()
        ref = [tuple(np.asarray(t.data) for t in b)
               for b in DataLoader(ds, batch_size=4, num_workers=0)]
        got = [tuple(np.asarray(t.data) for t in b)
               for b in DataLoader(ds, batch_size=4, num_workers=2)]
        assert len(got) == len(ref) == 8
        for (a1, b1), (a2, b2) in zip(ref, got):   # order preserved
            np.testing.assert_allclose(a1, a2)
            np.testing.assert_allclose(b1, b2)

    def test_worker_error_surfaces(self):
        from paddle_tpu.io import DataLoader

        class Bad:
            def __len__(self):
                return 8

            def __getitem__(self, i):
                if i == 5:
                    raise ValueError("boom")
                return np.zeros(2, np.float32)

        with pytest.raises(RuntimeError, match="boom"):
            list(DataLoader(Bad(), batch_size=2, num_workers=2))


class TestOptimizerTail:
    """Adadelta / DecayedAdagrad / Ftrl vs hand-computed update rules
    (operators/optimizers/*_op parity)."""

    def _one_step(self, opt_cls, **kw):
        import paddle_tpu as paddle
        paddle.seed(0)
        w = paddle.to_tensor(np.array([1.0, -2.0], 'float32'))
        w.stop_gradient = False
        opt = opt_cls(learning_rate=0.1, parameters=[w], **kw)
        loss = (w * w).sum()
        loss.backward()
        g = np.asarray(w.grad.data).copy()
        opt.step()
        return np.asarray(w.data), g

    def test_adadelta_rule(self):
        import paddle_tpu as paddle
        w, g = self._one_step(paddle.optimizer.Adadelta, rho=0.9,
                              epsilon=1e-6)
        g2 = 0.1 * g * g
        upd = g * np.sqrt(1e-6) / np.sqrt(g2 + 1e-6)
        np.testing.assert_allclose(w, [1.0, -2.0] - 0.1 * upd, rtol=1e-5)

    def test_decayed_adagrad_rule(self):
        import paddle_tpu as paddle
        w, g = self._one_step(paddle.optimizer.DecayedAdagrad, decay=0.9,
                              epsilon=1e-6)
        m = 0.1 * g * g
        np.testing.assert_allclose(
            w, [1.0, -2.0] - 0.1 * g / (np.sqrt(m) + 1e-6), rtol=1e-5)

    def test_ftrl_sparsifies(self):
        import paddle_tpu as paddle
        # strong l1 pushes small-coordinate weights exactly to zero
        paddle.seed(0)
        w = paddle.to_tensor(np.array([0.01, 5.0], 'float32'))
        w.stop_gradient = False
        opt = paddle.optimizer.Ftrl(learning_rate=0.5, l1=10.0, l2=0.0,
                                    parameters=[w])
        for _ in range(3):
            loss = (w * w).sum()
            loss.backward()
            opt.step()
            opt.clear_grad()
        vals = np.asarray(w.data)
        assert vals[0] == 0.0                   # l1 zeroed the small one


class TestMiscOpTail:
    def test_center_loss(self):
        from paddle_tpu.ops import contrib as C
        from paddle_tpu.core.tensor import Tensor
        import jax.numpy as jnp
        x = np.array([[1., 0.], [0., 1.], [2., 0.]], 'float32')
        c = np.zeros((2, 2), 'float32')
        y = np.array([0, 1, 0], 'int64')
        loss, nc = C.center_loss(Tensor(jnp.asarray(x)),
                                 Tensor(jnp.asarray(y)), 2,
                                 alpha=0.5,
                                 centers=Tensor(jnp.asarray(c)))
        np.testing.assert_allclose(
            np.asarray(loss.data).reshape(-1),
            [0.5, 0.5, 2.0], rtol=1e-6)
        # class 0: residual mean (x0 + x2)/ (2+1) * alpha
        np.testing.assert_allclose(np.asarray(nc.data)[0],
                                   0.5 * (x[0] + x[2]) / 3.0, rtol=1e-6)

    def test_hash_op_bounds_and_determinism(self):
        from paddle_tpu.ops import contrib as C
        from paddle_tpu.core.tensor import Tensor
        import jax.numpy as jnp
        ids = Tensor(jnp.asarray(np.arange(100, dtype='int64')))
        h1 = np.asarray(C.hash_op(ids, num_hash=4, mod_by=97).data)
        h2 = np.asarray(C.hash_op(ids, num_hash=4, mod_by=97).data)
        assert h1.shape == (100, 4)
        assert (h1 >= 0).all() and (h1 < 97).all()
        np.testing.assert_array_equal(h1, h2)
        assert len(np.unique(h1)) > 20          # spreads

    def test_ctc_align(self):
        from paddle_tpu.ops import contrib as C
        from paddle_tpu.core.tensor import Tensor
        import jax.numpy as jnp
        ids = np.array([[0, 1, 1, 0, 2, 2, 0, 3]], 'int32')
        out, lens = C.ctc_align(Tensor(jnp.asarray(ids)), blank=0)
        np.testing.assert_array_equal(np.asarray(out.data)[0][:3],
                                      [1, 2, 3])
        assert int(np.asarray(lens.data)[0]) == 3
        assert (np.asarray(out.data)[0][3:] == 0).all()

    def test_conv_shift_oracle(self):
        from paddle_tpu.ops import contrib as C
        from paddle_tpu.core.tensor import Tensor
        import jax.numpy as jnp
        rng = np.random.RandomState(0)
        x = rng.rand(2, 7).astype('float32')
        y = rng.rand(2, 3).astype('float32')
        out = np.asarray(C.conv_shift(Tensor(jnp.asarray(x)),
                                      Tensor(jnp.asarray(y))).data)
        want = np.zeros_like(x)
        for b in range(2):
            for i in range(7):
                for j in range(3):
                    want[b, i] += x[b, (i + j - 1) % 7] * y[b, j]
        np.testing.assert_allclose(out, want, rtol=1e-5)

    def test_filter_by_instag(self):
        from paddle_tpu.ops import contrib as C
        from paddle_tpu.core.tensor import Tensor
        import jax.numpy as jnp
        x = np.arange(12, dtype='float32').reshape(4, 3)
        tags = np.array([[1], [2], [1], [3]], 'int64')
        out, idx, w = C.filter_by_instag(
            Tensor(jnp.asarray(x)), Tensor(jnp.asarray(tags)),
            Tensor(jnp.asarray(np.array([1], 'int64'))))
        np.testing.assert_array_equal(np.asarray(idx.data), [0, 2])
        np.testing.assert_allclose(np.asarray(out.data), x[[0, 2]])
        assert np.asarray(w.data).sum() == 2

    def test_chunk_eval_iob(self):
        from paddle_tpu.ops import contrib as C
        from paddle_tpu.core.tensor import Tensor
        import jax.numpy as jnp
        # tags: B=0 I=1 (single chunk type); tags >= 2*num_chunk_types
        # are O — recognized WITHOUT manual exclusion
        lab = np.array([[0, 1, 4, 0, 1, 1]], 'int64')   # 2 chunks
        inf = np.array([[0, 1, 4, 0, 4, 4]], 'int64')   # 2nd truncated
        p, r, f1, ni, nl, nc = C.chunk_eval(
            Tensor(jnp.asarray(inf)), Tensor(jnp.asarray(lab)),
            num_chunk_types=1)
        assert int(np.asarray(ni.data)) == 2
        assert int(np.asarray(nl.data)) == 2
        assert int(np.asarray(nc.data)) == 1
        assert abs(float(np.asarray(f1.data)) - 0.5) < 1e-6


class TestCrypto:
    """N38: model-file encryption (framework/io/crypto parity)."""

    @pytest.fixture(autouse=True)
    def _need_cryptography(self):
        from paddle_tpu.utils import crypto
        if not crypto.HAVE_CRYPTOGRAPHY:
            pytest.skip("cryptography package not available in this image")

    def test_ctr_roundtrip_and_file(self, tmp_path):
        from paddle_tpu.utils.crypto import CipherFactory, CipherUtils
        key = CipherUtils.gen_key(256)
        c = CipherFactory.create_cipher()
        data = b'serialized program bytes' * 100
        ct = c.encrypt(data, key)
        assert ct != data and len(ct) > len(data)
        assert c.decrypt(ct, key) == data
        c.encrypt_to_file(data, key, str(tmp_path / 'm.enc'))
        assert c.decrypt_from_file(key, str(tmp_path / 'm.enc')) == data

    def test_gcm_detects_tamper(self, tmp_path):
        from paddle_tpu.utils.crypto import AESCipher, CipherUtils
        key = CipherUtils.gen_key(128)
        c = AESCipher('AES_GCM_NoPadding')
        ct = bytearray(c.encrypt(b'weights', key))
        ct[-1] ^= 0xFF
        with pytest.raises(Exception):
            c.decrypt(bytes(ct), key)

    def test_gcm_short_tag_roundtrip(self):
        from paddle_tpu.utils.crypto import AESCipher, CipherUtils
        key = CipherUtils.gen_key(128)
        c = AESCipher('AES_GCM_NoPadding', tag_size=96)
        assert c.decrypt(c.encrypt(b'weights', key), key) == b'weights'
        with pytest.raises(ValueError):
            AESCipher('AES_CTR_NoPadding', iv_size=256)

    def test_key_file_and_config(self, tmp_path):
        from paddle_tpu.utils.crypto import CipherFactory, CipherUtils
        key = CipherUtils.gen_key_to_file(128, str(tmp_path / 'k'))
        assert CipherUtils.read_key_from_file(str(tmp_path / 'k')) == key
        (tmp_path / 'cfg').write_text('cipher_name: AES_GCM_NoPadding\n')
        c = CipherFactory.create_cipher(str(tmp_path / 'cfg'))
        assert c.name == 'AES_GCM_NoPadding'
        assert c.decrypt(c.encrypt(b'x', key), key) == b'x'


class TestDatasetFolders:
    def _tree(self, tmp_path, labeled=True):
        for c, n in (('cat', 3), ('dog', 2)):
            d = tmp_path / c
            d.mkdir()
            for i in range(n):
                np.save(str(d / f'{i}.npy'),
                        np.full((3, 8, 8), ord(c[0]) + i, np.float32))
        return str(tmp_path)

    def test_dataset_folder_discovers_classes(self, tmp_path):
        from paddle_tpu.vision.datasets import DatasetFolder
        ds = DatasetFolder(self._tree(tmp_path))
        assert ds.classes == ['cat', 'dog'] and len(ds) == 5
        img, lb = ds[0]
        assert img.shape == (3, 8, 8) and int(lb[0]) == 0
        assert int(ds[4][1][0]) == 1

    def test_image_folder_unlabeled(self, tmp_path):
        from paddle_tpu.vision.datasets import ImageFolder
        ds = ImageFolder(self._tree(tmp_path))
        assert len(ds) == 5
        assert ds[0][0].shape == (3, 8, 8)

    def test_dataloader_over_folder(self, tmp_path):
        from paddle_tpu.vision.datasets import DatasetFolder
        from paddle_tpu.io import DataLoader
        ds = DatasetFolder(self._tree(tmp_path))
        batches = list(DataLoader(ds, batch_size=2, shuffle=False))
        assert len(batches) == 3
        assert batches[0][0].shape[0] == 2

    def test_voc_flowers_shapes(self):
        from paddle_tpu.vision.datasets import Flowers, VOC2012
        f = Flowers(mode='test')
        img, lb = f[0]
        assert img.shape == (3, 64, 64) and 0 <= int(lb[0]) < 102
        v = VOC2012(mode='test')
        img, mask = v[0]
        assert img.shape == (3, 64, 64) and mask.shape == (64, 64)

    def test_folder_contract_regressions(self, tmp_path):
        """Review regressions: uppercase .NPY decodes; is_valid_file
        receives the full path; a custom loader always wins."""
        import os
        from paddle_tpu.vision.datasets import DatasetFolder
        d = tmp_path / 'c0'
        d.mkdir()
        np.save(str(d / 'x.npy'), np.ones((2, 2), np.float32))
        os.rename(str(d / 'x.npy'), str(d / 'X.NPY'))
        ds = DatasetFolder(str(tmp_path))
        assert ds[0][0].shape == (2, 2)          # .NPY decoded via numpy
        seen = []
        DatasetFolder(str(tmp_path),
                      is_valid_file=lambda p: seen.append(p)
                      or os.path.exists(p))
        assert seen and all(os.path.isabs(p) or os.sep in p
                            for p in seen)       # full paths
        ds2 = DatasetFolder(str(tmp_path),
                            loader=lambda p: np.zeros((1,), np.float32))
        assert ds2[0][0].shape == (1,)           # custom loader wins
