"""DGC optimizer, fleet distributed metrics, multiprocess DataLoader."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.core.tensor import Tensor


class TestDGC:
    def test_dgc_momentum_converges(self):
        """Top-k sparsified updates + residual accumulation still solve
        the regression (parity: DGCMomentumOptimizer semantics)."""
        paddle.seed(0)
        rng = np.random.RandomState(0)
        xs = rng.rand(64, 8).astype('float32')
        w_true = rng.randn(8, 1).astype('float32')
        ys = xs @ w_true
        net = nn.Linear(8, 1)
        opt = paddle.optimizer.DGCMomentumOptimizer(
            learning_rate=0.2, momentum=0.9, sparsity=[0.75],
            rampup_begin_step=0, parameters=net.parameters())
        x, y = Tensor(xs), Tensor(ys)
        losses = []
        for _ in range(120):
            loss = ((net(x) - y) * (net(x) - y)).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss))
        assert losses[-1] < 0.05 * losses[0], (losses[0], losses[-1])

    def test_dgc_update_is_sparse(self):
        w = paddle.to_tensor(np.zeros(100, 'float32'))
        w.stop_gradient = False
        opt = paddle.optimizer.DGCMomentumOptimizer(
            learning_rate=1.0, momentum=0.0, sparsity=[0.9],
            rampup_begin_step=0, parameters=[w])
        g = np.random.RandomState(0).randn(100).astype('float32')
        loss = (w * Tensor(g)).sum()
        loss.backward()
        opt.step()
        # ~10% of entries updated, the rest accumulate locally
        changed = (np.asarray(w.data) != 0).sum()
        assert changed <= 15, changed

    def test_dgc_meta_optimizer_applies(self):
        import os
        import paddle_tpu.distributed.fleet as fleet
        import paddle_tpu.static as static
        os.environ.setdefault('PADDLE_TRAINER_ID', '0')
        paddle.enable_static()
        try:
            fleet.fleet._hcg = None
            main = static.Program()
            with static.program_guard(main):
                x = static.data('x', [4, 8])
                yv = static.nn.fc(x, 1)
                loss = paddle.mean(yv * yv)
            s = fleet.DistributedStrategy()
            s.dgc = True
            fleet.init(is_collective=True, strategy=s)
            opt = paddle.optimizer.Momentum(learning_rate=0.1, momentum=0.9)
            opt = fleet.fleet.distributed_optimizer(opt)
            fleet.fleet.minimize(loss)
            types = [op.type for op in main.global_block().ops]
            assert 'dgcmomentumoptimizer' in types, types
        finally:
            paddle.disable_static()


class TestFleetMetrics:
    def test_local_aggregates(self):
        from paddle_tpu.distributed.fleet import metrics as M
        assert M.sum(np.array([1.0, 2.0, 3.0])) == 6.0
        assert M.max(np.array([1.0, 5.0])) == 5.0
        assert M.min(Tensor(np.array([2.0, 7.0], 'float32'))) == 2.0
        assert abs(M.acc(np.array([8.0]), np.array([10.0])) - 0.8) < 1e-9

    def test_auc_from_buckets(self):
        from paddle_tpu.distributed.fleet import metrics as M
        # perfect separation: positives in the top bucket
        pos = np.array([0.0, 0.0, 0.0, 10.0])
        neg = np.array([10.0, 0.0, 0.0, 0.0])
        assert M.auc(pos, neg) == 1.0
        # identical distributions -> 0.5
        same = np.array([5.0, 5.0, 5.0, 5.0])
        assert abs(M.auc(same, same) - 0.5) < 1e-9


class _SquareDataset:
    def __len__(self):
        return 32

    def __getitem__(self, i):
        return (np.full((3,), i, np.float32),
                np.array([i * i], np.float32))


class TestMultiprocessDataLoader:
    def test_worker_processes_match_single(self):
        from paddle_tpu.io import DataLoader
        ds = _SquareDataset()
        ref = [tuple(np.asarray(t.data) for t in b)
               for b in DataLoader(ds, batch_size=4, num_workers=0)]
        got = [tuple(np.asarray(t.data) for t in b)
               for b in DataLoader(ds, batch_size=4, num_workers=2)]
        assert len(got) == len(ref) == 8
        for (a1, b1), (a2, b2) in zip(ref, got):   # order preserved
            np.testing.assert_allclose(a1, a2)
            np.testing.assert_allclose(b1, b2)

    def test_worker_error_surfaces(self):
        from paddle_tpu.io import DataLoader

        class Bad:
            def __len__(self):
                return 8

            def __getitem__(self, i):
                if i == 5:
                    raise ValueError("boom")
                return np.zeros(2, np.float32)

        with pytest.raises(RuntimeError, match="boom"):
            list(DataLoader(Bad(), batch_size=2, num_workers=2))
