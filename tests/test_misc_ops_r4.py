"""Wave-4 misc op tier vs numpy oracles (reference test semantics:
test_mean_iou.py, test_edit_distance_op.py, test_precision_recall_op.py,
test_positive_negative_pair_op.py, test_polygon_box_transform.py,
gather_tree docstring example fluid/layers/nn.py:14984)."""
import itertools

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.ops import contrib


def test_gather_tree_reference_example():
    ids = np.array([[[2, 2], [6, 1]], [[3, 9], [6, 1]],
                    [[0, 1], [9, 0]]], np.int64)
    parents = np.array([[[0, 0], [1, 1]], [[1, 0], [1, 0]],
                        [[0, 0], [0, 1]]], np.int64)
    out = contrib.gather_tree(Tensor(ids), Tensor(parents))
    want = np.array([[[2, 2], [1, 6]], [[3, 3], [6, 1]],
                     [[0, 1], [9, 0]]], np.int64)
    np.testing.assert_array_equal(np.asarray(out.data), want)


def _levenshtein(hyp, ref):
    m, n = len(hyp), len(ref)
    d = np.zeros((m + 1, n + 1), np.float32)
    d[:, 0] = np.arange(m + 1)
    d[0, :] = np.arange(n + 1)
    for i in range(1, m + 1):
        for j in range(1, n + 1):
            cost = 0 if hyp[i - 1] == ref[j - 1] else 1
            d[i][j] = min(d[i - 1][j] + 1, d[i][j - 1] + 1,
                          d[i - 1][j - 1] + cost)
    return d[m][n]


@pytest.mark.parametrize('normalized', [False, True])
def test_edit_distance_matches_levenshtein(normalized):
    rng = np.random.RandomState(0)
    B, T1, T2 = 5, 9, 7
    x = rng.randint(1, 20, (B, T1)).astype(np.int64)
    y = rng.randint(1, 20, (B, T2)).astype(np.int64)
    l1 = rng.randint(1, T1 + 1, (B,)).astype(np.int64)
    l2 = rng.randint(1, T2 + 1, (B,)).astype(np.int64)
    out, seq_num = contrib.edit_distance(
        Tensor(x), Tensor(y), normalized=normalized,
        input_length=Tensor(l1), label_length=Tensor(l2))
    got = np.asarray(out.data).reshape(-1)
    for b in range(B):
        want = _levenshtein(list(x[b, :l1[b]]), list(y[b, :l2[b]]))
        if normalized:
            want = want / max(float(l2[b]), 1.0)
        assert abs(got[b] - want) < 1e-5, (b, got[b], want)
    assert int(seq_num.data) == B


def test_edit_distance_ignored_tokens():
    x = np.array([[12, 3, 0, 5, 8]], np.int64)
    y = np.array([[12, 0, 3, 5]], np.int64)
    out, _ = contrib.edit_distance(
        Tensor(x), Tensor(y), normalized=False, ignored_tokens=[0])
    # after dropping 0s: [12,3,5,8] vs [12,3,5] -> distance 1
    assert float(out.data.reshape(())) == 1.0


def test_mean_iou_oracle():
    rng = np.random.RandomState(1)
    C = 5
    pred = rng.randint(0, C, (16, 8)).astype(np.int32)
    lab = rng.randint(0, C, (16, 8)).astype(np.int32)
    miou, wrong, correct = contrib.mean_iou(Tensor(pred), Tensor(lab), C)
    ow = np.zeros(C, np.int32)
    oc = np.zeros(C, np.int32)
    for p, l in zip(pred.ravel(), lab.ravel()):
        if p == l:
            oc[p] += 1
        else:
            ow[p] += 1
            ow[l] += 1
    denom = ow + oc
    valid = (denom != 0).sum()
    want = (oc / np.where(denom > 0, denom, 1)).sum() / valid
    np.testing.assert_array_equal(np.asarray(wrong.data), ow)
    np.testing.assert_array_equal(np.asarray(correct.data), oc)
    assert abs(float(miou.data) - want) < 1e-6


def test_precision_recall_oracle():
    rng = np.random.RandomState(2)
    N, C = 64, 10
    idx = rng.randint(0, C, (N, 1)).astype(np.int32)
    lab = rng.randint(0, C, (N, 1)).astype(np.int32)
    probs = rng.uniform(0, 1, (N, 1)).astype(np.float32)

    def oracle_states(idxs, labels):
        st = np.zeros((C, 4), np.float32)
        for i in range(N):
            p, l = idxs[i][0], labels[i][0]
            if p == l:
                st[p][0] += 1
                st[:, 2] += 1
                st[p][2] -= 1
            else:
                st[l][3] += 1
                st[p][1] += 1
                st[:, 2] += 1
                st[l][2] -= 1
                st[p][2] -= 1
        return st

    def oracle_metrics(st):
        def prec(t, f):
            return t / (t + f) if (t > 0 or f > 0) else 1.0

        def f1(p, r):
            return 2 * p * r / (p + r) if (p > 0 or r > 0) else 0.0
        mp = np.mean([prec(st[i][0], st[i][1]) for i in range(C)])
        mr = np.mean([prec(st[i][0], st[i][3]) for i in range(C)])
        tp, fp, fn = st[:, 0].sum(), st[:, 1].sum(), st[:, 3].sum()
        up, ur = prec(tp, fp), prec(tp, fn)
        return np.array([mp, mr, f1(mp, mr), up, ur, f1(up, ur)],
                        np.float32)

    st = oracle_states(idx, lab)
    bm, am, accum = contrib.precision_recall(
        Tensor(probs), Tensor(idx), Tensor(lab), C)
    np.testing.assert_allclose(np.asarray(accum.data), st, atol=1e-5)
    np.testing.assert_allclose(np.asarray(bm.data), oracle_metrics(st),
                               atol=1e-5)
    # streaming: feeding prior states accumulates
    bm2, am2, accum2 = contrib.precision_recall(
        Tensor(probs), Tensor(idx), Tensor(lab), C, states=accum)
    np.testing.assert_allclose(np.asarray(accum2.data), 2 * st, atol=1e-5)
    np.testing.assert_allclose(np.asarray(am2.data),
                               oracle_metrics(2 * st), atol=1e-5)


def test_positive_negative_pair_oracle():
    rng = np.random.RandomState(3)
    N = 20
    score = rng.normal(size=(N, 1)).astype(np.float32)
    label = rng.normal(size=(N, 1)).astype(np.float32)
    query = rng.randint(0, 5, (N, 1)).astype(np.int64)

    groups = {}
    for s, l, q in zip(score, label, query):
        groups.setdefault(int(q[0]), []).append((float(s[-1]),
                                                 float(l[0])))
    pos = neg = neu = 0.0
    for ranks in groups.values():
        for (s1, l1), (s2, l2) in itertools.combinations(ranks, 2):
            if l1 == l2:
                continue
            if s1 == s2:
                neu += 1
            elif (s1 - s2) * (l1 - l2) > 0:
                pos += 1
            else:
                neg += 1
    p, n, u = contrib.positive_negative_pair(
        Tensor(score), Tensor(label), Tensor(query))
    assert (float(p.data), float(n.data), float(u.data)) == (pos, neg, neu)


def test_affine_channel_grad():
    rng = np.random.RandomState(4)
    x = Tensor(rng.randn(2, 3, 4, 5).astype(np.float32))
    x.stop_gradient = False
    scale = Tensor(rng.randn(3).astype(np.float32))
    scale.stop_gradient = False
    bias = Tensor(rng.randn(3).astype(np.float32))
    out = contrib.affine_channel(x, scale, bias)
    want = np.asarray(x.data) * np.asarray(scale.data).reshape(1, 3, 1, 1) \
        + np.asarray(bias.data).reshape(1, 3, 1, 1)
    np.testing.assert_allclose(np.asarray(out.data), want, rtol=1e-6)
    out.sum().backward()
    np.testing.assert_allclose(
        np.asarray(scale.grad.data),
        np.asarray(x.data).sum(axis=(0, 2, 3)), rtol=1e-4)


def test_row_hash_shape_and_determinism():
    x = np.array([[1, 2, 3], [4, 5, 6], [1, 2, 3]], np.int64)
    out = contrib.row_hash(Tensor(x), hash_size=1000, num_hash=4)
    a = np.asarray(out.data)
    assert a.shape == (3, 4, 1)
    assert (a >= 0).all() and (a < 1000).all()
    np.testing.assert_array_equal(a[0], a[2])     # same row, same buckets
    assert not np.array_equal(a[0], a[1])
    # row-as-unit: permuting the row changes the bucket (order matters)
    y = np.array([[3, 2, 1]], np.int64)
    b = np.asarray(contrib.row_hash(Tensor(y), 1000, num_hash=4).data)
    assert not np.array_equal(a[0], b[0])
    # element-wise cousin keeps its original contract
    e = contrib.hash_op(Tensor(x), num_hash=2, mod_by=97)
    assert np.asarray(e.data).shape == (3, 3, 2)


def test_sample_logits_accidental_hit_masked():
    # force a collision: tiny class space makes negatives hit the label
    rng = np.random.RandomState(9)
    B, C, S = 4, 3, 32
    logits = rng.randn(B, C).astype(np.float32)
    labels = np.full((B, 1), 1, np.int64)
    samples, probs, slog, _ = contrib.sample_logits(
        Tensor(logits), Tensor(labels), num_samples=S,
        uniq=False, remove_accidental_hits=True, seed=13)
    sa, sl = np.asarray(samples.data), np.asarray(slog.data)
    hits = sa[:, 1:] == 1
    assert hits.any()                  # collision actually occurred
    assert (sl[:, 1:][hits] < -1e19).all()
    assert (sl[:, 1:][~hits] > -1e19).all()


def test_sample_logits_uniq_masks_duplicates():
    rng = np.random.RandomState(10)
    B, C, S = 2, 4, 16
    logits = rng.randn(B, C).astype(np.float32)
    labels = np.zeros((B, 1), np.int64)
    samples, probs, slog, _ = contrib.sample_logits(
        Tensor(logits), Tensor(labels), num_samples=S,
        uniq=True, remove_accidental_hits=False, seed=3)
    sa, pr, sl = (np.asarray(t.data) for t in (samples, probs, slog))
    neg = sa[0, 1:]
    live = sl[0, 1:] > -1e19
    # at most one live column per distinct sampled class
    for c in np.unique(neg):
        assert live[neg == c].sum() <= 1
    # every distinct class keeps exactly its first occurrence live
    first_idx = {c: int(np.argmax(neg == c)) for c in np.unique(neg)}
    for c, i in first_idx.items():
        assert live[i]
    # probabilities report the inclusion mass 1-(1-q)^S, in (0, 1]
    assert ((pr > 0) & (pr <= 1)).all()


def test_sample_logits_static_recordable():
    import paddle_tpu as pd
    from paddle_tpu import static
    pd.enable_static()
    try:
        main, start = static.Program(), static.Program()
        with static.program_guard(main, start):
            lg = static.data('lg', [4, 30], 'float32')
            lb = static.data('lb', [4, 1], 'int64')
            _, _, slog, _ = contrib.sample_logits(lg, lb, 8, seed=5)
            miou, _, _ = contrib.mean_iou(
                static.data('p', [8, 8], 'int32'),
                static.data('l', [8, 8], 'int32'), 5)
        exe = static.Executor()
        rng = np.random.RandomState(11)
        out = exe.run(main, feed={
            'lg': rng.randn(4, 30).astype(np.float32),
            'lb': rng.randint(0, 30, (4, 1)).astype(np.int64),
            'p': rng.randint(0, 5, (8, 8)).astype(np.int32),
            'l': rng.randint(0, 5, (8, 8)).astype(np.int32)},
            fetch_list=[slog, miou])
        assert out[0].shape == (4, 9)
        assert 0.0 <= float(out[1]) <= 1.0
    finally:
        pd.disable_static()


def test_sample_logits_contract():
    rng = np.random.RandomState(5)
    B, C, S = 4, 30, 8
    logits = rng.randn(B, C).astype(np.float32)
    labels = rng.randint(0, C, (B, 1)).astype(np.int64)
    samples, probs, slog, slab = contrib.sample_logits(
        Tensor(logits), Tensor(labels), num_samples=S,
        uniq=False, remove_accidental_hits=False, seed=7)
    sa, pr, sl = (np.asarray(t.data) for t in (samples, probs, slog))
    assert sa.shape == (B, 1 + S) and sl.shape == (B, 1 + S)
    np.testing.assert_array_equal(sa[:, 0], labels.reshape(-1))
    np.testing.assert_array_equal(np.asarray(slab.data),
                                  np.zeros((B, 1), np.int64))
    want = np.take_along_axis(logits, sa.astype(np.int64), 1) - np.log(pr)
    np.testing.assert_allclose(sl, want, rtol=1e-5)


def test_polygon_box_transform_oracle():
    rng = np.random.RandomState(6)
    x = rng.rand(2, 8, 3, 4).astype(np.float32)
    out = np.asarray(contrib.polygon_box_transform(Tensor(x)).data)
    h, w = 3, 4
    wi = np.tile(np.arange(w), (h, 1))
    hi = np.tile(np.arange(h)[:, None], (1, w))
    idx = np.stack([wi, hi])                      # [2, h, w]
    idx = np.tile(idx, (4, 1, 1))[None]           # [1, 8, h, w]
    np.testing.assert_allclose(out, idx * 4 - x, rtol=1e-6)


def test_random_crop_shape_and_content():
    rng = np.random.RandomState(7)
    x = rng.rand(4, 10, 12).astype(np.float32)
    out = np.asarray(contrib.random_crop(Tensor(x), [6, 7],
                                         seed=11).data)
    assert out.shape == (4, 6, 7)
    # each crop is a contiguous window of its instance
    for b in range(4):
        found = any(
            np.allclose(out[b], x[b, i:i + 6, j:j + 7])
            for i in range(5) for j in range(6))
        assert found


def test_static_nn_names_resolve():
    from paddle_tpu.static import nn as snn
    for n in ['mean_iou', 'precision_recall', 'positive_negative_pair',
              'affine_channel', 'sample_logits', 'random_crop',
              'polygon_box_transform', 'hash', 'gather_tree',
              'edit_distance']:
        assert callable(getattr(snn, n)), n
    assert callable(paddle.nn.functional.gather_tree)
    assert callable(paddle.metric.mean_iou)
