"""BASELINE config 3 (BERT + bf16 + ZeRO-ish sharding) + ASP tests."""
import numpy as np
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.distributed import topology_runtime
from paddle_tpu.distributed.fleet.meta_parallel.hybrid_engine import (
    HybridParallelTrainStep)


def test_bert_tiny_bf16_zero_trains():
    """Config 3 pattern: BERT pretraining, bf16 params + fp32 masters,
    dp=2 x sharding=4 optimizer-state sharding."""
    from paddle_tpu.models.bert import (BertConfig, BertForPretraining,
                                        bert_pretrain_loss)
    topology_runtime.build_mesh(['dp', 'sharding'], [2, 4])
    paddle.seed(0)
    cfg = BertConfig(vocab_size=128, hidden_size=32, num_layers=2,
                     num_heads=2, intermediate_size=64, max_seq_len=32,
                     hidden_dropout=0.0, attn_dropout=0.0)
    model = BertForPretraining(cfg)
    for p in model.parameters():
        if p.data.dtype == jnp.float32:
            p.data = p.data.astype(jnp.bfloat16)

    def loss_fn(m, ids, mlm_labels, nsp_labels):
        mlm_logits, nsp_logits = m(ids)
        return bert_pretrain_loss(mlm_logits, nsp_logits, mlm_labels,
                                  nsp_labels)

    opt = paddle.optimizer.AdamW(learning_rate=5e-3,
                                 parameters=model.parameters(),
                                 weight_decay=0.01)
    eng = HybridParallelTrainStep(model, loss_fn, opt)
    rng = np.random.RandomState(0)
    ids = Tensor(rng.randint(0, 128, (8, 32)).astype('int32'))
    mlm = Tensor(np.asarray(ids.data).astype('int64'))
    nsp = Tensor(rng.randint(0, 2, (8,)).astype('int64'))
    losses = [float(eng(ids, mlm, nsp)) for _ in range(6)]
    assert losses[-1] < losses[0], losses
    # ZeRO: adam moments (and the fp32 masters) shard 1/n over the dp
    # axes — since ISSUE 4 as flat bucket states partitioned over
    # ('dp','sharding') on dim 0 (core/bucketing.py), not per-param
    # 'sharding' slices
    assert eng._bucketed and 'sharding' in eng._rs_axes
    name = 'bert.encoder.layers.0.linear1.weight'
    slot = eng._layout.slots[name]
    spec = eng._state_specs['buckets'][slot.bucket]
    assert tuple(spec['moment1'])[0] == eng._rs_axes
    assert tuple(spec['master'])[0] == eng._rs_axes   # bf16 -> fp32 master


def test_asp_2_4_masks():
    from paddle_tpu.incubate import asp
    paddle.seed(0)
    net = nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 4))
    asp.prune_model(net)
    w = net[0].weight
    assert asp.check_sparsity(w)
    # masks survive an optimizer step
    opt = asp.decorate(
        paddle.optimizer.SGD(learning_rate=0.1,
                             parameters=net.parameters()), model=net)
    loss = net(paddle.randn([4, 16])).sum()
    loss.backward()
    opt.step()
    assert asp.check_sparsity(net[0].weight)
