"""Parameter-server track tests (BASELINE config 5 pattern)."""
import os
import time
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.core.native import load_native

pytestmark = pytest.mark.skipif(load_native() is None,
                                reason="native lib unavailable")


def _click_batch(rng, batch=64, slots=8, vocab=100000, dense=13):
    ids = rng.randint(0, vocab, (batch, slots)).astype(np.int64)
    dense_f = rng.rand(batch, dense).astype(np.float32)
    # clickier when feature-hash parity is even — learnable signal
    labels = ((ids.sum(1) + (dense_f.sum(1) * 10).astype(np.int64))
              % 2).astype(np.int64).reshape(batch, 1)
    return ids, dense_f, labels


def test_distributed_embedding_grad_flow():
    from paddle_tpu.distributed.ps.embedding import DistributedEmbedding
    emb = DistributedEmbedding(4, optimizer='sgd', learning_rate=0.5)
    ids = Tensor(np.array([[1, 2], [1, 3]], np.int64))
    out = emb(ids)
    assert out.shape == [2, 2, 4]
    before = emb.table.pull(np.array([1]))[0].copy()
    loss = paddle.sum(out)
    loss.backward()
    after = emb.table.pull(np.array([1]))[0]
    # id 1 appears twice; grad of sum = 1 per element → w -= 0.5*2
    np.testing.assert_allclose(after, before - 1.0, rtol=1e-5)


def test_wide_deep_trains():
    from paddle_tpu.models.wide_deep import WideDeep
    paddle.seed(0)
    rng = np.random.RandomState(0)
    model = WideDeep(sparse_feature_dim=8, num_sparse_slots=8,
                     dense_dim=13, hidden_sizes=(32, 16))
    opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                parameters=model.parameters())
    losses = []
    for step in range(30):
        ids, dense_f, labels = _click_batch(rng)
        logits = model(Tensor(ids), Tensor(dense_f))
        loss = model.loss(logits, Tensor(labels))
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    assert np.mean(losses[-5:]) < np.mean(losses[:5])
    assert len(model.embedding) > 0  # features materialized on demand


def test_async_communicator_flush():
    from paddle_tpu.distributed.ps.embedding import (DistributedEmbedding,
                                                     global_communicator)
    emb = DistributedEmbedding(4, optimizer='sgd', learning_rate=1.0,
                               a_sync=True)
    ids = Tensor(np.arange(32, dtype=np.int64).reshape(8, 4))
    before = emb.table.pull(np.arange(32))
    out = emb(ids)
    paddle.sum(out).backward()
    emb.flush()  # barrier: all async pushes applied
    after = emb.table.pull(np.arange(32))
    np.testing.assert_allclose(after, before - 1.0, rtol=1e-5)
    global_communicator().stop()


def test_ps_service_remote_pull_push():
    """BrpcPsClient/Server pattern: 2 servers, sharded ids, remote grads."""
    from paddle_tpu.distributed.ps.service import PsServer, PsClient
    s1 = PsServer().start()
    s2 = PsServer().start()
    for s in (s1, s2):
        s.add_table(0, dim=8, optimizer='sgd', seed=1)
    client = PsClient([f'127.0.0.1:{s1.port}', f'127.0.0.1:{s2.port}'])
    ids = np.arange(100, dtype=np.int64)
    rows = client.pull(0, ids, 8)
    assert rows.shape == (100, 8)
    # determinism: re-pull matches
    np.testing.assert_allclose(client.pull(0, ids, 8), rows)
    # push grads of ones with lr 0.5 → rows drop by 0.5
    client.push(0, ids, np.ones((100, 8), np.float32), lr=0.5)
    after = client.pull(0, ids, 8)
    np.testing.assert_allclose(after, rows - 0.5, rtol=1e-5)
    assert client.table_size(0) == 100
    client.shutdown()
    client.close()


def test_sparse_adam_accessor():
    """Sparse adam (row layout [w, m, v, t]) converges on a toy pull
    target (parity: the reference sparse-adam accessor)."""
    from paddle_tpu.core.native import NativeSparseTable
    t = NativeSparseTable(4, optimizer='adam', seed=1)
    ids = np.arange(10, dtype=np.int64)
    for _ in range(200):
        w = t.pull(ids)
        t.push(ids, w - 1.0, lr=0.05)
    assert np.abs(t.pull(ids) - 1.0).max() < 0.05


def test_dense_table_remote():
    """Server-side dense table (CommonDenseTable parity): init, pull,
    optimizer-applied push, save/load through the service."""
    import tempfile
    import os
    from paddle_tpu.distributed.ps.service import PsServer, PsClient
    server = PsServer().start()
    server.add_dense_table(3, size=16, optimizer='adam')
    client = PsClient([f'127.0.0.1:{server.port}'])
    client.dense_init(3, np.zeros(16, np.float32))
    for _ in range(100):
        w = client.dense_pull(3)
        client.dense_push(3, w - 2.0, lr=0.1)
    w = client.dense_pull(3)
    assert np.abs(w - 2.0).max() < 0.1, w
    path = os.path.join(tempfile.mkdtemp(), 'dense')
    client.dense_save(3, path)
    assert os.path.exists(path + '.part0')
    client.shutdown()
    client.close()


def test_application_errors_surface_not_retry():
    """Bad path / missing table / dim mismatch raise PsError immediately
    (application error), not a 30s transport-retry storm."""
    import time
    from paddle_tpu.distributed.ps.service import (PsServer, PsClient,
                                                   PsError)
    server = PsServer().start()
    server.add_table(0, dim=4, optimizer='sgd')
    client = PsClient([f'127.0.0.1:{server.port}'], retry_timeout=30)
    ids = np.arange(4, dtype=np.int64)
    t0 = time.time()
    with pytest.raises(PsError):
        client.save(0, '/nonexistent_dir_xyz/snap')
    with pytest.raises(PsError):
        client.pull(7, ids, 4)          # missing table
    with pytest.raises(PsError):
        client.pull(0, ids, 8)          # dim mismatch
    assert time.time() - t0 < 5        # no retry storm
    # connection still healthy afterwards (stream not desynced)
    assert client.pull(0, ids, 4).shape == (4, 4)
    client.shutdown()
    client.close()


def test_geo_push_without_pull():
    from paddle_tpu.distributed.ps.embedding import GeoCommunicator
    from paddle_tpu.core.native import NativeSparseTable
    base = NativeSparseTable(4, optimizer='sgd', seed=3)
    geo = GeoCommunicator(base, 4, k_steps=1)
    ids = np.array([5, 6], np.int64)
    w0 = base.pull(ids).copy()
    geo.push(ids, np.ones((2, 4), np.float32), lr=0.1)   # no prior pull
    np.testing.assert_allclose(base.pull(ids), w0 - 0.1, rtol=1e-5)


def test_kill_one_server_recovers():
    """Fault tolerance (VERDICT r1 #8 'done' criterion): kill a server,
    relaunch it on the same port from its snapshot — the client's
    reconnect-with-retry resumes pulls/pushes transparently."""
    import tempfile
    import os
    from paddle_tpu.distributed.ps.service import PsServer, PsClient
    snap = os.path.join(tempfile.mkdtemp(), 'snap')
    server = PsServer().start()
    port = server.port
    server.add_table(0, dim=4, optimizer='sgd', seed=7)
    client = PsClient([f'127.0.0.1:{port}'], retry_timeout=20)
    ids = np.arange(20, dtype=np.int64)
    rows = client.pull(0, ids, 4)
    client.push(0, ids, np.ones((20, 4), np.float32), lr=0.5)
    client.save(0, snap)

    server.stop()   # "kill" — connections drop

    relaunched = {}

    def relaunch():
        import time as _t
        _t.sleep(1.0)   # client sees the outage first
        s2 = PsServer(port=port).start()
        s2.add_table(0, dim=4, optimizer='sgd', seed=7)
        s2.tables[0].load(snap + '.part0')
        relaunched['server'] = s2
    import threading
    t = threading.Thread(target=relaunch)
    t.start()
    # issues during the outage: retried until the relaunched server is up
    after = client.pull(0, ids, 4)
    t.join()
    np.testing.assert_allclose(after, rows - 0.5, rtol=1e-5)
    client.push(0, ids, np.ones((20, 4), np.float32), lr=0.5)
    np.testing.assert_allclose(client.pull(0, ids, 4), rows - 1.0,
                               rtol=1e-5)
    client.close()
    relaunched['server'].stop()


def test_heartbeat_tracks_liveness():
    import time
    from paddle_tpu.distributed.ps.service import PsServer, PsClient
    server = PsServer().start()
    server.add_table(0, dim=4)
    client = PsClient([f'127.0.0.1:{server.port}'], retry_timeout=5)
    client.start_heartbeat(interval=0.2)
    time.sleep(0.6)
    assert client.alive == [True]
    server.stop()
    time.sleep(1.0)
    assert client.alive == [False]
    client.stop_heartbeat()
    client.close()


def test_geo_mode_converges_and_syncs():
    """Geo-SGD: local mirror trains, deltas land on the base table every
    k steps, wide_deep converges (VERDICT r1 #8 geo criterion)."""
    from paddle_tpu.models.wide_deep import WideDeep
    paddle.seed(0)
    rng = np.random.RandomState(0)
    model = WideDeep(sparse_feature_dim=8, num_sparse_slots=8,
                     dense_dim=13, hidden_sizes=(32, 16), mode='geo',
                     geo_k=5)
    opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                parameters=model.parameters())
    losses = []
    for step in range(30):
        ids, dense_f, labels = _click_batch(rng, vocab=1000)
        logits = model(Tensor(ids), Tensor(dense_f))
        loss = model.loss(logits, Tensor(labels))
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    assert np.mean(losses[-5:]) < np.mean(losses[:5])
    # deltas reached the BASE table (not just the local mirror)
    geo = model.embedding.table
    geo.sync()
    ids = np.array(sorted(geo.base))[:8].astype(np.int64)
    base_rows = geo.remote.pull(ids)
    fresh = geo.local.pull(ids)
    np.testing.assert_allclose(base_rows, fresh, rtol=1e-5, atol=1e-6)
    assert len(geo.remote) > 0


def test_wide_deep_remote_ps():
    """Wide&Deep with REMOTE embedding tables (the full PS deployment
    shape, in-process servers)."""
    from paddle_tpu.distributed.ps.service import PsServer
    from paddle_tpu.distributed.ps.embedding import DistributedEmbedding
    server = PsServer().start()
    server.add_table(0, dim=8, optimizer='adagrad')
    emb = DistributedEmbedding(8, endpoints=[f'127.0.0.1:{server.port}'],
                               table_id=0, learning_rate=0.1)
    ids = Tensor(np.array([[1, 2], [3, 1]], np.int64))
    out = emb(ids)
    assert out.shape == [2, 2, 8]
    paddle.sum(out).backward()
    assert len(emb) == 3
    server.stop()


def test_ssd_table_spill_and_kill_restart():
    """SSD spill tier (VERDICT r2 #6): a table bigger than the RAM budget
    pulls/pushes correctly through spill, and a killed server's table
    recovers from the spill logs alone (parity: ssd_sparse_table.h +
    rocksdb recovery)."""
    import tempfile
    from paddle_tpu.core.native import NativeSsdSparseTable
    d = tempfile.mkdtemp()
    kw = dict(num_shards=4, optimizer='adam', mem_budget_rows=128,
              beta1=0.9, beta2=0.999, eps=1e-8, init_range=0.05, seed=3)
    t = NativeSsdSparseTable(8, d, **kw)
    ids = np.arange(2000, dtype=np.int64)
    rows0 = t.pull(ids)
    assert t.mem_rows() <= 256          # far below 2000 — spill engaged
    assert t.total_rows() == 2000
    t.push(ids, np.ones((2000, 8), np.float32), lr=0.1)
    expected = t.pull(ids)
    assert not np.allclose(expected, rows0)
    t.flush()
    del t                                # "kill" the process's table
    t2 = NativeSsdSparseTable(8, d, **kw)
    t2.recover()
    np.testing.assert_allclose(t2.pull(ids), expected, atol=1e-6)


def test_per_table_accessor_hypers():
    """Adam hypers are per-table accessor config, not constants
    (VERDICT r2 weak #5; parity: ps.proto TableParameter)."""
    from paddle_tpu.core.native import NativeSparseTable
    ids = np.array([7], np.int64)
    g = np.full((1, 4), 0.5, np.float32)

    g2 = np.full((1, 4), -0.25, np.float32)

    def second_step(beta1):
        t = NativeSparseTable(4, optimizer='adam', seed=11, beta1=beta1)
        t.push(ids, g, lr=0.1)    # bias correction hides beta1 at t=1;
        w1 = t.pull(ids).copy()   # a second, different gradient exposes
        t.push(ids, g2, lr=0.1)
        return t.pull(ids) - w1

    d_a = second_step(0.9)
    d_b = second_step(0.0)
    assert not np.allclose(d_a, d_b)
    # beta1=0 at t=2: m = g2 (negative) → step is positive
    assert np.all(d_b > 0)
    # beta1=0.9: m2 = 0.9*0.05 + 0.1*(-0.25) = 0.02 > 0 → step negative
    assert np.all(d_a < 0)


def test_server_table_config_json_env():
    """JSON TableParameter configs through PADDLE_PS_TABLES reach
    add_table (the_one_ps _get_fleet_proto analogue)."""
    import json as _json
    import tempfile
    from paddle_tpu.distributed.ps import ps_runtime
    from paddle_tpu.core.native import NativeSsdSparseTable
    d = tempfile.mkdtemp()
    cfgs = [{'table_id': 0, 'embedx_dim': 8, 'optimizer': 'adam',
             'beta1': 0.8, 'shard_num': 4},
            {'table_id': 1, 'embedx_dim': 4, 'optimizer': 'adagrad',
             'ssd_path': d, 'mem_budget_rows': 64}]
    old = os.environ.get('PADDLE_PS_TABLES')
    ps_runtime.set_table_configs(None)
    os.environ['PADDLE_PS_TABLES'] = _json.dumps(cfgs)
    try:
        from paddle_tpu.distributed.ps.service import PsServer
        srv = PsServer(port=0)
        for cfg in ps_runtime._table_configs():
            c = dict(cfg)
            srv.add_table(c.pop('table_id'), c.pop('embedx_dim'), **c)
        assert srv.tables[0].dim == 8
        assert isinstance(srv.tables[1], NativeSsdSparseTable)
        # bad key rejected
        with pytest.raises(ValueError, match='unknown table config'):
            ps_runtime.set_table_configs([{'table_id': 2,
                                           'embedx_dim': 4,
                                           'bogus': 1}])
    finally:
        ps_runtime.set_table_configs(None)
        if old is None:
            os.environ.pop('PADDLE_PS_TABLES', None)
        else:
            os.environ['PADDLE_PS_TABLES'] = old


def test_ssd_table_snapshot_includes_cold_rows():
    """SaveAll/LoadAll must carry spilled rows — the base Save would
    silently snapshot only the hot set (review r3 finding)."""
    import tempfile
    from paddle_tpu.core.native import NativeSsdSparseTable
    d1, d2 = tempfile.mkdtemp(), tempfile.mkdtemp()
    kw = dict(num_shards=4, optimizer='adagrad', mem_budget_rows=64,
              seed=5)
    t = NativeSsdSparseTable(8, d1, **kw)
    ids = np.arange(1000, dtype=np.int64)
    t.push(ids, np.ones((1000, 8), np.float32), lr=0.05)
    expected = t.pull(ids)
    assert t.mem_rows() < 1000
    snap = os.path.join(d1, 'snap.bin')
    t.save(snap)
    t2 = NativeSsdSparseTable(8, d2, **kw)
    t2.load(snap)
    assert len(t2) == 1000
    assert t2.mem_rows() == 0          # restored straight to the logs
    np.testing.assert_allclose(t2.pull(ids), expected, atol=1e-6)


def test_push_at_most_once_across_server_restart(tmp_path):
    """VERDICT r3 #7: a push applied and made durable just before a crash
    must NOT re-apply when the transparently-reconnecting client replays
    it against the restarted server — the uuid->seq high-water mark is
    persisted to <state_dir>/applied.log with each apply and recovered on
    construction."""
    import threading
    from paddle_tpu.distributed.ps.service import PsServer, PsClient

    state = str(tmp_path)
    ssd = str(tmp_path / 'tbl')
    os.makedirs(ssd, exist_ok=True)
    kw = dict(optimizer='sgd', seed=1, num_shards=2, ssd_path=ssd)
    srv1 = PsServer(state_dir=state).start()
    srv1.add_table(0, dim=4, **kw)
    port = srv1.port
    client = PsClient([f'127.0.0.1:{port}'], retry_timeout=60)
    ids = np.arange(10, dtype=np.int64)
    rows0 = client.pull(0, ids, 4).copy()
    srv1.tables[0].flush()          # row creation durable pre-crash

    srv1._die_after_apply = 1       # apply+persist, then die before ack
    g = np.ones((10, 4), np.float32)
    err = []

    def do_push():
        try:
            client.push(0, ids, g, lr=0.5)   # blocks retrying
        except Exception as e:               # noqa: BLE001
            err.append(e)

    th = threading.Thread(target=do_push)
    th.start()
    deadline = time.time() + 30
    while srv1._running and time.time() < deadline:
        time.sleep(0.05)
    assert not srv1._running        # hook fired: applied, died, no ack

    # restart on the same port + state dir: table recovers from spill
    # logs, dedup map recovers from applied.log
    srv2 = PsServer(port=port, state_dir=state)
    srv2.add_table(0, dim=4, **kw).recover()
    srv2.start()
    th.join(timeout=60)
    assert not th.is_alive() and not err, err

    after = client.pull(0, ids, 4)
    np.testing.assert_allclose(after, rows0 - 0.5, atol=1e-6)  # ONCE
    client.shutdown()
    client.close()


def test_table_parameter_typed_validation():
    """VERDICT r3 weak #7: table configs are typed TableParameter
    analogues — bad keys, optimizers, and hyper ranges fail at
    configuration time, not as garbage tables on the server."""
    from paddle_tpu.distributed.ps.ps_runtime import (TableParameter,
                                                      set_table_configs)
    t = TableParameter.from_dict({'table_id': 0, 'embedx_dim': 8,
                                  'optimizer': 'adam', 'beta1': 0.95})
    assert t.to_dict()['beta1'] == 0.95
    for bad in (
        {'table_id': 0, 'embedx_dim': 8, 'optimzer': 'adam'},   # typo
        {'table_id': 0},                                        # missing
        {'table_id': 0, 'embedx_dim': -4},
        {'table_id': 0, 'embedx_dim': 8, 'optimizer': 'rmsprop'},
        {'table_id': 0, 'embedx_dim': 8, 'beta1': 1.5},
        {'table_id': 0, 'embedx_dim': 8, 'shard_num': 0},
    ):
        with pytest.raises(ValueError):
            TableParameter.from_dict(bad)
    with pytest.raises(ValueError, match='duplicate'):
        set_table_configs([{'table_id': 1, 'embedx_dim': 4},
                           {'table_id': 1, 'embedx_dim': 8}])
    set_table_configs(None)


class TestAsyncCommunicator:
    """reference communicator.h:197 — pull-ahead/push-behind decoupling."""

    def test_pull_ahead_order_and_push_flush(self):
        import threading
        from paddle_tpu.distributed.ps.communicator import (
            AsyncCommunicator)

        calls = {'pull': [], 'push': []}
        gate = threading.Event()

        class FakeClient:
            def pull(self, tid, ids, dim):
                calls['pull'].append(np.array(ids))
                return np.tile(np.asarray(ids, np.float32)[:, None],
                               (1, dim))

            def push(self, tid, ids, grads, lr):
                gate.wait(5)                 # prove push never blocks
                calls['push'].append((np.array(ids), np.array(grads)))

        comm = AsyncCommunicator(FakeClient(), 0, 4, depth=2)
        batches = [np.arange(i, i + 3, dtype=np.int64)
                   for i in range(5)]
        out = list(comm.pull_ahead(batches))
        assert len(out) == 5
        for (ids, rows), want in zip(out, batches):
            np.testing.assert_array_equal(ids, want)
            np.testing.assert_allclose(rows[:, 0],
                                       want.astype(np.float32))
        # pushes queue without blocking while the wire is stuck
        t0 = time.time()
        comm.push_async(batches[0], np.ones((3, 4), np.float32), 0.1)
        comm.push_async(batches[1], np.ones((3, 4), np.float32), 0.1)
        assert time.time() - t0 < 1.0
        assert not calls['push']
        gate.set()
        comm.flush()                         # barrier drains the queue
        assert len(calls['push']) == 2
        comm.stop()

    def test_push_error_surfaces(self):
        from paddle_tpu.distributed.ps.communicator import (
            AsyncCommunicator)

        class BadClient:
            def pull(self, tid, ids, dim):
                return np.zeros((len(ids), dim), np.float32)

            def push(self, tid, ids, grads, lr):
                raise ConnectionError("wire down")

        comm = AsyncCommunicator(BadClient(), 0, 4, depth=2)
        comm.push_async(np.arange(2, dtype=np.int64),
                        np.ones((2, 4), np.float32), 0.1)
        with pytest.raises(ConnectionError, match='wire down'):
            comm.flush()
        # a queued push error must not wedge shutdown: stop() re-raises
        # AFTER releasing the worker threads
        comm.push_async(np.arange(2, dtype=np.int64),
                        np.ones((2, 4), np.float32), 0.1)
        with pytest.raises(ConnectionError, match='wire down'):
            comm.stop()
        assert not comm._push_thread.is_alive()

    def test_abandoned_pull_iterator_releases_producer(self):
        from paddle_tpu.distributed.ps.communicator import (
            AsyncCommunicator)

        class SlowClient:
            def pull(self, tid, ids, dim):
                return np.zeros((len(ids), dim), np.float32)

            def push(self, tid, ids, grads, lr):
                pass

        comm = AsyncCommunicator(SlowClient(), 0, 4, depth=1)
        batches = [np.arange(3, dtype=np.int64)] * 50
        it = comm.pull_ahead(batches)
        next(it)                      # consume one, abandon the rest
        it.close()                    # GeneratorExit -> cancel_pull
        t0 = time.time()
        while comm._pull_thread is not None and time.time() - t0 < 5:
            time.sleep(0.01)
        assert comm._pull_thread is None
        # the communicator is reusable after cancellation
        out = list(comm.pull_ahead([np.arange(2, dtype=np.int64)]))
        assert len(out) == 1
        comm.stop()

    def test_stale_iterator_close_spares_newer_pull(self):
        from paddle_tpu.distributed.ps.communicator import (
            AsyncCommunicator)

        class SlowClient:
            def pull(self, tid, ids, dim):
                return np.zeros((len(ids), dim), np.float32)

            def push(self, tid, ids, grads, lr):
                pass

        comm = AsyncCommunicator(SlowClient(), 0, 4, depth=1)
        it1 = comm.pull_ahead([np.arange(3, dtype=np.int64)] * 20)
        next(it1)
        comm.cancel_pull()            # explicit cancel of generation 1
        it2 = comm.pull_ahead([np.arange(2, dtype=np.int64)] * 5)
        it1.close()                   # stale gen-1 finalizer fires late
        out = list(it2)               # gen 2 must complete, not hang
        assert len(out) == 5
        comm.stop()

    def test_stop_cancels_inflight_pull(self):
        from paddle_tpu.distributed.ps.communicator import (
            AsyncCommunicator)

        class SlowClient:
            def pull(self, tid, ids, dim):
                time.sleep(0.01)
                return np.zeros((len(ids), dim), np.float32)

            def push(self, tid, ids, grads, lr):
                pass

        comm = AsyncCommunicator(SlowClient(), 0, 4, depth=1)
        comm.pull_ahead([np.arange(3, dtype=np.int64)] * 200)
        t0 = time.time()
        comm.stop()                   # must not hang on the full queue
        assert time.time() - t0 < 5
        assert comm._pull_thread is None
