"""Disaggregated serving cluster (ISSUE 11): prefix-affinity router
placement, dp replicas (in-process + true subprocess workers),
prefill→decode page streaming, mp-sharded engine equivalence, and the
forced-hang replica drain path."""
import json
import os
import time

import numpy as np
import pytest

os.environ.setdefault('JAX_PLATFORMS', 'cpu')

import paddle_tpu as paddle
from paddle_tpu.serving import ServingConfig, ServingEngine
from paddle_tpu.serving.kv_pool import (KVPagePool, chain_hash,
                                        chain_hashes)
from paddle_tpu.serving.cluster import (ClusterRouter, LocalReplica,
                                        RemoteReplica, RouterRejected)
from paddle_tpu.serving.cluster.disagg import DisaggregatedEngine

MODEL_KW = dict(vocab_size=128, hidden_size=32, num_layers=2,
                num_heads=4, max_seq_len=128, hidden_dropout=0.0,
                attn_dropout=0.0, use_flash_attention=False)
ENGINE_KW = dict(page_size=8, max_batch_size=3, prefill_chunk=16)


@pytest.fixture(scope='module')
def tiny_lm():
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM
    paddle.seed(0)
    model = GPTForCausalLM(GPTConfig(**MODEL_KW))
    model.eval()
    return model


@pytest.fixture()
def mixed_prompts():
    rng = np.random.RandomState(1)
    fam = [list(rng.randint(1, 128, 24)) for _ in range(2)]
    order = [0, 1, 0, 1, 1, 0, 0, 1, 0, 1]
    return [fam[f] + list(rng.randint(1, 128,
                                      int(rng.randint(2, 10))))
            for f in order]


def _single_reference(model, prompts, max_new=8, **kw):
    eng = ServingEngine(model, ServingConfig(**{**ENGINE_KW, **kw}))
    out = eng.generate(prompts, max_new_tokens=max_new, top_k=0)
    eng.shutdown()
    return out


# ---------------------------------------------------------------------------
# chain hashes: the router and the pool must derive the SAME digest
# ---------------------------------------------------------------------------
class TestChainHashes:
    def test_pool_digest_matches_router_hashes(self):
        pool = KVPagePool(8, 4, prefix_cache=True)
        toks = list(range(100, 114))            # 3 full pages + tail
        pool.ensure_capacity(7, len(toks))
        pool.register_prefix(7, toks, len(toks))
        assert set(pool.prefix_chain_hashes()) == \
            set(chain_hashes(toks, 4))
        # the chain identifies the WHOLE prefix: same block behind a
        # different parent hashes differently
        other = [1, 2, 3, 4] + toks[4:8]
        assert chain_hashes(other, 4)[1] != chain_hashes(toks, 4)[1]

    def test_chain_hash_is_stable(self):
        # cross-process stability: fixed bytes, not Python hash()
        assert chain_hash(-1, (1, 2, 3, 4)) == \
            chain_hash(-1, (1, 2, 3, 4))
        assert chain_hashes([5, 6, 7, 8, 9], 4, limit=4) == \
            chain_hashes([5, 6, 7, 8, 1000], 4, limit=4)

    def test_limit_caps_full_blocks(self):
        toks = list(range(16))
        assert len(chain_hashes(toks, 4)) == 4
        assert len(chain_hashes(toks, 4, limit=15)) == 3
        assert chain_hashes(toks, 4, limit=3) == []


# ---------------------------------------------------------------------------
# router placement units over a fake status feed (no engines)
# ---------------------------------------------------------------------------
class FakeReplica:
    def __init__(self, rid, digest=(), waiting=0, in_flight=0,
                 occupancy=0.0, hung=False, beat_age=0.0):
        self.replica_id = rid
        self.feed = {'replica_id': rid, 'beat_age_s': beat_age,
                     'hung': hung, 'hang_reason': None,
                     'draining': False, 'waiting': waiting,
                     'in_flight': in_flight, 'pending_tokens': 0,
                     'decode_tokens_per_sec': 0.0,
                     'timeline': {'mean_occupancy': occupancy},
                     'pool': {}, 'prefix_digest': list(digest)}
        self.submitted = []
        self._next = 0

    def submit(self, prompt, opts, route_meta=None):
        self.submitted.append((list(prompt), dict(opts),
                               dict(route_meta or {})))
        self._next += 1
        return f'{self.replica_id}-{self._next}'

    def status(self):
        return dict(self.feed)

    def poll(self):
        return {}

    def pump(self):
        return False

    def drain(self):
        return []

    def shutdown(self):
        pass


class TestRouterPlacement:
    def _router(self, replicas, **kw):
        kw.setdefault('page_size', 4)
        kw.setdefault('max_queue', 4)
        return ClusterRouter(replicas, **kw)

    def test_affinity_beats_least_loaded(self):
        prompt = list(range(1, 13))
        hot = FakeReplica('hot', digest=chain_hashes(prompt, 4),
                          waiting=3)          # busier, but has pages
        cold = FakeReplica('cold', waiting=0)
        router = self._router([hot, cold])
        req = router.submit(prompt, max_new_tokens=4)
        assert req.replica_id == 'hot' and req.decision == 'affinity'
        assert hot.submitted[0][2]['router_decision'] == 'affinity'

    def test_deepest_prefix_wins(self):
        prompt = list(range(1, 17))
        h = chain_hashes(prompt, 4)
        shallow = FakeReplica('shallow', digest=h[:1])
        deep = FakeReplica('deep', digest=h[:3])
        router = self._router([shallow, deep])
        assert router.submit(prompt).replica_id == 'deep'

    def test_least_loaded_fallback_uses_timeline(self):
        # equal queue depth: the fake timeline feed breaks the tie
        busy = FakeReplica('busy', waiting=1, occupancy=0.9)
        idle = FakeReplica('idle', waiting=1, occupancy=0.1)
        router = self._router([busy, idle])
        req = router.submit(list(range(1, 9)))
        assert req.replica_id == 'idle'
        assert req.decision == 'least_loaded'

    def test_optimistic_digest_routes_burst_together(self):
        a = FakeReplica('a')
        b = FakeReplica('b')
        router = self._router([a, b], refresh_interval_s=3600.0)
        prompt = list(range(1, 13))
        first = router.submit(prompt + [77])
        second = router.submit(prompt + [88])     # before any refresh
        assert second.replica_id == first.replica_id
        assert second.decision == 'affinity'

    def test_published_digest_replaces_stale_entries(self):
        # a replica that LRU-evicted its cached chains publishes a
        # smaller digest — the router must stop routing 'affinity'
        # there once the optimistic overlay ages out, not keep a
        # forever-union of everything it ever saw
        prompt = list(range(1, 13))
        a = FakeReplica('a', digest=chain_hashes(prompt, 4))
        b = FakeReplica('b')
        router = self._router([a, b], refresh_interval_s=0.0)
        assert router.submit(prompt + [50]).replica_id == 'a'
        a.feed['prefix_digest'] = []        # pool evicted everything
        for _ in range(router.OPTIMISTIC_GENERATIONS + 2):
            router.refresh()
        req = router.submit(prompt + [60])
        assert req.decision == 'least_loaded', (req.decision,
                                                req.replica_id)

    def test_backpressure_spills_affinity(self):
        prompt = list(range(1, 13))
        hot = FakeReplica('hot', digest=chain_hashes(prompt, 4),
                          waiting=9)
        cold = FakeReplica('cold')
        router = self._router([hot, cold], max_queue=4)
        req = router.submit(prompt)
        assert req.replica_id == 'cold' and req.decision == 'spill'

    def test_spill_prefers_partial_affinity_among_open(self):
        # saturated full-prefix target: the spill should land on the
        # open replica holding PART of the prefix, not the marginally
        # less-loaded one with none of it
        prompt = list(range(1, 17))
        h = chain_hashes(prompt, 4)
        hot = FakeReplica('hot', digest=h, waiting=9)
        warm = FakeReplica('warm', digest=h[:2], waiting=2)
        cold = FakeReplica('cold', waiting=1)
        router = self._router([hot, warm, cold], max_queue=4)
        req = router.submit(prompt)
        assert req.replica_id == 'warm' and req.decision == 'spill'

    def test_reject_early_when_all_saturated(self):
        reps = [FakeReplica(r, waiting=9) for r in ('a', 'b')]
        router = self._router(reps, max_queue=4)
        with pytest.raises(RouterRejected, match='backpressure'):
            router.submit(list(range(1, 9)))
        assert router.snapshot()['rejects'] == 1

    def test_deadline_bound_rejects_slow_queue(self):
        slow = FakeReplica('slow')
        slow.feed['decode_tokens_per_sec'] = 10.0
        slow.feed['pending_tokens'] = 1000     # 100s of queue
        router = self._router([slow], deadline_bound_s=5.0)
        router.refresh()
        with pytest.raises(RouterRejected):
            router.submit(list(range(1, 9)))

    def test_hung_flag_set_on_stale_heartbeat(self):
        a = FakeReplica('a', beat_age=99.0)
        b = FakeReplica('b')
        router = self._router([a, b], hang_timeout_s=2.0)
        router.refresh()
        snap = router.snapshot()
        assert snap['replicas']['a']['hung'], snap
        assert snap['replicas']['a']['drained'], snap
        assert not snap['replicas']['b']['hung'], snap

    def test_stale_heartbeat_drains_and_resubmits(self):
        prompt = list(range(1, 13))
        a = FakeReplica('a', digest=chain_hashes(prompt, 4))
        b = FakeReplica('b')
        router = self._router([a, b], hang_timeout_s=2.0,
                              refresh_interval_s=0.0)
        req = router.submit(prompt, max_new_tokens=8)
        assert req.replica_id == 'a'
        a.feed['beat_age_s'] = 9.9              # wedged step loop
        router.refresh()
        snap = router.snapshot()
        assert snap['replicas']['a']['drained'], snap
        assert snap['placements']['drain'] == 1
        assert snap['placements']['resubmit'] == 1
        # resubmitted to the healthy peer, budget preserved
        assert req.replica_id == 'b' and req.resubmits == 1
        assert b.submitted[-1][1]['max_new_tokens'] == 8
        assert len(snap['drain_events']) == 1

    def test_drain_resubmit_bypasses_backpressure(self):
        # drained work is not new admission: even with the only peer
        # over max_queue, in-flight requests must land there rather
        # than strand (and the reject counter must not count it)
        a = FakeReplica('a')
        b = FakeReplica('b', waiting=9)
        router = self._router([a, b], max_queue=4)
        req = router.submit(list(range(1, 9)), max_new_tokens=8)
        assert req.replica_id == 'a'
        router.drain('a', reason='test')
        assert req.replica_id == 'b' and req.resubmits == 1
        snap = router.snapshot()
        assert snap['rejects'] == 0, snap
        assert snap['placements']['resubmit'] == 1

    def test_drain_survives_peer_dispatch_failure(self):
        # a transient channel error on the resubmission target must
        # not strand the request or escape the drain — pump() retries
        prompt = list(range(1, 13))
        a = FakeReplica('a', digest=chain_hashes(prompt, 4))
        flaky = FakeReplica('b')
        orig = flaky.submit
        calls = {'n': 0}

        def flaky_submit(p, opts, route_meta=None):
            calls['n'] += 1
            if calls['n'] == 1:
                raise OSError('channel hiccup')
            return orig(p, opts, route_meta)

        flaky.submit = flaky_submit
        router = self._router([a, flaky])
        req = router.submit(prompt, max_new_tokens=8)
        assert req.replica_id == 'a'
        router.drain('a', reason='test')        # dispatch fails once
        assert req.replica_id == 'a'            # parked, not crashed
        router.pump()                           # retry succeeds
        assert req.replica_id == 'b'
        assert not router._unplaced

    def test_drained_replica_not_placed(self):
        a, b = FakeReplica('a'), FakeReplica('b')
        router = self._router([a, b])
        router.drain('a', reason='operator')
        for _ in range(3):
            assert router.submit(list(range(1, 9))).replica_id == 'b'


# ---------------------------------------------------------------------------
# control channel: timeout desync protection
# ---------------------------------------------------------------------------
class TestControlChannel:
    def test_timeout_drops_connection_no_stale_replies(self):
        from paddle_tpu.serving.cluster.channel import (ControlClient,
                                                        ControlServer)

        def handler(msg):
            if msg.get('op') == 'slow':
                time.sleep(1.0)
                return {'which': 'slow'}
            return {'which': 'fast'}

        server = ControlServer(handler).start()
        try:
            client = ControlClient('127.0.0.1', server.port,
                                   timeout=5.0)
            import socket as _socket
            with pytest.raises((_socket.timeout, OSError)):
                client.call({'op': 'slow'}, timeout=0.2)
            # the late 'slow' reply must NOT surface as this reply —
            # the client reconnected after the timeout
            for _ in range(3):
                assert client.call({'op': 'fast'},
                                   timeout=5.0) == {'which': 'fast'}
            client.close()
        finally:
            server.close()


# ---------------------------------------------------------------------------
# schema v2: route events
# ---------------------------------------------------------------------------
class TestTraceSchemaV2:
    def test_route_event_reconstructs(self, tmp_path):
        from paddle_tpu.serving.request_trace import (RequestTracer,
                                                      SCHEMA,
                                                      load_trace,
                                                      reconstruct)
        tr = RequestTracer()
        tr.record(3, 'submit', t=1.0, prompt_tokens=4)
        tr.record(3, 'route', t=1.1, replica_id='r1',
                  router_decision='affinity')
        tr.record(3, 'retire', t=2.0, tokens_generated=2)
        p = str(tmp_path / 't.jsonl')
        tr.export_jsonl(p)
        header, events = load_trace(p)
        assert header['schema'] == SCHEMA == 'paddle_tpu.serve_trace/6'
        r = reconstruct(events)[3]
        assert r['replica_id'] == 'r1'
        assert r['router_decision'] == 'affinity'

    def test_load_trace_accepts_v1_rejects_unknown(self, tmp_path):
        from paddle_tpu.serving.request_trace import load_trace
        v1 = tmp_path / 'v1.jsonl'
        v1.write_text(
            json.dumps({'schema': 'paddle_tpu.serve_trace/1'}) + '\n'
            + json.dumps({'req': 0, 'event': 'submit', 't': 1.0})
            + '\n')
        header, events = load_trace(str(v1))
        assert header['schema'].endswith('/1') and len(events) == 1
        v9 = tmp_path / 'v9.jsonl'
        v9.write_text(
            json.dumps({'schema': 'paddle_tpu.serve_trace/9'}) + '\n')
        with pytest.raises(ValueError, match='unsupported serve'):
            load_trace(str(v9))


# ---------------------------------------------------------------------------
# in-process 2-replica cluster over real engines
# ---------------------------------------------------------------------------
def _round_robin_affinity_hits(prompts, n_replicas, page_size):
    """How many requests pure round-robin placement would land on a
    replica already holding their prefix chain — the baseline the
    router must beat."""
    digests = [set() for _ in range(n_replicas)]
    hits = 0
    for i, p in enumerate(prompts):
        h = chain_hashes(p, page_size, limit=len(p) - 1)
        r = i % n_replicas
        if h and h[0] in digests[r]:
            hits += 1
        digests[r].update(h)
    return hits


class TestLocalCluster:
    def test_shared_prefix_identical_outputs_and_affinity(
            self, tiny_lm, mixed_prompts):
        ref = _single_reference(tiny_lm, mixed_prompts)
        reps = [LocalReplica(
            ServingEngine(tiny_lm, ServingConfig(**ENGINE_KW)), rid)
            for rid in ('r0', 'r1')]
        router = ClusterRouter(reps, page_size=ENGINE_KW['page_size'],
                               max_queue=32)
        outs = router.serve(mixed_prompts, max_new_tokens=8, top_k=0)
        assert outs == ref
        snap = router.snapshot()
        hits = snap['placements']['affinity']
        rr = _round_robin_affinity_hits(mixed_prompts, 2,
                                        ENGINE_KW['page_size'])
        assert hits > rr, (hits, rr, snap['placements'])
        # both prefix families actually split across the replicas
        routed = [v['requests_routed']
                  for v in snap['replicas'].values()]
        assert all(n > 0 for n in routed), snap
        # route events landed in the per-replica journals (schema v2)
        table = reps[0].engine.request_table()
        assert any(r.get('router_decision') for r in table.values())
        router.shutdown()

    def test_serve_throttles_instead_of_stranding(self, tiny_lm,
                                                  mixed_prompts):
        # tight backpressure bound: serve() must pump-and-retry on
        # RouterRejected rather than raise mid-batch and orphan the
        # already-placed requests
        ref = _single_reference(tiny_lm, mixed_prompts)
        reps = [LocalReplica(
            ServingEngine(tiny_lm, ServingConfig(**ENGINE_KW)), rid)
            for rid in ('r0', 'r1')]
        router = ClusterRouter(reps, page_size=ENGINE_KW['page_size'],
                               max_queue=2)
        outs = router.serve(mixed_prompts, max_new_tokens=8, top_k=0,
                            timeout_s=120)
        assert outs == ref
        router.shutdown()

    def test_drain_midstream_completes_on_peer(self, tiny_lm,
                                               mixed_prompts):
        ref = _single_reference(tiny_lm, mixed_prompts, max_new=12)
        reps = [LocalReplica(
            ServingEngine(tiny_lm, ServingConfig(**ENGINE_KW)), rid)
            for rid in ('r0', 'r1')]
        router = ClusterRouter(reps, page_size=ENGINE_KW['page_size'],
                               max_queue=32)
        reqs = [router.submit(p, max_new_tokens=12, top_k=0)
                for p in mixed_prompts]
        for _ in range(6):              # partial progress
            router.pump()
        drained = reqs[0].replica_id
        router.drain(drained, reason='test drain')
        router.run(timeout_s=120)
        assert [r.output_ids() for r in reqs] == ref
        snap = router.snapshot()
        assert snap['placements']['drain'] == 1
        assert snap['replicas'][str(drained)]['drained']
        router.shutdown()


# ---------------------------------------------------------------------------
# prefill→decode disaggregation
# ---------------------------------------------------------------------------
class TestDisaggregation:
    @pytest.mark.parametrize('kv_dtype', [None, 'int8'])
    def test_streamed_pages_bit_identical(self, tiny_lm, kv_dtype):
        rng = np.random.RandomState(3)
        prompt = list(rng.randint(1, 128, 29))
        ref = ServingEngine(tiny_lm, ServingConfig(
            **{**ENGINE_KW, 'kv_dtype': kv_dtype}))
        req_r = ref.submit(prompt, max_new_tokens=4)
        from paddle_tpu.serving.scheduler import RequestState
        while req_r.state != RequestState.RUNNING:
            ref.step()
        ref_pages = ref.pool.page_table(req_r.id)

        d = DisaggregatedEngine(tiny_lm, ServingConfig(
            **{**ENGINE_KW, 'kv_dtype': kv_dtype,
               'disaggregate': True, 'stream_chunk_pages': 2}))
        req_d = d.submit(prompt, max_new_tokens=4)
        while req_d.state != RequestState.RUNNING:
            d.step()
        dst_pages = d.decode.pool.page_table(req_d.id)
        assert len(dst_pages) == len(ref_pages)
        # full prompt pages must be byte-equal after the stream —
        # int8 pools compare quantized payload AND scale siblings
        n_full = len(prompt) // ENGINE_KW['page_size']
        for lr, ld in zip(ref.pool.kv, d.decode.pool.kv):
            for br, bd in zip(lr, ld):
                for pr, pd_ in zip(ref_pages[:n_full],
                                   dst_pages[:n_full]):
                    np.testing.assert_array_equal(
                        np.asarray(br[pr]), np.asarray(bd[pd_]))
        st = d.stats()
        assert st['pd_handoffs_total'] == 1
        assert st['pd_streamed_pages_total'] >= n_full
        ref.shutdown()
        d.shutdown()

    def test_serving_engine_refuses_disaggregate_config(self,
                                                        tiny_lm):
        with pytest.raises(ValueError, match='disaggregate'):
            ServingEngine(tiny_lm, ServingConfig(
                **{**ENGINE_KW, 'disaggregate': True}))

    def test_disagg_outputs_identical(self, tiny_lm, mixed_prompts):
        ref = _single_reference(tiny_lm, mixed_prompts)
        d = DisaggregatedEngine(tiny_lm, ServingConfig(
            **{**ENGINE_KW, 'disaggregate': True}))
        outs = d.generate(mixed_prompts, max_new_tokens=8, top_k=0)
        assert outs == ref
        st = d.stats()
        assert st['pd_handoffs_total'] == len(mixed_prompts)
        d.shutdown()

    def test_decode_side_prefix_sharing_skips_streaming(
            self, tiny_lm, mixed_prompts):
        d = DisaggregatedEngine(tiny_lm, ServingConfig(
            **{**ENGINE_KW, 'disaggregate': True}))
        d.generate(mixed_prompts, max_new_tokens=4, top_k=0)
        st = d.stats()
        ps = ENGINE_KW['page_size']
        full_pages = sum(len(p) // ps for p in mixed_prompts)
        # shared system-prompt pages resurrect decode-side instead of
        # re-streaming — strictly fewer pages moved than exist
        assert st['pd_streamed_pages_total'] < full_pages, st
        d.shutdown()

    def test_cluster_of_disaggregated_replicas(self, tiny_lm,
                                               mixed_prompts):
        from paddle_tpu.serving.cluster.disagg import build_engine
        ref = _single_reference(tiny_lm, mixed_prompts)
        reps = [LocalReplica(build_engine(tiny_lm, ServingConfig(
            **{**ENGINE_KW, 'disaggregate': True})), rid)
            for rid in ('d0', 'd1')]
        router = ClusterRouter(reps, page_size=ENGINE_KW['page_size'],
                               max_queue=32)
        outs = router.serve(mixed_prompts, max_new_tokens=8, top_k=0)
        assert outs == ref
        assert router.snapshot()['placements']['affinity'] > 0
        router.shutdown()


# ---------------------------------------------------------------------------
# mp-sharded engine: heads + KV pages split over an 'mp' mesh axis
# ---------------------------------------------------------------------------
class TestMpSharding:
    def test_mp2_token_identical(self, tiny_lm, mixed_prompts):
        import paddle_tpu.distributed.fleet as fleet_mod
        from paddle_tpu.distributed import topology_runtime
        from paddle_tpu.distributed.fleet.base.topology import (
            CommunicateTopology, HybridCommunicateGroup)
        from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM
        os.environ.setdefault('PADDLE_TRAINER_ID', '0')
        ref = _single_reference(tiny_lm, mixed_prompts[:4])
        topo = CommunicateTopology(
            ["data", "pipe", "sharding", "model"], [1, 1, 1, 2])
        fleet_mod.fleet._topology = topo
        fleet_mod.fleet._hcg = HybridCommunicateGroup(topo)
        try:
            mesh = topology_runtime.build_mesh(['mp'], [2])
            paddle.seed(0)          # same init stream as tiny_lm
            mp_model = GPTForCausalLM(GPTConfig(**MODEL_KW))
            mp_model.eval()
            eng = ServingEngine(mp_model,
                                ServingConfig(**ENGINE_KW), mesh=mesh)
            # the pool spans GLOBAL heads, sharded over the mesh
            assert eng.pool.num_heads == MODEL_KW['num_heads']
            outs = eng.generate(mixed_prompts[:4], max_new_tokens=8,
                                top_k=0)
            assert outs == ref
            eng.shutdown()
        finally:
            fleet_mod.fleet._hcg = None
            fleet_mod.fleet._topology = None

    def test_mesh_degree_mismatch_raises(self, tiny_lm):
        from paddle_tpu.distributed import topology_runtime
        mesh = topology_runtime.build_mesh(['mp'], [2])
        with pytest.raises(ValueError, match='mp degree'):
            ServingEngine(tiny_lm, ServingConfig(**ENGINE_KW),
                          mesh=mesh)


# ---------------------------------------------------------------------------
# true 2-replica subprocess cluster: identity, affinity, forced-hang
# drain (watchdog fires -> router drains -> requests finish on peer)
# ---------------------------------------------------------------------------
class TestSubprocessCluster:
    def test_subprocess_cluster_end_to_end(self, tiny_lm,
                                           mixed_prompts, tmp_path):
        ref = _single_reference(tiny_lm, mixed_prompts)
        reps = []
        try:
            reps = [RemoteReplica.spawn(
                rid, MODEL_KW, ENGINE_KW, seed=0, hang_timeout_s=2.0,
                env={'PTPU_SERVE_REPORT_DIR': str(tmp_path)})
                for rid in ('w0', 'w1')]
            router = ClusterRouter(reps,
                                   page_size=ENGINE_KW['page_size'],
                                   max_queue=32, hang_timeout_s=5.0)
            outs = router.serve(mixed_prompts, max_new_tokens=8,
                                top_k=0, timeout_s=180)
            assert outs == ref
            snap = router.snapshot()
            rr = _round_robin_affinity_hits(
                mixed_prompts, 2, ENGINE_KW['page_size'])
            assert snap['placements']['affinity'] > rr, snap

            # forced hang: wedge one worker's step loop mid-stream;
            # its watchdog dumps, the router drains, every in-flight
            # request completes on the peer — token-identically
            rng = np.random.RandomState(9)
            fam = mixed_prompts[0][:24]
            long_prompts = [fam + list(rng.randint(1, 128, 4))
                            for _ in range(4)]
            ref2 = _single_reference(tiny_lm, long_prompts,
                                     max_new=16)
            reqs = [router.submit(p, max_new_tokens=16, top_k=0)
                    for p in long_prompts]
            hung = router._replicas[reqs[0].replica_id]
            hung.inject_hang()
            router.run(timeout_s=180)
            assert [r.output_ids() for r in reqs] == ref2
            snap = router.snapshot()
            assert snap['placements']['drain'] >= 1, snap
            assert any(e['resubmitted'] > 0
                       for e in snap['drain_events']), snap
            # the worker-side watchdog wrote its diagnosis artifact
            deadline = time.time() + 10
            report = None
            while time.time() < deadline and report is None:
                cands = list(tmp_path.glob('replica_hang.*.json'))
                report = cands[0] if cands else None
                time.sleep(0.2)
            assert report is not None, list(tmp_path.iterdir())
            doc = json.loads(report.read_text())
            assert doc['kind'] == 'replica_hang_report'
            assert 'stacks' in doc and 'flight_recorder' in doc, \
                sorted(doc)
            router.shutdown()
        finally:
            for r in reps:
                try:
                    r.shutdown()
                except Exception:           # noqa: BLE001
                    pass
