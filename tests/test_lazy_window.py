"""Lazy op-fusion window (VERDICT r3 weak #6: eager per-op dispatch is
RTT-bound on the tunneled chip; the window batches N eager ops into one
XLA dispatch — the core.ops.* fast-path analogue)."""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle
from paddle_tpu.core import lazy


def test_fuses_to_single_dispatch_and_matches_eager():
    x = paddle.to_tensor(np.arange(12, dtype='float32').reshape(3, 4))
    w = paddle.to_tensor(np.ones((4, 2), 'float32'))

    # eager reference
    ref = paddle.nn.functional.relu(
        paddle.matmul(x, w) + 1.0) * 2.0

    calls = {'n': 0}
    orig_jit = jax.jit

    def counting_jit(fn, *a, **k):
        wrapped = orig_jit(fn, *a, **k)

        def run(*args, **kw):
            calls['n'] += 1
            return wrapped(*args, **kw)
        return run

    lazy._COMPILE_CACHE.clear()
    jax.jit = counting_jit
    try:
        with paddle.lazy_guard():
            y = paddle.matmul(x, w)
            y = y + 1.0
            y = paddle.nn.functional.relu(y)
            y = y * 2.0
            # nothing executed yet: placeholder data
            assert getattr(y, '_lazy', False)
        out = np.asarray(y.data)
    finally:
        jax.jit = orig_jit
    np.testing.assert_allclose(out, np.asarray(ref.data), rtol=1e-6)
    assert calls['n'] == 1          # the whole window = ONE dispatch


def test_materialization_inside_window():
    with paddle.lazy_guard():
        a = paddle.to_tensor(np.ones((2, 2), 'float32'))
        b = a + 3.0
        v = float(b.sum())          # triggers a flush mid-window
        assert v == 16.0
        c = b * 2.0                 # window continues recording
    np.testing.assert_allclose(np.asarray(c.data), np.full((2, 2), 8.0))


def test_structural_cache_reuses_compile():
    lazy._COMPILE_CACHE.clear()

    def run(scale):
        with paddle.lazy_guard():
            t = paddle.to_tensor(np.full((2, 3), scale, 'float32'))
            u = (t * 2.0) + 1.0
        return np.asarray(u.data)

    np.testing.assert_allclose(run(1.0), np.full((2, 3), 3.0))
    n_after_first = len(lazy._COMPILE_CACHE)
    np.testing.assert_allclose(run(5.0), np.full((2, 3), 11.0))
    assert len(lazy._COMPILE_CACHE) == n_after_first   # same program


def test_window_is_no_grad():
    x = paddle.to_tensor(np.ones((2,), 'float32'))
    x.stop_gradient = False
    with paddle.lazy_guard():
        y = x * 2.0
    assert y.stop_gradient            # no tape inside the window


def test_defaults_distinguish_cache_entries():
    """Ops baking attributes as default args must NOT share a compiled
    program across different attribute values."""
    from paddle_tpu.ops import contrib as C
    lazy._COMPILE_CACHE.clear()
    ids = paddle.to_tensor(np.arange(8, dtype='int64'))
    with paddle.lazy_guard():
        a = C.hash_op(ids, num_hash=2, mod_by=97)
    a_np = np.asarray(a.data)
    with paddle.lazy_guard():
        b = C.hash_op(ids, num_hash=2, mod_by=13)
    b_np = np.asarray(b.data)
    assert (a_np < 97).all() and (b_np < 13).all()
    assert not np.array_equal(a_np, b_np)


def test_bool_inside_window_materializes():
    with paddle.lazy_guard():
        x = paddle.to_tensor(np.array([-1.0, -2.0], 'float32'))
        cond = (x.sum() > 0)
        assert bool(cond) is False     # flushes; no placeholder truthiness
