"""Pallas flash-attention kernels vs the dense reference (interpret mode on
the CPU test mesh — same kernel bodies that lower on TPU).

Reference parity: the numerics tests the reference keeps for its fused
attention ops (test_fused_attention_op.py pattern: fused vs composed-ops
oracle, forward and grads).
"""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

from paddle_tpu.ops.pallas import flash_attention as fa


def _rand(shape, seed):
    return np.random.RandomState(seed).randn(*shape).astype('float32')


def _padding_bias(B, L, valid_lens):
    bias = np.zeros((B, L), 'float32')
    for i, n in enumerate(valid_lens):
        bias[i, n:] = -1e9
    return bias


class TestFlashKernels:
    def test_causal_matches_reference(self):
        bh, L, d = 4, 256, 16
        q, k, v = (_rand((bh, L, d), s) for s in (0, 1, 2))
        o = fa.flash_attention(jnp.asarray(q), jnp.asarray(k),
                               jnp.asarray(v), causal=True)
        ref = fa._reference_attention(jnp.asarray(q), jnp.asarray(k),
                                      jnp.asarray(v), causal=True)
        np.testing.assert_allclose(np.asarray(o), np.asarray(ref),
                                   rtol=2e-4, atol=2e-5)

    def test_noncausal_matches_reference(self):
        bh, L, d = 4, 256, 16
        q, k, v = (_rand((bh, L, d), s) for s in (3, 4, 5))
        o = fa.flash_attention(jnp.asarray(q), jnp.asarray(k),
                               jnp.asarray(v), num_heads=2, causal=False)
        ref = fa._reference_attention(jnp.asarray(q), jnp.asarray(k),
                                      jnp.asarray(v), causal=False)
        np.testing.assert_allclose(np.asarray(o), np.asarray(ref),
                                   rtol=2e-4, atol=2e-5)

    def test_key_padding_bias_matches_reference(self):
        B, nh, L, d = 2, 2, 256, 16
        bh = B * nh
        q, k, v = (_rand((bh, L, d), s) for s in (6, 7, 8))
        bias = jnp.asarray(_padding_bias(B, L, [200, 64]))
        o = fa.flash_attention(jnp.asarray(q), jnp.asarray(k),
                               jnp.asarray(v), bias=bias, num_heads=nh,
                               causal=False)
        ref = fa._reference_attention(jnp.asarray(q), jnp.asarray(k),
                                      jnp.asarray(v), bias=bias,
                                      num_heads=nh, causal=False)
        np.testing.assert_allclose(np.asarray(o), np.asarray(ref),
                                   rtol=2e-4, atol=2e-5)
        # padded keys must not leak into any query row of their batch
        o_np = np.asarray(o).reshape(B, nh, L, d)
        v2 = np.array(v)
        v2.reshape(B, nh, L, d)[1, :, 64:] += 100.0  # mutate masked keys
        o2 = fa.flash_attention(jnp.asarray(q), jnp.asarray(k),
                                jnp.asarray(v2), bias=bias, num_heads=nh,
                                causal=False)
        np.testing.assert_allclose(np.asarray(o2).reshape(B, nh, L, d),
                                   o_np, rtol=2e-4, atol=2e-5)

    @pytest.mark.parametrize('causal', [True, False])
    def test_grads_match_reference(self, causal):
        B, nh, L, d = 2, 2, 256, 8
        bh = B * nh
        q, k, v = (jnp.asarray(_rand((bh, L, d), s)) for s in (9, 10, 11))
        bias = jnp.asarray(_padding_bias(B, L, [256, 128]))

        def loss_flash(q_, k_, v_):
            o = fa.flash_attention(q_, k_, v_, bias=bias, num_heads=nh,
                                   causal=causal)
            return jnp.sum(o * o)

        def loss_ref(q_, k_, v_):
            o = fa._reference_attention(q_, k_, v_, bias=bias, num_heads=nh,
                                        causal=causal)
            return jnp.sum(o * o)

        gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gf, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=5e-4, atol=5e-4)


class TestMHAFlashRouting:
    def _models(self, seed=0):
        import paddle_tpu as paddle
        paddle.seed(seed)
        mha = paddle.nn.MultiHeadAttention(32, 2, dropout=0.0)
        return paddle, mha

    def test_mha_flash_matches_dense(self):
        import paddle_tpu as paddle
        from paddle_tpu.core import flags
        from paddle_tpu.core.tensor import Tensor
        from paddle_tpu.nn.layer import transformer as T
        paddle, mha = self._models()
        # L=1024: above _try_flash's threshold so the flash route (incl.
        # the mask reduction) actually executes
        x = Tensor(jnp.asarray(_rand((2, 1024, 32), 12)))
        # additive [B, 1, 1, L] padding mask (the BertModel form)
        m = np.zeros((2, 1, 1, 1024), 'float32')
        m[0, :, :, 800:] = -1e9
        mask = Tensor(jnp.asarray(m))
        assert T._as_key_bias(mask) is not None
        flags.set_flags({'FLAGS_use_flash_attention': True})
        out_flash = mha(x, x, x, attn_mask=mask)
        flags.set_flags({'FLAGS_use_flash_attention': False})
        out_dense = mha(x, x, x, attn_mask=mask)
        flags.set_flags({'FLAGS_use_flash_attention': True})
        np.testing.assert_allclose(np.asarray(out_flash.data),
                                   np.asarray(out_dense.data),
                                   rtol=2e-4, atol=2e-4)

    def test_mha_flash_grads_match_dense(self):
        import paddle_tpu as paddle
        from paddle_tpu.core import flags
        from paddle_tpu.core.tensor import Tensor

        def run(use_flash):
            flags.set_flags({'FLAGS_use_flash_attention': use_flash})
            paddle, mha = self._models(seed=7)
            x = Tensor(jnp.asarray(_rand((2, 1024, 32), 13)))
            x.stop_gradient = False
            out = mha(x, x, x)
            loss = paddle.sum(out * out)
            loss.backward()
            grads = {n: np.asarray(p.grad.data)
                     for n, p in mha.named_parameters()}
            flags.set_flags({'FLAGS_use_flash_attention': True})
            return np.asarray(loss.data), grads

        l_f, g_f = run(True)
        l_d, g_d = run(False)
        np.testing.assert_allclose(l_f, l_d, rtol=1e-4)
        for n in g_d:
            np.testing.assert_allclose(g_f[n], g_d[n], rtol=5e-4,
                                       atol=5e-4, err_msg=n)

    def test_dense_fallback_for_full_mask(self):
        """[B, 1, L, L] and 2-D [L, L] masks are not key-padding biases —
        they must take the dense path (2-D masks are [L_q, L_k] per paddle
        broadcast semantics, NOT per-batch key biases)."""
        import paddle_tpu as paddle
        from paddle_tpu.core.tensor import Tensor
        from paddle_tpu.nn.layer import transformer as T
        paddle, mha = self._models()
        L = 1024
        full = np.triu(np.full((L, L), -1e9, 'float32'), 1)[None, None]
        assert T._as_key_bias(Tensor(jnp.asarray(full))) is None
        assert T._as_key_bias(Tensor(jnp.asarray(full[0]))) is None  # 3-D
        assert T._as_key_bias(Tensor(jnp.asarray(full[0, 0]))) is None  # 2-D
        # causal 2-D mask through the full layer: flash routing must not
        # swallow it (it would silently drop causality — regression test)
        x = Tensor(jnp.asarray(_rand((1, L, 32), 14)))
        out = mha(x, x, x, attn_mask=Tensor(jnp.asarray(full[0, 0])))
        from paddle_tpu.core import flags
        flags.set_flags({'FLAGS_use_flash_attention': False})
        ref = mha(x, x, x, attn_mask=Tensor(jnp.asarray(full[0, 0])))
        flags.set_flags({'FLAGS_use_flash_attention': True})
        np.testing.assert_allclose(np.asarray(out.data),
                                   np.asarray(ref.data), rtol=2e-4,
                                   atol=2e-4)


class TestBlockFitting:
    def test_non_divisible_length_correct(self):
        """L=768 (divisible by 256, not 512): the block must shrink to a
        divisor — a clamped last slice would silently misalign the causal
        mask (review r3)."""
        bh, L, d = 2, 768, 16
        q, k, v = (jnp.asarray(_rand((bh, L, d), s)) for s in (20, 21, 22))
        o = fa.flash_attention(q, k, v, causal=True)
        ref = fa._reference_attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(o), np.asarray(ref),
                                   rtol=2e-4, atol=2e-5)

    def test_fit_block(self):
        assert fa._fit_block(512, 2048) == 512
        assert fa._fit_block(512, 768) == 256
        assert fa._fit_block(512, 100) == 25 or fa._fit_block(512, 100) in (4, 25, 100)
        assert 100 % fa._fit_block(512, 100) == 0
        assert fa._fit_block(512, 7) == 7    # prime: single block
