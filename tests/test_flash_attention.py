"""Pallas flash-attention kernels vs the dense reference (interpret mode on
the CPU test mesh — same kernel bodies that lower on TPU).

Reference parity: the numerics tests the reference keeps for its fused
attention ops (test_fused_attention_op.py pattern: fused vs composed-ops
oracle, forward and grads).
"""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

from paddle_tpu.ops.pallas import flash_attention as fa


def _rand(shape, seed):
    return np.random.RandomState(seed).randn(*shape).astype('float32')


def _padding_bias(B, L, valid_lens):
    bias = np.zeros((B, L), 'float32')
    for i, n in enumerate(valid_lens):
        bias[i, n:] = -1e9
    return bias


class TestFlashKernels:
    def test_causal_matches_reference(self):
        bh, L, d = 4, 256, 16
        q, k, v = (_rand((bh, L, d), s) for s in (0, 1, 2))
        o = fa.flash_attention(jnp.asarray(q), jnp.asarray(k),
                               jnp.asarray(v), causal=True)
        ref = fa._reference_attention(jnp.asarray(q), jnp.asarray(k),
                                      jnp.asarray(v), causal=True)
        np.testing.assert_allclose(np.asarray(o), np.asarray(ref),
                                   rtol=2e-4, atol=2e-5)

    def test_noncausal_matches_reference(self):
        bh, L, d = 4, 256, 16
        q, k, v = (_rand((bh, L, d), s) for s in (3, 4, 5))
        o = fa.flash_attention(jnp.asarray(q), jnp.asarray(k),
                               jnp.asarray(v), num_heads=2, causal=False)
        ref = fa._reference_attention(jnp.asarray(q), jnp.asarray(k),
                                      jnp.asarray(v), causal=False)
        np.testing.assert_allclose(np.asarray(o), np.asarray(ref),
                                   rtol=2e-4, atol=2e-5)

    def test_key_padding_bias_matches_reference(self):
        B, nh, L, d = 2, 2, 256, 16
        bh = B * nh
        q, k, v = (_rand((bh, L, d), s) for s in (6, 7, 8))
        bias = jnp.asarray(_padding_bias(B, L, [200, 64]))
        o = fa.flash_attention(jnp.asarray(q), jnp.asarray(k),
                               jnp.asarray(v), bias=bias, num_heads=nh,
                               causal=False)
        ref = fa._reference_attention(jnp.asarray(q), jnp.asarray(k),
                                      jnp.asarray(v), bias=bias,
                                      num_heads=nh, causal=False)
        np.testing.assert_allclose(np.asarray(o), np.asarray(ref),
                                   rtol=2e-4, atol=2e-5)
        # padded keys must not leak into any query row of their batch
        o_np = np.asarray(o).reshape(B, nh, L, d)
        v2 = np.array(v)
        v2.reshape(B, nh, L, d)[1, :, 64:] += 100.0  # mutate masked keys
        o2 = fa.flash_attention(jnp.asarray(q), jnp.asarray(k),
                                jnp.asarray(v2), bias=bias, num_heads=nh,
                                causal=False)
        np.testing.assert_allclose(np.asarray(o2).reshape(B, nh, L, d),
                                   o_np, rtol=2e-4, atol=2e-5)

    @pytest.mark.parametrize('causal', [True, False])
    def test_grads_match_reference(self, causal):
        B, nh, L, d = 2, 2, 256, 8
        bh = B * nh
        q, k, v = (jnp.asarray(_rand((bh, L, d), s)) for s in (9, 10, 11))
        bias = jnp.asarray(_padding_bias(B, L, [256, 128]))

        def loss_flash(q_, k_, v_):
            o = fa.flash_attention(q_, k_, v_, bias=bias, num_heads=nh,
                                   causal=causal)
            return jnp.sum(o * o)

        def loss_ref(q_, k_, v_):
            o = fa._reference_attention(q_, k_, v_, bias=bias, num_heads=nh,
                                        causal=causal)
            return jnp.sum(o * o)

        gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gf, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=5e-4, atol=5e-4)


class TestMHAFlashRouting:
    def _models(self, seed=0):
        import paddle_tpu as paddle
        paddle.seed(seed)
        mha = paddle.nn.MultiHeadAttention(32, 2, dropout=0.0)
        return paddle, mha

    def test_mha_flash_matches_dense(self):
        import paddle_tpu as paddle
        from paddle_tpu.core import flags
        from paddle_tpu.core.tensor import Tensor
        from paddle_tpu.nn.layer import transformer as T
        paddle, mha = self._models()
        # L=1024: above _try_flash's threshold so the flash route (incl.
        # the mask reduction) actually executes
        x = Tensor(jnp.asarray(_rand((2, 1024, 32), 12)))
        # additive [B, 1, 1, L] padding mask (the BertModel form)
        m = np.zeros((2, 1, 1, 1024), 'float32')
        m[0, :, :, 800:] = -1e9
        mask = Tensor(jnp.asarray(m))
        assert T._as_key_bias(mask) is not None
        flags.set_flags({'FLAGS_use_flash_attention': True})
        out_flash = mha(x, x, x, attn_mask=mask)
        flags.set_flags({'FLAGS_use_flash_attention': False})
        out_dense = mha(x, x, x, attn_mask=mask)
        flags.set_flags({'FLAGS_use_flash_attention': True})
        np.testing.assert_allclose(np.asarray(out_flash.data),
                                   np.asarray(out_dense.data),
                                   rtol=2e-4, atol=2e-4)

    def test_mha_flash_grads_match_dense(self):
        import paddle_tpu as paddle
        from paddle_tpu.core import flags
        from paddle_tpu.core.tensor import Tensor

        def run(use_flash):
            flags.set_flags({'FLAGS_use_flash_attention': use_flash})
            paddle, mha = self._models(seed=7)
            x = Tensor(jnp.asarray(_rand((2, 1024, 32), 13)))
            x.stop_gradient = False
            out = mha(x, x, x)
            loss = paddle.sum(out * out)
            loss.backward()
            grads = {n: np.asarray(p.grad.data)
                     for n, p in mha.named_parameters()}
            flags.set_flags({'FLAGS_use_flash_attention': True})
            return np.asarray(loss.data), grads

        l_f, g_f = run(True)
        l_d, g_d = run(False)
        np.testing.assert_allclose(l_f, l_d, rtol=1e-4)
        for n in g_d:
            np.testing.assert_allclose(g_f[n], g_d[n], rtol=5e-4,
                                       atol=5e-4, err_msg=n)

    def test_dense_fallback_for_full_mask(self):
        """[B, 1, L, L] and 2-D [L, L] masks are not key-padding biases —
        they must take the dense path (2-D masks are [L_q, L_k] per paddle
        broadcast semantics, NOT per-batch key biases)."""
        import paddle_tpu as paddle
        from paddle_tpu.core.tensor import Tensor
        from paddle_tpu.nn.layer import transformer as T
        paddle, mha = self._models()
        L = 1024
        full = np.triu(np.full((L, L), -1e9, 'float32'), 1)[None, None]
        assert T._as_key_bias(Tensor(jnp.asarray(full))) is None
        assert T._as_key_bias(Tensor(jnp.asarray(full[0]))) is None  # 3-D
        assert T._as_key_bias(Tensor(jnp.asarray(full[0, 0]))) is None  # 2-D
        # causal 2-D mask through the full layer: flash routing must not
        # swallow it (it would silently drop causality — regression test)
        x = Tensor(jnp.asarray(_rand((1, L, 32), 14)))
        out = mha(x, x, x, attn_mask=Tensor(jnp.asarray(full[0, 0])))
        from paddle_tpu.core import flags
        flags.set_flags({'FLAGS_use_flash_attention': False})
        ref = mha(x, x, x, attn_mask=Tensor(jnp.asarray(full[0, 0])))
        flags.set_flags({'FLAGS_use_flash_attention': True})
        np.testing.assert_allclose(np.asarray(out.data),
                                   np.asarray(ref.data), rtol=2e-4,
                                   atol=2e-4)


class TestBlockFitting:
    def test_non_divisible_length_correct(self):
        """L=768 (divisible by 256, not 512): the block must shrink to a
        divisor — a clamped last slice would silently misalign the causal
        mask (review r3)."""
        bh, L, d = 2, 768, 16
        q, k, v = (jnp.asarray(_rand((bh, L, d), s)) for s in (20, 21, 22))
        o = fa.flash_attention(q, k, v, causal=True)
        ref = fa._reference_attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(o), np.asarray(ref),
                                   rtol=2e-4, atol=2e-5)

    def test_fit_block(self):
        assert fa._fit_block(512, 2048) == 512
        assert fa._fit_block(512, 768) == 256
        assert fa._fit_block(512, 100) == 25 or fa._fit_block(512, 100) in (4, 25, 100)
        assert 100 % fa._fit_block(512, 100) == 0
        assert fa._fit_block(512, 7) == 7    # prime: single block


class TestPackedFlash:
    """Transpose-free packed layout ([B, L, H*D]; the BERT-path kernels —
    one program per (batch, q-block) runs every head over static column
    slices, so the [B, nh, L, hd] physical transpose never exists)."""

    def _qkv(self, B=1, L=256, H=2, D=64, seed=0):
        rng = np.random.RandomState(seed)
        mk = lambda s: jnp.asarray(rng.randn(B, L, H, D), jnp.float32)
        return mk(0), mk(1), mk(2), jnp.asarray(
            np.where(rng.rand(B, L) > 0.25, 0, -1e9), jnp.float32)

    def test_packed_matches_reference_with_bias(self):
        from paddle_tpu.ops.pallas.flash_attention import (
            flash_attention_packed, _reference_attention)
        B, L, H, D = 1, 256, 2, 64
        q, k, v, bias = self._qkv(B, L, H, D)
        o = flash_attention_packed(q.reshape(B, L, H * D),
                                   k.reshape(B, L, H * D),
                                   v.reshape(B, L, H * D), H, D,
                                   bias=bias)
        to = lambda x: x.transpose(0, 2, 1, 3).reshape(B * H, L, D)
        ref = _reference_attention(to(q), to(k), to(v), bias=bias,
                                   num_heads=H, causal=False)
        ref = ref.reshape(B, H, L, D).transpose(0, 2, 1, 3) \
            .reshape(B, L, H * D)
        np.testing.assert_allclose(np.asarray(o), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_packed_grads_match_reference(self):
        from paddle_tpu.ops.pallas.flash_attention import (
            flash_attention_packed, _reference_attention)
        B, L, H, D = 1, 256, 2, 64
        q, k, v, bias = self._qkv(B, L, H, D, seed=3)

        def loss_p(q, k, v):
            return jnp.sum(flash_attention_packed(
                q.reshape(B, L, H * D), k.reshape(B, L, H * D),
                v.reshape(B, L, H * D), H, D, bias=bias) ** 2)

        def loss_r(q, k, v):
            to = lambda x: x.transpose(0, 2, 1, 3).reshape(B * H, L, D)
            return jnp.sum(_reference_attention(
                to(q), to(k), to(v), bias=bias, num_heads=H,
                causal=False) ** 2)

        g1 = jax.grad(loss_p, argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(loss_r, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=5e-4, atol=5e-5)

    def test_mha_blhd_route_matches_dense(self):
        """MultiHeadAttention's transpose-free flash route == the dense
        path, values AND grads (FLAGS_flash_min_seq lowered to force the
        route at a test-sized L)."""
        import paddle_tpu as paddle
        from paddle_tpu.core import flags
        from paddle_tpu.core.tensor import Tensor
        paddle.seed(0)
        mha = paddle.nn.MultiHeadAttention(128, 2, dropout=0.0)
        x_np = np.random.RandomState(5).randn(2, 256, 128) \
            .astype('float32') * 0.3
        mask = np.zeros((2, 1, 1, 256), 'float32')
        mask[1, :, :, 200:] = -1e9

        def run():
            x = Tensor(jnp.asarray(x_np))
            x.stop_gradient = False
            out = mha(x, attn_mask=Tensor(jnp.asarray(mask)))
            out.sum().backward()
            g = np.asarray(x.grad.data)
            for p in mha.parameters():
                p.clear_grad() if hasattr(p, 'clear_grad') else None
            return np.asarray(out.data), g

        old = flags.flag('FLAGS_flash_min_seq')
        try:
            flags.set_flags({'FLAGS_flash_min_seq': 4096})
            dense_out, dense_g = run()
            flags.set_flags({'FLAGS_flash_min_seq': 256})
            flash_out, flash_g = run()
        finally:
            flags.set_flags({'FLAGS_flash_min_seq': old})
        np.testing.assert_allclose(flash_out, dense_out, rtol=2e-4,
                                   atol=2e-5)
        np.testing.assert_allclose(flash_g, dense_g, rtol=5e-4,
                                   atol=5e-5)

    def test_packed_causal_matches_reference(self):
        from paddle_tpu.ops.pallas.flash_attention import (
            flash_attention_packed, _reference_attention)
        B, L, H, D = 1, 256, 2, 64
        rng = np.random.RandomState(7)
        q = jnp.asarray(rng.randn(B, L, H, D), jnp.float32)
        k = jnp.asarray(rng.randn(B, L, H, D), jnp.float32)
        v = jnp.asarray(rng.randn(B, L, H, D), jnp.float32)
        o = flash_attention_packed(q.reshape(B, L, H * D),
                                   k.reshape(B, L, H * D),
                                   v.reshape(B, L, H * D), H, D,
                                   causal=True)
        to = lambda x: x.transpose(0, 2, 1, 3).reshape(B * H, L, D)
        ref = _reference_attention(to(q), to(k), to(v), causal=True)
        ref = ref.reshape(B, H, L, D).transpose(0, 2, 1, 3) \
            .reshape(B, L, H * D)
        np.testing.assert_allclose(np.asarray(o), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_causal_attention_entry_packed_vs_bhld(self):
        """The GPT qkv entry gives identical results through the packed
        (default) and BHLD routes."""
        from paddle_tpu.ops.pallas import flash_attention as FA
        from paddle_tpu.core import flags
        from paddle_tpu.core.tensor import Tensor
        B, L, H, D = 1, 256, 2, 64
        rng = np.random.RandomState(9)
        qkv = Tensor(jnp.asarray(rng.randn(B, L, H * 3 * D) * 0.3,
                                 jnp.float32))
        old = flags.flag('FLAGS_flash_packed_causal')
        try:
            flags.set_flags({'FLAGS_flash_packed_causal': True})
            a = np.asarray(FA.causal_attention(qkv, H, D).data)
            flags.set_flags({'FLAGS_flash_packed_causal': False})
            b = np.asarray(FA.causal_attention(qkv, H, D).data)
        finally:
            flags.set_flags({'FLAGS_flash_packed_causal': old})
        np.testing.assert_allclose(a, b, rtol=2e-5, atol=2e-5)
