"""dy2static: break/continue lowering + convert_call + live globals.

Reference parity: break_continue_transformer.py (flag-variable lowering),
convert_call_func.py (recursive callee conversion), and the
eager-vs-converted comparison pattern of the dygraph_to_static tests.
"""
import numpy as np
import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.jit.dy2static import convert_function


def _t(v, dtype='float32'):
    return Tensor(jnp.asarray(v, dtype))


def _run_both(fn, *args):
    """eager result vs converted-under-jit result."""
    eager = fn(*[_t(a) if isinstance(a, (np.ndarray, float, int))
                 else a for a in args])
    conv = convert_function(fn)

    def jitted(*arrs):
        out = conv(*[Tensor(a) for a in arrs])
        return out.data if isinstance(out, Tensor) else out
    traced = jax.jit(jitted)(*[jnp.asarray(a) for a in args])
    return np.asarray(eager.data), np.asarray(traced)


class TestBreakContinue:
    def test_break_in_while_tensor_cond(self):
        def f(x, n):
            i = _t(0, 'int32')
            acc = x * 0.0
            while i < n:
                acc = acc + x
                i = i + 1
                if paddle.sum(acc) > 2.5:
                    break
            return acc

        e, t = _run_both(f, np.array([1.0, 0.5], 'float32'),
                         np.asarray(10, 'int32'))
        np.testing.assert_allclose(e, t, rtol=1e-6)
        np.testing.assert_allclose(e, [2.0, 1.0])  # stops after 2 iters

    def test_continue_in_for_range(self):
        def f(x):
            acc = x * 0.0
            for i in range(6):
                if i == 2:          # python condition: python continue
                    continue
                acc = acc + x
            return acc

        e, t = _run_both(f, np.array([2.0], 'float32'))
        np.testing.assert_allclose(e, t)
        np.testing.assert_allclose(e, [10.0])      # 5 of 6 iterations

    def test_tensor_continue_in_for_range(self):
        def f(x):
            acc = x * 0.0
            for i in range(5):
                step = acc + x
                if paddle.sum(step) > 3.5:   # tensor condition
                    continue
                acc = step
            return acc

        e, t = _run_both(f, np.array([1.0], 'float32'))
        np.testing.assert_allclose(e, t)
        np.testing.assert_allclose(e, [3.0])   # grows 1,2,3 then skips

    def test_break_then_statements_skipped(self):
        def f(x, n):
            total = x * 0.0
            extra = x * 0.0
            i = _t(0, 'int32')
            while i < n:
                i = i + 1
                if paddle.sum(total) > 1.5:
                    break
                total = total + x
                extra = extra + 2.0 * x     # must not run after break
            return total + extra

        e, t = _run_both(f, np.array([1.0], 'float32'),
                         np.asarray(10, 'int32'))
        np.testing.assert_allclose(e, t)

    def test_nested_loop_break_binds_inner(self):
        def f(x):
            acc = x * 0.0
            for i in range(3):
                for j in range(4):
                    if paddle.sum(acc) > 4.5:
                        break
                    acc = acc + x
            return acc

        e, t = _run_both(f, np.array([1.0], 'float32'))
        np.testing.assert_allclose(e, t)
        np.testing.assert_allclose(e, [5.0])


class TestConvertCall:
    def test_callee_with_tensor_if_converts(self):
        def helper(v):
            if paddle.sum(v) > 0:
                return v * 2.0
            return v - 1.0

        def f(x):
            return helper(x) + helper(-x)

        e, t = _run_both(f, np.array([1.0, 2.0], 'float32'))
        np.testing.assert_allclose(e, t)
        np.testing.assert_allclose(e, [0.0, 1.0])

    def test_callee_with_loop_converts(self):
        def repeat_add(v, n):
            out = v * 0.0
            i = _t(0, 'int32')
            while i < n:
                out = out + v
                i = i + 1
            return out

        def f(x, n):
            return repeat_add(x, n) * 0.5

        e, t = _run_both(f, np.array([2.0], 'float32'),
                         np.asarray(3, 'int32'))
        np.testing.assert_allclose(e, t)
        np.testing.assert_allclose(e, [3.0])

    def test_library_calls_pass_through(self):
        def f(x):
            y = paddle.sum(x)          # framework call: not converted
            z = np.float32(2.0)        # numpy call: not converted
            return x * z + y

        e, t = _run_both(f, np.array([1.0, 3.0], 'float32'))
        np.testing.assert_allclose(e, t)

    def test_method_callee_converts(self):
        class Helper:
            def scale_if_positive(self, v):
                if paddle.sum(v) > 0:
                    return v * 3.0
                return v

        h = Helper()

        def f(x):
            return h.scale_if_positive(x)

        e, t = _run_both(f, np.array([1.0], 'float32'))
        np.testing.assert_allclose(e, t)
        np.testing.assert_allclose(e, [3.0])


_GLOBAL_SCALE = 2.0


def _scaled(x):
    return x * _GLOBAL_SCALE


class TestLiveGlobals:
    def test_global_rebinding_visible(self):
        """ADVICE r2 low #4: converted functions see LIVE module globals,
        matching eager semantics."""
        global _GLOBAL_SCALE

        def f(x):
            if paddle.sum(x) > 0:      # force conversion
                return _scaled(x)
            return x

        conv = convert_function(f)
        _GLOBAL_SCALE = 2.0
        r1 = conv(_t([1.0]))
        _GLOBAL_SCALE = 5.0
        try:
            r2 = conv(_t([1.0]))
        finally:
            _GLOBAL_SCALE = 2.0
        np.testing.assert_allclose(np.asarray(r1.data), [2.0])
        np.testing.assert_allclose(np.asarray(r2.data), [5.0])


class TestReviewRegressions:
    def test_python_range_loop_stays_differentiable(self):
        """Python-condition loops unroll (differentiable); the traced-
        state lax routing applies only to loops with lowered jumps."""
        def f(x):
            for i in range(3):
                x = x + x * 0.5
            return paddle.sum(x)

        conv = convert_function(f)

        def loss(a):
            out = conv(Tensor(a))
            return out.data.reshape(())
        g = jax.grad(loss)(jnp.asarray([1.0, 2.0]))
        np.testing.assert_allclose(np.asarray(g), [1.5 ** 3] * 2,
                                   rtol=1e-6)

    def test_and_keeps_value_semantics(self):
        """`flag and t` must return t's VALUES, not a bool cast."""
        def f(x):
            flag = True
            y = flag and x * 3.0
            return y

        conv = convert_function(f)
        out = conv(_t([2.0]))
        np.testing.assert_allclose(np.asarray(out.data), [6.0])
        assert np.asarray(out.data).dtype == np.float32

    def test_break_under_with_keeps_function_convertible(self):
        """break inside `with` can't lower to flags — that LOOP stays
        Python, but other constructs in the same function still
        convert."""
        import contextlib

        def f(x, use_double):
            total = 0.0
            for i in range(5):
                with contextlib.nullcontext():
                    if i >= 2:       # python condition
                        break
                total = total + 1.0
            if paddle.sum(x) > 0:    # tensor condition must still convert
                x = x * 2.0
            return x + total

        conv = convert_function(f)

        def jitted(a):
            return conv(Tensor(a), True).data
        out = jax.jit(jitted)(jnp.asarray([1.0]))
        np.testing.assert_allclose(np.asarray(out), [4.0])  # 2*1 + 2

    def test_user_module_prefix_not_swallowed(self):
        from paddle_tpu.jit.dy2static import convert_call

        def helper(v):
            return v
        helper.__module__ = 'mathutils'     # starts with 'math'
        assert convert_call(helper) is not helper or True
        # exact stdlib module still passes through
        import math as _m
        assert convert_call(_m.sqrt) is _m.sqrt

    def test_convert_call_caches_plain_functions(self):
        from paddle_tpu.jit import dy2static as d

        def helper(v):
            if paddle.sum(v) > 0:
                return v * 2.0
            return v

        c1 = d.convert_call(helper)
        c2 = d.convert_call(helper)
        assert c1 is c2
        assert c1 is not helper
