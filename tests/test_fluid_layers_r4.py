"""fluid.layers remainder wrappers (static/fluid_layers.py) — every name
executes with real values and matches its documented semantics
(reference: python/paddle/fluid/layers __all__ sheet)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.static import fluid_layers as fl
from paddle_tpu.static import nn as snn


def test_rank_is_empty_reverse():
    x = Tensor(np.ones((2, 3, 4), np.float32))
    assert int(fl.rank(x).data) == 3
    assert not bool(fl.is_empty(x).data)
    assert bool(fl.is_empty(Tensor(np.ones((0, 3), np.float32))).data)
    r = np.asarray(fl.reverse(Tensor(np.arange(6).reshape(2, 3)), 1).data)
    np.testing.assert_array_equal(r, [[2, 1, 0], [5, 4, 3]])


def test_pad2d_and_pad_constant_like():
    x = Tensor(np.ones((1, 1, 2, 2), np.float32))
    out = np.asarray(fl.pad2d(x, [1, 1, 2, 2], pad_value=5.0).data)
    assert out.shape == (1, 1, 4, 6)
    assert out[0, 0, 0, 0] == 5.0 and out[0, 0, 1, 2] == 1.0
    big = Tensor(np.zeros((2, 4), np.float32))
    small = Tensor(np.ones((1, 2), np.float32))
    out = np.asarray(fl.pad_constant_like(big, small, -1.0).data)
    assert out.shape == (2, 4)
    assert out[0, 0] == 1.0 and out[1, 3] == -1.0


def test_adaptive_pools_and_pool3d():
    rng = np.random.RandomState(0)
    x = Tensor(rng.rand(2, 3, 8, 8).astype(np.float32))
    assert fl.adaptive_pool2d(x, 4, 'avg').shape == [2, 3, 4, 4]
    assert fl.adaptive_pool2d(x, 2, 'max').shape == [2, 3, 2, 2]
    x3 = Tensor(rng.rand(1, 2, 4, 8, 8).astype(np.float32))
    o = fl.adaptive_pool3d(x3, 2, 'avg')
    assert o.shape == [1, 2, 2, 2, 2]
    om = fl.adaptive_pool3d(x3, 2, 'max')
    # max pool >= avg pool everywhere
    assert (np.asarray(om.data) >= np.asarray(o.data) - 1e-6).all()
    p3 = fl.pool3d(x3, pool_size=2, pool_type='max', pool_stride=2)
    assert p3.shape == [1, 2, 2, 4, 4]
    g = fl.pool3d(x3, global_pooling=True, pool_type='avg')
    np.testing.assert_allclose(
        np.asarray(g.data).reshape(1, 2),
        np.asarray(x3.data).mean(axis=(2, 3, 4)), rtol=1e-5)


def test_lrn_matches_fluid_formula():
    rng = np.random.RandomState(1)
    x = rng.rand(1, 7, 3, 3).astype(np.float32)
    out = np.asarray(fl.lrn(Tensor(x), n=5, k=1.0, alpha=1e-4,
                            beta=0.75).data)
    # fluid formula: x / (k + alpha * sum_window x^2)^beta
    want = np.zeros_like(x)
    C = 7
    for c in range(C):
        lo, hi = max(0, c - 2), min(C, c + 3)
        sq = (x[:, lo:hi] ** 2).sum(axis=1)
        want[:, c] = x[:, c] / (1.0 + 1e-4 * sq) ** 0.75
    np.testing.assert_allclose(out, want, rtol=1e-4)


def test_ctc_greedy_decoder():
    # logits argmax path: [T=4, steps] -> collapse repeats, drop blank 0
    probs = np.zeros((1, 5, 4), np.float32)
    ids = [2, 2, 0, 3]
    for t, i in enumerate(ids):
        probs[0, t, i] = 5.0
    probs[0, 4, 0] = 5.0
    out, lens = fl.ctc_greedy_decoder(Tensor(probs), blank=0,
                                      padding_value=-1)
    o = np.asarray(out.data)[0]
    assert o[0] == 2 and o[1] == 3
    assert int(np.asarray(lens.data).reshape(-1)[0]) == 2


def test_unique_with_counts():
    x = Tensor(np.array([2, 3, 3, 1, 5, 3], np.int64))
    u, idx, cnt = fl.unique_with_counts(x)
    uv = np.asarray(u.data)
    cv = np.asarray(cnt.data)
    assert set(uv.tolist()) == {1, 2, 3, 5}
    assert cv[uv.tolist().index(3)] == 3


def test_batch_size_like_randoms():
    ref = Tensor(np.zeros((7, 3), np.float32))
    u = fl.uniform_random_batch_size_like(ref, [0, 4], min=0.0, max=1.0)
    assert u.shape[0] == 7 and u.shape[1] == 4
    g = fl.gaussian_random_batch_size_like(ref, [0, 5], mean=0.0,
                                           std=1.0)
    assert g.shape == [7, 5]


def test_grid_sampler_and_warpctc_alias():
    rng = np.random.RandomState(2)
    x = Tensor(rng.rand(1, 1, 4, 4).astype(np.float32))
    grid = Tensor((rng.rand(1, 3, 3, 2).astype(np.float32) - 0.5) * 2)
    assert fl.grid_sampler(x, grid).shape == [1, 1, 3, 3]
    logits = Tensor(rng.randn(6, 2, 5).astype(np.float32))
    labels = Tensor(np.array([[1, 2, 3], [2, 3, 4]], np.int32))
    ll = Tensor(np.array([6, 6], np.int64))
    tl = Tensor(np.array([3, 3], np.int64))
    loss = fl.warpctc(logits, labels, blank=0, input_length=ll,
                      label_length=tl)
    assert np.isfinite(np.asarray(loss.data)).all()


def test_similarity_focus_mask():
    rng = np.random.RandomState(3)
    x = rng.rand(2, 4, 3, 5).astype(np.float32)
    out = np.asarray(fl.similarity_focus(Tensor(x), axis=1,
                                         indexes=[0, 2]).data)
    assert out.shape == x.shape
    assert set(np.unique(out)) <= {0.0, 1.0}
    # every row and column of the selected maps contributes >= 1 hit
    assert out[0, 0].sum() >= max(3, 5)


def test_lr_decay_bridges():
    s = fl.noam_decay(512, 4000, learning_rate=2.0)
    assert hasattr(s, 'get_lr') or hasattr(s, '__call__')
    e = fl.exponential_decay(0.1, 10, 0.5, staircase=True)
    for _ in range(10):
        e.step()
    assert abs(e() - 0.05) < 1e-8
    p = fl.piecewise_decay([5, 10], [1.0, 0.5, 0.1])
    for _ in range(6):
        p.step()
    assert abs(p() - 0.5) < 1e-8
    c = fl.cosine_decay(0.1, step_each_epoch=10, epochs=4)
    assert c() <= 0.1
    w = fl.linear_lr_warmup(0.1, 5, 0.0, 0.1)
    assert w() <= 0.1
    inv = fl.inverse_time_decay(1.0, 1, 1.0)
    inv.step()
    assert abs(inv() - 0.5) < 1e-8
    n = fl.natural_exp_decay(1.0, 1, 1.0)
    n.step()
    assert abs(n() - float(np.exp(-1))) < 1e-6


def test_static_names_resolve_and_record():
    paddle.enable_static()
    try:
        from paddle_tpu import static
        main, start = static.Program(), static.Program()
        with static.program_guard(main, start):
            x = snn.data('x', [2, 3, 4, 4], 'float32')
            y = snn.pad2d(x, [1, 1, 1, 1])
            z = snn.adaptive_pool2d(y, 2, 'avg')
        exe = static.Executor()
        out = exe.run(main, feed={
            'x': np.ones((2, 3, 4, 4), np.float32)},
            fetch_list=[z])
        assert out[0].shape == (2, 3, 2, 2)
    finally:
        paddle.disable_static()
    for n in ['accuracy', 'auc', 'data', 'center_loss',
              'sampled_softmax_with_cross_entropy', 'inplace_abn']:
        assert callable(getattr(snn, n)), n


def test_accuracy_auc_static_recordable():
    paddle.enable_static()
    try:
        from paddle_tpu import static
        main, start = static.Program(), static.Program()
        with static.program_guard(main, start):
            p = snn.data('p', [8, 4], 'float32')
            l = snn.data('l', [8, 1], 'int64')
            acc = snn.accuracy(p, l, k=1)
            a = snn.auc(p, l)
        exe = static.Executor()
        rng = np.random.RandomState(0)
        pv = rng.rand(8, 4).astype(np.float32)
        lv = rng.randint(0, 4, (8, 1)).astype(np.int64)
        out = exe.run(main, feed={'p': pv, 'l': lv},
                      fetch_list=[acc, a])
        want = (pv.argmax(-1) == lv.reshape(-1)).mean()
        assert abs(float(out[0]) - want) < 1e-6
        assert 0.0 <= float(out[1]) <= 1.0
    finally:
        paddle.disable_static()


def test_auc_orders_scores_correctly():
    # perfectly separable scores -> AUC 1
    p = np.array([[0.9], [0.8], [0.2], [0.1]], np.float32)
    l = np.array([[1], [1], [0], [0]], np.int64)
    a = float(snn.auc(Tensor(np.concatenate([1 - p, p], 1)),
                      Tensor(l)).data)
    assert a > 0.99
    # inverted -> AUC 0
    a2 = float(snn.auc(Tensor(np.concatenate([p, 1 - p], 1)),
                       Tensor(l)).data)
    assert a2 < 0.01


def test_similarity_focus_axis_2_and_validation():
    rng = np.random.RandomState(4)
    x = rng.rand(2, 3, 4, 5).astype(np.float32)
    out = np.asarray(fl.similarity_focus(Tensor(x), axis=2,
                                         indexes=[1]).data)
    assert out.shape == x.shape
    # mask constant along the selected axis (2)
    assert (out == out[:, :, :1, :]).all()
    with pytest.raises(ValueError, match='out of range'):
        fl.similarity_focus(Tensor(x), axis=2, indexes=[9])
    with pytest.raises(ValueError, match='axis'):
        fl.similarity_focus(Tensor(x), axis=0, indexes=[0])


def test_pool3d_ceil_mode_shape():
    x = Tensor(np.ones((1, 1, 6, 6, 6), np.float32))
    flo = fl.pool3d(x, pool_size=3, pool_type='avg', pool_stride=2)
    cei = fl.pool3d(x, pool_size=3, pool_type='avg', pool_stride=2,
                    ceil_mode=True)
    assert flo.shape == [1, 1, 2, 2, 2]
    assert cei.shape == [1, 1, 3, 3, 3]


def test_py_func_skip_vars_in_backward_input():
    paddle.enable_static()
    try:
        from paddle_tpu import static
        main, start = static.Program(), static.Program()
        with static.program_guard(main, start):
            x = snn.data('x', [2, 2], 'float32')
            x.stop_gradient = False
            seen_args = []

            def fwd(a):
                return a * 2.0

            def bwd(o, do):          # x skipped: only (out, dout)
                seen_args.append(len([o, do]))
                return do * 2.0

            y = snn.py_func(fwd, x, ([2, 2], 'float32'),
                            backward_func=bwd,
                            skip_vars_in_backward_input=[x])
            loss = paddle.mean(y)
            static.append_backward(loss)
        exe = static.Executor()
        out = exe.run(main, feed={'x': np.ones((2, 2), np.float32)},
                      fetch_list=[y])
        np.testing.assert_allclose(out[0], 2.0)
    finally:
        paddle.disable_static()
