"""bf16-vs-fp32 loss-parity (the north star's "loss-curve-matching"
criterion; VERDICT r1 #9). Both legs run on CPU here for determinism; the
tools/loss_parity.py script runs the same harness on the TPU chip."""
import os

import pytest
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))), 'tools'))


@pytest.mark.slow       # ~45s 30-step curve: run_tests.sh tiers
def test_bf16_curve_tracks_fp32():
    from loss_parity import compare
    report = compare(steps=30, rel_tol=0.05)
    assert report['fp32_decreased'] and report['bf16_decreased'], report
    assert report['max_rel_gap'] < 0.05, report
